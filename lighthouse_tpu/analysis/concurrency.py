"""Pass 5 — the concurrency certifier: lock-discipline proofs for the
host-side thread fabric (ISSUE 9).

PRs 1 and 7 made the host side deeply multi-threaded — firehose
prep/device pipeline, beacon_processor worker pool, gossip/sync serve
loops, the resilience watchdogs — and "Security Review of Ethereum Beacon
Clients" catalogs races and lost wakeups in exactly those pipelines as a
top real-world client failure mode. This pass is the concurrency twin of
the limb-bound certifier: every module importing ``threading`` is parsed
and proved against four rules, a package-wide lock-order graph is built
and checked for deadlock cycles, and an env-gated runtime lockdep wrapper
cross-validates the static graph against the acquisition orders actually
observed under the chaos scenario.

Three coordinated pieces:

1. **Static lock-discipline certifier.** Per class, the guard relation
   (attribute -> lock) is inferred from accesses dominated by
   ``with self._lock:`` blocks; thread entrypoints (``Thread`` targets,
   serve-loop closures, the public API surface) are identified; and a
   shared-attribute mutation reachable from >= 2 entrypoint threads
   without the inferred guard is an ``unguarded-write`` finding. Module
   globals get the same treatment against module-level locks
   (``unguarded-global``). Context-sensitive: a private helper only ever
   called under the lock (``_set_state``-style "caller holds the lock"
   contracts) is proven guarded through the call-site held-set fixpoint,
   not flagged.

2. **Lock-order deadlock graph.** Nested ``with``-lock statements and
   intra-package call edges (``self.method()``, typed ``self.attr.m()``
   receivers, imported module functions, metrics-family globals) build
   the acquires-while-holding graph over lock *classes*
   (``module.Class.attr`` / ``module.GLOBAL`` identities, the standard
   lockdep keying). Any cycle is a ``lock-order-cycle`` finding, and a
   blocking call while holding a lock — device dispatch
   (``block_until_ready``), unbounded ``Thread.join()``, socket
   send/recv, untimed ``Condition.wait()`` / ``queue.get()`` — is a
   ``blocking-under-lock`` finding: the pattern behind watchdog
   false-trips and wedged shutdowns.

3. **Runtime lockdep cross-validation** (``LIGHTHOUSE_LOCKDEP=1``).
   ``install()`` swaps ``threading.Lock/RLock/Condition`` for
   instrumented factories that record the creation site (matched back to
   the static ``module.Class.attr`` identity through the site map), the
   actual acquisition-order edges per thread, and hold times. Observed
   edges are merged into the static graph (``merge_observed``), the
   union must stay acyclic, and static edges never observed are reported
   as the coverage gap. ``tests/conftest.py`` arms this for a whole
   pytest run and writes ``LOCKDEP_OBSERVED.json``; the CLI merges that
   file into ``CONCURRENCY_CERT.json`` when present.

Like the hygiene linter, intentional sites carry a
``# lint: allow(<rule>)`` pragma (flagged line or the line above) with a
justification, and whole findings can live in the checked-in
``analysis/concurrency_baseline.json`` keyed by (path, rule, source
line) so line churn does not invalidate them. The lifecycle rule
(``unjoined-thread``) enforces the shutdown discipline: a class that
starts a thread must bound-join it somewhere (stop-event + ``join``
with a timeout), so a wedged worker can never hang shutdown silently.
"""

from __future__ import annotations

import ast
import json
import os
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

from .hygiene import _PRAGMA_RE, Finding, _dotted

__all__ = [
    "RULES",
    "certify_concurrency",
    "analyze_tree",
    "load_baseline",
    "write_cert",
    "install",
    "uninstall",
    "installed",
    "lockdep_enabled",
    "observed_report",
    "reset_observed",
    "merge_observed",
    "OBSERVED_DEFAULT_PATH",
]

RULES = {
    "unguarded-write": "shared attribute mutated without its inferred guard lock",
    "unguarded-global": "module global mutated without its inferred guard lock",
    "lock-order-cycle": "cycle in the acquires-while-holding lock graph",
    "blocking-under-lock": "blocking call while holding a lock",
    "unjoined-thread": "started thread with no bounded join on shutdown",
}

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTOR = "Condition"
# object-mutating method names (a call on a shared attribute that rewrites it)
_MUTATORS = {
    "append", "extend", "add", "update", "pop", "popleft", "appendleft",
    "insert", "remove", "discard", "clear", "setdefault", "popitem",
    "move_to_end",
}
# blocking-call table: attribute-call names that park the calling thread
# indefinitely. ``join``/``wait``/``get`` only count when untimed (no args /
# no timeout) — a bounded join/wait is exactly the discipline we enforce.
_BLOCKING_ALWAYS = {
    "block_until_ready",  # device dispatch barrier
    "sendall", "sendto", "recv", "recvfrom", "accept", "connect",  # sockets
    "serve_forever",
}
_BLOCKING_UNTIMED = {"join", "wait", "get"}


# =============================================================================
# package model
# =============================================================================


@dataclass
class _Func:
    key: str                      # "mod.Class.meth" | "mod.func"
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    module: "_Module"
    cls: "_Class | None" = None
    # local facts (filled by _FuncScan)
    acquires: list = field(default_factory=list)     # (lock_id, lineno)
    edges: set = field(default_factory=set)          # (held, acq, lineno)
    blocking: list = field(default_factory=list)     # (desc, lineno, held_ids)
    calls: list = field(default_factory=list)        # (callee_key, lineno, held, on_self)
    worker_calls: list = field(default_factory=list) # closure calls (own thread)
    accesses: list = field(default_factory=list)     # _Access (methods only)
    global_writes: list = field(default_factory=list)   # (name, lineno, held)
    thread_starts: list = field(default_factory=list)   # (lineno, target_desc)
    has_bounded_join: bool = False
    # fixpoint summaries
    trans_acquires: set = field(default_factory=set)
    trans_blocking: tuple | None = None              # (desc, lineno) or None


@dataclass
class _Access:
    attr: str
    write: bool
    held: frozenset
    lineno: int
    method: str                   # method name within the class
    in_init: bool


@dataclass
class _Class:
    name: str
    module: "_Module"
    bases: list = field(default_factory=list)        # raw dotted base names
    locks: dict = field(default_factory=dict)        # attr -> (lock_id, lineno, kind)
    lock_aliases: dict = field(default_factory=dict) # attr -> attr (Condition(self._lock))
    attr_types: dict = field(default_factory=dict)   # attr -> class key
    methods: dict = field(default_factory=dict)      # name -> _Func
    thread_targets: set = field(default_factory=set) # method/closure root names

    def key(self) -> str:
        return f"{self.module.mod}.{self.name}"


@dataclass
class _Module:
    path: str                     # absolute
    rel: str                      # repo-relative (finding path)
    mod: str                      # dotted, package-relative ("firehose.engine")
    tree: ast.Module | None
    lines: list
    uses_threading: bool = False
    imports: dict = field(default_factory=dict)      # local name -> dotted target
    classes: dict = field(default_factory=dict)
    functions: dict = field(default_factory=dict)    # module-level funcs
    global_locks: dict = field(default_factory=dict) # name -> (lock_id, lineno, kind)
    global_types: dict = field(default_factory=dict) # name -> class key


class _Index:
    """The package-wide symbol index: modules, classes, functions, locks."""

    def __init__(self):
        self.modules: dict[str, _Module] = {}
        self.classes: dict[str, _Class] = {}
        self.funcs: dict[str, _Func] = {}
        self.lock_sites: dict[tuple, str] = {}       # (rel, lineno) -> lock_id

    def resolve_class(self, dotted: str) -> _Class | None:
        """Resolve a possibly re-exported dotted class name to a _Class."""
        for _ in range(4):
            cls = self.classes.get(dotted)
            if cls is not None:
                return cls
            # follow one re-export hop: "a.b.Name" where a.b is a module
            # whose imports bind Name
            mod, _, name = dotted.rpartition(".")
            m = self.modules.get(mod)
            if m is None or name not in m.imports:
                return None
            dotted = m.imports[name]
        return None

    def resolve_func(self, dotted: str) -> _Func | None:
        for _ in range(4):
            fn = self.funcs.get(dotted)
            if fn is not None:
                return fn
            mod, _, name = dotted.rpartition(".")
            m = self.modules.get(mod)
            if m is None or name not in m.imports:
                return None
            dotted = m.imports[name]
        return None

    def mro_lookup(self, cls: _Class, what: str, name: str, depth: int = 0):
        """Walk single-inheritance bases (package classes only)."""
        table = getattr(cls, what)
        if name in table:
            return table[name]
        if depth >= 4:
            return None
        for base in cls.bases:
            b = self.resolve_class(base)
            if b is not None:
                hit = self.mro_lookup(b, what, name, depth + 1)
                if hit is not None:
                    return hit
        return None

    def all_locks(self, cls: _Class) -> dict:
        """attr -> (lock_id, lineno, kind), inherited attrs included (keyed
        by the DEFINING class — the lockdep class identity)."""
        out: dict = {}
        stack, seen = [cls], set()
        while stack:
            c = stack.pop()
            if c.key() in seen:
                continue
            seen.add(c.key())
            for attr, rec in c.locks.items():
                out.setdefault(attr, rec)
            for attr, tgt in c.lock_aliases.items():
                out.setdefault(attr, out.get(tgt) or c.locks.get(tgt))
            for base in c.bases:
                b = self.resolve_class(base)
                if b is not None:
                    stack.append(b)
        return {a: r for a, r in out.items() if r is not None}


def _module_name(rel: str) -> str:
    """'lighthouse_tpu/firehose/engine.py' -> 'firehose.engine'."""
    parts = rel.replace(os.sep, "/").split("/")
    if parts and parts[0] == "lighthouse_tpu":
        parts = parts[1:]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<root>"


def _resolve_imports(m: _Module) -> None:
    """Map local names to package-relative dotted targets."""
    pkg_parts = m.mod.split(".") if m.mod != "<root>" else []
    if m.path.endswith("__init__.py"):
        base = pkg_parts               # relative to the package itself
    else:
        base = pkg_parts[:-1]
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                if alias.name == "threading":
                    m.uses_threading = True
                m.imports[name] = alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                anchor = base[: len(base) - (node.level - 1)] if node.level > 1 else base
                prefix = ".".join(anchor + ([node.module] if node.module else []))
            else:
                prefix = node.module or ""
                if prefix == "threading":
                    m.uses_threading = True
            for alias in node.names:
                name = alias.asname or alias.name
                m.imports[name] = f"{prefix}.{alias.name}" if prefix else alias.name


def _lock_ctor_kind(call: ast.Call) -> str | None:
    d = _dotted(call.func)
    if d is None:
        return None
    tail = d.rsplit(".", 1)[-1]
    head = d.split(".")[0]
    if head not in ("threading",) and d != tail:
        return None
    if tail in _LOCK_CTORS:
        return tail.lower()
    if tail == _COND_CTOR:
        return "condition"
    return None


def _scan_module(m: _Module, index: _Index) -> None:
    """First pass: classes, lock attrs, attr/global types, module funcs."""
    _resolve_imports(m)
    for node in m.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = f"{m.mod}.{node.name}"
            fn = _Func(key, node, m)
            m.functions[node.name] = fn
            index.funcs[key] = fn
        elif isinstance(node, ast.ClassDef):
            cls = _Class(node.name, m)
            cls.bases = [b for b in (_dotted(x) for x in node.bases) if b]
            cls._bases_raw = list(cls.bases)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    key = f"{m.mod}.{cls.name}.{item.name}"
                    fn = _Func(key, item, m, cls)
                    cls.methods[item.name] = fn
                    index.funcs[key] = fn
            m.classes[cls.name] = cls
            index.classes[cls.key()] = cls
        elif (
            isinstance(node, (ast.Assign, ast.AnnAssign))
            and isinstance(getattr(node, "value", None), ast.Call)
        ):
            kind = _lock_ctor_kind(node.value)
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if kind:
                    lock_id = f"{m.mod}.{tgt.id}"
                    m.global_locks[tgt.id] = (lock_id, node.lineno, kind)
                    index.lock_sites[(m.rel, node.lineno)] = lock_id
                else:
                    t = _callee_class_key(node.value, m, index)
                    if t:
                        m.global_types[tgt.id] = t
    # second sweep per class: __init__-declared locks / aliases / attr types
    for cls in m.classes.values():
        for meth in cls.methods.values():
            for st in ast.walk(meth.node):
                if not (
                    isinstance(st, (ast.Assign, ast.AnnAssign))
                    and isinstance(getattr(st, "value", None), ast.Call)
                ):
                    continue
                st_targets = (
                    st.targets if isinstance(st, ast.Assign) else [st.target]
                )
                for tgt in st_targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    kind = _lock_ctor_kind(st.value)
                    if kind == "condition" and st.value.args:
                        # Condition(self._lock) ALIASES the existing lock
                        inner = st.value.args[0]
                        if (
                            isinstance(inner, ast.Attribute)
                            and isinstance(inner.value, ast.Name)
                            and inner.value.id == "self"
                        ):
                            cls.lock_aliases[tgt.attr] = inner.attr
                            continue
                    if kind:
                        lock_id = f"{cls.key()}.{tgt.attr}"
                        cls.locks[tgt.attr] = (lock_id, st.lineno, kind)
                        index.lock_sites[(m.rel, st.lineno)] = lock_id
                    else:
                        t = _callee_class_key(st.value, m, index)
                        if t:
                            cls.attr_types.setdefault(tgt.attr, t)


# metrics-family factory returns: module-global ``X = REGISTRY.counter(...)``
# binds an instance of the metrics class — the one return-type special case
# the lock graph needs (those globals are inc()'d from under other locks).
_FACTORY_RETURNS = {"counter": "Counter", "gauge": "Gauge", "histogram": "Histogram"}


def _callee_class_key(call: ast.Call, m: _Module, index: _Index) -> str | None:
    d = _dotted(call.func)
    if d is None:
        return None
    tail = d.rsplit(".", 1)[-1]
    if tail in _FACTORY_RETURNS and "." in d:
        key = f"utils.metrics.{_FACTORY_RETURNS[tail]}"
        if key in index.classes or not index.classes:
            return key
    head = d.split(".")[0]
    target = m.imports.get(head)
    if target is None:
        target = d if head in m.classes or head in m.functions else None
        if target is not None:
            target = f"{m.mod}.{d}"
    elif "." in d:
        target = f"{target}.{d.split('.', 1)[1]}"
    return target


# =============================================================================
# per-function fact extraction
# =============================================================================


class _FuncScan:
    """Walk one function body tracking the held-lock stack; record
    acquisitions, nested-acquire edges, resolved calls, blocking calls,
    self-attribute accesses and module-global accesses."""

    def __init__(self, fn: _Func, index: _Index, method_name: str = "",
                 in_init: bool = False):
        self.fn = fn
        self.index = index
        self.m = fn.module
        self.cls = fn.cls
        self.locks = index.all_locks(fn.cls) if fn.cls else {}
        self.method_name = method_name
        self.in_init = in_init
        self.self_method_refs: set = set()        # non-call self.<method> loads

    # -- lock-expression recognition ---------------------------------------

    def _lock_id_of(self, expr) -> tuple | None:
        """(lock_id, kind) when ``expr`` denotes a known lock."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            rec = self.locks.get(expr.attr)
            if rec:
                return rec[0], rec[2]
        elif isinstance(expr, ast.Name):
            rec = self.m.global_locks.get(expr.id)
            if rec:
                return rec[0], rec[2]
        return None

    # -- call resolution ----------------------------------------------------

    def _resolve_call(self, call: ast.Call) -> tuple | None:
        """(callee_key, on_self) for a package call we can name."""
        f = call.func
        if isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name) and recv.id == "self" and self.cls:
                meth = self.index.mro_lookup(self.cls, "methods", f.attr)
                if meth is not None:
                    return meth.key, True
                return None
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and self.cls
            ):
                t = self.index.mro_lookup(self.cls, "attr_types", recv.attr)
                if t:
                    cls = self.index.resolve_class(t)
                    if cls:
                        meth = self.index.mro_lookup(cls, "methods", f.attr)
                        if meth is not None:
                            return meth.key, False
                return None
            if isinstance(recv, ast.Name):
                # module-global instance or imported module
                t = self.m.global_types.get(recv.id)
                if t is None and recv.id in self.m.imports:
                    target = self.m.imports[recv.id]
                    fn = self.index.resolve_func(f"{target}.{f.attr}")
                    if fn is not None:
                        return fn.key, False
                    # imported instance global (a metrics family counter):
                    # type comes from the defining module's global table
                    t = self._imported_instance_type(recv.id)
                if t:
                    cls = self.index.resolve_class(t)
                    if cls:
                        meth = self.index.mro_lookup(cls, "methods", f.attr)
                        if meth is not None:
                            return meth.key, False
            return None
        if isinstance(f, ast.Name):
            if f.id in self.m.functions:
                return self.m.functions[f.id].key, False
            if self.cls and f.id in self.m.classes:
                ctor = self.index.mro_lookup(self.m.classes[f.id], "methods", "__init__")
                if ctor is not None:
                    return ctor.key, False
            target = self.m.imports.get(f.id)
            if target:
                fn = self.index.resolve_func(target)
                if fn is not None:
                    return fn.key, False
                cls = self.index.resolve_class(target)
                if cls is not None:
                    ctor = self.index.mro_lookup(cls, "methods", "__init__")
                    if ctor is not None:
                        return ctor.key, False
        return None

    def _imported_instance_type(self, name: str) -> str | None:
        """``from ..utils.metrics import FIREHOSE_DROPPED`` -> Counter."""
        target = self.m.imports.get(name)
        if not target:
            return None
        mod, _, sym = target.rpartition(".")
        src = self.index.modules.get(mod)
        if src is not None:
            return src.global_types.get(sym)
        return None

    # -- blocking-call recognition ------------------------------------------

    def _blocking_desc(self, call: ast.Call) -> str | None:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        name = f.attr
        if name in _BLOCKING_ALWAYS:
            # ",".join(...)-style false positives cannot arise here; the
            # always-blocking names are device/socket verbs
            return f".{name}()"
        if name not in _BLOCKING_UNTIMED:
            return None
        if any(kw.arg == "timeout" for kw in call.keywords):
            return None
        if name == "join":
            # str.join / os.path.join always take an argument; Thread.join()
            # is unbounded exactly when called with none
            return ".join() [unbounded]" if not call.args and not call.keywords else None
        if name == "get":
            # dict.get(k) has args; Queue.get() / Queue.get(True) block
            if not call.args:
                return ".get() [untimed]"
            if (
                len(call.args) == 1
                and isinstance(call.args[0], ast.Constant)
                and call.args[0].value is True
            ):
                return ".get(True) [untimed]"
            return None
        if name == "wait":
            # Condition.wait() / Event.wait() with no timeout parks forever
            return ".wait() [untimed]" if not call.args else None
        return None

    # -- thread lifecycle ---------------------------------------------------

    def _note_thread(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d not in ("threading.Thread", "Thread"):
            return
        target = ""
        for kw in node.keywords:
            if kw.arg == "target":
                target = _dotted(kw.value) or "<expr>"
        self.fn.thread_starts.append((node.lineno, target))
        if self.cls is not None and target.startswith("self."):
            self.cls.thread_targets.add(target[len("self."):])

    # -- the walk -----------------------------------------------------------

    def run(self) -> None:
        body = self.fn.node.body
        self._walk(body, ())
        if self.fn.thread_starts and self.cls is not None:
            # a thread-starting method that holds bare references to own
            # methods is handing them to Thread(target=...) through a
            # variable (the firehose double-loop idiom); a local-closure
            # target makes the method itself the worker root
            self.cls.thread_targets |= self.self_method_refs
            if any(
                t and not t.startswith("self.")
                for _ln, t in self.fn.thread_starts
            ):
                self.cls.thread_targets.add(self.method_name)

    def _walk(self, stmts, held: tuple) -> None:
        for st in stmts:
            if isinstance(st, ast.With):
                acquired = []
                for item in st.items:
                    rec = self._lock_id_of(item.context_expr)
                    if rec is not None:
                        lock_id, kind = rec
                        self.fn.acquires.append((lock_id, st.lineno))
                        for h in held + tuple(acquired):
                            if h != lock_id:
                                self.fn.edges.add((h, lock_id, st.lineno))
                            elif kind == "lock":
                                # same non-reentrant lock nested on the same
                                # object: guaranteed self-deadlock
                                self.fn.edges.add((h, lock_id, st.lineno))
                        acquired.append(lock_id)
                    else:
                        self._scan_expr(item.context_expr, held)
                self._walk(st.body, held + tuple(acquired))
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closures (Thread targets, local workers) run later on
                # their own thread: their acquisitions/blocking belong to a
                # SYNTHETIC function (own entry, empty held set) so the
                # fixpoint never attributes worker-thread operations to
                # inline callers of the enclosing method — only the
                # attribute accesses stay with the method, feeding the
                # guard analysis under its thread-root label
                sub_fn = _Func(
                    f"{self.fn.key}.<{st.name}>", st, self.fn.module,
                    self.fn.cls,
                )
                self.index.funcs[sub_fn.key] = sub_fn
                sub = _FuncScan(sub_fn, self.index, self.method_name,
                                self.in_init)
                sub._walk(st.body, ())
                self.fn.accesses.extend(sub_fn.accesses)
                self.fn.global_writes.extend(sub_fn.global_writes)
                self.fn.thread_starts.extend(sub_fn.thread_starts)
                self.fn.worker_calls.extend(
                    sub_fn.calls + sub_fn.worker_calls
                )
                if sub_fn.has_bounded_join:
                    self.fn.has_bounded_join = True
                self.self_method_refs |= sub.self_method_refs
                continue
            # attribute / global writes at statement level
            if isinstance(st, ast.Assign):
                for tgt in st.targets:
                    self._note_store(tgt, held, st.lineno)
                self._scan_expr(st.value, held)
                continue
            if isinstance(st, ast.AugAssign):
                self._note_store(st.target, held, st.lineno)
                self._scan_expr(st.value, held)
                continue
            if isinstance(st, ast.AnnAssign):
                if st.value is not None:   # bare annotations store nothing
                    self._note_store(st.target, held, st.lineno)
                    self._scan_expr(st.value, held)
                continue
            if isinstance(st, ast.Delete):
                for tgt in st.targets:
                    self._note_store(tgt, held, st.lineno)
                continue
            # recurse: statements with bodies keep the held set (except
            # handlers are ExceptHandler nodes, not stmts — walk their
            # bodies explicitly or the whole fault path goes unanalyzed)
            for fieldname, value in ast.iter_fields(st):
                if isinstance(value, list) and value and isinstance(value[0], ast.stmt):
                    self._walk(value, held)
                elif isinstance(value, list) and value and isinstance(
                    value[0], ast.ExceptHandler
                ):
                    for h in value:
                        self._walk(h.body, held)
                elif isinstance(value, ast.stmt):
                    self._walk([value], held)
                elif isinstance(value, ast.expr):
                    self._scan_expr(value, held)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._scan_expr(v, held)

    def _note_store(self, tgt, held: tuple, lineno: int) -> None:
        held_f = frozenset(held)
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
            and self.cls is not None
        ):
            if tgt.attr not in self.locks:
                self.fn.accesses.append(_Access(
                    tgt.attr, True, held_f, lineno, self.method_name,
                    self.in_init,
                ))
        elif isinstance(tgt, ast.Subscript):
            base = tgt.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and self.cls is not None
            ):
                self.fn.accesses.append(_Access(
                    base.attr, True, held_f, lineno, self.method_name,
                    self.in_init,
                ))
            elif isinstance(base, ast.Name) and base.id in self._module_globals():
                self.fn.global_writes.append((base.id, lineno, held_f))
        elif isinstance(tgt, ast.Name) and self.fn.cls is None:
            # rebinding a module global needs a `global` decl to matter;
            # treat names declared global in this function as global stores
            if base_is_global(self.fn.node, tgt.id):
                self.fn.global_writes.append((tgt.id, lineno, held_f))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._note_store(el, held, lineno)

    def _module_globals(self) -> set:
        return set(self.m.global_types) | {
            n for n in self.m.global_locks
        } | getattr(self.m, "_mutable_globals", set())

    def _scan_expr(self, expr, held: tuple) -> None:
        held_f = frozenset(held)
        callee_nodes: set = set()     # Attribute nodes in call position
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                if (
                    isinstance(node, ast.Attribute)
                    and id(node) not in callee_nodes
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and self.cls is not None
                    and node.attr not in self.locks
                ):
                    if node.attr in self.cls.methods:
                        self.self_method_refs.add(node.attr)
                    self.fn.accesses.append(_Access(
                        node.attr, False, held_f, node.lineno,
                        self.method_name, self.in_init,
                    ))
                continue
            callee_nodes.add(id(node.func))
            self._note_thread(node)
            f = node.func
            # mutator call on a shared attribute / global
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                recv = f.value
                if (
                    isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                    and self.cls is not None
                ):
                    self.fn.accesses.append(_Access(
                        recv.attr, True, held_f, node.lineno,
                        self.method_name, self.in_init,
                    ))
                elif isinstance(recv, ast.Name) and recv.id in self._module_globals():
                    self.fn.global_writes.append((recv.id, node.lineno, held_f))
            if isinstance(f, ast.Attribute) and f.attr == "join":
                # only the canonical bounded form counts — join(timeout=...)
                # — so str.join can never satisfy the lifecycle rule
                if any(kw.arg == "timeout" for kw in node.keywords):
                    self.fn.has_bounded_join = True
            desc = self._blocking_desc(node)
            if desc is not None:
                self.fn.blocking.append((desc, node.lineno, held_f))
            resolved = self._resolve_call(node)
            if resolved is not None:
                key, on_self = resolved
                self.fn.calls.append((key, node.lineno, held_f, on_self))


def base_is_global(fn_node, name: str) -> bool:
    for st in ast.walk(fn_node):
        if isinstance(st, ast.Global) and name in st.names:
            return True
    return False


# =============================================================================
# the tree analysis
# =============================================================================


def _collect_mutable_globals(m: _Module) -> None:
    """Names assigned a mutable container at module level (the fault ring,
    peer tables, caches): candidates for the unguarded-global rule."""
    mut: set = set()
    for node in m.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            v = getattr(node, "value", None)
            is_mut = isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(v, ast.Call)
                and (_dotted(v.func) or "").rsplit(".", 1)[-1]
                in ("dict", "list", "set", "deque", "OrderedDict", "defaultdict")
            )
            if is_mut:
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        mut.add(tgt.id)
    m._mutable_globals = mut


def _parse_tree(root: str) -> _Index:
    index = _Index()
    pkg_parent = os.path.dirname(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, pkg_parent)
            try:
                with open(full) as f:
                    src = f.read()
                tree = ast.parse(src)
            except (OSError, SyntaxError):
                continue
            m = _Module(full, rel, _module_name(rel), tree, src.splitlines())
            index.modules[m.mod] = m
    for m in index.modules.values():
        _collect_mutable_globals(m)
        _scan_module(m, index)
    # base names resolve against the defining module: same-module classes
    # first, then the import table (inheritance carries lock attrs)
    for m in index.modules.values():
        for cls in m.classes.values():
            cls.bases = [
                f"{m.mod}.{b}" if b in m.classes else m.imports.get(b, b)
                for b in cls._bases_raw
            ]
    # fact extraction over every function in the package (call summaries
    # must cross into modules that do not themselves import threading)
    for m in index.modules.values():
        for fn in m.functions.values():
            _FuncScan(fn, index).run()
        for cls in m.classes.values():
            for name, meth in cls.methods.items():
                _FuncScan(meth, index, name, in_init=(name == "__init__")).run()
    return index


def _fixpoint_summaries(index: _Index) -> tuple[set, list]:
    """Propagate acquisitions and blocking calls through the call graph.
    Returns (global lock-order edges, blocking findings raw)."""
    funcs = list(index.funcs.values())
    for fn in funcs:
        fn.trans_acquires = {a for a, _ in fn.acquires}
        fn.trans_blocking = fn.blocking[0][:2] if fn.blocking else None
    for _ in range(24):
        changed = False
        for fn in funcs:
            for key, _ln, _held, _on_self in fn.calls:
                callee = index.funcs.get(key)
                if callee is None:
                    continue
                before = len(fn.trans_acquires)
                fn.trans_acquires |= callee.trans_acquires
                if len(fn.trans_acquires) != before:
                    changed = True
                if fn.trans_blocking is None and callee.trans_blocking is not None:
                    fn.trans_blocking = (
                        f"{callee.trans_blocking[0]} via {key.rsplit('.', 1)[-1]}()",
                        None,
                    )
                    changed = True
        if not changed:
            break
    edges: dict[tuple, tuple] = {}   # (held, acq) -> (rel, lineno)
    blocking_raw: list = []          # (rel, lineno, desc, held_ids)
    for fn in funcs:
        for held, acq, ln in fn.edges:
            edges.setdefault((held, acq), (fn.module.rel, ln))
        for desc, ln, held in fn.blocking:
            if held:
                blocking_raw.append((fn.module.rel, ln, desc, held))
        for key, ln, held, on_self in fn.calls:
            if not held:
                continue
            callee = index.funcs.get(key)
            if callee is None:
                continue
            for acq in callee.trans_acquires:
                for h in held:
                    if h != acq:
                        edges.setdefault((h, acq), (fn.module.rel, ln))
                    elif not on_self:
                        # same lock CLASS on (possibly) another instance:
                        # not provably the same object — skip the self-edge
                        pass
            if callee.trans_blocking is not None:
                desc = callee.trans_blocking[0]
                blocking_raw.append(
                    (fn.module.rel, ln,
                     f"{desc} inside {key.rsplit('.', 1)[-1]}()", held)
                )
    return edges, blocking_raw


def _find_cycles(edges: dict) -> list[list[str]]:
    """Elementary cycles via DFS (the graph is small: tens of nodes)."""
    graph: dict[str, set] = defaultdict(set)
    for (a, b) in edges:
        graph[a].add(b)
    cycles: list[list[str]] = []
    seen_keys: set = set()

    def dfs(start: str, node: str, path: list, visited: set) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path[:]
                # rotation-invariant key so one cycle reports once
                i = cyc.index(min(cyc))
                key = tuple(cyc[i:] + cyc[:i])
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(cyc + [start])
            elif nxt not in visited and nxt > start:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return cycles


# -- guard inference ----------------------------------------------------------


def _class_findings(index: _Index, findings: list) -> None:
    for m in index.modules.values():
        if not m.uses_threading:
            continue
        for cls in m.classes.values():
            locks = index.all_locks(cls)
            if not locks and not cls.thread_targets:
                continue
            lock_ids = {rec[0] for rec in locks.values()}
            accesses: list[_Access] = []
            for meth in cls.methods.values():
                accesses.extend(meth.accesses)
            # context-sensitive entry held-sets: intersection over call sites
            entry = _entry_held(index, cls)
            methods = set(cls.methods)

            def effective(acc: _Access) -> frozenset:
                e = entry.get(acc.method)
                if e is None:          # never called: unreachable, assume safe
                    return frozenset(lock_ids)
                return acc.held | e

            # guard inference: the lock most often held across accesses
            per_attr: dict[str, list[_Access]] = defaultdict(list)
            for acc in accesses:
                if acc.attr in methods or acc.attr in cls.attr_types:
                    continue           # method refs / owned sub-objects
                per_attr[acc.attr].append(acc)
            guards: dict[str, str] = {}
            for attr, accs in per_attr.items():
                votes: dict[str, int] = defaultdict(int)
                for acc in accs:
                    if acc.in_init:
                        continue
                    for lid in effective(acc) & lock_ids:
                        votes[lid] += 1
                if votes:
                    guards[attr] = max(sorted(votes), key=lambda k: votes[k])
            # thread-entry roots: each Thread-target method is its own root;
            # the public API surface is one shared root
            roots: dict[str, str] = {}
            for t in cls.thread_targets:
                roots[t] = f"thread:{t}"
            for name in cls.methods:
                if not name.startswith("_") and name not in roots:
                    roots[name] = "api"
            reach = _reachable_roots(index, cls, roots)
            for attr, accs in sorted(per_attr.items()):
                guard = guards.get(attr)
                writer_roots = set()
                toucher_roots = set()
                for acc in accs:
                    if acc.in_init:
                        continue
                    rts = reach.get(acc.method, set())
                    toucher_roots |= rts
                    if acc.write:
                        writer_roots |= rts
                for acc in accs:
                    if not acc.write or acc.in_init:
                        continue
                    eff = effective(acc)
                    if guard is not None:
                        if guard not in eff and len(toucher_roots) >= 2:
                            findings.append(_mk(
                                m, acc.lineno, "unguarded-write",
                                f"`self.{attr}` is guarded by `{guard.rsplit('.', 1)[-1]}`"
                                f" elsewhere but mutated without it in"
                                f" {cls.name}.{acc.method} (reachable from"
                                f" {_fmt_roots(toucher_roots)})",
                            ))
                    elif len(writer_roots) >= 2 and not (eff & lock_ids):
                        findings.append(_mk(
                            m, acc.lineno, "unguarded-write",
                            f"`self.{attr}` mutated lock-free in"
                            f" {cls.name}.{acc.method} with writers on"
                            f" {_fmt_roots(writer_roots)} and no inferred guard",
                        ))


def _fmt_roots(roots: set) -> str:
    return " + ".join(sorted(roots))


def _entry_held(index: _Index, cls: _Class) -> dict:
    """method -> intersection of held-lock sets across its call sites
    (roots enter with the empty set). None = never called."""
    entry: dict[str, frozenset | None] = {}
    for name in cls.methods:
        is_root = (
            not name.startswith("_")
            or name in cls.thread_targets
            or name.startswith("__")
        )
        entry[name] = frozenset() if is_root else None
    for _ in range(12):
        changed = False
        for name, meth in cls.methods.items():
            e = entry[name]
            if e is None:
                continue
            # worker-closure call sites enter the callee on their own
            # thread: the spawning method's entry context does NOT carry in
            sites = [
                (key, frozenset(held) | e, on_self)
                for key, _ln, held, on_self in meth.calls
            ] + [
                (key, frozenset(held), on_self)
                for key, _ln, held, on_self in meth.worker_calls
            ]
            for key, cand, on_self in sites:
                if not on_self:
                    continue
                callee = key.rsplit(".", 1)[-1]
                if callee not in entry:
                    continue
                cur = entry[callee]
                new = cand if cur is None else (cur & cand)
                if new != cur:
                    entry[callee] = new
                    changed = True
        if not changed:
            break
    return entry


def _reachable_roots(index: _Index, cls: _Class, roots: dict) -> dict:
    """method -> set of root labels that can reach it."""
    calls: dict[str, set] = defaultdict(set)
    for name, meth in cls.methods.items():
        for key, _ln, _held, on_self in meth.calls + meth.worker_calls:
            if on_self:
                calls[name].add(key.rsplit(".", 1)[-1])
    reach: dict[str, set] = defaultdict(set)
    for root_meth, label in roots.items():
        stack, seen = [root_meth], set()
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            reach[n].add(label)
            stack.extend(calls.get(n, ()))
    return reach


def _global_findings(index: _Index, findings: list) -> None:
    """unguarded-global: a module global written both under and outside a
    module-level lock (the fault-ring / registry pattern)."""
    for m in index.modules.values():
        if not m.uses_threading or not m.global_locks:
            continue
        lock_ids = {rec[0] for rec in m.global_locks.values()}
        writes: dict[str, list] = defaultdict(list)
        for fn in m.functions.values():
            for name, ln, held in fn.global_writes:
                writes[name].append((ln, held, fn))
        for cls in m.classes.values():
            for fn in cls.methods.values():
                for name, ln, held in fn.global_writes:
                    writes[name].append((ln, held, fn))
        for name, sites in sorted(writes.items()):
            guarded = [s for s in sites if frozenset(s[1]) & lock_ids]
            if not guarded:
                continue
            guard = sorted(frozenset(guarded[0][1]) & lock_ids)[0]
            for ln, held, fn in sites:
                if not (frozenset(held) & lock_ids):
                    findings.append(_mk(
                        m, ln, "unguarded-global",
                        f"module global `{name}` is guarded by"
                        f" `{guard.rsplit('.', 1)[-1]}` elsewhere but mutated"
                        f" without it in {fn.key.rsplit('.', 1)[-1]}",
                    ))


def _lifecycle_findings(index: _Index, findings: list) -> None:
    """unjoined-thread: a scope that starts a thread whose owning class (or
    function) never bound-joins any thread."""
    for m in index.modules.values():
        if not m.uses_threading:
            continue
        for cls in m.classes.values():
            starts = []
            joined = False
            for meth in cls.methods.values():
                starts.extend(meth.thread_starts)
                joined = joined or meth.has_bounded_join
            if starts and not joined:
                for ln, target in starts:
                    findings.append(_mk(
                        m, ln, "unjoined-thread",
                        f"{cls.name} starts a thread"
                        f"{f' (target={target})' if target else ''} but no"
                        " method bound-joins it on shutdown (stop-event +"
                        " join(timeout=...))",
                    ))
        for fn in m.functions.values():
            if fn.thread_starts and not fn.has_bounded_join:
                for ln, target in fn.thread_starts:
                    findings.append(_mk(
                        m, ln, "unjoined-thread",
                        f"{fn.key.rsplit('.', 1)[-1]}() starts a thread"
                        f"{f' (target={target})' if target else ''} without a"
                        " bounded join",
                    ))


def _mk(m: _Module, lineno: int, rule: str, message: str) -> Finding:
    ctx = m.lines[lineno - 1].strip() if 0 < lineno <= len(m.lines) else ""
    return Finding(m.rel, lineno, rule, message, ctx)


# =============================================================================
# public entry points
# =============================================================================


_BASELINE_PATH = os.path.join(
    os.path.dirname(__file__), "concurrency_baseline.json"
)
OBSERVED_DEFAULT_PATH = "LOCKDEP_OBSERVED.json"


def git_head() -> str | None:
    """Best-effort HEAD of the repo this package lives in (stamps the
    lockdep artifact so a stale observed graph is never merged)."""
    try:
        import subprocess

        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        return proc.stdout.strip() or None if proc.returncode == 0 else None
    except Exception:  # noqa: BLE001 — no git, no stamp
        return None


def load_baseline(path: str | None = None) -> set:
    try:
        with open(path or _BASELINE_PATH) as f:
            entries = json.load(f)
    except (OSError, ValueError):
        return set()
    return {(e["path"], e["rule"], e["context"]) for e in entries}


def _apply_pragmas(index: _Index, findings: list) -> list:
    kept = []
    for f in findings:
        mod = next((m for m in index.modules.values() if m.rel == f.path), None)
        allowed: set = set()
        if mod is not None:
            for ln in (f.line, f.line - 1):
                if 1 <= ln <= len(mod.lines):
                    m = _PRAGMA_RE.search(mod.lines[ln - 1])
                    if m:
                        allowed.update(p.strip() for p in m.group(1).split(","))
        if f.rule in allowed or "all" in allowed:
            continue
        kept.append(f)
    # dedupe (nested walks may revisit a line)
    seen, out = set(), []
    for f in kept:
        k = (f.path, f.line, f.rule, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def analyze_tree(root: str | None = None) -> tuple[_Index, list, dict, list]:
    """Parse + analyze the package. Returns (index, pragma-filtered
    findings, lock-order edges, cycles)."""
    root = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    index = _parse_tree(root)
    findings: list[Finding] = []
    edges, blocking_raw = _fixpoint_summaries(index)
    for rel, ln, desc, held in blocking_raw:
        mod = next((m for m in index.modules.values() if m.rel == rel), None)
        if mod is None:
            continue
        findings.append(_mk(
            mod, ln, "blocking-under-lock",
            f"blocking call {desc} while holding"
            f" {', '.join(s.rsplit('.', 1)[-1] for s in sorted(held))}",
        ))
    cycles = _find_cycles(edges)
    for cyc in cycles:
        site = edges.get((cyc[0], cyc[1]))
        mod = next(
            (m for m in index.modules.values() if site and m.rel == site[0]),
            None,
        )
        desc = " -> ".join(cyc)
        if mod is not None:
            findings.append(Finding(
                mod.rel, site[1], "lock-order-cycle",
                f"lock-order cycle: {desc}", desc,
            ))
        else:
            findings.append(Finding(
                "<package>", 1, "lock-order-cycle",
                f"lock-order cycle: {desc}", desc,
            ))
    _class_findings(index, findings)
    _global_findings(index, findings)
    _lifecycle_findings(index, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return index, _apply_pragmas(index, findings), edges, cycles


def certify_concurrency(
    root: str | None = None,
    baseline: set | None = None,
    observed_path: str | None = None,
) -> dict:
    """Run the full pass; returns the CONCURRENCY_CERT payload."""
    t0 = time.perf_counter()
    index, findings, edges, cycles = analyze_tree(root)
    baseline = load_baseline() if baseline is None else baseline
    kept = [f for f in findings if f.key() not in baseline]
    suppressed = len(findings) - len(kept)
    nodes = sorted({n for e in edges for n in e})
    observed = None
    if observed_path is None and os.path.exists(OBSERVED_DEFAULT_PATH):
        observed_path = OBSERVED_DEFAULT_PATH
    observed_stale = False
    if observed_path and os.path.exists(observed_path):
        try:
            with open(observed_path) as f:
                observed = json.load(f)
        except (OSError, ValueError):
            observed = None
        if observed is not None:
            # an observed graph from a DIFFERENT tree must not be merged:
            # a refactored acquisition order would produce a false cycle
            # (or a stale green) against the current static graph
            ohead = observed.get("head")
            head = git_head()
            if ohead and head and ohead != head:
                observed, observed_stale = None, True
    merged = merge_observed(edges, observed["edges"] if observed else [])
    merged["observed_stale_ignored"] = observed_stale
    n_threading = sum(1 for m in index.modules.values() if m.uses_threading)
    ok = not kept and not cycles and merged["ok"]
    return {
        "ok": ok,
        "pass": "concurrency",
        "n_modules_threading": n_threading,
        "n_lock_classes": len(index.lock_sites),
        "rules": dict(RULES),
        "n_findings": len(kept),
        "n_baseline_suppressed": suppressed,
        "findings": [f.as_dict() for f in kept],
        "lock_graph": {
            "nodes": nodes,
            "edges": [
                {"from": a, "to": b, "site": f"{rel}:{ln}"}
                for (a, b), (rel, ln) in sorted(edges.items())
            ],
        },
        "cycles": [" -> ".join(c) for c in cycles],
        "lockdep": merged,
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }


def write_cert(cert: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(cert, f, indent=1, sort_keys=True)
        f.write("\n")


# =============================================================================
# piece 3 — runtime lockdep (LIGHTHOUSE_LOCKDEP=1)
# =============================================================================


def lockdep_enabled() -> bool:
    return os.environ.get("LIGHTHOUSE_LOCKDEP", "") == "1"


class _LockdepState:
    def __init__(self):
        self.tls = threading.local()
        self.mu = _REAL_LOCK()                 # guards the tables below
        self.edges: dict[tuple, int] = {}      # (held_id, acq_id) -> count
        self.holds: dict[str, list] = {}       # id -> [count, total_s, max_s]
        self.n_locks = 0
        self.site_map: dict[tuple, str] = {}

    def stack(self) -> list:
        st = getattr(self.tls, "stack", None)
        if st is None:
            st = self.tls.stack = []
        return st


_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_state: _LockdepState | None = None


def _caller_site() -> tuple | None:
    """(repo-relative path, lineno) of the first lighthouse_tpu frame that
    called the lock factory."""
    import sys

    fr = sys._getframe(2)
    for _ in range(12):
        if fr is None:
            return None
        fname = fr.f_code.co_filename
        if f"lighthouse_tpu{os.sep}" in fname and "analysis" not in fname:
            i = fname.rindex(f"lighthouse_tpu{os.sep}")
            return fname[i:].replace(os.sep, "/"), fr.f_lineno
        fr = fr.f_back
    return None


class _InstrumentedLock:
    """Drop-in Lock/RLock wrapper recording acquisition-order edges and
    hold times into the process lockdep state."""

    def __init__(self, inner, lock_id: str, reentrant: bool):
        self._inner = inner
        self._id = lock_id
        self._reentrant = reentrant
        self._acquired_at = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def _note_acquired(self) -> None:
        st = _state
        if st is None:
            return
        stack = st.stack()
        if any(entry is self for entry, _ in stack):
            stack.append((self, True))   # reentrant re-acquire: no edge
            return
        with st.mu:
            for entry, _re in stack:
                if entry._id != self._id:
                    key = (entry._id, self._id)
                    st.edges[key] = st.edges.get(key, 0) + 1
        stack.append((self, False))
        self._acquired_at = time.perf_counter()

    def release(self):
        st = _state
        if st is not None:
            stack = st.stack()
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] is self:
                    _entry, was_reentrant = stack.pop(i)
                    if not was_reentrant:
                        dt = time.perf_counter() - self._acquired_at
                        with st.mu:
                            rec = st.holds.setdefault(self._id, [0, 0.0, 0.0])
                            rec[0] += 1
                            rec[1] += dt
                            rec[2] = max(rec[2], dt)
                    break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") else False

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<lockdep {self._id} {self._inner!r}>"


def _make_factory(real, reentrant: bool):
    def factory():
        st = _state
        site = _caller_site()
        lock_id = None
        if st is not None and site is not None:
            lock_id = st.site_map.get(site)
        if lock_id is None:
            lock_id = f"{site[0]}:{site[1]}" if site else "<unknown>"
        if st is not None:
            with st.mu:
                st.n_locks += 1
        return _InstrumentedLock(real(), lock_id, reentrant)

    return factory


def _instrumented_condition(lock=None):
    # Condition over an instrumented lock works through the wrapper's
    # acquire/release (no _release_save shortcut — see threading.Condition)
    return _REAL_CONDITION(lock if lock is not None else threading.Lock())


def install(site_map: dict | None = None) -> None:
    """Swap the threading lock factories for instrumented ones. ``site_map``
    maps (repo-relative path, lineno) -> static lock id; when omitted it is
    computed from the static pass so runtime ids match the static graph."""
    global _state
    if _state is not None:
        return
    _state = _LockdepState()
    if site_map is None:
        index = _parse_tree(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        site_map = {
            (rel.replace(os.sep, "/"), ln): lock_id
            for (rel, ln), lock_id in index.lock_sites.items()
        }
    _state.site_map = dict(site_map)
    threading.Lock = _make_factory(_REAL_LOCK, False)
    threading.RLock = _make_factory(_REAL_RLOCK, True)
    threading.Condition = _instrumented_condition


def uninstall() -> None:
    global _state
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _state = None


def installed() -> bool:
    return _state is not None


def reset_observed() -> None:
    if _state is not None:
        with _state.mu:
            _state.edges.clear()
            _state.holds.clear()


def observed_report() -> dict:
    """The runtime side of the cert: observed edges + hold times."""
    if _state is None:
        return {"edges": [], "holds": {}, "n_locks": 0}
    with _state.mu:
        edges = [
            {"from": a, "to": b, "count": c}
            for (a, b), c in sorted(_state.edges.items())
        ]
        holds = {
            k: {
                "acquisitions": v[0],
                "total_hold_s": round(v[1], 6),
                "max_hold_s": round(v[2], 6),
            }
            for k, v in sorted(_state.holds.items())
        }
        return {"edges": edges, "holds": holds, "n_locks": _state.n_locks}


def merge_observed(static_edges: dict, observed_edges: list) -> dict:
    """Cross-validate: merge observed acquisition-order edges into the
    static graph, re-check acyclicity, report coverage (static edges never
    seen at runtime) and runtime edges the static pass missed."""
    combined: dict[tuple, tuple] = dict(static_edges)
    obs_pairs = set()
    for e in observed_edges:
        pair = (e["from"], e["to"])
        obs_pairs.add(pair)
        combined.setdefault(pair, ("<observed>", 0))
    cycles = _find_cycles(combined)
    static_pairs = set(static_edges)
    return {
        "ok": not cycles,
        "n_static_edges": len(static_pairs),
        "n_observed_edges": len(obs_pairs),
        "observed_only_edges": sorted(
            f"{a} -> {b}" for (a, b) in obs_pairs - static_pairs
        ),
        "static_edges_unobserved": sorted(
            f"{a} -> {b}" for (a, b) in static_pairs - obs_pairs
        ),
        "merged_cycles": [" -> ".join(c) for c in cycles],
    }
