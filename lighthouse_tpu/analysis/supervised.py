"""Pass 4 — the supervisor-transparency probe (ISSUE 7).

The fault-domain supervisor (``resilience.supervisor``) wraps every jitted
device call on the serving path. The wrapper must be *invisible* to XLA:
it passes arguments through untouched (same shapes, same dtypes, same
callable identity), so it may add exactly ZERO steady-state recompiles —
one stray recompile per supervised call is the hazard the recompilation
sentinel exists to catch, multiplied across the whole hot path.

This pass proves three properties, cheaply enough for the hunter preflight:

1. the ``resilience`` package itself lints clean under the trace-hygiene
   rules (its jit-facing wrappers introduce no host-sync/tracer-branch
   anti-patterns);
2. running a jitted kernel through ``run_ladder`` triggers no compilation
   after warm-up (watchdog thread included — jit dispatch from the worker
   thread must hit the same executable cache);
3. the supervised result is the kernel's result, bit for bit.
"""

from __future__ import annotations

import os


def supervisor_probe(steps: int = 4) -> dict:
    """Run the three checks; returns a report dict with ``ok``."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..resilience.supervisor import BackendSupervisor, SupervisorConfig
    from .hygiene import lint_tree
    from .recompile import steady_state_compiles

    res_root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "resilience",
    )
    findings, _suppressed = lint_tree(root=res_root)

    kern = jax.jit(lambda x: (x * 3 + 1).sum())
    x = jnp.arange(128, dtype=jnp.int32)
    bare = int(np.asarray(kern(x)))
    # direct construction: the probe supervisor stays OUT of the global
    # registry so it never shows up in /health or bench integrity stamps
    sup = BackendSupervisor(
        "analysis.supervisor_probe", SupervisorConfig(deadline_s=60.0)
    )

    def step():
        return sup.run_ladder(
            "analysis.probe", (("device_full", lambda: kern(x)),)
        )

    recompiles = steady_state_compiles(step, warmup=2, steps=steps)
    supervised = int(np.asarray(step()))
    transparent = supervised == bare
    return {
        "ok": not findings and not recompiles and transparent,
        "lint_findings": [f.as_dict() for f in findings],
        "steady_state_compiles": recompiles,
        "transparent": transparent,
        "supervised_calls": sup.calls,
    }
