"""Static-analysis subsystem: machine-checked kernel + concurrency
certification.

Six passes, run in tier-1 CI (``tests/test_analysis.py``), by the TPU
window hunter's preflight (``tools_tpu_hunter.py``), and by hand via
``python -m lighthouse_tpu.analysis``:

* **Pass 1 — limb-bound certifier** (``bounds.py``): re-executes every
  fq/fq2 op graph abstractly (``jax.eval_shape``) with a certification sink
  installed in ``ops/bls/fq.py``/``plans.py``, so every statically-derived
  bound — f64/f32 convolution exactness, u32/u64 accumulator wrap safety,
  reduction-walk targets, lazy ``CHAIN_BOUND`` fixed points — is recorded
  as a (proven, declared) proof obligation per conv backend. Emits
  ``BOUNDS_CERT.json``; any unproven edge fails the pass loudly.
* **Pass 2 — trace-hygiene linter** (``hygiene.py``): an AST pass over
  ``lighthouse_tpu/`` flagging jit anti-patterns (host syncs, Python
  branches on tracers, unhashable static-argnum values, impure closures)
  with a ``# lint: allow(<rule>)`` pragma and a checked-in baseline.
* **Pass 3 — recompilation sentinel** (``recompile.py``): a
  compilation-count hook (``jax_log_compiles`` capture) asserting that
  steady-state loops — the firehose verify pipeline, the epoch-engine
  sweep — trigger ZERO recompiles after warm-up; ``recompile_probe()``
  is the CLI's cheap in-process check of the capture plumbing.
* **Pass 4 — supervisor-transparency probe** (``supervised.py``): the
  resilience wrappers lint clean, add zero steady-state recompiles, and
  return the kernel's result bit for bit.
* **Pass 5 — concurrency certifier** (``concurrency.py``): lock-discipline
  proofs over every module importing ``threading`` (guard inference,
  unguarded shared mutations, thread-lifecycle joins), a package-wide
  acquires-while-holding lock-order graph that must stay acyclic with a
  blocking-call-under-lock rule, and an env-gated runtime lockdep wrapper
  (``LIGHTHOUSE_LOCKDEP=1``) whose observed acquisition orders are merged
  back into the static graph. Emits ``CONCURRENCY_CERT.json``.
* **Pass 6 — device-memory certifier & footprint planner** (``memory.py``):
  abstractly re-executes every registry graph under all three conv
  backends x both batch regimes, recording argument/output/temp/peak
  bytes per row (``jax.eval_shape`` + a jaxpr liveness walk, with XLA's
  lowered-computation cost analysis cross-checking a subset); walks every
  pallas VMEM tile signature against declared per-tier VMEM caps; models
  the five device-resident subsystem plane families (epoch mirror,
  slasher spans, LC committee cache, KZG tables, firehose staging) as
  static ``*_bytes(config)`` functions parity-pinned against real
  ``device_put`` accounting; and derives ``max_safe_shape(graph, tier)``
  so the TPU window hunter skips unfittable rungs with a logged verdict.
  Emits ``MEMORY_CERT.json``; a row that fits no declared finite tier
  fails the certificate exactly like a tripped bound.
"""

from .bounds import certify, certify_callable, write_cert  # noqa: F401
from .concurrency import (  # noqa: F401
    certify_concurrency,
    lockdep_enabled,
    merge_observed,
)
from .hygiene import lint_tree  # noqa: F401
from .memory import (  # noqa: F401
    DEVICE_TIERS,
    certify_memory,
    epoch_mirror_bytes,
    fault_memory_context,
    firehose_staging_bytes,
    kzg_table_bytes,
    lc_committee_cache_bytes,
    max_safe_shape,
    rung_fit,
    slasher_span_bytes,
)
from .recompile import (  # noqa: F401
    CompilationSentinel,
    recompile_probe,
    steady_state_compiles,
)
