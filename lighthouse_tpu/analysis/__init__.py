"""Static-analysis subsystem: machine-checked kernel certification.

Three passes, run in tier-1 CI (``tests/test_analysis.py``), by the TPU
window hunter's preflight (``tools_tpu_hunter.py``), and by hand via
``python -m lighthouse_tpu.analysis``:

* **Pass 1 — limb-bound certifier** (``bounds.py``): re-executes every
  fq/fq2 op graph abstractly (``jax.eval_shape``) with a certification sink
  installed in ``ops/bls/fq.py``/``plans.py``, so every statically-derived
  bound — f64/f32 convolution exactness, u32/u64 accumulator wrap safety,
  reduction-walk targets, lazy ``CHAIN_BOUND`` fixed points — is recorded
  as a (proven, declared) proof obligation per conv backend. Emits
  ``BOUNDS_CERT.json``; any unproven edge fails the pass loudly.
* **Pass 2 — trace-hygiene linter** (``hygiene.py``): an AST pass over
  ``lighthouse_tpu/`` flagging jit anti-patterns (host syncs, Python
  branches on tracers, unhashable static-argnum values, impure closures)
  with a ``# lint: allow(<rule>)`` pragma and a checked-in baseline.
* **Pass 3 — recompilation sentinel** (``recompile.py``): a
  compilation-count hook (``jax_log_compiles`` capture) asserting that
  steady-state loops — the firehose verify pipeline, the epoch-engine
  sweep — trigger ZERO recompiles after warm-up.
"""

from .bounds import certify, certify_callable, write_cert  # noqa: F401
from .hygiene import lint_tree  # noqa: F401
from .recompile import CompilationSentinel, steady_state_compiles  # noqa: F401
