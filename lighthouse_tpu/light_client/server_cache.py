"""Light-client server cache (ref light_client_server_cache.rs).

Subscribes to the chain's block-import seam. Each altair+ block's sync
aggregate attests the PARENT header; when participation meets
MIN_SYNC_COMMITTEE_PARTICIPANTS the cache refreshes its latest optimistic and
finality updates. Bootstraps are computed on demand from a held state.
"""

from __future__ import annotations

import numpy as np

from ..types.containers import BeaconBlockHeader
from .proofs import field_branch
from .types import light_client_types


def _header_for(signed_block) -> BeaconBlockHeader:
    blk = signed_block.message
    return BeaconBlockHeader(
        slot=int(blk.slot),
        proposer_index=int(blk.proposer_index),
        parent_root=bytes(blk.parent_root),
        state_root=bytes(blk.state_root),
        body_root=type(blk.body).hash_tree_root(blk.body),
    )


class LightClientServerCache:
    def __init__(self, chain):
        self.chain = chain
        self.latest_optimistic = None
        self.latest_finality = None
        chain.block_observers.append(self.on_imported_block)

    def _types_at_slot(self, slot: int):
        """Branch depths follow the fork's state-tree depth."""
        fork = self.chain.spec.fork_name_at_slot(int(slot))
        return light_client_types(self.chain.spec.preset.name, fork)

    # -- ingest (block_observers seam) --------------------------------------

    def on_imported_block(self, signed_block) -> None:
        blk = signed_block.message
        agg = getattr(blk.body, "sync_aggregate", None)
        if agg is None:
            return
        bits = np.asarray(agg.sync_committee_bits, dtype=bool)
        if bits.sum() < self.chain.spec.preset.MIN_SYNC_COMMITTEE_PARTICIPANTS:
            return
        parent_root = bytes(blk.parent_root)
        attested_block = self.chain._blocks.get(parent_root)
        attested_state = self.chain._states.get(parent_root)
        if attested_block is None or attested_state is None:
            return
        # recency guard: a late import of an OLDER block must not regress
        # the served updates (light_client_server_cache.rs is-latest check)
        if (
            self.latest_optimistic is not None
            and int(blk.slot)
            <= int(self.latest_optimistic.signature_slot)
        ):
            return
        t = self._types_at_slot(int(attested_block.message.slot))
        attested_header = t.LightClientHeader(
            beacon=_header_for(attested_block)
        )
        self.latest_optimistic = t.LightClientOptimisticUpdate(
            attested_header=attested_header,
            sync_aggregate=agg,
            signature_slot=int(blk.slot),
        )
        fin_cp = attested_state.finalized_checkpoint
        fin_root = bytes(fin_cp.root)
        fin_block = self.chain._blocks.get(fin_root)
        if fin_block is None or fin_root == b"\x00" * 32:
            return
        self.latest_finality = t.LightClientFinalityUpdate(
            attested_header=attested_header,
            finalized_header=t.LightClientHeader(
                beacon=_header_for(fin_block)
            ),
            finality_branch=field_branch(
                attested_state, ["finalized_checkpoint", "root"]
            ),
            sync_aggregate=agg,
            signature_slot=int(blk.slot),
        )

    # -- serving ------------------------------------------------------------

    def bootstrap(self, block_root: bytes):
        """LightClientBootstrap for a held block root (the trusted checkpoint
        a light client starts from)."""
        root = bytes(block_root)
        state = self.chain.state_by_root(root)
        if state is None or not hasattr(state, "current_sync_committee"):
            return None
        sb = self.chain._blocks.get(root)
        if sb is not None:
            header = _header_for(sb)
        elif root == self.chain.genesis_block_root:
            # the anchor has no SignedBeaconBlock: its header is the state's
            # latest_block_header with the state root filled in
            header = state.latest_block_header.copy()
            if bytes(header.state_root) == b"\x00" * 32:
                header.state_root = state.tree_root()
        else:
            return None
        t = self._types_at_slot(int(header.slot))
        return t.LightClientBootstrap(
            header=t.LightClientHeader(beacon=header),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=field_branch(
                state, ["current_sync_committee"]
            ),
        )
