"""Light-client server cache (ref light_client_server_cache.rs).

Subscribes to the chain's block-import seam. Each altair+ block's sync
aggregate attests the PARENT header; when participation meets
MIN_SYNC_COMMITTEE_PARTICIPANTS the cache refreshes its latest optimistic and
finality updates, produces a full ``LightClientUpdate`` (next sync committee
+ branch, finality proof when the attested state has one) into the
period-indexed ``LightClientUpdateStore``, and emits the standard
``light_client_optimistic_update`` / ``light_client_finality_update`` SSE
events. Bootstraps are computed on demand from a held state.

Every chain read goes through ``chain.get_signed_block`` /
``chain.state_by_root`` — the finalization migration prunes the in-memory
hot maps, and reading them directly silently dropped bootstraps and
finality updates below the finalized horizon (the same truncation class the
``blocks_by_range`` fix covered).
"""

from __future__ import annotations

import numpy as np

from ..types.containers import BeaconBlockHeader, for_preset
from .proofs import field_branch
from .types import light_client_types, state_tree_depth
from .update_store import LightClientUpdateStore


def _header_for(signed_block) -> BeaconBlockHeader:
    blk = signed_block.message
    return BeaconBlockHeader(
        slot=int(blk.slot),
        proposer_index=int(blk.proposer_index),
        parent_root=bytes(blk.parent_root),
        state_root=bytes(blk.state_root),
        body_root=type(blk.body).hash_tree_root(blk.body),
    )


def _participation(update_or_agg) -> int:
    agg = getattr(update_or_agg, "sync_aggregate", update_or_agg)
    return int(np.asarray(agg.sync_committee_bits, dtype=bool).sum())


class LightClientServerCache:
    def __init__(self, chain):
        self.chain = chain
        self.latest_optimistic = None
        self.latest_finality = None
        # period-indexed full-update archive; rides the chain's hot KV
        # store when one exists so the archive survives restarts
        kv = getattr(getattr(chain, "store", None), "hot", None)
        self.update_store = LightClientUpdateStore(chain.spec, kv)
        chain.block_observers.append(self.on_imported_block)

    def _types_at_slot(self, slot: int):
        """Branch depths follow the fork's state-tree depth."""
        fork = self.chain.spec.fork_name_at_slot(int(slot))
        return light_client_types(self.chain.spec.preset.name, fork)

    # -- ingest (block_observers seam) --------------------------------------

    def on_imported_block(self, signed_block) -> None:
        blk = signed_block.message
        agg = getattr(blk.body, "sync_aggregate", None)
        if agg is None:
            return
        bits = np.asarray(agg.sync_committee_bits, dtype=bool)
        if bits.sum() < self.chain.spec.preset.MIN_SYNC_COMMITTEE_PARTICIPANTS:
            return
        parent_root = bytes(blk.parent_root)
        attested_block = self.chain.get_signed_block(parent_root)
        attested_state = self.chain.state_by_root(parent_root)
        if attested_block is None or attested_state is None:
            return
        # recency guard (light_client_server_cache.rs is-latest check) with
        # the participation refinement: a late import of an OLDER block must
        # not regress the served updates, but a SAME-slot aggregate with
        # more participants is a strictly better proof and replaces it
        if self.latest_optimistic is not None:
            latest_slot = int(self.latest_optimistic.signature_slot)
            if int(blk.slot) < latest_slot or (
                int(blk.slot) == latest_slot
                and int(bits.sum()) <= _participation(self.latest_optimistic)
            ):
                return
        t = self._types_at_slot(int(attested_block.message.slot))
        attested_header = t.LightClientHeader(
            beacon=_header_for(attested_block)
        )
        self.latest_optimistic = t.LightClientOptimisticUpdate(
            attested_header=attested_header,
            sync_aggregate=agg,
            signature_slot=int(blk.slot),
        )
        self._emit("light_client_optimistic_update", self.latest_optimistic)

        fin_header, fin_branch = self._finality_proof(attested_state, t)
        if fin_header is not None:
            self.latest_finality = t.LightClientFinalityUpdate(
                attested_header=attested_header,
                finalized_header=fin_header,
                finality_branch=fin_branch,
                sync_aggregate=agg,
                signature_slot=int(blk.slot),
            )
            self._emit("light_client_finality_update", self.latest_finality)

        self._consider_full_update(
            t, attested_header, attested_state, agg, int(blk.slot),
            fin_header, fin_branch,
        )

    def _finality_proof(self, attested_state, t):
        """(finalized LightClientHeader, branch) from the attested state,
        or (None, None) when it has no finalized ancestor we hold."""
        fin_root = bytes(attested_state.finalized_checkpoint.root)
        if fin_root == b"\x00" * 32:
            return None, None
        fin_block = self.chain.get_signed_block(fin_root)
        if fin_block is None:
            return None, None
        return (
            t.LightClientHeader(beacon=_header_for(fin_block)),
            field_branch(attested_state, ["finalized_checkpoint", "root"]),
        )

    def _consider_full_update(
        self, t, attested_header, attested_state, agg, signature_slot,
        fin_header, fin_branch,
    ):
        """Full LightClientUpdate (the period-rollover product: next sync
        committee + REAL branch) ranked into the period archive. A missing
        finality proof becomes the spec's empty proof (zeroed header +
        zero branch), never a fabricated one."""
        if not hasattr(attested_state, "next_sync_committee"):
            return
        spec = self.chain.spec
        fork = spec.fork_name_at_slot(int(attested_header.beacon.slot))
        depth = state_tree_depth(for_preset(spec.preset.name).state_types[fork])
        if fin_header is None:
            fin_header = t.LightClientHeader(
                beacon=BeaconBlockHeader(
                    slot=0,
                    proposer_index=0,
                    parent_root=b"\x00" * 32,
                    state_root=b"\x00" * 32,
                    body_root=b"\x00" * 32,
                )
            )
            fin_branch = [b"\x00" * 32] * (depth + 1)
        update = t.LightClientUpdate(
            attested_header=attested_header,
            next_sync_committee=attested_state.next_sync_committee,
            next_sync_committee_branch=field_branch(
                attested_state, ["next_sync_committee"]
            ),
            finalized_header=fin_header,
            finality_branch=fin_branch,
            sync_aggregate=agg,
            signature_slot=signature_slot,
        )
        self.update_store.consider(update)

    def _emit(self, topic: str, update) -> None:
        emit = getattr(self.chain, "_emit_event", None)
        if emit is None:
            return
        emit(
            topic,
            lambda: {
                "signature_slot": str(int(update.signature_slot)),
                "attested_slot": str(int(update.attested_header.beacon.slot)),
                "data": "0x" + type(update).encode(update).hex(),
            },
        )

    # -- serving ------------------------------------------------------------

    def bootstrap(self, block_root: bytes):
        """LightClientBootstrap for a held block root (the trusted checkpoint
        a light client starts from). Reads through the persistent store so
        pre-finalization-horizon roots keep serving after the migration
        prunes the hot maps."""
        root = bytes(block_root)
        state = self.chain.state_by_root(root)
        if state is None or not hasattr(state, "current_sync_committee"):
            return None
        sb = self.chain.get_signed_block(root)
        if sb is not None:
            header = _header_for(sb)
        elif root == self.chain.genesis_block_root:
            # the anchor has no SignedBeaconBlock: its header is the state's
            # latest_block_header with the state root filled in
            header = state.latest_block_header.copy()
            if bytes(header.state_root) == b"\x00" * 32:
                header.state_root = state.tree_root()
        else:
            return None
        t = self._types_at_slot(int(header.slot))
        return t.LightClientBootstrap(
            header=t.LightClientHeader(beacon=header),
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=field_branch(
                state, ["current_sync_committee"]
            ),
        )

    def updates_by_range(self, start_period: int, count: int) -> list:
        """Best full update per period in the requested range (the
        ``/eth/v1/beacon/light_client/updates`` + UpdatesByRange payload)."""
        return self.update_store.get_updates(start_period, count)
