"""LightClient SSZ containers (ref consensus/types/src/light_client_*.rs).

Altair-shape headers (beacon only); built per (preset, fork) since branch
vector lengths derive from the fork's state-tree depth (electra's 37-field
state deepens every proof by one level).
"""

from __future__ import annotations

from functools import lru_cache

from ..ssz import Container, Vector, uint64
from ..ssz.merkle import next_pow2
from ..types.containers import BeaconBlockHeader, Root, for_preset


def state_tree_depth(state_cls) -> int:
    return (next_pow2(len(state_cls.FIELDS)) - 1).bit_length()


def light_client_types(preset_name: str, fork: str = "altair"):
    # normalize BEFORE the cache: ("minimal",) and ("minimal", "altair")
    # must yield the SAME classes or isinstance checks (the wire codec's
    # fork scan) silently fail across call sites
    return _light_client_types(preset_name, fork)


@lru_cache(maxsize=None)
def _light_client_types(preset_name: str, fork: str):
    ns = for_preset(preset_name)
    depth = state_tree_depth(ns.state_types[fork])
    finality_depth = depth + 1  # + the Checkpoint container level

    class LightClientHeader(Container):
        FIELDS = [("beacon", BeaconBlockHeader)]

    class LightClientBootstrap(Container):
        FIELDS = [
            ("header", LightClientHeader),
            ("current_sync_committee", ns.SyncCommittee),
            ("current_sync_committee_branch", Vector(Root, depth)),
        ]

    class LightClientUpdate(Container):
        FIELDS = [
            ("attested_header", LightClientHeader),
            ("next_sync_committee", ns.SyncCommittee),
            ("next_sync_committee_branch", Vector(Root, depth)),
            ("finalized_header", LightClientHeader),
            ("finality_branch", Vector(Root, finality_depth)),
            ("sync_aggregate", ns.SyncAggregate),
            ("signature_slot", uint64),
        ]

    class LightClientFinalityUpdate(Container):
        FIELDS = [
            ("attested_header", LightClientHeader),
            ("finalized_header", LightClientHeader),
            ("finality_branch", Vector(Root, finality_depth)),
            ("sync_aggregate", ns.SyncAggregate),
            ("signature_slot", uint64),
        ]

    class LightClientOptimisticUpdate(Container):
        FIELDS = [
            ("attested_header", LightClientHeader),
            ("sync_aggregate", ns.SyncAggregate),
            ("signature_slot", uint64),
        ]

    from types import SimpleNamespace

    return SimpleNamespace(
        LightClientHeader=LightClientHeader,
        LightClientBootstrap=LightClientBootstrap,
        LightClientUpdate=LightClientUpdate,
        LightClientFinalityUpdate=LightClientFinalityUpdate,
        LightClientOptimisticUpdate=LightClientOptimisticUpdate,
    )
