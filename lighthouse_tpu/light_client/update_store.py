"""Period-indexed LightClientUpdate archive (spec ``get_light_client_update``
serving side + ref ``light_client_server_cache.rs`` best-update tracking).

One best ``LightClientUpdate`` per sync-committee period, ranked by the spec
``is_better_update`` total order (supermajority first, then committee /
finality relevance, then participation, then age). Accepted updates are
persisted to the hot KV store as SINGLE WAL frames (key = 8-byte BE period,
value = fork byte + SSZ) so a restart serves the same archive — on a
durable ``LevelStore`` each accept is one crash-atomic commit.
"""

from __future__ import annotations

import struct

import numpy as np

from ..store.kv import DBColumn
from .types import light_client_types

# matches network/codec.py's fork tagging (kept local: light_client must not
# import the network layer)
_FORK_ORDER = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]

_ZERO_ROOT = b"\x00" * 32


def sync_committee_period(spec, slot: int) -> int:
    return spec.compute_epoch_at_slot(int(slot)) // int(
        spec.preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    )


def _num_active(update) -> int:
    return int(
        np.asarray(
            update.sync_aggregate.sync_committee_bits, dtype=bool
        ).sum()
    )


def _is_sync_committee_update(update) -> bool:
    return any(
        bytes(b) != _ZERO_ROOT for b in update.next_sync_committee_branch
    )


def _is_finality_update(update) -> bool:
    return any(bytes(b) != _ZERO_ROOT for b in update.finality_branch)


def is_better_update(spec, new, old) -> bool:
    """The spec's ``is_better_update`` total order (sync-protocol.md):
    True when ``new`` should replace ``old`` for its period."""
    max_active = int(spec.preset.SYNC_COMMITTEE_SIZE)
    new_active, old_active = _num_active(new), _num_active(old)
    new_super = new_active * 3 >= max_active * 2
    old_super = old_active * 3 >= max_active * 2
    if new_super != old_super:
        return new_super
    if not new_super and new_active != old_active:
        return new_active > old_active

    # relevant sync committee: the committee branch is populated AND the
    # attested header sits in the period the signature slot belongs to
    new_rel = _is_sync_committee_update(new) and sync_committee_period(
        spec, int(new.attested_header.beacon.slot)
    ) == sync_committee_period(spec, int(new.signature_slot))
    old_rel = _is_sync_committee_update(old) and sync_committee_period(
        spec, int(old.attested_header.beacon.slot)
    ) == sync_committee_period(spec, int(old.signature_slot))
    if new_rel != old_rel:
        return new_rel

    new_fin, old_fin = _is_finality_update(new), _is_finality_update(old)
    if new_fin != old_fin:
        return new_fin

    # sync-committee finality: the finalized header lives in the attested
    # header's period, so applying the update cannot skip a committee
    if new_fin:
        new_cf = sync_committee_period(
            spec, int(new.finalized_header.beacon.slot)
        ) == sync_committee_period(spec, int(new.attested_header.beacon.slot))
        old_cf = old_fin and sync_committee_period(
            spec, int(old.finalized_header.beacon.slot)
        ) == sync_committee_period(spec, int(old.attested_header.beacon.slot))
        if new_cf != old_cf:
            return new_cf

    if new_active != old_active:
        return new_active > old_active
    if int(new.attested_header.beacon.slot) != int(
        old.attested_header.beacon.slot
    ):
        return int(new.attested_header.beacon.slot) < int(
            old.attested_header.beacon.slot
        )
    return int(new.signature_slot) < int(old.signature_slot)


class LightClientUpdateStore:
    """Best update per period, optionally backed by a KV store.

    ``kv`` is any ``store.kv.KeyValueStore`` (the chain passes its hot
    store); ``None`` keeps the archive memory-only. Known periods are
    restored from the column on construction — a restarted node serves its
    archive without re-seeing the blocks."""

    def __init__(self, spec, kv=None):
        self.spec = spec
        self._kv = kv
        self._best: dict[int, object] = {}
        if kv is not None:
            self._restore()

    # -- persistence --------------------------------------------------------

    def _decode_frame(self, value: bytes):
        fork = _FORK_ORDER[value[0]]
        cls = light_client_types(
            self.spec.preset.name, fork
        ).LightClientUpdate
        return cls.decode(value[1:])

    def _restore(self) -> None:
        for key, value in self._kv.iter_column(DBColumn.LightClientUpdate):
            if len(key) != 8 or not value:
                continue
            period = struct.unpack(">Q", key)[0]
            try:
                self._best[period] = self._decode_frame(value)
            except Exception:  # noqa: BLE001 — a bad row is skipped, not fatal
                continue

    def _load(self, period: int):
        """Read-through backfill: a period absent from the hot map (pruned
        to bound memory, or skipped by a partial restore) is fetched from
        its persisted KV frame and re-cached, so ``updates_by_range``
        serves the full archive over both HTTP and Req/Resp."""
        if self._kv is None:
            return None
        value = self._kv.get(
            DBColumn.LightClientUpdate, struct.pack(">Q", int(period))
        )
        if not value:
            return None
        try:
            update = self._decode_frame(value)
        except Exception:  # noqa: BLE001 — a bad row serves nothing
            return None
        self._best[int(period)] = update
        return update

    def _get(self, period: int):
        u = self._best.get(int(period))
        return u if u is not None else self._load(period)

    def _persist(self, period: int, update) -> None:
        if self._kv is None:
            return
        fork = self.spec.fork_name_at_slot(int(update.signature_slot))
        value = bytes([_FORK_ORDER.index(fork)]) + type(update).encode(update)
        # ONE frame per accept: crash-atomic on LevelStore-backed nodes
        self._kv.do_atomically(
            [
                (
                    "put",
                    DBColumn.LightClientUpdate,
                    struct.pack(">Q", period),
                    value,
                )
            ]
        )

    # -- ranking ------------------------------------------------------------

    def consider(self, update) -> bool:
        """Rank ``update`` against the period's incumbent; keep + persist
        the winner. Returns True when ``update`` became the served one."""
        period = sync_committee_period(
            self.spec, int(update.attested_header.beacon.slot)
        )
        # read-through: a pruned period's persisted incumbent still ranks
        old = self._get(period)
        if old is not None and not is_better_update(self.spec, update, old):
            return False
        self._best[period] = update
        self._persist(period, update)
        return True

    # -- serving ------------------------------------------------------------

    def get_updates(self, start_period: int, count: int) -> list:
        """Best updates for ``[start_period, start_period + count)`` —
        periods with no update are skipped (the API contract: the response
        carries what the server holds, in period order). Periods missing
        from the hot map read through to their persisted KV frames."""
        out = []
        for p in range(int(start_period), int(start_period) + int(count)):
            u = self._get(p)
            if u is not None:
                out.append(u)
        return out

    def best(self, period: int):
        return self._get(int(period))

    def prune_hot(self, keep: int) -> int:
        """Evict all but the newest ``keep`` periods from the hot map. The
        KV frames stay — serving reads pruned periods back through
        ``_load`` on demand. Returns the number of evicted periods."""
        periods = sorted(self._best)
        evict = periods[: max(len(periods) - max(int(keep), 0), 0)]
        for p in evict:
            del self._best[p]
        return len(evict)

    def known_periods(self) -> list[int]:
        return sorted(self._best)

    def __len__(self) -> int:
        return len(self._best)
