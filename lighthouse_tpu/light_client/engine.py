"""Device-batched light-client serving engine — the third cryptosystem on
the plan compiler (ISSUE 17).

``verify_light_client_update`` runs one host pairing per session; this
engine folds a whole batch of heterogeneous sessions (distinct periods,
bitfields, attested roots) into ONE device dispatch (see ``ops/lc/verify``
for the math) behind the ``LIGHTHOUSE_LC_BACKEND = auto | device | host``
seam that mirrors the BLS / KZG / epoch / slasher seams:

* ``host``   — the per-session ``verify_light_client_update`` loop (the
  parity oracle).
* ``device`` — the batched graph: bitfield-masked committee aggregation
  over a device-resident per-period pubkey cache, device h2c for the
  signing roots, one shared-accumulator Miller product + one final
  exponentiation per batch. Data-parallel over period groups via the
  PR-10 shard planner when more than one local device is visible.
* ``auto``   — ``device`` iff JAX is backed by an accelerator.

The device path runs under the ``lc_device`` resilience domain (injection
stage ``lc.batch_verify``): ``device_full`` → ``device_reduced`` (split
halves) → ``cpu_oracle`` (the host loop). A fully faulted ladder reports
every session UNVERIFIED — light-client service FAILS CLOSED, a broken
device can never vouch for a session.
"""

from __future__ import annotations

import os
import secrets

import numpy as np

from ..resilience import SupervisedFault, lc_supervisor
from .verify import precheck_update, sync_signing_root, verify_light_client_update

_BACKEND = os.environ.get("LIGHTHOUSE_LC_BACKEND", "auto")
_AUTO_DECISION: bool | None = None


def set_lc_backend(name: str) -> None:
    global _BACKEND, _AUTO_DECISION
    if name not in ("auto", "device", "host"):
        raise ValueError(f"unknown lc backend {name!r}")
    _BACKEND = name
    _AUTO_DECISION = None


def get_lc_backend() -> str:
    return _BACKEND


def _accelerator_present() -> bool:
    global _AUTO_DECISION
    if _AUTO_DECISION is None:
        try:
            import jax

            _AUTO_DECISION = jax.devices()[0].platform in ("tpu", "gpu")
        except Exception:  # noqa: BLE001 — no jax / no devices: host path
            _AUTO_DECISION = False
    return _AUTO_DECISION


def device_backend_active() -> bool:
    if _BACKEND == "host":
        return False
    if _BACKEND == "device":
        return True
    return _accelerator_present()


# --------------------------------------------------------------------------------------
# The engine
# --------------------------------------------------------------------------------------


class LcEngine:
    """Committee cache + jitted stages for one chain spec's geometry.

    Committee pubkeys are decompressed ONCE per sync committee (keyed by
    the committee's hash tree root) into host projective limb rows; the
    device cache ``[P_pad, C, 3, 25]`` stacks every known committee so a
    batch mixing periods gathers different rows in the same dispatch.
    Stages are jitted separately (the firehose staged-compile lesson —
    one fused program compiled superlinearly)."""

    def __init__(self, spec):
        self.spec = spec
        self.committee_size = int(spec.preset.SYNC_COMMITTEE_SIZE)
        self._rows: dict[bytes, int] = {}    # committee root -> cache row
        self._host_rows: list[np.ndarray] = []
        self._cache = None                   # device [P_pad, C, 3, 25]
        self._cache_rows = 0
        self._jit = {}                       # stage name -> jitted fn

    # -- committee cache ----------------------------------------------------

    def committee_row(self, committee) -> int:
        """Cache row for a sync committee, decompressing its pubkeys on
        first sight (bls.PublicKey validates encodings + subgroup)."""
        key = bytes(type(committee).hash_tree_root(committee))
        row = self._rows.get(key)
        if row is None:
            from .. import bls
            from ..ops.bls import g1

            pts = [
                bls.PublicKey.from_bytes(bytes(pk)).point
                for pk in committee.pubkeys
            ]
            arr = np.asarray(g1.from_oracle_batch(pts))
            row = len(self._host_rows)
            self._rows[key] = row
            self._host_rows.append(arr)
            self._cache = None               # rebuilt (padded) on next use
        return row

    def _cache_arr(self):
        import jax.numpy as jnp

        from ..firehose.sharding import _bucket

        p = len(self._host_rows)
        p_pad = _bucket(p, floor=4)
        if self._cache is None or self._cache_rows != p_pad:
            stacked = np.stack(self._host_rows)
            if p_pad > p:
                pad = np.zeros((p_pad - p,) + stacked.shape[1:], stacked.dtype)
                stacked = np.concatenate([stacked, pad])
            self._cache = jnp.asarray(stacked)
            self._cache_rows = p_pad
            from ..utils import metrics

            metrics.LC_COMMITTEE_CACHE_BYTES.set(stacked.nbytes)
        return self._cache

    # -- jitted stages ------------------------------------------------------

    def _stage(self, name: str):
        fn = self._jit.get(name)
        if fn is None:
            import jax

            from ..ops.lc import verify

            fn = jax.jit(getattr(verify, name))
            self._jit[name] = fn
        return fn

    # -- marshalling --------------------------------------------------------

    def _marshal(self, sessions, genesis_validators_root: bytes, n_pad: int):
        """(update, committee) pairs -> padded device arrays. Signing
        roots and committee rows are host work; pad rows broadcast row 0's
        hash residues (never hash dummy messages) and carry valid=False."""
        import jax.numpy as jnp

        from ..bls.serde import parse_g2_bytes
        from ..ops.bls import h2c
        from ..ops.bls_oracle.ciphersuite import DST

        n = len(sessions)
        c = self.committee_size
        pidx = np.zeros(n_pad, dtype=np.int32)
        bits = np.zeros((n_pad, c), dtype=bool)
        sig_bytes = np.zeros((n_pad, 96), dtype=np.uint8)
        roots = []
        for i, (update, committee) in enumerate(sessions):
            pidx[i] = self.committee_row(committee)
            bits[i] = np.asarray(
                update.sync_aggregate.sync_committee_bits, dtype=bool
            )
            sig_bytes[i] = np.frombuffer(
                bytes(update.sync_aggregate.sync_committee_signature),
                dtype=np.uint8,
            )
            roots.append(
                sync_signing_root(self.spec, update, genesis_validators_root)
            )

        parsed = parse_g2_bytes(sig_bytes)
        sig_wf = parsed["wf_ok"] & ~parsed["is_inf"]
        u0, u1 = h2c.hash_to_field_batch(roots, DST)
        if n_pad > n:  # pad by broadcast, not by hashing dummy messages
            u0 = jnp.concatenate(
                [u0, jnp.broadcast_to(u0[:1], (n_pad - n,) + u0.shape[1:])]
            )
            u1 = jnp.concatenate(
                [u1, jnp.broadcast_to(u1[:1], (n_pad - n,) + u1.shape[1:])]
            )
        scalars = np.array(
            [secrets.randbits(64) or 1 for _ in range(n_pad)], dtype=np.uint64
        )
        valid = np.arange(n_pad) < n
        return (
            jnp.asarray(pidx), jnp.asarray(bits), u0, u1,
            jnp.asarray(parsed["x_c0"]), jnp.asarray(parsed["x_c1"]),
            jnp.asarray(parsed["s_flag"]), jnp.asarray(sig_wf),
            jnp.asarray(scalars), jnp.asarray(valid),
        )

    # -- verify -------------------------------------------------------------

    def _run_one(self, sessions, genesis_validators_root: bytes) -> bool:
        from ..firehose.sharding import _bucket

        n = len(sessions)
        if n == 0:
            return True
        n_pad = _bucket(n, floor=4)
        (pidx, bits, u0, u1, sxc0, sxc1, s_flag, sig_wf, scalars,
         valid) = self._marshal(sessions, genesis_validators_root, n_pad)
        cache = self._cache_arr()
        mxa, mya = self._stage("lc_h2c")(u0, u1)
        pkx, pky, sax, say, set_ok = self._stage("lc_prep")(
            cache, pidx, bits, sxc0, sxc1, s_flag, sig_wf, scalars, valid
        )
        ok = self._stage("lc_pair")(
            pkx, pky, sax, say, mxa, mya, set_ok, valid
        )
        return bool(np.asarray(ok))

    def verify_batch(self, sessions, genesis_validators_root: bytes) -> bool:
        """ONE combined pairing check for the whole batch of
        ``(update, committee)`` sessions — signature verdict only, the
        host prechecks (participation floor, merkle branches) are the
        dispatch layer's job. Splits into per-period-group shards when a
        multi-device mesh is visible (each shard still one check)."""
        n = len(sessions)
        if n == 0:
            return True
        try:
            import jax

            n_dev = jax.local_device_count()
        except Exception:  # noqa: BLE001 — no jax: host semantics
            n_dev = 1
        groups = _period_groups(
            [self.committee_row(c) for _, c in sessions]
        )
        if n_dev > 1 and len(groups) > 1:
            from ..firehose.sharding import plan_shards

            plan = plan_shards(groups, min(n_dev, len(groups)))
            for shard in plan.shard_items:
                if not shard:
                    continue
                if not self._run_one(
                    [sessions[i] for i in shard], genesis_validators_root
                ):
                    return False
            return True
        return self._run_one(sessions, genesis_validators_root)

    # -- instrumentation ----------------------------------------------------

    def compile_probe(self, batch: int, periods: int = 4) -> dict:
        """Trace (don't run) the composed batch graph and report what the
        LOWERED program contains: pairing checks per batch, pairs per
        check, masked aggregation sums. This is the 'one pairing check
        per batch' proof every bench --light-clients record embeds."""
        import functools as _ft

        import jax

        from ..firehose.sharding import _bucket
        from ..ops.bls import fq
        from ..ops.lc import verify

        n_pad, c = _bucket(batch, floor=4), self.committee_size
        u64, sd = np.uint64, jax.ShapeDtypeStruct
        specs = (
            sd((periods, c, 3, 25), u64),       # cache
            sd((n_pad,), np.int32),             # pidx
            sd((n_pad, c), bool),               # bits
            sd((n_pad, 2, 25), u64),            # u0
            sd((n_pad, 2, 25), u64),            # u1
            sd((n_pad, 25), u64),               # sxc0
            sd((n_pad, 25), u64),               # sxc1
            sd((n_pad,), u64),                  # s_flag
            sd((n_pad,), bool),                 # sig_wf
            sd((n_pad,), u64),                  # scalars
            sd((n_pad,), bool),                 # valid
        )
        before = dict(verify.PROBE)
        jax.jit(_ft.partial(verify.lc_batch_check)).lower(*specs)
        checks = verify.PROBE["pairing_checks"] - before["pairing_checks"]
        return {
            "batch": n_pad,
            "committee_size": c,
            "pairing_checks_per_batch_trace": checks,
            "pairs_per_check": (
                (verify.PROBE["pairs"] - before["pairs"]) // max(1, checks)
            ),
            "agg_sums_per_batch_trace": (
                verify.PROBE["agg_sums"] - before["agg_sums"]
            ),
            "conv_impl": fq.conv_backend(),
        }


def _period_groups(rows) -> list[list[int]]:
    """Group batch positions by committee cache row — the shard planner's
    whole-group unit (sessions of one period stay on one device)."""
    by_row: dict[int, list[int]] = {}
    for pos, r in enumerate(rows):
        by_row.setdefault(int(r), []).append(pos)
    return [by_row[r] for r in sorted(by_row)]


# --------------------------------------------------------------------------------------
# Module-level dispatch (the seam the serving tier and the bench call)
# --------------------------------------------------------------------------------------

_engines: dict[str, LcEngine] = {}


def get_engine(spec) -> LcEngine:
    eng = _engines.get(spec.preset.name)
    if eng is None:
        eng = _engines[spec.preset.name] = LcEngine(spec)
    return eng


def _device_verdicts(eng, spec, sessions, gvr, pre_ok, finality_required):
    """Per-session verdicts through the batched engine: host prechecks
    first (sessions failing them are False without touching the device),
    then one combined check over the rest; a failing batch bisects so one
    bad session cannot take honest neighbours down with it."""
    verdicts = list(pre_ok)
    live = [i for i, ok in enumerate(pre_ok) if ok]
    if not live:
        return verdicts

    def descend(idxs):
        if eng.verify_batch([sessions[i] for i in idxs], gvr):
            for i in idxs:
                verdicts[i] = True
            return
        if len(idxs) == 1:
            verdicts[idxs[0]] = False
            return
        mid = len(idxs) // 2
        descend(idxs[:mid])
        descend(idxs[mid:])

    descend(live)
    return verdicts


def verify_update_batch(
    spec, sessions, genesis_validators_root: bytes,
    finality_required: bool = False,
) -> list[bool]:
    """Backend-dispatched batch verification — THE serving entry point.
    ``sessions`` is a list of ``(update, sync_committee)`` pairs; returns
    one verdict per session. Host backend: the per-session oracle loop.
    Device backend: the batched engine under the ``lc_device`` degradation
    ladder; a fully faulted ladder FAILS CLOSED (every session reported
    unverified — never a false-verified session)."""
    gvr = bytes(genesis_validators_root)
    n = len(sessions)
    if n == 0:
        return []
    if not device_backend_active():
        return [
            verify_light_client_update(spec, u, c, gvr, finality_required)
            for u, c in sessions
        ]
    pre_ok = [
        precheck_update(spec, u, finality_required) for u, _ in sessions
    ]

    # engine construction (committee decompression + stage compiles) is
    # deferred INTO the device rungs: a ladder demoted to cpu_oracle — or
    # one whose device rungs fault before running — never pays it
    def device_full():
        return _device_verdicts(
            get_engine(spec), spec, sessions, gvr, pre_ok, finality_required
        )

    def device_reduced():
        # halved batches, fresh scalars: a shape-specific compile or
        # size-dependent numeric fault on the full graph doesn't take the
        # device path down with it
        eng = get_engine(spec)
        mid = max(1, n // 2)
        out = []
        for lo, hi in ((0, mid), (mid, n)):
            if lo == hi:
                continue
            out.extend(
                _device_verdicts(
                    eng, spec, sessions[lo:hi], gvr, pre_ok[lo:hi],
                    finality_required,
                )
            )
        return out

    def cpu_oracle():
        return [
            verify_light_client_update(spec, u, c, gvr, finality_required)
            for u, c in sessions
        ]

    try:
        return list(
            lc_supervisor().run_ladder(
                "lc.batch_verify",
                (
                    ("device_full", device_full),
                    ("device_reduced", device_reduced),
                    ("cpu_oracle", cpu_oracle),
                ),
            )
        )
    except SupervisedFault:
        return [False] * n  # fail CLOSED: never a false-verified session
