"""Light-client server + verification (ref ``consensus/types`` LightClient*
containers, ``beacon_chain/src/light_client_server_cache.rs``, and the spec's
altair light-client sync protocol).

The server cache subscribes to block imports: every altair+ block whose sync
aggregate meets MIN_SYNC_COMMITTEE_PARTICIPANTS yields an optimistic update
(the aggregate attests the parent header) and, when the attested state knows a
finalized header, a finality update. Bootstraps (header + current sync
committee + merkle branch) are served per finalized block root. Branches are
REAL SSZ proofs generated from the state's field tree
(ssz.merkle.merkle_branch_from_chunks) and verify against the spec
generalized indices (current=54, next=55, finality root=105 for a 32-field
state tree).

The mass-service tier (ISSUE 17) adds ``engine`` — device-batched update
verification (one combined pairing check per batch of heterogeneous
sessions behind ``LIGHTHOUSE_LC_BACKEND``, failing CLOSED under the
``lc_device`` resilience domain) — and ``update_store``, the
period-indexed, spec-ranked ``LightClientUpdate`` archive behind
``/eth/v1/beacon/light_client/updates`` and the LightClientUpdatesByRange
Req/Resp protocol.
"""

from .engine import (
    get_lc_backend,
    set_lc_backend,
    verify_update_batch,
)
from .proofs import field_branch
from .server_cache import LightClientServerCache
from .types import light_client_types
from .update_store import LightClientUpdateStore, is_better_update
from .verify import verify_light_client_update

__all__ = [
    "LightClientServerCache",
    "LightClientUpdateStore",
    "field_branch",
    "get_lc_backend",
    "is_better_update",
    "light_client_types",
    "set_lc_backend",
    "verify_light_client_update",
    "verify_update_batch",
]
