"""Light-client server + verification (ref ``consensus/types`` LightClient*
containers, ``beacon_chain/src/light_client_server_cache.rs``, and the spec's
altair light-client sync protocol).

The server cache subscribes to block imports: every altair+ block whose sync
aggregate meets MIN_SYNC_COMMITTEE_PARTICIPANTS yields an optimistic update
(the aggregate attests the parent header) and, when the attested state knows a
finalized header, a finality update. Bootstraps (header + current sync
committee + merkle branch) are served per finalized block root. Branches are
REAL SSZ proofs generated from the state's field tree
(ssz.merkle.merkle_branch_from_chunks) and verify against the spec
generalized indices (current=54, next=55, finality root=105 for a 32-field
state tree).
"""

from .proofs import field_branch
from .server_cache import LightClientServerCache
from .types import light_client_types
from .verify import verify_light_client_update

__all__ = [
    "LightClientServerCache",
    "field_branch",
    "light_client_types",
    "verify_light_client_update",
]
