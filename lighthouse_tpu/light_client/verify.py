"""Light-client update verification (spec process_light_client_update core).

A light client holding a trusted sync committee checks an update by (1)
verifying the merkle branches against the attested header's state root and
(2) verifying the sync aggregate over the attested header root with the
committee's pubkeys — the backend-blind ``bls`` seam does the pairing.
"""

from __future__ import annotations

import numpy as np

from .. import bls
from ..state_transition.per_block import is_valid_merkle_branch
from ..types.containers import SigningData, for_preset
from ..types.helpers import compute_domain

# altair..deneb 32-field state tree; electra+ recomputed per fork below
FINALIZED_ROOT_GINDEX = 105
CURRENT_SYNC_COMMITTEE_GINDEX = 54
NEXT_SYNC_COMMITTEE_GINDEX = 55


def _gindex_depth_index(gindex: int) -> tuple[int, int]:
    depth = gindex.bit_length() - 1
    return depth, gindex - (1 << depth)


def _state_gindex(spec, slot: int, path: list[str]) -> int:
    from .proofs import leaf_gindex

    fork = spec.fork_name_at_slot(int(slot))
    state_cls = for_preset(spec.preset.name).state_types[fork]
    return leaf_gindex(state_cls, path)


def verify_bootstrap(spec, bootstrap, trusted_block_root: bytes) -> bool:
    """header matches the trusted root and the committee branch proves
    membership in the header's state."""
    header_root = type(bootstrap.header.beacon).hash_tree_root(
        bootstrap.header.beacon
    )
    if header_root != bytes(trusted_block_root):
        return False
    depth, index = _gindex_depth_index(
        _state_gindex(
            spec, int(bootstrap.header.beacon.slot), ["current_sync_committee"]
        )
    )
    cls = type(bootstrap.current_sync_committee)
    return is_valid_merkle_branch(
        cls.hash_tree_root(bootstrap.current_sync_committee),
        list(bootstrap.current_sync_committee_branch),
        depth,
        index,
        bytes(bootstrap.header.beacon.state_root),
    )


def sync_signing_root(spec, update, genesis_validators_root: bytes) -> bytes:
    """The root the sync committee signs: the attested header root under
    the sync domain of the epoch before ``signature_slot``. Shared by the
    host oracle below and the device engine's marshalling."""
    prev_slot = max(int(update.signature_slot), 1) - 1
    fork_version = spec.fork_version(spec.fork_name_at_slot(prev_slot))
    domain = compute_domain(
        spec.DOMAIN_SYNC_COMMITTEE, fork_version, bytes(genesis_validators_root)
    )
    attested_root = type(update.attested_header.beacon).hash_tree_root(
        update.attested_header.beacon
    )
    return SigningData(object_root=attested_root, domain=domain).tree_root()


def precheck_update(spec, update, finality_required: bool = False) -> bool:
    """Everything BUT the signature: participation floor + the merkle
    branches present on the update (finality and, for full
    ``LightClientUpdate`` objects, the next-sync-committee branch). The
    device engine applies the same prechecks on the host before batching
    signatures, so host/device verdicts agree session-for-session."""
    agg = update.sync_aggregate
    bits = np.asarray(agg.sync_committee_bits, dtype=bool)
    if bits.sum() < spec.preset.MIN_SYNC_COMMITTEE_PARTICIPANTS:
        return False
    attested_slot = int(update.attested_header.beacon.slot)
    if hasattr(update, "finality_branch"):
        branch = [bytes(b) for b in update.finality_branch]
        # spec: a full LightClientUpdate may carry an EMPTY finality proof
        # (zeroed header + zero branch) when the signed period had no
        # finalized ancestor yet — skip the branch check, it proves nothing
        empty = int(update.finalized_header.beacon.slot) == 0 and all(
            b == b"\x00" * 32 for b in branch
        )
        if empty:
            if finality_required:
                return False
        else:
            depth, index = _gindex_depth_index(
                _state_gindex(
                    spec, attested_slot, ["finalized_checkpoint", "root"]
                )
            )
            fin_root = type(update.finalized_header.beacon).hash_tree_root(
                update.finalized_header.beacon
            )
            if not is_valid_merkle_branch(
                fin_root,
                branch,
                depth,
                index,
                bytes(update.attested_header.beacon.state_root),
            ):
                return False
    elif finality_required:
        return False
    if hasattr(update, "next_sync_committee_branch"):
        depth, index = _gindex_depth_index(
            _state_gindex(spec, attested_slot, ["next_sync_committee"])
        )
        cls = type(update.next_sync_committee)
        if not is_valid_merkle_branch(
            cls.hash_tree_root(update.next_sync_committee),
            list(update.next_sync_committee_branch),
            depth,
            index,
            bytes(update.attested_header.beacon.state_root),
        ):
            return False
    return True


def verify_light_client_update(
    spec, update, sync_committee, genesis_validators_root: bytes,
    finality_required: bool = False,
) -> bool:
    """Verify an optimistic/finality update against a trusted committee."""
    if not precheck_update(spec, update, finality_required):
        return False
    agg = update.sync_aggregate
    bits = np.asarray(agg.sync_committee_bits, dtype=bool)
    root = sync_signing_root(spec, update, genesis_validators_root)
    try:
        keys = [
            bls.PublicKey.from_bytes(bytes(sync_committee.pubkeys[i]))
            for i, b in enumerate(bits)
            if b
        ]
        sig = bls.Signature.from_bytes(bytes(agg.sync_committee_signature))
    except bls.BlsError:
        return False  # malformed encoding is a verdict, not an error
    return bls.verify_signature_sets(
        [bls.SignatureSet.multiple_pubkeys(sig, keys, root)]
    )
