"""SSZ merkle-branch generation through nested container fields.

The proof-generation counterpart of ``is_valid_merkle_branch`` (the reference
grows this inside ``consensus/merkle_proof`` / ``tree_hash``): walk a field
path down a container, emit each level's sibling branch bottom-up, so the
concatenated branch proves the leaf against the outer container's root under
the standard generalized-index layout.
"""

from __future__ import annotations

import numpy as np

from ..ssz.merkle import merkle_branch_from_chunks, next_pow2


def _field_roots(obj) -> np.ndarray:
    cls = type(obj)
    return np.stack(
        [
            np.frombuffer(t.hash_tree_root(getattr(obj, n)), dtype=np.uint8)
            for n, t in cls.FIELDS
        ]
    )


def field_branch(container, path: list[str]) -> list[bytes]:
    """Bottom-up sibling branch proving ``path``'s leaf inside ``container``'s
    hash tree root. Total depth = sum of per-level container depths; the leaf
    gindex is the standard nested generalized index."""
    steps = []
    obj = container
    for name in path:
        cls = type(obj)
        names = [n for n, _ in cls.FIELDS]
        idx = names.index(name)
        steps.append((obj, idx))
        obj = getattr(obj, name)
    out: list[bytes] = []
    for obj_at, idx in reversed(steps):
        roots = _field_roots(obj_at)
        limit = next_pow2(len(type(obj_at).FIELDS))
        out.extend(merkle_branch_from_chunks(roots, limit, idx))
    return out


def leaf_gindex(container_cls, path: list[str]) -> int:
    """Generalized index of ``path`` under ``container_cls`` (for spec
    cross-checks: altair current_sync_committee=54, next=55, finality
    root=105)."""
    g = 1
    cls = container_cls
    for name in path:
        names = [n for n, _ in cls.FIELDS]
        idx = names.index(name)
        depth = (next_pow2(len(names)) - 1).bit_length()
        g = (g << depth) + idx
        cls = dict(cls.FIELDS)[name]
    return g
