"""Router: pubsub + RPC dispatch into the beacon processor.

Twin of ``network/src/router.rs:381-535`` (one arm per PubsubMessage variant)
plus the ``NetworkBeaconProcessor`` packaging
(``network_beacon_processor/mod.rs:88-116``): every gossip message becomes a
``Work`` item with ``process_individual`` AND ``process_batch`` closures so
the scheduler can form attestation/aggregate batches for the device backend
(``gossip_methods.rs:198,230``).
"""

from __future__ import annotations

import time

from ..beacon_processor.processor import Work, WorkType
from ..loadshed import DEFAULT_SLOT_SECONDS, deadline_for
from .transport import Topic


class Router:
    def __init__(self, service):
        self.svc = service
        try:
            self._slot_seconds = float(
                service.chain.spec.preset.SECONDS_PER_SLOT
            )
        except AttributeError:
            self._slot_seconds = DEFAULT_SLOT_SECONDS

    def _stamp(self, work: Work) -> Work:
        """Deadline propagation starts at the wire: every gossip Work item
        carries its ingest time plus a per-type processing deadline, so
        stale work is dropped before it ever reaches BLS or the device."""
        now = time.monotonic()
        work.ingest_at = now
        work.deadline = deadline_for(
            work.work_type, now=now, slot_seconds=self._slot_seconds
        )
        return work

    # -- gossip ------------------------------------------------------------

    def on_gossip(self, topic: str, message, from_peer: str) -> None:
        svc = self.svc

        def submit(**kw) -> None:
            svc.processor.submit(self._stamp(Work(**kw)))

        if topic == Topic.BEACON_BLOCK:
            submit(
                work_type=WorkType.GossipBlock,
                item=(message, from_peer),
                process_individual=svc.process_gossip_block,
            )
        elif topic == Topic.BEACON_ATTESTATION:
            submit(
                work_type=WorkType.GossipAttestation,
                item=message,
                process_individual=svc.process_gossip_attestation,
                process_batch=svc.process_gossip_attestation_batch,
            )
        elif topic == Topic.AGGREGATE_AND_PROOF:
            submit(
                work_type=WorkType.GossipAggregate,
                item=message,
                process_individual=svc.process_gossip_aggregate,
                process_batch=svc.process_gossip_aggregate_batch,
            )
        elif topic == Topic.SYNC_COMMITTEE_MESSAGE:
            submit(
                work_type=WorkType.GossipSyncSignature,
                item=message,
                process_individual=svc.process_gossip_sync_message,
                process_batch=svc.process_gossip_sync_message_batch,
            )
        elif topic == Topic.SYNC_CONTRIBUTION:
            submit(
                work_type=WorkType.GossipSyncContribution,
                item=message,
                process_individual=svc.process_gossip_sync_contribution,
            )
        elif topic == Topic.DATA_COLUMN_SIDECAR:
            submit(
                work_type=WorkType.GossipBlock,  # block-class priority
                item=message,
                process_individual=svc.process_gossip_data_column,
            )
        elif topic == Topic.VOLUNTARY_EXIT:
            submit(
                work_type=WorkType.GossipVoluntaryExit,
                item=message,
                process_individual=svc.process_gossip_exit,
            )
        elif topic == Topic.PROPOSER_SLASHING:
            submit(
                work_type=WorkType.GossipProposerSlashing,
                item=message,
                process_individual=svc.process_gossip_proposer_slashing,
            )
        elif topic == Topic.ATTESTER_SLASHING:
            submit(
                work_type=WorkType.GossipAttesterSlashing,
                item=message,
                process_individual=svc.process_gossip_attester_slashing,
            )
        # unknown topics are dropped (gossipsub would penalize the peer)

    # -- req/resp ----------------------------------------------------------

    def on_rpc(self, method: str, payload, from_peer: str):
        if method == "status":
            self.svc.sync.on_peer_status(from_peer, payload)
            return self.svc.local_status()
        if method == "blocks_by_range":
            start_slot, count = payload
            return self.svc.blocks_by_range(start_slot, count)
        if method == "blocks_by_root":
            return self.svc.blocks_by_root(payload)
        if method == "data_column_sidecars_by_root":
            return self.svc.data_column_sidecars_by_root(payload)
        if method == "data_column_sidecars_by_range":
            start_slot, count, columns = payload
            return self.svc.data_column_sidecars_by_range(
                start_slot, count, columns
            )
        if method == "light_client_bootstrap":
            return self.svc.light_client_bootstrap(payload)
        if method == "light_client_updates_by_range":
            start_period, count = payload
            return self.svc.light_client_updates_by_range(start_period, count)
        if method == "light_client_optimistic_update":
            return self.svc.light_client_optimistic_update()
        if method == "light_client_finality_update":
            return self.svc.light_client_finality_update()
        raise ValueError(f"unknown rpc method {method!r}")
