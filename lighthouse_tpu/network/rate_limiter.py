"""Req/Resp rate limiter: token buckets per (peer, protocol).

The twin of the reference's ``lighthouse_network/src/rpc/rate_limiter.rs:1-531``:
each inbound request spends tokens from a per-peer per-method bucket that
refills continuously over its quota period. A request that does not fit is
refused (the RPC error path — the reference responds with RateLimited);
sustained abuse is reported to the peer manager's score ledger by the
transport, which bans the flooder while honest peers stay unaffected.

Quotas mirror the reference's defaults in spirit: bulk data methods
(blocks/blobs/columns by range) get token counts proportional to the batch
sizes the sync pipeline legitimately requests; cheap control methods get
small steady allowances.
"""

from __future__ import annotations

import threading
import time


class Quota:
    """max_tokens per period_seconds; a request of size n spends n tokens."""

    __slots__ = ("max_tokens", "period")

    def __init__(self, max_tokens: float, period: float):
        self.max_tokens = float(max_tokens)
        self.period = float(period)


# method -> quota (rate_limiter.rs RPCRateLimiterBuilder defaults, adapted
# to this transport's method names)
DEFAULT_QUOTAS: dict[str, Quota] = {
    "status": Quota(5, 15.0),
    "ping": Quota(2, 10.0),
    "metadata": Quota(2, 5.0),
    "goodbye": Quota(1, 10.0),
    "blocks_by_range": Quota(1024, 10.0),   # tokens = blocks requested
    "blocks_by_root": Quota(128, 10.0),     # tokens = roots requested
    "blob_sidecars_by_range": Quota(768, 10.0),
    "blob_sidecars_by_root": Quota(128, 10.0),
    "data_column_sidecars_by_range": Quota(2048, 10.0),
    "data_column_sidecars_by_root": Quota(256, 10.0),
    "light_client_bootstrap": Quota(1, 10.0),
}
_DEFAULT = Quota(64, 10.0)  # unlisted methods


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, tokens: float, last: float):
        self.tokens = tokens
        self.last = last


class RateLimiter:
    def __init__(self, quotas: dict[str, Quota] | None = None,
                 clock=time.monotonic):
        self.quotas = dict(DEFAULT_QUOTAS if quotas is None else quotas)
        self._buckets: dict[tuple[str, str], _Bucket] = {}
        self._lock = threading.Lock()
        self._clock = clock
        self._last_prune = clock()

    def allow(self, peer: str, method: str, tokens: float = 1.0) -> bool:
        """Spend ``tokens`` from (peer, method)'s bucket; False = refused.
        Oversized single requests (tokens > quota) are always refused."""
        quota = self.quotas.get(method, _DEFAULT)
        if tokens > quota.max_tokens:
            return False
        now = self._clock()
        rate = quota.max_tokens / quota.period
        with self._lock:
            b = self._buckets.get((peer, method))
            if b is None:
                b = self._buckets[(peer, method)] = _Bucket(
                    quota.max_tokens, now
                )
            b.tokens = min(
                quota.max_tokens, b.tokens + (now - b.last) * rate
            )
            b.last = now
            if b.tokens >= tokens:
                b.tokens -= tokens
                return True
            return False

    def prune(self, max_age: float = 60.0) -> None:
        """Drop idle buckets (the reference prunes by quota period)."""
        cutoff = self._clock() - max_age
        with self._lock:
            for key in [k for k, b in self._buckets.items()
                        if b.last < cutoff]:
                del self._buckets[key]

    def maybe_prune(self, max_age: float = 60.0) -> bool:
        """Time-gated :meth:`prune` — cheap enough for the transport's
        serve loop to call per request; actually prunes at most once per
        ``max_age``. Without this the per-(peer, method) bucket map grows
        without bound over long DHT walks. Returns True iff it pruned."""
        now = self._clock()
        with self._lock:
            if now - self._last_prune < max_age:
                return False
            self._last_prune = now
        self.prune(max_age)
        return True

    def wait_time(self, peer: str, method: str, tokens: float = 1.0) -> float:
        """Seconds until ``tokens`` would be available for (peer, method)
        — 0.0 if a request would be admitted now, ``inf`` if ``tokens``
        exceeds the quota outright. Does NOT spend tokens: the client-side
        self-limiter uses this to pace itself below a peer's refill rate."""
        quota = self.quotas.get(method, _DEFAULT)
        if tokens > quota.max_tokens:
            return float("inf")
        now = self._clock()
        rate = quota.max_tokens / quota.period
        with self._lock:
            b = self._buckets.get((peer, method))
            if b is None:
                return 0.0
            have = min(quota.max_tokens, b.tokens + (now - b.last) * rate)
        if have >= tokens:
            return 0.0
        return (tokens - have) / rate


def request_cost(method: str, payload) -> float:
    """Token cost of a request: bulk methods cost what they ask for."""
    if method.endswith("_by_range"):
        # codec payloads are (start, count) tuples; object/dict forms carry
        # a count attribute/key
        count = None
        if isinstance(payload, tuple) and len(payload) >= 2:
            count = payload[1]
        elif isinstance(payload, dict):
            count = payload.get("count")
        else:
            count = getattr(payload, "count", None)
        return float(max(int(count or 1), 1))
    if method.endswith("_by_root"):
        try:
            return float(max(len(payload), 1))
        except TypeError:
            return 1.0
    return 1.0
