"""Gossipsub v1.1 mesh over the socket transport.

The TPU-framework twin of the reference's vendored gossipsub fork
(``lighthouse_network/gossipsub/src/behaviour.rs``, ``peer_score.rs``,
``mcache.rs``): instead of flooding every message to every peer, each node
maintains a per-topic **mesh** of degree ~D full-message peers (GRAFT/PRUNE
with backoff), announces recent message ids to a few non-mesh peers each
heartbeat (IHAVE) which can fetch bodies on demand (IWANT), and scores peers
per topic (time-in-mesh, first deliveries, mesh delivery deficit, invalid
messages, behaviour penalty) so misbehaving peers are pruned and eventually
graylisted. Per-node message load is O(D), not O(peers).

Wire format: one new frame kind CONTROL (5) carrying a sequence of control
entries, multiplexed on the same length-prefixed TCP streams as GOSSIP/RPC:

    u8 op | fields
    op 1 SUBSCRIBE   : u8 topic_len | topic
    op 2 UNSUBSCRIBE : u8 topic_len | topic
    op 3 GRAFT       : u8 topic_len | topic
    op 4 PRUNE       : u8 topic_len | topic | u16 backoff_secs
    op 5 IHAVE       : u8 topic_len | topic | u16 n | n * 20B msg ids
    op 6 IWANT       : u16 n | n * 20B msg ids

Validation precedes forwarding (gossipsub v1.1): a message the local service
rejects is never propagated, and the sender takes an invalid-message penalty
(behaviour.rs ``report_message_validation_result``).
"""

from __future__ import annotations

import random
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import hashlib

from ..utils.logging import get_logger
from .codec import WireError
from .socket_transport import (
    SocketTransport,
    _GOSSIP,
    _Peer,
)

log = get_logger("gossipsub")

_CONTROL = 5

_SUB, _UNSUB, _GRAFT, _PRUNE, _IHAVE, _IWANT = range(1, 7)


@dataclass
class GossipsubParams:
    """Mesh + scoring knobs (gossipsub v1.1 defaults, behaviour.rs config)."""

    d: int = 6            # target mesh degree
    d_lo: int = 4         # graft below this
    d_hi: int = 12        # prune above this
    d_lazy: int = 6       # IHAVE targets per heartbeat
    heartbeat_interval: float = 1.0
    mcache_len: int = 5       # history windows kept for IWANT
    mcache_gossip: int = 3    # windows advertised in IHAVE
    fanout_ttl: float = 60.0
    prune_backoff: float = 60.0
    max_ihave_ids: int = 5000     # ids per IHAVE message
    max_iwant_ids: int = 512      # ids requested per peer per heartbeat
    max_iwant_served: int = 512   # bodies served per peer per heartbeat
    max_peer_topics: int = 256    # per-peer subscription/score table bound

    # -- scoring (peer_score.rs at its load-bearing core) ------------------
    decay: float = 0.9                 # per-heartbeat counter decay
    time_in_mesh_quantum: float = 1.0  # seconds per P1 point
    time_in_mesh_cap: float = 300.0
    w_time_in_mesh: float = 0.01           # P1 weight
    first_delivery_cap: float = 100.0
    w_first_delivery: float = 1.0          # P2 weight
    mesh_delivery_threshold: float = 2.0   # P3: expected deliveries/heartbeat
    mesh_delivery_activation: float = 3.0  # seconds in mesh before P3 applies
    w_mesh_delivery_deficit: float = -1.0  # P3 weight (x deficit^2)
    w_invalid: float = -10.0               # P4 weight (x invalid^2)
    w_behaviour: float = -5.0              # behaviour penalty weight (x n^2)

    gossip_threshold: float = -10.0    # below: no IHAVE/IWANT exchange
    publish_threshold: float = -50.0   # below: not a publish/fanout target
    graylist_threshold: float = -80.0  # below: ignore entirely + disconnect


@dataclass
class _TopicScore:
    time_in_mesh: float = 0.0        # seconds (while in OUR mesh)
    graft_time: float = 0.0          # 0 = not in mesh
    first_deliveries: float = 0.0
    mesh_deliveries: float = 0.0
    invalid: float = 0.0


@dataclass
class _PeerState:
    topics: set = field(default_factory=set)        # their subscriptions
    scores: dict = field(default_factory=dict)      # topic -> _TopicScore
    behaviour_penalty: float = 0.0
    iwant_budget: int = 0                           # ids requested this round
    iwant_served: int = 0                           # bodies sent this round

    def topic(self, t: str, cap: int = 256) -> _TopicScore:
        """Per-topic score row, bounded: attacker-chosen topic strings can't
        grow the table (or score()'s iteration cost) without limit — beyond
        the cap, counters go to a throwaway row."""
        ts = self.scores.get(t)
        if ts is None:
            if len(self.scores) >= cap:
                return _TopicScore()
            ts = self.scores[t] = _TopicScore()
        return ts


class GossipsubTransport(SocketTransport):
    """SocketTransport with a gossipsub mesh replacing flood forwarding."""

    def __init__(self, spec, host: str = "127.0.0.1", port: int = 0,
                 rpc_timeout: float = 10.0,
                 params: GossipsubParams | None = None,
                 topics: list[str] | None = None,
                 run_heartbeat: bool = True,
                 peer_manager=None, discovery=None,
                 self_limit: bool = True):
        self.params = params or GossipsubParams()
        self._gs_lock = threading.RLock()
        self._subs: set[str] = set()
        self._mesh: dict[str, set[_Peer]] = {}
        self._fanout: dict[str, set[_Peer]] = {}
        self._fanout_last: dict[str, float] = {}
        self._backoff: dict[tuple[str, str], float] = {}  # (topic,addr)->until
        # decaying per-topic delivery rate: P3 mesh-delivery deficits only
        # apply on topics that actually carry traffic (an idle subnet must
        # not bleed honest mesh peers)
        self._topic_activity: dict[str, float] = {}
        # message cache: id -> (topic, wire body); windows of ids per heartbeat
        self._mcache: dict[bytes, tuple[str, bytes]] = {}
        self._mcache_windows: deque[list[bytes]] = deque([[]])
        self.gossip_rx = 0      # gossip frames received (incl. duplicates)
        self.iwant_served = 0
        self.ihave_sent = 0
        self._hb_stop = threading.Event()
        if topics is None:
            from .transport import Topic

            topics = [
                v for k, v in vars(Topic).items() if not k.startswith("_")
            ]
        self._subs.update(topics)
        # honest-node default: self-limit our own Req/Resp against the
        # peer's quotas so a full node never trips a remote rate limiter
        super().__init__(spec, host=host, port=port, rpc_timeout=rpc_timeout,
                         peer_manager=peer_manager, discovery=discovery,
                         self_limit=self_limit)
        self._hb_thread = None
        if run_heartbeat:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"gs-heartbeat-{self.local_addr}",
            )
            self._hb_thread.start()

    # -- scoring -----------------------------------------------------------

    def _ps(self, peer: _Peer) -> _PeerState:
        st = getattr(peer, "gs", None)
        if st is None:
            st = peer.gs = _PeerState()
        return st

    def _tscore(self, peer: _Peer, topic: str) -> _TopicScore:
        # _gs_lock guards the scores table: reader threads insert rows while
        # the heartbeat thread iterates them in score()
        with self._gs_lock:
            return self._ps(peer).topic(topic, self.params.max_peer_topics)

    def score(self, peer: _Peer) -> float:
        """Combined peer score: per-topic terms + behaviour + frame-level."""
        p = self.params
        st = self._ps(peer)
        total = peer.score  # wire-level events from the base transport
        now = time.monotonic()
        with self._gs_lock:
            score_rows = list(st.scores.items())
        for t, ts in score_rows:
            tim = ts.time_in_mesh
            if ts.graft_time:
                tim += now - ts.graft_time
            total += p.w_time_in_mesh * min(
                tim / p.time_in_mesh_quantum, p.time_in_mesh_cap
            )
            total += p.w_first_delivery * min(
                ts.first_deliveries, p.first_delivery_cap
            )
            if (
                ts.graft_time
                and now - ts.graft_time > p.mesh_delivery_activation
                and self._topic_activity.get(t, 0.0)
                >= p.mesh_delivery_threshold
            ):
                deficit = p.mesh_delivery_threshold - ts.mesh_deliveries
                if deficit > 0:
                    total += p.w_mesh_delivery_deficit * deficit * deficit
            total += p.w_invalid * ts.invalid * ts.invalid
        total += p.w_behaviour * st.behaviour_penalty * st.behaviour_penalty
        return total

    def peer_scores(self) -> dict[str, float]:
        with self._lock:
            peers = list(self._peers.items())
        return {a: round(self.score(p), 2) for a, p in peers}

    # -- subscriptions -----------------------------------------------------

    def subscribe(self, topic: str) -> None:
        with self._gs_lock:
            self._subs.add(topic)
        self._send_control_all([(_SUB, topic)])

    def unsubscribe(self, topic: str) -> None:
        now = time.monotonic()
        with self._gs_lock:
            self._subs.discard(topic)
            mesh = self._mesh.pop(topic, set())
        for peer in mesh:
            ts = self._tscore(peer, topic)
            if ts.graft_time:
                ts.time_in_mesh += now - ts.graft_time
                ts.graft_time = 0.0
            self._send_control(peer, [(_PRUNE, topic)])
        self._send_control_all([(_UNSUB, topic)])

    def mesh_peers(self, topic: str) -> list[str]:
        with self._gs_lock:
            return sorted(p.addr for p in self._mesh.get(topic, set()))

    # -- publish -----------------------------------------------------------

    def publish(self, from_peer: str, topic: str, message) -> None:
        msg_id, body = self._gossip_body(topic, message)
        self._mark_seen(msg_id)
        self.published += 1
        self._mcache_put(msg_id, topic, body)
        for peer in self._publish_targets(topic):
            self._safe_send(peer, _GOSSIP, body)

    def _publish_targets(self, topic: str) -> list[_Peer]:
        p = self.params
        with self._gs_lock:
            if topic in self._subs:
                mesh = self._mesh.setdefault(topic, set())
                targets = {pr for pr in mesh if pr.alive}
                if len(targets) < p.d:
                    # mesh still forming: top up from topic peers (flood-
                    # publish at its smallest — our own messages must go out)
                    now = time.monotonic()
                    for pr in self._topic_peers(topic):
                        if len(targets) >= p.d:
                            break
                        if (
                            pr not in targets
                            and self.score(pr) >= p.publish_threshold
                            and self._backoff.get((topic, pr.addr), 0) <= now
                        ):
                            targets.add(pr)
                return list(targets)
            # not subscribed: fanout (behaviour.rs fanout handling)
            fan = self._fanout.setdefault(topic, set())
            fan = {pr for pr in fan if pr.alive}
            while len(fan) < p.d:
                extra = [
                    pr for pr in self._topic_peers(topic)
                    if pr not in fan and self.score(pr) >= p.publish_threshold
                ]
                if not extra:
                    break
                fan.add(random.choice(extra))
            self._fanout[topic] = fan
            self._fanout_last[topic] = time.monotonic()
            return list(fan)

    def _topic_peers(self, topic: str) -> list[_Peer]:
        with self._lock:
            peers = list(self._peers.values())
        return [
            p for p in peers if p.alive and topic in self._ps(p).topics
        ]

    # -- frame handling ----------------------------------------------------

    def _add_peer(self, sock, addr: str) -> _Peer:
        peer = super()._add_peer(sock, addr)
        with self._gs_lock:
            subs = sorted(self._subs)
        if subs:
            self._send_control(peer, [(_SUB, t) for t in subs])
        return peer

    def _drop_peer(self, peer: _Peer, why: str) -> None:
        with self._gs_lock:
            for mesh in self._mesh.values():
                mesh.discard(peer)
            for fan in self._fanout.values():
                fan.discard(peer)
        super()._drop_peer(peer, why)

    def _handle_frame(self, peer: _Peer, kind: int, body: bytes) -> None:
        if kind == _GOSSIP:
            self._handle_gossip(peer, body)
        elif kind == _CONTROL:
            self._handle_control(peer, body)
        else:
            super()._handle_frame(peer, kind, body)

    def _handle_gossip(self, peer: _Peer, body: bytes) -> None:
        p = self.params
        self.gossip_rx += 1
        if self.score(peer) < p.graylist_threshold:
            self._drop_peer(peer, "graylisted")
            return
        tn = body[0]
        topic = body[1 : 1 + tn].decode()
        msg_id = body[1 + tn : 21 + tn]
        payload = body[21 + tn :]
        st = self._ps(peer)
        ts = self._tscore(peer, topic)
        if not self._mark_seen(msg_id):
            # duplicate: counts toward the sender's mesh-delivery credit
            with self._gs_lock:
                if peer in self._mesh.get(topic, set()):
                    ts.mesh_deliveries += 1.0
            return
        ts.first_deliveries += 1.0
        ts.mesh_deliveries += 1.0
        with self._gs_lock:
            self._topic_activity[topic] = (
                self._topic_activity.get(topic, 0.0) + 1.0
            )
        self._mcache_put(msg_id, topic, body)
        # validate BEFORE forwarding (v1.1); invalid -> P4 penalty, no forward
        if self._service is not None:
            try:
                message = self.codec.decode_gossip(topic, payload)
                self._service.on_gossip(topic, message, peer.addr)
            except Exception:
                ts.invalid += 1.0
                # rejected messages must not be re-advertised (IHAVE) or
                # served (IWANT); they stay in _seen so they aren't reprocessed
                with self._gs_lock:
                    self._mcache.pop(msg_id, None)
                raise
        self.delivered += 1
        with self._gs_lock:
            targets = [
                pr for pr in self._mesh.get(topic, set())
                if pr is not peer and pr.alive
            ]
        for pr in targets:
            self._safe_send(pr, _GOSSIP, body)

    def _handle_control(self, peer: _Peer, body: bytes) -> None:
        p = self.params
        st = self._ps(peer)
        off = 0
        iwant_ids: list[bytes] = []
        out: list[tuple] = []
        while off < len(body):
            op = body[off]
            off += 1
            if op in (_SUB, _UNSUB, _GRAFT, _PRUNE, _IHAVE):
                if off >= len(body):
                    raise WireError("truncated control topic")
                tn = body[off]
                topic = body[off + 1 : off + 1 + tn].decode()
                if len(topic.encode()) != tn:
                    raise WireError("truncated control topic")
                off += 1 + tn
            if op == _SUB:
                if len(st.topics) < p.max_peer_topics:
                    st.topics.add(topic)
            elif op == _UNSUB:
                st.topics.discard(topic)
                with self._gs_lock:
                    self._mesh.get(topic, set()).discard(peer)
            elif op == _GRAFT:
                out.extend(self._on_graft(peer, topic))
            elif op == _PRUNE:
                (backoff,) = struct.unpack(">H", body[off : off + 2])
                off += 2
                with self._gs_lock:
                    self._mesh.get(topic, set()).discard(peer)
                    self._backoff[(topic, peer.addr)] = (
                        time.monotonic() + min(backoff, 3600)
                    )
                ts = self._tscore(peer, topic)
                if ts.graft_time:
                    ts.time_in_mesh += time.monotonic() - ts.graft_time
                    ts.graft_time = 0.0
            elif op == _IHAVE:
                (n,) = struct.unpack(">H", body[off : off + 2])
                off += 2
                ids = [body[off + 20 * i : off + 20 * (i + 1)] for i in range(n)]
                off += 20 * n
                if self.score(peer) >= p.gossip_threshold:
                    with self._lock:
                        want = [i for i in ids if i not in self._seen]
                    budget = max(0, p.max_iwant_ids - st.iwant_budget)
                    want = want[:budget]
                    st.iwant_budget += len(want)
                    iwant_ids.extend(want)
            elif op == _IWANT:
                (n,) = struct.unpack(">H", body[off : off + 2])
                off += 2
                ids = [body[off + 20 * i : off + 20 * (i + 1)] for i in range(n)]
                off += 20 * n
                if self.score(peer) >= p.gossip_threshold:
                    # bounded + deduped per heartbeat round: IWANT must not
                    # be a 20-bytes-in / full-body-out amplifier
                    served = getattr(peer, "gs_served_ids", None)
                    if served is None:
                        served = peer.gs_served_ids = set()
                    for mid in ids:
                        if st.iwant_served >= p.max_iwant_served:
                            break
                        if mid in served:
                            continue
                        with self._gs_lock:
                            entry = self._mcache.get(mid)
                        if entry is not None:
                            served.add(mid)
                            st.iwant_served += 1
                            self._safe_send(peer, _GOSSIP, entry[1])
                            self.iwant_served += 1
            else:
                raise WireError(f"unknown control op {op}")
        if iwant_ids:
            out.append((_IWANT, iwant_ids))
        if out:
            self._send_control(peer, out)

    def _on_graft(self, peer: _Peer, topic: str) -> list[tuple]:
        """GRAFT received: accept into our mesh or PRUNE back
        (behaviour.rs handle_graft)."""
        p = self.params
        st = self._ps(peer)
        now = time.monotonic()
        with self._gs_lock:
            if topic not in self._subs:
                return [(_PRUNE, topic)]
            if self._backoff.get((topic, peer.addr), 0) > now:
                # grafting while backed off is a protocol violation
                st.behaviour_penalty += 1.0
                return [(_PRUNE, topic)]
            if self.score(peer) < 0:
                return [(_PRUNE, topic)]
            mesh = self._mesh.setdefault(topic, set())
            if peer not in mesh and len(mesh) >= p.d_hi:
                return [(_PRUNE, topic)]
            mesh.add(peer)
        ts = self._tscore(peer, topic)
        if not ts.graft_time:
            ts.graft_time = now
        return []

    # -- control send helpers ----------------------------------------------

    def _encode_control(self, entries: list[tuple]) -> bytes:
        parts = []
        for entry in entries:
            op = entry[0]
            if op in (_SUB, _UNSUB, _GRAFT):
                tb = entry[1].encode()
                parts.append(bytes([op, len(tb)]) + tb)
            elif op == _PRUNE:
                tb = entry[1].encode()
                backoff = int(entry[2]) if len(entry) > 2 else int(
                    self.params.prune_backoff
                )
                parts.append(
                    bytes([op, len(tb)]) + tb + struct.pack(">H", backoff)
                )
            elif op == _IHAVE:
                tb = entry[1].encode()
                ids = entry[2][: self.params.max_ihave_ids]
                parts.append(
                    bytes([op, len(tb)]) + tb
                    + struct.pack(">H", len(ids)) + b"".join(ids)
                )
            elif op == _IWANT:
                ids = entry[1]
                parts.append(
                    bytes([op]) + struct.pack(">H", len(ids)) + b"".join(ids)
                )
        return b"".join(parts)

    def _send_control(self, peer: _Peer, entries: list[tuple]) -> None:
        if entries:
            self._safe_send(peer, _CONTROL, self._encode_control(entries))

    def _send_control_all(self, entries: list[tuple]) -> None:
        with self._lock:
            peers = list(self._peers.values())
        for peer in peers:
            self._send_control(peer, entries)

    def _safe_send(self, peer: _Peer, kind: int, body: bytes) -> None:
        try:
            peer.send_frame(kind, body)
        except OSError:
            self._drop_peer(peer, "send failed")

    # -- message cache -----------------------------------------------------

    def _mcache_put(self, msg_id: bytes, topic: str, body: bytes) -> None:
        with self._gs_lock:
            if msg_id not in self._mcache:
                self._mcache[msg_id] = (topic, body)
                self._mcache_windows[-1].append(msg_id)

    def _mcache_shift(self) -> None:
        with self._gs_lock:
            self._mcache_windows.append([])
            while len(self._mcache_windows) > self.params.mcache_len:
                for mid in self._mcache_windows.popleft():
                    self._mcache.pop(mid, None)

    def _mcache_gossip_ids(self, topic: str) -> list[bytes]:
        with self._gs_lock:
            windows = list(self._mcache_windows)[-self.params.mcache_gossip :]
            return [
                mid
                for w in windows
                for mid in w
                if self._mcache.get(mid, (None,))[0] == topic
            ]

    # -- heartbeat ---------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.params.heartbeat_interval):
            try:
                self.heartbeat()
            except Exception as e:  # noqa: BLE001 — keep the mesh alive
                log.warn("Heartbeat failed", error=str(e))

    def heartbeat(self) -> None:
        """One mesh-maintenance round (behaviour.rs ``heartbeat``)."""
        p = self.params
        now = time.monotonic()
        self.decay_scores()
        with self._lock:
            peers = list(self._peers.values())
        # counter decay + iwant budget refill + graylist enforcement
        for peer in peers:
            st = self._ps(peer)
            st.behaviour_penalty *= p.decay
            st.iwant_budget = 0
            st.iwant_served = 0
            if getattr(peer, "gs_served_ids", None):
                peer.gs_served_ids.clear()
            for ts in st.scores.values():
                ts.first_deliveries *= p.decay
                ts.mesh_deliveries *= p.decay
                ts.invalid *= p.decay
            if self.score(peer) < p.graylist_threshold:
                self._drop_peer(peer, "graylisted (score)")
        with self._gs_lock:
            self._backoff = {
                k: v for k, v in self._backoff.items() if v > now
            }
            self._topic_activity = {
                t: v * p.decay
                for t, v in self._topic_activity.items()
                if v * p.decay > 0.01
            }
            subs = sorted(self._subs)
        to_send: dict[_Peer, list[tuple]] = {}
        for topic in subs:
            self._maintain_mesh(topic, now, to_send)
            self._emit_gossip(topic, to_send)
        # fanout expiry + degree top-up
        with self._gs_lock:
            for topic in list(self._fanout):
                if now - self._fanout_last.get(topic, 0) > p.fanout_ttl:
                    del self._fanout[topic]
                    self._fanout_last.pop(topic, None)
                else:
                    self._fanout[topic] = {
                        pr for pr in self._fanout[topic] if pr.alive
                    }
        for peer, entries in to_send.items():
            self._send_control(peer, entries)
        self._mcache_shift()

    def _maintain_mesh(
        self, topic: str, now: float, to_send: dict
    ) -> None:
        p = self.params
        with self._gs_lock:
            mesh = self._mesh.setdefault(topic, set())
            # evict dead + negative-score peers
            for peer in list(mesh):
                if not peer.alive or self.score(peer) < 0:
                    mesh.discard(peer)
                    self._backoff[(topic, peer.addr)] = (
                        now + p.prune_backoff
                    )
                    if peer.alive:
                        to_send.setdefault(peer, []).append((_PRUNE, topic))
                    ts = self._tscore(peer, topic)
                    if ts.graft_time:
                        ts.time_in_mesh += now - ts.graft_time
                        ts.graft_time = 0.0
            if len(mesh) < p.d_lo:
                candidates = [
                    pr for pr in self._topic_peers(topic)
                    if pr not in mesh
                    and self.score(pr) >= 0
                    and self._backoff.get((topic, pr.addr), 0) <= now
                ]
                random.shuffle(candidates)
                for pr in candidates[: p.d - len(mesh)]:
                    mesh.add(pr)
                    ts = self._tscore(pr, topic)
                    if not ts.graft_time:
                        ts.graft_time = now
                    to_send.setdefault(pr, []).append((_GRAFT, topic))
            elif len(mesh) > p.d_hi:
                # keep the best-scoring D, prune the rest (v1.1 keeps score)
                ranked = sorted(mesh, key=self.score, reverse=True)
                for pr in ranked[p.d :]:
                    mesh.discard(pr)
                    self._backoff[(topic, pr.addr)] = now + p.prune_backoff
                    to_send.setdefault(pr, []).append((_PRUNE, topic))
                    ts = self._tscore(pr, topic)
                    if ts.graft_time:
                        ts.time_in_mesh += now - ts.graft_time
                        ts.graft_time = 0.0

    def _emit_gossip(self, topic: str, to_send: dict) -> None:
        p = self.params
        ids = self._mcache_gossip_ids(topic)
        if not ids:
            return
        with self._gs_lock:
            mesh = self._mesh.get(topic, set())
        targets = [
            pr for pr in self._topic_peers(topic)
            if pr not in mesh and self.score(pr) >= p.gossip_threshold
        ]
        random.shuffle(targets)
        for pr in targets[: p.d_lazy]:
            to_send.setdefault(pr, []).append(
                (_IHAVE, topic, ids[: p.max_ihave_ids])
            )
            # heartbeat thread and the publish path both bump this counter
            with self._gs_lock:
                self.ihave_sent += 1

    def stop(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None and self._hb_thread.is_alive():
            self._hb_thread.join(timeout=5.0)
        super().stop()
