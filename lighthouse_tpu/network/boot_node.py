"""UDP boot node: the discovery rendezvous (ref ``boot_node/``, discv5 seam).

One datagram protocol, two messages:

    client -> boot : b"ANNOUNCE " + "host:port"   (the client's TCP listener)
    boot -> client : b"PEERS "    + comma-joined known addresses

The boot node remembers every announcer (bounded, LRU) and answers with the
rest — enough for nodes to find each other and dial TCP, the role discv5's
FINDNODE/NODES random-walk plays for the reference. Run standalone via
``python -m lighthouse_tpu boot-node``.
"""

from __future__ import annotations

import socket
import threading
from collections import OrderedDict

from ..utils.logging import get_logger

log = get_logger("boot_node")

_MAX_PEERS = 1024


class BootNode:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self.local_addr = f"{host}:{self._sock.getsockname()[1]}"
        self._known: OrderedDict[str, None] = OrderedDict()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "BootNode":
        self._thread = threading.Thread(
            target=self._serve, daemon=True, name="boot-node"
        )
        self._thread.start()
        log.info("Boot node listening", addr=self.local_addr)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()   # unblocks the recvfrom in the serve loop
        except OSError:
            pass
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def known_peers(self) -> list[str]:
        with self._lock:
            return list(self._known)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                data, src = self._sock.recvfrom(4096)
            except OSError:
                return
            if not data.startswith(b"ANNOUNCE "):
                continue
            addr = data[len(b"ANNOUNCE "):].decode(errors="replace").strip()
            with self._lock:
                others = [a for a in self._known if a != addr]
                self._known[addr] = None
                self._known.move_to_end(addr)
                while len(self._known) > _MAX_PEERS:
                    self._known.popitem(last=False)
            reply = b"PEERS " + ",".join(others).encode()
            try:
                self._sock.sendto(reply, src)
            except OSError:
                pass


def client_announce(boot_addr: str, my_addr: str, timeout: float = 5.0) -> list[str]:
    """Announce ``my_addr`` to the boot node; returns the peer list."""
    host, port = boot_addr.rsplit(":", 1)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    try:
        s.sendto(b"ANNOUNCE " + my_addr.encode(), (host, int(port)))
        data, _ = s.recvfrom(65536)
    finally:
        s.close()
    if not data.startswith(b"PEERS "):
        return []
    rest = data[len(b"PEERS "):].decode(errors="replace")
    return [a for a in rest.split(",") if a]
