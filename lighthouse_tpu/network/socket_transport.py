"""Real-socket transport: TCP gossip mesh + Req/Resp, UDP discovery.

The internet-facing twin of the reference's libp2p stack
(``lighthouse_network/src/service/mod.rs``): a TCP listener per node carries
both the gossip mesh and Req/Resp streams; peers are found via the UDP boot
node (``boot_node/``, the discv5 seam). Gossip propagation is flood-with-dedup:
every message carries a 20-byte id (hash of topic+payload, the gossipsub
message-id function); peers forward each id at most once, so messages reach
the whole connected component without a routing table. Malformed frames
disconnect the peer (the peer-scoring hook).

Frame layout (length-prefixed, one TCP stream per peer pair):

    u32 len | u8 kind | body
    kind 0 GOSSIP : u8 topic_len | topic | 20B msg_id | payload
    kind 1 REQ    : u64 req_id | u8 method_len | method | payload
    kind 2 RESP   : u64 req_id | payload
    kind 3 ERROR  : u64 req_id | utf-8 message
    kind 4 HELLO  : u8 addr_len | addr      (peer's canonical listen address)
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
import time
from collections import OrderedDict

from ..loadshed.adaptive import RttEstimator, SelfLimiter
from ..loadshed.priorities import method_priority, should_shed_method
from ..utils.logging import get_logger
from ..utils.metrics import RPC_EXPIRED, RPC_RTT, SHED_REQUESTS
from .codec import MessageCodec, WireError
from .transport import Transport


def _shutdown_close(sock: socket.socket) -> None:
    """shutdown(SHUT_RDWR) before close: on Linux, close() alone does NOT
    tear down a connection whose fd another thread is blocked in recv() on —
    the in-flight syscall pins the open file description, no FIN is sent,
    and BOTH sides' read loops hang forever (the peer never learns the
    connection died). shutdown() interrupts the blocked recv immediately."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass

log = get_logger("socket_transport")

_GOSSIP, _REQ, _RESP, _ERROR, _HELLO = range(5)
_MAX_FRAME = 1 << 28
_SEEN_CAP = 4096  # gossipsub duplicate-cache size


# Peer-score weights (the gossipsub peer_score.rs shape at its smallest):
# negative events push a peer toward the ban threshold; useful deliveries
# claw back slowly. Scores decay toward zero so old sins expire.
SCORE_MALFORMED = -50.0     # undecodable frame / codec error
SCORE_HANDLER_ERROR = -10.0  # message that made the service raise
SCORE_DUPLICATE = -0.5       # redundant gossip (mesh noise)
SCORE_DELIVERY = 1.0         # first delivery of a message
SCORE_RATE_LIMITED = -20.0   # request refused by the rate limiter
SCORE_BAN_THRESHOLD = -100.0
SCORE_DECAY = 0.9            # per decay interval


class _Peer:
    def __init__(self, sock: socket.socket, addr: str):
        self.sock = sock
        self.addr = addr  # canonical "host:port" listen address
        self.send_lock = threading.Lock()
        self.alive = True
        self.score = 0.0
        # monotonic stamp of the recv() that completed the frame currently
        # being handled: the server-side Req/Resp deadline runs from it
        self.frame_recv_t = time.monotonic()

    def adjust_score(self, delta: float) -> float:
        self.score = max(-1000.0, min(100.0, self.score + delta))
        return self.score

    def send_frame(self, kind: int, body: bytes) -> None:
        frame = struct.pack(">IB", len(body) + 1, kind) + body
        with self.send_lock:
            # the send lock exists precisely to serialize whole frames onto
            # the socket; it guards nothing else and nothing is acquired
            # under it, so holding it across the write cannot deadlock
            self.sock.sendall(frame)  # lint: allow(blocking-under-lock)


class SocketTransport(Transport):
    """One node's network endpoint. Satisfies the Transport seam the
    BeaconNodeService/Router/SyncManager stack is written against, so the
    same node code runs over loopback (tests) or real sockets."""

    def __init__(self, spec, host: str = "127.0.0.1", port: int = 0,
                 rpc_timeout: float = 10.0, peer_manager=None, discovery=None,
                 self_limit: bool = False):
        from .peer_manager import PeerManager

        self.codec = MessageCodec(spec)
        # rpc_timeout is the CEILING: per-peer adaptive timeouts (EWMA RTT +
        # variance, RFC 6298 shape) take over once round-trips are observed
        self.rpc_timeout = rpc_timeout
        self._rtt: dict[str, RttEstimator] = {}
        self._rtt_lock = threading.Lock()
        # server-side Req/Resp deadline: a request that waited in the read
        # pipeline longer than any well-behaved client waits is answered
        # with an error instead of doing the (now pointless) work
        self.server_deadline_s = rpc_timeout
        # optional loadshed.LoadMonitor: when attached, lowest-priority
        # Req/Resp methods are shed first under BUSY/SATURATED
        self.load_monitor = None
        # client-side self-limiting (honest-node mode): pace our own
        # requests under the peer's published quotas so we never trip a
        # remote rate limiter and never take its -20 score penalty
        self.self_limiter = SelfLimiter() if self_limit else None
        self._service = None
        # durable peer records + ban lifecycle (peer_manager/mod.rs parity):
        # scores and bans survive the TCP connection, so reconnects by a
        # banned peer are refused until the ban expires
        self.peer_manager = peer_manager or PeerManager()
        self.discovery = discovery
        # per-(peer, method) token buckets (rpc/rate_limiter.rs): refused
        # requests get an RPC error + a score penalty; sustained flooding
        # crosses the ban threshold and drops the peer
        from .rate_limiter import RateLimiter

        self.rate_limiter = RateLimiter()
        self._peers: dict[str, _Peer] = {}  # canonical addr -> peer
        self._lock = threading.Lock()
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        self.published = 0  # gossip messages originated here
        self.delivered = 0  # gossip messages fully processed here
        self._req_id = 0
        self._pending: dict[int, tuple[threading.Event, list]] = {}
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.local_addr = f"{host}:{self._listener.getsockname()[1]}"
        if self.discovery is not None:
            self.discovery.peer_manager = self.peer_manager
            self.discovery.update_tcp_port(self._listener.getsockname()[1])
        self._stopped = False
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"net-accept-{self.local_addr}",
        )
        self._accept_thread.start()

    # -- Transport seam ----------------------------------------------------

    def register(self, peer_id: str, service) -> None:
        self._service = service

    def peers(self, exclude: str | None = None) -> list[str]:
        with self._lock:
            return [a for a in self._peers if a != exclude]

    def peer_scores(self) -> dict[str, float]:
        with self._lock:
            return {a: round(p.score, 2) for a, p in self._peers.items()}

    def decay_scores(self) -> None:
        """Periodic score decay toward zero (peer_score.rs decay interval)."""
        with self._lock:
            for p in self._peers.values():
                p.score *= SCORE_DECAY
        self.peer_manager.decay_scores()
        # ride the same periodic tick to bound the rate-limiter bucket map
        self.rate_limiter.maybe_prune()

    def report_peer(self, addr: str, delta: float) -> None:
        """Application-level score report (sync demotions etc. — the
        reference's PeerAction reporting into the peer manager)."""
        with self._lock:
            peer = self._peers.get(addr)
        if peer is not None and self._score(peer, delta):
            self._drop_peer(peer, "banned (reported)")

    def _score(self, peer: _Peer, delta: float) -> bool:
        """Adjust both the connection-local score and the durable peer-DB
        record; True when the peer has crossed the ban threshold."""
        peer.adjust_score(delta)
        self.peer_manager.report(peer.addr, delta)
        return self.peer_manager.is_banned(addr=peer.addr)

    def _gossip_body(self, topic: str, message) -> tuple[bytes, bytes]:
        """Encode a gossip message into (msg_id, wire body). The single
        definition of message identity: sha256(topic || payload)[:20]."""
        payload = self.codec.encode_gossip(topic, message)
        msg_id = hashlib.sha256(topic.encode() + payload).digest()[:20]
        tb = topic.encode()
        return msg_id, bytes([len(tb)]) + tb + msg_id + payload

    def publish(self, from_peer: str, topic: str, message) -> None:
        msg_id, body = self._gossip_body(topic, message)
        self._mark_seen(msg_id)
        self.published += 1
        self._flood(body, except_addr=None)

    def peer_timeout(self, addr: str) -> float:
        """Current request timeout for ``addr``: adaptive (EWMA RTT +
        variance) once samples exist, the ``rpc_timeout`` ceiling before."""
        with self._rtt_lock:
            est = self._rtt.get(addr)
            if est is None or not est.samples:
                return self.rpc_timeout
            return est.timeout()

    def _rtt_for_locked(self, addr: str) -> RttEstimator:
        est = self._rtt.get(addr)
        if est is None:
            est = self._rtt[addr] = RttEstimator(
                max_timeout=self.rpc_timeout
            )
        return est

    def _self_limit(self, to_peer: str, method: str, payload) -> None:
        """Honest-client pacing: wait out our own shadow of the peer's
        quota instead of tripping its limiter (and its score penalty)."""
        if self.self_limiter is None:
            return
        from .rate_limiter import request_cost

        cost = request_cost(method, payload)
        wait = self.self_limiter.throttle(to_peer, method, cost)
        if wait <= 0:
            return
        if wait > self.rpc_timeout:
            raise ConnectionError(
                f"self-limited: {method} to {to_peer} needs {wait:.1f}s "
                "of quota refill"
            )
        time.sleep(wait)
        # tokens have refilled; spend them (a second refusal only happens
        # under concurrent senders — treat it as paced enough and proceed)
        self.self_limiter.throttle(to_peer, method, cost)

    def request(self, from_peer: str, to_peer: str, method: str, payload):
        peer = self._peers.get(to_peer)
        if peer is None or not peer.alive:
            raise ConnectionError(f"not connected to {to_peer}")
        self._self_limit(to_peer, method, payload)
        with self._lock:
            self._req_id += 1
            rid = self._req_id
            ev, box = threading.Event(), []
            self._pending[rid] = (ev, box)
        body = (
            struct.pack(">Q", rid)
            + bytes([len(method)])
            + method.encode()
            + self.codec.encode_request(method, payload)
        )
        timeout = self.peer_timeout(to_peer)
        t0 = time.monotonic()
        try:
            peer.send_frame(_REQ, body)
            if not ev.wait(timeout):
                with self._rtt_lock:
                    self._rtt_for_locked(to_peer).on_timeout()
                raise ConnectionError(
                    f"rpc {method} to {to_peer} timed out after {timeout:.2f}s"
                )
        finally:
            with self._lock:
                self._pending.pop(rid, None)
        # any completed round trip (including an ERROR reply) is an RTT
        # sample for the adaptive timeout
        rtt = time.monotonic() - t0
        with self._rtt_lock:
            self._rtt_for_locked(to_peer).observe(rtt)
        RPC_RTT.observe(rtt)
        kind, data = box[0]
        if kind == _ERROR:
            raise ConnectionError(data.decode(errors="replace"))
        return self.codec.decode_response(method, data)

    # -- dialing / discovery ----------------------------------------------

    def dial(self, addr: str) -> bool:
        """Connect to ``host:port``; HELLO exchanges canonical addresses.
        Banned peers are refused (reconnect suppression)."""
        if addr == self.local_addr or addr in self._peers:
            return False
        if self.peer_manager.is_banned(addr=addr):
            return False
        host, port = addr.rsplit(":", 1)
        try:
            s = socket.create_connection((host, int(port)), timeout=5)
            # the connect timeout must not linger: a timed-out socket raises
            # on recv after 5 IDLE seconds, silently killing quiet peers
            s.settimeout(None)
        except OSError as e:
            log.warn("Dial failed", addr=addr, error=str(e))
            return False
        self._add_peer(s, addr)
        return True

    def discover(self, boot_addr: str, dial: bool = True) -> list[str]:
        """Announce to the UDP boot node and dial the peers it returns."""
        from .boot_node import client_announce

        found = client_announce(boot_addr, self.local_addr)
        if dial:
            for addr in found:
                self.dial(addr)
        return found

    def discover_enr(self, dial: bool = True) -> list[str]:
        """Run an iterative discv5-style lookup on the attached
        DiscoveryService and dial the discovered TCP listeners (banned
        peers filtered by dial())."""
        if self.discovery is None:
            return []
        self.discovery.lookup()
        found = [
            a for a in self.discovery.known_tcp_addrs()
            if a != self.local_addr
        ]
        if dial:
            for addr in found:
                self.dial(addr)
        return found

    def stop(self) -> None:
        self._stopped = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            peers = list(self._peers.values())
            self._peers.clear()
            readers = list(self._threads)
            self._threads.clear()
        for p in peers:
            _shutdown_close(p.sock)
        # closing the listener/sockets unblocks both loops; the joins are
        # bounded so a half-closed socket can never wedge shutdown
        self._accept_thread.join(timeout=2.0)
        for th in readers:
            th.join(timeout=2.0)

    # -- internals ---------------------------------------------------------

    def _add_peer(self, sock: socket.socket, addr: str) -> _Peer:
        peer = _Peer(sock, addr)
        self.peer_manager.on_connect(addr)
        with self._lock:
            old = self._peers.get(addr)
            self._peers[addr] = peer
        if old is not None:
            _shutdown_close(old.sock)
        peer.send_frame(
            _HELLO, bytes([len(self.local_addr)]) + self.local_addr.encode()
        )
        th = threading.Thread(
            target=self._read_loop, args=(peer,), daemon=True,
            name=f"net-read-{addr}",
        )
        th.start()
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(th)
        return peer

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                sock, (h, p) = self._listener.accept()
            except OSError:
                return
            # canonical addr arrives in the peer's HELLO; key by socket addr
            # meanwhile so duplicate dials don't race
            self._add_peer(sock, f"{h}:{p}")

    def _drop_peer(self, peer: _Peer, why: str) -> None:
        peer.alive = False
        with self._lock:
            if self._peers.get(peer.addr) is peer:
                del self._peers[peer.addr]
        self.peer_manager.on_disconnect(peer.addr)
        if self.discovery is not None and why.startswith("banned"):
            # a banned peer's record leaves the routing table too, so
            # lookups stop advertising it while the ban lasts
            for enr in self.discovery.table.all_records():
                if enr.tcp_addr == peer.addr:
                    self.discovery.table.remove(enr.node_id)
        _shutdown_close(peer.sock)
        if why != "closed":
            log.warn("Peer dropped", addr=peer.addr, reason=why)

    def _mark_seen(self, msg_id: bytes) -> bool:
        """True if the id is new (and records it)."""
        with self._lock:
            if msg_id in self._seen:
                return False
            self._seen[msg_id] = None
            while len(self._seen) > _SEEN_CAP:
                self._seen.popitem(last=False)
            return True

    def _flood(self, gossip_body: bytes, except_addr: str | None) -> None:
        with self._lock:
            targets = [
                p for a, p in self._peers.items() if a != except_addr
            ]
        for p in targets:
            try:
                p.send_frame(_GOSSIP, gossip_body)
            except OSError:
                self._drop_peer(p, "send failed")

    def _read_loop(self, peer: _Peer) -> None:
        buf = b""
        sock = peer.sock
        while peer.alive:
            try:
                chunk = sock.recv(1 << 16)
            except OSError:
                chunk = b""
            if not chunk:
                self._drop_peer(peer, "closed")
                return
            buf += chunk
            peer.frame_recv_t = time.monotonic()
            while len(buf) >= 4:
                (n,) = struct.unpack(">I", buf[:4])
                if n > _MAX_FRAME or n < 1:
                    self._drop_peer(peer, "bad frame length")
                    return
                if len(buf) < 4 + n:
                    break
                kind, body = buf[4], buf[5 : 4 + n]
                buf = buf[4 + n :]
                try:
                    self._handle_frame(peer, kind, body)
                except WireError as e:
                    if self._score(peer, SCORE_MALFORMED):
                        self._drop_peer(peer, f"banned (codec: {e})")
                        return
                    log.warn("Malformed frame", addr=peer.addr, error=str(e),
                             score=round(peer.score, 1))
                except Exception as e:  # noqa: BLE001 — protocol boundary
                    if self._score(peer, SCORE_HANDLER_ERROR):
                        self._drop_peer(peer, f"banned (handler: {e})")
                        return
                    log.warn("Peer message failed", addr=peer.addr,
                             error=str(e), score=round(peer.score, 1))

    def _handle_frame(self, peer: _Peer, kind: int, body: bytes) -> None:
        if kind == _HELLO:
            n = body[0]
            canonical = body[1 : 1 + n].decode()
            stale = None
            with self._lock:
                if self._peers.get(peer.addr) is peer:
                    del self._peers[peer.addr]
                peer.addr = canonical
                existing = self._peers.get(canonical)
                if existing is not None and existing is not peer:
                    # simultaneous dial: keep exactly one connection per pair,
                    # deterministically (smaller address keeps its outbound)
                    keep_new = self.local_addr < canonical
                    stale = existing if keep_new else peer
                    self._peers[canonical] = peer if keep_new else existing
                else:
                    self._peers[canonical] = peer
            if stale is not None:
                stale.alive = False
                _shutdown_close(stale.sock)
            # reconnect suppression: a banned peer announcing its canonical
            # address through a fresh inbound connection is cut here
            if self.peer_manager.is_banned(addr=canonical):
                self._drop_peer(peer, "banned (reconnect refused)")
                return
            self.peer_manager.on_connect(canonical)
        elif kind == _GOSSIP:
            tn = body[0]
            topic = body[1 : 1 + tn].decode()
            msg_id = body[1 + tn : 21 + tn]
            payload = body[21 + tn :]
            if not self._mark_seen(msg_id):
                peer.adjust_score(SCORE_DUPLICATE)
                return
            peer.adjust_score(SCORE_DELIVERY)
            # forward FIRST (gossip latency), then process locally
            self._flood(body, except_addr=peer.addr)
            if self._service is not None:
                message = self.codec.decode_gossip(topic, payload)
                self._service.on_gossip(topic, message, peer.addr)
            self.delivered += 1
        elif kind == _REQ:
            from .rate_limiter import request_cost

            (rid,) = struct.unpack(">Q", body[:8])
            mn = body[8]
            method = body[9 : 9 + mn].decode()
            payload = self.codec.decode_request(method, body[9 + mn :])
            cost = request_cost(method, payload)
            # serve-loop prune keeps the per-(peer, method) bucket map
            # bounded over long peer churn (time-gated, usually a no-op)
            self.rate_limiter.maybe_prune()
            if not self.rate_limiter.allow(peer.addr, method, cost):
                peer.send_frame(
                    _ERROR, struct.pack(">Q", rid) + b"rate limited"
                )
                if self._score(peer, SCORE_RATE_LIMITED):
                    self._drop_peer(peer, "banned (rpc flood)")
                return
            # admission-level shedding: lowest-priority methods are refused
            # first when the node is BUSY/SATURATED. No score penalty — the
            # peer did nothing wrong; OUR load is the problem.
            lvl = (self.load_monitor.level()
                   if self.load_monitor is not None else None)
            if lvl is not None and should_shed_method(method, lvl):
                SHED_REQUESTS.inc(
                    surface="req_resp",
                    priority=str(method_priority(method)),
                )
                peer.send_frame(
                    _ERROR,
                    struct.pack(">Q", rid) + b"overloaded: retry later",
                )
                return
            # server-side deadline: a request that waited in the read
            # pipeline past the client's timeout gets an error, not work —
            # the response would be discarded anyway
            if (time.monotonic() - peer.frame_recv_t
                    > self.server_deadline_s):
                RPC_EXPIRED.inc(method=method)
                peer.send_frame(
                    _ERROR, struct.pack(">Q", rid) + b"expired"
                )
                return
            try:
                out = self._service.on_rpc(method, payload, peer.addr)
                resp = self.codec.encode_response(method, out)
                peer.send_frame(_RESP, struct.pack(">Q", rid) + resp)
            except Exception as e:  # noqa: BLE001 — report to the requester
                peer.send_frame(
                    _ERROR, struct.pack(">Q", rid) + str(e).encode()
                )
        elif kind in (_RESP, _ERROR):
            (rid,) = struct.unpack(">Q", body[:8])
            with self._lock:
                entry = self._pending.get(rid)
            if entry is not None:
                ev, box = entry
                box.append((kind, body[8:]))
                ev.set()
        else:
            raise WireError(f"unknown frame kind {kind}")
