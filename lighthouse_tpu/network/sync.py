"""SyncManager: status-driven range sync with batched epochs.

Twin of ``network/src/sync/manager.rs`` (peer status intake, choosing a sync
target) + ``range_sync/{chain,batch}.rs`` (per-epoch batches requested via
BlocksByRange and imported as chain segments through the processor's
ChainSegment queue). Unknown-parent blocks trigger a sync round against the
best peer (the single-block-lookup path collapses into range sync here).
"""

from __future__ import annotations

from ..beacon_processor.processor import Work, WorkType
from .transport import Status

EPOCHS_PER_BATCH = 2  # range_sync/batch.rs EPOCHS_PER_BATCH


class SyncManager:
    def __init__(self, service):
        self.svc = service
        self.peer_status: dict[str, Status] = {}
        self.syncing = False

    # -- peer intake -------------------------------------------------------

    def on_peer_status(self, peer: str, status: Status) -> None:
        self.peer_status[peer] = status
        self.maybe_sync()

    def best_peer(self):
        """Peer with the highest head slot beyond our own."""
        ours = self.svc.chain.head.slot
        best = None
        for peer, st in self.peer_status.items():
            if st.head_slot > ours and (
                best is None or st.head_slot > self.peer_status[best].head_slot
            ):
                best = peer
        return best

    # -- range sync --------------------------------------------------------

    def maybe_sync(self) -> None:
        if self.syncing:
            return
        peer = self.best_peer()
        if peer is None:
            return
        self.syncing = True
        try:
            self._range_sync(peer)
        finally:
            self.syncing = False

    def _range_sync(self, peer: str) -> None:
        """Batched-epoch requests from our FINALIZED epoch to the peer's head.

        Starting at finalized (not at our head) is what makes the sync fork-
        tolerant: if we diverged from the peer after finality, the segment
        walks their branch from a block whose parent we share
        (range_sync/chain.rs starts chains at the local finalized epoch)."""
        chain = self.svc.chain
        spec = chain.spec
        batch_slots = EPOCHS_PER_BATCH * spec.preset.SLOTS_PER_EPOCH
        target = self.peer_status[peer].head_slot
        start = spec.start_slot(
            int(chain.head.state.finalized_checkpoint.epoch)
        ) + 1
        while start <= target:
            try:
                blocks = self.svc.transport.request(
                    self.svc.node_id, peer, "blocks_by_range",
                    (start, batch_slots),
                )
            except ConnectionError:
                return
            if blocks:
                self.svc.processor.submit(
                    Work(
                        work_type=WorkType.ChainSegment,
                        item=blocks,
                        process_individual=self.svc.process_chain_segment,
                    )
                )
            start += batch_slots
