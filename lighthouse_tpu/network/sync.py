"""SyncManager: range sync, backfill sync, and single-block lookups with
peer rotation and failure handling.

Twin of ``network/src/sync/manager.rs`` (peer status intake, sync-state
machine), ``range_sync/{chain,batch}.rs`` (per-epoch batches via
BlocksByRange with per-batch retry against rotated peers and demotion of
peers serving bad segments), ``backfill_sync/mod.rs`` (checkpoint-synced
nodes download history BACKWARDS to genesis, batch-verifying signatures and
anchoring each segment to the oldest known block), and ``block_lookups/``
(gossip blocks with unknown parents trigger a bounded parent-chain walk via
BlocksByRoot before import).

Sync work runs on a dedicated worker thread — a stalled or lying peer slows
one round, never the gossip/RPC callers (the reference's sync manager is its
own task for the same reason). Peers whose segments fail verification are
demoted and eventually ignored; a peer advertising a bogus high head gets
demoted when its promised blocks never verify, unsticking the target
selection (VERDICT r2 weakness #4).
"""

from __future__ import annotations

import threading
import time

from ..loadshed.adaptive import BackoffPolicy
from ..utils.logging import get_logger
from .transport import Status

log = get_logger("sync")

EPOCHS_PER_BATCH = 2        # range_sync/batch.rs EPOCHS_PER_BATCH
MAX_BATCH_RETRIES = 3       # distinct peers tried per batch (batch.rs MAX_BATCH_DOWNLOAD_ATTEMPTS)
PEER_FAILURE_LIMIT = 3      # demotions before a peer is ignored entirely
MAX_LOOKUP_DEPTH = 32       # parent-chain hops (block_lookups PARENT_DEPTH_TOLERANCE)
SCORE_BAD_SEGMENT = -20.0   # transport score hit for an unverifiable segment


class SyncManager:
    def __init__(self, service, threaded: bool = True, backoff=None):
        self.svc = service
        self.peer_status: dict[str, Status] = {}
        self.peer_failures: dict[str, int] = {}
        # jittered exponential backoff + per-peer cooldown for the retry
        # loops: a failing peer is not immediately re-asked, and repeated
        # failures grow its cooldown (loadshed.adaptive.BackoffPolicy)
        self.backoff = backoff if backoff is not None else BackoffPolicy()
        self.backfill_enabled = True
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stopped = False
        self._threaded = threaded
        self._thread = None
        self._lookup_threads: list[threading.Thread] = []
        if threaded:
            self._thread = threading.Thread(
                target=self._worker, daemon=True,
                name=f"sync-{getattr(service, 'node_id', '?')}",
            )
            self._thread.start()

    def stop(self) -> None:
        self._stopped = True
        self._wake.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        with self._lock:
            lookups = list(self._lookup_threads)
        for th in lookups:
            th.join(timeout=2.0)

    # -- peer intake -------------------------------------------------------

    def on_peer_status(self, peer: str, status: Status) -> None:
        with self._lock:
            self.peer_status[peer] = status
        self.maybe_sync()

    def _demote(self, peer: str, why: str) -> None:
        """A peer served a bad/unverifiable segment or lied about its head:
        count the strike, score it on the transport, forget its status once
        it crosses the limit (sync/manager.rs peer-action reporting)."""
        with self._lock:
            n = self.peer_failures.get(peer, 0) + 1
            self.peer_failures[peer] = n
            if n >= PEER_FAILURE_LIMIT:
                self.peer_status.pop(peer, None)
        log.warn("Sync peer demoted", peer=peer, reason=why, strikes=n)
        report = getattr(self.svc.transport, "report_peer", None)
        if report is not None:
            report(peer, SCORE_BAD_SEGMENT)

    def _usable_peers(self) -> list[str]:
        """Peers ahead of us, best head first, failure-limited peers last."""
        ours = self.svc.chain.head.slot
        with self._lock:
            peers = [
                (st.head_slot, -self.peer_failures.get(p, 0), p)
                for p, st in self.peer_status.items()
                if st.head_slot > ours
                and self.peer_failures.get(p, 0) < PEER_FAILURE_LIMIT
            ]
        peers.sort(reverse=True)
        return [p for _, _, p in peers]

    def _serving_peers(self) -> list[str]:
        """Any non-demoted peer (backfill serves from peers at ANY head)."""
        with self._lock:
            return [
                p for p in self.peer_status
                if self.peer_failures.get(p, 0) < PEER_FAILURE_LIMIT
            ]

    def best_peer(self):
        peers = self._usable_peers()
        return peers[0] if peers else None

    # -- the worker --------------------------------------------------------

    def maybe_sync(self) -> None:
        if self._threaded:
            self._idle.clear()
            self._wake.set()
        else:
            self._sync_round()
            if self.backfill_enabled:
                self._backfill_round()

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the worker has drained its queue (tests/drivers)."""
        if not self._threaded:
            return True
        return self._idle.wait(timeout)

    def _worker(self) -> None:
        while not self._stopped:
            self._wake.wait()
            self._wake.clear()
            if self._stopped:
                return
            try:
                self._sync_round()
                if self.backfill_enabled:
                    self._backfill_round()
            except Exception as e:  # noqa: BLE001 — sync must survive anything
                log.warn("Sync round failed", error=str(e))
            if not self._wake.is_set():
                self._idle.set()

    # -- range sync (forwards) ---------------------------------------------

    def _sync_round(self) -> None:
        """Catch up to the best advertised head, batch by batch, rotating
        peers per batch and demoting peers that serve unverifiable segments
        (range_sync/chain.rs). A target peer whose promised head never
        materializes is demoted, so a liar cannot wedge sync."""
        chain = self.svc.chain
        spec = chain.spec
        batch_slots = EPOCHS_PER_BATCH * spec.preset.SLOTS_PER_EPOCH
        while True:
            peers = self._usable_peers()
            if not peers:
                return
            target_peer = peers[0]
            with self._lock:
                target = self.peer_status[target_peer].head_slot
            # fork-tolerant start: local finalized epoch (range_sync/chain.rs)
            # — but never below the checkpoint anchor, whose earlier history
            # is the backfill's job, not forward sync's
            start = max(
                spec.start_slot(
                    int(chain.head.state.finalized_checkpoint.epoch)
                ),
                getattr(chain, "oldest_block_slot", 0),
            ) + 1
            head_before = chain.head.slot
            failed = False
            while start <= target:
                got = self._download_batch(start, batch_slots)
                if got is None:
                    failed = True
                    break
                start += batch_slots
            if chain.head.slot >= target:
                return  # caught up to this target
            if failed:
                return  # no peer could serve; try again on next status
            # progress means the HEAD advanced — downloads that import as
            # no-ops must not count, or a lying/unusable target loops the
            # sync forever. Demote and re-select.
            if chain.head.slot <= head_before:
                self._demote(target_peer, "advertised head never materialized")
                continue

    def _download_batch(self, start: int, count: int):
        """One BlocksByRange batch tried against up to MAX_BATCH_RETRIES
        peers. Returns imported block count, or None if no peer served.

        Rotation is backoff-aware: peers inside their failure cooldown are
        skipped, and consecutive failed attempts within this batch sleep a
        growing jittered delay instead of hammering the next peer."""
        tried = 0
        for peer in self._usable_peers():
            if tried >= MAX_BATCH_RETRIES:
                break
            if not self.backoff.ready(peer):
                continue
            if tried:
                time.sleep(self.backoff.attempt_delay(tried))
            tried += 1
            try:
                blocks = self.svc.transport.request(
                    self.svc.node_id, peer, "blocks_by_range", (start, count)
                )
            except ConnectionError as e:
                self.backoff.record_failure(peer)
                self._demote(peer, f"blocks_by_range failed: {e}")
                continue
            self.backoff.record_success(peer)
            if not blocks:
                return 0
            if self._import_segment(blocks, peer, "bad segment"):
                return len(blocks)
        return None

    def _import_segment(self, blocks, peer: str, label: str) -> bool:
        """Import a downloaded segment, coupling PeerDAS column downloads
        to the block download (block_sidecar_coupling.rs): a block parked
        on column availability pulls its missing custody/sample columns
        from the serving peer by root and the import retries. A peer that
        cannot close the gap rotates WITHOUT a strike — pending
        availability is a property of the data, not peer misbehavior; only
        segments that fail verification demote.

        Direct call, NOT processor.submit: the synchronous processor
        drains every queue, so a failure raised here could belong to a
        concurrent submitter's work and the demotion would hit the wrong
        peer."""
        from ..beacon_chain.chain import BlockPendingAvailability

        fetch = getattr(self.svc, "_fetch_missing_columns", None)
        pending_seen: set[bytes] = set()
        while True:
            try:
                self.svc.process_chain_segment_strict(blocks)
                return True
            except BlockPendingAvailability as e:
                root = bytes(e.block_root)
                if fetch is None or root in pending_seen:
                    return False  # this peer can't close the gap: rotate
                pending_seen.add(root)
                fetch(root, peer)
            except Exception as e:  # noqa: BLE001 — bad segment
                self._demote(peer, f"{label}: {e}")
                return False

    # -- backfill sync (backwards) -----------------------------------------

    def _backfill_round(self) -> None:
        """Checkpoint-synced nodes: download history backwards from the
        oldest known block to genesis (backfill_sync/mod.rs +
        historical_blocks.rs). Batches anchor by hash-chain linkage + one
        batched signature verification; bad segments demote the peer and
        rotate."""
        chain = self.svc.chain
        if not hasattr(chain, "backfill_complete") or chain.backfill_complete:
            return
        if getattr(chain, "anchor_block_missing", False):
            # the checkpoint anchor block itself first (root-pinned fetch)
            block = self._lookup_by_root(chain.genesis_block_root)
            if block is None:
                return
            chain.import_anchor_block(block)
        spec = chain.spec
        batch_slots = EPOCHS_PER_BATCH * spec.preset.SLOTS_PER_EPOCH
        while not chain.backfill_complete:
            oldest = chain.oldest_block_slot
            # the window's upper edge slides DOWN without demotion when the
            # linking parent sits below it (a skip-slot gap wider than one
            # batch is honest chain shape, not peer misbehavior)
            hi = oldest
            imported = False
            while not imported and hi > 1:
                start = max(1, hi - batch_slots)
                count = hi - start
                got_any = False
                ready = [
                    p for p in self._serving_peers()
                    if self.backoff.ready(p)
                ]
                for peer in ready[:MAX_BATCH_RETRIES]:
                    try:
                        blocks = self.svc.transport.request(
                            self.svc.node_id, peer, "blocks_by_range",
                            (start, count),
                        )
                    except ConnectionError as e:
                        self.backoff.record_failure(peer)
                        self._demote(peer, f"backfill download failed: {e}")
                        continue
                    self.backoff.record_success(peer)
                    blocks = [
                        b for b in blocks if int(b.message.slot) < oldest
                    ]
                    if not blocks:
                        continue
                    got_any = True
                    try:
                        n = chain.import_historical_blocks(blocks)
                        log.info(
                            "Backfilled", blocks=n,
                            oldest_slot=chain.oldest_block_slot,
                        )
                        imported = True
                        break
                    except Exception as e:  # noqa: BLE001 — bad segment
                        if start > 1 and "link" in str(e):
                            # parent below the window: widen, don't punish
                            break
                        self._demote(peer, f"bad backfill segment: {e}")
                if imported:
                    break
                if start == 1:
                    if not got_any:
                        return  # nothing below our oldest block: done
                    return  # full-range segment unusable; retry next wake
                hi = start
            if not imported:
                return  # retry on next wake

    # -- single-block lookups ----------------------------------------------

    def on_unknown_parent(self, signed_block, from_peer: str) -> None:
        """A gossip block whose parent we don't know: walk the parent chain
        backwards via BlocksByRoot (bounded), then import the recovered
        segment oldest-first (sync/block_lookups/ parent lookups).

        Lookups dedup by block root — N mesh peers regossiping the same
        orphan (or a peer fabricating orphans) must not fan out N thread/RPC
        walks for one chain (block_lookups' by-root dedup)."""
        root = signed_block.message.tree_root()
        with self._lock:
            inflight = getattr(self, "_inflight_lookups", None)
            if inflight is None:
                inflight = self._inflight_lookups = set()
            if root in inflight or len(inflight) >= 32:
                return
            inflight.add(root)
        if self._threaded:
            th = threading.Thread(
                target=self._parent_lookup_tracked,
                args=(root, signed_block, from_peer),
                daemon=True, name="sync-lookup",
            )
            th.start()
            with self._lock:
                self._lookup_threads[:] = [
                    t for t in self._lookup_threads if t.is_alive()
                ]
                self._lookup_threads.append(th)
        else:
            self._parent_lookup_tracked(root, signed_block, from_peer)

    def _parent_lookup_tracked(self, root, signed_block, from_peer) -> None:
        try:
            self._parent_lookup(signed_block, from_peer)
        finally:
            with self._lock:
                self._inflight_lookups.discard(root)

    def _parent_lookup(self, signed_block, from_peer: str) -> None:
        chain = self.svc.chain
        segment = [signed_block]
        for _ in range(MAX_LOOKUP_DEPTH):
            parent_root = bytes(segment[0].message.parent_root)
            if parent_root in chain._seen_blocks:
                break
            block = self._lookup_by_root(parent_root, prefer=from_peer)
            if block is None:
                log.warn(
                    "Parent lookup failed", root=parent_root.hex()[:16],
                )
                return
            segment.insert(0, block)
        else:
            log.warn("Parent chain deeper than lookup tolerance")
            return
        self._import_segment(segment, from_peer, "unviable lookup segment")

    def _lookup_by_root(self, root: bytes, prefer: str | None = None):
        """BlocksByRoot from the preferring peer first, then rotation. The
        sender goes first even before its status handshake lands — it is
        the one peer guaranteed to hold the block it just gossiped."""
        # cooldown-aware rotation — but the preferring peer always goes
        # first regardless (it just gossiped the block; it has it)
        peers = [
            p for p in self._serving_peers()
            if p == prefer or self.backoff.ready(p)
        ]
        if prefer is not None:
            if prefer in peers:
                peers.remove(prefer)
            peers.insert(0, prefer)
        for peer in peers[: MAX_BATCH_RETRIES + 1]:
            try:
                blocks = self.svc.transport.request(
                    self.svc.node_id, peer, "blocks_by_root", [root]
                )
            except ConnectionError:
                self.backoff.record_failure(peer)
                continue
            self.backoff.record_success(peer)
            for b in blocks:
                if b.message.tree_root() == root:
                    return b
        return None
