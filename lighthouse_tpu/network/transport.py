"""Transport seam + the in-process loopback implementation.

Gossip topics mirror the gossipsub topic family
(``lighthouse_network/src/types/topics.rs``); req/resp mirrors the Req/Resp
protocols (``lighthouse_network/src/rpc/protocol.rs``: Status, BlocksByRange,
BlocksByRoot). The loopback bus delivers synchronously and deterministically —
the shape ``testing/simulator`` relies on for multi-node tests without
sockets.
"""

from __future__ import annotations

from dataclasses import dataclass


class Topic:
    BEACON_BLOCK = "beacon_block"
    BEACON_ATTESTATION = "beacon_attestation"  # subnet topics collapse to one
    AGGREGATE_AND_PROOF = "beacon_aggregate_and_proof"
    VOLUNTARY_EXIT = "voluntary_exit"
    PROPOSER_SLASHING = "proposer_slashing"
    ATTESTER_SLASHING = "attester_slashing"
    SYNC_COMMITTEE_MESSAGE = "sync_committee"  # subnet topics collapse to one
    SYNC_CONTRIBUTION = "sync_committee_contribution_and_proof"
    DATA_COLUMN_SIDECAR = "data_column_sidecar"  # PeerDAS (subnets collapse)


@dataclass
class Status:
    """Req/resp Status handshake payload (rpc STATUS message)."""

    fork_digest: bytes
    finalized_root: bytes
    finalized_epoch: int
    head_root: bytes
    head_slot: int


class Transport:
    """What a node needs from the wire: publish/subscribe + peer RPC."""

    def publish(self, from_peer: str, topic: str, message) -> None:
        raise NotImplementedError

    def request(self, from_peer: str, to_peer: str, method: str, payload):
        raise NotImplementedError

    def peers(self, exclude: str | None = None) -> list[str]:
        raise NotImplementedError


class LoopbackTransport(Transport):
    """All nodes in one process; delivery is an immediate method call.

    Fault injection: ``partition(a, b)`` drops traffic between two peers
    (both gossip and RPC) until ``heal()``; ``set_gossip_loss(rate, seed)``
    drops each gossip delivery with a SEEDED probability — deterministic
    given the seed and the (synchronous) publish order, so a chaos run
    replays exactly; ``unregister`` simulates a node crash (the chaos
    harness re-``register``s on restart).
    """

    def __init__(self):
        self._handlers: dict[str, object] = {}  # peer_id -> service
        self._partitions: set[frozenset] = set()
        self._loss_rate = 0.0
        self._loss_rng = None
        self.gossip_delivered = 0
        self.gossip_dropped = 0  # seeded-loss drops only (not partitions)
        # chaos-harness hook: called with an InjectedCrash raised by a
        # RECIPIENT during delivery. A kill -9 of one subscriber must not
        # unwind the publisher's fan-out — the hook crashes that node and
        # delivery continues to the remaining peers.
        self.on_injected_crash = None

    def register(self, peer_id: str, service) -> None:
        if peer_id in self._handlers:
            raise ValueError(f"duplicate peer id {peer_id}")
        self._handlers[peer_id] = service

    def unregister(self, peer_id: str) -> None:
        """Crash ``peer_id``: all delivery to/from it stops until a new
        service registers under the same id."""
        self._handlers.pop(peer_id, None)

    def partition(self, a: str, b: str) -> None:
        self._partitions.add(frozenset((a, b)))

    def heal(self) -> None:
        self._partitions.clear()

    def set_gossip_loss(self, rate: float, seed: int = 0) -> None:
        """Drop each (recipient, message) gossip delivery with probability
        ``rate``, decided by a dedicated seeded RNG. ``rate=0`` disables."""
        import random as _random

        self._loss_rate = float(rate)
        self._loss_rng = _random.Random(seed) if rate > 0 else None

    def _blocked(self, a: str, b: str) -> bool:
        return frozenset((a, b)) in self._partitions

    def publish(self, from_peer: str, topic: str, message) -> None:
        for pid, svc in list(self._handlers.items()):
            if pid == from_peer or self._blocked(pid, from_peer):
                continue
            if self._loss_rng is not None and (
                self._loss_rng.random() < self._loss_rate
            ):
                self.gossip_dropped += 1
                continue
            self.gossip_delivered += 1
            if self.on_injected_crash is None:
                svc.on_gossip(topic, message, from_peer)
                continue
            from ..resilience import InjectedCrash

            try:
                svc.on_gossip(topic, message, from_peer)
            except InjectedCrash as e:
                # the recipient died at one of its persistence barriers;
                # the publisher and every other peer keep going
                self.on_injected_crash(e)

    def request(self, from_peer: str, to_peer: str, method: str, payload):
        if self._blocked(from_peer, to_peer):
            raise ConnectionError(f"partitioned: {from_peer} <-> {to_peer}")
        svc = self._handlers.get(to_peer)
        if svc is None:
            raise ConnectionError(f"unknown peer {to_peer}")
        return svc.on_rpc(method, payload, from_peer)

    def peers(self, exclude: str | None = None) -> list[str]:
        return [p for p in self._handlers if p != exclude]
