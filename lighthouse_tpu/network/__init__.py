"""Networking layer: transport seam, router, sync, node service.

Twin of the reference's L5 stack (``beacon_node/network`` +
``lighthouse_network``), built seam-first: the ``Transport`` interface carries
gossip topics and req/resp RPC; ``LoopbackTransport`` is the in-process
message bus (the multi-node-without-sockets pattern of
``testing/simulator/src/local_network.rs:128`` and the sync tests at
``network/src/sync/tests/lookups.rs``); a libp2p/gossipsub/discv5 transport
plugs in behind the same interface for real peers. ``Router`` dispatches
pubsub messages into the beacon processor's prioritized queues
(``network/src/router.rs:381-535``); ``SyncManager`` does status-driven range
sync with batched epochs (``network/src/sync/manager.rs``,
``range_sync/batch.rs``); ``BeaconNodeService`` wires one node together.
"""

from .router import Router  # noqa: F401
from .service import BeaconNodeService  # noqa: F401
from .sync import SyncManager  # noqa: F401
from .transport import LoopbackTransport, Topic  # noqa: F401
