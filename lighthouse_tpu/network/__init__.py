"""Networking layer: transport seam, router, sync, node service.

Twin of the reference's L5 stack (``beacon_node/network`` +
``lighthouse_network``), built seam-first: the ``Transport`` interface carries
gossip topics and req/resp RPC; ``LoopbackTransport`` is the in-process
message bus (the multi-node-without-sockets pattern of
``testing/simulator/src/local_network.rs:128`` and the sync tests at
``network/src/sync/tests/lookups.rs``); ``SocketTransport`` is the
real-peer implementation — TCP flood-gossip with message-id dedup plus
Req/Resp framing — with ``BootNode`` as the UDP discovery rendezvous
(``boot_node/``, the discv5 seam). ``Router`` dispatches
pubsub messages into the beacon processor's prioritized queues
(``network/src/router.rs:381-535``); ``SyncManager`` does status-driven range
sync with batched epochs (``network/src/sync/manager.rs``,
``range_sync/batch.rs``); ``BeaconNodeService`` wires one node together.
"""

from .boot_node import BootNode  # noqa: F401
from .codec import MessageCodec, WireError  # noqa: F401
from .gossipsub import GossipsubParams, GossipsubTransport  # noqa: F401
from .router import Router  # noqa: F401
from .service import BeaconNodeService  # noqa: F401
from .socket_transport import SocketTransport  # noqa: F401
from .sync import SyncManager  # noqa: F401
from .transport import LoopbackTransport, Topic  # noqa: F401
