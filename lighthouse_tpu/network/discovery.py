"""discv5-style node discovery: signed ENRs, XOR routing table, iterative
FINDNODE lookup over UDP.

The TPU-native twin of the reference's discovery stack
(``lighthouse_network/src/discovery/mod.rs:1-1338``, ``discovery/enr.rs:1-399``):

* **ENR** — a signed, sequenced node record carrying (node_id, fork_digest,
  ip, tcp/udp ports). The reference signs with secp256k1 ("v4" identity
  scheme); this stack signs with BLS12-381 (the curve the framework already
  implements end to end) — identity scheme ``"bls"``; records are
  self-certifying: any packet carries the sender's ENR and receivers verify
  the signature before admitting it to the table.
* **Routing table** — Kademlia buckets by XOR log-distance over the 32-byte
  node id, k=16 per bucket, LRU within a bucket (discv5 table semantics).
* **Wire protocol** (UDP datagrams):
      kind 1 PING      : empty                      (liveness + ENR exchange)
      kind 2 PONG      : empty
      kind 3 FINDNODE  : u8 cookie_len | cookie | u8 n | u16 log-distances
      kind 4 NODES     : u16 count | ENR*           (response)
      kind 5 WHOAREYOU : 16-byte cookie             (source-address challenge)
  every packet = u16 enr_len | sender ENR | u8 kind | body — contact alone
  teaches a verified record.
* **Stateless source-address validation** (discv5 WHOAREYOU): a FINDNODE
  whose cookie does not validate is answered with a tiny fixed-size
  WHOAREYOU challenge — BEFORE any ENR signature verification — carrying
  an HMAC cookie bound to (source ip, port, time window) under a local
  secret; no per-peer state is kept. The requester retries with the cookie
  echoed. A spoofed-source FINDNODE therefore costs the server one HMAC and
  a reply no larger than the request (no ~10x NODES amplification toward
  the victim, no attacker-triggered BLS signature verification), and the
  cookie only ever reaches the true owner of the source address. NODES
  responses are ingested solicited-only (a forged NODES from a node we
  asked nothing of is dropped before any signature work). Unsolicited
  PING/PONG stay one bounded ENR verify per datagram — the
  eviction-liveness protocol needs them — until the real discv5 session
  handshake lands behind the transport seam (ROADMAP).
* **Iterative lookup** — query the α closest known nodes for the target's
  distance, admit returned records, repeat while strictly closer nodes
  appear (bounded rounds). This is how a node bootstrapped from ONE boot
  node transitively discovers the rest of the network.

Fork-digest filtering mirrors the reference's `eth2` ENR field: lookups and
table admission drop records whose fork digest differs from ours.
"""

from __future__ import annotations

import secrets
import socket
import struct
import threading
import time

from ..utils.logging import get_logger

log = get_logger("discovery")

K_BUCKET = 16          # discv5 bucket size
ALPHA = 3              # lookup concurrency
MAX_LOOKUP_ROUNDS = 8
_PING, _PONG, _FINDNODE, _NODES, _WHOAREYOU = 1, 2, 3, 4, 5
_MAX_NODES_PER_RESPONSE = 16
_COOKIE_LEN = 16       # WHOAREYOU cookie bytes (truncated HMAC-SHA256)
_COOKIE_WINDOW_S = 60  # cookie validity window (current + previous accepted)
_COOKIE_CACHE_MAX = 1024  # client-side cached cookies (expired pruned first)
# Liveness-checked eviction (discv5 pending-node semantics): before a full
# bucket evicts its oldest record, the service PINGs it and only replaces it
# if no packet arrives within this window. Unconditional LRU eviction lets
# an attacker flush honest long-lived peers with a stream of fresh ENRs
# (eclipse pressure); a live oldest node always survives.
LIVENESS_TIMEOUT_S = 1.0
_SERVE_TICK_S = 0.25   # serve-loop wakeup for pending-eviction expiry


def _sign_payload(sk_scalar: int, content: bytes) -> bytes:
    from ..ops.bls_oracle import ciphersuite as cs
    from ..ops.bls_oracle import curves as oc
    import hashlib

    return oc.g2_compress(cs.sign(sk_scalar, hashlib.sha256(content).digest()))


def _verify_payload(pubkey: bytes, content: bytes, sig: bytes) -> bool:
    import hashlib

    msg = hashlib.sha256(content).digest()
    # ENR verification runs per received packet on the discovery thread —
    # use the native C++ backend when buildable (sub-ms) regardless of the
    # configured chain backend; the pure-Python oracle is the fallback
    try:
        from ..bls import _native

        return bool(_native().verify(pubkey, msg, sig))
    except Exception:  # noqa: BLE001 — fall back to the in-process path
        pass
    from ..bls import PublicKey, Signature, BlsError

    try:
        pk = PublicKey.from_bytes(pubkey)
        s = Signature.from_bytes(sig)
    except BlsError:
        return False
    return s.verify(pk, msg)


class ENR:
    """Ethereum Node Record, identity scheme "bls": content = (seq,
    fork_digest, ip, tcp, udp, pubkey); node_id = sha256(pubkey)."""

    __slots__ = ("seq", "fork_digest", "ip", "tcp", "udp", "pubkey", "sig")

    def __init__(self, seq, fork_digest, ip, tcp, udp, pubkey, sig=b""):
        self.seq = seq
        self.fork_digest = fork_digest
        self.ip = ip
        self.tcp = tcp
        self.udp = udp
        self.pubkey = pubkey
        self.sig = sig

    @property
    def node_id(self) -> bytes:
        import hashlib

        return hashlib.sha256(self.pubkey).digest()

    @property
    def tcp_addr(self) -> str:
        return f"{self.ip}:{self.tcp}"

    @property
    def udp_addr(self) -> tuple:
        return (self.ip, self.udp)

    def _content(self) -> bytes:
        ip_b = self.ip.encode()
        return (
            struct.pack(">Q4sB", self.seq, self.fork_digest, len(ip_b))
            + ip_b
            + struct.pack(">HH", self.tcp, self.udp)
            + self.pubkey
        )

    def encode(self) -> bytes:
        body = self._content() + self.sig
        return struct.pack(">H", len(body)) + body

    @classmethod
    def decode(cls, data: bytes, off: int = 0):
        """Returns (enr, next_offset); raises ValueError on malformed data."""
        if len(data) < off + 2:
            raise ValueError("short ENR length")
        (n,) = struct.unpack_from(">H", data, off)
        body = data[off + 2 : off + 2 + n]
        if len(body) != n:
            raise ValueError("short ENR body")
        seq, fork_digest, ip_len = struct.unpack_from(">Q4sB", body, 0)
        p = 13
        ip = body[p : p + ip_len].decode()
        p += ip_len
        tcp, udp = struct.unpack_from(">HH", body, p)
        p += 4
        pubkey = body[p : p + 48]
        sig = body[p + 48 :]
        if len(pubkey) != 48 or len(sig) != 96:
            raise ValueError("bad ENR key/sig lengths")
        return cls(seq, fork_digest, ip, tcp, udp, pubkey, sig), off + 2 + n

    def sign(self, sk_scalar: int) -> "ENR":
        self.sig = _sign_payload(sk_scalar, self._content())
        return self

    def verify(self) -> bool:
        return _verify_payload(self.pubkey, self._content(), self.sig)


def log_distance(a: bytes, b: bytes) -> int:
    """discv5 log2-distance: bit length of a XOR b (0 when equal)."""
    x = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return x.bit_length()


class RoutingTable:
    """256 XOR-distance buckets of K_BUCKET records each, LRU per bucket."""

    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self._buckets: dict[int, list[ENR]] = {}
        self._lock = threading.Lock()

    def admit(self, enr: ENR, on_full=None) -> bool:
        """Admit/refresh a record. On a full bucket: with ``on_full`` set
        (the service's liveness path) the candidate is handed to
        ``on_full(oldest, candidate)`` and NOT admitted yet — the caller
        pings the oldest and either keeps it (drop candidate) or calls
        ``replace``; without it, legacy LRU eviction applies (direct table
        users/tests). ``on_full`` runs under the table lock and must not
        call back into the table."""
        nid = enr.node_id
        if nid == self.local_id:
            return False
        d = log_distance(self.local_id, nid)
        with self._lock:
            bucket = self._buckets.setdefault(d, [])
            for i, existing in enumerate(bucket):
                if existing.node_id == nid:
                    if enr.seq >= existing.seq:
                        bucket.pop(i)
                        bucket.append(enr)
                    return True
            if len(bucket) >= K_BUCKET:
                if on_full is not None:
                    on_full(bucket[0], enr)
                    return False
                bucket.pop(0)  # LRU eviction (head is oldest)
            bucket.append(enr)
            return True

    def touch(self, node_id: bytes) -> None:
        """Refresh a record to most-recently-seen (liveness proof)."""
        d = log_distance(self.local_id, node_id)
        with self._lock:
            bucket = self._buckets.get(d, [])
            for i, e in enumerate(bucket):
                if e.node_id == node_id:
                    bucket.append(bucket.pop(i))
                    return

    def replace(self, old_id: bytes, new_enr: ENR) -> bool:
        """Swap a liveness-check failure for the pending candidate (same
        bucket by construction; a vanished oldest still admits the new)."""
        d = log_distance(self.local_id, old_id)
        with self._lock:
            bucket = self._buckets.get(d, [])
            self._buckets[d] = [e for e in bucket if e.node_id != old_id]
        return self.admit(new_enr)

    def remove(self, node_id: bytes) -> None:
        d = log_distance(self.local_id, node_id)
        with self._lock:
            bucket = self._buckets.get(d, [])
            self._buckets[d] = [e for e in bucket if e.node_id != node_id]

    def at_distance(self, d: int) -> list[ENR]:
        with self._lock:
            return list(self._buckets.get(d, []))

    def closest(self, target: bytes, n: int) -> list[ENR]:
        with self._lock:
            allr = [e for b in self._buckets.values() for e in b]
        return sorted(
            allr,
            key=lambda e: int.from_bytes(e.node_id, "big")
            ^ int.from_bytes(target, "big"),
        )[:n]

    def all_records(self) -> list[ENR]:
        with self._lock:
            return [e for b in self._buckets.values() for e in b]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._buckets.values())


class DiscoveryService:
    """One node's discovery endpoint: local signed ENR + routing table +
    UDP server answering PING/FINDNODE, with iterative lookup client."""

    def __init__(
        self,
        fork_digest: bytes = b"\x00\x00\x00\x00",
        ip: str = "127.0.0.1",
        tcp_port: int = 0,
        udp_port: int = 0,
        sk_scalar: int | None = None,
        peer_manager=None,
    ):
        from ..ops.bls_oracle.fields import R

        self.sk = sk_scalar or (
            int.from_bytes(secrets.token_bytes(31), "big") % R or 1
        )
        from ..ops.bls_oracle import ciphersuite as cs
        from ..ops.bls_oracle import curves as oc

        self.pubkey = oc.g1_compress(cs.sk_to_pk(self.sk))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((ip, udp_port))
        self.enr = ENR(
            1, fork_digest, ip, tcp_port, self._sock.getsockname()[1],
            self.pubkey,
        ).sign(self.sk)
        self.table = RoutingTable(self.enr.node_id)
        self.peer_manager = peer_manager
        self._stopped = False
        self._thread: threading.Thread | None = None
        # pending liveness-checked evictions: bucket distance -> (oldest
        # node_id, candidate ENR, deadline). One pending slot per bucket
        # (discv5); candidates arriving while a check is in flight drop.
        self._pending_evictions: dict[int, tuple[bytes, ENR, float]] = {}
        self._pending_lock = threading.Lock()
        # per-request FINDNODE response tracking: responder node_id ->
        # events set by the serve loop when that peer's NODES response
        # lands (a list — concurrent lookups may query the same peer, and
        # one response settles every waiter). Replaces the old table-size
        # polling, which burned the full timeout whenever a response taught
        # nothing new (already-known records).
        self._pending_requests: dict[bytes, list[threading.Event]] = {}
        # addr -> outstanding FINDNODE count: lives for the WHOLE request
        # (unlike _findnode_inflight, which the WHOAREYOU retry consumes) —
        # the serve loop's NODES gate requires the SOURCE ADDRESS to match
        # an outstanding request, not just the forgeable node_id
        self._pending_addrs: dict[tuple, int] = {}
        self._requests_lock = threading.Lock()
        # stateless WHOAREYOU source-address validation: cookies we hand out
        # are HMAC(secret, src_addr || time window) — no per-peer state; the
        # client side caches the cookie each server gave us and remembers
        # the in-flight FINDNODE body per destination so a WHOAREYOU
        # challenge can be answered with one retry.
        self._cookie_secret = secrets.token_bytes(16)
        # addr -> (cookie, expiry): bounded — entries expire with the server
        # window and the insert path prunes, so walking the whole DHT
        # keyspace over a long uptime cannot grow this without limit
        self._cookies: dict[tuple, tuple[bytes, float]] = {}
        self._findnode_inflight: dict[tuple, bytes] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DiscoveryService":
        self._thread = threading.Thread(
            target=self._serve, daemon=True,
            name=f"discovery-{self.enr.udp_addr[1]}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def update_tcp_port(self, port: int) -> None:
        """Re-sign the local ENR with the final TCP listen port (the
        transport binds after discovery starts); bumps seq. The serve
        thread answers FINDNODE from self.enr concurrently, so the
        read-bump-resign sequence must be atomic under the pending lock."""
        with self._pending_lock:
            self.enr = ENR(
                self.enr.seq + 1, self.enr.fork_digest, self.enr.ip, port,
                self.enr.udp_addr[1], self.pubkey,
            ).sign(self.sk)

    # -- record admission --------------------------------------------------

    def _admit(self, enr: ENR) -> bool:
        """Verify + filter a remote record: signature, fork digest, and the
        peer-manager's ban list all gate table admission. Full buckets go
        through the liveness-checked eviction path instead of blind LRU."""
        if enr.node_id == self.enr.node_id:
            return False
        if enr.fork_digest != self.enr.fork_digest:
            return False
        if not enr.verify():
            return False
        if self.peer_manager is not None and self.peer_manager.is_banned(
            node_id=enr.node_id, addr=enr.tcp_addr
        ):
            return False
        return self.table.admit(enr, on_full=self._on_bucket_full)

    # -- liveness-checked eviction ----------------------------------------

    def _on_bucket_full(self, oldest: ENR, candidate: ENR) -> None:
        """Called (under the table lock — no table calls here) when a
        verified candidate hits a full bucket: ping the bucket's oldest
        record and park the candidate. Any packet from the oldest before
        the deadline cancels the eviction; expiry replaces it."""
        d = log_distance(self.enr.node_id, oldest.node_id)
        with self._pending_lock:
            if d in self._pending_evictions:
                return  # one pending check per bucket; extra candidates drop
            self._pending_evictions[d] = (
                oldest.node_id, candidate, time.monotonic() + LIVENESS_TIMEOUT_S,
            )
        self._send(oldest.udp_addr, _PING, b"")

    def _note_liveness(self, node_id: bytes) -> None:
        """A packet from ``node_id`` proves liveness: cancel any pending
        eviction of it (candidate drops) and refresh its LRU position."""
        d = log_distance(self.enr.node_id, node_id)
        cancelled = False
        with self._pending_lock:
            pend = self._pending_evictions.get(d)
            if pend is not None and pend[0] == node_id:
                del self._pending_evictions[d]
                cancelled = True
        if cancelled:
            self.table.touch(node_id)
            log.debug(
                "bucket eviction cancelled: oldest is alive",
                node_id=node_id.hex()[:16],
            )

    def _expire_pending_evictions(self) -> None:
        now = time.monotonic()
        expired = []
        with self._pending_lock:
            for d, (old_id, cand, deadline) in list(
                self._pending_evictions.items()
            ):
                if now >= deadline:
                    expired.append((old_id, cand))
                    del self._pending_evictions[d]
        for old_id, cand in expired:
            self.table.replace(old_id, cand)
            log.debug(
                "evicted unresponsive bucket head",
                evicted=old_id.hex()[:16], admitted=cand.node_id.hex()[:16],
            )

    # -- stateless source-address cookies ----------------------------------

    def _cookie_for(self, src: tuple, window_offset: int = 0) -> bytes:
        """The cookie THIS node hands to (and later expects back from) a
        source address, for the current (or offset) time window. Stateless:
        derived from the local secret, so validation needs no per-peer
        bookkeeping and a restart only invalidates outstanding handshakes."""
        import hashlib
        import hmac

        w = int(time.time() / _COOKIE_WINDOW_S) + window_offset
        msg = f"{src[0]}:{src[1]}:{w}".encode()
        return hmac.new(self._cookie_secret, msg, hashlib.sha256).digest()[
            :_COOKIE_LEN
        ]

    def _cookie_ok(self, cookie: bytes, src: tuple) -> bool:
        import hmac

        if len(cookie) != _COOKIE_LEN:
            return False
        return any(
            hmac.compare_digest(cookie, self._cookie_for(src, -i))
            for i in (0, 1)
        )

    # -- client side -------------------------------------------------------

    def bootstrap(self, boot_enr: ENR) -> bool:
        """Admit a trusted boot record and ping it (teaches it our ENR).
        A rejected boot record is LOUD: a node bootstrapped from nothing has
        no other way into the network, and a silently-dropped boot ENR
        (bad signature, fork mismatch, banned) looks identical to an empty
        network from the outside."""
        admitted = self._admit(boot_enr)
        if not admitted:
            reason = "duplicate-or-pending"
            if boot_enr.fork_digest != self.enr.fork_digest:
                reason = "fork digest mismatch"
            elif not boot_enr.verify():
                reason = "invalid ENR signature"
            elif self.peer_manager is not None and self.peer_manager.is_banned(
                node_id=boot_enr.node_id, addr=boot_enr.tcp_addr
            ):
                reason = "banned"
            log.warning(
                "boot ENR rejected",
                reason=reason,
                node_id=boot_enr.node_id.hex()[:16],
                addr=boot_enr.tcp_addr,
            )
        self._send(boot_enr.udp_addr, _PING, b"")
        return admitted

    def lookup(self, target: bytes | None = None, timeout: float = 2.0) -> list[ENR]:
        """Iterative FINDNODE toward ``target`` (random by default — the
        discv5 random-walk that fills the table). Returns the records known
        afterwards, closest first."""
        target = target or secrets.token_bytes(32)
        queried: set[bytes] = set()
        for _ in range(MAX_LOOKUP_ROUNDS):
            candidates = [
                e for e in self.table.closest(target, ALPHA * 2)
                if e.node_id not in queried
            ][:ALPHA]
            if not candidates:
                break
            before = len(self.table)
            for enr in candidates:
                queried.add(enr.node_id)
                d = log_distance(enr.node_id, target)
                dists = sorted({max(d, 1), min(max(d, 1) + 1, 256),
                                max(d - 1, 1)})
                self._find_node(enr, dists, timeout)
            if len(self.table) == before:
                break
        return self.table.closest(target, K_BUCKET)

    def _find_node(self, enr: ENR, distances: list[int], timeout: float) -> bool:
        """Send FINDNODE and wait for THIS peer's NODES response (per-request
        tracking — the serve loop signals the event when the response
        arrives, whether or not it taught any new record). Returns True when
        the peer answered within the timeout."""
        inner = bytes([len(distances)]) + b"".join(
            struct.pack(">H", d) for d in distances
        )
        cached = self._cookies.get(enr.udp_addr)
        cookie = cached[0] if cached and cached[1] > time.time() else b""
        ev = threading.Event()
        with self._requests_lock:
            self._pending_requests.setdefault(enr.node_id, []).append(ev)
            # remember the request body so a WHOAREYOU challenge can be
            # answered by resending with the fresh cookie (last writer wins
            # for concurrent requests to one peer — both retries carry a
            # valid body, the answers settle every waiter)
            self._findnode_inflight[enr.udp_addr] = inner
            self._pending_addrs[enr.udp_addr] = (
                self._pending_addrs.get(enr.udp_addr, 0) + 1
            )
        try:
            self._send(
                enr.udp_addr, _FINDNODE, bytes([len(cookie)]) + cookie + inner
            )
            return ev.wait(timeout)
        finally:
            with self._requests_lock:
                # compare-and-pop: only clear our OWN body — a concurrent
                # request to the same peer may have overwritten the slot, and
                # its WHOAREYOU retry still needs it
                if self._findnode_inflight.get(enr.udp_addr) is inner:
                    del self._findnode_inflight[enr.udp_addr]
                n_out = self._pending_addrs.get(enr.udp_addr, 0) - 1
                if n_out > 0:
                    self._pending_addrs[enr.udp_addr] = n_out
                else:
                    self._pending_addrs.pop(enr.udp_addr, None)
                evs = self._pending_requests.get(enr.node_id)
                if evs is not None:
                    # remove only THIS call's event — a concurrent request
                    # to the same peer must keep its own waiter registered
                    try:
                        evs.remove(ev)
                    except ValueError:
                        pass
                    if not evs:
                        del self._pending_requests[enr.node_id]

    # -- wire --------------------------------------------------------------

    def _send(self, udp_addr: tuple, kind: int, body: bytes) -> None:
        pkt = self.enr.encode() + bytes([kind]) + body
        try:
            self._sock.sendto(pkt, udp_addr)
        except OSError:
            pass

    def _serve(self) -> None:
        # bounded recv so pending-eviction deadlines fire even on an idle
        # socket (the liveness check must conclude without inbound traffic)
        self._sock.settimeout(_SERVE_TICK_S)
        while not self._stopped:
            try:
                data, src = self._sock.recvfrom(65535)
            except socket.timeout:
                self._expire_pending_evictions()
                continue
            except OSError:
                return
            self._expire_pending_evictions()
            try:
                sender, off = ENR.decode(data)
                kind = data[off]
                body = data[off + 1 :]
            except (ValueError, IndexError):
                continue
            if kind == _FINDNODE:
                # stateless WHOAREYOU gate BEFORE any ENR signature work: a
                # FINDNODE without a valid source-address cookie costs this
                # node one HMAC and a reply no larger than the request —
                # never a BLS verification, never a NODES payload. A spoofed
                # source address never sees the cookie, so it can neither
                # force signature verifies nor aim amplified responses.
                if not body:
                    continue
                ck_len = body[0]
                if len(body) < 1 + ck_len:
                    continue
                cookie, rest = body[1 : 1 + ck_len], body[1 + ck_len :]
                if not self._cookie_ok(cookie, src):
                    self._send(src, _WHOAREYOU, self._cookie_for(src))
                    continue
                self._note_liveness(sender.node_id)
                self._admit(sender)
                self._answer_findnode(src, rest)
                continue
            if kind == _WHOAREYOU:
                self._on_whoareyou(src, body)
                continue
            if kind == _NODES:
                # solicited-only: a NODES packet is dropped BEFORE any ENR
                # signature work unless BOTH its self-reported node_id has a
                # FINDNODE outstanding AND it arrives from an address we
                # sent one to — the node_id alone is attacker-chosen (a
                # public boot node's id is in its published ENR), so an
                # id-only gate still buys up to 1 + _MAX_NODES_PER_RESPONSE
                # BLS verifications per spoofed datagram and falsely
                # settles the waiters
                with self._requests_lock:
                    evs = list(self._pending_requests.get(sender.node_id, ()))
                    addr_ok = src in self._pending_addrs
                if not evs or not addr_ok:
                    continue
            self._note_liveness(sender.node_id)
            self._admit(sender)
            if kind == _PING:
                # residual unauthenticated surface (documented): one ENR
                # verify + a tiny PONG per datagram, no amplification. The
                # eviction-liveness protocol needs unsolicited PING/PONG;
                # per-packet cost stays one bounded verify until the real
                # discv5 session handshake lands with the transport seam.
                self._send(src, _PONG, b"")
            elif kind == _NODES:
                self._ingest_nodes(body)
                # settle every outstanding FINDNODE to this responder only
                # after ingest, so the waiters observe the admitted records
                for ev in evs:
                    ev.set()
            # PONG: the ENR admission above is the whole effect

    def _on_whoareyou(self, src: tuple, body: bytes) -> None:
        """A WHOAREYOU challenge for an in-flight FINDNODE: cache the cookie
        for the challenger's address and retry the request ONCE — the
        in-flight body is consumed here, so N challenges (spoofed or real)
        to one outstanding request yield one resend and one cache write.
        Challenges from addresses we have nothing outstanding to are
        dropped. Residual surface: an attacker who spoofs the peer's
        address WHILE we have a request to it in flight can burn that
        request's single retry and leave a garbage cookie, costing one
        extra WHOAREYOU round trip on the next request — bounded by our
        own request rate, never amplified."""
        if len(body) != _COOKIE_LEN:
            return
        with self._requests_lock:
            inner = self._findnode_inflight.pop(src, None)
        if inner is None:
            return
        now = time.time()
        if len(self._cookies) >= _COOKIE_CACHE_MAX:
            self._cookies = {
                a: ce for a, ce in self._cookies.items() if ce[1] > now
            }
            while len(self._cookies) >= _COOKIE_CACHE_MAX:
                self._cookies.pop(next(iter(self._cookies)))
        self._cookies[src] = (bytes(body), now + _COOKIE_WINDOW_S)
        self._send(
            src, _FINDNODE, bytes([_COOKIE_LEN]) + bytes(body) + inner
        )

    def _answer_findnode(self, src: tuple, body: bytes) -> None:
        try:
            n = body[0]
            dists = [
                struct.unpack_from(">H", body, 1 + 2 * i)[0] for i in range(n)
            ]
        except (IndexError, struct.error):
            return
        out: list[ENR] = []
        for d in dists:
            out.extend(self.table.at_distance(d))
        if len(out) < _MAX_NODES_PER_RESPONSE:
            # sparse-table padding: strict discv5 answers only the exact
            # distances, which leaves bootstrap-size meshes (a boot node and
            # a handful of peers) unable to find each other; pad with the
            # table's other records (dense tables behave like discv5 — the
            # exact-distance records fill the response first)
            seen = {e.node_id for e in out}
            for e in self.table.all_records():
                if len(out) >= _MAX_NODES_PER_RESPONSE:
                    break
                if e.node_id not in seen:
                    out.append(e)
        out = out[:_MAX_NODES_PER_RESPONSE]
        payload = struct.pack(">H", len(out)) + b"".join(
            e.encode() for e in out
        )
        self._send(src, _NODES, payload)

    def _ingest_nodes(self, body: bytes) -> None:
        try:
            (count,) = struct.unpack_from(">H", body, 0)
            off = 2
            for _ in range(min(count, _MAX_NODES_PER_RESPONSE)):
                enr, off = ENR.decode(body, off)
                self._admit(enr)
        except ValueError:
            return

    # -- transport integration --------------------------------------------

    def known_tcp_addrs(self) -> list[str]:
        """TCP addresses of every verified record (the dial candidates)."""
        return [
            e.tcp_addr for e in self.table.all_records() if e.tcp > 0
        ]
