"""Peer manager: connection registry, score ledger, and ban lifecycle.

The twin of the reference's ``peer_manager/mod.rs:1-2471`` + peerdb: a
durable per-peer record that outlives the TCP connection, so a peer that
earns a ban stays out across reconnect attempts (the transport's in-object
scores died with the socket, which let an abuser reconnect with a clean
slate). Scores use the same shape as the transport's gossip scoring; bans
expire after BAN_DURATION (the reference's temporary ban semantics) and the
record's score is reset on unban, mirroring peerdb's score decay floor.

States: disconnected -> connected -> {disconnected | banned(expiry)}.
"""

from __future__ import annotations

import threading
import time

from ..utils.logging import get_logger

log = get_logger("peer_manager")

BAN_THRESHOLD = -100.0
BAN_DURATION = 900.0   # seconds (reference: temp ban, then forgiven)
SCORE_FLOOR = -1000.0
SCORE_CEIL = 100.0
SCORE_DECAY = 0.9


class _PeerRecord:
    __slots__ = ("addr", "node_id", "score", "state", "ban_until",
                 "connections", "disconnections")

    def __init__(self, addr: str):
        self.addr = addr
        self.node_id: bytes | None = None
        self.score = 0.0
        self.state = "disconnected"
        self.ban_until = 0.0
        self.connections = 0
        self.disconnections = 0


class PeerManager:
    """Address-keyed peer DB (node-id aliases recorded when known)."""

    def __init__(self, clock=time.monotonic):
        self._peers: dict[str, _PeerRecord] = {}
        self._banned_ids: dict[bytes, float] = {}
        self._lock = threading.Lock()
        self._clock = clock

    def _rec(self, addr: str) -> _PeerRecord:
        rec = self._peers.get(addr)
        if rec is None:
            rec = self._peers[addr] = _PeerRecord(addr)
        return rec

    # -- connection lifecycle ---------------------------------------------

    def on_connect(self, addr: str, node_id: bytes | None = None) -> bool:
        """Record a connection; False if the peer is banned (caller must
        refuse/close — reconnect suppression)."""
        with self._lock:
            if self._is_banned_locked(addr, node_id):
                return False
            rec = self._rec(addr)
            rec.state = "connected"
            rec.connections += 1
            if node_id is not None:
                rec.node_id = node_id
            return True

    def on_disconnect(self, addr: str) -> None:
        with self._lock:
            rec = self._peers.get(addr)
            if rec is not None and rec.state == "connected":
                rec.state = "disconnected"
                rec.disconnections += 1

    # -- scoring / bans ----------------------------------------------------

    def report(self, addr: str, delta: float) -> float:
        """Adjust a peer's durable score; crossing BAN_THRESHOLD bans it.
        Returns the new score."""
        with self._lock:
            rec = self._rec(addr)
            rec.score = max(SCORE_FLOOR, min(SCORE_CEIL, rec.score + delta))
            if rec.score <= BAN_THRESHOLD and rec.state != "banned":
                self._ban_locked(rec)
            return rec.score

    def ban(self, addr: str, duration: float = BAN_DURATION) -> None:
        with self._lock:
            rec = self._rec(addr)
            self._ban_locked(rec, duration)

    def _ban_locked(self, rec: _PeerRecord, duration: float = BAN_DURATION):
        rec.state = "banned"
        rec.ban_until = self._clock() + duration
        if rec.node_id is not None:
            self._banned_ids[rec.node_id] = rec.ban_until
        log.warn("Peer banned", addr=rec.addr,
                 until_s=round(duration, 1), score=round(rec.score, 1))

    def is_banned(self, addr: str | None = None,
                  node_id: bytes | None = None) -> bool:
        with self._lock:
            return self._is_banned_locked(addr, node_id)

    def _is_banned_locked(self, addr, node_id) -> bool:
        now = self._clock()
        if addr is not None:
            rec = self._peers.get(addr)
            if rec is not None and rec.state == "banned":
                if rec.ban_until > now:
                    return True
                # ban expired: forgive (score reset to the threshold's
                # recovery point so one more offence re-bans quickly)
                rec.state = "disconnected"
                rec.score = BAN_THRESHOLD / 2
        if node_id is not None:
            until = self._banned_ids.get(node_id)
            if until is not None:
                if until > now:
                    return True
                del self._banned_ids[node_id]
        return False

    def decay_scores(self) -> None:
        with self._lock:
            for rec in self._peers.values():
                rec.score *= SCORE_DECAY

    # -- introspection -----------------------------------------------------

    def score(self, addr: str) -> float:
        with self._lock:
            rec = self._peers.get(addr)
            return rec.score if rec else 0.0

    def state(self, addr: str) -> str:
        with self._lock:
            rec = self._peers.get(addr)
            return rec.state if rec else "unknown"

    def connected(self) -> list[str]:
        with self._lock:
            return [a for a, r in self._peers.items()
                    if r.state == "connected"]

    def summary(self) -> dict:
        with self._lock:
            states: dict[str, int] = {}
            for r in self._peers.values():
                states[r.state] = states.get(r.state, 0) + 1
            return states
