"""BeaconNodeService: one in-process node (chain + processor + router + sync).

The glue the reference spreads across ``NetworkService::spawn``
(``network/src/service.rs``) and ``NetworkBeaconProcessor``
(``network_beacon_processor/mod.rs``): gossip handlers feed the chain through
the prioritized processor queues (batch closures included so attestation
batches hit the batched BLS path), RPC serves Status/BlocksByRange from the
chain, and unknown-parent blocks kick the sync manager.
"""

from __future__ import annotations

from ..beacon_chain.chain import BeaconChain, BlockError
from ..beacon_processor.processor import BeaconProcessor, BeaconProcessorConfig
from ..loadshed import LoadMonitor
from ..op_pool import OperationPool
from ..types.helpers import compute_fork_digest
from .router import Router
from .sync import SyncManager
from .transport import Status, Topic, Transport


class BeaconNodeService:
    def __init__(
        self,
        node_id: str,
        spec,
        genesis_state=None,
        transport: Transport = None,
        slot_clock=None,
        execution_layer=None,
        chain: BeaconChain | None = None,
        op_pool: OperationPool | None = None,
    ):
        if transport is None:
            raise ValueError("BeaconNodeService requires a transport")
        if chain is None and genesis_state is None:
            raise ValueError("pass either a prebuilt chain or a genesis state")
        self.node_id = node_id
        self.transport = transport
        # a prebuilt chain (the ClientBuilder path) or a fresh one (tests)
        self.chain = chain or BeaconChain(
            spec, genesis_state, slot_clock=slot_clock,
            execution_layer=execution_layer,
        )
        self.processor = BeaconProcessor(
            BeaconProcessorConfig(), synchronous=True
        )
        self.op_pool = op_pool or OperationPool(spec, self.chain.ns.Attestation)
        # overload-protection tier: one monitor folds processor queue
        # depths, drop rates, and resilience-ladder state into an
        # admission level shared by the HTTP API and Req/Resp surfaces
        from ..resilience import snapshot_all

        self.load_monitor = LoadMonitor()
        self.load_monitor.attach_processor(self.processor)
        self.load_monitor.attach_supervisors(snapshot_all)
        if getattr(transport, "load_monitor", "absent") is None:
            # socket transports expose the slot; the shared loopback
            # transport (many nodes, one object) must not be clobbered
            transport.load_monitor = self.load_monitor
        self.router = Router(self)
        # loopback runs sync inline (the deterministic simulator contract);
        # socket stacks get the dedicated sync worker thread
        from .transport import LoopbackTransport

        self.sync = SyncManager(
            self, threaded=not isinstance(transport, LoopbackTransport)
        )
        transport.register(node_id, self)

    def stop(self) -> None:
        """Shut down the sync worker before the transport so no sync round
        runs against closed sockets."""
        self.sync.stop()
        stop = getattr(self.transport, "stop", None)
        if stop is not None:
            stop()

    # -- transport-facing --------------------------------------------------

    def on_gossip(self, topic: str, message, from_peer: str) -> None:
        self.router.on_gossip(topic, message, from_peer)

    def on_rpc(self, method: str, payload, from_peer: str):
        return self.router.on_rpc(method, payload, from_peer)

    def local_status(self) -> Status:
        head = self.chain.head
        st = head.state
        return Status(
            fork_digest=compute_fork_digest(
                bytes(st.fork.current_version),
                bytes(st.genesis_validators_root),
            ),
            finalized_root=bytes(st.finalized_checkpoint.root),
            finalized_epoch=int(st.finalized_checkpoint.epoch),
            head_root=head.root,
            head_slot=head.slot,
        )

    def connect(self, peer: str) -> None:
        """Status handshake with a peer (network service dial path)."""
        theirs = self.transport.request(
            self.node_id, peer, "status", self.local_status()
        )
        self.sync.on_peer_status(peer, theirs)

    # -- gossip publication ------------------------------------------------

    def publish_block(self, signed_block) -> None:
        self.transport.publish(self.node_id, Topic.BEACON_BLOCK, signed_block)

    def publish_attestation(self, attestation) -> None:
        self.transport.publish(
            self.node_id, Topic.BEACON_ATTESTATION, attestation
        )

    def publish_aggregate(self, signed_aggregate) -> None:
        self.transport.publish(
            self.node_id, Topic.AGGREGATE_AND_PROOF, signed_aggregate
        )

    def publish_sync_message(self, message) -> None:
        self.transport.publish(
            self.node_id, Topic.SYNC_COMMITTEE_MESSAGE, message
        )

    def publish_contribution(self, signed_contribution) -> None:
        self.transport.publish(
            self.node_id, Topic.SYNC_CONTRIBUTION, signed_contribution
        )

    def publish_data_column(self, sidecar) -> None:
        self.transport.publish(
            self.node_id, Topic.DATA_COLUMN_SIDECAR, sidecar
        )

    # -- work handlers (network_beacon_processor/gossip_methods.rs) --------

    def process_gossip_block(self, item) -> None:
        from ..beacon_chain.chain import BlockPendingAvailability

        block, from_peer = item
        try:
            self.chain.process_block(block)
        except BlockPendingAvailability as e:
            # PeerDAS: the block is parked until its columns verify; pull
            # whatever custody/sample columns the proposer's side already
            # serves, then re-check availability
            self._fetch_missing_columns(e.block_root, from_peer)
        except BlockError as e:
            if "unknown parent" in str(e):
                # single-block parent lookup (sync/block_lookups/), falling
                # back to a status handshake -> range sync for deep gaps
                self.sync.on_unknown_parent(block, from_peer)
                try:
                    theirs = self.transport.request(
                        self.node_id, from_peer, "status", self.local_status()
                    )
                    self.sync.on_peer_status(from_peer, theirs)
                except ConnectionError:
                    pass
            # other invalid blocks are dropped (peer scoring would fire here)

    def process_gossip_attestation(self, att) -> None:
        self.process_gossip_attestation_batch([att])

    def process_gossip_attestation_batch(self, atts) -> None:
        results = self.chain.verify_unaggregated_attestations(atts)
        for att, verdict in results:
            if not isinstance(verdict, Exception):
                self.op_pool.insert_attestation(att)

    def process_gossip_aggregate(self, agg) -> None:
        self.process_gossip_aggregate_batch([agg])

    def process_gossip_aggregate_batch(self, aggs) -> None:
        results = self.chain.verify_aggregated_attestations(aggs)
        for sap, verdict in results:
            if not isinstance(verdict, Exception):
                self.op_pool.insert_attestation(sap.message.aggregate)

    def process_gossip_sync_message(self, msg) -> None:
        self.process_gossip_sync_message_batch([msg])

    def process_gossip_sync_message_batch(self, msgs) -> None:
        self.chain.verify_sync_committee_messages(msgs)

    def process_gossip_sync_contribution(self, sc) -> None:
        self.chain.verify_sync_contributions([sc])

    def process_gossip_data_column(self, sidecar) -> None:
        """PeerDAS column ingest: verify, retain under the chain lock
        (``chain.put_data_column`` — created in chain init, pruned with the
        availability horizon), record sampling progress, and import any
        block the new column completes
        (data_column_verification.rs gossip path)."""
        chain = self.chain
        ctx = chain.cell_context
        if ctx is None:
            return  # column sampling not enabled on this node
        from ..beacon_chain.data_columns import (
            DataColumnError,
            verify_data_column_sidecar,
        )

        try:
            verify_data_column_sidecar(chain.ns, sidecar, ctx)
        except DataColumnError:
            return  # invalid columns drop (peer scoring fires upstream)
        root = chain.put_data_column(sidecar)
        if chain.peerdas is None:
            return
        chain.peerdas.on_verified_column(root, int(sidecar.index))
        self._try_column_availability(root)

    def _try_column_availability(self, block_root: bytes) -> None:
        """Re-evaluate a block against the sampling gate; reconstruct from
        a >= 50% held column set when that's what closes the gap. Every
        column marked verified here went through
        ``verify_data_column_sidecar`` — reconstruction output included —
        so a corrupt recovery can never flip a block to available."""
        chain = self.chain
        sampler = chain.peerdas
        missing = sampler.missing_columns(block_root)
        if missing and sampler.can_reconstruct(block_root):
            from ..beacon_chain.data_columns import (
                DataColumnError,
                verify_data_column_sidecar,
            )
            from ..kzg.kzg import KzgError

            try:
                rebuilt = sampler.reconstruct(block_root)
            except KzgError:
                rebuilt = None  # inconsistent held data: stay unavailable
            if rebuilt is not None:
                for col in missing:
                    sc = rebuilt[col]
                    try:
                        verify_data_column_sidecar(
                            chain.ns, sc, chain.cell_context
                        )
                    except DataColumnError:
                        return  # recovery produced garbage: fail closed
                    chain.put_data_column(sc)
                    sampler.on_verified_column(block_root, col)
                    # re-seed the network with the recovered column (spec:
                    # reconstructing nodes republish)
                    self.publish_data_column(sc)
        res = chain.da_checker.notify_columns(block_root)
        if res is None:
            return
        blk, _ = res
        with chain.lock:
            try:
                chain._process_block_locked(
                    blk, blk.message, block_root, True,
                    check_availability=False,
                )
            except BlockError:
                pass  # e.g. unknown parent: range sync re-imports it later

    def _fetch_missing_columns(self, block_root: bytes, peer: str) -> None:
        """Pull this node's missing custody/sample columns from a peer over
        the DataColumnSidecarsByRoot Req/Resp, then retry availability."""
        chain = self.chain
        if chain.peerdas is None:
            return
        missing = chain.peerdas.missing_columns(block_root)
        if not missing:
            self._try_column_availability(block_root)
            return
        try:
            sidecars = self.transport.request(
                self.node_id, peer, "data_column_sidecars_by_root",
                [(bytes(block_root), c) for c in missing],
            )
        except (ConnectionError, ValueError):
            return  # peer gone / refused: gossip or sync will retry
        for sc in sidecars:
            self.process_gossip_data_column(sc)

    def process_gossip_exit(self, exit_msg) -> None:
        self.op_pool.insert_voluntary_exit(exit_msg)

    def process_gossip_proposer_slashing(self, slashing) -> None:
        self.op_pool.insert_proposer_slashing(slashing)

    def process_gossip_attester_slashing(self, slashing) -> None:
        self.op_pool.insert_attester_slashing(slashing)

    def process_chain_segment(self, blocks) -> None:
        try:
            self.chain.process_chain_segment(list(blocks))
        except BlockError:
            pass  # scored + retried against another peer in the full stack

    def process_chain_segment_strict(self, blocks) -> None:
        """Segment import that RAISES on failure so the sync manager can
        demote the serving peer and retry elsewhere (range_sync batch
        failure handling)."""
        self.chain.process_chain_segment(list(blocks))

    # -- rpc handlers ------------------------------------------------------

    def blocks_by_range(self, start_slot: int, count: int) -> list:
        """Canonical-chain blocks in [start_slot, start_slot+count)
        (rpc_methods.rs BlocksByRange). Reads through to the persistent
        store (``chain.get_signed_block``) so serving keeps working below
        the finalized horizon, where the in-memory map is pruned."""
        out = []
        root = self.chain.head.root
        while root is not None:
            sb = self.chain.get_signed_block(root)
            if sb is None:
                break
            s = int(sb.message.slot)
            if s < start_slot:
                break  # walking backwards: everything older is out of range
            if s < start_slot + count:
                out.append(sb)
            root = bytes(sb.message.parent_root)
        out.reverse()
        return out

    def blocks_by_root(self, roots) -> list:
        blocks = (self.chain.get_signed_block(r) for r in roots)
        return [sb for sb in blocks if sb is not None]

    def data_column_sidecars_by_root(self, identifiers) -> list:
        """DataColumnSidecarsByRoot: serve held columns for
        (block_root, column_index) pairs (rpc_methods.rs
        DataColumnsByRootRequest). Unknown identifiers are skipped —
        responses carry only what this node custodies."""
        out = []
        for root, idx in identifiers:
            sc = self.chain.data_columns_for(bytes(root)).get(int(idx))
            if sc is not None:
                out.append(sc)
        return out

    def data_column_sidecars_by_range(
        self, start_slot: int, count: int, columns=None
    ) -> list:
        """DataColumnSidecarsByRange: held columns for slots in
        [start_slot, start_slot + count), optionally filtered to a column
        subset; (slot, index)-ordered like the reference's response
        stream."""
        with self.chain.lock:
            snapshot = [
                sc
                for cols in self.chain.data_column_cache.values()
                for sc in cols.values()
            ]
        wanted = None if columns is None else {int(c) for c in columns}
        out = [
            sc
            for sc in snapshot
            if start_slot
            <= int(sc.signed_block_header.message.slot)
            < start_slot + count
            and (wanted is None or int(sc.index) in wanted)
        ]
        out.sort(
            key=lambda sc: (
                int(sc.signed_block_header.message.slot), int(sc.index)
            )
        )
        return out

    # -- light-client serving (rpc_methods.rs LightClient* protocols) -------

    def light_client_bootstrap(self, block_root: bytes):
        """LightClientBootstrap by trusted block root; None when the root's
        state is not held (the codec encodes an empty response)."""
        return self.chain.light_client_cache.bootstrap(bytes(block_root))

    def light_client_updates_by_range(
        self, start_period: int, count: int
    ) -> list:
        """Best full update per sync-committee period in
        [start_period, start_period + count)."""
        return self.chain.light_client_cache.updates_by_range(
            int(start_period), int(count)
        )

    def light_client_optimistic_update(self):
        return self.chain.light_client_cache.latest_optimistic

    def light_client_finality_update(self):
        return self.chain.light_client_cache.latest_finality
