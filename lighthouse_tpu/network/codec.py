"""Wire codec: typed SSZ message encoding for the socket transport.

Twin of the reference's SSZ+snappy Req/Resp codec and gossip encoding
(``lighthouse_network/src/rpc/codec.rs``, ``types/pubsub.rs``): every gossip
topic and RPC method has a typed SSZ payload, compressed on the wire. The
stdlib provides zlib, not snappy — framing and semantics are the same, the
compressor differs (noted deviation).

Gossip payloads are fork-tagged with a leading fork byte so block containers
decode under the right fork variant without needing the slot first.
"""

from __future__ import annotations

import struct
import zlib

from ..types.containers import ProposerSlashing, SignedVoluntaryExit, for_preset
from .transport import Status, Topic

_FORK_ORDER = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]


class WireError(Exception):
    pass


class MessageCodec:
    """Encodes/decodes gossip + RPC payloads for one node's preset."""

    def __init__(self, spec):
        self.spec = spec
        self.ns = for_preset(spec.preset.name)

    # -- fork-tagged signed blocks ----------------------------------------

    def _enc_block(self, signed_block) -> bytes:
        for name in reversed(_FORK_ORDER):
            cls = self.ns.block_types.get(name)
            if cls is not None and isinstance(signed_block, cls):
                return bytes([_FORK_ORDER.index(name)]) + cls.encode(
                    signed_block
                )
        raise WireError(f"unknown block container {type(signed_block)}")

    def _dec_block(self, data: bytes):
        fork = _FORK_ORDER[data[0]]
        cls = self.ns.block_types.get(fork)
        if cls is None:
            raise WireError(f"fork {fork} not in preset")
        return cls.decode(data[1:])

    # -- fork-tagged light-client containers --------------------------------

    def _enc_lc(self, obj, kind: str) -> bytes:
        from ..light_client.types import light_client_types

        for name in reversed(_FORK_ORDER):
            if name not in self.ns.state_types:
                continue
            cls = getattr(
                light_client_types(self.spec.preset.name, name), kind
            )
            if isinstance(obj, cls):
                return bytes([_FORK_ORDER.index(name)]) + cls.encode(obj)
        raise WireError(f"unknown {kind} container {type(obj)}")

    def _dec_lc(self, data: bytes, kind: str):
        from ..light_client.types import light_client_types

        fork = _FORK_ORDER[data[0]]
        if fork not in self.ns.state_types:
            raise WireError(f"fork {fork} not in preset")
        cls = getattr(light_client_types(self.spec.preset.name, fork), kind)
        return cls.decode(data[1:])

    # -- gossip ------------------------------------------------------------

    def encode_gossip(self, topic: str, message) -> bytes:
        ns = self.ns
        if topic == Topic.BEACON_BLOCK:
            raw = self._enc_block(message)
        elif topic == Topic.BEACON_ATTESTATION:
            raw = ns.Attestation.encode(message)
        elif topic == Topic.AGGREGATE_AND_PROOF:
            raw = ns.SignedAggregateAndProof.encode(message)
        elif topic == Topic.VOLUNTARY_EXIT:
            raw = SignedVoluntaryExit.encode(message)
        elif topic == Topic.PROPOSER_SLASHING:
            raw = ProposerSlashing.encode(message)
        elif topic == Topic.ATTESTER_SLASHING:
            raw = ns.AttesterSlashing.encode(message)
        elif topic == Topic.SYNC_COMMITTEE_MESSAGE:
            raw = ns.SyncCommitteeMessage.encode(message)
        elif topic == Topic.SYNC_CONTRIBUTION:
            raw = ns.SignedContributionAndProof.encode(message)
        elif topic == Topic.DATA_COLUMN_SIDECAR:
            raw = ns.DataColumnSidecar.encode(message)
        else:
            raise WireError(f"no codec for topic {topic}")
        return zlib.compress(raw)

    def decode_gossip(self, topic: str, data: bytes):
        try:
            raw = zlib.decompress(data)
        except zlib.error as e:
            raise WireError(f"bad compression: {e}") from None
        ns = self.ns
        if topic == Topic.BEACON_BLOCK:
            return self._dec_block(raw)
        if topic == Topic.BEACON_ATTESTATION:
            return ns.Attestation.decode(raw)
        if topic == Topic.AGGREGATE_AND_PROOF:
            return ns.SignedAggregateAndProof.decode(raw)
        if topic == Topic.VOLUNTARY_EXIT:
            return SignedVoluntaryExit.decode(raw)
        if topic == Topic.PROPOSER_SLASHING:
            return ProposerSlashing.decode(raw)
        if topic == Topic.ATTESTER_SLASHING:
            return ns.AttesterSlashing.decode(raw)
        if topic == Topic.SYNC_COMMITTEE_MESSAGE:
            return ns.SyncCommitteeMessage.decode(raw)
        if topic == Topic.SYNC_CONTRIBUTION:
            return ns.SignedContributionAndProof.decode(raw)
        if topic == Topic.DATA_COLUMN_SIDECAR:
            return ns.DataColumnSidecar.decode(raw)
        raise WireError(f"no codec for topic {topic}")

    # -- rpc ---------------------------------------------------------------

    def encode_request(self, method: str, payload) -> bytes:
        if method == "status":
            s: Status = payload
            raw = (
                bytes(s.fork_digest)
                + bytes(s.finalized_root)
                + struct.pack(">Q", s.finalized_epoch)
                + bytes(s.head_root)
                + struct.pack(">Q", s.head_slot)
            )
        elif method == "blocks_by_range":
            start, count = payload
            raw = struct.pack(">QQ", start, count)
        elif method == "blocks_by_root":
            raw = b"".join(bytes(r) for r in payload)
        elif method == "data_column_sidecars_by_root":
            # DataColumnIdentifier stream: 32-byte root + u64 column index
            raw = b"".join(
                bytes(root) + struct.pack(">Q", int(idx))
                for root, idx in payload
            )
        elif method == "data_column_sidecars_by_range":
            start, count, columns = payload
            cols = list(columns) if columns is not None else []
            # column-count 0xFFFF is the "no filter" sentinel (None)
            n = 0xFFFF if columns is None else len(cols)
            raw = struct.pack(">QQH", start, count, n) + b"".join(
                struct.pack(">H", int(c)) for c in cols
            )
        elif method == "light_client_bootstrap":
            raw = bytes(payload)  # the trusted block root
        elif method == "light_client_updates_by_range":
            start_period, count = payload
            raw = struct.pack(">QQ", start_period, count)
        elif method in (
            "light_client_optimistic_update", "light_client_finality_update"
        ):
            raw = b""  # latest-update requests carry no body
        else:
            raise WireError(f"no codec for rpc {method}")
        return zlib.compress(raw)

    def decode_request(self, method: str, data: bytes):
        try:
            raw = zlib.decompress(data)
        except zlib.error as e:
            raise WireError(f"bad compression: {e}") from None
        if method == "status":
            return Status(
                fork_digest=raw[0:4],
                finalized_root=raw[4:36],
                finalized_epoch=struct.unpack(">Q", raw[36:44])[0],
                head_root=raw[44:76],
                head_slot=struct.unpack(">Q", raw[76:84])[0],
            )
        if method == "blocks_by_range":
            return struct.unpack(">QQ", raw)
        if method == "blocks_by_root":
            return [raw[i : i + 32] for i in range(0, len(raw), 32)]
        if method == "data_column_sidecars_by_root":
            return [
                (raw[i : i + 32], struct.unpack(">Q", raw[i + 32 : i + 40])[0])
                for i in range(0, len(raw), 40)
            ]
        if method == "data_column_sidecars_by_range":
            start, count, n = struct.unpack(">QQH", raw[:18])
            if n == 0xFFFF:
                return start, count, None
            cols = [
                struct.unpack(">H", raw[18 + 2 * i : 20 + 2 * i])[0]
                for i in range(n)
            ]
            return start, count, cols
        if method == "light_client_bootstrap":
            return raw[:32]
        if method == "light_client_updates_by_range":
            return struct.unpack(">QQ", raw)
        if method in (
            "light_client_optimistic_update", "light_client_finality_update"
        ):
            return None
        raise WireError(f"no codec for rpc {method}")

    def encode_response(self, method: str, payload) -> bytes:
        if method == "status":
            return self.encode_request("status", payload)
        if method in ("blocks_by_range", "blocks_by_root"):
            parts = [self._enc_block(b) for b in payload]
            raw = b"".join(struct.pack(">I", len(p)) + p for p in parts)
            return zlib.compress(raw)
        if method in (
            "data_column_sidecars_by_root", "data_column_sidecars_by_range"
        ):
            parts = [self.ns.DataColumnSidecar.encode(sc) for sc in payload]
            raw = b"".join(struct.pack(">I", len(p)) + p for p in parts)
            return zlib.compress(raw)
        if method == "light_client_bootstrap":
            raw = b"" if payload is None else self._enc_lc(
                payload, "LightClientBootstrap"
            )
            return zlib.compress(raw)
        if method == "light_client_updates_by_range":
            parts = [self._enc_lc(u, "LightClientUpdate") for u in payload]
            raw = b"".join(struct.pack(">I", len(p)) + p for p in parts)
            return zlib.compress(raw)
        if method == "light_client_optimistic_update":
            raw = b"" if payload is None else self._enc_lc(
                payload, "LightClientOptimisticUpdate"
            )
            return zlib.compress(raw)
        if method == "light_client_finality_update":
            raw = b"" if payload is None else self._enc_lc(
                payload, "LightClientFinalityUpdate"
            )
            return zlib.compress(raw)
        raise WireError(f"no codec for rpc response {method}")

    def decode_response(self, method: str, data: bytes):
        if method == "status":
            return self.decode_request("status", data)
        if method in ("blocks_by_range", "blocks_by_root"):
            raw = zlib.decompress(data)
            out, off = [], 0
            while off < len(raw):
                (n,) = struct.unpack(">I", raw[off : off + 4])
                out.append(self._dec_block(raw[off + 4 : off + 4 + n]))
                off += 4 + n
            return out
        if method in (
            "data_column_sidecars_by_root", "data_column_sidecars_by_range"
        ):
            raw = zlib.decompress(data)
            out, off = [], 0
            while off < len(raw):
                (n,) = struct.unpack(">I", raw[off : off + 4])
                out.append(
                    self.ns.DataColumnSidecar.decode(raw[off + 4 : off + 4 + n])
                )
                off += 4 + n
            return out
        if method == "light_client_updates_by_range":
            raw = zlib.decompress(data)
            out, off = [], 0
            while off < len(raw):
                (n,) = struct.unpack(">I", raw[off : off + 4])
                out.append(
                    self._dec_lc(raw[off + 4 : off + 4 + n], "LightClientUpdate")
                )
                off += 4 + n
            return out
        if method in (
            "light_client_bootstrap",
            "light_client_optimistic_update",
            "light_client_finality_update",
        ):
            raw = zlib.decompress(data)
            if not raw:
                return None
            kind = {
                "light_client_bootstrap": "LightClientBootstrap",
                "light_client_optimistic_update": "LightClientOptimisticUpdate",
                "light_client_finality_update": "LightClientFinalityUpdate",
            }[method]
            return self._dec_lc(raw, kind)
        raise WireError(f"no codec for rpc response {method}")
