"""Consensus type system: SSZ containers, presets, runtime ChainSpec.

TPU twin of ``consensus/types`` (``/root/reference/consensus/types``): the
``EthSpec`` compile-time preset trait becomes ``spec.Preset`` + per-preset
container generation (``containers.for_preset``); ``ChainSpec`` is a plain
runtime dataclass.
"""

from .spec import (
    ChainSpec,
    FAR_FUTURE_EPOCH,
    FORK_ORDER,
    MAINNET,
    MINIMAL,
    PRESETS,
    Preset,
    mainnet_spec,
    minimal_spec,
)
from .containers import (
    AttestationData,
    BeaconBlockHeader,
    Checkpoint,
    Deposit,
    DepositData,
    DepositMessage,
    Eth1Data,
    Fork,
    ForkData,
    ProposerSlashing,
    SignedBeaconBlockHeader,
    SignedVoluntaryExit,
    SigningData,
    Validator,
    VoluntaryExit,
    for_preset,
)
from .helpers import (
    compute_domain,
    compute_fork_data_root,
    compute_fork_digest,
    compute_signing_root,
    get_domain,
    is_active_validator,
    is_slashable_attestation_data,
    is_slashable_validator,
)
