"""Chain configuration: compile-time presets + runtime ChainSpec.

The reference splits configuration between the ``EthSpec`` trait of typenum
constants selected at compile time (``consensus/types/src/eth_spec.rs:53-165``,
``MainnetEthSpec``/``MinimalEthSpec`` at ``:389,453``) and the runtime
``ChainSpec`` (``consensus/types/src/chain_spec.rs``: fork schedule, domains,
preset values that vary per network). Python has no monomorphization, so a
``Preset`` is a frozen dataclass of the same constants and per-preset container
classes are generated once and cached (``types.containers.for_preset``).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

FAR_FUTURE_EPOCH = 2**64 - 1

# Fork names in activation order (superstruct variant order in the reference).
FORK_ORDER = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]
_FORK_RANK = {f: i for i, f in enumerate(FORK_ORDER)}


def fork_at_least(fork_name: str, target: str) -> bool:
    """True when fork_name is target or any later fork (single source of
    fork-ordering truth for feature gating)."""
    return _FORK_RANK[fork_name] >= _FORK_RANK[target]


def proportional_slashing_multiplier_for(spec, fork_name: str) -> int:
    """The fork's proportional slashing multiplier (process_slashings) —
    shared by the numpy epoch path and the device epoch kernels so a future
    fork's change cannot silently diverge the two."""
    return {
        "phase0": spec.proportional_slashing_multiplier,
        "altair": spec.proportional_slashing_multiplier_altair,
    }.get(fork_name, spec.proportional_slashing_multiplier_bellatrix)


@dataclass(frozen=True)
class Preset:
    """Compile-time constants (eth_spec.rs trait consts)."""

    name: str
    # time
    SLOTS_PER_EPOCH: int
    SECONDS_PER_SLOT: int
    # state sizes
    SLOTS_PER_HISTORICAL_ROOT: int
    EPOCHS_PER_HISTORICAL_VECTOR: int
    EPOCHS_PER_SLASHINGS_VECTOR: int
    HISTORICAL_ROOTS_LIMIT: int
    VALIDATOR_REGISTRY_LIMIT: int
    EPOCHS_PER_ETH1_VOTING_PERIOD: int
    # committees
    MAX_COMMITTEES_PER_SLOT: int
    TARGET_COMMITTEE_SIZE: int
    MAX_VALIDATORS_PER_COMMITTEE: int
    SHUFFLE_ROUND_COUNT: int
    # block body limits
    MAX_PROPOSER_SLASHINGS: int
    MAX_ATTESTER_SLASHINGS: int
    MAX_ATTESTATIONS: int
    MAX_DEPOSITS: int
    MAX_VOLUNTARY_EXITS: int
    # altair
    SYNC_COMMITTEE_SIZE: int
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD: int
    MIN_SYNC_COMMITTEE_PARTICIPANTS: int
    # bellatrix
    MAX_BYTES_PER_TRANSACTION: int
    MAX_TRANSACTIONS_PER_PAYLOAD: int
    BYTES_PER_LOGS_BLOOM: int
    MAX_EXTRA_DATA_BYTES: int
    # capella
    MAX_WITHDRAWALS_PER_PAYLOAD: int
    MAX_BLS_TO_EXECUTION_CHANGES: int
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP: int
    # deneb
    MAX_BLOB_COMMITMENTS_PER_BLOCK: int
    FIELD_ELEMENTS_PER_BLOB: int
    MAX_BLOBS_PER_BLOCK: int
    # electra
    MAX_ATTESTER_SLASHINGS_ELECTRA: int
    MAX_ATTESTATIONS_ELECTRA: int
    MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP: int
    MAX_PENDING_DEPOSITS_PER_EPOCH: int
    MAX_DEPOSIT_REQUESTS_PER_PAYLOAD: int
    MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD: int
    MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD: int
    PENDING_DEPOSITS_LIMIT: int
    PENDING_PARTIAL_WITHDRAWALS_LIMIT: int
    PENDING_CONSOLIDATIONS_LIMIT: int

    @property
    def slots_per_eth1_voting_period(self) -> int:
        return self.EPOCHS_PER_ETH1_VOTING_PERIOD * self.SLOTS_PER_EPOCH


MAINNET = Preset(
    name="mainnet",
    SLOTS_PER_EPOCH=32,
    SECONDS_PER_SLOT=12,
    SLOTS_PER_HISTORICAL_ROOT=8192,
    EPOCHS_PER_HISTORICAL_VECTOR=65536,
    EPOCHS_PER_SLASHINGS_VECTOR=8192,
    HISTORICAL_ROOTS_LIMIT=2**24,
    VALIDATOR_REGISTRY_LIMIT=2**40,
    EPOCHS_PER_ETH1_VOTING_PERIOD=64,
    MAX_COMMITTEES_PER_SLOT=64,
    TARGET_COMMITTEE_SIZE=128,
    MAX_VALIDATORS_PER_COMMITTEE=2048,
    SHUFFLE_ROUND_COUNT=90,
    MAX_PROPOSER_SLASHINGS=16,
    MAX_ATTESTER_SLASHINGS=2,
    MAX_ATTESTATIONS=128,
    MAX_DEPOSITS=16,
    MAX_VOLUNTARY_EXITS=16,
    SYNC_COMMITTEE_SIZE=512,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=256,
    MIN_SYNC_COMMITTEE_PARTICIPANTS=1,
    MAX_BYTES_PER_TRANSACTION=2**30,
    MAX_TRANSACTIONS_PER_PAYLOAD=2**20,
    BYTES_PER_LOGS_BLOOM=256,
    MAX_EXTRA_DATA_BYTES=32,
    MAX_WITHDRAWALS_PER_PAYLOAD=16,
    MAX_BLS_TO_EXECUTION_CHANGES=16,
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP=16384,
    MAX_BLOB_COMMITMENTS_PER_BLOCK=4096,
    FIELD_ELEMENTS_PER_BLOB=4096,
    MAX_BLOBS_PER_BLOCK=6,
    MAX_ATTESTER_SLASHINGS_ELECTRA=1,
    MAX_ATTESTATIONS_ELECTRA=8,
    MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP=8,
    MAX_PENDING_DEPOSITS_PER_EPOCH=16,
    MAX_DEPOSIT_REQUESTS_PER_PAYLOAD=8192,
    MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD=16,
    MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD=2,
    PENDING_DEPOSITS_LIMIT=2**27,
    PENDING_PARTIAL_WITHDRAWALS_LIMIT=2**27,
    PENDING_CONSOLIDATIONS_LIMIT=2**18,
)

MINIMAL = replace(
    MAINNET,
    name="minimal",
    SLOTS_PER_EPOCH=8,
    SECONDS_PER_SLOT=6,
    SLOTS_PER_HISTORICAL_ROOT=64,
    EPOCHS_PER_HISTORICAL_VECTOR=64,
    EPOCHS_PER_SLASHINGS_VECTOR=64,
    EPOCHS_PER_ETH1_VOTING_PERIOD=4,
    MAX_COMMITTEES_PER_SLOT=4,
    TARGET_COMMITTEE_SIZE=4,
    SHUFFLE_ROUND_COUNT=10,
    SYNC_COMMITTEE_SIZE=32,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=8,
    MAX_WITHDRAWALS_PER_PAYLOAD=4,
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP=16,
    MAX_BLOB_COMMITMENTS_PER_BLOCK=16,
    FIELD_ELEMENTS_PER_BLOB=4096,
    MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP=2,
)

PRESETS = {"mainnet": MAINNET, "minimal": MINIMAL}


@dataclass
class ChainSpec:
    """Runtime network parameters (chain_spec.rs). Domains are 4-byte
    little-endian type tags; fork schedule maps fork name -> activation epoch
    (FAR_FUTURE_EPOCH = never)."""

    preset: Preset = MAINNET
    config_name: str = "mainnet"

    # deposits / genesis
    min_genesis_active_validator_count: int = 16384
    min_genesis_time: int = 1606824000
    genesis_fork_version: bytes = b"\x00\x00\x00\x00"
    genesis_delay: int = 604800

    # forks: name -> (version, epoch)
    altair_fork_version: bytes = b"\x01\x00\x00\x00"
    altair_fork_epoch: int = FAR_FUTURE_EPOCH
    bellatrix_fork_version: bytes = b"\x02\x00\x00\x00"
    bellatrix_fork_epoch: int = FAR_FUTURE_EPOCH
    capella_fork_version: bytes = b"\x03\x00\x00\x00"
    capella_fork_epoch: int = FAR_FUTURE_EPOCH
    deneb_fork_version: bytes = b"\x04\x00\x00\x00"
    deneb_fork_epoch: int = FAR_FUTURE_EPOCH
    electra_fork_version: bytes = b"\x05\x00\x00\x00"
    electra_fork_epoch: int = FAR_FUTURE_EPOCH

    # validator lifecycle
    min_deposit_amount: int = 10**9
    max_effective_balance: int = 32 * 10**9
    max_effective_balance_electra: int = 2048 * 10**9
    effective_balance_increment: int = 10**9
    ejection_balance: int = 16 * 10**9
    min_per_epoch_churn_limit: int = 4
    max_per_epoch_activation_churn_limit: int = 8
    churn_limit_quotient: int = 65536
    min_per_epoch_churn_limit_electra: int = 128 * 10**9
    max_per_epoch_activation_exit_churn_limit: int = 256 * 10**9

    # time windows
    min_attestation_inclusion_delay: int = 1
    min_seed_lookahead: int = 1
    max_seed_lookahead: int = 4
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    min_epochs_to_inactivity_penalty: int = 4

    # rewards & penalties (phase0 values; altair variants below)
    base_reward_factor: int = 64
    whistleblower_reward_quotient: int = 512
    proposer_reward_quotient: int = 8
    inactivity_penalty_quotient: int = 2**26
    min_slashing_penalty_quotient: int = 128
    proportional_slashing_multiplier: int = 1
    # altair
    inactivity_penalty_quotient_altair: int = 3 * 2**24
    min_slashing_penalty_quotient_altair: int = 64
    proportional_slashing_multiplier_altair: int = 2
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16
    # bellatrix
    inactivity_penalty_quotient_bellatrix: int = 2**24
    min_slashing_penalty_quotient_bellatrix: int = 32
    proportional_slashing_multiplier_bellatrix: int = 3
    # electra
    min_activation_balance: int = 32 * 10**9
    whistleblower_reward_quotient_electra: int = 4096
    min_slashing_penalty_quotient_electra: int = 4096

    # deposit contract
    deposit_chain_id: int = 1
    deposit_network_id: int = 1
    deposit_contract_address: bytes = bytes(20)
    seconds_per_eth1_block: int = 14
    eth1_follow_distance: int = 2048

    # domains (domain type bytes, little-endian u32 tags)
    DOMAIN_BEACON_PROPOSER: bytes = b"\x00\x00\x00\x00"
    DOMAIN_BEACON_ATTESTER: bytes = b"\x01\x00\x00\x00"
    DOMAIN_RANDAO: bytes = b"\x02\x00\x00\x00"
    DOMAIN_DEPOSIT: bytes = b"\x03\x00\x00\x00"
    DOMAIN_VOLUNTARY_EXIT: bytes = b"\x04\x00\x00\x00"
    DOMAIN_SELECTION_PROOF: bytes = b"\x05\x00\x00\x00"
    DOMAIN_AGGREGATE_AND_PROOF: bytes = b"\x06\x00\x00\x00"
    DOMAIN_SYNC_COMMITTEE: bytes = b"\x07\x00\x00\x00"
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF: bytes = b"\x08\x00\x00\x00"
    DOMAIN_CONTRIBUTION_AND_PROOF: bytes = b"\x09\x00\x00\x00"
    DOMAIN_BLS_TO_EXECUTION_CHANGE: bytes = b"\x0a\x00\x00\x00"
    DOMAIN_APPLICATION_MASK: bytes = b"\x00\x00\x00\x01"

    # misc
    proposer_score_boost: int = 40
    attestation_subnet_count: int = 64
    target_aggregators_per_committee: int = 16

    # ----- fork helpers -------------------------------------------------------

    def fork_epoch(self, fork: str) -> int:
        if fork == "phase0":
            return 0
        return getattr(self, f"{fork}_fork_epoch")

    def fork_version(self, fork: str) -> bytes:
        if fork == "phase0":
            return self.genesis_fork_version
        return getattr(self, f"{fork}_fork_version")

    def fork_name_at_epoch(self, epoch: int) -> str:
        current = "phase0"
        for fork in FORK_ORDER[1:]:
            if epoch >= self.fork_epoch(fork):
                current = fork
        return current

    def fork_name_at_slot(self, slot: int) -> str:
        return self.fork_name_at_epoch(slot // self.preset.SLOTS_PER_EPOCH)

    def fork_version_at_epoch(self, epoch: int) -> bytes:
        return self.fork_version(self.fork_name_at_epoch(epoch))

    # ----- preset-derived helpers --------------------------------------------

    def compute_epoch_at_slot(self, slot: int) -> int:
        return slot // self.preset.SLOTS_PER_EPOCH

    def start_slot(self, epoch: int) -> int:
        return epoch * self.preset.SLOTS_PER_EPOCH


def mainnet_spec(**overrides) -> ChainSpec:
    return ChainSpec(preset=MAINNET, config_name="mainnet", **overrides)


def minimal_spec(**overrides) -> ChainSpec:
    """Minimal preset with the standard minimal-config churn override."""
    overrides.setdefault("churn_limit_quotient", 32)
    overrides.setdefault("min_genesis_active_validator_count", 64)
    overrides.setdefault("eth1_follow_distance", 16)
    overrides.setdefault("shard_committee_period", 64)
    overrides.setdefault("min_validator_withdrawability_delay", 256)
    return ChainSpec(preset=MINIMAL, config_name="minimal", **overrides)
