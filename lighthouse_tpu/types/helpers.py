"""Signing roots, domains, and small spec helpers.

Parity: ``consensus/types/src/chain_spec.rs`` domain computation and the
signing-root flow used by every signature-set constructor
(``consensus/state_processing/src/per_block_processing/signature_sets.rs:74-``).
"""

from __future__ import annotations

from .containers import ForkData, SigningData
from .spec import ChainSpec


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return ForkData(
        current_version=current_version,
        genesis_validators_root=genesis_validators_root,
    ).tree_root()


def compute_fork_digest(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(
    domain_type: bytes, fork_version: bytes, genesis_validators_root: bytes
) -> bytes:
    fdr = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + fdr[:28]


def get_domain(
    spec: ChainSpec, state, domain_type: bytes, epoch: int | None = None
) -> bytes:
    ep = epoch if epoch is not None else spec.compute_epoch_at_slot(state.slot)
    fork = state.fork
    version = (
        fork.previous_version if ep < fork.epoch else fork.current_version
    )
    return compute_domain(domain_type, version, state.genesis_validators_root)


def compute_signing_root(obj, domain: bytes) -> bytes:
    return SigningData(object_root=obj.tree_root(), domain=domain).tree_root()


# -- validator predicates (beacon_state helpers) ----------------------------------


def is_active_validator(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


def is_eligible_for_activation_queue(v, spec: ChainSpec) -> bool:
    from .spec import FAR_FUTURE_EPOCH

    return (
        v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
        and v.effective_balance == spec.max_effective_balance
    )


def is_slashable_validator(v, epoch: int) -> bool:
    return not v.slashed and v.activation_epoch <= epoch < v.withdrawable_epoch


def is_slashable_attestation_data(d1, d2) -> bool:
    """Double vote or surround vote (proto: is_slashable_attestation_data)."""
    double = d1 != d2 and d1.target.epoch == d2.target.epoch
    surround = (
        d1.source.epoch < d2.source.epoch and d2.target.epoch < d1.target.epoch
    )
    return double or surround


def sync_committee_signing_root(spec, state_or_fork_info, slot: int,
                                beacon_block_root: bytes) -> bytes:
    """Signing root of a sync-committee message: the block root under the
    sync-committee domain of ``slot``'s epoch. Shared by the BN verifier and
    the VC signer so the two can never diverge."""
    from .containers import SigningData

    domain = get_domain(
        spec, state_or_fork_info, spec.DOMAIN_SYNC_COMMITTEE,
        epoch=spec.compute_epoch_at_slot(int(slot)),
    )
    return SigningData(
        object_root=bytes(beacon_block_root), domain=domain
    ).tree_root()
