"""Consensus containers (phase0 + altair core; later forks extend here).

Per-preset container classes are generated once by ``for_preset`` — the Python
analog of the reference's ``EthSpec``-monomorphized types
(``consensus/types/src/*.rs``; fork variants via superstruct become subclass
chains here, e.g. ``BeaconStateAltair(BeaconStatePhase0)`` with extended
FIELDS). Field names and SSZ shapes match the consensus spec exactly so EF
ssz_static vectors apply unchanged.
"""

from __future__ import annotations

import functools
from types import SimpleNamespace

from ..ssz import (
    Bitlist, Bitvector, ByteList, ByteVector, Container, List, Vector,
    boolean, uint8, uint64, uint256,
)
from .spec import Preset, PRESETS

# -- aliases (fixed across presets) ----------------------------------------------

Root = ByteVector(32)
Hash32 = ByteVector(32)
Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
BLSPubkey = ByteVector(48)
BLSSignature = ByteVector(96)
KZGCommitment = ByteVector(48)

Slot = uint64
Epoch = uint64
Gwei = uint64
ValidatorIndex = uint64
CommitteeIndex = uint64

DEPOSIT_CONTRACT_TREE_DEPTH = 32
JUSTIFICATION_BITS_LENGTH = 4


class Fork(Container):
    FIELDS = [
        ("previous_version", Bytes4),
        ("current_version", Bytes4),
        ("epoch", Epoch),
    ]


class ForkData(Container):
    FIELDS = [("current_version", Bytes4), ("genesis_validators_root", Root)]


class Checkpoint(Container):
    FIELDS = [("epoch", Epoch), ("root", Root)]


class SigningData(Container):
    FIELDS = [("object_root", Root), ("domain", ByteVector(32))]


class Validator(Container):
    FIELDS = [
        ("pubkey", BLSPubkey),
        ("withdrawal_credentials", ByteVector(32)),
        ("effective_balance", Gwei),
        ("slashed", boolean),
        ("activation_eligibility_epoch", Epoch),
        ("activation_epoch", Epoch),
        ("exit_epoch", Epoch),
        ("withdrawable_epoch", Epoch),
    ]


class AttestationData(Container):
    FIELDS = [
        ("slot", Slot),
        ("index", CommitteeIndex),
        ("beacon_block_root", Root),
        ("source", Checkpoint),
        ("target", Checkpoint),
    ]


class Eth1Data(Container):
    FIELDS = [
        ("deposit_root", Root),
        ("deposit_count", uint64),
        ("block_hash", Hash32),
    ]


class DepositMessage(Container):
    FIELDS = [
        ("pubkey", BLSPubkey),
        ("withdrawal_credentials", ByteVector(32)),
        ("amount", Gwei),
    ]


class DepositData(Container):
    FIELDS = [
        ("pubkey", BLSPubkey),
        ("withdrawal_credentials", ByteVector(32)),
        ("amount", Gwei),
        ("signature", BLSSignature),
    ]


class Withdrawal(Container):
    FIELDS = [
        ("index", uint64),
        ("validator_index", ValidatorIndex),
        ("address", Bytes20),
        ("amount", Gwei),
    ]


class BLSToExecutionChange(Container):
    FIELDS = [
        ("validator_index", ValidatorIndex),
        ("from_bls_pubkey", BLSPubkey),
        ("to_execution_address", Bytes20),
    ]


class SignedBLSToExecutionChange(Container):
    FIELDS = [
        ("message", BLSToExecutionChange),
        ("signature", BLSSignature),
    ]


class HistoricalSummary(Container):
    """Capella replacement for HistoricalBatch accumulation
    (consensus/types/src/historical_summary.rs)."""

    FIELDS = [
        ("block_summary_root", Root),
        ("state_summary_root", Root),
    ]


class BeaconBlockHeader(Container):
    FIELDS = [
        ("slot", Slot),
        ("proposer_index", ValidatorIndex),
        ("parent_root", Root),
        ("state_root", Root),
        ("body_root", Root),
    ]


class SignedBeaconBlockHeader(Container):
    FIELDS = [("message", BeaconBlockHeader), ("signature", BLSSignature)]


class ProposerSlashing(Container):
    FIELDS = [
        ("signed_header_1", SignedBeaconBlockHeader),
        ("signed_header_2", SignedBeaconBlockHeader),
    ]


class Deposit(Container):
    FIELDS = [
        ("proof", Vector(ByteVector(32), DEPOSIT_CONTRACT_TREE_DEPTH + 1)),
        ("data", DepositData),
    ]


class VoluntaryExit(Container):
    FIELDS = [("epoch", Epoch), ("validator_index", ValidatorIndex)]


class SignedVoluntaryExit(Container):
    FIELDS = [("message", VoluntaryExit), ("signature", BLSSignature)]


# -- preset-parameterized containers ----------------------------------------------


@functools.lru_cache(maxsize=None)
def for_preset(preset_name: str) -> SimpleNamespace:
    p: Preset = PRESETS[preset_name]

    class IndexedAttestation(Container):
        FIELDS = [
            ("attesting_indices", List(uint64, p.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", AttestationData),
            ("signature", BLSSignature),
        ]

    class Attestation(Container):
        FIELDS = [
            ("aggregation_bits", Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", AttestationData),
            ("signature", BLSSignature),
        ]

    class PendingAttestation(Container):
        FIELDS = [
            ("aggregation_bits", Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", AttestationData),
            ("inclusion_delay", Slot),
            ("proposer_index", ValidatorIndex),
        ]

    class AggregateAndProof(Container):
        """Gossip aggregate envelope (consensus/types/src/aggregate_and_proof.rs)."""

        FIELDS = [
            ("aggregator_index", ValidatorIndex),
            ("aggregate", Attestation),
            ("selection_proof", BLSSignature),
        ]

    class SignedAggregateAndProof(Container):
        FIELDS = [
            ("message", AggregateAndProof),
            ("signature", BLSSignature),
        ]

    class AttesterSlashing(Container):
        FIELDS = [
            ("attestation_1", IndexedAttestation),
            ("attestation_2", IndexedAttestation),
        ]

    class HistoricalBatch(Container):
        FIELDS = [
            ("block_roots", Vector(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", Vector(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
        ]

    class SyncCommittee(Container):
        FIELDS = [
            ("pubkeys", Vector(BLSPubkey, p.SYNC_COMMITTEE_SIZE)),
            ("aggregate_pubkey", BLSPubkey),
        ]

    class SyncAggregate(Container):
        FIELDS = [
            ("sync_committee_bits", Bitvector(p.SYNC_COMMITTEE_SIZE)),
            ("sync_committee_signature", BLSSignature),
        ]

    class SyncCommitteeMessage(Container):
        FIELDS = [
            ("slot", uint64),
            ("beacon_block_root", Root),
            ("validator_index", uint64),
            ("signature", BLSSignature),
        ]

    class SyncCommitteeContribution(Container):
        FIELDS = [
            ("slot", uint64),
            ("beacon_block_root", Root),
            ("subcommittee_index", uint64),
            ("aggregation_bits", Bitvector(p.SYNC_COMMITTEE_SIZE // 4)),
            ("signature", BLSSignature),
        ]

    class SyncAggregatorSelectionData(Container):
        FIELDS = [
            ("slot", uint64),
            ("subcommittee_index", uint64),
        ]

    class ContributionAndProof(Container):
        FIELDS = [
            ("aggregator_index", uint64),
            ("contribution", SyncCommitteeContribution),
            ("selection_proof", BLSSignature),
        ]

    class SignedContributionAndProof(Container):
        FIELDS = [
            ("message", ContributionAndProof),
            ("signature", BLSSignature),
        ]

    class BeaconBlockBody(Container):
        FIELDS = [
            ("randao_reveal", BLSSignature),
            ("eth1_data", Eth1Data),
            ("graffiti", ByteVector(32)),
            ("proposer_slashings", List(ProposerSlashing, p.MAX_PROPOSER_SLASHINGS)),
            ("attester_slashings", List(AttesterSlashing, p.MAX_ATTESTER_SLASHINGS)),
            ("attestations", List(Attestation, p.MAX_ATTESTATIONS)),
            ("deposits", List(Deposit, p.MAX_DEPOSITS)),
            ("voluntary_exits", List(SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS)),
        ]

    class BeaconBlock(Container):
        FIELDS = [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBody),
        ]

    class SignedBeaconBlock(Container):
        FIELDS = [("message", BeaconBlock), ("signature", BLSSignature)]

    class BeaconState(Container):
        FIELDS = [
            ("genesis_time", uint64),
            ("genesis_validators_root", Root),
            ("slot", Slot),
            ("fork", Fork),
            ("latest_block_header", BeaconBlockHeader),
            ("block_roots", Vector(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", Vector(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("historical_roots", List(Root, p.HISTORICAL_ROOTS_LIMIT)),
            ("eth1_data", Eth1Data),
            ("eth1_data_votes", List(Eth1Data, p.slots_per_eth1_voting_period)),
            ("eth1_deposit_index", uint64),
            ("validators", List(Validator, p.VALIDATOR_REGISTRY_LIMIT)),
            ("balances", List(Gwei, p.VALIDATOR_REGISTRY_LIMIT)),
            ("randao_mixes", Vector(Root, p.EPOCHS_PER_HISTORICAL_VECTOR)),
            ("slashings", Vector(Gwei, p.EPOCHS_PER_SLASHINGS_VECTOR)),
            ("previous_epoch_attestations",
             List(PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH)),
            ("current_epoch_attestations",
             List(PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH)),
            ("justification_bits", Bitvector(JUSTIFICATION_BITS_LENGTH)),
            ("previous_justified_checkpoint", Checkpoint),
            ("current_justified_checkpoint", Checkpoint),
            ("finalized_checkpoint", Checkpoint),
        ]

        fork_name = "phase0"

    # -- altair variants -----------------------------------------------------

    class BeaconBlockBodyAltair(Container):
        FIELDS = BeaconBlockBody.FIELDS + [("sync_aggregate", SyncAggregate)]

    class BeaconBlockAltair(Container):
        FIELDS = [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBodyAltair),
        ]

    class SignedBeaconBlockAltair(Container):
        FIELDS = [("message", BeaconBlockAltair), ("signature", BLSSignature)]

    class BeaconStateAltair(Container):
        FIELDS = [
            f for f in BeaconState.FIELDS
            if f[0] not in ("previous_epoch_attestations", "current_epoch_attestations")
        ]
        # splice participation in place of pending attestations, append the rest
        _idx = [n for n, _ in FIELDS].index("slashings") + 1
        FIELDS = (
            FIELDS[:_idx]
            + [
                ("previous_epoch_participation",
                 List(uint8, p.VALIDATOR_REGISTRY_LIMIT)),
                ("current_epoch_participation",
                 List(uint8, p.VALIDATOR_REGISTRY_LIMIT)),
            ]
            + FIELDS[_idx:]
            + [
                ("inactivity_scores", List(uint64, p.VALIDATOR_REGISTRY_LIMIT)),
                ("current_sync_committee", SyncCommittee),
                ("next_sync_committee", SyncCommittee),
            ]
        )
        fork_name = "altair"

    # -- bellatrix / capella variants (execution payloads) -------------------

    Transaction = ByteList(p.MAX_BYTES_PER_TRANSACTION)

    _payload_common = [
        ("parent_hash", Hash32),
        ("fee_recipient", Bytes20),
        ("state_root", Root),
        ("receipts_root", Root),
        ("logs_bloom", ByteVector(p.BYTES_PER_LOGS_BLOOM)),
        ("prev_randao", Hash32),
        ("block_number", uint64),
        ("gas_limit", uint64),
        ("gas_used", uint64),
        ("timestamp", uint64),
        ("extra_data", ByteList(p.MAX_EXTRA_DATA_BYTES)),
        ("base_fee_per_gas", uint256),
        ("block_hash", Hash32),
    ]

    class ExecutionPayloadBellatrix(Container):
        FIELDS = _payload_common + [
            ("transactions", List(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD)),
        ]

    class ExecutionPayloadHeaderBellatrix(Container):
        FIELDS = _payload_common + [("transactions_root", Root)]

    class ExecutionPayloadCapella(Container):
        FIELDS = ExecutionPayloadBellatrix.FIELDS + [
            ("withdrawals", List(Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD)),
        ]

    class ExecutionPayloadHeaderCapella(Container):
        FIELDS = ExecutionPayloadHeaderBellatrix.FIELDS + [
            ("withdrawals_root", Root),
        ]

    class BeaconBlockBodyBellatrix(Container):
        FIELDS = BeaconBlockBodyAltair.FIELDS + [
            ("execution_payload", ExecutionPayloadBellatrix),
        ]

    class BeaconBlockBellatrix(Container):
        FIELDS = [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBodyBellatrix),
        ]

    class SignedBeaconBlockBellatrix(Container):
        FIELDS = [("message", BeaconBlockBellatrix), ("signature", BLSSignature)]

    class BeaconBlockBodyCapella(Container):
        FIELDS = [
            (n, t) if n != "execution_payload" else (n, ExecutionPayloadCapella)
            for n, t in BeaconBlockBodyBellatrix.FIELDS
        ] + [
            (
                "bls_to_execution_changes",
                List(SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES),
            ),
        ]

    class BeaconBlockCapella(Container):
        FIELDS = [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBodyCapella),
        ]

    class SignedBeaconBlockCapella(Container):
        FIELDS = [("message", BeaconBlockCapella), ("signature", BLSSignature)]

    class BeaconStateBellatrix(Container):
        FIELDS = BeaconStateAltair.FIELDS + [
            ("latest_execution_payload_header", ExecutionPayloadHeaderBellatrix),
        ]
        fork_name = "bellatrix"

    class BeaconStateCapella(Container):
        FIELDS = [
            (n, t)
            if n != "latest_execution_payload_header"
            else (n, ExecutionPayloadHeaderCapella)
            for n, t in BeaconStateBellatrix.FIELDS
        ] + [
            ("next_withdrawal_index", uint64),
            ("next_withdrawal_validator_index", ValidatorIndex),
            ("historical_summaries",
             List(HistoricalSummary, p.HISTORICAL_ROOTS_LIMIT)),
        ]
        fork_name = "capella"

    # -- deneb variants (blobs; consensus/types/src/blob_sidecar.rs) ---------

    class ExecutionPayloadDeneb(Container):
        FIELDS = ExecutionPayloadCapella.FIELDS + [
            ("blob_gas_used", uint64),
            ("excess_blob_gas", uint64),
        ]

    class ExecutionPayloadHeaderDeneb(Container):
        FIELDS = ExecutionPayloadHeaderCapella.FIELDS + [
            ("blob_gas_used", uint64),
            ("excess_blob_gas", uint64),
        ]

    class BeaconBlockBodyDeneb(Container):
        FIELDS = [
            (n, t) if n != "execution_payload" else (n, ExecutionPayloadDeneb)
            for n, t in BeaconBlockBodyCapella.FIELDS
        ] + [
            (
                "blob_kzg_commitments",
                List(KZGCommitment, p.MAX_BLOB_COMMITMENTS_PER_BLOCK),
            ),
        ]

    class BeaconBlockDeneb(Container):
        FIELDS = [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBodyDeneb),
        ]

    class SignedBeaconBlockDeneb(Container):
        FIELDS = [("message", BeaconBlockDeneb), ("signature", BLSSignature)]

    class BeaconStateDeneb(Container):
        FIELDS = [
            (n, t)
            if n != "latest_execution_payload_header"
            else (n, ExecutionPayloadHeaderDeneb)
            for n, t in BeaconStateCapella.FIELDS
        ]
        fork_name = "deneb"

    Blob = ByteVector(32 * p.FIELD_ELEMENTS_PER_BLOB)

    # inclusion-proof depth: commitments-list subtree + length mix-in +
    # body-fields level (17 on mainnet, 9 on minimal)
    _commitments_depth = (p.MAX_BLOB_COMMITMENTS_PER_BLOCK - 1).bit_length()
    _body_depth = (len(BeaconBlockBodyDeneb.FIELDS) - 1).bit_length()
    KZG_COMMITMENT_INCLUSION_PROOF_DEPTH = _commitments_depth + 1 + _body_depth

    class BlobSidecar(Container):
        """Gossiped blob container (consensus/types/src/blob_sidecar.rs)."""

        FIELDS = [
            ("index", uint64),
            ("blob", Blob),
            ("kzg_commitment", KZGCommitment),
            ("kzg_proof", ByteVector(48)),
            ("signed_block_header", SignedBeaconBlockHeader),
            (
                "kzg_commitment_inclusion_proof",
                Vector(Root, KZG_COMMITMENT_INCLUSION_PROOF_DEPTH),
            ),
        ]

    class BlobIdentifier(Container):
        FIELDS = [("block_root", Root), ("index", uint64)]

    # -- PeerDAS / fulu groundwork (EIP-7594) --------------------------------
    # consensus/types/src/data_column_sidecar.rs: columns slice the erasure-
    # extended blob matrix the other way — one cell per blob per column.

    NUMBER_OF_COLUMNS = 128          # spec CELLS_PER_EXT_BLOB geometry
    BYTES_PER_CELL = 2048            # 64 field elements x 32 bytes
    Cell = ByteVector(BYTES_PER_CELL)
    # the proof covers the WHOLE blob_kzg_commitments list root under the
    # body root (one body-depth branch), unlike the per-commitment blob path
    KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH = _body_depth

    class DataColumnSidecar(Container):
        FIELDS = [
            ("index", uint64),
            ("column", List(Cell, p.MAX_BLOB_COMMITMENTS_PER_BLOCK)),
            (
                "kzg_commitments",
                List(KZGCommitment, p.MAX_BLOB_COMMITMENTS_PER_BLOCK),
            ),
            (
                "kzg_proofs",
                List(ByteVector(48), p.MAX_BLOB_COMMITMENTS_PER_BLOCK),
            ),
            ("signed_block_header", SignedBeaconBlockHeader),
            (
                "kzg_commitments_inclusion_proof",
                Vector(Root, KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH),
            ),
        ]

    class DataColumnIdentifier(Container):
        FIELDS = [("block_root", Root), ("index", uint64)]

    # -- electra variants (EIP-6110/7002/7251/7549) --------------------------

    class DepositRequest(Container):
        FIELDS = [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", ByteVector(32)),
            ("amount", Gwei),
            ("signature", BLSSignature),
            ("index", uint64),
        ]

    class WithdrawalRequest(Container):
        FIELDS = [
            ("source_address", Bytes20),
            ("validator_pubkey", BLSPubkey),
            ("amount", Gwei),
        ]

    class ConsolidationRequest(Container):
        FIELDS = [
            ("source_address", Bytes20),
            ("source_pubkey", BLSPubkey),
            ("target_pubkey", BLSPubkey),
        ]

    class ExecutionRequests(Container):
        FIELDS = [
            ("deposits", List(DepositRequest, p.MAX_DEPOSIT_REQUESTS_PER_PAYLOAD)),
            ("withdrawals",
             List(WithdrawalRequest, p.MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD)),
            ("consolidations",
             List(ConsolidationRequest, p.MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD)),
        ]

    class PendingDeposit(Container):
        FIELDS = [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", ByteVector(32)),
            ("amount", Gwei),
            ("signature", BLSSignature),
            ("slot", Slot),
        ]

    class PendingPartialWithdrawal(Container):
        FIELDS = [
            ("validator_index", ValidatorIndex),
            ("amount", Gwei),
            ("withdrawable_epoch", Epoch),
        ]

    class PendingConsolidation(Container):
        FIELDS = [("source_index", ValidatorIndex), ("target_index", ValidatorIndex)]

    _electra_agg_limit = p.MAX_VALIDATORS_PER_COMMITTEE * p.MAX_COMMITTEES_PER_SLOT

    class AttestationElectra(Container):
        """EIP-7549: committee index moves out of AttestationData into
        committee_bits; aggregation bits span the whole slot."""

        FIELDS = [
            ("aggregation_bits", Bitlist(_electra_agg_limit)),
            ("data", AttestationData),
            ("signature", BLSSignature),
            ("committee_bits", Bitvector(p.MAX_COMMITTEES_PER_SLOT)),
        ]

    class IndexedAttestationElectra(Container):
        FIELDS = [
            ("attesting_indices", List(uint64, _electra_agg_limit)),
            ("data", AttestationData),
            ("signature", BLSSignature),
        ]

    class AttesterSlashingElectra(Container):
        FIELDS = [
            ("attestation_1", IndexedAttestationElectra),
            ("attestation_2", IndexedAttestationElectra),
        ]

    class SingleAttestation(Container):
        """Unaggregated electra gossip attestation."""

        FIELDS = [
            ("committee_index", CommitteeIndex),
            ("attester_index", ValidatorIndex),
            ("data", AttestationData),
            ("signature", BLSSignature),
        ]

    class AggregateAndProofElectra(Container):
        FIELDS = [
            ("aggregator_index", ValidatorIndex),
            ("aggregate", AttestationElectra),
            ("selection_proof", BLSSignature),
        ]

    class SignedAggregateAndProofElectra(Container):
        FIELDS = [
            ("message", AggregateAndProofElectra),
            ("signature", BLSSignature),
        ]

    class BeaconBlockBodyElectra(Container):
        FIELDS = [
            (n,
             List(ProposerSlashing, p.MAX_PROPOSER_SLASHINGS) if n == "proposer_slashings"
             else List(AttesterSlashingElectra, p.MAX_ATTESTER_SLASHINGS_ELECTRA) if n == "attester_slashings"
             else List(AttestationElectra, p.MAX_ATTESTATIONS_ELECTRA) if n == "attestations"
             else t)
            for n, t in BeaconBlockBodyDeneb.FIELDS
        ] + [("execution_requests", ExecutionRequests)]

    class BeaconBlockElectra(Container):
        FIELDS = [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBodyElectra),
        ]

    class SignedBeaconBlockElectra(Container):
        FIELDS = [("message", BeaconBlockElectra), ("signature", BLSSignature)]

    class BeaconStateElectra(Container):
        FIELDS = BeaconStateDeneb.FIELDS + [
            ("deposit_requests_start_index", uint64),
            ("deposit_balance_to_consume", Gwei),
            ("exit_balance_to_consume", Gwei),
            ("earliest_exit_epoch", Epoch),
            ("consolidation_balance_to_consume", Gwei),
            ("earliest_consolidation_epoch", Epoch),
            ("pending_deposits", List(PendingDeposit, p.PENDING_DEPOSITS_LIMIT)),
            ("pending_partial_withdrawals",
             List(PendingPartialWithdrawal, p.PENDING_PARTIAL_WITHDRAWALS_LIMIT)),
            ("pending_consolidations",
             List(PendingConsolidation, p.PENDING_CONSOLIDATIONS_LIMIT)),
        ]
        fork_name = "electra"

    ns = SimpleNamespace(
        preset=p,
        IndexedAttestation=IndexedAttestation,
        Attestation=Attestation,
        PendingAttestation=PendingAttestation,
        AttesterSlashing=AttesterSlashing,
        AggregateAndProof=AggregateAndProof,
        SignedAggregateAndProof=SignedAggregateAndProof,
        HistoricalBatch=HistoricalBatch,
        SyncCommittee=SyncCommittee,
        SyncAggregate=SyncAggregate,
        SyncCommitteeMessage=SyncCommitteeMessage,
        SyncAggregatorSelectionData=SyncAggregatorSelectionData,
        SyncCommitteeContribution=SyncCommitteeContribution,
        ContributionAndProof=ContributionAndProof,
        SignedContributionAndProof=SignedContributionAndProof,
        BeaconBlockBody=BeaconBlockBody,
        BeaconBlock=BeaconBlock,
        SignedBeaconBlock=SignedBeaconBlock,
        BeaconState=BeaconState,
        BeaconBlockBodyAltair=BeaconBlockBodyAltair,
        BeaconBlockAltair=BeaconBlockAltair,
        SignedBeaconBlockAltair=SignedBeaconBlockAltair,
        BeaconStateAltair=BeaconStateAltair,
        ExecutionPayloadBellatrix=ExecutionPayloadBellatrix,
        ExecutionPayloadHeaderBellatrix=ExecutionPayloadHeaderBellatrix,
        ExecutionPayloadCapella=ExecutionPayloadCapella,
        ExecutionPayloadHeaderCapella=ExecutionPayloadHeaderCapella,
        BeaconBlockBodyBellatrix=BeaconBlockBodyBellatrix,
        BeaconBlockBellatrix=BeaconBlockBellatrix,
        SignedBeaconBlockBellatrix=SignedBeaconBlockBellatrix,
        BeaconBlockBodyCapella=BeaconBlockBodyCapella,
        BeaconBlockCapella=BeaconBlockCapella,
        SignedBeaconBlockCapella=SignedBeaconBlockCapella,
        BeaconStateBellatrix=BeaconStateBellatrix,
        BeaconStateCapella=BeaconStateCapella,
        ExecutionPayloadDeneb=ExecutionPayloadDeneb,
        ExecutionPayloadHeaderDeneb=ExecutionPayloadHeaderDeneb,
        BeaconBlockBodyDeneb=BeaconBlockBodyDeneb,
        BeaconBlockDeneb=BeaconBlockDeneb,
        SignedBeaconBlockDeneb=SignedBeaconBlockDeneb,
        BeaconStateDeneb=BeaconStateDeneb,
        Blob=Blob,
        BlobSidecar=BlobSidecar,
        BlobIdentifier=BlobIdentifier,
        KZG_COMMITMENT_INCLUSION_PROOF_DEPTH=KZG_COMMITMENT_INCLUSION_PROOF_DEPTH,
        NUMBER_OF_COLUMNS=NUMBER_OF_COLUMNS,
        BYTES_PER_CELL=BYTES_PER_CELL,
        Cell=Cell,
        DataColumnSidecar=DataColumnSidecar,
        DataColumnIdentifier=DataColumnIdentifier,
        KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH=KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH,
        DepositRequest=DepositRequest,
        WithdrawalRequest=WithdrawalRequest,
        ConsolidationRequest=ConsolidationRequest,
        ExecutionRequests=ExecutionRequests,
        PendingDeposit=PendingDeposit,
        PendingPartialWithdrawal=PendingPartialWithdrawal,
        PendingConsolidation=PendingConsolidation,
        AttestationElectra=AttestationElectra,
        IndexedAttestationElectra=IndexedAttestationElectra,
        AttesterSlashingElectra=AttesterSlashingElectra,
        SingleAttestation=SingleAttestation,
        AggregateAndProofElectra=AggregateAndProofElectra,
        SignedAggregateAndProofElectra=SignedAggregateAndProofElectra,
        BeaconBlockBodyElectra=BeaconBlockBodyElectra,
        BeaconBlockElectra=BeaconBlockElectra,
        SignedBeaconBlockElectra=SignedBeaconBlockElectra,
        BeaconStateElectra=BeaconStateElectra,
        # fork-indexed lookup used by generic code
        state_types={
            "phase0": BeaconState,
            "altair": BeaconStateAltair,
            "bellatrix": BeaconStateBellatrix,
            "capella": BeaconStateCapella,
            "deneb": BeaconStateDeneb,
            "electra": BeaconStateElectra,
        },
        block_types={
            "phase0": SignedBeaconBlock,
            "altair": SignedBeaconBlockAltair,
            "bellatrix": SignedBeaconBlockBellatrix,
            "capella": SignedBeaconBlockCapella,
            "deneb": SignedBeaconBlockDeneb,
            "electra": SignedBeaconBlockElectra,
        },
        body_types={
            "phase0": BeaconBlockBody,
            "altair": BeaconBlockBodyAltair,
            "bellatrix": BeaconBlockBodyBellatrix,
            "capella": BeaconBlockBodyCapella,
            "deneb": BeaconBlockBodyDeneb,
            "electra": BeaconBlockBodyElectra,
        },
        payload_types={
            "bellatrix": ExecutionPayloadBellatrix,
            "capella": ExecutionPayloadCapella,
            "deneb": ExecutionPayloadDeneb,
            "electra": ExecutionPayloadDeneb,  # payload unchanged in electra
        },
        payload_header_types={
            "bellatrix": ExecutionPayloadHeaderBellatrix,
            "capella": ExecutionPayloadHeaderCapella,
            "deneb": ExecutionPayloadHeaderDeneb,
            "electra": ExecutionPayloadHeaderDeneb,
        },
        attestation_types={
            "phase0": Attestation, "altair": Attestation,
            "bellatrix": Attestation, "capella": Attestation,
            "deneb": Attestation, "electra": AttestationElectra,
        },
        indexed_attestation_types={
            "phase0": IndexedAttestation, "altair": IndexedAttestation,
            "bellatrix": IndexedAttestation, "capella": IndexedAttestation,
            "deneb": IndexedAttestation, "electra": IndexedAttestationElectra,
        },
        attester_slashing_types={
            "phase0": AttesterSlashing, "altair": AttesterSlashing,
            "bellatrix": AttesterSlashing, "capella": AttesterSlashing,
            "deneb": AttesterSlashing, "electra": AttesterSlashingElectra,
        },
    )
    return ns
