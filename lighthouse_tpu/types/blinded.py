"""Blinded block variants (execution payload replaced by its header).

The reference defines ``BlindedBeaconBlock`` via superstruct macros
(``consensus/types/src/beacon_block.rs`` blinded variants, used by the
builder flow in ``beacon_node/execution_layer/src/lib.rs``); here the
classes are derived from the full containers by swapping the payload field
for the header. Because ``ExecutionPayloadHeader`` carries the Merkle roots
of the list fields, a blinded block's ``hash_tree_root`` equals the full
block's — a proposer signature over one is valid for the other, which is
what makes the blinded production/publication round-trip sound.
"""

from __future__ import annotations

from ..ssz import Container
from .containers import BLSSignature


def blinded_types(ns):
    """Augment a ``for_preset`` namespace with ``blinded_body_types``,
    ``blinded_block_types`` (signed, fork-indexed). Idempotent."""
    if hasattr(ns, "blinded_block_types"):
        return ns
    bodies, signed_blocks = {}, {}
    for fork, hdr_cls in ns.payload_header_types.items():
        body_cls = ns.body_types[fork]
        fields = [
            (("execution_payload_header", hdr_cls)
             if name == "execution_payload" else (name, t))
            for name, t in body_cls.FIELDS
        ]
        body = type(
            f"BlindedBeaconBlockBody_{fork}", (Container,), {"FIELDS": fields}
        )
        inner_full = dict(ns.block_types[fork].FIELDS)["message"]
        blk_fields = [
            (name, body if name == "body" else t)
            for name, t in inner_full.FIELDS
        ]
        blk = type(
            f"BlindedBeaconBlock_{fork}", (Container,), {"FIELDS": blk_fields}
        )
        signed = type(
            f"SignedBlindedBeaconBlock_{fork}",
            (Container,),
            {"FIELDS": [("message", blk), ("signature", BLSSignature)]},
        )
        bodies[fork] = body
        signed_blocks[fork] = signed
    ns.blinded_body_types = bodies
    ns.blinded_block_types = signed_blocks
    return ns


def payload_to_header(ns, fork: str, payload):
    """ExecutionPayload -> ExecutionPayloadHeader (list fields replaced by
    their hash_tree_roots — per_block_processing builds headers the same
    way; spec ``get_execution_payload_header``)."""
    payload_cls = ns.payload_types[fork]
    hdr_cls = ns.payload_header_types[fork]
    types = dict(payload_cls.FIELDS)
    fields = {}
    for name, _ in payload_cls.FIELDS:
        if name in ("transactions", "withdrawals"):
            fields[f"{name}_root"] = types[name].hash_tree_root(
                getattr(payload, name)
            )
        else:
            fields[name] = getattr(payload, name)
    return hdr_cls(**fields)


def blind_signed_block(ns, fork: str, signed_block):
    """Full signed block -> signed blinded block (same signature — the tree
    roots agree)."""
    blinded_types(ns)
    body = signed_block.message.body
    blinded_body_cls = ns.blinded_body_types[fork]
    fields = {}
    for name, _ in blinded_body_cls.FIELDS:
        if name == "execution_payload_header":
            fields[name] = payload_to_header(ns, fork, body.execution_payload)
        else:
            fields[name] = getattr(body, name)
    blinded_cls = ns.blinded_block_types[fork]
    inner_cls = dict(blinded_cls.FIELDS)["message"]
    msg = signed_block.message
    inner = inner_cls(
        slot=msg.slot,
        proposer_index=msg.proposer_index,
        parent_root=msg.parent_root,
        state_root=msg.state_root,
        body=blinded_body_cls(**fields),
    )
    return blinded_cls(message=inner, signature=signed_block.signature)


def unblind_signed_block(ns, fork: str, signed_blinded, payload):
    """Signed blinded block + the matching full payload -> full signed block.
    Raises ``ValueError`` if the payload does not match the header root."""
    hdr = signed_blinded.message.body.execution_payload_header
    rebuilt = payload_to_header(ns, fork, payload)
    if type(hdr).hash_tree_root(hdr) != type(rebuilt).hash_tree_root(rebuilt):
        raise ValueError("payload does not match the blinded header")
    body_cls = ns.body_types[fork]
    bb = signed_blinded.message.body
    fields = {}
    for name, _ in body_cls.FIELDS:
        if name == "execution_payload":
            fields[name] = payload
        else:
            fields[name] = getattr(bb, name)
    block_cls = ns.block_types[fork]
    inner_cls = dict(block_cls.FIELDS)["message"]
    msg = signed_blinded.message
    inner = inner_cls(
        slot=msg.slot,
        proposer_index=msg.proposer_index,
        parent_root=msg.parent_root,
        state_root=msg.state_root,
        body=body_cls(**fields),
    )
    return block_cls(message=inner, signature=signed_blinded.signature)
