"""Genesis construction: interop/deterministic validators.

Parity: ``/root/reference/beacon_node/genesis/src/interop.rs`` (deterministic
keypairs + quick-start genesis) and the spec's
``initialize_beacon_state_from_eth1``. Interop secret keys follow the
eth2-interop convention: sk_i = int_LE(sha256(uint_LE_32(i))) mod r.
"""

from __future__ import annotations

import numpy as np

from ..ops.bls_oracle import ciphersuite as cs
from ..ops.bls_oracle import curves as oc
from ..ops.bls_oracle.fields import R as CURVE_ORDER
from ..ssz.sha256 import sha256
from ..types.containers import Eth1Data, Fork, Validator, for_preset
from ..types.spec import ChainSpec, FAR_FUTURE_EPOCH

ETH1_BLOCK_HASH = b"\x42" * 32
GENESIS_SLOT = 0
GENESIS_EPOCH = 0


def interop_secret_keys(n: int) -> list[int]:
    return [
        int.from_bytes(sha256(i.to_bytes(32, "little")), "little") % CURVE_ORDER
        for i in range(n)
    ]


def interop_keypairs(n: int):
    sks = interop_secret_keys(n)
    return [(sk, oc.g1_compress(cs.sk_to_pk(sk))) for sk in sks]


def interop_genesis_state(
    spec: ChainSpec, n_validators: int, genesis_time: int = 0
):
    """Build a post-activation genesis state with n deterministic validators,
    at the fork active at epoch 0 (phase0 or altair)."""
    ns = for_preset(spec.preset.name)
    fork_name = spec.fork_name_at_epoch(GENESIS_EPOCH)
    state_cls = ns.state_types.get(fork_name)
    if state_cls is None:
        raise ValueError(f"genesis fork {fork_name} not yet supported")
    state = state_cls()

    keypairs = interop_keypairs(n_validators)
    validators = []
    for _, pk in keypairs:
        wc = b"\x00" + sha256(pk)[1:]
        validators.append(
            Validator(
                pubkey=pk,
                withdrawal_credentials=wc,
                effective_balance=spec.max_effective_balance,
                slashed=False,
                activation_eligibility_epoch=GENESIS_EPOCH,
                activation_epoch=GENESIS_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            )
        )
    state.genesis_time = genesis_time
    state.validators = validators
    state.balances = np.full(
        n_validators, spec.max_effective_balance, dtype=np.uint64
    )
    version = spec.fork_version(fork_name)
    state.fork = Fork(
        previous_version=version, current_version=version, epoch=GENESIS_EPOCH
    )
    state.eth1_data = Eth1Data(
        deposit_root=b"\x00" * 32,
        deposit_count=n_validators,
        block_hash=ETH1_BLOCK_HASH,
    )
    state.eth1_deposit_index = n_validators
    state.randao_mixes = [
        ETH1_BLOCK_HASH for _ in range(spec.preset.EPOCHS_PER_HISTORICAL_VECTOR)
    ]
    from ..types.containers import BeaconBlockHeader

    body_cls = ns.body_types[fork_name]
    state.latest_block_header = BeaconBlockHeader(
        body_root=body_cls.hash_tree_root(body_cls())
    )
    state.genesis_validators_root = _validators_root(spec, validators)

    if fork_name != "phase0":
        state.previous_epoch_participation = np.zeros(n_validators, np.uint8)
        state.current_epoch_participation = np.zeros(n_validators, np.uint8)
        state.inactivity_scores = np.zeros(n_validators, np.uint64)
        from .per_epoch import get_next_sync_committee

        sc = get_next_sync_committee(spec, state)
        state.current_sync_committee = sc
        state.next_sync_committee = get_next_sync_committee(spec, state)
    from ..types.spec import fork_at_least

    if fork_at_least(fork_name, "bellatrix"):
        # post-merge interop genesis: the execution chain starts at the mock
        # EL's genesis block so payload parent hashes link up
        # (interop.rs + mock_execution_layer genesis wiring)
        from ..execution_layer.mock import GENESIS_BLOCK_HASH

        hdr_cls = ns.payload_header_types[fork_name]
        state.latest_execution_payload_header = hdr_cls(
            block_hash=GENESIS_BLOCK_HASH,
            timestamp=genesis_time,
            prev_randao=ETH1_BLOCK_HASH,
        )
    if fork_at_least(fork_name, "electra"):
        from .common import compute_activation_exit_epoch
        from .electra import UNSET_DEPOSIT_REQUESTS_START_INDEX

        state.deposit_requests_start_index = UNSET_DEPOSIT_REQUESTS_START_INDEX
        state.earliest_exit_epoch = compute_activation_exit_epoch(
            spec, GENESIS_EPOCH
        )
        state.earliest_consolidation_epoch = compute_activation_exit_epoch(
            spec, GENESIS_EPOCH
        )
        # interop validators carry 32 ETH with 0x00 credentials: effective
        # balance ceiling is min_activation_balance, already satisfied
    return state


def _validators_root(spec: ChainSpec, validators) -> bytes:
    from ..ssz import List
    from ..types.containers import Validator

    t = List(Validator, spec.preset.VALIDATOR_REGISTRY_LIMIT)
    return t.hash_tree_root(validators)
