"""Slot processing + epoch trigger (per_slot_processing.rs:28)."""

from __future__ import annotations

from ..types.spec import ChainSpec
from .beacon_state_util import get_current_epoch, invalidate_caches


def process_slot(spec: ChainSpec, state, state_root: bytes | None = None) -> None:
    p = spec.preset
    prev_root = state_root or state.tree_root()
    state.state_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = prev_root
    if bytes(state.latest_block_header.state_root) == b"\x00" * 32:
        state.latest_block_header.state_root = prev_root
    state.block_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = (
        state.latest_block_header.tree_root()
    )


def per_slot_processing(
    spec: ChainSpec, state, state_root: bytes | None = None
) -> None:
    """Advance one slot in place (epoch processing at boundaries). The
    ``state_root`` argument lets callers skip re-hashing when they already
    know the root (state_advance.rs does the same)."""
    from .per_epoch import process_epoch

    from .upgrades import apply_fork_upgrades

    process_slot(spec, state, state_root)
    epoch_boundary = (state.slot + 1) % spec.preset.SLOTS_PER_EPOCH == 0
    if epoch_boundary:
        process_epoch(spec, state)
    state.slot += 1
    if epoch_boundary:
        # committee caches are per-epoch; they stay valid within an epoch
        # (the reference keeps prev/cur/next caches across slots)
        invalidate_caches(state)
        # fork upgrades fire exactly when the boundary enters the fork epoch
        apply_fork_upgrades(spec, state)


def process_slots(spec: ChainSpec, state, target_slot: int) -> None:
    if state.slot > target_slot:
        raise ValueError(f"state slot {state.slot} ahead of {target_slot}")
    while state.slot < target_slot:
        per_slot_processing(spec, state)
