"""Epoch processing as vectorized columnar sweeps.

Parity: ``/root/reference/consensus/state_processing/src/per_epoch_processing.rs``
and the fused O(n) sweep (``per_epoch_processing/single_pass.rs``). The
reference fuses rewards/registry/effective-balance updates into one loop over
validators; here the same fusion is numpy column arithmetic: validator fields
are gathered into uint64 arrays once, every per-validator rule is an array
expression, and results scatter back. That is the TPU-native shape — the
"sequence axis" of this framework is the validator set (SURVEY §5).
"""

from __future__ import annotations

import math

import numpy as np

from ..types.spec import ChainSpec, FAR_FUTURE_EPOCH
from .beacon_state_util import (
    get_active_validator_indices,
    get_attesting_indices,
    get_block_root,
    get_block_root_at_slot,
    get_current_epoch,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    get_total_balance,
)
from .common import balances_array, compute_activation_exit_epoch
from .per_block import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    get_base_reward_per_increment,
)

BASE_REWARDS_PER_EPOCH = 4  # phase0


class _Cols:
    """Columnar gather of the validator registry (struct-of-arrays)."""

    def __init__(self, state):
        vs = state.validators
        n = len(vs)
        self.n = n
        self.effective = np.array([v.effective_balance for v in vs], dtype=np.uint64)
        self.slashed = np.array([v.slashed for v in vs], dtype=bool)
        self.activation = np.array([v.activation_epoch for v in vs], dtype=np.uint64)
        self.exit = np.array([v.exit_epoch for v in vs], dtype=np.uint64)
        self.withdrawable = np.array(
            [v.withdrawable_epoch for v in vs], dtype=np.uint64
        )
        self.activation_eligibility = np.array(
            [v.activation_eligibility_epoch for v in vs], dtype=np.uint64
        )

    def active(self, epoch: int) -> np.ndarray:
        e = np.uint64(epoch)
        return (self.activation <= e) & (e < self.exit)


def process_epoch(spec: ChainSpec, state) -> None:
    # Backend seam (mirrors the BLS backend registry): the device epoch
    # engine owns the whole transition when selected; otherwise the columnar
    # numpy path below runs. See lighthouse_tpu/epoch_engine/.
    from ..epoch_engine import maybe_process_epoch_on_device

    if maybe_process_epoch_on_device(spec, state):
        return
    fork = getattr(state, "fork_name", "phase0")
    if fork == "phase0":
        _process_epoch_phase0(spec, state)
    else:
        _process_epoch_altair(spec, state)


# ==================================================================================
# phase0
# ==================================================================================


def _matching_attestations(spec, state, epoch: int):
    if epoch == get_current_epoch(spec, state):
        return list(state.current_epoch_attestations)
    if epoch == get_previous_epoch(spec, state):
        return list(state.previous_epoch_attestations)
    raise ValueError("epoch out of range")


def _matching_target_attestations(spec, state, epoch: int):
    root = get_block_root(spec, state, epoch)
    return [
        a
        for a in _matching_attestations(spec, state, epoch)
        if bytes(a.data.target.root) == bytes(root)
    ]


def _matching_head_attestations(spec, state, epoch: int):
    return [
        a
        for a in _matching_target_attestations(spec, state, epoch)
        if bytes(a.data.beacon_block_root)
        == bytes(get_block_root_at_slot(spec, state, a.data.slot))
    ]


def _attesting_mask(spec, state, attestations, cols: _Cols) -> np.ndarray:
    mask = np.zeros(cols.n, dtype=bool)
    for a in attestations:
        idx = get_attesting_indices(spec, state, a.data, a.aggregation_bits)
        mask[idx.astype(np.int64)] = True
    return mask & ~cols.slashed


def _unslashed_attesting_balance(spec, cols: _Cols, mask: np.ndarray) -> int:
    return max(
        spec.effective_balance_increment, int(cols.effective[mask].sum())
    )


def _process_epoch_phase0(spec: ChainSpec, state) -> None:
    # the field loops below mutate validators without journaling; a bound
    # device mirror must re-gather on its next sync
    from ..epoch_engine import invalidate_registry_journal

    invalidate_registry_journal(state)
    cols = _Cols(state)
    process_justification_and_finalization_phase0(spec, state, cols)
    process_rewards_and_penalties_phase0(spec, state, cols)
    process_registry_updates(spec, state, cols)
    process_slashings(spec, state, cols)
    process_eth1_data_reset(spec, state)
    process_effective_balance_updates(spec, state)
    process_slashings_reset(spec, state)
    process_randao_mixes_reset(spec, state)
    process_historical_roots_update(spec, state)
    # participation record rotation
    state.previous_epoch_attestations = list(state.current_epoch_attestations)
    state.current_epoch_attestations = []


def process_justification_and_finalization_phase0(spec, state, cols: _Cols):
    if get_current_epoch(spec, state) <= 1:
        return
    prev_ep, cur_ep = get_previous_epoch(spec, state), get_current_epoch(spec, state)
    total = get_total_active_balance(spec, state)
    prev_target = _unslashed_attesting_balance(
        spec, cols,
        _attesting_mask(
            spec, state, _matching_target_attestations(spec, state, prev_ep), cols
        ),
    )
    cur_target = _unslashed_attesting_balance(
        spec, cols,
        _attesting_mask(
            spec, state, _matching_target_attestations(spec, state, cur_ep), cols
        ),
    )
    _weigh_justification_and_finalization(
        spec, state, total, prev_target, cur_target
    )


def _weigh_justification_and_finalization(
    spec, state, total_balance, prev_target_balance, cur_target_balance
):
    from ..types.containers import Checkpoint

    prev_ep, cur_ep = get_previous_epoch(spec, state), get_current_epoch(spec, state)
    old_prev = state.previous_justified_checkpoint
    old_cur = state.current_justified_checkpoint

    state.previous_justified_checkpoint = state.current_justified_checkpoint
    bits = np.asarray(state.justification_bits, dtype=bool).copy()
    bits[1:] = bits[:-1]
    bits[0] = False
    if prev_target_balance * 3 >= total_balance * 2:
        state.current_justified_checkpoint = Checkpoint(
            epoch=prev_ep, root=get_block_root(spec, state, prev_ep)
        )
        bits[1] = True
    if cur_target_balance * 3 >= total_balance * 2:
        state.current_justified_checkpoint = Checkpoint(
            epoch=cur_ep, root=get_block_root(spec, state, cur_ep)
        )
        bits[0] = True
    state.justification_bits = bits

    # finalization rules
    if bits[1:4].all() and old_prev.epoch + 3 == cur_ep:
        state.finalized_checkpoint = old_prev
    if bits[1:3].all() and old_prev.epoch + 2 == cur_ep:
        state.finalized_checkpoint = old_prev
    if bits[0:3].all() and old_cur.epoch + 2 == cur_ep:
        state.finalized_checkpoint = old_cur
    if bits[0:2].all() and old_cur.epoch + 1 == cur_ep:
        state.finalized_checkpoint = old_cur


def _base_reward_phase0(spec, cols: _Cols, total_balance: int) -> np.ndarray:
    sqrt_total = math.isqrt(total_balance)
    return (
        cols.effective
        * np.uint64(spec.base_reward_factor)
        // np.uint64(sqrt_total)
        // np.uint64(BASE_REWARDS_PER_EPOCH)
    )


def process_rewards_and_penalties_phase0(spec, state, cols: _Cols):
    if get_current_epoch(spec, state) == 0:
        return
    prev_ep = get_previous_epoch(spec, state)
    total = get_total_active_balance(spec, state)
    base = _base_reward_phase0(spec, cols, total)

    src_atts = _matching_attestations(spec, state, prev_ep)
    tgt_atts = _matching_target_attestations(spec, state, prev_ep)
    head_atts = _matching_head_attestations(spec, state, prev_ep)
    src_mask = _attesting_mask(spec, state, src_atts, cols)
    tgt_mask = _attesting_mask(spec, state, tgt_atts, cols)
    head_mask = _attesting_mask(spec, state, head_atts, cols)

    eligible = cols.active(prev_ep) | (
        cols.slashed & (np.uint64(prev_ep + 1) < cols.withdrawable)
    )

    rewards = np.zeros(cols.n, dtype=np.uint64)
    penalties = np.zeros(cols.n, dtype=np.uint64)

    finality_delay = prev_ep - state.finalized_checkpoint.epoch
    in_inactivity_leak = finality_delay > spec.min_epochs_to_inactivity_penalty

    for mask, att_balance in (
        (src_mask, _unslashed_attesting_balance(spec, cols, src_mask)),
        (tgt_mask, _unslashed_attesting_balance(spec, cols, tgt_mask)),
        (head_mask, _unslashed_attesting_balance(spec, cols, head_mask)),
    ):
        attesters = eligible & mask
        non_attesters = eligible & ~mask
        if in_inactivity_leak:
            rewards[attesters] += base[attesters]
        else:
            increments = att_balance // spec.effective_balance_increment
            total_increments = total // spec.effective_balance_increment
            rewards[attesters] += (
                base[attesters] * np.uint64(increments) // np.uint64(total_increments)
            )
        penalties[non_attesters] += base[non_attesters]

    # proposer & inclusion-delay micro-rewards (earliest inclusion per attester)
    earliest: dict[int, tuple[int, int]] = {}
    for a in src_atts:
        idx = get_attesting_indices(spec, state, a.data, a.aggregation_bits)
        for i in idx:
            i = int(i)
            cand = (int(a.inclusion_delay), int(a.proposer_index))
            if i not in earliest or cand[0] < earliest[i][0]:
                earliest[i] = cand
    for i, (delay, proposer) in earliest.items():
        if cols.slashed[i]:
            continue
        proposer_reward = int(base[i]) // spec.proposer_reward_quotient
        rewards[proposer] += np.uint64(proposer_reward)
        max_attester_reward = int(base[i]) - proposer_reward
        rewards[i] += np.uint64(max_attester_reward // delay)

    if in_inactivity_leak:
        # spec get_inactivity_penalty_deltas: every eligible validator pays
        # BASE_REWARDS_PER_EPOCH * base - proposer_reward; non-target
        # attesters additionally pay the quadratic leak penalty.
        penalties[eligible] += (
            np.uint64(BASE_REWARDS_PER_EPOCH) * base[eligible]
            - base[eligible] // np.uint64(spec.proposer_reward_quotient)
        )
        not_tgt = eligible & ~tgt_mask
        penalties[not_tgt] += (
            cols.effective[not_tgt]
            * np.uint64(finality_delay)
            // np.uint64(spec.inactivity_penalty_quotient)
        )

    bal = balances_array(state)
    bal += rewards
    dec = np.minimum(penalties, bal)
    bal -= dec


def process_registry_updates(spec, state, cols: _Cols):
    from ..types.spec import fork_at_least

    electra = fork_at_least(getattr(state, "fork_name", "phase0"), "electra")
    cur = get_current_epoch(spec, state)
    # eligibility: electra keys on MIN_ACTIVATION_BALANCE (EIP-7251)
    for i, v in enumerate(state.validators):
        eligible = (
            v.effective_balance >= spec.min_activation_balance
            if electra
            else v.effective_balance == spec.max_effective_balance
        )
        if v.activation_eligibility_epoch == FAR_FUTURE_EPOCH and eligible:
            v.activation_eligibility_epoch = cur + 1
        if (
            (cols.activation[i] <= np.uint64(cur) < cols.exit[i])
            and v.effective_balance <= spec.ejection_balance
        ):
            from .common import initiate_validator_exit

            initiate_validator_exit(spec, state, i)
    # activation queue, FIFO by (eligibility epoch, index), churn-limited
    queue = sorted(
        (
            i
            for i, v in enumerate(state.validators)
            if v.activation_eligibility_epoch <= state.finalized_checkpoint.epoch
            and v.activation_epoch == FAR_FUTURE_EPOCH
        ),
        key=lambda i: (state.validators[i].activation_eligibility_epoch, i),
    )
    from .common import get_validator_activation_churn_limit

    # electra: activations are throttled by the pending-deposit balance
    # churn instead of a head-count limit here (EIP-7251)
    limit = None if electra else get_validator_activation_churn_limit(spec, state)
    for i in queue[:limit]:
        state.validators[i].activation_epoch = compute_activation_exit_epoch(
            spec, cur
        )


def process_slashings(spec, state, cols: _Cols):
    from ..types.spec import proportional_slashing_multiplier_for

    cur = get_current_epoch(spec, state)
    total = get_total_active_balance(spec, state)
    fork = getattr(state, "fork_name", "phase0")
    mult = proportional_slashing_multiplier_for(spec, fork)
    slash_sum = int(np.asarray(state.slashings, dtype=np.uint64).sum())
    adjusted = min(slash_sum * mult, total)
    target_wd = np.uint64(cur + spec.preset.EPOCHS_PER_SLASHINGS_VECTOR // 2)
    hit = cols.slashed & (cols.withdrawable == target_wd)
    if not hit.any():
        return
    increment = spec.effective_balance_increment
    from ..types.spec import fork_at_least

    if fork_at_least(fork, "electra"):
        # EIP-7251 overflow-safe form: per-increment penalty first
        per_increment = np.uint64(adjusted // (total // increment))
        penalty = cols.effective[hit] // np.uint64(increment) * per_increment
    else:
        penalty_numer = (
            cols.effective[hit] // np.uint64(increment) * np.uint64(adjusted)
        )
        penalty = penalty_numer // np.uint64(total) * np.uint64(increment)
    bal = balances_array(state)
    idx = np.nonzero(hit)[0]
    dec = np.minimum(penalty, bal[idx])
    bal[idx] -= dec


def process_eth1_data_reset(spec, state):
    next_ep = get_current_epoch(spec, state) + 1
    if next_ep % spec.preset.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        state.eth1_data_votes = []


def process_effective_balance_updates(spec, state):
    HYSTERESIS_QUOTIENT = 4
    HYSTERESIS_DOWNWARD_MULTIPLIER = 1
    HYSTERESIS_UPWARD_MULTIPLIER = 5
    increment = spec.effective_balance_increment
    hysteresis = increment // HYSTERESIS_QUOTIENT
    down = hysteresis * HYSTERESIS_DOWNWARD_MULTIPLIER
    up = hysteresis * HYSTERESIS_UPWARD_MULTIPLIER
    from ..types.spec import fork_at_least

    electra = fork_at_least(getattr(state, "fork_name", "phase0"), "electra")
    if electra:
        from .electra import get_max_effective_balance

    bal = balances_array(state)
    for i, v in enumerate(state.validators):
        b = int(bal[i])
        if b + down < v.effective_balance or v.effective_balance + up < b:
            limit = (
                get_max_effective_balance(spec, v)
                if electra
                else spec.max_effective_balance
            )
            v.effective_balance = min(b - b % increment, limit)


def process_slashings_reset(spec, state):
    next_ep = get_current_epoch(spec, state) + 1
    state.slashings[next_ep % spec.preset.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(spec, state):
    cur = get_current_epoch(spec, state)
    next_ep = cur + 1
    p = spec.preset
    state.randao_mixes[next_ep % p.EPOCHS_PER_HISTORICAL_VECTOR] = get_randao_mix(
        spec, state, cur
    )


def process_historical_roots_update(spec, state):
    next_ep = get_current_epoch(spec, state) + 1
    p = spec.preset
    if next_ep % (p.SLOTS_PER_HISTORICAL_ROOT // p.SLOTS_PER_EPOCH) == 0:
        from ..types.containers import for_preset

        ns = for_preset(spec.preset.name)
        from ..types.spec import fork_at_least

        if fork_at_least(getattr(state, "fork_name", "phase0"), "capella"):
            # capella: accumulate summaries instead of batch roots
            from ..types.containers import HistoricalSummary
            from ..ssz import Vector
            from ..types.containers import Root

            br = Vector(Root, p.SLOTS_PER_HISTORICAL_ROOT)
            state.historical_summaries = list(state.historical_summaries) + [
                HistoricalSummary(
                    block_summary_root=br.hash_tree_root(list(state.block_roots)),
                    state_summary_root=br.hash_tree_root(list(state.state_roots)),
                )
            ]
            return
        batch = ns.HistoricalBatch(
            block_roots=list(state.block_roots),
            state_roots=list(state.state_roots),
        )
        state.historical_roots = list(state.historical_roots) + [batch.tree_root()]


# ==================================================================================
# altair
# ==================================================================================


def _participation_cols(state):
    prev = np.asarray(state.previous_epoch_participation, dtype=np.uint8)
    cur = np.asarray(state.current_epoch_participation, dtype=np.uint8)
    return prev, cur


def _process_epoch_altair(spec: ChainSpec, state) -> None:
    from ..epoch_engine import invalidate_registry_journal

    invalidate_registry_journal(state)
    cols = _Cols(state)
    process_justification_and_finalization_altair(spec, state, cols)
    process_inactivity_updates(spec, state, cols)
    process_rewards_and_penalties_altair(spec, state, cols)
    process_registry_updates(spec, state, cols)
    process_slashings(spec, state, cols)
    process_eth1_data_reset(spec, state)
    from ..types.spec import fork_at_least

    if fork_at_least(getattr(state, "fork_name", "altair"), "electra"):
        from .electra import (
            process_pending_consolidations,
            process_pending_deposits,
        )

        process_pending_deposits(spec, state)
        process_pending_consolidations(spec, state)
    process_effective_balance_updates(spec, state)
    process_slashings_reset(spec, state)
    process_randao_mixes_reset(spec, state)
    process_historical_roots_update(spec, state)
    process_participation_flag_updates(spec, state)
    process_sync_committee_updates(spec, state)


def _unslashed_participating_mask(spec, state, cols, flag_index: int, epoch: int):
    prev, cur = _participation_cols(state)
    part = cur if epoch == get_current_epoch(spec, state) else prev
    has_flag = (part & np.uint8(1 << flag_index)) != 0
    return cols.active(epoch) & has_flag & ~cols.slashed


def process_justification_and_finalization_altair(spec, state, cols):
    if get_current_epoch(spec, state) <= 1:
        return
    prev_ep, cur_ep = get_previous_epoch(spec, state), get_current_epoch(spec, state)
    total = get_total_active_balance(spec, state)
    prev_mask = _unslashed_participating_mask(
        spec, state, cols, TIMELY_TARGET_FLAG_INDEX, prev_ep
    )
    cur_mask = _unslashed_participating_mask(
        spec, state, cols, TIMELY_TARGET_FLAG_INDEX, cur_ep
    )
    prev_bal = max(
        spec.effective_balance_increment, int(cols.effective[prev_mask].sum())
    )
    cur_bal = max(
        spec.effective_balance_increment, int(cols.effective[cur_mask].sum())
    )
    _weigh_justification_and_finalization(spec, state, total, prev_bal, cur_bal)


def process_inactivity_updates(spec, state, cols):
    if get_current_epoch(spec, state) == 0:
        return
    prev_ep = get_previous_epoch(spec, state)
    scores = np.asarray(state.inactivity_scores, dtype=np.uint64).copy()
    eligible = cols.active(prev_ep) | (
        cols.slashed & (np.uint64(prev_ep + 1) < cols.withdrawable)
    )
    target_mask = _unslashed_participating_mask(
        spec, state, cols, TIMELY_TARGET_FLAG_INDEX, prev_ep
    )
    finality_delay = prev_ep - state.finalized_checkpoint.epoch
    is_leak = finality_delay > spec.min_epochs_to_inactivity_penalty

    inc = eligible & target_mask
    scores[inc] -= np.minimum(np.uint64(1), scores[inc])
    notinc = eligible & ~target_mask
    scores[notinc] += np.uint64(spec.inactivity_score_bias)
    if not is_leak:
        dec = np.minimum(np.uint64(spec.inactivity_score_recovery_rate), scores)
        scores[eligible] -= dec[eligible]
    state.inactivity_scores = scores


def process_rewards_and_penalties_altair(spec, state, cols):
    if get_current_epoch(spec, state) == 0:
        return
    prev_ep = get_previous_epoch(spec, state)
    total = get_total_active_balance(spec, state)
    total_increments = total // spec.effective_balance_increment
    per_inc = get_base_reward_per_increment(spec, state)
    base = (
        cols.effective // np.uint64(spec.effective_balance_increment)
    ) * np.uint64(per_inc)

    eligible = cols.active(prev_ep) | (
        cols.slashed & (np.uint64(prev_ep + 1) < cols.withdrawable)
    )
    finality_delay = prev_ep - state.finalized_checkpoint.epoch
    is_leak = finality_delay > spec.min_epochs_to_inactivity_penalty

    rewards = np.zeros(cols.n, dtype=np.uint64)
    penalties = np.zeros(cols.n, dtype=np.uint64)

    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        mask = _unslashed_participating_mask(spec, state, cols, flag_index, prev_ep)
        flag_balance = max(
            spec.effective_balance_increment, int(cols.effective[mask].sum())
        )
        flag_increments = flag_balance // spec.effective_balance_increment
        attesters = eligible & mask
        if not is_leak:
            numer = base[attesters] * np.uint64(weight * flag_increments)
            rewards[attesters] += numer // np.uint64(
                total_increments * WEIGHT_DENOMINATOR
            )
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            non = eligible & ~mask
            penalties[non] += (
                base[non] * np.uint64(weight) // np.uint64(WEIGHT_DENOMINATOR)
            )

    # inactivity penalties (altair formula)
    target_mask = _unslashed_participating_mask(
        spec, state, cols, TIMELY_TARGET_FLAG_INDEX, prev_ep
    )
    scores = np.asarray(state.inactivity_scores, dtype=np.uint64)
    non_target = eligible & ~target_mask
    numer = cols.effective[non_target] * scores[non_target]
    denom = np.uint64(
        spec.inactivity_score_bias * spec.inactivity_penalty_quotient_altair
    )
    penalties[non_target] += numer // denom

    bal = balances_array(state)
    bal += rewards
    dec = np.minimum(penalties, bal)
    bal -= dec


def process_participation_flag_updates(spec, state):
    state.previous_epoch_participation = np.asarray(
        state.current_epoch_participation, dtype=np.uint8
    ).copy()
    state.current_epoch_participation = np.zeros(
        len(state.validators), dtype=np.uint8
    )


def process_sync_committee_updates(spec, state):
    next_ep = get_current_epoch(spec, state) + 1
    if next_ep % spec.preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(spec, state)


def get_next_sync_committee(spec, state):
    """Effective-balance-weighted sync committee sampling + aggregate pubkey
    (altair spec get_next_sync_committee)."""
    from ..ssz.sha256 import sha256
    from ..types.containers import for_preset
    from ..ops.bls_oracle import ciphersuite as cs
    from ..ops.bls_oracle import curves as oc
    from .beacon_state_util import get_seed

    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state) + 1
    active = get_active_validator_indices(state, epoch)
    seed = get_seed(spec, state, epoch, spec.DOMAIN_SYNC_COMMITTEE)
    from ..ops.shuffle import compute_shuffled_index

    indices = []
    i = 0
    MAX_RANDOM_BYTE = 255
    while len(indices) < spec.preset.SYNC_COMMITTEE_SIZE:
        shuffled = compute_shuffled_index(
            i % active.size, active.size, seed, spec.preset.SHUFFLE_ROUND_COUNT
        )
        candidate = int(active[shuffled])
        random_byte = sha256(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eb = state.validators[candidate].effective_balance
        if eb * MAX_RANDOM_BYTE >= spec.max_effective_balance * random_byte:
            indices.append(candidate)
        i += 1
    pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
    agg = None
    for pk in pubkeys:
        agg = oc.g1_add(agg, oc.g1_decompress(pk))
    return ns.SyncCommittee(
        pubkeys=pubkeys, aggregate_pubkey=oc.g1_compress(agg)
    )
