"""Block processing: header, randao, eth1 data, operations, sync aggregate.

Parity: ``/root/reference/consensus/state_processing/src/per_block_processing.rs:100-196``
with ``BlockSignatureStrategy`` (``:125-145``) and the bulk signature collector
(``block_signature_verifier.rs:127-396``): under VerifyBulk every signature in
the block lands in ONE ``bls.verify_signature_sets`` batch — the TPU-friendly
path. Operations parity: ``per_block_processing/process_operations.rs``.
"""

from __future__ import annotations

import enum
import functools

import numpy as np

from .. import bls
from ..ssz.merkle import next_pow2
from ..ssz.sha256 import sha256
from ..types.helpers import (
    compute_signing_root, get_domain, is_active_validator,
    is_slashable_attestation_data, is_slashable_validator,
)
from ..types.spec import ChainSpec, FAR_FUTURE_EPOCH, fork_at_least
from . import signature_sets as sigs
from .beacon_state_util import (
    StateTransitionError,
    get_attesting_indices,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_current_epoch,
    get_indexed_attestation,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    invalidate_caches,
)
from .common import (
    decrease_balance,
    get_validator_churn_limit,
    increase_balance,
    initiate_validator_exit,
    slash_validator,
)

# altair participation flag indices / weights
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = [
    TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT, TIMELY_HEAD_WEIGHT,
]


class BlockProcessingError(StateTransitionError):
    pass


class BlockSignatureStrategy(enum.Enum):
    NO_VERIFICATION = "no_verification"
    VERIFY_INDIVIDUAL = "verify_individual"
    VERIFY_BULK = "verify_bulk"
    VERIFY_RANDAO = "verify_randao"


class ConsensusContext:
    """Memoizes proposer index / block root across pipeline stages
    (consensus_context.rs:12)."""

    def __init__(self):
        self.proposer_index: int | None = None
        self.block_root: bytes | None = None
        self.indexed_attestations: dict = {}
        # optional pubkey-bytes -> validator-index lookup (the chain threads
        # its ValidatorPubkeyCache.get_index here to avoid O(n) registry scans)
        self.get_pubkey_index = None

    def lookup_pubkey_index(self, state, pk: bytes) -> int | None:
        """Resolve a pubkey to its index in *this* state (cache hit must be
        bounded by the state's registry and byte-verified — indices are
        append-ordered so cross-fork caches stay consistent)."""
        if self.get_pubkey_index is not None:
            idx = self.get_pubkey_index(pk)
            if (
                idx is not None
                and idx < len(state.validators)
                and bytes(state.validators[idx].pubkey) == pk
            ):
                return idx
            return None
        for i, v in enumerate(state.validators):
            if bytes(v.pubkey) == pk:
                return i
        return None

    def get_proposer_index(self, spec, state) -> int:
        if self.proposer_index is None:
            self.proposer_index = get_beacon_proposer_index(spec, state)
        return self.proposer_index


class BlockSignatureVerifier:
    """Collects every block signature into one batch
    (block_signature_verifier.rs:127-396)."""

    def __init__(self, spec: ChainSpec, state, get_pubkey=None):
        self.spec = spec
        self.state = state
        self.get_pubkey = get_pubkey
        self.sets: list = []

    def include_all_signatures(self, signed_block, ctxt: ConsensusContext):
        self.include_block_proposal(signed_block)
        self.include_all_signatures_except_proposal(signed_block, ctxt)

    def include_all_signatures_except_proposal(self, signed_block, ctxt):
        block = signed_block.message
        self.include_randao_reveal(block)
        self.include_proposer_slashings(block)
        self.include_attester_slashings(block)
        self.include_attestations(block, ctxt)
        self.include_exits(block)
        self.include_sync_aggregate(block)
        self.include_bls_to_execution_changes(block)

    def include_block_proposal(self, signed_block):
        self.sets.append(
            sigs.block_proposal_signature_set(
                self.spec, self.state, signed_block, get_pubkey=self.get_pubkey
            )
        )

    def include_randao_reveal(self, block):
        self.sets.append(
            sigs.randao_signature_set(
                self.spec, self.state, block.proposer_index,
                self.spec.compute_epoch_at_slot(block.slot),
                block.body.randao_reveal, self.get_pubkey,
            )
        )

    def include_proposer_slashings(self, block):
        for sl in block.body.proposer_slashings:
            self.sets.extend(
                sigs.proposer_slashing_signature_sets(
                    self.spec, self.state, sl, self.get_pubkey
                )
            )

    def include_attester_slashings(self, block):
        for sl in block.body.attester_slashings:
            for indexed in (sl.attestation_1, sl.attestation_2):
                self.sets.append(
                    sigs.indexed_attestation_signature_set(
                        self.spec, self.state, indexed, self.get_pubkey
                    )
                )

    def include_attestations(self, block, ctxt: ConsensusContext):
        for i, att in enumerate(block.body.attestations):
            indexed = get_indexed_attestation(self.spec, self.state, att)
            ctxt.indexed_attestations[i] = indexed
            self.sets.append(
                sigs.indexed_attestation_signature_set(
                    self.spec, self.state, indexed, self.get_pubkey
                )
            )

    def include_exits(self, block):
        for ex in block.body.voluntary_exits:
            self.sets.append(
                sigs.exit_signature_set(self.spec, self.state, ex, self.get_pubkey)
            )

    def include_bls_to_execution_changes(self, block):
        for ch in getattr(block.body, "bls_to_execution_changes", []):
            self.sets.append(
                sigs.bls_to_execution_change_signature_set(
                    self.spec, self.state, ch
                )
            )

    def include_sync_aggregate(self, block):
        agg = getattr(block.body, "sync_aggregate", None)
        if agg is None:
            return
        s = sync_aggregate_signature_set(
            self.spec, self.state, block.slot, agg, self.get_pubkey
        )
        if s is not None:
            self.sets.append(s)

    def verify(self) -> None:
        if not bls.verify_signature_sets(self.sets):
            raise BlockProcessingError("bulk signature verification failed")


def sync_aggregate_signature_set(spec, state, block_slot, agg, get_pubkey=None):
    """Signature set for the sync committee aggregate: signs the previous
    slot's block root with the sync-committee domain. None when no bits set
    (infinity signature allowed iff zero participants)."""
    bits = np.asarray(agg.sync_committee_bits, dtype=bool)
    sig = bls.Signature.from_bytes(bytes(agg.sync_committee_signature))
    if not bits.any():
        if sig.point is None:
            return None
        raise BlockProcessingError("non-infinity sync signature with no bits")
    previous_slot = max(int(block_slot), 1) - 1
    domain = get_domain(
        spec, state, spec.DOMAIN_SYNC_COMMITTEE,
        epoch=spec.compute_epoch_at_slot(previous_slot),
    )
    from ..ssz import ByteVector
    from ..types.containers import SigningData

    root = SigningData(
        object_root=get_block_root_at_slot(spec, state, previous_slot),
        domain=domain,
    ).tree_root()
    keys = []
    for i, bit in enumerate(bits):
        if bit:
            pk_bytes = bytes(state.current_sync_committee.pubkeys[i])
            keys.append(bls.PublicKey.from_bytes(pk_bytes))
    return bls.SignatureSet.multiple_pubkeys(sig, keys, root)


# -------------------------------------------------------------------------------
# Top-level entry (per_block_processing.rs:100)
# -------------------------------------------------------------------------------


def per_block_processing(
    spec: ChainSpec,
    state,
    signed_block,
    strategy: BlockSignatureStrategy = BlockSignatureStrategy.VERIFY_BULK,
    ctxt: ConsensusContext | None = None,
    get_pubkey=None,
    verify_block_root: bool = True,
) -> ConsensusContext:
    ctxt = ctxt or ConsensusContext()
    block = signed_block.message

    if strategy == BlockSignatureStrategy.VERIFY_BULK:
        v = BlockSignatureVerifier(spec, state, get_pubkey)
        v.include_all_signatures(signed_block, ctxt)
        v.verify()
        inner = "none"
    elif strategy == BlockSignatureStrategy.VERIFY_INDIVIDUAL:
        if not bls.verify_signature_sets(
            [sigs.block_proposal_signature_set(spec, state, signed_block, get_pubkey=get_pubkey)]
        ):
            raise BlockProcessingError("invalid proposer signature")
        inner = "individual"
    elif strategy == BlockSignatureStrategy.VERIFY_RANDAO:
        inner = "randao"
    else:
        inner = "none"

    process_block_header(spec, state, block, ctxt)
    fork = getattr(state, "fork_name", "phase0")
    commitments = getattr(block.body, "blob_kzg_commitments", None)
    if commitments is not None and len(commitments) > spec.preset.MAX_BLOBS_PER_BLOCK:
        raise BlockProcessingError(
            f"{len(commitments)} blob commitments exceeds "
            f"MAX_BLOBS_PER_BLOCK {spec.preset.MAX_BLOBS_PER_BLOCK}"
        )
    payload = getattr(block.body, "execution_payload", None)
    if payload is not None and is_execution_enabled(state, payload):
        if fork_at_least(fork, "capella"):
            process_withdrawals(spec, state, payload)
        # EL notify_new_payload happens at the chain layer
        # (block_verification.rs ExecutionPendingBlock); here only the
        # consensus-consistency checks + header update run.
        process_execution_payload(spec, state, payload)
    process_randao(spec, state, block, verify=(inner in ("individual", "randao")))
    process_eth1_data(spec, state, block.body)
    process_operations(spec, state, block.body, ctxt, verify=(inner == "individual"))
    agg = getattr(block.body, "sync_aggregate", None)
    if agg is not None:
        process_sync_aggregate(
            spec, state, block.slot, agg, verify=(inner == "individual"),
            ctxt=ctxt,
        )
    if verify_block_root:
        sr = state.tree_root()
        if bytes(block.state_root) != sr:
            raise BlockProcessingError(
                f"state root mismatch: block {bytes(block.state_root).hex()[:16]} "
                f"!= computed {sr.hex()[:16]}"
            )
    return ctxt


def process_block_header(spec, state, block, ctxt: ConsensusContext):
    if block.slot != state.slot:
        raise BlockProcessingError("block slot != state slot")
    if block.slot <= state.latest_block_header.slot:
        raise BlockProcessingError("block not newer than latest header")
    expected = ctxt.get_proposer_index(spec, state)
    if block.proposer_index != expected:
        raise BlockProcessingError(
            f"wrong proposer {block.proposer_index} != {expected}"
        )
    if bytes(block.parent_root) != state.latest_block_header.tree_root():
        raise BlockProcessingError("parent root mismatch")
    from ..types.containers import BeaconBlockHeader

    state.latest_block_header = BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=b"\x00" * 32,
        body_root=type(block.body).hash_tree_root(block.body),
    )
    proposer = state.validators[block.proposer_index]
    if proposer.slashed:
        raise BlockProcessingError("proposer slashed")


def process_randao(spec, state, block, verify: bool):
    epoch = get_current_epoch(spec, state)
    if verify:
        s = sigs.randao_signature_set(
            spec, state, block.proposer_index, epoch, block.body.randao_reveal
        )
        if not bls.verify_signature_sets([s]):
            raise BlockProcessingError("invalid randao reveal")
    mix = bytes(
        a ^ b
        for a, b in zip(
            get_randao_mix(spec, state, epoch),
            sha256(bytes(block.body.randao_reveal)),
        )
    )
    state.randao_mixes[epoch % spec.preset.EPOCHS_PER_HISTORICAL_VECTOR] = mix


def process_eth1_data(spec, state, body):
    state.eth1_data_votes = list(state.eth1_data_votes) + [body.eth1_data]
    period = spec.preset.slots_per_eth1_voting_period
    count = sum(1 for v in state.eth1_data_votes if v == body.eth1_data)
    if count * 2 > period:
        state.eth1_data = body.eth1_data


# -------------------------------------------------------------------------------
# Operations (process_operations.rs)
# -------------------------------------------------------------------------------


def process_operations(spec, state, body, ctxt: ConsensusContext, verify: bool):
    electra = fork_at_least(getattr(state, "fork_name", "phase0"), "electra")
    if electra:
        # EIP-6110: legacy eth1 deposits stop at deposit_requests_start_index
        limit = min(
            int(state.eth1_data.deposit_count),
            int(state.deposit_requests_start_index),
        )
        if int(state.eth1_deposit_index) < limit:
            expected_deposits = min(
                spec.preset.MAX_DEPOSITS, limit - int(state.eth1_deposit_index)
            )
        else:
            expected_deposits = 0
    else:
        expected_deposits = min(
            spec.preset.MAX_DEPOSITS,
            state.eth1_data.deposit_count - state.eth1_deposit_index,
        )
    if len(body.deposits) != expected_deposits:
        raise BlockProcessingError(
            f"expected {expected_deposits} deposits, block has {len(body.deposits)}"
        )
    for sl in body.proposer_slashings:
        process_proposer_slashing(spec, state, sl, ctxt, verify)
    for sl in body.attester_slashings:
        process_attester_slashing(spec, state, sl, verify)
    for i, att in enumerate(body.attestations):
        process_attestation(spec, state, att, i, ctxt, verify)
    for dep in body.deposits:
        process_deposit(spec, state, dep, ctxt)
    for ex in body.voluntary_exits:
        process_exit(spec, state, ex, verify)
    for change in getattr(body, "bls_to_execution_changes", []):
        process_bls_to_execution_change(spec, state, change, verify)
    requests = getattr(body, "execution_requests", None)
    if requests is not None:
        from .electra import (
            process_consolidation_request,
            process_deposit_request,
            process_withdrawal_request,
        )

        for dr in requests.deposits:
            process_deposit_request(spec, state, dr)
        for wr in requests.withdrawals:
            process_withdrawal_request(spec, state, wr, ctxt)
        for cr in requests.consolidations:
            process_consolidation_request(spec, state, cr, ctxt)


# -- execution payloads (bellatrix+) ---------------------------------------------


@functools.lru_cache(maxsize=None)
def _default_tree_root(cls) -> bytes:
    return cls().tree_root()


@functools.lru_cache(maxsize=None)
def _default_encoding(cls) -> bytes:
    return cls.encode(cls())


def is_merge_transition_complete(state) -> bool:
    hdr = getattr(state, "latest_execution_payload_header", None)
    if hdr is None:
        return False
    return hdr.tree_root() != _default_tree_root(type(hdr))


def payload_is_default(payload) -> bool:
    return type(payload).encode(payload) == _default_encoding(type(payload))


def is_execution_enabled(state, payload) -> bool:
    """Bellatrix is_execution_enabled: post-merge, or this IS the merge
    transition block (non-default payload on a pre-merge state)."""
    return is_merge_transition_complete(state) or not payload_is_default(payload)


def compute_timestamp_at_slot(spec, state, slot: int) -> int:
    return int(state.genesis_time) + slot * spec.preset.SECONDS_PER_SLOT


def process_execution_payload(spec, state, payload) -> None:
    """Consensus-side payload checks + header update (bellatrix
    process_execution_payload minus the engine call, which the chain layer
    performs — the reference's split between per_block_processing.rs:100 and
    block_verification.rs ExecutionPendingBlock)."""
    from .beacon_state_util import get_current_epoch, get_randao_mix

    if is_merge_transition_complete(state):
        if bytes(payload.parent_hash) != bytes(
            state.latest_execution_payload_header.block_hash
        ):
            raise BlockProcessingError("payload parent hash mismatch")
    if bytes(payload.prev_randao) != get_randao_mix(
        spec, state, get_current_epoch(spec, state)
    ):
        raise BlockProcessingError("payload prev_randao mismatch")
    if int(payload.timestamp) != compute_timestamp_at_slot(spec, state, state.slot):
        raise BlockProcessingError("payload timestamp mismatch")

    from ..types.containers import for_preset
    from ..ssz import List as SSZList

    ns = for_preset(spec.preset.name)
    fork = getattr(state, "fork_name", "bellatrix")
    hdr_cls = ns.payload_header_types[fork]
    payload_cls = ns.payload_types[fork]
    tx_type = dict(payload_cls.FIELDS)["transactions"]
    fields = {
        n: getattr(payload, n)
        for n, _ in payload_cls.FIELDS
        if n not in ("transactions", "withdrawals")
    }
    fields["transactions_root"] = tx_type.hash_tree_root(payload.transactions)
    if hasattr(payload, "withdrawals"):
        w_type = dict(payload_cls.FIELDS)["withdrawals"]
        fields["withdrawals_root"] = w_type.hash_tree_root(payload.withdrawals)
    state.latest_execution_payload_header = hdr_cls(**fields)


# -- withdrawals (capella+) --------------------------------------------------------


def has_eth1_withdrawal_credential(validator) -> bool:
    return bytes(validator.withdrawal_credentials)[:1] == b"\x01"


def is_fully_withdrawable_validator(
    validator, balance: int, epoch: int, electra: bool = False
) -> bool:
    if electra:
        from .electra import has_execution_withdrawal_credential

        cred_ok = has_execution_withdrawal_credential(validator)
    else:
        cred_ok = has_eth1_withdrawal_credential(validator)
    return cred_ok and validator.withdrawable_epoch <= epoch and balance > 0


def is_partially_withdrawable_validator(
    spec, validator, balance: int, electra: bool = False
) -> bool:
    if electra:
        from .electra import (
            get_max_effective_balance,
            has_execution_withdrawal_credential,
        )

        max_eb = get_max_effective_balance(spec, validator)
        return (
            has_execution_withdrawal_credential(validator)
            and int(validator.effective_balance) == max_eb
            and balance > max_eb
        )
    return (
        has_eth1_withdrawal_credential(validator)
        and validator.effective_balance == spec.max_effective_balance
        and balance > spec.max_effective_balance
    )


def get_expected_withdrawals(spec, state):
    """Withdrawal sweep. Capella: full/partial sweep only. Electra adds the
    pending-partial-withdrawal queue ahead of the sweep (EIP-7251) and
    credential-dependent effective-balance ceilings.

    Always returns ``(withdrawals, processed_partials)`` — the second
    element is 0 before electra.
    """
    from ..types.containers import Withdrawal
    from .beacon_state_util import get_current_epoch

    electra = fork_at_least(getattr(state, "fork_name", "phase0"), "electra")
    epoch = get_current_epoch(spec, state)
    widx = int(state.next_withdrawal_index)
    vidx = int(state.next_withdrawal_validator_index)
    n = len(state.validators)
    out = []
    processed_partials = 0

    if electra:
        from .electra import has_execution_withdrawal_credential

        for w in state.pending_partial_withdrawals:
            if (
                int(w.withdrawable_epoch) > epoch
                or len(out)
                == spec.preset.MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP
            ):
                break
            i = int(w.validator_index)
            v = state.validators[i]
            ok = (
                v.exit_epoch == FAR_FUTURE_EPOCH
                and int(v.effective_balance) >= spec.min_activation_balance
                and int(state.balances[i]) > spec.min_activation_balance
            )
            if ok:
                amount = min(
                    int(state.balances[i]) - spec.min_activation_balance,
                    int(w.amount),
                )
                out.append(
                    Withdrawal(
                        index=widx, validator_index=i,
                        address=bytes(v.withdrawal_credentials)[12:],
                        amount=amount,
                    )
                )
                widx += 1
            processed_partials += 1

    for _ in range(min(n, spec.preset.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)):
        v = state.validators[vidx]
        # balances already claimed by the partial stage don't double-count
        already = sum(
            int(w.amount) for w in out if int(w.validator_index) == vidx
        )
        balance = int(state.balances[vidx]) - already
        address = bytes(v.withdrawal_credentials)[12:]
        if electra:
            from .electra import get_max_effective_balance

            max_eb = get_max_effective_balance(spec, v)
        else:
            max_eb = spec.max_effective_balance
        if is_fully_withdrawable_validator(v, balance, epoch, electra=electra):
            out.append(
                Withdrawal(
                    index=widx, validator_index=vidx, address=address,
                    amount=balance,
                )
            )
            widx += 1
        elif is_partially_withdrawable_validator(
            spec, v, balance, electra=electra
        ):
            out.append(
                Withdrawal(
                    index=widx, validator_index=vidx, address=address,
                    amount=balance - max_eb,
                )
            )
            widx += 1
        if len(out) == spec.preset.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        vidx = (vidx + 1) % n
    return out, processed_partials


def _expected_withdrawals_list(spec, state) -> list:
    return get_expected_withdrawals(spec, state)[0]


def process_withdrawals(spec, state, payload) -> None:
    from .common import decrease_balance

    expected, processed_partials = get_expected_withdrawals(spec, state)
    if processed_partials:
        state.pending_partial_withdrawals = list(
            state.pending_partial_withdrawals
        )[processed_partials:]
    got = list(payload.withdrawals)
    if len(got) != len(expected) or any(
        type(a).encode(a) != type(b).encode(b) for a, b in zip(got, expected)
    ):
        raise BlockProcessingError("payload withdrawals != expected sweep")
    for w in expected:
        decrease_balance(state, int(w.validator_index), int(w.amount))
    n = len(state.validators)
    if expected:
        state.next_withdrawal_index = int(expected[-1].index) + 1
    if len(expected) == spec.preset.MAX_WITHDRAWALS_PER_PAYLOAD:
        state.next_withdrawal_validator_index = (
            int(expected[-1].validator_index) + 1
        ) % n
    else:
        state.next_withdrawal_validator_index = (
            int(state.next_withdrawal_validator_index)
            + spec.preset.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP
        ) % n


def process_bls_to_execution_change(spec, state, signed_change, verify: bool):
    """Capella BLS->execution credential rotation. Signature semantics
    (GENESIS fork domain) live in the shared set constructor
    (signature_sets.bls_to_execution_change_signature_set)."""
    import hashlib as _hashlib

    msg = signed_change.message
    idx = int(msg.validator_index)
    if idx >= len(state.validators):
        raise BlockProcessingError("bls change: unknown validator")
    v = state.validators[idx]
    creds = bytes(v.withdrawal_credentials)
    if creds[:1] != b"\x00":
        raise BlockProcessingError("bls change: not a BLS credential")
    if creds[1:] != _hashlib.sha256(bytes(msg.from_bls_pubkey)).digest()[1:]:
        raise BlockProcessingError("bls change: pubkey does not match credential")
    if verify:
        s = sigs.bls_to_execution_change_signature_set(spec, state, signed_change)
        if not bls.verify_signature_sets([s]):
            raise BlockProcessingError("bls change: invalid signature")
    v.withdrawal_credentials = (
        b"\x01" + b"\x00" * 11 + bytes(msg.to_execution_address)
    )


def process_proposer_slashing(spec, state, slashing, ctxt, verify: bool):
    h1 = slashing.signed_header_1.message
    h2 = slashing.signed_header_2.message
    if h1.slot != h2.slot:
        raise BlockProcessingError("proposer slashing: slots differ")
    if h1.proposer_index != h2.proposer_index:
        raise BlockProcessingError("proposer slashing: proposers differ")
    if h1 == h2:
        raise BlockProcessingError("proposer slashing: identical headers")
    proposer = state.validators[h1.proposer_index]
    if not is_slashable_validator(proposer, get_current_epoch(spec, state)):
        raise BlockProcessingError("proposer not slashable")
    if verify:
        for s in sigs.proposer_slashing_signature_sets(spec, state, slashing):
            if not bls.verify_signature_sets([s]):
                raise BlockProcessingError("proposer slashing: bad signature")
    slash_validator(spec, state, h1.proposer_index)


def is_valid_indexed_attestation(spec, state, indexed, verify: bool) -> bool:
    idx = list(indexed.attesting_indices)
    if not idx or idx != sorted(set(int(i) for i in idx)):
        return False
    if any(int(i) >= len(state.validators) for i in idx):
        return False
    if verify:
        s = sigs.indexed_attestation_signature_set(spec, state, indexed)
        return bls.verify_signature_sets([s])
    return True


def process_attester_slashing(spec, state, slashing, verify: bool):
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        raise BlockProcessingError("attestations not slashable")
    for a in (a1, a2):
        if not is_valid_indexed_attestation(spec, state, a, verify):
            raise BlockProcessingError("invalid indexed attestation")
    slashed_any = False
    cur = get_current_epoch(spec, state)
    common = sorted(
        set(int(i) for i in a1.attesting_indices)
        & set(int(i) for i in a2.attesting_indices)
    )
    for index in common:
        if is_slashable_validator(state.validators[index], cur):
            slash_validator(spec, state, index)
            slashed_any = True
    if not slashed_any:
        raise BlockProcessingError("no validators slashed")


def _validate_attestation_common(spec, state, data):
    if data.target.epoch not in (
        get_previous_epoch(spec, state), get_current_epoch(spec, state)
    ):
        raise BlockProcessingError("attestation target epoch out of range")
    if data.target.epoch != spec.compute_epoch_at_slot(data.slot):
        raise BlockProcessingError("attestation target/slot mismatch")
    if data.slot + spec.min_attestation_inclusion_delay > state.slot:
        raise BlockProcessingError("attestation outside inclusion window")
    # EIP-7045 (deneb) removed the one-epoch inclusion upper bound; the
    # target-epoch range check above is the only recency constraint since
    if not fork_at_least(getattr(state, "fork_name", "phase0"), "deneb"):
        if state.slot > data.slot + spec.preset.SLOTS_PER_EPOCH:
            raise BlockProcessingError("attestation outside inclusion window")
    if data.index >= get_committee_count_per_slot(spec, state, data.target.epoch):
        # electra attestations carry index 0 and pass trivially; the real
        # committee bound is checked against committee_bits by the caller
        raise BlockProcessingError("committee index out of range")


def process_attestation(spec, state, attestation, att_index, ctxt, verify: bool):
    data = attestation.data
    _validate_attestation_common(spec, state, data)
    if hasattr(attestation, "committee_bits"):
        # EIP-7549: data.index must be zero; committee structure rides in
        # committee_bits, aggregation bits span the slot's committees
        from .electra import get_committee_indices

        if int(data.index) != 0:
            raise BlockProcessingError("electra attestation: nonzero data.index")
        committee_indices = get_committee_indices(attestation.committee_bits)
        per_slot = get_committee_count_per_slot(spec, state, data.target.epoch)
        if not committee_indices:
            raise BlockProcessingError("electra attestation: no committee bits")
        if any(ci >= per_slot for ci in committee_indices):
            raise BlockProcessingError("electra attestation: committee oob")
        bits = np.asarray(attestation.aggregation_bits, dtype=bool)
        total = sum(
            get_beacon_committee(spec, state, data.slot, ci).size
            for ci in committee_indices
        )
        if bits.size != total:
            raise BlockProcessingError(
                "electra attestation: aggregation bits != committee sizes"
            )
    else:
        committee = get_beacon_committee(spec, state, data.slot, data.index)
        bits = np.asarray(attestation.aggregation_bits, dtype=bool)
        if bits.size != committee.size:
            raise BlockProcessingError("aggregation bits != committee size")

    indexed = ctxt.indexed_attestations.get(att_index)
    if indexed is None:
        indexed = get_indexed_attestation(spec, state, attestation)
    if not is_valid_indexed_attestation(spec, state, indexed, verify):
        raise BlockProcessingError("invalid attestation")

    if getattr(state, "fork_name", "phase0") == "phase0":
        _process_attestation_phase0(spec, state, attestation, data, ctxt)
    else:
        _process_attestation_altair(spec, state, data, indexed, ctxt)


def _process_attestation_phase0(spec, state, attestation, data, ctxt):
    from ..types.containers import for_preset

    ns = for_preset(spec.preset.name)
    pending = ns.PendingAttestation(
        aggregation_bits=attestation.aggregation_bits,
        data=data,
        inclusion_delay=state.slot - data.slot,
        proposer_index=ctxt.get_proposer_index(spec, state),
    )
    if data.target.epoch == get_current_epoch(spec, state):
        if data.source != state.current_justified_checkpoint:
            raise BlockProcessingError("attestation source != current justified")
        state.current_epoch_attestations = list(
            state.current_epoch_attestations
        ) + [pending]
    else:
        if data.source != state.previous_justified_checkpoint:
            raise BlockProcessingError("attestation source != previous justified")
        state.previous_epoch_attestations = list(
            state.previous_epoch_attestations
        ) + [pending]


def get_attestation_participation_flag_indices(spec, state, data, inclusion_delay):
    justified = (
        state.current_justified_checkpoint
        if data.target.epoch == get_current_epoch(spec, state)
        else state.previous_justified_checkpoint
    )
    is_matching_source = data.source == justified
    if not is_matching_source:
        raise BlockProcessingError("attestation source mismatch")
    is_matching_target = is_matching_source and bytes(data.target.root) == bytes(
        get_block_root(spec, state, data.target.epoch)
    )
    is_matching_head = is_matching_target and bytes(
        data.beacon_block_root
    ) == bytes(get_block_root_at_slot(spec, state, data.slot))
    flags = []
    sqrt_epoch = _integer_sqrt(spec.preset.SLOTS_PER_EPOCH)
    if is_matching_source and inclusion_delay <= sqrt_epoch:
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= spec.preset.SLOTS_PER_EPOCH:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == spec.min_attestation_inclusion_delay:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def _integer_sqrt(n: int) -> int:
    import math

    return math.isqrt(n)


def _process_attestation_altair(spec, state, data, indexed, ctxt):
    inclusion_delay = state.slot - data.slot
    flag_indices = get_attestation_participation_flag_indices(
        spec, state, data, inclusion_delay
    )
    epoch_participation = (
        state.current_epoch_participation
        if data.target.epoch == get_current_epoch(spec, state)
        else state.previous_epoch_participation
    )
    if not isinstance(epoch_participation, np.ndarray):
        epoch_participation = np.asarray(epoch_participation, dtype=np.uint8)
    total_base = get_base_reward_per_increment(spec, state)
    proposer_reward_numerator = 0
    for index in indexed.attesting_indices:
        index = int(index)
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            has = bool(epoch_participation[index] & (1 << flag_index))
            if flag_index in flag_indices and not has:
                epoch_participation[index] |= np.uint8(1 << flag_index)
                proposer_reward_numerator += (
                    get_base_reward_altair(spec, state, index, total_base) * weight
                )
    if data.target.epoch == get_current_epoch(spec, state):
        state.current_epoch_participation = epoch_participation
    else:
        state.previous_epoch_participation = epoch_participation
    denom = (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT) * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT
    increase_balance(
        state, ctxt.get_proposer_index(spec, state),
        proposer_reward_numerator // denom,
    )


def get_base_reward_per_increment(spec, state) -> int:
    return (
        spec.effective_balance_increment
        * spec.base_reward_factor
        // _integer_sqrt(get_total_active_balance(spec, state))
    )


def get_base_reward_altair(spec, state, index: int, per_increment: int) -> int:
    increments = (
        state.validators[index].effective_balance
        // spec.effective_balance_increment
    )
    return increments * per_increment


def is_valid_merkle_branch(leaf, branch, depth, index, root) -> bool:
    value = bytes(leaf)
    for i in range(depth):
        b = bytes(branch[i])
        if (index >> i) & 1:
            value = sha256(b + value)
        else:
            value = sha256(value + b)
    return value == bytes(root)


def process_deposit(spec, state, deposit, ctxt: ConsensusContext | None = None):
    from ..types.containers import DepositData

    if not is_valid_merkle_branch(
        DepositData.hash_tree_root(deposit.data),
        deposit.proof,
        32 + 1,  # DEPOSIT_CONTRACT_TREE_DEPTH + 1 (mix-in of count)
        state.eth1_deposit_index,
        state.eth1_data.deposit_root,
    ):
        raise BlockProcessingError("invalid deposit merkle proof")
    state.eth1_deposit_index += 1
    apply_deposit(spec, state, deposit.data, ctxt=ctxt)


def apply_deposit(spec, state, data, check_signature: bool = True, ctxt=None):
    pk = bytes(data.pubkey)
    index = (ctxt or ConsensusContext()).lookup_pubkey_index(state, pk)
    if fork_at_least(getattr(state, "fork_name", "phase0"), "electra"):
        # EIP-7251: every deposit flows through the pending queue; new keys
        # join the registry immediately with zero balance
        from ..types.containers import for_preset

        ns = for_preset(spec.preset.name)
        if index is None:
            if check_signature and not sigs.deposit_signature_is_valid(spec, data):
                return
            add_validator_to_registry(spec, state, data, amount_override=0)
        state.pending_deposits = list(state.pending_deposits) + [
            ns.PendingDeposit(
                pubkey=pk,
                withdrawal_credentials=bytes(data.withdrawal_credentials),
                amount=int(data.amount),
                signature=bytes(data.signature),
                slot=0,  # GENESIS_SLOT: eth1-bridge deposits are pre-finalized
            )
        ]
        return
    if index is None:
        if check_signature and not sigs.deposit_signature_is_valid(spec, data):
            return  # invalid deposit signature: skipped, not fatal
        add_validator_to_registry(spec, state, data)
    else:
        increase_balance(state, index, data.amount)


def add_validator_to_registry(spec, state, data, amount_override=None):
    from ..types.containers import Validator

    amount = int(data.amount) if amount_override is None else amount_override
    if fork_at_least(getattr(state, "fork_name", "phase0"), "electra"):
        from .electra import COMPOUNDING_WITHDRAWAL_PREFIX

        max_eff = (
            spec.max_effective_balance_electra
            if bytes(data.withdrawal_credentials)[:1] == COMPOUNDING_WITHDRAWAL_PREFIX
            else spec.min_activation_balance
        )
    else:
        max_eff = spec.max_effective_balance
    effective = min(
        amount - amount % spec.effective_balance_increment, max_eff
    )
    state.validators = list(state.validators) + [
        Validator(
            pubkey=data.pubkey,
            withdrawal_credentials=data.withdrawal_credentials,
            effective_balance=effective,
            slashed=False,
            activation_eligibility_epoch=FAR_FUTURE_EPOCH,
            activation_epoch=FAR_FUTURE_EPOCH,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
    ]
    from ..epoch_engine import mark_registry_delta

    mark_registry_delta(state, len(state.validators) - 1)
    state.balances = np.concatenate(
        [np.asarray(state.balances, dtype=np.uint64), [np.uint64(amount)]]
    )
    if getattr(state, "fork_name", "phase0") != "phase0":
        state.previous_epoch_participation = np.concatenate(
            [np.asarray(state.previous_epoch_participation, np.uint8), [0]]
        )
        state.current_epoch_participation = np.concatenate(
            [np.asarray(state.current_epoch_participation, np.uint8), [0]]
        )
        state.inactivity_scores = np.concatenate(
            [np.asarray(state.inactivity_scores, np.uint64), [0]]
        )


def process_exit(spec, state, signed_exit, verify: bool):
    exit_msg = signed_exit.message
    v = state.validators[exit_msg.validator_index]
    cur = get_current_epoch(spec, state)
    if not is_active_validator(v, cur):
        raise BlockProcessingError("exit: validator not active")
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        raise BlockProcessingError("exit: already exiting")
    if cur < exit_msg.epoch:
        raise BlockProcessingError("exit: not yet valid")
    if cur < v.activation_epoch + spec.shard_committee_period:
        raise BlockProcessingError("exit: too young")
    if fork_at_least(getattr(state, "fork_name", "phase0"), "electra"):
        from .electra import get_pending_balance_to_withdraw

        if get_pending_balance_to_withdraw(state, int(exit_msg.validator_index)):
            raise BlockProcessingError("exit: pending partial withdrawals")
    if verify:
        s = sigs.exit_signature_set(spec, state, signed_exit)
        if not bls.verify_signature_sets([s]):
            raise BlockProcessingError("exit: bad signature")
    initiate_validator_exit(spec, state, exit_msg.validator_index)


# -------------------------------------------------------------------------------
# Sync aggregate (altair)
# -------------------------------------------------------------------------------


def process_sync_aggregate(spec, state, block_slot, agg, verify: bool, ctxt=None):
    if verify:
        s = sync_aggregate_signature_set(spec, state, block_slot, agg)
        if s is not None and not bls.verify_signature_sets([s]):
            raise BlockProcessingError("invalid sync aggregate signature")
    total_base = get_base_reward_per_increment(spec, state)
    total_active_increments = (
        get_total_active_balance(spec, state) // spec.effective_balance_increment
    )
    max_total_reward = (
        total_base * total_active_increments * SYNC_REWARD_WEIGHT
        // WEIGHT_DENOMINATOR
    )
    participant_reward = max_total_reward // spec.preset.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward * PROPOSER_WEIGHT // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )
    proposer_index = get_beacon_proposer_index(spec, state)
    pubkeys = [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    lookup = ctxt or ConsensusContext()
    if lookup.get_pubkey_index is None:
        # one O(n) build amortized over the committee, not per deposit
        table = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
        resolve = table.__getitem__
    else:
        resolve = lambda pk: lookup.lookup_pubkey_index(state, pk)
    bits = np.asarray(agg.sync_committee_bits, dtype=bool)
    for i, bit in enumerate(bits):
        participant_index = resolve(pubkeys[i])
        if bit:
            increase_balance(state, participant_index, participant_reward)
            increase_balance(state, proposer_index, proposer_reward)
        else:
            decrease_balance(state, participant_index, participant_reward)
