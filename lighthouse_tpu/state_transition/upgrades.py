"""Fork-boundary state upgrades (bellatrix, capella).

Twin of ``consensus/state_processing/src/upgrade/{bellatrix,capella}.rs``.
Upgrades mutate IN PLACE by swapping the container class and adding the new
fork's fields — every holder of the state reference sees the upgraded state,
matching the mutate-in-place convention of the rest of the transition code.
"""

from __future__ import annotations

from ..types.containers import Fork, for_preset
from .per_block import BlockProcessingError
from ..types.spec import ChainSpec
from .beacon_state_util import get_current_epoch, invalidate_caches


def upgrade_to_altair(spec: ChainSpec, state) -> None:
    """phase0 -> altair: participation flags + sync committees; previous-epoch
    pending attestations are translated into participation flags
    (upgrade/altair.rs translate_participation)."""
    import numpy as np

    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    n = len(state.validators)
    pending = list(state.previous_epoch_attestations)

    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.altair_fork_version,
        epoch=epoch,
    )
    del state.previous_epoch_attestations
    del state.current_epoch_attestations
    state.__class__ = ns.BeaconStateAltair
    state.previous_epoch_participation = np.zeros(n, np.uint8)
    state.current_epoch_participation = np.zeros(n, np.uint8)
    state.inactivity_scores = np.zeros(n, np.uint64)
    invalidate_caches(state)

    # translate_participation: replay pending attestations as flag sets
    from .beacon_state_util import get_beacon_committee
    from .per_block import get_attestation_participation_flag_indices

    for att in pending:
        try:
            flag_indices = get_attestation_participation_flag_indices(
                spec, state, att.data, int(att.inclusion_delay)
            )
        except BlockProcessingError:
            continue  # source no longer matches after the boundary: no flags
        committee = get_beacon_committee(
            spec, state, int(att.data.slot), int(att.data.index)
        )
        bits = np.asarray(att.aggregation_bits, dtype=bool)
        for pos, vi in enumerate(committee):
            if pos < len(bits) and bits[pos]:
                for fi in flag_indices:
                    state.previous_epoch_participation[int(vi)] |= np.uint8(1 << fi)

    from .per_epoch import get_next_sync_committee

    state.current_sync_committee = get_next_sync_committee(spec, state)
    state.next_sync_committee = get_next_sync_committee(spec, state)


def upgrade_to_bellatrix(spec: ChainSpec, state) -> None:
    """altair -> bellatrix: default execution payload header (pre-merge)."""
    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.bellatrix_fork_version,
        epoch=epoch,
    )
    state.__class__ = ns.BeaconStateBellatrix
    state.latest_execution_payload_header = ns.ExecutionPayloadHeaderBellatrix()
    invalidate_caches(state)


def upgrade_to_capella(spec: ChainSpec, state) -> None:
    """bellatrix -> capella: withdrawals bookkeeping + header gains
    withdrawals_root + historical accumulation switches to summaries."""
    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.capella_fork_version,
        epoch=epoch,
    )
    old = state.latest_execution_payload_header
    new_hdr = ns.ExecutionPayloadHeaderCapella(
        **{n: getattr(old, n) for n, _ in type(old).FIELDS}
    )
    state.__class__ = ns.BeaconStateCapella
    state.latest_execution_payload_header = new_hdr
    state.next_withdrawal_index = 0
    state.next_withdrawal_validator_index = 0
    state.historical_summaries = []
    invalidate_caches(state)


def upgrade_to_deneb(spec: ChainSpec, state) -> None:
    """capella -> deneb: payload header gains blob-gas fields
    (upgrade/deneb.rs)."""
    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.deneb_fork_version,
        epoch=epoch,
    )
    old = state.latest_execution_payload_header
    new_hdr = ns.ExecutionPayloadHeaderDeneb(
        **{n: getattr(old, n) for n, _ in type(old).FIELDS}
    )
    state.__class__ = ns.BeaconStateDeneb
    state.latest_execution_payload_header = new_hdr
    invalidate_caches(state)


UPGRADES = {
    "altair": upgrade_to_altair,
    "bellatrix": upgrade_to_bellatrix,
    "capella": upgrade_to_capella,
    "deneb": upgrade_to_deneb,
}

_FORK_RANK = {f: i for i, f in enumerate(["phase0", *UPGRADES])}


def apply_fork_upgrades(spec: ChainSpec, state) -> None:
    """Run any upgrade scheduled exactly at the state's current epoch
    (called by process_slots right after crossing an epoch boundary).
    Upgrades apply strictly in fork order from the state's CURRENT fork, so a
    later upgrade can never fire on a state missing earlier forks' fields."""
    epoch = get_current_epoch(spec, state)
    for fork, fn in UPGRADES.items():
        if (
            spec.fork_epoch(fork) == epoch
            and _FORK_RANK[getattr(state, "fork_name", "phase0")]
            == _FORK_RANK[fork] - 1
        ):
            fn(spec, state)
