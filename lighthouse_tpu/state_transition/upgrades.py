"""Fork-boundary state upgrades (bellatrix, capella).

Twin of ``consensus/state_processing/src/upgrade/{bellatrix,capella}.rs``.
Upgrades mutate IN PLACE by swapping the container class and adding the new
fork's fields — every holder of the state reference sees the upgraded state,
matching the mutate-in-place convention of the rest of the transition code.
"""

from __future__ import annotations

from ..types.containers import Fork, for_preset
from .per_block import BlockProcessingError
from ..types.spec import ChainSpec
from .beacon_state_util import get_current_epoch, invalidate_caches


def upgrade_to_altair(spec: ChainSpec, state) -> None:
    """phase0 -> altair: participation flags + sync committees; previous-epoch
    pending attestations are translated into participation flags
    (upgrade/altair.rs translate_participation)."""
    import numpy as np

    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    n = len(state.validators)
    pending = list(state.previous_epoch_attestations)

    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.altair_fork_version,
        epoch=epoch,
    )
    del state.previous_epoch_attestations
    del state.current_epoch_attestations
    state.__class__ = ns.BeaconStateAltair
    state.previous_epoch_participation = np.zeros(n, np.uint8)
    state.current_epoch_participation = np.zeros(n, np.uint8)
    state.inactivity_scores = np.zeros(n, np.uint64)
    invalidate_caches(state)

    # translate_participation: replay pending attestations as flag sets
    from .beacon_state_util import get_beacon_committee
    from .per_block import get_attestation_participation_flag_indices

    for att in pending:
        try:
            flag_indices = get_attestation_participation_flag_indices(
                spec, state, att.data, int(att.inclusion_delay)
            )
        except BlockProcessingError:
            continue  # source no longer matches after the boundary: no flags
        committee = get_beacon_committee(
            spec, state, int(att.data.slot), int(att.data.index)
        )
        bits = np.asarray(att.aggregation_bits, dtype=bool)
        for pos, vi in enumerate(committee):
            if pos < len(bits) and bits[pos]:
                for fi in flag_indices:
                    state.previous_epoch_participation[int(vi)] |= np.uint8(1 << fi)

    from .per_epoch import get_next_sync_committee

    state.current_sync_committee = get_next_sync_committee(spec, state)
    state.next_sync_committee = get_next_sync_committee(spec, state)


def upgrade_to_bellatrix(spec: ChainSpec, state) -> None:
    """altair -> bellatrix: default execution payload header (pre-merge)."""
    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.bellatrix_fork_version,
        epoch=epoch,
    )
    state.__class__ = ns.BeaconStateBellatrix
    state.latest_execution_payload_header = ns.ExecutionPayloadHeaderBellatrix()
    invalidate_caches(state)


def upgrade_to_capella(spec: ChainSpec, state) -> None:
    """bellatrix -> capella: withdrawals bookkeeping + header gains
    withdrawals_root + historical accumulation switches to summaries."""
    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.capella_fork_version,
        epoch=epoch,
    )
    old = state.latest_execution_payload_header
    new_hdr = ns.ExecutionPayloadHeaderCapella(
        **{n: getattr(old, n) for n, _ in type(old).FIELDS}
    )
    state.__class__ = ns.BeaconStateCapella
    state.latest_execution_payload_header = new_hdr
    state.next_withdrawal_index = 0
    state.next_withdrawal_validator_index = 0
    state.historical_summaries = []
    invalidate_caches(state)


def upgrade_to_deneb(spec: ChainSpec, state) -> None:
    """capella -> deneb: payload header gains blob-gas fields
    (upgrade/deneb.rs)."""
    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.deneb_fork_version,
        epoch=epoch,
    )
    old = state.latest_execution_payload_header
    new_hdr = ns.ExecutionPayloadHeaderDeneb(
        **{n: getattr(old, n) for n, _ in type(old).FIELDS}
    )
    state.__class__ = ns.BeaconStateDeneb
    state.latest_execution_payload_header = new_hdr
    invalidate_caches(state)


def upgrade_to_electra(spec: ChainSpec, state) -> None:
    """deneb -> electra (upgrade/electra.rs): balance-churn bookkeeping,
    pre-activation validators re-queued as pending deposits, compounding
    early adopters get their excess balance queued."""
    from .common import FAR_FUTURE_EPOCH, compute_activation_exit_epoch
    from .electra import (
        G2_POINT_AT_INFINITY,
        UNSET_DEPOSIT_REQUESTS_START_INDEX,
        get_activation_exit_churn_limit,
        get_consolidation_churn_limit,
        has_compounding_withdrawal_credential,
        queue_excess_active_balance,
    )

    ns = for_preset(spec.preset.name)
    epoch = get_current_epoch(spec, state)
    state.fork = Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=spec.electra_fork_version,
        epoch=epoch,
    )
    earliest_exit = compute_activation_exit_epoch(spec, epoch)
    for v in state.validators:
        if v.exit_epoch != FAR_FUTURE_EPOCH:
            earliest_exit = max(earliest_exit, int(v.exit_epoch))
    earliest_exit += 1

    state.__class__ = ns.BeaconStateElectra
    state.deposit_requests_start_index = UNSET_DEPOSIT_REQUESTS_START_INDEX
    state.deposit_balance_to_consume = 0
    state.exit_balance_to_consume = 0
    state.earliest_exit_epoch = earliest_exit
    state.consolidation_balance_to_consume = 0
    state.earliest_consolidation_epoch = compute_activation_exit_epoch(spec, epoch)
    state.pending_deposits = []
    state.pending_partial_withdrawals = []
    state.pending_consolidations = []
    invalidate_caches(state)
    state.exit_balance_to_consume = get_activation_exit_churn_limit(spec, state)
    state.consolidation_balance_to_consume = get_consolidation_churn_limit(
        spec, state
    )

    # re-queue validators that had not activated as pending deposits
    pre_activation = sorted(
        (
            i
            for i, v in enumerate(state.validators)
            if v.activation_epoch == FAR_FUTURE_EPOCH
        ),
        key=lambda i: (
            int(state.validators[i].activation_eligibility_epoch),
            i,
        ),
    )
    for i in pre_activation:
        v = state.validators[i]
        balance = int(state.balances[i])
        state.balances[i] = 0
        v.effective_balance = 0
        v.activation_eligibility_epoch = FAR_FUTURE_EPOCH
        state.pending_deposits = list(state.pending_deposits) + [
            ns.PendingDeposit(
                pubkey=bytes(v.pubkey),
                withdrawal_credentials=bytes(v.withdrawal_credentials),
                amount=balance,
                signature=G2_POINT_AT_INFINITY,
                slot=0,
            )
        ]
    # early compounding adopters keep their excess working
    for i, v in enumerate(state.validators):
        if has_compounding_withdrawal_credential(v):
            queue_excess_active_balance(spec, state, i)


UPGRADES = {
    "altair": upgrade_to_altair,
    "bellatrix": upgrade_to_bellatrix,
    "capella": upgrade_to_capella,
    "deneb": upgrade_to_deneb,
    "electra": upgrade_to_electra,
}

_FORK_RANK = {f: i for i, f in enumerate(["phase0", *UPGRADES])}


def apply_fork_upgrades(spec: ChainSpec, state) -> None:
    """Run any upgrade scheduled exactly at the state's current epoch
    (called by process_slots right after crossing an epoch boundary).
    Upgrades apply strictly in fork order from the state's CURRENT fork, so a
    later upgrade can never fire on a state missing earlier forks' fields."""
    epoch = get_current_epoch(spec, state)
    for fork, fn in UPGRADES.items():
        if (
            spec.fork_epoch(fork) == epoch
            and _FORK_RANK[getattr(state, "fork_name", "phase0")]
            == _FORK_RANK[fork] - 1
        ):
            fn(spec, state)
            # upgrades mutate registry fields (and the kernel fork family)
            # without journaling — force a full mirror re-gather
            from ..epoch_engine import invalidate_registry_journal

            invalidate_registry_journal(state)
