"""Electra state-transition pieces (EIP-6110/7002/7251/7549).

Twin of the reference's electra modules in ``consensus/state_processing``
(process_operations.rs request handlers, single_pass.rs pending-deposit /
consolidation sweeps, upgrade/electra.rs). Balance-denominated churn
replaces validator-count churn; deposits flow through an in-state pending
queue; withdrawals and consolidations arrive as execution-layer requests.
"""

from __future__ import annotations

import numpy as np

from ..types.spec import ChainSpec, FAR_FUTURE_EPOCH
from .beacon_state_util import get_current_epoch, get_total_active_balance
from .common import (
    compute_activation_exit_epoch,
    decrease_balance,
    increase_balance,
)

UNSET_DEPOSIT_REQUESTS_START_INDEX = 2**64 - 1
FULL_EXIT_REQUEST_AMOUNT = 0
G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95
COMPOUNDING_WITHDRAWAL_PREFIX = b"\x02"
ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"


# -- credential / balance helpers -------------------------------------------------


def has_compounding_withdrawal_credential(validator) -> bool:
    return bytes(validator.withdrawal_credentials)[:1] == COMPOUNDING_WITHDRAWAL_PREFIX


def has_eth1_withdrawal_credential(validator) -> bool:
    from .per_block import has_eth1_withdrawal_credential as _impl

    return _impl(validator)


def has_execution_withdrawal_credential(validator) -> bool:
    return has_compounding_withdrawal_credential(validator) or (
        has_eth1_withdrawal_credential(validator)
    )


def get_max_effective_balance(spec: ChainSpec, validator) -> int:
    if has_compounding_withdrawal_credential(validator):
        return spec.max_effective_balance_electra
    return spec.min_activation_balance


def get_pending_balance_to_withdraw(state, validator_index: int) -> int:
    return sum(
        int(w.amount)
        for w in state.pending_partial_withdrawals
        if int(w.validator_index) == validator_index
    )


# -- balance-denominated churn (EIP-7251) -----------------------------------------


def get_balance_churn_limit(spec: ChainSpec, state) -> int:
    churn = max(
        spec.min_per_epoch_churn_limit_electra,
        get_total_active_balance(spec, state) // spec.churn_limit_quotient,
    )
    return churn - churn % spec.effective_balance_increment


def get_activation_exit_churn_limit(spec: ChainSpec, state) -> int:
    return min(
        spec.max_per_epoch_activation_exit_churn_limit,
        get_balance_churn_limit(spec, state),
    )


def get_consolidation_churn_limit(spec: ChainSpec, state) -> int:
    return get_balance_churn_limit(spec, state) - get_activation_exit_churn_limit(
        spec, state
    )


def compute_exit_epoch_and_update_churn(spec, state, exit_balance: int) -> int:
    earliest = max(
        int(state.earliest_exit_epoch),
        compute_activation_exit_epoch(spec, get_current_epoch(spec, state)),
    )
    per_epoch_churn = get_activation_exit_churn_limit(spec, state)
    exit_balance_to_consume = (
        per_epoch_churn
        if int(state.earliest_exit_epoch) < earliest
        else int(state.exit_balance_to_consume)
    )
    if exit_balance > exit_balance_to_consume:
        balance_to_process = exit_balance - exit_balance_to_consume
        additional_epochs = (balance_to_process - 1) // per_epoch_churn + 1
        earliest += additional_epochs
        exit_balance_to_consume += additional_epochs * per_epoch_churn
    state.exit_balance_to_consume = exit_balance_to_consume - exit_balance
    state.earliest_exit_epoch = earliest
    return earliest


def compute_consolidation_epoch_and_update_churn(
    spec, state, consolidation_balance: int
) -> int:
    earliest = max(
        int(state.earliest_consolidation_epoch),
        compute_activation_exit_epoch(spec, get_current_epoch(spec, state)),
    )
    per_epoch_churn = get_consolidation_churn_limit(spec, state)
    balance_to_consume = (
        per_epoch_churn
        if int(state.earliest_consolidation_epoch) < earliest
        else int(state.consolidation_balance_to_consume)
    )
    if consolidation_balance > balance_to_consume:
        balance_to_process = consolidation_balance - balance_to_consume
        additional_epochs = (balance_to_process - 1) // per_epoch_churn + 1
        earliest += additional_epochs
        balance_to_consume += additional_epochs * per_epoch_churn
    state.consolidation_balance_to_consume = (
        balance_to_consume - consolidation_balance
    )
    state.earliest_consolidation_epoch = earliest
    return earliest


def initiate_validator_exit_electra(spec, state, index: int) -> None:
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_queue_epoch = compute_exit_epoch_and_update_churn(
        spec, state, int(v.effective_balance)
    )
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = (
        exit_queue_epoch + spec.min_validator_withdrawability_delay
    )
    from ..epoch_engine import mark_registry_delta

    mark_registry_delta(state, index)


def queue_excess_active_balance(spec, state, index: int) -> None:
    from ..types.containers import for_preset

    ns = for_preset(spec.preset.name)
    balance = int(state.balances[index])
    if balance > spec.min_activation_balance:
        excess = balance - spec.min_activation_balance
        state.balances[index] = spec.min_activation_balance
        v = state.validators[index]
        state.pending_deposits = list(state.pending_deposits) + [
            ns.PendingDeposit(
                pubkey=bytes(v.pubkey),
                withdrawal_credentials=bytes(v.withdrawal_credentials),
                amount=excess,
                signature=G2_POINT_AT_INFINITY,
                slot=0,  # GENESIS_SLOT: exempt from finality delay
            )
        ]


def switch_to_compounding_validator(spec, state, index: int) -> None:
    v = state.validators[index]
    v.withdrawal_credentials = (
        COMPOUNDING_WITHDRAWAL_PREFIX + bytes(v.withdrawal_credentials)[1:]
    )
    # the credential prefix feeds the mirror's derived "compounding" column
    from ..epoch_engine import mark_registry_delta

    mark_registry_delta(state, index)
    queue_excess_active_balance(spec, state, index)


# -- execution-layer requests (block processing) ----------------------------------


def process_deposit_request(spec, state, request) -> None:
    """EIP-6110: deposits surface as EL receipts, queued in-state."""
    from ..types.containers import for_preset

    ns = for_preset(spec.preset.name)
    if int(state.deposit_requests_start_index) == UNSET_DEPOSIT_REQUESTS_START_INDEX:
        state.deposit_requests_start_index = int(request.index)
    state.pending_deposits = list(state.pending_deposits) + [
        ns.PendingDeposit(
            pubkey=bytes(request.pubkey),
            withdrawal_credentials=bytes(request.withdrawal_credentials),
            amount=int(request.amount),
            signature=bytes(request.signature),
            slot=int(state.slot),
        )
    ]


def process_withdrawal_request(spec, state, request, ctxt=None) -> None:
    """EIP-7002: EL-triggered (partial or full) withdrawal. Invalid
    requests are no-ops, never block failures."""
    amount = int(request.amount)
    is_full_exit = amount == FULL_EXIT_REQUEST_AMOUNT
    # partial withdrawals bounded by queue capacity
    if (
        not is_full_exit
        and len(state.pending_partial_withdrawals)
        >= spec.preset.PENDING_PARTIAL_WITHDRAWALS_LIMIT
    ):
        return
    index = _pubkey_index(state, bytes(request.validator_pubkey), ctxt)
    if index is None:
        return
    v = state.validators[index]
    # source address must own the credentials
    if not has_execution_withdrawal_credential(v):
        return
    if bytes(v.withdrawal_credentials)[12:] != bytes(request.source_address):
        return
    cur = get_current_epoch(spec, state)
    from ..types.helpers import is_active_validator

    if not is_active_validator(v, cur):
        return
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    if cur < int(v.activation_epoch) + spec.shard_committee_period:
        return

    pending_balance = get_pending_balance_to_withdraw(state, index)
    if is_full_exit:
        if pending_balance == 0:
            initiate_validator_exit_electra(spec, state, index)
        return
    has_sufficient = (
        has_compounding_withdrawal_credential(v)
        and int(v.effective_balance) >= spec.min_activation_balance
        and int(state.balances[index])
        > spec.min_activation_balance + pending_balance
    )
    if not has_sufficient:
        return
    from ..types.containers import for_preset

    ns = for_preset(spec.preset.name)
    to_withdraw = min(
        int(state.balances[index]) - spec.min_activation_balance - pending_balance,
        amount,
    )
    exit_queue_epoch = compute_exit_epoch_and_update_churn(spec, state, to_withdraw)
    withdrawable_epoch = (
        exit_queue_epoch + spec.min_validator_withdrawability_delay
    )
    state.pending_partial_withdrawals = list(state.pending_partial_withdrawals) + [
        ns.PendingPartialWithdrawal(
            validator_index=index,
            amount=to_withdraw,
            withdrawable_epoch=withdrawable_epoch,
        )
    ]


def process_consolidation_request(spec, state, request, ctxt=None) -> None:
    """EIP-7251: merge source validator's balance into target."""
    from ..types.helpers import is_active_validator

    if _is_valid_switch_to_compounding(spec, state, request, ctxt):
        index = _pubkey_index(state, bytes(request.source_pubkey), ctxt)
        switch_to_compounding_validator(spec, state, index)
        return
    # queue capacity + churn sanity
    if (
        len(state.pending_consolidations)
        >= spec.preset.PENDING_CONSOLIDATIONS_LIMIT
    ):
        return
    if get_consolidation_churn_limit(spec, state) <= spec.min_activation_balance:
        return
    source_index = _pubkey_index(state, bytes(request.source_pubkey), ctxt)
    target_index = _pubkey_index(state, bytes(request.target_pubkey), ctxt)
    if source_index is None or target_index is None or source_index == target_index:
        return
    source = state.validators[source_index]
    target = state.validators[target_index]
    if not has_execution_withdrawal_credential(source):
        return
    if not has_compounding_withdrawal_credential(target):
        return
    if bytes(source.withdrawal_credentials)[12:] != bytes(request.source_address):
        return
    cur = get_current_epoch(spec, state)
    if not is_active_validator(source, cur) or not is_active_validator(target, cur):
        return
    if source.exit_epoch != FAR_FUTURE_EPOCH or target.exit_epoch != FAR_FUTURE_EPOCH:
        return
    if cur < int(source.activation_epoch) + spec.shard_committee_period:
        return
    if get_pending_balance_to_withdraw(state, source_index) > 0:
        return

    from ..types.containers import for_preset

    ns = for_preset(spec.preset.name)
    exit_epoch = compute_consolidation_epoch_and_update_churn(
        spec, state, int(source.effective_balance)
    )
    source.exit_epoch = exit_epoch
    source.withdrawable_epoch = exit_epoch + spec.min_validator_withdrawability_delay
    from ..epoch_engine import mark_registry_delta

    mark_registry_delta(state, source_index)
    state.pending_consolidations = list(state.pending_consolidations) + [
        ns.PendingConsolidation(
            source_index=source_index, target_index=target_index
        )
    ]


def _is_valid_switch_to_compounding(spec, state, request, ctxt=None) -> bool:
    from ..types.helpers import is_active_validator

    if bytes(request.source_pubkey) != bytes(request.target_pubkey):
        return False
    index = _pubkey_index(state, bytes(request.source_pubkey), ctxt)
    if index is None:
        return False
    v = state.validators[index]
    if not has_eth1_withdrawal_credential(v):
        return False
    if bytes(v.withdrawal_credentials)[12:] != bytes(request.source_address):
        return False
    if not is_active_validator(v, get_current_epoch(spec, state)):
        return False
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return False
    return True


def _pubkey_index(state, pubkey: bytes, ctxt=None):
    if ctxt is not None:
        return ctxt.lookup_pubkey_index(state, pubkey)
    for i, v in enumerate(state.validators):
        if bytes(v.pubkey) == pubkey:
            return i
    return None


# -- pending queues (epoch processing) --------------------------------------------


def apply_pending_deposit(spec, state, deposit, ctxt=None) -> None:
    from . import signature_sets as sigs
    from .per_block import add_validator_to_registry

    index = _pubkey_index(state, bytes(deposit.pubkey), ctxt)
    if index is None:
        if sigs.deposit_signature_is_valid(spec, deposit):
            add_validator_to_registry(spec, state, deposit, amount_override=0)
            increase_balance(state, len(state.validators) - 1, int(deposit.amount))
        return
    increase_balance(state, index, int(deposit.amount))


def process_pending_deposits(spec, state, ctxt=None) -> None:
    next_epoch = get_current_epoch(spec, state) + 1
    available = int(state.deposit_balance_to_consume) + get_activation_exit_churn_limit(
        spec, state
    )
    processed_amount = 0
    next_deposit_index = 0
    deposits_to_postpone = []
    is_churn_limit_reached = False
    finalized_slot = spec.start_slot(int(state.finalized_checkpoint.epoch))

    pending = list(state.pending_deposits)
    for deposit in pending:
        # EIP-6110 transition: EL deposit requests wait until every
        # eth1-bridge deposit has been applied
        if (
            int(deposit.slot) > 0
            and int(state.eth1_deposit_index)
            < int(state.deposit_requests_start_index)
        ):
            break
        # deposits snapshotted from EL receipts wait for finality
        if int(deposit.slot) > finalized_slot:
            break
        if next_deposit_index >= spec.preset.MAX_PENDING_DEPOSITS_PER_EPOCH:
            break
        index = _pubkey_index(state, bytes(deposit.pubkey), ctxt)
        is_validator_exited = False
        is_validator_withdrawn = False
        if index is not None:
            v = state.validators[index]
            is_validator_exited = int(v.exit_epoch) < FAR_FUTURE_EPOCH
            is_validator_withdrawn = int(v.withdrawable_epoch) < next_epoch
        if is_validator_withdrawn:
            # deposited balance will simply be withdrawn again: free
            apply_pending_deposit(spec, state, deposit, ctxt)
        elif is_validator_exited:
            deposits_to_postpone.append(deposit)
        else:
            is_churn_limit_reached = (
                processed_amount + int(deposit.amount) > available
            )
            if is_churn_limit_reached:
                break
            apply_pending_deposit(spec, state, deposit, ctxt)
            processed_amount += int(deposit.amount)
        next_deposit_index += 1

    state.pending_deposits = pending[next_deposit_index:] + deposits_to_postpone
    if is_churn_limit_reached:
        state.deposit_balance_to_consume = available - processed_amount
    else:
        state.deposit_balance_to_consume = 0


def process_pending_consolidations(spec, state) -> None:
    next_epoch = get_current_epoch(spec, state) + 1
    next_index = 0
    pending = list(state.pending_consolidations)
    for consolidation in pending:
        source = state.validators[int(consolidation.source_index)]
        if source.slashed:
            next_index += 1
            continue
        if int(source.withdrawable_epoch) > next_epoch:
            break
        # move active balance; excess stays with source as withdrawable
        balance = min(
            int(state.balances[int(consolidation.source_index)]),
            int(source.effective_balance),
        )
        decrease_balance(state, int(consolidation.source_index), balance)
        increase_balance(state, int(consolidation.target_index), balance)
        next_index += 1
    state.pending_consolidations = pending[next_index:]


# -- attestations (EIP-7549) ------------------------------------------------------


def get_committee_indices(committee_bits) -> list[int]:
    return [i for i, b in enumerate(np.asarray(committee_bits, dtype=bool)) if b]


def get_attesting_indices_electra(spec, state, attestation) -> list[int]:
    """Committee-spanning aggregation bits -> attesting validator indices."""
    from .beacon_state_util import get_beacon_committee

    out = []
    bits = np.asarray(attestation.aggregation_bits, dtype=bool)
    offset = 0
    for ci in get_committee_indices(attestation.committee_bits):
        committee = get_beacon_committee(
            spec, state, int(attestation.data.slot), ci
        )
        chunk = bits[offset : offset + committee.size]
        out.extend(int(v) for v, b in zip(committee, chunk) if b)
        offset += committee.size
    return out
