"""Block replayer: re-apply a range of blocks onto a base state
(ref consensus/state_processing/src/block_replayer.rs:30-313).

Used by the freezer's replay layer (states below the finest diff cadence
are reconstructed by replaying canonical blocks from the nearest stored
anchor), historical state queries, and — later — backfill verification.
Signature verification is skipped by default (the blocks were verified at
import; replay is deterministic recomputation), matching the reference's
``no_signature_verification`` builder default for store use.
"""

from __future__ import annotations

from ..types.spec import ChainSpec
from .per_block import BlockSignatureStrategy, per_block_processing
from .per_slot import process_slots


class BlockReplayer:
    def __init__(
        self,
        spec: ChainSpec,
        state,
        verify_signatures: bool = False,
        verify_block_roots: bool = True,
    ):
        self.spec = spec
        self.state = state
        self._strategy = (
            BlockSignatureStrategy.VERIFY_BULK
            if verify_signatures
            else BlockSignatureStrategy.NO_VERIFICATION
        )
        self._verify_roots = verify_block_roots
        # state-root provider seam (block_replayer.rs state_root_iter): lets
        # callers skip recomputing known roots during slot processing
        self.state_root_provider = None

    def apply_blocks(self, blocks, target_slot: int | None = None) -> "BlockReplayer":
        for signed in blocks:
            slot = int(signed.message.slot)
            if self.state.slot < slot:
                process_slots(self.spec, self.state, slot)
            per_block_processing(
                self.spec,
                self.state,
                signed,
                strategy=self._strategy,
                verify_block_root=self._verify_roots,
            )
        if target_slot is not None and self.state.slot < target_slot:
            process_slots(self.spec, self.state, target_slot)
        return self
