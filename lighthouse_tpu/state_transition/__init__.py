"""Pure state-transition functions (consensus/state_processing twin).

Everything here is deterministic and I/O-free: ``per_slot_processing``,
``per_block_processing`` (with pluggable BlockSignatureStrategy feeding the
bls seam in batches), and epoch processing as vectorized numpy sweeps over the
validator set (the reference's single-pass design,
``per_epoch_processing/single_pass.rs``, maps to columnar array ops here).
"""

from .beacon_state_util import (
    CommitteeCache,
    get_active_validator_indices,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_current_epoch,
    get_indexed_attestation,
    get_previous_epoch,
    get_randao_mix,
    get_seed,
    get_total_active_balance,
    get_total_balance,
)
from .per_block import (
    BlockSignatureStrategy,
    BlockProcessingError,
    per_block_processing,
    process_block_header,
    process_operations,
    process_randao,
)
from .per_slot import per_slot_processing, process_slots
from .per_epoch import process_epoch
from .state_advance import complete_state_advance, partial_state_advance
