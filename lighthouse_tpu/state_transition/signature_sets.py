"""SignatureSet constructors for every consensus message type.

Parity: ``/root/reference/consensus/state_processing/src/per_block_processing/
signature_sets.rs:74-609``. Each constructor resolves pubkeys through a
``get_pubkey`` callback (the decompressed-cache seam — the chain layer passes
its ValidatorPubkeyCache lookup) and returns a ``bls.SignatureSet`` ready for
batched verification.
"""

from __future__ import annotations

from .. import bls
from ..types.helpers import compute_signing_root, get_domain
from ..types.spec import ChainSpec
from .beacon_state_util import get_indexed_attestation


class SignatureSetError(bls.BlsError):
    """Set construction failed on untrusted input (subclasses BlsError so the
    chain's block-rejection handling catches it as a clean BlockError)."""


def _pubkey(get_pubkey, state, index: int) -> bls.PublicKey:
    pk = get_pubkey(int(index)) if get_pubkey else None
    if pk is None:
        try:
            pk = bls.PublicKey.from_bytes(bytes(state.validators[int(index)].pubkey))
        except bls.BlsError as e:
            raise SignatureSetError(f"validator {index}: {e}") from None
    return pk


def _header_signature_ok(spec: ChainSpec, state, signed_header, pubkey) -> bool:
    """Proposer signature over a SignedBeaconBlockHeader (the blob-sidecar
    gossip check, blob_verification.rs verify_header_signature).

    The domain's fork version comes from the SPEC's schedule at the header's
    slot, not from ``state.fork`` — the head state can lag a fork boundary
    the header has already crossed."""
    from ..types.helpers import compute_domain

    hdr = signed_header.message
    epoch = spec.compute_epoch_at_slot(int(hdr.slot))
    version = spec.fork_version(spec.fork_name_at_epoch(epoch))
    domain = compute_domain(
        spec.DOMAIN_BEACON_PROPOSER,
        version,
        bytes(state.genesis_validators_root),
    )
    root = compute_signing_root(hdr, domain)
    try:
        sig = bls.Signature.from_bytes(bytes(signed_header.signature))
    except bls.BlsError:
        return False
    return bls.verify_signature_sets(
        [bls.SignatureSet.single_pubkey(sig, pubkey, root)]
    )


def block_proposal_signature_set(
    spec: ChainSpec, state, signed_block, block_root=None, get_pubkey=None
) -> bls.SignatureSet:
    block = signed_block.message
    domain = get_domain(
        spec, state, spec.DOMAIN_BEACON_PROPOSER,
        epoch=spec.compute_epoch_at_slot(block.slot),
    )
    root = compute_signing_root(block, domain)
    return bls.SignatureSet.single_pubkey(
        bls.Signature.from_bytes(bytes(signed_block.signature)),
        _pubkey(get_pubkey, state, block.proposer_index),
        root,
    )


def randao_signature_set(
    spec: ChainSpec, state, proposer_index: int, epoch: int, randao_reveal,
    get_pubkey=None,
) -> bls.SignatureSet:
    from ..ssz import uint64

    domain = get_domain(spec, state, spec.DOMAIN_RANDAO, epoch=epoch)
    # signing root of the epoch number itself
    from ..types.containers import SigningData

    root = SigningData(
        object_root=uint64.hash_tree_root(epoch), domain=domain
    ).tree_root()
    return bls.SignatureSet.single_pubkey(
        bls.Signature.from_bytes(bytes(randao_reveal)),
        _pubkey(get_pubkey, state, proposer_index),
        root,
    )


def proposer_slashing_signature_sets(
    spec: ChainSpec, state, slashing, get_pubkey=None
) -> list:
    sets = []
    for signed_header in (slashing.signed_header_1, slashing.signed_header_2):
        header = signed_header.message
        domain = get_domain(
            spec, state, spec.DOMAIN_BEACON_PROPOSER,
            epoch=spec.compute_epoch_at_slot(header.slot),
        )
        root = compute_signing_root(header, domain)
        sets.append(
            bls.SignatureSet.single_pubkey(
                bls.Signature.from_bytes(bytes(signed_header.signature)),
                _pubkey(get_pubkey, state, header.proposer_index),
                root,
            )
        )
    return sets


def indexed_attestation_signature_set(
    spec: ChainSpec, state, indexed, get_pubkey=None
) -> bls.SignatureSet:
    if not indexed.attesting_indices:
        raise SignatureSetError("empty attesting indices")
    domain = get_domain(
        spec, state, spec.DOMAIN_BEACON_ATTESTER, epoch=indexed.data.target.epoch
    )
    root = compute_signing_root(indexed.data, domain)
    keys = [_pubkey(get_pubkey, state, i) for i in indexed.attesting_indices]
    return bls.SignatureSet.multiple_pubkeys(
        bls.Signature.from_bytes(bytes(indexed.signature)), keys, root
    )


def attestation_signature_set(
    spec: ChainSpec, state, attestation, get_pubkey=None
) -> bls.SignatureSet:
    indexed = get_indexed_attestation(spec, state, attestation)
    return indexed_attestation_signature_set(spec, state, indexed, get_pubkey)


def exit_signature_set(
    spec: ChainSpec, state, signed_exit, get_pubkey=None
) -> bls.SignatureSet:
    exit_msg = signed_exit.message
    from ..types.spec import fork_at_least

    if fork_at_least(getattr(state, "fork_name", "phase0"), "deneb"):
        # deneb pins exit domains to the capella fork version forever
        # (EIP-7044; ref signature_sets.rs eip7044 handling)
        from ..types.helpers import compute_domain

        domain = compute_domain(
            spec.DOMAIN_VOLUNTARY_EXIT,
            spec.capella_fork_version,
            bytes(state.genesis_validators_root),
        )
    else:
        domain = get_domain(
            spec, state, spec.DOMAIN_VOLUNTARY_EXIT, epoch=exit_msg.epoch
        )
    root = compute_signing_root(exit_msg, domain)
    return bls.SignatureSet.single_pubkey(
        bls.Signature.from_bytes(bytes(signed_exit.signature)),
        _pubkey(get_pubkey, state, exit_msg.validator_index),
        root,
    )


def bls_to_execution_change_signature_set(
    spec: ChainSpec, state, signed_change
) -> bls.SignatureSet:
    """Capella credential rotation: signed by the OLD BLS key under the
    GENESIS fork domain (signature_sets.rs bls_execution_change_signature_set)."""
    from ..types.helpers import compute_domain

    msg = signed_change.message
    domain = compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        spec.genesis_fork_version,
        bytes(state.genesis_validators_root),
    )
    root = compute_signing_root(msg, domain)
    try:
        pk = bls.PublicKey.from_bytes(bytes(msg.from_bls_pubkey))
    except bls.BlsError as e:
        raise SignatureSetError(str(e)) from None
    return bls.SignatureSet.single_pubkey(
        bls.Signature.from_bytes(bytes(signed_change.signature)), pk, root
    )


def deposit_signature_is_valid(spec: ChainSpec, deposit_data) -> bool:
    """Deposits verify standalone against the *deposit* domain (no fork —
    compute_domain with genesis_validators_root = zero), and invalid
    signatures merely skip the deposit rather than failing the block."""
    from ..types.containers import DepositMessage
    from ..types.helpers import compute_domain

    try:
        pk = bls.PublicKey.from_bytes(bytes(deposit_data.pubkey))
    except bls.BlsError:
        return False
    domain = compute_domain(
        spec.DOMAIN_DEPOSIT, spec.genesis_fork_version, b"\x00" * 32
    )
    msg = DepositMessage(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount,
    )
    root = compute_signing_root(msg, domain)
    sig = bls.Signature.from_bytes(bytes(deposit_data.signature))
    return sig.verify(pk, root)
