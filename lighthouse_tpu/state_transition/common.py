"""Shared mutators: balances, exits, slashing (state_processing/src/common)."""

from __future__ import annotations

import numpy as np

from ..types.spec import ChainSpec, FAR_FUTURE_EPOCH
from .beacon_state_util import (
    get_active_validator_indices,
    get_beacon_proposer_index,
    get_current_epoch,
)


def balances_array(state) -> np.ndarray:
    """View/convert state.balances as a numpy uint64 column."""
    if not isinstance(state.balances, np.ndarray):
        state.balances = np.asarray(state.balances, dtype=np.uint64)
    return state.balances


def increase_balance(state, index: int, delta: int) -> None:
    b = balances_array(state)
    b[index] += np.uint64(delta)


def decrease_balance(state, index: int, delta: int) -> None:
    b = balances_array(state)
    b[index] -= np.uint64(min(int(delta), int(b[index])))


def get_validator_churn_limit(spec: ChainSpec, state) -> int:
    n_active = len(
        get_active_validator_indices(state, get_current_epoch(spec, state))
    )
    return max(
        spec.min_per_epoch_churn_limit, n_active // spec.churn_limit_quotient
    )


def get_validator_activation_churn_limit(spec: ChainSpec, state) -> int:
    """Deneb caps the activation churn (spec get_validator_activation_churn_limit)."""
    from ..types.spec import fork_at_least

    limit = get_validator_churn_limit(spec, state)
    if fork_at_least(getattr(state, "fork_name", "phase0"), "deneb"):
        limit = min(spec.max_per_epoch_activation_churn_limit, limit)
    return limit


def compute_activation_exit_epoch(spec: ChainSpec, epoch: int) -> int:
    return epoch + 1 + spec.max_seed_lookahead


def initiate_validator_exit(spec: ChainSpec, state, index: int) -> None:
    from ..types.spec import fork_at_least

    if fork_at_least(getattr(state, "fork_name", "phase0"), "electra"):
        from .electra import initiate_validator_exit_electra

        return initiate_validator_exit_electra(spec, state, index)
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_epochs = [
        w.exit_epoch for w in state.validators if w.exit_epoch != FAR_FUTURE_EPOCH
    ]
    exit_queue_epoch = max(
        exit_epochs + [compute_activation_exit_epoch(spec, get_current_epoch(spec, state))]
    )
    exit_queue_churn = sum(
        1 for w in state.validators if w.exit_epoch == exit_queue_epoch
    )
    if exit_queue_churn >= get_validator_churn_limit(spec, state):
        exit_queue_epoch += 1
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = exit_queue_epoch + spec.min_validator_withdrawability_delay
    from ..epoch_engine import mark_registry_delta

    mark_registry_delta(state, index)


def slash_validator(
    spec: ChainSpec, state, slashed_index: int, whistleblower_index: int | None = None
) -> None:
    epoch = get_current_epoch(spec, state)
    initiate_validator_exit(spec, state, slashed_index)
    v = state.validators[slashed_index]
    v.slashed = True
    v.withdrawable_epoch = max(
        v.withdrawable_epoch, epoch + spec.preset.EPOCHS_PER_SLASHINGS_VECTOR
    )
    from ..epoch_engine import mark_registry_delta

    mark_registry_delta(state, slashed_index)
    state.slashings[epoch % spec.preset.EPOCHS_PER_SLASHINGS_VECTOR] += (
        v.effective_balance
    )
    from ..types.spec import fork_at_least

    fork = getattr(state, "fork_name", "phase0")
    if fork == "phase0":
        slash_quotient = spec.min_slashing_penalty_quotient
    elif fork == "altair":
        slash_quotient = spec.min_slashing_penalty_quotient_altair
    elif fork_at_least(fork, "electra"):
        slash_quotient = spec.min_slashing_penalty_quotient_electra
    else:
        slash_quotient = spec.min_slashing_penalty_quotient_bellatrix
    decrease_balance(state, slashed_index, v.effective_balance // slash_quotient)

    proposer_index = get_beacon_proposer_index(spec, state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    wb_quotient = (
        spec.whistleblower_reward_quotient_electra
        if fork_at_least(fork, "electra")
        else spec.whistleblower_reward_quotient
    )
    whistleblower_reward = v.effective_balance // wb_quotient
    proposer_reward = whistleblower_reward // spec.proposer_reward_quotient
    if fork != "phase0":
        # altair+: proposer gets PROPOSER_WEIGHT/WEIGHT_DENOMINATOR of the reward
        proposer_reward = whistleblower_reward * 8 // 64
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(
        state, whistleblower_index, whistleblower_reward - proposer_reward
    )
