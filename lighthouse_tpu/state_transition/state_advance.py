"""State advance helpers (state_advance.rs:28,61).

``complete_state_advance`` hashes every intermediate state (valid roots);
``partial_state_advance`` skips hashing for speed by writing a placeholder
root, valid only when the final state will never be hashed across the skipped
range (the attestation-shuffling use case).
"""

from __future__ import annotations

from ..types.spec import ChainSpec
from .per_slot import per_slot_processing


def _crosses_epoch_boundary(spec: ChainSpec, state, target_slot: int) -> bool:
    per_epoch = spec.preset.SLOTS_PER_EPOCH
    return target_slot // per_epoch > state.slot // per_epoch


def _warm_epoch_engine(spec: ChainSpec, state, target_slot: int) -> None:
    """Bind the device epoch engine's registry mirror before a multi-epoch
    advance: the boundary transitions inside the loop then run as journal
    deltas against a resident mirror instead of first-bind full gathers."""
    if not _crosses_epoch_boundary(spec, state, target_slot):
        return
    from ..epoch_engine import prepare_state

    prepare_state(state)  # no-op unless the device backend is active


def complete_state_advance(spec: ChainSpec, state, target_slot: int) -> None:
    if state.slot > target_slot:
        raise ValueError("state ahead of target")
    _warm_epoch_engine(spec, state, target_slot)
    while state.slot < target_slot:
        per_slot_processing(spec, state)


def partial_state_advance(spec: ChainSpec, state, target_slot: int) -> None:
    if state.slot > target_slot:
        raise ValueError("state ahead of target")
    _warm_epoch_engine(spec, state, target_slot)
    first = True
    while state.slot < target_slot:
        # Only the first slot's root must be real (it may already be wanted by
        # the caller); subsequent roots are placeholders.
        root = None if first else b"\x00" * 32
        per_slot_processing(spec, state, state_root=root)
        first = False
