"""BeaconState accessors: epochs, seeds, committees, proposers.

Parity targets: the accessor impl block of
``/root/reference/consensus/types/src/beacon_state.rs`` and the committee
cache (``beacon_state/committee_cache.rs``). The committee cache here shuffles
the whole active set once per (epoch, seed) with the vectorized swap-or-not
kernel and slices committees out of the flat permutation — the same layout the
reference caches.
"""

from __future__ import annotations

import numpy as np

from ..ops.shuffle import shuffle_list
from ..ssz.sha256 import sha256
from ..types.helpers import is_active_validator
from ..types.spec import ChainSpec

DOMAIN_BEACON_ATTESTER = b"\x01\x00\x00\x00"


class StateTransitionError(Exception):
    pass


def get_current_epoch(spec: ChainSpec, state) -> int:
    return state.slot // spec.preset.SLOTS_PER_EPOCH


def get_previous_epoch(spec: ChainSpec, state) -> int:
    cur = get_current_epoch(spec, state)
    return cur - 1 if cur > 0 else 0


def get_active_validator_indices(state, epoch: int) -> np.ndarray:
    return np.array(
        [i for i, v in enumerate(state.validators) if is_active_validator(v, epoch)],
        dtype=np.uint64,
    )


def get_randao_mix(spec: ChainSpec, state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % spec.preset.EPOCHS_PER_HISTORICAL_VECTOR]


def get_seed(spec: ChainSpec, state, epoch: int, domain_type: bytes) -> bytes:
    mix = get_randao_mix(
        spec,
        state,
        epoch
        + spec.preset.EPOCHS_PER_HISTORICAL_VECTOR
        - spec.min_seed_lookahead
        - 1,
    )
    return sha256(domain_type + epoch.to_bytes(8, "little") + mix)


def get_block_root_at_slot(spec: ChainSpec, state, slot: int) -> bytes:
    if not (slot < state.slot <= slot + spec.preset.SLOTS_PER_HISTORICAL_ROOT):
        raise StateTransitionError(f"block root slot {slot} out of range")
    return state.block_roots[slot % spec.preset.SLOTS_PER_HISTORICAL_ROOT]


def get_block_root(spec: ChainSpec, state, epoch: int) -> bytes:
    return get_block_root_at_slot(spec, state, spec.start_slot(epoch))


def get_committee_count_per_slot(spec: ChainSpec, state, epoch: int) -> int:
    n_active = len(get_active_validator_indices(state, epoch))
    return committee_count_from_active(spec, n_active)


def committee_count_from_active(spec: ChainSpec, n_active: int) -> int:
    p = spec.preset
    return max(
        1,
        min(
            p.MAX_COMMITTEES_PER_SLOT,
            n_active // p.SLOTS_PER_EPOCH // p.TARGET_COMMITTEE_SIZE,
        ),
    )


class CommitteeCache:
    """All committees of one epoch: the active-set permutation plus slicing.

    ``shuffled`` holds active validator indices in shuffled order (the
    reference stores exactly this, committee_cache.rs); committee (slot, idx)
    is a contiguous slice.
    """

    def __init__(self, spec: ChainSpec, state, epoch: int):
        cur = get_current_epoch(spec, state)
        if epoch > cur + 1:
            raise StateTransitionError("committee epoch beyond lookahead")
        self.epoch = epoch
        self.spec = spec
        active = get_active_validator_indices(state, epoch)
        if active.size == 0:
            raise StateTransitionError("no active validators")
        seed = get_seed(spec, state, epoch, DOMAIN_BEACON_ATTESTER)
        # Spec committees use compute_shuffled_index forward on positions;
        # shuffling the *list* backwards yields the same assignment in O(n)
        # (the reference's shuffle_list(forwards=false) trick).
        self.shuffled = active[
            shuffle_list(
                np.arange(active.size, dtype=np.uint64),
                seed,
                spec.preset.SHUFFLE_ROUND_COUNT,
                forwards=False,
            ).astype(np.int64)
        ]
        self.committees_per_slot = committee_count_from_active(spec, active.size)
        self.slots_per_epoch = spec.preset.SLOTS_PER_EPOCH
        self.n_active = active.size

    def committee(self, slot: int, index: int) -> np.ndarray:
        p = self.spec.preset
        if slot // p.SLOTS_PER_EPOCH != self.epoch:
            raise StateTransitionError("slot not in cached epoch")
        if index >= self.committees_per_slot:
            raise StateTransitionError("committee index out of range")
        total = self.committees_per_slot * self.slots_per_epoch
        ci = (slot % p.SLOTS_PER_EPOCH) * self.committees_per_slot + index
        start = self.n_active * ci // total
        end = self.n_active * (ci + 1) // total
        return self.shuffled[start:end]

    def committees_at_slot(self, slot: int) -> list:
        return [
            self.committee(slot, i) for i in range(self.committees_per_slot)
        ]


def get_beacon_committee(spec: ChainSpec, state, slot: int, index: int) -> np.ndarray:
    epoch = slot // spec.preset.SLOTS_PER_EPOCH
    return _committee_cache(spec, state, epoch).committee(slot, index)


def _committee_cache(spec: ChainSpec, state, epoch: int) -> CommitteeCache:
    """Per-state memo of up to 3 epochs (reference keeps prev/cur/next)."""
    cache = getattr(state, "_committee_caches", None)
    if cache is None:
        cache = {}
        object.__setattr__(state, "_committee_caches", cache)
    key = epoch
    if key not in cache:
        cache[key] = CommitteeCache(spec, state, epoch)
    return cache[key]


def invalidate_caches(state) -> None:
    if hasattr(state, "_committee_caches"):
        state._committee_caches.clear()


def compute_proposer_index(
    spec: ChainSpec, state, indices: np.ndarray, seed: bytes
) -> int:
    """Effective-balance-weighted rejection sampling (spec literal)."""
    if indices.size == 0:
        raise StateTransitionError("no candidates")
    MAX_RANDOM_BYTE = 2**8 - 1
    max_eb = spec.max_effective_balance
    i = 0
    total = indices.size
    while True:
        candidate = int(indices[compute_shuffled_position(spec, i % total, total, seed)])
        random_byte = sha256(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eb = state.validators[candidate].effective_balance
        if eb * MAX_RANDOM_BYTE >= max_eb * random_byte:
            return candidate
        i += 1


def compute_shuffled_position(spec: ChainSpec, index: int, n: int, seed: bytes) -> int:
    from ..ops.shuffle import compute_shuffled_index

    return compute_shuffled_index(index, n, seed, spec.preset.SHUFFLE_ROUND_COUNT)


def get_beacon_proposer_index(spec: ChainSpec, state, slot: int | None = None) -> int:
    slot = state.slot if slot is None else slot
    epoch = slot // spec.preset.SLOTS_PER_EPOCH
    seed = sha256(
        get_seed(spec, state, epoch, spec.DOMAIN_BEACON_PROPOSER)
        + int(slot).to_bytes(8, "little")
    )
    indices = get_active_validator_indices(state, epoch)
    return compute_proposer_index(spec, state, indices, seed)


def get_total_balance(spec: ChainSpec, state, indices) -> int:
    total = sum(int(state.validators[int(i)].effective_balance) for i in indices)
    return max(spec.effective_balance_increment, total)


def get_total_active_balance(spec: ChainSpec, state) -> int:
    epoch = get_current_epoch(spec, state)
    return get_total_balance(spec, state, get_active_validator_indices(state, epoch))


def get_attesting_indices(spec: ChainSpec, state, data, aggregation_bits) -> np.ndarray:
    committee = get_beacon_committee(spec, state, data.slot, data.index)
    bits = np.asarray(aggregation_bits, dtype=bool)
    if bits.size != committee.size:
        raise StateTransitionError("aggregation bits length != committee size")
    return committee[bits]


def get_indexed_attestation(spec: ChainSpec, state, attestation):
    from ..types.containers import for_preset

    ns = for_preset(spec.preset.name)
    if hasattr(attestation, "committee_bits"):
        from .electra import get_attesting_indices_electra

        return ns.IndexedAttestationElectra(
            attesting_indices=sorted(
                get_attesting_indices_electra(spec, state, attestation)
            ),
            data=attestation.data,
            signature=attestation.signature,
        )
    indices = get_attesting_indices(
        spec, state, attestation.data, attestation.aggregation_bits
    )
    return ns.IndexedAttestation(
        attesting_indices=sorted(int(i) for i in indices),
        data=attestation.data,
        signature=attestation.signature,
    )
