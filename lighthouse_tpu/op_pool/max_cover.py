"""Greedy maximum-coverage selection (max_cover.rs, 225 LoC in the reference).

Classic (1 - 1/e)-approximation: repeatedly take the candidate with the
highest residual score, then strip its covered items from the rest. Items are
numpy bool masks so the strip step is vectorized."""

from __future__ import annotations

import numpy as np


def maximum_cover(candidates: list, limit: int) -> list:
    """candidates: list of (mask: np.ndarray[bool], weights: np.ndarray[u64],
    payload). Returns up to ``limit`` payloads maximizing covered weight.
    ``weights`` aligns with mask positions (per-item reward)."""
    live = [
        [mask.copy(), np.asarray(weights, dtype=np.uint64), payload]
        for mask, weights, payload in candidates
    ]
    chosen = []
    for _ in range(min(limit, len(live))):
        best_i, best_score = -1, 0
        for i, (mask, w, _) in enumerate(live):
            score = int(w[mask].sum())
            if score > best_score:
                best_i, best_score = i, score
        if best_i < 0:
            break
        mask, w, payload = live.pop(best_i)
        chosen.append((payload, mask))
        for other in live:
            other[0] &= ~mask
    return [p for p, _ in chosen]
