"""Operation pool: attestations, slashings, exits awaiting inclusion.

Twin of ``beacon_node/operation_pool``: attestations aggregated per
``AttestationData`` (attestation_storage.rs), block packing by greedy
max-cover over reward-weighted candidates (max_cover.rs), plus the naive
per-(slot,committee) aggregation pool for gossip subnets
(``beacon_chain/src/naive_aggregation_pool.rs``).
"""

from .pool import OperationPool
from .max_cover import maximum_cover
from .naive_aggregation import NaiveAggregationPool
