"""Sync-committee aggregation pool (naive_aggregation_pool's sync twin +
``OperationPool::get_sync_aggregate``, ref operation_pool/src/lib.rs:156 and
``beacon_chain/src/sync_committee_verification.rs`` aggregation shape).

Individual ``SyncCommitteeMessage``s and subnet ``SyncCommitteeContribution``s
are union-aggregated per (slot, beacon_block_root); block production asks for
the best ``SyncAggregate`` for the block's parent root at the previous slot.
"""

from __future__ import annotations

import threading

import numpy as np

from ..ops.bls_oracle import curves as oc

INFINITY_SIG = b"\xc0" + b"\x00" * 95


class SyncContributionPool:
    def __init__(self, sync_committee_size: int):
        self.size = sync_committee_size
        # (slot, root) -> [bits ndarray, agg_sig_point]
        self._entries: dict[tuple[int, bytes], list] = {}
        self._lock = threading.Lock()

    # -- ingest -------------------------------------------------------------

    def insert_message(self, slot: int, root: bytes, positions, signature: bytes) -> None:
        """One validator's signed sync message; ``positions`` are its indices
        in the CURRENT sync committee (a validator can hold several seats).
        Verification aggregates the committee pubkey once per SET BIT, so the
        signature joins the aggregate once per seat too."""
        bits = np.zeros(self.size, dtype=bool)
        for pos in positions:
            bits[int(pos)] = True
        point = oc.g2_decompress(bytes(signature))
        acc = point
        for _ in range(len(positions) - 1):
            acc = oc.g2_add(acc, point)
        self._merge(slot, bytes(root), bits, acc)

    def insert_contribution(self, contribution) -> None:
        """A subnet aggregate: bits cover one of the 4 subcommittees
        (sync_committee_verification.rs contribution path)."""
        sub = int(contribution.subcommittee_index)
        sub_size = self.size // 4
        bits = np.zeros(self.size, dtype=bool)
        sub_bits = np.asarray(contribution.aggregation_bits, dtype=bool)
        bits[sub * sub_size : (sub + 1) * sub_size] = sub_bits
        self._merge(
            int(contribution.slot),
            bytes(contribution.beacon_block_root),
            bits,
            oc.g2_decompress(bytes(contribution.signature)),
        )

    def _merge(self, slot: int, root: bytes, bits, sig_point) -> None:
        if not bits.any():
            return
        with self._lock:
            entry = self._entries.get((slot, root))
            if entry is None:
                self._entries[(slot, root)] = [bits, sig_point]
                return
            have, agg = entry
            overlap = have & bits
            if overlap.any():
                return  # naive aggregation: only disjoint unions combine
            entry[0] = have | bits
            entry[1] = oc.g2_add(agg, sig_point)

    # -- block production ----------------------------------------------------

    def get_sync_aggregate(self, ns, slot: int, beacon_block_root: bytes):
        """Best aggregate signed at ``slot`` over ``beacon_block_root`` (the
        parent of the block being built), or the empty infinity aggregate."""
        with self._lock:
            entry = self._entries.get((int(slot), bytes(beacon_block_root)))
            if entry is None:
                return ns.SyncAggregate(
                    sync_committee_bits=np.zeros(self.size, dtype=bool),
                    sync_committee_signature=INFINITY_SIG,
                )
            bits, agg = entry
            return ns.SyncAggregate(
                sync_committee_bits=bits.copy(),
                sync_committee_signature=oc.g2_compress(agg),
            )

    def prune(self, current_slot: int) -> None:
        with self._lock:
            for key in [k for k in self._entries if k[0] < current_slot - 2]:
                del self._entries[key]
