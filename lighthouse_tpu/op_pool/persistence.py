"""Operation-pool persistence (operation_pool/src/persistence.rs).

The pool's attestations / slashings / exits survive restarts: on shutdown
the pool is serialized into the store's metadata bucket and rehydrated on
boot. Format: one JSON document with hex-encoded SSZ payloads — attestation
variants store (packed aggregation bits, compressed signature) pairs so the
union-aggregated pool state round-trips exactly.
"""

from __future__ import annotations

import json

import numpy as np

from ..ops.bls_oracle import curves as oc
from ..types.containers import AttestationData

META_KEY = b"op_pool_v1"


def persist(store, pool) -> None:
    """The op-pool persistence barrier: serialize + one metadata put (the
    ``persist.op_pool`` crash point; shutdown AND per-slot durable-datadir
    cadence both route through here)."""
    from ..resilience.crashpoints import maybe_crash

    maybe_crash("persist.op_pool", owner=getattr(store.hot, "owner", None))
    store.put_meta(META_KEY, serialize_pool(pool))


def serialize_pool(pool) -> bytes:
    with pool._lock:
        atts = []
        for data, variants in pool._attestations.values():
            atts.append(
                {
                    "data": type(data).encode(data).hex(),
                    "variants": [
                        {
                            "n": int(bits.size),
                            "bits": np.packbits(bits).tobytes().hex(),
                            "sig": oc.g2_compress(sig).hex(),
                        }
                        for bits, sig in variants
                    ],
                }
            )
        doc = {
            "attestations": atts,
            "proposer_slashings": [
                type(s).encode(s).hex()
                for s in pool._proposer_slashings.values()
            ],
            "attester_slashings": [
                type(s).encode(s).hex() for s in pool._attester_slashings
            ],
            "voluntary_exits": [
                type(e).encode(e).hex()
                for e in pool._voluntary_exits.values()
            ],
            "bls_changes": [
                type(c).encode(c).hex() for c in pool._bls_changes.values()
            ],
        }
    return json.dumps(doc).encode()


def restore_pool(pool, ns, blob: bytes) -> int:
    """Rehydrate ``pool`` in place from ``serialize_pool`` output; returns
    the number of attestation variants restored."""
    doc = json.loads(blob)
    n = 0
    with pool._lock:
        for entry in doc.get("attestations", []):
            data = AttestationData.decode(bytes.fromhex(entry["data"]))
            root = type(data).hash_tree_root(data)
            variants = []
            for v in entry["variants"]:
                bits = np.unpackbits(
                    np.frombuffer(bytes.fromhex(v["bits"]), dtype=np.uint8)
                )[: v["n"]].astype(bool)
                variants.append(
                    (bits, oc.g2_decompress(bytes.fromhex(v["sig"])))
                )
                n += 1
            pool._attestations[root] = (data, variants)
        for h in doc.get("proposer_slashings", []):
            s = ns.ProposerSlashing.decode(bytes.fromhex(h))
            pool._proposer_slashings[
                int(s.signed_header_1.message.proposer_index)
            ] = s
        for h in doc.get("attester_slashings", []):
            pool._attester_slashings.append(
                ns.AttesterSlashing.decode(bytes.fromhex(h))
            )
        for h in doc.get("voluntary_exits", []):
            e = ns.SignedVoluntaryExit.decode(bytes.fromhex(h))
            pool._voluntary_exits[int(e.message.validator_index)] = e
        for h in doc.get("bls_changes", []):
            from ..types.containers import SignedBLSToExecutionChange

            c = SignedBLSToExecutionChange.decode(bytes.fromhex(h))
            pool._bls_changes[int(c.message.validator_index)] = c
    return n
