"""Naive aggregation pool: fold unaggregated gossip attestations into one
aggregate per AttestationData (naive_aggregation_pool.rs).

Signature aggregation is G2 point addition via the oracle backend (cheap);
overlapping-bit inserts are rejected exactly like the reference's
``Error::AlreadyKnown`` path is skipped."""

from __future__ import annotations

import numpy as np

from ..ops.bls_oracle import curves as oc


class NaiveAggregationPool:
    SLOTS_RETAINED = 3

    def __init__(self, attestation_cls):
        self.att_cls = attestation_cls
        # data_root -> (data, bits, sig_point)
        self._maps: dict[bytes, tuple] = {}
        self._by_slot: dict[int, set] = {}

    def insert(self, attestation) -> bool:
        """Insert an attestation (typically single-bit from gossip). Returns
        True if it added new aggregation bits."""
        data = attestation.data
        root = type(data).hash_tree_root(data)
        bits = np.asarray(attestation.aggregation_bits, dtype=bool)
        sig = oc.g2_decompress(bytes(attestation.signature))
        entry = self._maps.get(root)
        if entry is None:
            self._maps[root] = (data, bits.copy(), sig)
            self._by_slot.setdefault(int(data.slot), set()).add(root)
            return True
        _, have, agg = entry
        if (have & bits).any():
            return False  # overlapping signer(s): skip (already known)
        self._maps[root] = (data, have | bits, oc.g2_add(agg, sig))
        return True

    def get(self, data) -> "object | None":
        return self.get_by_root(type(data).hash_tree_root(data))

    def get_by_root(self, root: bytes) -> "object | None":
        entry = self._maps.get(bytes(root))
        if entry is None:
            return None
        d, bits, sig = entry
        return self.att_cls(
            aggregation_bits=bits.copy(), data=d, signature=oc.g2_compress(sig)
        )

    def iter_all(self):
        for d, bits, sig in self._maps.values():
            yield self.att_cls(
                aggregation_bits=bits.copy(), data=d,
                signature=oc.g2_compress(sig),
            )

    def prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.SLOTS_RETAINED
        for slot in [s for s in self._by_slot if s < cutoff]:
            for root in self._by_slot.pop(slot):
                self._maps.pop(root, None)
