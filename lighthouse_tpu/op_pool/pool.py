"""The operation pool (operation_pool/src/lib.rs:48).

Attestations are stored split by checkpoint (epoch, source) and keyed by
``AttestationData`` root with their union-aggregated variants
(attestation_storage.rs); ``get_attestations`` (lib.rs:250) packs a block via
greedy max-cover over per-attestation reward scores; slashings and exits
dedupe by their slashable targets (lib.rs:388)."""

from __future__ import annotations

import threading

import numpy as np

from ..ops.bls_oracle import curves as oc
from ..state_transition.beacon_state_util import (
    get_attesting_indices, get_beacon_committee, get_current_epoch,
    get_previous_epoch,
)
from ..types.spec import ChainSpec
from .max_cover import maximum_cover


class OperationPool:
    def __init__(self, spec: ChainSpec, attestation_cls):
        self.spec = spec
        self.att_cls = attestation_cls
        # data_root -> (data, list[(bits, sig_point)])
        self._attestations: dict[bytes, tuple] = {}
        self._attester_slashings: list = []
        self._proposer_slashings: dict[int, object] = {}
        self._voluntary_exits: dict[int, object] = {}
        self._bls_changes: dict[int, object] = {}
        # The reference wraps each map in its own RwLock (lib.rs:48-60);
        # here one pool lock serializes inserts (HTTP publishers) against
        # packing reads (block production).
        self._lock = threading.RLock()
        from .reward_cache import RewardCache

        self.reward_cache = RewardCache()

    # -- attestations (insert_attestation, lib.rs:200) ---------------------------

    def insert_attestation(self, attestation) -> None:
        with self._lock:
            self._insert_attestation(attestation)

    def _insert_attestation(self, attestation) -> None:
        data = attestation.data
        root = type(data).hash_tree_root(data)
        bits = np.asarray(attestation.aggregation_bits, dtype=bool)
        sig = oc.g2_decompress(bytes(attestation.signature))
        entry = self._attestations.get(root)
        if entry is None:
            self._attestations[root] = (data, [(bits, sig)])
            return
        _, variants = entry
        for i, (have, agg) in enumerate(variants):
            if ((have | bits) == have).all():
                return  # subset of an existing aggregate: nothing new
            if not (have & bits).any():
                variants[i] = (have | bits, oc.g2_add(agg, sig))
                return
        variants.append((bits, sig))

    def num_attestations(self) -> int:
        with self._lock:
            return sum(len(v) for _, v in self._attestations.values())

    def get_attestations(self, state, ctxt_reward_fn=None) -> list:
        """Max-cover packed attestations valid for inclusion in a block built
        on ``state`` (lib.rs:250)."""
        spec = self.spec
        cur, prev = get_current_epoch(spec, state), get_previous_epoch(spec, state)
        candidates = []
        n_val = len(state.validators)
        self.reward_cache.update(spec, state)
        with self._lock:
            entries = [
                (data, [(b.copy(), s) for b, s in variants])
                for data, variants in self._attestations.values()
            ]
        for data, variants in entries:
            if data.target.epoch not in (cur, prev):
                continue
            if not (
                data.slot + spec.min_attestation_inclusion_delay
                <= state.slot
                <= data.slot + spec.preset.SLOTS_PER_EPOCH
            ):
                continue
            # source must match the state's justified checkpoint
            justified = (
                state.current_justified_checkpoint
                if data.target.epoch == cur
                else state.previous_justified_checkpoint
            )
            if data.source != justified:
                continue
            try:
                committee = get_beacon_committee(spec, state, data.slot, data.index)
            except Exception:
                continue
            for bits, sig in variants:
                if bits.size != committee.size:
                    continue
                mask = np.zeros(n_val, dtype=bool)
                mask[committee[bits].astype(np.int64)] = True
                weights = self.reward_cache.weights_for_epoch(
                    int(data.target.epoch), n_val
                )
                att = self.att_cls(
                    aggregation_bits=bits.copy(), data=data,
                    signature=oc.g2_compress(sig),
                )
                candidates.append((mask, weights, att))
        return maximum_cover(candidates, self.spec.preset.MAX_ATTESTATIONS)

    # -- slashings / exits -------------------------------------------------------

    def insert_proposer_slashing(self, slashing) -> None:
        idx = int(slashing.signed_header_1.message.proposer_index)
        with self._lock:
            self._proposer_slashings.setdefault(idx, slashing)

    def insert_attester_slashing(self, slashing) -> None:
        with self._lock:
            self._attester_slashings.append(slashing)

    def insert_voluntary_exit(self, exit_msg) -> None:
        idx = int(exit_msg.message.validator_index)
        with self._lock:
            self._voluntary_exits.setdefault(idx, exit_msg)

    def insert_bls_to_execution_change(self, signed_change) -> None:
        idx = int(signed_change.message.validator_index)
        with self._lock:
            self._bls_changes.setdefault(idx, signed_change)

    def get_bls_to_execution_changes(self, state) -> list:
        """Changes still applicable (validator still has a BLS credential),
        bounded by MAX_BLS_TO_EXECUTION_CHANGES
        (lib.rs get_bls_to_execution_changes)."""
        with self._lock:
            items = list(self._bls_changes.items())
        out = [
            c
            for i, c in items
            if i < len(state.validators)
            and bytes(state.validators[i].withdrawal_credentials)[:1] == b"\x00"
        ]
        limit = getattr(self.spec.preset, "MAX_BLS_TO_EXECUTION_CHANGES", 16)
        return out[:limit]

    def get_slashings_and_exits(self, state):
        from ..types.helpers import is_slashable_validator
        from ..types.spec import FAR_FUTURE_EPOCH

        epoch = get_current_epoch(self.spec, state)
        with self._lock:
            proposer_items = list(self._proposer_slashings.items())
            attester_slashings = list(self._attester_slashings)
            exit_items = list(self._voluntary_exits.items())
        proposer = [
            s
            for i, s in proposer_items
            if i < len(state.validators)
            and is_slashable_validator(state.validators[i], epoch)
        ][: self.spec.preset.MAX_PROPOSER_SLASHINGS]
        attester = []
        covered: set[int] = set()
        for sl in attester_slashings:
            common = set(int(i) for i in sl.attestation_1.attesting_indices) & set(
                int(i) for i in sl.attestation_2.attesting_indices
            )
            fresh = [
                i
                for i in common
                if i not in covered
                and i < len(state.validators)
                and is_slashable_validator(state.validators[i], epoch)
            ]
            if fresh:
                attester.append(sl)
                covered.update(fresh)
            if len(attester) >= self.spec.preset.MAX_ATTESTER_SLASHINGS:
                break
        exits = [
            e
            for i, e in exit_items
            if i < len(state.validators)
            and state.validators[i].exit_epoch == FAR_FUTURE_EPOCH
            and state.validators[i].activation_epoch != FAR_FUTURE_EPOCH
        ][: self.spec.preset.MAX_VOLUNTARY_EXITS]
        return proposer, attester, exits

    # -- maintenance -------------------------------------------------------------

    def prune(self, state) -> None:
        """Drop attestations/ops no longer includable (prune_all, lib.rs)."""
        cur = get_current_epoch(self.spec, state)
        with self._lock:
            self._prune_locked(state, cur)

    def _prune_locked(self, state, cur) -> None:
        self._attestations = {
            r: (d, v)
            for r, (d, v) in self._attestations.items()
            if d.target.epoch + 1 >= cur
        }
        self._voluntary_exits = {
            i: e
            for i, e in self._voluntary_exits.items()
            if i < len(state.validators)
            and state.validators[i].exit_epoch == 2**64 - 1
        }
