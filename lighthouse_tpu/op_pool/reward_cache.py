"""Per-validator attestation packing weights (reward_cache.rs).

The max-cover packer should optimize actual proposer reward, not attester
head-count: a validator whose TIMELY_TARGET flag is already set in the state
being packed earns the proposer nothing, and attesters earn proportionally
to effective balance. The cache computes, per epoch referenced by packable
attestations (previous/current), a weight column:

    weight[i] = effective_balance[i] / EFFECTIVE_BALANCE_INCREMENT
                if TIMELY_TARGET not yet set for i in that epoch, else 0

Recomputed only when the packing state changes (keyed by state root+slot),
mirroring the reference's invalidation-on-state-change contract
(``operation_pool/src/reward_cache.rs``).
"""

from __future__ import annotations

import numpy as np

TIMELY_TARGET_FLAG_INDEX = 1  # participation flag bit (altair spec)


class RewardCache:
    def __init__(self):
        self._key = None
        self._weights: dict[int, np.ndarray] = {}  # epoch -> weight column

    def update(self, spec, state) -> None:
        key = (int(state.slot), bytes(state.latest_block_header.parent_root))
        if key == self._key:
            return
        self._key = key
        self._weights = {}
        eff = (
            np.asarray(
                [int(v.effective_balance) for v in state.validators],
                dtype=np.uint64,
            )
            // spec.effective_balance_increment
        )
        cur_epoch = spec.compute_epoch_at_slot(int(state.slot))
        target_bit = np.uint8(1 << TIMELY_TARGET_FLAG_INDEX)
        if hasattr(state, "current_epoch_participation"):
            cur = np.asarray(state.current_epoch_participation, dtype=np.uint8)
            prev = np.asarray(
                state.previous_epoch_participation, dtype=np.uint8
            )
            self._weights[cur_epoch] = np.where(
                cur & target_bit, np.uint64(0), eff
            )
            if cur_epoch > 0:
                self._weights[cur_epoch - 1] = np.where(
                    prev & target_bit, np.uint64(0), eff
                )
        else:
            # phase0: no participation flags on the state; weight by balance
            # alone (the reference's cache is altair+ for the same reason)
            self._weights[cur_epoch] = eff
            if cur_epoch > 0:
                self._weights[cur_epoch - 1] = eff

    def weights_for_epoch(self, epoch: int, n_validators: int) -> np.ndarray:
        w = self._weights.get(int(epoch))
        if w is None or w.shape[0] != n_validators:
            return np.ones(n_validators, dtype=np.uint64)
        return w
