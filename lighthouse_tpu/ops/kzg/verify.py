"""Batched KZG cell-proof verification: ONE combined pairing check per batch.

Per cell (EIP-7594 verify_cell_kzg_proof): with coset H_i = {c_i mu^t},
d_i = c_i^k, interpolant I_i of the cell values on H_i, and proof Q_i,

    e(C_i - [I_i(tau)], G2) * e(-Q_i, [tau^k - d_i]G2) == 1.

Expanding the second pair through T2 = [tau^k]G2 and folding the whole
batch with Fiat-Shamir weights r_i turns B checks into TWO pairs:

    e( sum_i r_i (C_i - [I_i] + d_i Q_i),  G2 )
  * e( -sum_i r_i Q_i,                     T2 )  ==  1

where sum_i r_i [I_i] is ONE trusted-setup MSM with device-computed
scalars: cell values arrive in bit-reversed coset order, so a single
static gather (the k-point bit-reversal, an involution) plus one shared
k x k inverse-NTT matrix over mu and a per-coset descale c_i^{-t} yields
the monomial interpolant coefficients,

    a_{i,t} = c_i^{-t} * U_{i,t},   U_i = M v'_i,  M[t,j] = mu^{-jt}/k,

and the aggregated setup scalars s_t = sum_i (r_i c_i^{-t}) U_{i,t} are
one ``frops.fr_weighted_sum`` per coefficient row. Every scalar multiply
in the graph — C/Q weights, d-shifted Q weights, and the setup scalars —
funnels into ONE ``curve.scale_bits`` scan over 3B + k lanes, two halving
point trees, and one backend-dispatched Miller product with a single final
exponentiation.

``PROBE`` counts trace-time pairing checks/pairs: jit tracing runs this
module's Python once per compile, so a probe of exactly one
``multi_pairing_is_one`` with two pairs is a property of the LOWERED
graph, not of runtime logging (the bench embeds the record).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..bls import curve, pairing
from . import frops

# trace-time instrumentation (see module docstring)
PROBE = {"pairing_checks": 0, "pairs": 0, "scale_scans": 0}


class VerifyTables(NamedTuple):
    """Static per-context constants (host-built once per CellContext).

    perm   int32  [k]          bit-reversal chunk order -> natural coset order
    idft   uint64 [k, k, 25]   M[t, j] = mu^{-jt} / k mod r (Fr limbs)
    cinv   uint64 [cells, k, 25]  c_i^{-t} descale rows
    dtab   uint64 [cells, 25]  d_i = c_i^k
    setup  uint64 [k, 3, 25]   G1 monomial setup points (projective)
    g2x/y  uint64 [2, 25]      G2 generator (affine Fq2)
    t2x/y  uint64 [2, 25]      [tau^k]G2 (affine Fq2)
    """

    perm: np.ndarray
    idft: np.ndarray
    cinv: np.ndarray
    dtab: np.ndarray
    setup: np.ndarray
    g2x: np.ndarray
    g2y: np.ndarray
    t2x: np.ndarray
    t2y: np.ndarray


def interpolate_rows(tables: VerifyTables, v):
    """Cell values [B, k, 25] (bit-reversed coset order) -> mu-basis
    interpolant rows U [B, k, 25]: static permutation gather + the shared
    inverse-NTT matrix, one IDFT row per scan step (peak memory one
    [B, k, 50] conv accumulator instead of the full [B, k, k, 50])."""
    nat = jnp.take(v, jnp.asarray(tables.perm), axis=1)

    def row(_, m_row):
        return None, frops.fr_dot(nat, m_row)

    _, u = jax.lax.scan(row, None, jnp.asarray(tables.idft))
    return jnp.moveaxis(u, 0, 1)


def cell_batch_check(tables: VerifyTables, v, r, idx, cx, cy, cinf, qx, qy,
                     qinf):
    """The ONE-combined-check verification graph.

    v    [B, k, 25]  cell field elements (canonical Fr limbs)
    r    [B, 25]     Fiat-Shamir weights (canonical, nonzero)
    idx  int32 [B]   cell/coset indices
    cx/cy/cinf, qx/qy/qinf: commitment / proof affine Fq limbs [B, 25]
                     + infinity masks [B]

    Returns a scalar bool. Zero-weight rows (r_i = 0) contribute the
    identity on both sides, so callers pad ragged batches with
    (r=0, C=Q=inf) rows to keep shapes bucketed.
    """
    b = v.shape[0]
    u = interpolate_rows(tables, v)

    # per-cell descaled weights and the aggregated setup scalars
    cinv_g = jnp.take(jnp.asarray(tables.cinv), idx, axis=0)
    w = frops.fr_mul(r[:, None, :], cinv_g)          # [B, k, 25]
    s = frops.fr_weighted_sum(w, u, b)               # [k, 25]

    rd = frops.fr_mul(r, jnp.take(jnp.asarray(tables.dtab), idx, axis=0))

    # every scalar multiply in one scan: C by r, Q by r*d, Q by r, setup by s
    c_pt = curve.from_affine(1, cx[:, None, :], cy[:, None, :], inf=cinf)
    q_pt = curve.from_affine(1, qx[:, None, :], qy[:, None, :], inf=qinf)
    setup_neg = curve.point_neg(1, jnp.asarray(tables.setup))
    pts = jnp.concatenate([c_pt, q_pt, q_pt, setup_neg], axis=0)
    bits = jnp.concatenate(
        [frops.fr_bits(r), frops.fr_bits(rd), frops.fr_bits(r),
         frops.fr_bits(s)],
        axis=1,
    )
    scaled = curve.scale_bits(1, pts, bits)          # [3B + k, 3, 25]
    PROBE["scale_scans"] += 1

    # lhs = sum r_i C_i + sum r_i d_i Q_i - sum s_t setup_t
    lhs = curve.point_sum(
        1, jnp.concatenate([scaled[: 2 * b], scaled[3 * b :]], axis=0)
    )
    q_neg = curve.point_neg(1, curve.point_sum(1, scaled[2 * b : 3 * b]))

    lx, ly = curve.to_affine(1, lhs)
    nx, ny = curve.to_affine(1, q_neg)
    px = jnp.stack([lx[0], nx[0]], axis=0)
    py = jnp.stack([ly[0], ny[0]], axis=0)
    g2qx = jnp.stack([jnp.asarray(tables.g2x), jnp.asarray(tables.t2x)])
    g2qy = jnp.stack([jnp.asarray(tables.g2y), jnp.asarray(tables.t2y)])
    # an infinity side contributes e(inf, .) = 1: mask it valid=False
    valid = jnp.stack([~curve.is_inf(1, lhs), ~curve.is_inf(1, q_neg)])
    PROBE["pairing_checks"] += 1
    PROBE["pairs"] += 2
    return pairing.multi_pairing_is_one(px, py, g2qx, g2qy, valid)


def cell_single_check(z2_tab, v, r_one, idx, cx, cy, cinf, qx, qy, qinf,
                      tables: VerifyTables):
    """Single-cell device check against the chain-plans coset table
    ``z2_tab`` ([cells, 6, 25] projective [tau^k - d_i]G2 rows): the direct
    two-pair form e(C - [I], G2) * e(-Q, Z_i) == 1 without RLC weights.
    Shapes are the B = 1 slice of the batch layout."""
    u = interpolate_rows(tables, v)                  # [1, k, 25]
    cinv_g = jnp.take(jnp.asarray(tables.cinv), idx, axis=0)
    a = frops.fr_mul(r_one[:, None, :], cinv_g)      # r_one = 1: descale only
    s = frops.fr_weighted_sum(a, u, 1)               # [k, 25]

    setup_scaled = curve.scale_bits(
        1, jnp.asarray(tables.setup), frops.fr_bits(s)
    )
    i_commit = curve.point_sum(1, setup_scaled)
    c_pt = curve.from_affine(1, cx[:, None, :], cy[:, None, :], inf=cinf)[0]
    q_pt = curve.from_affine(1, qx[:, None, :], qy[:, None, :], inf=qinf)[0]
    lhs = curve.point_add(1, c_pt, curve.point_neg(1, i_commit))
    q_neg = curve.point_neg(1, q_pt)

    z2 = jnp.take(jnp.asarray(z2_tab), idx[0], axis=0)
    z2x, z2y = curve.to_affine(2, z2)
    lx, ly = curve.to_affine(1, lhs)
    nx, ny = curve.to_affine(1, q_neg)
    px = jnp.stack([lx[0], nx[0]], axis=0)
    py = jnp.stack([ly[0], ny[0]], axis=0)
    g2qx = jnp.stack([jnp.asarray(tables.g2x), z2x])
    g2qy = jnp.stack([jnp.asarray(tables.g2y), z2y])
    valid = jnp.stack([~curve.is_inf(1, lhs), ~curve.is_inf(1, q_neg)])
    PROBE["pairing_checks"] += 1
    PROBE["pairs"] += 2
    return pairing.multi_pairing_is_one(px, py, g2qx, g2qy, valid)
