"""Device KZG kernels: scalar-field (Fr) limb math + batched cell verify.

The second cryptosystem on the plan compiler (ISSUE 16): everything here
rides the ``ops/bls`` machinery — the 25x16-bit limb layout and the
``fq._conv_product`` seam (so all three ``LIGHTHOUSE_CONV_IMPL`` backends
work unchanged), ``curve.scale_bits``/``point_sum`` for the MSMs,
``chain_plans`` for the setup-time fixed-scalar tables, and
``pairing.miller_product`` for the one combined pairing check per batch.

* ``frops``  — Fr (BLS12-381 scalar field) arithmetic in the limb domain:
  products through the conv seam, dot products as conv-accumulator sums,
  and the fold/normalize/conditional-subtract reduction mod r with every
  bound recorded through ``fq._cert`` (the bounds certifier picks the
  ``kzg.*`` obligations up like any other op graph).
* ``verify`` — the batched cell-proof verification graph: device
  interpolation (uniform bit-reversal + one shared inverse-NTT matrix +
  per-coset descale), random-linear-combination aggregation, three MSMs
  (one with device-computed scalars), and ONE 2-pair Miller product.
"""

from . import frops, verify  # noqa: F401
