"""Fr (BLS12-381 scalar field) limb arithmetic on the fq conv seam.

Fr elements reuse the 25x16-bit uint64 limb layout of ``ops/bls/fq`` —
canonical values (< r, 255 bits) occupy the low 16 limbs, the top 9 limbs
are zero — so the multiply pipeline is exactly the base-field one:
``fq._conv_product`` (dispatched to pallas / digits / f64 / shear by
``LIGHTHOUSE_CONV_IMPL``) produces 50 exact u64 accumulators in the 16-bit
radix, dot products SUM those accumulators in u64 (exact far below 2^64 for
every batch shape we run), and one ``fr_wide_reduce`` brings the wide value
back to canonical form mod r:

    carry-normalize to exact 16-bit limbs
      -> fold limbs >= 16 with rows 2^(16*(16+j)) mod r  (repeat; each tail
         round shaves ~3.3 bits since 2^256 mod r ~ 2^252.7)
      -> conditional-subtract ladder of 2r, r

Every static bound the walk relies on is asserted AND recorded through
``fq._cert`` under ``kzg.*`` kinds, so ``analysis/bounds`` certifies these
graphs beside the BLS ones.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..bls import fq
from ..bls_oracle.fields import R as R_INT

NLIMBS = fq.NLIMBS
LIMB_BITS = fq.LIMB_BITS
R2_INT = R_INT * R_INT

# fold rows: 2^(16*(16+j)) mod r as exact 16-limb arrays (j up to 24 covers
# wide values through 2^640 — far past the 2^522 worst case we certify)
_N_FOLD = 24
_FOLD_INT = [pow(2, LIMB_BITS * (16 + j), R_INT) for j in range(_N_FOLD)]
_FOLD_TAB = np.stack(
    [np.asarray(fq.int_to_limbs(v))[:16] for v in _FOLD_INT]
).astype(np.uint64)

# conditional-subtract ladder constants (25-limb, exact 16-bit limbs)
_MR_LIMBS = {m: np.asarray(fq.int_to_limbs(m * R_INT)) for m in (2, 1)}

# MSB-first bit extraction tables: bit m (m=0 is bit 254) lives in
# limb pos//16 at offset pos%16 with pos = 254 - m
_BIT_POS = np.arange(254, -1, -1)
_BIT_LIMB = (_BIT_POS // LIMB_BITS).astype(np.int32)
_BIT_OFF = (_BIT_POS % LIMB_BITS).astype(np.uint64)


def fr_to_limbs(vals) -> np.ndarray:
    """Host: iterable of canonical ints -> uint64 [n, 25] limb rows."""
    vals = list(vals)
    raw = b"".join(int(v).to_bytes(32, "little") for v in vals)
    a = np.frombuffer(raw, dtype="<u2").reshape(len(vals), 16)
    out = np.zeros((len(vals), NLIMBS), dtype=np.uint64)
    out[:, :16] = a
    return out


def limbs_to_fr(a) -> int:
    """Host: one canonical limb row -> Python int."""
    return fq.limbs_to_int(a)


def fr_wide_reduce(t, value_bound: int):
    """Wide 16-bit-radix u64 accumulator [..., L] with value < value_bound
    -> canonical Fr limbs [..., 25]. The fold/normalize schedule is resolved
    statically from ``value_bound`` at trace time (no data-dependent
    control flow reaches the device)."""
    assert fq._cert(
        "kzg.fr_reduce.in_value", value_bound, 1 << (LIMB_BITS * 40),
        note="wide Fr value fits the fold table",
    ), "fr_wide_reduce input bound exceeds the fold table"
    def _normalize(t, width):
        # _carry_propagate slices to ``width``; pad first so carries can
        # spill into the high limbs the value is entitled to
        if t.shape[-1] < width:
            t = jnp.pad(t, [(0, 0)] * (t.ndim - 1) + [(0, width - t.shape[-1])])
        return fq._carry_propagate(t, width)

    width = max(16, -(-value_bound.bit_length() // LIMB_BITS))
    t = _normalize(t, width)  # exact 16-bit limbs, value-preserving
    vb = value_bound
    while width > 16 and vb > (1 << 256) + _FOLD_INT[0]:
        hi_w = width - 16
        caps = [
            min((1 << LIMB_BITS) - 1, vb >> (LIMB_BITS * (16 + j)))
            for j in range(hi_w)
        ]
        # fold contribution per output limb: sum_j cap_j * 0xFFFF, plus the
        # 16-bit low limb — far inside u64 (certified, not assumed)
        limb_bound = ((1 << LIMB_BITS) - 1) * (1 + sum(caps))
        assert fq._cert(
            "kzg.fr_reduce.fold_limb", limb_bound, (1 << 63) - 1,
            note="fold accumulator limbs stay exact in u64",
        ), "fr fold accumulator would overflow"
        lo = t[..., :16]
        hi = t[..., 16:width]
        fold = (hi[..., :, None] * jnp.asarray(_FOLD_TAB[:hi_w])).sum(axis=-2)
        vb = (1 << 256) - 1 + sum(c * f for c, f in zip(caps, _FOLD_INT))
        width = max(16, -(-vb.bit_length() // LIMB_BITS))
        t = _normalize(lo + fold, width)
    assert fq._cert(
        "kzg.fr_reduce.tail", vb, 4 * R_INT,
        note="post-fold value inside the 2r/r subtract ladder",
    ), "fr fold walk did not converge below 4r"
    pad = [(0, 0)] * (t.ndim - 1) + [(0, NLIMBS - t.shape[-1])]
    t = jnp.pad(t, pad)
    for m in (2, 1):
        diff, borrow = fq._sub_limbs(t, jnp.asarray(_MR_LIMBS[m]))
        t = jnp.where((borrow == 1)[..., None], t, diff)
    return t


def fr_mul(a, b):
    """Canonical [..., 25] x [..., 25] -> canonical product mod r. Runs on
    whichever conv backend ``LIGHTHOUSE_CONV_IMPL`` selects."""
    fq.conv_limb_bounds((1 << LIMB_BITS) - 1)  # certify conv exactness
    return fr_wide_reduce(fq._conv_product(a, b), R2_INT)


def fr_dot(a, b):
    """sum_j a[..., j, :] * b[..., j, :] mod r for canonical inputs
    [..., K, 25]: K conv products summed as u64 accumulators (exact — the
    per-limb bound is certified), then ONE reduction."""
    k = a.shape[-2]
    conv_bound = max(fq.conv_limb_bounds((1 << LIMB_BITS) - 1))
    assert fq._cert(
        "kzg.fr_dot.acc", k * conv_bound, (1 << 63) - 1,
        note="summed conv accumulators stay exact in u64",
    ), "fr_dot accumulator would overflow"
    t = fq._conv_product(a, b).sum(axis=-2)
    return fr_wide_reduce(t, k * R2_INT)


def fr_weighted_sum(w, u, batch: int):
    """sum over the LEADING axis of w*u mod r (w, u: [B, ..., 25] canonical;
    ``batch`` must equal the static leading extent). The aggregation stage
    of the batched verifier: one conv per pair, one u64 accumulator sum over
    the batch, one reduction per output element."""
    assert w.shape[0] == batch and u.shape[0] == batch
    conv_bound = max(fq.conv_limb_bounds((1 << LIMB_BITS) - 1))
    assert fq._cert(
        "kzg.fr_wsum.acc", batch * conv_bound, (1 << 63) - 1,
        note="batch-summed conv accumulators stay exact in u64",
    ), "fr_weighted_sum accumulator would overflow"
    t = fq._conv_product(w, u).sum(axis=0)
    return fr_wide_reduce(t, batch * R2_INT)


def fr_bits(s):
    """Canonical limbs [..., 25] -> uint64 bit plane [255, ...] MSB-first
    (the ``curve.scale_bits`` input layout). On-device bit extraction: the
    MSM over device-computed scalars never round-trips to the host."""
    v = s[..., jnp.asarray(_BIT_LIMB)]
    bits = (v >> jnp.asarray(_BIT_OFF)) & jnp.uint64(1)
    return jnp.moveaxis(bits, -1, 0)
