"""Swap-or-not shuffle — vectorized full-list kernel.

The spec's committee shuffling. The reference ships both the per-index
``compute_shuffled_index`` (``consensus/swap_or_not_shuffle/src/
compute_shuffled_index.rs``) and the O(n)-per-round whole-list ``shuffle_list``
(``shuffle_list.rs``); validating a committee needs the *whole* shuffling, so
the list form is the hot one. Here each round is ~4 numpy array ops over all
indices at once: the round hash stream is precomputed as a [rounds, n_bytes]
matrix with vectorized SHA-256, and the swap decision is a boolean gather —
no per-index Python. ``shuffle_list(..., forwards=False)`` is the inverse
permutation (the direction Lighthouse uses for committee assignment).
"""

from __future__ import annotations

import numpy as np

from ..ssz.sha256 import sha256_short

SEED_SIZE = 32
ROUND_SIZE = 1
POSITION_WINDOW_SIZE = 4
PIVOT_VIEW_SIZE = SEED_SIZE + ROUND_SIZE
TOTAL_SIZE = SEED_SIZE + ROUND_SIZE + POSITION_WINDOW_SIZE


def _hash_batch(msgs: np.ndarray) -> np.ndarray:
    """[n, <=55]-byte messages -> [n, 32] real SHA-256 digests."""
    return sha256_short(msgs, msgs.shape[1])


def shuffle_list(
    indices: np.ndarray, seed: bytes, rounds: int, forwards: bool = True
) -> np.ndarray:
    """Permute ``indices`` (any int array of values < n applied positionally —
    the spec shuffles positions) with the swap-or-not network."""
    values = np.asarray(indices, dtype=np.uint64).copy()
    n = values.shape[0]
    if n <= 1 or rounds == 0:
        return values
    seed_arr = np.frombuffer(seed, dtype=np.uint8)
    assert seed_arr.shape[0] == SEED_SIZE

    round_order = range(rounds) if forwards else range(rounds - 1, -1, -1)
    # pivot hashes for every round in one batch
    pivot_msgs = np.zeros((rounds, PIVOT_VIEW_SIZE), dtype=np.uint8)
    pivot_msgs[:, :SEED_SIZE] = seed_arr
    pivot_msgs[:, SEED_SIZE] = np.arange(rounds, dtype=np.uint8)
    pivot_digests = _hash_batch(pivot_msgs)
    pivots = (
        pivot_digests[:, :8].copy().view("<u8").reshape(rounds) % np.uint64(n)
    )

    positions = np.arange(n, dtype=np.uint64)
    n_windows = (n + 255) // 256 + 1  # position windows possibly needed
    for r in round_order:
        pivot = int(pivots[r])
        # flip(i) = (pivot + n - i) % n
        flipped = (np.uint64(pivot) + np.uint64(n) - positions) % np.uint64(n)
        combined = np.maximum(positions, flipped)
        # source byte for position j comes from H(seed || r || (j >> 8))
        windows = np.unique(combined >> np.uint64(8))
        msgs = np.zeros((windows.shape[0], TOTAL_SIZE), dtype=np.uint8)
        msgs[:, :SEED_SIZE] = seed_arr
        msgs[:, SEED_SIZE] = r
        msgs[:, SEED_SIZE + 1 :] = (
            windows.astype("<u4").view(np.uint8).reshape(-1, 4)
        )
        digests = _hash_batch(msgs)  # [w, 32]
        win_index = np.searchsorted(windows, combined >> np.uint64(8))
        byte = digests[win_index, ((combined & np.uint64(0xFF)) >> np.uint64(3)).astype(np.int64)]
        bit = (byte >> (combined & np.uint64(7)).astype(np.uint8)) & 1
        values = np.where(bit == 1, values[flipped.astype(np.int64)], values)
        # positions themselves don't move; the *values* swap pairwise:
        # note flip is an involution pairing i <-> flip(i); where bit==1 both
        # ends take each other's value, which the gather above performs.
    return values


def compute_shuffled_index(index: int, n: int, seed: bytes, rounds: int) -> int:
    """Spec single-index forward shuffle (compute_shuffled_index.rs)."""
    assert index < n
    cur = index
    for r in range(rounds):
        pivot_msg = np.zeros((1, PIVOT_VIEW_SIZE), dtype=np.uint8)
        pivot_msg[0, :SEED_SIZE] = np.frombuffer(seed, dtype=np.uint8)
        pivot_msg[0, SEED_SIZE] = r
        pivot = int(_hash_batch(pivot_msg)[0, :8].view("<u8")[0]) % n
        flip = (pivot + n - cur) % n
        position = max(cur, flip)
        msg = np.zeros((1, TOTAL_SIZE), dtype=np.uint8)
        msg[0, :SEED_SIZE] = np.frombuffer(seed, dtype=np.uint8)
        msg[0, SEED_SIZE] = r
        msg[0, SEED_SIZE + 1 :] = np.frombuffer(
            (position >> 8).to_bytes(4, "little"), dtype=np.uint8
        )
        byte = int(_hash_batch(msg)[0, (position & 0xFF) >> 3])
        if (byte >> (position & 7)) & 1:
            cur = flip
    return cur
