"""Optimal-ate pairing on BLS12-381 (oracle).

Strategy (correctness over speed): untwist G2 points into E(Fq12) and run a plain
affine Miller loop with denominator elimination, then a final exponentiation.
Two final-exponentiation routines are provided:

  * ``final_exponentiation``      — easy part + hard part via the x-addition chain,
                                    computing f^(3*(p^4-p^2+1)/r). The factor 3 is
                                    harmless for every pairing *check* (gcd(3, r) = 1),
                                    and is what blst-style implementations use.
  * ``final_exponentiation_naive`` — literal f^((p^12-1)/r) by square-and-multiply;
                                    used in tests to cross-check the chain.

Parity target: the pairing entry points used by
``/root/reference/crypto/bls/src/impls/blst.rs:37-119`` (verify_multiple_aggregate_
signatures) and ``generic_signature.rs`` verify.
"""

from __future__ import annotations

from .fields import P, R, BLS_X, Fq2, Fq6, Fq12
from .curves import g1_is_on_curve, g2_is_on_curve

# w^2 = v: untwist divides x by w^2 = v and y by w^3 = v*w.
# x' in Fq2 embeds at position c0 of Fq6 coefficient; easier: work with generic Fq12.


def _fq12_from_fq(a: int) -> Fq12:
    return Fq12(Fq6(Fq2(a, 0), Fq2.ZERO, Fq2.ZERO), Fq6.ZERO)


def _fq12_from_fq2(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2.ZERO, Fq2.ZERO), Fq6.ZERO)


# w = (0, 1) in the (c0, c1) Fq6 decomposition: w = 0 + 1*w.
_W = Fq12(Fq6.ZERO, Fq6.ONE)
_W2_INV = (_W * _W).inv()
_W3_INV = (_W * _W * _W).inv()


def untwist(q):
    """Map a G2 point (over Fq2) to E(Fq12): (x/w^2, y/w^3)."""
    if q is None:
        return None
    x, y = q
    return (_fq12_from_fq2(x) * _W2_INV, _fq12_from_fq2(y) * _W3_INV)


def _line(p1, p2, t):
    """Evaluate the line through p1 and p2 (or tangent if equal) at point t.
    All points affine over Fq12. Denominators are omitted (killed by the final
    exponentiation since the embedding degree is even)."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if not (x1 == x2):
        # chord
        lam_num = y2 - y1
        lam_den = x2 - x1
    elif y1 == y2:
        # tangent
        three = _fq12_from_fq(3)
        two = _fq12_from_fq(2)
        lam_num = three * x1 * x1
        lam_den = two * y1
    else:
        # vertical
        return (xt - x1, Fq12.ONE)
    # l(t) = lam*(xt - x1) - (yt - y1); return (numerator, denominator) lazily
    return (lam_num * (xt - x1) - lam_den * (yt - y1), lam_den)


def _ec_double(p):
    x, y = p
    lam = _fq12_from_fq(3) * x * x * (_fq12_from_fq(2) * y).inv()
    x3 = lam * lam - x - x
    y3 = lam * (x - x3) - y
    return (x3, y3)


def _ec_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if y1 == y2:
            return _ec_double(p)
        return None
    lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam * lam - x1 - x2
    y3 = lam * (x1 - x3) - y1
    return (x3, y3)


def miller_loop(p, q) -> Fq12:
    """Miller loop for e(P, Q): P in G1 (affine over Fq), Q in G2 (affine over Fq2).

    Returns the unreduced pairing value; apply final_exponentiation to obtain the
    pairing. Infinity in either argument yields one.
    """
    if p is None or q is None:
        return Fq12.ONE
    assert g1_is_on_curve(p) and g2_is_on_curve(q)
    pe = (_fq12_from_fq(p[0]), _fq12_from_fq(p[1]))
    qe = untwist(q)
    t = qe
    f_num = Fq12.ONE
    f_den = Fq12.ONE
    x_abs = -BLS_X
    for bit in bin(x_abs)[3:]:  # MSB already consumed (t starts at Q)
        ln, ld = _line(t, t, pe)
        f_num = f_num * f_num * ln
        f_den = f_den * f_den * ld
        t = _ec_double(t)
        if bit == "1":
            ln, ld = _line(t, qe, pe)
            f_num = f_num * ln
            f_den = f_den * ld
            t = _ec_add(t, qe)
    f = f_num * f_den.inv()
    # x < 0: conjugate (equivalent to inversion after the easy part).
    return f.conjugate()


# ------------------------------------------------------------------------------
# Final exponentiation
# ------------------------------------------------------------------------------

def _cyclotomic_exp_abs_x(f: Fq12) -> Fq12:
    """f^|x| using cyclotomic squarings (f must be in the cyclotomic subgroup)."""
    x_abs = -BLS_X
    res = Fq12.ONE
    started = False
    for bit in bin(x_abs)[2:]:
        if started:
            res = res.cyclotomic_square()
        if bit == "1":
            res = res * f if started else f
            started = True
    return res


def _exp_x_minus_1(f: Fq12) -> Fq12:
    """f^(|x|+1)?? No: f^(x-1) with x negative = conj(f^(|x|+1))."""
    # x - 1 = -(|x| + 1)
    fx = _cyclotomic_exp_abs_x(f)  # f^|x|
    return (fx * f).conjugate()


def final_exponentiation(f: Fq12) -> Fq12:
    """Easy part then hard part computing f^(3*(p^4-p^2+1)/r).

    Uses 3*(p^4-p^2+1)/r = (x-1)^2 * (x+p) * (x^2+p^2-1) + 3.
    """
    # Easy part: f^((p^6-1)(p^2+1))
    f = f.conjugate() * f.inv()           # f^(p^6 - 1)
    f = f.frobenius(2) * f                # ^(p^2 + 1); now f is cyclotomic
    # Hard part
    m1 = _exp_x_minus_1(f)                # f^(x-1)
    m2 = _exp_x_minus_1(m1)               # f^((x-1)^2)
    # ^(x+p): m3 = m2^x * m2^p
    m2x = _cyclotomic_exp_abs_x(m2).conjugate()   # m2^x (x negative)
    m3 = m2x * m2.frobenius(1)
    # ^(x^2+p^2-1): m4 = m3^(x^2) * m3^(p^2) * m3^(-1)
    m3x = _cyclotomic_exp_abs_x(m3).conjugate()
    m3x2 = _cyclotomic_exp_abs_x(m3x).conjugate()
    m4 = m3x2 * m3.frobenius(2) * m3.conjugate()  # conjugate = inverse (cyclotomic)
    return m4 * f * f * f


def final_exponentiation_naive(f: Fq12) -> Fq12:
    return f.pow((P ** 12 - 1) // R)


def pairing(p, q) -> Fq12:
    """Reduced pairing e(P, Q)^3 (the cube is consistent across all uses)."""
    return final_exponentiation(miller_loop(p, q))


def multi_pairing_is_one(pairs) -> bool:
    """Check prod e(P_i, Q_i) == 1 with a single final exponentiation."""
    acc = Fq12.ONE
    for p, q in pairs:
        acc = acc * miller_loop(p, q)
    return final_exponentiation(acc).is_one()
