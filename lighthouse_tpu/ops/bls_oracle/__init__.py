"""Pure-Python BLS12-381 oracle: the trusted reference + portable CPU backend.

Role model: the reference's dual-backend BLS seam
(``/root/reference/crypto/bls/src/lib.rs:8-18`` — blst vs fake_crypto). Every JAX/TPU
kernel in ``lighthouse_tpu.ops.bls`` is validated against this package.
"""

from .fields import P, R, BLS_X, Fq2, Fq6, Fq12, fq_inv, fq_sqrt
from .curves import (
    g1_generator, g2_generator, g1_add, g2_add, g1_mul, g2_mul, g1_neg, g2_neg,
    g1_is_on_curve, g2_is_on_curve, g1_in_subgroup, g2_in_subgroup,
    g1_compress, g1_decompress, g2_compress, g2_decompress, g1_msm,
)
from .pairing import miller_loop, final_exponentiation, pairing, multi_pairing_is_one
from .hash_to_curve import hash_to_curve_g2, expand_message_xmd, hash_to_field_fq2
from .ciphersuite import (
    DST, keygen_from_ikm, sk_to_pk, sign, verify, aggregate_pubkeys,
    aggregate_signatures, fast_aggregate_verify, aggregate_verify,
    SignatureSet, verify_signature_sets,
)
