"""BLS12-381 field tower arithmetic over Python integers.

This is the *oracle*: a slow, obviously-correct reference implementation used to
validate the JAX/TPU kernels in ``lighthouse_tpu.ops.bls``. It mirrors the role the
``fake_crypto``/blst dual-backend split plays in the reference client
(``/root/reference/crypto/bls/src/lib.rs:8-18``): every device kernel must agree with
this module on random inputs before it is trusted.

Tower construction (standard for BLS12-381):
    Fq2  = Fq[u]  / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - (u + 1))
    Fq12 = Fq6[w] / (w^2 - v)
"""

from __future__ import annotations

# Base field modulus (public spec constant).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order (scalar field modulus).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative; |x| has Hamming weight 6).
BLS_X = -0xD201000000010000


def fq_inv(a: int) -> int:
    return pow(a % P, P - 2, P)


def fq_sqrt(a: int) -> int | None:
    """Square root in Fq (p = 3 mod 4). Returns None if a is not a QR."""
    a %= P
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a else None


class Fq2:
    """c0 + c1*u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    ZERO: "Fq2"
    ONE: "Fq2"

    def __eq__(self, o):
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def __add__(self, o):
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o):
        if isinstance(o, int):
            return Fq2(self.c0 * o, self.c1 * o)
        # (a0 + a1 u)(b0 + b1 u) = a0b0 - a1b1 + (a0b1 + a1b0) u
        return Fq2(
            self.c0 * o.c0 - self.c1 * o.c1,
            self.c0 * o.c1 + self.c1 * o.c0,
        )

    __rmul__ = __mul__

    def square(self):
        # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
        return Fq2((self.c0 + self.c1) * (self.c0 - self.c1), 2 * self.c0 * self.c1)

    def conjugate(self):
        return Fq2(self.c0, -self.c1)

    def mul_by_nonresidue(self):
        """Multiply by (u + 1), the Fq6 non-residue."""
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    def inv(self):
        # 1/(a0 + a1 u) = (a0 - a1 u) / (a0^2 + a1^2)
        t = fq_inv(self.c0 * self.c0 + self.c1 * self.c1)
        return Fq2(self.c0 * t, -self.c1 * t)

    def pow(self, e: int):
        if e < 0:
            return self.inv().pow(-e)
        res, base = Fq2.ONE, self
        while e:
            if e & 1:
                res = res * base
            base = base.square()
            e >>= 1
        return res

    def is_zero(self):
        return self.c0 == 0 and self.c1 == 0

    def sqrt(self) -> "Fq2 | None":
        """Square root in Fq2 (RFC 9380 style for q = 9 mod 16 ... BLS12-381 uses
        the p = 3 mod 4 complex-method algorithm)."""
        if self.is_zero():
            return Fq2(0, 0)
        # Algorithm (p = 3 mod 4): a1 = a^((p-3)/4); x0 = a1*a; alpha = a1*x0.
        a1 = self.pow((P - 3) // 4)
        x0 = a1 * self
        alpha = a1 * x0
        if alpha == Fq2(P - 1, 0):
            cand = Fq2(-x0.c1, x0.c0)  # u * x0
        else:
            b = (alpha + Fq2.ONE).pow((P - 1) // 2)
            cand = b * x0
        return cand if cand.square() == self else None

    def sgn0(self) -> int:
        """RFC 9380 sign of an Fq2 element."""
        s0 = self.c0 & 1
        z0 = self.c0 == 0
        s1 = self.c1 & 1
        return s0 | (z0 & s1)

    def __repr__(self):
        return f"Fq2({hex(self.c0)}, {hex(self.c1)})"


Fq2.ZERO = Fq2(0, 0)
Fq2.ONE = Fq2(1, 0)

# Frobenius coefficient for Fq2 -> handled by conjugate().

# Frobenius coefficients: for the power-k map the v / v^2 / w coefficients are
# (u+1)^((p^k-1)/3), (u+1)^(2(p^k-1)/3), (u+1)^((p^k-1)/6). We store the power-1
# constants and realize higher powers by composing the power-1 map.
_FROB_FQ6_C1_1 = Fq2(1, 1).pow((P - 1) // 3)
_FROB_FQ6_C2_1 = Fq2(1, 1).pow(2 * (P - 1) // 3)
_FROB_FQ12_C1_1 = Fq2(1, 1).pow((P - 1) // 6)


class Fq6:
    """c0 + c1*v + c2*v^2 with v^3 = u + 1."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    ZERO: "Fq6"
    ONE: "Fq6"

    def __eq__(self, o):
        return self.c0 == o.c0 and self.c1 == o.c1 and self.c2 == o.c2

    def __add__(self, o):
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o):
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self):
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o):
        if isinstance(o, Fq2):
            return Fq6(self.c0 * o, self.c1 * o, self.c2 * o)
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_nonresidue() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_nonresidue()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def square(self):
        return self * self

    def mul_by_nonresidue(self):
        """Multiply by v (for the Fq12 tower)."""
        return Fq6(self.c2.mul_by_nonresidue(), self.c0, self.c1)

    def inv(self):
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - (a1 * a2).mul_by_nonresidue()
        t1 = a2.square().mul_by_nonresidue() - a0 * a1
        t2 = a1.square() - a0 * a2
        denom = a0 * t0 + (a2 * t1 + a1 * t2).mul_by_nonresidue()
        dinv = denom.inv()
        return Fq6(t0 * dinv, t1 * dinv, t2 * dinv)

    def _frobenius1(self):
        return Fq6(
            self.c0.conjugate(),
            self.c1.conjugate() * _FROB_FQ6_C1_1,
            self.c2.conjugate() * _FROB_FQ6_C2_1,
        )

    def frobenius(self, power: int):
        out = self
        for _ in range(power % 6):
            out = out._frobenius1()
        return out

    def is_zero(self):
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __repr__(self):
        return f"Fq6({self.c0}, {self.c1}, {self.c2})"


Fq6.ZERO = Fq6(Fq2.ZERO, Fq2.ZERO, Fq2.ZERO)
Fq6.ONE = Fq6(Fq2.ONE, Fq2.ZERO, Fq2.ZERO)


def _frob_fq2(a: Fq2, power: int) -> Fq2:
    return a if power % 2 == 0 else a.conjugate()


class Fq12:
    """c0 + c1*w with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0, self.c1 = c0, c1

    ZERO: "Fq12"
    ONE: "Fq12"

    def __eq__(self, o):
        return self.c0 == o.c0 and self.c1 == o.c1

    def __add__(self, o):
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o):
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_nonresidue()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fq12(c0, c1)

    def square(self):
        a0, a1 = self.c0, self.c1
        t0 = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_by_nonresidue()) - t0 - t0.mul_by_nonresidue()
        return Fq12(c0, t0 + t0)

    def conjugate(self):
        """The p^6 Frobenius: negate the w coefficient."""
        return Fq12(self.c0, -self.c1)

    def inv(self):
        t = (self.c0.square() - self.c1.square().mul_by_nonresidue()).inv()
        return Fq12(self.c0 * t, -(self.c1 * t))

    def _frobenius1(self):
        c0 = self.c0._frobenius1()
        c1 = self.c1._frobenius1()
        c1 = Fq6(c1.c0 * _FROB_FQ12_C1_1, c1.c1 * _FROB_FQ12_C1_1, c1.c2 * _FROB_FQ12_C1_1)
        return Fq12(c0, c1)

    def frobenius(self, power: int):
        out = self
        for _ in range(power % 12):
            out = out._frobenius1()
        return out

    def pow(self, e: int):
        if e < 0:
            return self.inv().pow(-e)
        res, base = Fq12.ONE, self
        while e:
            if e & 1:
                res = res * base
            base = base.square()
            e >>= 1
        return res

    def cyclotomic_square(self):
        """Granger-Scott squaring for elements of the cyclotomic subgroup
        (norm 1 after the easy part of the final exponentiation)."""
        # Decompose into Fq4 pieces: (c0.c0, c1.c1), (c1.c0, c0.c2), (c0.c1, c1.c2)
        z0, z4, z3, z2, z1, z5 = (
            self.c0.c0, self.c0.c1, self.c0.c2, self.c1.c0, self.c1.c1, self.c1.c2,
        )

        def fq4_square(a: Fq2, b: Fq2):
            t0 = a.square()
            t1 = b.square()
            return t1.mul_by_nonresidue() + t0, (a + b).square() - t0 - t1

        t0, t1 = fq4_square(z0, z1)
        t2, t3 = fq4_square(z2, z3)
        t4, t5 = fq4_square(z4, z5)
        z0 = (t0 - z0) * 2 + t0
        z1 = (t1 + z1) * 2 + t1
        z2 = (t5.mul_by_nonresidue() + z2) * 2 + t5.mul_by_nonresidue()
        z3 = (t4 - z3) * 2 + t4
        z4 = (t2 - z4) * 2 + t2
        z5 = (t3 + z5) * 2 + t3
        return Fq12(Fq6(z0, z4, z3), Fq6(z2, z1, z5))

    def is_one(self):
        return self == Fq12.ONE

    def __repr__(self):
        return f"Fq12({self.c0}, {self.c1})"


Fq12.ZERO = Fq12(Fq6.ZERO, Fq6.ZERO)
Fq12.ONE = Fq12(Fq6.ONE, Fq6.ZERO)
