"""BLS12-381 G1/G2 group arithmetic + ZCash-format serialization (oracle).

Parity targets in the reference:
  - point types / compression: ``/root/reference/crypto/bls/src/generic_public_key.rs``
    (48-byte compressed G1 pubkeys) and ``generic_signature.rs`` (96-byte compressed
    G2 signatures).
  - subgroup checks: blst's ``key_validate`` / sig group-check behavior used at
    ``/root/reference/crypto/bls/src/impls/blst.rs:75``.

Points are affine (x, y) with a separate infinity flag; hot loops use Jacobian
coordinates internally. Fq elements are Python ints, Fq2 elements `fields.Fq2`.
"""

from __future__ import annotations

from .fields import P, R, Fq2, fq_inv, fq_sqrt

# Curve coefficients: E1: y^2 = x^3 + 4;  E2: y^2 = x^3 + 4(u+1).
B1 = 4
B2 = Fq2(4, 4)

# Generators (spec constants).
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = Fq2(
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = Fq2(
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

INF = None  # affine representation of the point at infinity


# --------------------------------------------------------------------------------------
# Generic affine/Jacobian arithmetic, parameterized by the field.
# Field ops are dispatched through small helper lambdas so the same code serves
# Fq (ints) and Fq2.
# --------------------------------------------------------------------------------------

class _Ops:
    """Field operation table for int (Fq) or Fq2 elements."""

    def __init__(self, is_fq2: bool):
        if is_fq2:
            self.add = lambda a, b: a + b
            self.sub = lambda a, b: a - b
            self.mul = lambda a, b: a * b
            self.sqr = lambda a: a.square()
            self.neg = lambda a: -a
            self.inv = lambda a: a.inv()
            self.eq = lambda a, b: a == b
            self.zero = Fq2.ZERO
            self.one = Fq2.ONE
            self.is_zero = lambda a: a.is_zero()
        else:
            self.add = lambda a, b: (a + b) % P
            self.sub = lambda a, b: (a - b) % P
            self.mul = lambda a, b: (a * b) % P
            self.sqr = lambda a: (a * a) % P
            self.neg = lambda a: (-a) % P
            self.inv = fq_inv
            self.eq = lambda a, b: a % P == b % P
            self.zero = 0
            self.one = 1
            self.is_zero = lambda a: a % P == 0


OPS_FQ = _Ops(False)
OPS_FQ2 = _Ops(True)


def _jac_double(p, ops):
    """Jacobian doubling (a = 0 curve)."""
    if p is None:
        return None
    x, y, z = p
    if ops.is_zero(y):
        return None
    a = ops.sqr(x)
    b = ops.sqr(y)
    c = ops.sqr(b)
    d = ops.sub(ops.sqr(ops.add(x, b)), ops.add(a, c))
    d = ops.add(d, d)
    e = ops.add(ops.add(a, a), a)
    f = ops.sqr(e)
    x3 = ops.sub(f, ops.add(d, d))
    c8 = ops.add(ops.add(c, c), ops.add(c, c))
    c8 = ops.add(c8, c8)
    y3 = ops.sub(ops.mul(e, ops.sub(d, x3)), c8)
    z3 = ops.mul(ops.add(y, y), z)
    return (x3, y3, z3)


def _jac_add(p, q, ops):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = ops.sqr(z1)
    z2z2 = ops.sqr(z2)
    u1 = ops.mul(x1, z2z2)
    u2 = ops.mul(x2, z1z1)
    s1 = ops.mul(ops.mul(y1, z2), z2z2)
    s2 = ops.mul(ops.mul(y2, z1), z1z1)
    if ops.eq(u1, u2):
        if ops.eq(s1, s2):
            return _jac_double(p, ops)
        return None
    h = ops.sub(u2, u1)
    i = ops.sqr(ops.add(h, h))
    j = ops.mul(h, i)
    rr = ops.add(ops.sub(s2, s1), ops.sub(s2, s1))
    v = ops.mul(u1, i)
    x3 = ops.sub(ops.sub(ops.sqr(rr), j), ops.add(v, v))
    s1j = ops.mul(s1, j)
    y3 = ops.sub(ops.mul(rr, ops.sub(v, x3)), ops.add(s1j, s1j))
    z3 = ops.mul(ops.sub(ops.sqr(ops.add(z1, z2)), ops.add(z1z1, z2z2)), h)
    return (x3, y3, z3)


def _to_jac(p, ops):
    return None if p is None else (p[0], p[1], ops.one)


def _to_affine(p, ops):
    if p is None:
        return None
    x, y, z = p
    zi = ops.inv(z)
    zi2 = ops.sqr(zi)
    return (ops.mul(x, zi2), ops.mul(y, ops.mul(zi2, zi)))


def _mul(p, k: int, ops):
    """Scalar multiplication (double-and-add, MSB first)."""
    if k < 0:
        p = _neg_affine(p, ops)
        k = -k
    acc = None
    pj = _to_jac(p, ops)
    for bit in bin(k)[2:] if k else "":
        acc = _jac_double(acc, ops)
        if bit == "1":
            acc = _jac_add(acc, pj, ops)
    return _to_affine(acc, ops)


def _add_affine(p, q, ops):
    return _to_affine(_jac_add(_to_jac(p, ops), _to_jac(q, ops), ops), ops)


def _neg_affine(p, ops):
    return None if p is None else (p[0], ops.neg(p[1]))


# --------------------------------------------------------------------------------------
# G1 (over Fq)
# --------------------------------------------------------------------------------------

def g1_generator():
    return (G1_X, G1_Y)


def g1_add(p, q):
    return _add_affine(p, q, OPS_FQ)


def g1_neg(p):
    return _neg_affine(p, OPS_FQ)


def g1_mul(p, k: int):
    return _mul(p, k, OPS_FQ)


def g1_is_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - (x * x * x + B1)) % P == 0


def g1_in_subgroup(p) -> bool:
    return g1_is_on_curve(p) and g1_mul(p, R) is None


def g1_msm(points, scalars):
    """Naive multi-scalar multiplication (oracle only)."""
    acc = None
    for pt, s in zip(points, scalars):
        acc = g1_add(acc, g1_mul(pt, s))
    return acc


# --------------------------------------------------------------------------------------
# G2 (over Fq2)
# --------------------------------------------------------------------------------------

def g2_generator():
    return (G2_X, G2_Y)


def g2_add(p, q):
    return _add_affine(p, q, OPS_FQ2)


def g2_neg(p):
    return _neg_affine(p, OPS_FQ2)


def g2_mul(p, k: int):
    return _mul(p, k, OPS_FQ2)


def g2_is_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return y.square() == x.square() * x + B2


def g2_in_subgroup(p) -> bool:
    return g2_is_on_curve(p) and g2_mul(p, R) is None


# --------------------------------------------------------------------------------------
# Serialization — ZCash/Ethereum compressed format.
#   G1: 48 bytes big-endian x | flags in top 3 bits of byte 0.
#   G2: 96 bytes: x.c1 (48B, flagged) || x.c0 (48B).
#   flags: bit7 compression=1, bit6 infinity, bit5 y-sign (lexicographically largest).
# --------------------------------------------------------------------------------------

_HALF_P = (P - 1) // 2


def g1_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0]) + bytes(47)
    x, y = p
    flags = 0x80 | (0x20 if y > _HALF_P else 0)
    b = bytearray(x.to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g1_decompress(data: bytes):
    """Returns the affine point, or raises ValueError on invalid encoding.
    Performs on-curve check; subgroup check is the caller's responsibility
    (mirroring blst's split between deserialize and key_validate)."""
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    c_flag = (data[0] >> 7) & 1
    i_flag = (data[0] >> 6) & 1
    s_flag = (data[0] >> 5) & 1
    if not c_flag:
        raise ValueError("uncompressed flag on compressed input")
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if i_flag:
        if x != 0 or s_flag:
            raise ValueError("invalid infinity encoding")
        return None
    if x >= P:
        raise ValueError("x >= p")
    y = fq_sqrt((x * x * x + B1) % P)
    if y is None:
        raise ValueError("x not on curve")
    if (y > _HALF_P) != bool(s_flag):
        y = P - y
    return (x, y)


def g2_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0]) + bytes(95)
    x, y = p
    # sign: lexicographically largest comparing c1 then c0
    if y.c1 != 0:
        sign = y.c1 > _HALF_P
    else:
        sign = y.c0 > _HALF_P
    flags = 0x80 | (0x20 if sign else 0)
    b = bytearray(x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g2_decompress(data: bytes):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    c_flag = (data[0] >> 7) & 1
    i_flag = (data[0] >> 6) & 1
    s_flag = (data[0] >> 5) & 1
    if not c_flag:
        raise ValueError("uncompressed flag on compressed input")
    x_c1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x_c0 = int.from_bytes(data[48:], "big")
    if i_flag:
        if x_c0 != 0 or x_c1 != 0 or s_flag:
            raise ValueError("invalid infinity encoding")
        return None
    if x_c0 >= P or x_c1 >= P:
        raise ValueError("x >= p")
    x = Fq2(x_c0, x_c1)
    y = (x.square() * x + B2).sqrt()
    if y is None:
        raise ValueError("x not on curve")
    if y.c1 != 0:
        sign = y.c1 > _HALF_P
    else:
        sign = y.c0 > _HALF_P
    if sign != bool(s_flag):
        y = -y
    return (x, y)
