"""Ethereum BLS signature ciphersuite (oracle backend).

BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_ — minimal-pubkey-size variant:
public keys in G1 (48 B compressed), signatures in G2 (96 B compressed).

This module is the oracle twin of the reference's blst backend
(``/root/reference/crypto/bls/src/impls/blst.rs``):

  * sign / verify / aggregate                 -> blst.rs:172-283 equivalents
  * verify_multiple_aggregate_signatures      -> blst.rs:37-119 (random linear
    combination batch verification with 64-bit scalars, RAND_BITS at blst.rs:16)
  * key validation (infinity + subgroup)      -> blst.rs:75 key_validate

Used (a) as the trusted reference for the JAX kernels, and (b) as the portable
CPU fallback backend behind the `SignatureSet` seam.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from .fields import R
from .curves import (
    g1_generator, g1_add, g1_neg, g1_mul, g1_compress, g1_decompress, g1_in_subgroup,
    g2_add, g2_mul, g2_compress, g2_decompress, g2_in_subgroup,
)
from .hash_to_curve import hash_to_curve_g2
from .pairing import multi_pairing_is_one

DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# Matches blst.rs:16 — 64-bit random scalars are enough for batch soundness.
RAND_BITS = 64


def hash_to_g2(message: bytes):
    return hash_to_curve_g2(message, DST)


def keygen_from_ikm(ikm: bytes, key_info: bytes = b"") -> int:
    """RFC-style HKDF KeyGen (draft-irtf-cfrg-bls-signature-05 2.3)."""
    import hmac

    def hkdf_extract(salt, ikm_):
        return hmac.new(salt, ikm_, hashlib.sha256).digest()

    def hkdf_expand(prk, info, length):
        out, t, i = b"", b"", 1
        while len(out) < length:
            t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
            out += t
            i += 1
        return out[:length]

    if len(ikm) < 32:
        raise ValueError("IKM must be at least 32 bytes (BLS keygen spec 2.3)")
    salt = b"BLS-SIG-KEYGEN-SALT-"
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = hkdf_extract(salt, ikm + b"\x00")
        okm = hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % R
    return sk


def sk_to_pk(sk: int):
    return g1_mul(g1_generator(), sk % R)


def sign(sk: int, message: bytes):
    return g2_mul(hash_to_g2(message), sk % R)


def pk_validate(pk) -> bool:
    """blst key_validate: not infinity, on curve, in subgroup."""
    return pk is not None and g1_in_subgroup(pk)


def sig_validate(sig, allow_infinity: bool = False) -> bool:
    if sig is None:
        return allow_infinity
    return g2_in_subgroup(sig)


def verify(pk, message: bytes, sig) -> bool:
    if not pk_validate(pk) or not sig_validate(sig):
        return False
    # e(pk, H(m)) == e(g1, sig)  <=>  e(pk, H(m)) * e(-g1, sig) == 1
    return multi_pairing_is_one(
        [(pk, hash_to_g2(message)), (g1_neg(g1_generator()), sig)]
    )


def aggregate_pubkeys(pks):
    acc = None
    for pk in pks:
        acc = g1_add(acc, pk)
    return acc


def aggregate_signatures(sigs):
    acc = None
    for s in sigs:
        acc = g2_add(acc, s)
    return acc


def fast_aggregate_verify(pks, message: bytes, sig) -> bool:
    """All signers signed the same message (Ethereum attestation aggregation)."""
    if not pks or not all(pk_validate(pk) for pk in pks) or not sig_validate(sig):
        return False
    return verify_already_validated(aggregate_pubkeys(pks), message, sig)


def aggregate_verify(pks, messages, sig) -> bool:
    """Distinct messages per signer."""
    if not pks or len(pks) != len(messages):
        return False
    if not all(pk_validate(pk) for pk in pks) or not sig_validate(sig):
        return False
    pairs = [(pk, hash_to_g2(m)) for pk, m in zip(pks, messages)]
    pairs.append((g1_neg(g1_generator()), sig))
    return multi_pairing_is_one(pairs)


def verify_already_validated(pk, message: bytes, sig) -> bool:
    if pk is None or sig is None:
        return False
    return multi_pairing_is_one(
        [(pk, hash_to_g2(message)), (g1_neg(g1_generator()), sig)]
    )


@dataclass
class SignatureSet:
    """One verification task: signature over message by (the aggregate of)
    signing_keys. Mirrors GenericSignatureSet
    (``/root/reference/crypto/bls/src/generic_signature_set.rs:61-72``)."""

    signature: object          # G2 point or None
    signing_keys: list         # list of G1 points (pre-validated)
    message: bytes             # 32-byte signing root


def verify_signature_sets(sets: list[SignatureSet], rand_fn=None) -> bool:
    """Random-linear-combination batch verification (blst.rs:37-119 semantics).

    Check: prod_i e(r_i * agg_pk_i, H(m_i)) * e(-g1, sum_i r_i * sig_i) == 1.
    """
    if not sets:
        return False
    import secrets

    # Nonzero 64-bit scalars, matching blst's RAND_BITS draw (blst.rs:16,56-60).
    rand_fn = rand_fn or (lambda: secrets.randbits(RAND_BITS) or 1)
    pairs = []
    sig_acc = None
    for s in sets:
        if s.signature is None or not s.signing_keys:
            return False
        # Per-set signature group check (sigs_groupcheck in blst.rs:75-78).
        if not g2_in_subgroup(s.signature):
            return False
        r = rand_fn()
        agg_pk = aggregate_pubkeys(s.signing_keys)
        if agg_pk is None:
            return False
        pairs.append((g1_mul(agg_pk, r), hash_to_g2(s.message)))
        sig_acc = g2_add(sig_acc, g2_mul(s.signature, r))
    pairs.append((g1_neg(g1_generator()), sig_acc))
    return multi_pairing_is_one(pairs)


# Serialization re-exports for the API layer.
pubkey_to_bytes = g1_compress
pubkey_from_bytes = g1_decompress
signature_to_bytes = g2_compress
signature_from_bytes = g2_decompress
