"""RFC 9380 hash-to-curve for BLS12-381 G2 (oracle).

Suite BLS12381G2_XMD:SHA-256_SSWU_RO_ — the suite Ethereum's BLS signatures use
(DST fixed by the spec; see ciphersuite.py). Components:

  expand_message_xmd (SHA-256) -> hash_to_field (Fq2, m=2, L=64)
  -> simplified SWU on the 3-isogenous curve E' (A' = 240*u, B' = 1012*(1+u), Z = -(2+u))
  -> 3-isogeny map back to E2 -> cofactor clearing.

Cofactor clearing is done two independent ways (scalar-mul by h_eff, and the
psi-endomorphism method); tests assert they agree — this cross-validates the
remembered RFC constants, since neither path shares constants with the other.

Parity: the reference reaches hash-to-curve inside blst via
``/root/reference/crypto/bls/src/impls/blst.rs`` sign/verify (the HASH_OR_ENCODE
flag); we surface it explicitly because the TPU backend runs the map on device.
"""

from __future__ import annotations

import hashlib

from .fields import P, BLS_X, Fq2
from .curves import g2_add, g2_mul

# --- expand_message_xmd --------------------------------------------------------------

_B_IN_BYTES = 32   # SHA-256 output size
_R_IN_BYTES = 64   # SHA-256 block size


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255 or len(dst) > 255:
        raise ValueError("expand_message_xmd bounds")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = bytes(_R_IN_BYTES)
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = _sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime)
    b = [_sha256(b0 + b"\x01" + dst_prime)]
    for i in range(2, ell + 1):
        tmp = bytes(x ^ y for x, y in zip(b0, b[-1]))
        b.append(_sha256(tmp + i.to_bytes(1, "big") + dst_prime))
    return b"".join(b)[:len_in_bytes]


_L = 64  # ceil((ceil(log2(p)) + k) / 8) = ceil((381 + 128) / 8)


def hash_to_field_fq2(msg: bytes, dst: bytes, count: int) -> list[Fq2]:
    m = 2
    uniform = expand_message_xmd(msg, dst, count * m * _L)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(m):
            off = _L * (j + i * m)
            coeffs.append(int.from_bytes(uniform[off : off + _L], "big") % P)
        out.append(Fq2(coeffs[0], coeffs[1]))
    return out


# --- simplified SWU on E': y^2 = x^3 + A'x + B' --------------------------------------

ISO_A = Fq2(0, 240)
ISO_B = Fq2(1012, 1012)
SSWU_Z = Fq2(P - 2, P - 1)  # -(2 + u)


def _inv0(a: Fq2) -> Fq2:
    return Fq2(0, 0) if a.is_zero() else a.inv()


def map_to_curve_sswu(u: Fq2):
    """Simplified SWU for AB != 0 (RFC 9380 6.6.2). Returns a point on E'."""
    u2 = u.square()
    tv1 = _inv0(SSWU_Z.square() * u2.square() + SSWU_Z * u2)
    x1 = (-ISO_B) * ISO_A.inv() * (Fq2.ONE + tv1)
    if tv1.is_zero():
        x1 = ISO_B * (SSWU_Z * ISO_A).inv()
    gx1 = (x1.square() + ISO_A) * x1 + ISO_B
    x2 = SSWU_Z * u2 * x1
    gx2 = (x2.square() + ISO_A) * x2 + ISO_B
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x, y = x2, gx2.sqrt()
        assert y is not None, "SSWU: gx2 must be square when gx1 is not"
    if u.sgn0() != y.sgn0():
        y = -y
    return (x, y)


def is_on_iso_curve(p) -> bool:
    x, y = p
    return y.square() == (x.square() + ISO_A) * x + ISO_B


# --- 3-isogeny map E' -> E2 (RFC 9380 appendix E.3 constants) ------------------------

_K = {
    "x_num": [
        Fq2(0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
            0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6),
        Fq2(0,
            0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
        Fq2(0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
            0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
        Fq2(0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
            0),
    ],
    "x_den": [
        Fq2(0,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
        Fq2(0xC,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
        Fq2.ONE,
    ],
    "y_num": [
        Fq2(0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
            0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
        Fq2(0,
            0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
        Fq2(0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
            0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
        Fq2(0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
            0),
    ],
    "y_den": [
        Fq2(0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
        Fq2(0,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
        Fq2(0x12,
            0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
        Fq2.ONE,
    ],
}


def _horner(coeffs: list[Fq2], x: Fq2) -> Fq2:
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = acc * x + c
    return acc


def iso_map(p):
    """Apply the 3-isogeny E' -> E2."""
    x, y = p
    x_num = _horner(_K["x_num"], x)
    x_den = _horner(_K["x_den"], x)
    y_num = _horner(_K["y_num"], x)
    y_den = _horner(_K["y_den"], x)
    return (x_num * x_den.inv(), y * y_num * y_den.inv())


# --- cofactor clearing ---------------------------------------------------------------

# h_eff for G2 (RFC 9380 8.8.2).
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def clear_cofactor_h_eff(p):
    return g2_mul(p, H_EFF)


# psi endomorphism, computed through untwist -> frobenius -> twist so that no new
# constants are introduced (self-validating against the pairing tower).
def _psi_constants():
    from .pairing import _W  # local import to avoid cycle at module load
    w2 = _W * _W
    w3 = w2 * _W
    # untwist: X = x * w^-2 ; frobenius: X^p ; twist back: * w^2
    # psi(x, y) = (conj(x) * cx, conj(y) * cy) with:
    cx12 = w2.frobenius(1).inv() * w2  # w^2 / (w^2)^p ... as Fq12; must be Fq2-rational
    cy12 = w3.frobenius(1).inv() * w3
    def extract_fq2(a):
        # assert only the c0.c0 Fq2 coefficient is populated
        assert a.c0.c1.is_zero() and a.c0.c2.is_zero() and a.c1.is_zero(), a
        return a.c0.c0
    return extract_fq2(cx12), extract_fq2(cy12)


_PSI_CX, _PSI_CY = None, None


def psi(p):
    """The untwist-Frobenius-twist endomorphism on E2."""
    global _PSI_CX, _PSI_CY
    if _PSI_CX is None:
        _PSI_CX, _PSI_CY = _psi_constants()
    if p is None:
        return None
    x, y = p
    return (x.conjugate() * _PSI_CX, y.conjugate() * _PSI_CY)


def clear_cofactor_psi(p):
    """Budroni-Pintore fast clearing: [x^2-x-1]P + [x-1]psi(P) + psi^2(2P)."""
    x = BLS_X
    t = g2_add(g2_mul(p, x * x - x - 1), g2_mul(psi(p), x - 1))
    return g2_add(t, psi(psi(g2_mul(p, 2))))


# --- full hash_to_curve --------------------------------------------------------------

def hash_to_curve_g2(msg: bytes, dst: bytes):
    u0, u1 = hash_to_field_fq2(msg, dst, 2)
    q0 = iso_map(map_to_curve_sswu(u0))
    q1 = iso_map(map_to_curve_sswu(u1))
    return clear_cofactor_psi(g2_add(q0, q1))
