"""Fq2 / Fq6 / Fq12 tower arithmetic as JAX kernels (plan-compiled).

Flat element layout (see plans.py): fq2 = [..., 2, 25], fq6 = [..., 6, 25],
fq12 = [..., 12, 25] of uint64 16-bit limbs, Montgomery form, "public" bounds
(16-bit limbs, value < 16p — reduced mod p only at comparisons/serialization).

Every multiplication-bearing op runs as lincomb -> one stacked mont_mul -> lincomb
via a prebuilt plan. Additions are lazy (no carries). Fixed-exponent walks use
lax.scan. Tower layout matches the oracle (``ops.bls_oracle.fields``): Fq2 =
Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3-(u+1)), Fq12 = Fq6[w]/(w^2-v).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import fq
from . import plans
from .plans import PUB_BOUND, _Bound
from ..bls_oracle import fields as _of

# --------------------------------------------------------------------------------------
# Generic helpers on flat elements
# --------------------------------------------------------------------------------------

def t_add(a, b):
    """Lazy add (any width)."""
    return a + b


def t_sub(a, b, b_bound: _Bound = PUB_BOUND):
    """Lazy a - b via a borrow-inflated constant that limb-wise dominates b's
    static bound. Callers with non-public b must pass its exact bound."""
    sc, _ = plans._subc(b_bound.limb, b_bound.top)
    return a + (jnp.asarray(sc) - b)


def t_neg(b, b_bound: _Bound = PUB_BOUND):
    sc, _ = plans._subc(b_bound.limb, b_bound.top)
    return jnp.asarray(sc) - b


def nr_bound(in_b: _Bound = PUB_BOUND) -> _Bound:
    """Static bound of fq2_mul_by_nonresidue output given its input bound:
    c0' = c0 + (C - c1) and c1' = c0 + c1."""
    return plans.sub_bound(in_b, in_b) | in_b.scaled(2)


def t_select(cond, a, b):
    """cond ? a : b with cond of batch shape (no component/limb axes)."""
    return jnp.where(cond[..., None, None], a, b)


def t_canon(a):
    """Fully reduce each coefficient mod p (for comparisons / serialization):
    one stacked Montgomery multiply by R (same op as fq.normalize)."""
    return fq.normalize(a)


def t_eq(a, b, b_bound: _Bound = PUB_BOUND):
    """Equality mod p via ONE canonicalization of the lazy difference (a == b
    iff canonical(a - b) == 0) — half the program size of canonicalizing both
    sides."""
    return jnp.all(fq.canonical(t_sub(a, b, b_bound)) == 0, axis=(-2, -1))


def t_is_zero(a):
    return jnp.all(t_canon(a) == 0, axis=(-2, -1))


def zero(k: int, shape=()):
    return jnp.zeros(shape + (k, fq.NLIMBS), dtype=jnp.uint64)


def one(k: int, shape=()):
    z = np.zeros((k, fq.NLIMBS), dtype=np.uint64)
    z[0] = np.asarray(fq.int_to_limbs(fq.R_MONT % _of.P))
    return jnp.broadcast_to(jnp.asarray(z), shape + (k, fq.NLIMBS))


# host <-> device ----------------------------------------------------------------------

def from_ints(coeffs, mont: bool = True):
    """list of k ints -> [k, 25]."""
    return fq.from_ints(coeffs, mont)


def to_ints(a, mont: bool = True):
    arr = np.asarray(a)
    assert arr.ndim == 2
    return [fq.to_int(arr[i], mont) for i in range(arr.shape[0])]


def fq2_from_oracle(x: _of.Fq2):
    return from_ints([x.c0, x.c1])


def fq2_to_oracle(a) -> _of.Fq2:
    a = np.asarray(t_canon(a))
    return _of.Fq2(*to_ints(a))


def fq6_from_oracle(x: _of.Fq6):
    return from_ints([x.c0.c0, x.c0.c1, x.c1.c0, x.c1.c1, x.c2.c0, x.c2.c1])


def fq12_from_oracle(x: _of.Fq12):
    return from_ints(
        [
            x.c0.c0.c0, x.c0.c0.c1, x.c0.c1.c0, x.c0.c1.c1, x.c0.c2.c0, x.c0.c2.c1,
            x.c1.c0.c0, x.c1.c0.c1, x.c1.c1.c0, x.c1.c1.c1, x.c1.c2.c0, x.c1.c2.c1,
        ]
    )


def fq12_to_oracle(a) -> _of.Fq12:
    v = to_ints(np.asarray(t_canon(a)))
    f2 = lambda i: _of.Fq2(v[i], v[i + 1])
    return _of.Fq12(
        _of.Fq6(f2(0), f2(2), f2(4)),
        _of.Fq6(f2(6), f2(8), f2(10)),
    )


def fq6_to_oracle(a) -> _of.Fq6:
    v = to_ints(np.asarray(t_canon(a)))
    f2 = lambda i: _of.Fq2(v[i], v[i + 1])
    return _of.Fq6(f2(0), f2(2), f2(4))


# --------------------------------------------------------------------------------------
# Fq2
# --------------------------------------------------------------------------------------

def fq2_mul(a, b, in_bound=PUB_BOUND):
    return plans.execute(plans.MUL2, a, b, in_bound, in_bound, "fq2_mul")


def fq2_sqr(a, in_bound=PUB_BOUND):
    return plans.execute(plans.SQR2, a, a, in_bound, in_bound, "fq2_sqr")


def fq2_add(a, b):
    return a + b


def fq2_sub(a, b, b_bound: _Bound = PUB_BOUND):
    return t_sub(a, b, b_bound)


def fq2_neg(a, b_bound: _Bound = PUB_BOUND):
    return t_neg(a, b_bound)


def fq2_conj(a, b_bound: _Bound = PUB_BOUND):
    return jnp.stack([a[..., 0, :], t_neg(a[..., 1, :], b_bound)], axis=-2)


def fq2_mul_by_nonresidue(a, b_bound: _Bound = PUB_BOUND):
    """(u+1) * a = (c0 - c1, c0 + c1). Output bound: nr_bound(b_bound)."""
    c0, c1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([t_sub(c0, c1, b_bound), c0 + c1], axis=-2)


def fq2_inv(a):
    """1/(c0 + c1 u) = (c0 - c1 u) / (c0^2 + c1^2); inv0 semantics for zero.
    Accepts public-bounded input."""
    a = t_canon(a)
    c0, c1 = a[..., 0, :], a[..., 1, :]
    n = fq.mont_sqr(c0) + fq.mont_sqr(c1)
    t = fq.inv(n)  # canonical
    r = fq.mont_mul(
        jnp.stack([c0, fq.neg(c1)], axis=-2),
        jnp.broadcast_to(t[..., None, :], a.shape),
    )
    return r


def fq2_pow_fixed(a, e: int):
    """a^e for a fixed exponent (windowed table scan; see fq.windowed_pow)."""
    return fq.windowed_pow(a, e, fq2_sqr, fq2_mul, one(2))


def fq2_sgn0(a):
    c = fq.from_mont(a)  # one canonicalization (from_mont fully reduces)
    c0, c1 = c[..., 0, :], c[..., 1, :]
    s0 = c0[..., 0] & jnp.uint64(1)
    z0 = fq.is_zero(c0)
    s1 = c1[..., 0] & jnp.uint64(1)
    return s0 | (z0.astype(jnp.uint64) & s1)


def fq2_sqrt(a):
    """Square root in Fq2 (p = 3 mod 4). Returns (root, is_square)."""
    a1 = fq2_pow_fixed(a, (_of.P - 3) // 4)
    x0 = fq2_mul(a1, a)
    alpha = fq2_mul(a1, x0)
    minus_one = from_ints([_of.P - 1, 0])
    is_minus_one = t_eq(alpha, jnp.broadcast_to(minus_one, alpha.shape))
    x0c = t_canon(x0)
    cand_a = jnp.stack(
        [fq.neg(x0c[..., 1, :]), x0c[..., 0, :]], axis=-2
    )  # u * x0
    b = fq2_pow_fixed(fq2_add(alpha, one(2, alpha.shape[:-2])), (_of.P - 1) // 2)
    cand_b = fq2_mul(b, x0)
    root = t_select(is_minus_one, cand_a, cand_b)
    ok = t_eq(fq2_sqr(root), a)
    return root, ok


# Stacked many-muls: k independent fq2 products in one kernel (for curve formulas).
_MUL2_MANY: dict[int, plans.Plan] = {}


def _mul2_many_plan(k: int) -> plans.Plan:
    if k not in _MUL2_MANY:
        p = plans.Plan(2 * k, 2 * k)
        out = []
        for i in range(k):
            x = [plans.LC.basis(2 * i), plans.LC.basis(2 * i + 1)]
            out += p.mul2(x, x)  # a_rows index the A input, b_rows the B input
        p.out_rows = out
        _MUL2_MANY[k] = p
    return _MUL2_MANY[k]


def fq2_mul_many(pairs, in_bound=PUB_BOUND):
    """pairs: list of (a, b) fq2 arrays (same batch shape). One kernel for all.
    Returns list of fq2 products."""
    k = len(pairs)
    plan = _mul2_many_plan(k)
    A = jnp.concatenate([p[0] for p in pairs], axis=-2)  # [..., 2k, 25]
    B = jnp.concatenate([p[1] for p in pairs], axis=-2)
    out = plans.execute(plan, A, B, in_bound, in_bound, f"fq2_mul_many{k}")
    return [out[..., 2 * i : 2 * i + 2, :] for i in range(k)]


# --------------------------------------------------------------------------------------
# Fq6 (used by fq12 inversion)
# --------------------------------------------------------------------------------------

def fq6_mul(a, b, in_bound=PUB_BOUND):
    return plans.execute(plans.MUL6, a, b, in_bound, in_bound, "fq6_mul")


def fq6_nr(a):
    """v * a: rotate fq2 slots and apply (u+1) to the last."""
    c2 = fq2_mul_by_nonresidue(a[..., 4:6, :])
    return jnp.concatenate([c2, a[..., 0:4, :]], axis=-2)


def fq6_neg(a, b_bound: _Bound = PUB_BOUND):
    return t_neg(a, b_bound)


def fq6_inv(a):
    PUB = PUB_BOUND
    a0, a1, a2 = a[..., 0:2, :], a[..., 2:4, :], a[..., 4:6, :]
    s0, s2, s1, m12, m01, m02 = fq2_mul_many(
        [(a0, a0), (a2, a2), (a1, a1), (a1, a2), (a0, a1), (a0, a2)]
    )
    # exact static bounds threaded through every lazy sub
    nrb = nr_bound(PUB)
    t0 = t_sub(s0, fq2_mul_by_nonresidue(m12), nrb)
    t0_b = plans.sub_bound(PUB, nrb)
    t1 = fq2_sub(fq2_mul_by_nonresidue(s2), m01)
    t1_b = plans.sub_bound(nrb, PUB)
    t2 = fq2_sub(s1, m02)
    t2_b = plans.sub_bound(PUB, PUB)
    lazy = t0_b | t1_b | t2_b
    m0, m1, m2 = fq2_mul_many([(a0, t0), (a2, t1), (a1, t2)], in_bound=lazy)
    denom = fq2_add(m0, fq2_mul_by_nonresidue(fq2_add(m1, m2), PUB.scaled(2)))
    dinv = fq2_inv(denom)
    r0, r1, r2 = fq2_mul_many(
        [(t0, dinv), (t1, dinv), (t2, dinv)], in_bound=lazy
    )
    return jnp.concatenate([r0, r1, r2], axis=-2)


# --------------------------------------------------------------------------------------
# Fq12
# --------------------------------------------------------------------------------------

def fq12_mul(a, b, in_bound=PUB_BOUND):
    return plans.execute(plans.MUL12, a, b, in_bound, in_bound, "fq12_mul")


def fq12_sqr(a, in_bound=PUB_BOUND):
    return plans.execute(plans.SQR12, a, a, in_bound, in_bound, "fq12_sqr")


def fq12_conj(a):
    """p^6 Frobenius: negate the w coefficient (last 6 fq coefficients).
    Output is carry-normalized so downstream plans' PUB_BOUND contract holds."""
    return jnp.concatenate(
        [a[..., 0:6, :], plans.carry_norm(fq6_neg(a[..., 6:12, :]))], axis=-2
    )


def fq12_inv(a):
    a0, a1 = a[..., 0:6, :], a[..., 6:12, :]
    s0 = fq6_mul(a0, a0)
    s1 = fq6_mul(a1, a1)
    t = fq6_inv(t_canon(t_sub(s0, fq6_nr(s1), nr_bound(PUB_BOUND))))
    c0 = fq6_mul(a0, t)
    c1 = plans.carry_norm(fq6_neg(fq6_mul(a1, t)))
    return jnp.concatenate([c0, c1], axis=-2)


def fq12_frobenius1(a):
    return plans.execute(plans.FROB12, a, a, PUB_BOUND, PUB_BOUND, "frob12")


def fq12_frobenius(a, power: int):
    for _ in range(power % 12):
        a = fq12_frobenius1(a)
    return a


def fq12_cyclotomic_sqr(a, in_bound=PUB_BOUND):
    return plans.execute(plans.CYC_SQR, a, a, in_bound, in_bound, "cyc_sqr")


def fq12_cyclotomic_exp_abs_x(a):
    """a^|x| (|x| = 0xd201000000010000, popcount 6): the exponent is fixed at
    trace time, so zero bits are squarings only — 63 cyc_sqr + 5 fq12_mul
    instead of the ladder's 63 x (cyc_sqr + mul + select). Final
    exponentiation calls this 5 times; the segment schedule runs as one
    lax.scan (dynamic-count cyc-sqr fori_loop + masked multiply) so each call
    site compiles a single (sqr + mul) body instead of unrolling the chain."""
    from .curve import fixed_schedule

    segs = fixed_schedule(-_of.BLS_X)
    runs = jnp.asarray([r for r, _ in segs], dtype=jnp.int32)
    muls = jnp.asarray([m for _, m in segs], dtype=jnp.int32)

    def seg_body(res, seg):
        run, mulf = seg
        res = jax.lax.fori_loop(
            0, run, lambda _, g: fq12_cyclotomic_sqr(g), res
        )
        return t_select(mulf == 1, fq12_mul(res, a), res), None

    res, _ = jax.lax.scan(seg_body, a, (runs, muls))
    return res


def fq12_is_one(a):
    return t_eq(a, one(12, a.shape[:-2]))
