"""Fq2 / Fq6 / Fq12 tower arithmetic as JAX kernels (plan-compiled).

Flat element layout (see plans.py): fq2 = [..., 2, 25], fq6 = [..., 6, 25],
fq12 = [..., 12, 25] of uint64 limbs, plain residues (no Montgomery domain),
"public" bounds — plans.PUB_BOUND: 17-bit limbs, value < 16p, top limb <= 2 —
reduced mod p only at comparisons/serialization. Bound claims here are
machine-checked by the limb-bound certifier (analysis/bounds.py).

Every multiplication-bearing op runs as lincomb -> one stacked mont_mul -> lincomb
via a prebuilt plan. Additions are lazy (no carries). Fixed-exponent walks use
lax.scan. Tower layout matches the oracle (``ops.bls_oracle.fields``): Fq2 =
Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3-(u+1)), Fq12 = Fq6[w]/(w^2-v).
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from . import fq
from . import plans
from .plans import PUB_BOUND, _Bound
from ..bls_oracle import fields as _of

# --------------------------------------------------------------------------------------
# Generic helpers on flat elements
# --------------------------------------------------------------------------------------

def t_add(a, b):
    """Lazy add (any width)."""
    return a + b


def t_sub(a, b, b_bound: _Bound = PUB_BOUND):
    """Lazy a - b via a borrow-inflated constant that limb-wise dominates b's
    static bound. Callers with non-public b must pass its exact bound."""
    sc, _ = plans._subc(b_bound.limb, b_bound.top)
    return a + (jnp.asarray(sc) - b)


def t_neg(b, b_bound: _Bound = PUB_BOUND):
    sc, _ = plans._subc(b_bound.limb, b_bound.top)
    return jnp.asarray(sc) - b


def nr_bound(in_b: _Bound = PUB_BOUND) -> _Bound:
    """Static bound of fq2_mul_by_nonresidue output given its input bound:
    c0' = c0 + (C - c1) and c1' = c0 + c1."""
    return plans.sub_bound(in_b, in_b) | in_b.scaled(2)


def t_select(cond, a, b):
    """cond ? a : b with cond of batch shape (no component/limb axes)."""
    return jnp.where(cond[..., None, None], a, b)


def t_canon(a):
    """Fully reduce each coefficient mod p (for comparisons / serialization):
    one stacked congruence-fold reduction walk (same op as fq.normalize)."""
    return fq.normalize(a)


def t_eq(a, b, b_bound: _Bound = PUB_BOUND):
    """Equality mod p via ONE canonicalization of the lazy difference (a == b
    iff canonical(a - b) == 0) — half the program size of canonicalizing both
    sides."""
    return jnp.all(fq.canonical(t_sub(a, b, b_bound)) == 0, axis=(-2, -1))


def t_is_zero(a):
    return jnp.all(t_canon(a) == 0, axis=(-2, -1))


def zero(k: int, shape=()):
    return jnp.zeros(shape + (k, fq.NLIMBS), dtype=jnp.uint64)


def one(k: int, shape=()):
    z = np.zeros((k, fq.NLIMBS), dtype=np.uint64)
    z[0] = np.asarray(fq.int_to_limbs(fq.R_MONT % _of.P))
    return jnp.broadcast_to(jnp.asarray(z), shape + (k, fq.NLIMBS))


# host <-> device ----------------------------------------------------------------------

def from_ints(coeffs, mont: bool = True):
    """list of k ints -> [k, 25]."""
    return fq.from_ints(coeffs, mont)


def to_ints(a, mont: bool = True):
    arr = np.asarray(a)
    assert arr.ndim == 2
    return [fq.to_int(arr[i], mont) for i in range(arr.shape[0])]


def fq2_from_oracle(x: _of.Fq2):
    return from_ints([x.c0, x.c1])


def fq2_to_oracle(a) -> _of.Fq2:
    a = np.asarray(t_canon(a))
    return _of.Fq2(*to_ints(a))


def fq6_from_oracle(x: _of.Fq6):
    return from_ints([x.c0.c0, x.c0.c1, x.c1.c0, x.c1.c1, x.c2.c0, x.c2.c1])


def fq12_from_oracle(x: _of.Fq12):
    return from_ints(
        [
            x.c0.c0.c0, x.c0.c0.c1, x.c0.c1.c0, x.c0.c1.c1, x.c0.c2.c0, x.c0.c2.c1,
            x.c1.c0.c0, x.c1.c0.c1, x.c1.c1.c0, x.c1.c1.c1, x.c1.c2.c0, x.c1.c2.c1,
        ]
    )


def fq12_to_oracle(a) -> _of.Fq12:
    v = to_ints(np.asarray(t_canon(a)))
    f2 = lambda i: _of.Fq2(v[i], v[i + 1])
    return _of.Fq12(
        _of.Fq6(f2(0), f2(2), f2(4)),
        _of.Fq6(f2(6), f2(8), f2(10)),
    )


def fq6_to_oracle(a) -> _of.Fq6:
    v = to_ints(np.asarray(t_canon(a)))
    f2 = lambda i: _of.Fq2(v[i], v[i + 1])
    return _of.Fq6(f2(0), f2(2), f2(4))


# --------------------------------------------------------------------------------------
# Fq2
# --------------------------------------------------------------------------------------

def fq2_mul(a, b, in_bound=PUB_BOUND):
    return plans.execute(plans.MUL2, a, b, in_bound, in_bound, "fq2_mul")


def fq2_sqr(a, in_bound=PUB_BOUND):
    return plans.execute(plans.SQR2, a, a, in_bound, in_bound, "fq2_sqr")


def fq2_add(a, b):
    return a + b


def fq2_sub(a, b, b_bound: _Bound = PUB_BOUND):
    return t_sub(a, b, b_bound)


def fq2_neg(a, b_bound: _Bound = PUB_BOUND):
    return t_neg(a, b_bound)


def fq2_conj(a, b_bound: _Bound = PUB_BOUND):
    return jnp.stack([a[..., 0, :], t_neg(a[..., 1, :], b_bound)], axis=-2)


def fq2_mul_by_nonresidue(a, b_bound: _Bound = PUB_BOUND):
    """(u+1) * a = (c0 - c1, c0 + c1). Output bound: nr_bound(b_bound)."""
    c0, c1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([t_sub(c0, c1, b_bound), c0 + c1], axis=-2)


def fq2_inv(a):
    """1/(c0 + c1 u) = (c0 - c1 u) / (c0^2 + c1^2); inv0 semantics for zero.
    Accepts public-bounded input."""
    a = t_canon(a)
    c0, c1 = a[..., 0, :], a[..., 1, :]
    n = fq.mont_sqr(c0) + fq.mont_sqr(c1)
    t = fq.inv(n)  # canonical
    r = fq.mont_mul(
        jnp.stack([c0, fq.neg(c1)], axis=-2),
        jnp.broadcast_to(t[..., None, :], a.shape),
    )
    return r


def fq2_sgn0(a):
    c = fq.from_mont(a)  # one canonicalization (from_mont fully reduces)
    return fq2_sgn0_canon(c)


def fq2_sgn0_canon(c):
    """RFC 9380 sgn0 of an ALREADY-CANONICAL element (skips the reduction
    walk — e.g. hash_to_field outputs, which arrive canonical from the
    host)."""
    c0, c1 = c[..., 0, :], c[..., 1, :]
    s0 = c0[..., 0] & jnp.uint64(1)
    z0 = fq.is_zero(c0)
    s1 = c1[..., 0] & jnp.uint64(1)
    return s0 | (z0.astype(jnp.uint64) & s1)


def fq2_sqr_lazy(a, in_bound=None):
    """Chain-interior square: lazy in/out bounds (plans.CHAIN_BOUND)."""
    b = in_bound or plans.CHAIN_BOUND
    return plans.execute(
        plans.SQR2, a, a, b, b, "fq2_sqr_c", out_bound=plans.CHAIN_BOUND
    )


def fq2_mul_lazy(a, b, in_bound=None):
    """Chain-interior product: lazy in/out bounds (plans.CHAIN_BOUND)."""
    bd = in_bound or plans.CHAIN_BOUND
    return plans.execute(
        plans.MUL2, a, b, bd, bd, "fq2_mul_c", out_bound=plans.CHAIN_BOUND
    )


# --------------------------------------------------------------------------------------
# Fq2 square roots: one fixed-exponent chain (q = p^2, q ≡ 9 mod 16)
# --------------------------------------------------------------------------------------
#
# q - 1 = 8 m with m odd, so Tonelli–Shanks needs only the 8th roots of unity:
# ONE chain t = w^((q-9)/16) (= w^((m-1)/2)) yields z = t^2 w = w^m ∈ μ8, and
# the candidate root is r = t·w (r^2 = z·w) corrected by a PRECOMPUTED
# constant c with c^2 = 1/z — no second exponentiation, unlike the classic
# two-chain (a^((p-3)/4), (α+1)^((p-1)/2)) method this replaces. The chain
# itself runs as a 2-lane joint plan (chain_plans): w^e0 · conj(w)^e1 with
# (q-9)/16 = e0 + e1·p — Frobenius in Fq2 is conjugation, so both ~381-bit
# lanes share every squaring dispatch. Non-residues (z ∈ μ8 \ μ4) fold the Z
# correction of RFC 9380's sqrt_ratio into the same constant table.

_Q = _of.P * _of.P
assert _Q % 16 == 9
_M8 = (_Q - 1) // 8                      # odd
_SQRT_E = (_Q - 9) // 16                 # (m-1)/2
_SQRT_E1, _SQRT_E0 = divmod(_SQRT_E, _of.P)


def _fq2_pow_host(a: "_of.Fq2", e: int) -> "_of.Fq2":
    r = _of.Fq2(1, 0)
    while e:
        if e & 1:
            r = r * a
        a = a.square()
        e >>= 1
    return r


def _sqrt_constants():
    from ..bls_oracle.fields import fq_sqrt
    from ..bls_oracle.hash_to_curve import SSWU_Z

    # zeta = b(1 - u) with b^2 = -1/2 has zeta^2 = u: an order-8 root of unity
    b = fq_sqrt((-pow(2, _of.P - 2, _of.P)) % _of.P)
    assert b is not None
    zeta = _of.Fq2(b, _of.P - b)
    assert _fq2_pow_host(zeta, 8) == _of.Fq2(1, 0)
    assert _fq2_pow_host(zeta, 4) != _of.Fq2(1, 0)
    roots8 = [_fq2_pow_host(zeta, i) for i in range(8)]
    # Z^m locates the sswu nonresidue Z inside μ8 (odd index: Z is a non-QR)
    zm = _fq2_pow_host(SSWU_Z, _M8)
    jz = roots8.index(zm)
    assert jz % 2 == 1
    z_half = _fq2_pow_host(SSWU_Z, (_M8 + 1) // 2)
    cf = []
    for j in range(8):
        if j % 2 == 0:
            # z = zeta^j square: c^2 = z^-1
            cf.append(roots8[(8 - j) // 2 % 8])
        else:
            # z odd: correct Z·w instead — (Zw)^m = zeta^(j+jz) (even)
            j2 = (j + jz) % 8
            cf.append(z_half * roots8[(8 - j2) // 2 % 8])
    roots_dev = jnp.stack([fq2_from_oracle(r) for r in roots8])
    cf_dev = jnp.stack([fq2_from_oracle(c) for c in cf])
    return roots_dev, cf_dev


_ROOTS8, _SQRT_CF = _sqrt_constants()


def _sqrt_chain(w):
    """w^((q-9)/16) as the 2-lane joint Frobenius chain."""
    from . import chain_plans

    sched = chain_plans.compile_chains((_SQRT_E0, _SQRT_E1), signed=False)
    bases = jnp.stack([w, plans.carry_norm(fq2_conj(w))])
    out = chain_plans.run_field_chains(
        sched, bases, fq2_sqr_lazy, fq2_mul_lazy, one(2)
    )
    return plans.execute(
        plans.MUL2, out[0], out[1], plans.CHAIN_BOUND, plans.CHAIN_BOUND,
        "sqrt_t",
    )


def _sqrt_core(w):
    """(is_qr, t, cf) for w: t = w^((q-9)/16); cf the μ8 correction constant.
    The caller's root is t·w·cf (times Z-folded factors for non-residues,
    already folded into cf). w == 0 -> is_qr True, root 0."""
    t = _sqrt_chain(w)
    z = fq2_mul(fq2_sqr(t), w)                    # w^m ∈ μ8 (or 0)
    zc = t_canon(z)
    matches = jnp.all(
        zc == _ROOTS8.reshape((8,) + (1,) * (zc.ndim - 2) + zc.shape[-2:]),
        axis=(-2, -1),
    )                                              # [8, *batch]
    odd = matches[1::2].any(axis=0)
    is_qr = ~odd
    cf = jnp.zeros_like(zc)
    for j in range(8):
        cf = cf + jnp.where(
            matches[j][..., None, None], _SQRT_CF[j], jnp.zeros_like(cf)
        )
    return is_qr, t, cf


def fq2_sqrt(a):
    """Square root in Fq2. Returns (root, is_square). ONE fixed-exponent
    chain (see _sqrt_core) instead of the classic two; the root's sign is
    unspecified — callers normalize (sgn0 / lex flips)."""
    is_qr, t, cf = _sqrt_core(a)
    root = fq2_mul(fq2_mul(t, a), cf)
    return root, is_qr


def fq2_sqrt_ratio(u, v):
    """RFC 9380 sqrt_ratio in Fq2: (b, y) with y^2 = u/v when b else Z·u/v
    (Z the sswu nonresidue). One chain on w = u·v^3; y = t·u·v·cf — the
    exponents are arranged so no division is needed at all."""
    v2 = fq2_sqr(v)
    uv = fq2_mul(u, v)
    w = fq2_mul(uv, v2)                            # u v^3
    is_qr, t, cf = _sqrt_core(w)
    y = fq2_mul(fq2_mul(t, uv), cf)
    return is_qr, y


# Stacked many-muls: k independent fq2 products in one kernel (for curve formulas).
_MUL2_MANY: dict[int, plans.Plan] = {}


def _mul2_many_plan(k: int) -> plans.Plan:
    if k not in _MUL2_MANY:
        p = plans.Plan(2 * k, 2 * k)
        out = []
        for i in range(k):
            x = [plans.LC.basis(2 * i), plans.LC.basis(2 * i + 1)]
            out += p.mul2(x, x)  # a_rows index the A input, b_rows the B input
        p.out_rows = out
        _MUL2_MANY[k] = p
    return _MUL2_MANY[k]


def fq2_mul_many(pairs, in_bound=PUB_BOUND):
    """pairs: list of (a, b) fq2 arrays (same batch shape). One kernel for all.
    Returns list of fq2 products."""
    k = len(pairs)
    plan = _mul2_many_plan(k)
    A = jnp.concatenate([p[0] for p in pairs], axis=-2)  # [..., 2k, 25]
    B = jnp.concatenate([p[1] for p in pairs], axis=-2)
    out = plans.execute(plan, A, B, in_bound, in_bound, f"fq2_mul_many{k}")
    return [out[..., 2 * i : 2 * i + 2, :] for i in range(k)]


# --------------------------------------------------------------------------------------
# Fq6 (used by fq12 inversion)
# --------------------------------------------------------------------------------------

def fq6_mul(a, b, in_bound=PUB_BOUND):
    return plans.execute(plans.MUL6, a, b, in_bound, in_bound, "fq6_mul")


def fq6_nr(a):
    """v * a: rotate fq2 slots and apply (u+1) to the last."""
    c2 = fq2_mul_by_nonresidue(a[..., 4:6, :])
    return jnp.concatenate([c2, a[..., 0:4, :]], axis=-2)


def fq6_neg(a, b_bound: _Bound = PUB_BOUND):
    return t_neg(a, b_bound)


def fq6_inv(a):
    PUB = PUB_BOUND
    a0, a1, a2 = a[..., 0:2, :], a[..., 2:4, :], a[..., 4:6, :]
    s0, s2, s1, m12, m01, m02 = fq2_mul_many(
        [(a0, a0), (a2, a2), (a1, a1), (a1, a2), (a0, a1), (a0, a2)]
    )
    # exact static bounds threaded through every lazy sub
    nrb = nr_bound(PUB)
    t0 = t_sub(s0, fq2_mul_by_nonresidue(m12), nrb)
    t0_b = plans.sub_bound(PUB, nrb)
    t1 = fq2_sub(fq2_mul_by_nonresidue(s2), m01)
    t1_b = plans.sub_bound(nrb, PUB)
    t2 = fq2_sub(s1, m02)
    t2_b = plans.sub_bound(PUB, PUB)
    lazy = t0_b | t1_b | t2_b
    m0, m1, m2 = fq2_mul_many([(a0, t0), (a2, t1), (a1, t2)], in_bound=lazy)
    denom = fq2_add(m0, fq2_mul_by_nonresidue(fq2_add(m1, m2), PUB.scaled(2)))
    dinv = fq2_inv(denom)
    r0, r1, r2 = fq2_mul_many(
        [(t0, dinv), (t1, dinv), (t2, dinv)], in_bound=lazy
    )
    return jnp.concatenate([r0, r1, r2], axis=-2)


# --------------------------------------------------------------------------------------
# Fq12
# --------------------------------------------------------------------------------------

def fq12_mul(a, b, in_bound=PUB_BOUND):
    return plans.execute(plans.MUL12, a, b, in_bound, in_bound, "fq12_mul")


def fq12_sqr(a, in_bound=PUB_BOUND):
    return plans.execute(plans.SQR12, a, a, in_bound, in_bound, "fq12_sqr")


def fq12_conj(a):
    """p^6 Frobenius: negate the w coefficient (last 6 fq coefficients).
    Output is carry-normalized so downstream plans' PUB_BOUND contract holds."""
    return jnp.concatenate(
        [a[..., 0:6, :], plans.carry_norm(fq6_neg(a[..., 6:12, :]))], axis=-2
    )


def fq12_inv(a):
    a0, a1 = a[..., 0:6, :], a[..., 6:12, :]
    s0 = fq6_mul(a0, a0)
    s1 = fq6_mul(a1, a1)
    t = fq6_inv(t_canon(t_sub(s0, fq6_nr(s1), nr_bound(PUB_BOUND))))
    c0 = fq6_mul(a0, t)
    c1 = plans.carry_norm(fq6_neg(fq6_mul(a1, t)))
    return jnp.concatenate([c0, c1], axis=-2)


def fq12_frobenius1(a):
    return plans.execute(plans.FROB12, a, a, PUB_BOUND, PUB_BOUND, "frob12")


def fq12_frobenius(a, power: int):
    for _ in range(power % 12):
        a = fq12_frobenius1(a)
    return a


def fq12_cyclotomic_sqr(a, in_bound=PUB_BOUND):
    return plans.execute(plans.CYC_SQR, a, a, in_bound, in_bound, "cyc_sqr")


# Lazy fq12 chain interiors: on conv-bound backends (digits) the pairing
# accumulator and the final exponentiation's cyclotomic runs keep their
# values at plans.F12_BOUND (18-bit limbs / < 64p) between multiplies,
# paying the full PUB_BOUND walk only at chain boundaries; on the f64 CPU
# path the wider inputs cost more fold rounds than the lazier target saves,
# so plans.f12_interior() resolves these to plain PUB_BOUND ops there.
# CHAIN_BOUND itself (20-bit limbs) would overflow the fq12 plans'
# input-lincomb budget — see the F12_BOUND derivation note in plans.py.

def fq12_mul_lazy(a, b, in_bound=None):
    bd, ob = plans.f12_interior()
    bd = in_bound or bd
    return plans.execute(plans.MUL12, a, b, bd, bd, "fq12_mul_c", out_bound=ob)


def fq12_sqr_lazy(a, in_bound=None):
    bd, ob = plans.f12_interior()
    bd = in_bound or bd
    return plans.execute(plans.SQR12, a, a, bd, bd, "fq12_sqr_c", out_bound=ob)


def fq12_cyclotomic_sqr_lazy(a, in_bound=None):
    bd, ob = plans.f12_interior()
    bd = in_bound or bd
    return plans.execute(plans.CYC_SQR, a, a, bd, bd, "cyc_sqr_c", out_bound=ob)


# --------------------------------------------------------------------------------------
# Karabina compressed cyclotomic squaring
# --------------------------------------------------------------------------------------
#
# In the Granger–Scott z-slot notation the cyclotomic square of the CYC_SQR
# plan reads (with xi = u+1 and t2 = z2^2 + xi z3^2, t3 = 2 z2 z3,
# t4 = z4^2 + xi z5^2, t5 = 2 z4 z5):
#
#   z2' = 6 xi z4 z5 + 2 z2        z3' = 3 (z4^2 + xi z5^2) - 2 z3
#   z4' = 3 (z2^2 + xi z3^2) - 2 z4    z5' = 6 z2 z3 + 2 z5
#
# i.e. the (z2, z3, z4, z5) quadruple is closed under squaring — the
# Karabina compression. A compressed element is [..., 8, 25] = [z2|z3|z4|z5];
# compressed squaring is a 14-lane plan (4 sqr2 + 2 mul2) reducing 8 rows,
# versus CYC_SQR's 18 lanes / 12 rows. Decompression recovers
#
#   z1 = (xi z5^2 + 3 z4^2 - 2 z3) / (4 z2)            [z2 != 0]
#   z1 = (2 z4 z5) / z3                                [z2 == 0]
#   z0 = (2 z1^2 + z2 z5 - 3 z3 z4) xi + 1
#
# with ONE fq2 inversion (inv0 semantics make the z2 == z3 == 0 identity
# element fall out as z1 = 0, z0 = 1) — callers batch the decompression of
# all bit-position collect points so the Fermat chain is paid once.

# flat fq12 layout <-> z-slots (see CYC_SQR): coefficients
# [z0(0:2) z4(2:4) z3(4:6) z2(6:8) z1(8:10) z5(10:12)].


def fq12_compress(a):
    """Cyclotomic fq12 [..., 12, 25] -> compressed [..., 8, 25] = [z2|z3|z4|z5]."""
    return jnp.concatenate(
        [a[..., 6:8, :], a[..., 4:6, :], a[..., 2:4, :], a[..., 10:12, :]],
        axis=-2,
    )


def _build_karabina_sqr() -> plans.Plan:
    from .plans import LC, v2_add, v2_nr

    p = plans.Plan(8, 8)
    x = plans.vbasis(8)
    z2, z3, z4, z5 = x[0:2], x[2:4], x[4:6], x[6:8]
    iz2 = [p.inp(0), p.inp(1)]
    iz3 = [p.inp(2), p.inp(3)]
    iz4 = [p.inp(4), p.inp(5)]
    iz5 = [p.inp(6), p.inp(7)]
    s2, s3, s4, s5 = p.sqr2(z2), p.sqr2(z3), p.sqr2(z4), p.sqr2(z5)
    m45 = p.mul2(z4, z5)
    m23 = p.mul2(z2, z3)
    t2 = v2_add(s2, v2_nr(s3))
    t4 = v2_add(s4, v2_nr(s5))

    def scale(v, k):
        return [c.scale(k) for c in v]

    z2n = v2_add(scale(v2_nr(m45), 6), scale(iz2, 2))
    z3n = [a.scale(3) - b.scale(2) for a, b in zip(t4, iz3)]
    z4n = [a.scale(3) - b.scale(2) for a, b in zip(t2, iz4)]
    z5n = v2_add(scale(m23, 6), scale(iz5, 2))
    p.out_rows = z2n + z3n + z4n + z5n
    return p


KARABINA_SQR = _build_karabina_sqr()


def fq12_compressed_sqr(c, in_bound=PUB_BOUND):
    """One Karabina squaring on a compressed element [..., 8, 25]."""
    return plans.execute(KARABINA_SQR, c, c, in_bound, in_bound, "kar_sqr")


def fq12_compressed_sqr_lazy(c, in_bound=None):
    bd, ob = plans.f12_interior()
    bd = in_bound or bd
    return plans.execute(KARABINA_SQR, c, c, bd, bd, "kar_sqr_c", out_bound=ob)


def fq12_decompress(c):
    """Compressed [..., 8, 25] (public-bounded) -> full cyclotomic fq12.
    Branchless over the z2 == 0 special case; ONE fq2 inversion (the callers'
    batch axis amortizes the Fermat chain)."""
    z2, z3, z4, z5 = (
        c[..., 0:2, :], c[..., 2:4, :], c[..., 4:6, :], c[..., 6:8, :]
    )
    s5, s4, m45, m35 = fq2_mul_many([(z5, z5), (z4, z4), (z4, z5), (z3, z4)])
    z2_zero = t_is_zero(z2)
    # numerator / denominator of z1 for both branches
    num_a = plans.carry_norm(
        t_sub(fq2_mul_by_nonresidue(s5) + s4 * np.uint64(3), z3 * np.uint64(2),
              PUB_BOUND.scaled(2))
    )
    num_b = plans.carry_norm(m45 * np.uint64(2))
    den_a = plans.carry_norm(z2 * np.uint64(4))
    num = t_select(z2_zero, num_b, num_a)
    den = t_select(z2_zero, z3, den_a)
    z1 = fq2_mul(num, fq2_inv(den))
    s1, m25 = fq2_mul_many([(z1, z1), (z2, z5)])
    z0 = plans.carry_norm(
        fq2_mul_by_nonresidue(
            plans.carry_norm(
                t_sub(s1 * np.uint64(2) + m25, m35 * np.uint64(3),
                      PUB_BOUND.scaled(3))
            )
        )
        + one(2, z1.shape[:-2])
    )
    return jnp.concatenate([z0, z4, z3, z2, z1, z5], axis=-2)


def fq12_cyclotomic_exp_abs_x(a, compressed: "bool | None" = None):
    """a^|x| (|x| = 0xd201000000010000, popcount 6), chain-plan compiled:
    the exponent's schedule comes from ``chain_plans.compile_chains`` and
    runs as ONE ``lax.scan`` of shared squaring runs with lazy fq12 interiors
    (plans.F12_BOUND) — only the result pays the full PUB_BOUND walk.

    ``compressed=True`` routes the squaring runs through the Karabina
    compressed kernel: 63 compressed squarings collect the 6 bit-position
    points, ONE batched decompression (a single fq2 Fermat chain for all 6)
    recovers them, and a halving product tree combines. The Fermat chain is a
    ~470-step scan, so compression can win only where the conv work (not the
    step count) dominates — and on BOTH measurable CPU proxies it loses (f64:
    direct unroll already 1.5x ahead; u64-digit: 300 ms compressed vs 183 ms
    direct at the bench shape, the decompression chain dominating exactly as
    the step-count model predicts). Until a ``platform: tpu`` record shows
    the f32 conv path inverting that, compression is OPT-IN:
    LIGHTHOUSE_PAIRING_KARABINA=1 flips the default."""
    if compressed is None:
        compressed = os.environ.get("LIGHTHOUSE_PAIRING_KARABINA") == "1"
    if compressed:
        return _cyc_exp_abs_x_compressed(a)
    # direct trace-time unroll of the |x| segment schedule: each doubling
    # run is one static-count fori_loop of the lazy cyclotomic square and
    # the 5 set bits are unconditional multiplies — no table, no gathered
    # operands, no masked multiply (the generic run_field_chains machinery
    # measured ~20% slower here: |x| is binary-sparse, so its "table" is
    # just the base and every gather/select is pure overhead)
    from .curve import fixed_schedule

    segs = fixed_schedule(-_of.BLS_X)
    assert segs[0] == (1, 1)
    res = fq12_mul_lazy(fq12_cyclotomic_sqr_lazy(a), a)
    for run, mul in segs[1:]:
        res = jax.lax.fori_loop(
            0, run, lambda _, g: fq12_cyclotomic_sqr_lazy(g), res
        )
        if mul:
            res = fq12_mul_lazy(res, a)
    return plans.carry_norm(res)


_ABS_X_BITS = tuple(
    i for i in range(64) if ((-_of.BLS_X) >> i) & 1
)  # (16, 48, 57, 60, 62, 63)


def _cyc_exp_abs_x_compressed(a):
    """a^|x| via compressed squarings: a^|x| = prod_e a^(2^e) over the set
    bits e of |x|; every a^(2^e) is a collect point of ONE compressed
    squaring chain, decompressed as a single batch."""
    c0 = fq12_compress(a)

    def body(cc, _):
        nxt = fq12_compressed_sqr_lazy(cc)
        return nxt, nxt

    _, states = jax.lax.scan(body, c0, None, length=max(_ABS_X_BITS))
    collect = plans.carry_norm(
        jnp.stack([states[e - 1] for e in _ABS_X_BITS], axis=0)
    )
    fs = fq12_decompress(collect)  # [6, ..., 12, 25]
    n = fs.shape[0]
    while n > 1:
        if n % 2:
            fs = jnp.concatenate(
                [fs, one(12, (1,) + fs.shape[1:-2])], axis=0
            )
            n += 1
        fs = fq12_mul(fs[: n // 2], fs[n // 2 :])
        n //= 2
    return fs[0]


def fq12_is_one(a):
    return t_eq(a, one(12, a.shape[:-2]))
