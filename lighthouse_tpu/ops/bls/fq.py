"""Fq (BLS12-381 base field) arithmetic as JAX limb kernels.

Representation: little-endian 16-bit limbs in uint64 lanes, **25 limbs** (R = 2^400
Montgomery domain), shape ``[..., 25]``. The 25th limb buys ~19 bits of headroom over
the 381-bit modulus, which enables the two properties the whole kernel stack is built
on:

  * **Lazy addition/subtraction.** ``add``/``sub``/``neg`` are pure elementwise limb
    ops — no carry propagation, no comparison, ~2 HLO ops each. Limbs grow beyond 16
    bits and values beyond p; that's fine. The operand budget (enforced statically by
    plans.lincomb) is: values < 600p and limbs < 2^22. Derivation: mont_mul needs
    t = a*b < R*p, and 600p * 600p = 360000 p^2 < (2^400/p) * p^2 since
    2^400/p > 2^18.7 > 360000; its REDC output is then t/R + p < 1.7p, made
    canonical by one conditional subtract. The schoolbook convolution is exact for
    limbs up to 2^22 (25 * 2^44 < 2^50 per uint64 accumulator). Convention: values
    crossing a public tower-op boundary satisfy plans.PUB_BOUND (16-bit limbs,
    value < 16p); lazy values live only between two Montgomery multiplies.
    ``sub(a, b)``/``neg`` here require a *canonical* (< p) subtrahend: they add the
    borrow-inflated constant 2p (every non-top limb rewritten >= 2^16 - 1). The
    tower layer (plans/tower) uses bound-tracked inflated constants instead.

  * **One normalization point.** ``mont_mul`` is the only place carries propagate
    (three lax.scan walks: REDC, carry, conditional subtract), and its output is
    canonical. Tower ops stack all their independent multiplies into one mont_mul
    call (see tower.py), so a full Fq12 multiply costs a single scan-compiled kernel.

Correctness is pinned against ``lighthouse_tpu.ops.bls_oracle`` on random inputs.
This layer is the TPU twin of the blst field backend the reference links against
(``/root/reference/crypto/bls/src/impls/blst.rs`` seam).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..bls_oracle.fields import P

jax.config.update("jax_enable_x64", True)

NLIMBS = 25
LIMB_BITS = 16
MASK = np.uint64(0xFFFF)

R_MONT = 1 << (NLIMBS * LIMB_BITS)          # 2^400
R_INV_INT = pow(R_MONT, -1, P)
N0_INT = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


def int_to_limbs(x: int) -> np.ndarray:
    """Host helper: Python int -> uint64[25] little-endian 16-bit limbs."""
    return np.array(
        [(x >> (LIMB_BITS * i)) & 0xFFFF for i in range(NLIMBS)], dtype=np.uint64
    )


def limbs_to_int(a) -> int:
    """Host helper: limb array (last axis 25, any limb values) -> Python int."""
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(a))


def _inflated_2p() -> np.ndarray:
    """Limbs of 2p rewritten so every limb except the top is >= 2^16 - 1, preserving
    the value: c_0 stays, c_i (0<i<top) := c_i - 1 + 2^16, top := top - 1."""
    c = [int(v) for v in int_to_limbs(2 * P)]
    top = max(i for i, v in enumerate(c) if v)
    for i in range(1, top + 1):
        c[i - 1] += 1 << LIMB_BITS
        c[i] -= 1
    # re-add: above loop borrowed 1 from each c_i (1..top) into c_{i-1}
    assert sum(v << (LIMB_BITS * i) for i, v in enumerate(c)) == 2 * P
    assert all(v >= (1 << LIMB_BITS) - 1 for v in c[:top])
    return np.array(c, dtype=np.uint64)


P_LIMBS = jnp.asarray(int_to_limbs(P))
SUB2P = jnp.asarray(_inflated_2p())
N0 = jnp.uint64(N0_INT)
ONE_M = jnp.asarray(int_to_limbs(R_MONT % P))
ONE_RAW = jnp.zeros((NLIMBS,), dtype=jnp.uint64).at[0].set(1)


def from_int(x: int, mont: bool = True):
    """Host int -> device limbs (Montgomery form by default); conversion happens
    host-side with Python bignums."""
    x %= P
    return jnp.asarray(int_to_limbs(x * R_MONT % P if mont else x))


def from_ints(xs, mont: bool = True):
    """Batch host conversion: list of ints -> uint64[len(xs), 25]."""
    return jnp.asarray(
        np.stack([int_to_limbs(x % P * (R_MONT if mont else 1) % P) for x in xs])
    )


def to_int(a, mont: bool = True) -> int:
    """Device limbs -> Python int (out of Montgomery form by default). Accepts lazy
    (non-canonical) values."""
    v = limbs_to_int(np.asarray(a)) % P
    return v * R_INV_INT % P if mont else v


def to_ints(a, mont: bool = True) -> list:
    arr = np.asarray(a)
    return [to_int(arr[i], mont) for i in range(arr.shape[0])]


# --------------------------------------------------------------------------------------
# Lazy ring operations (no normalization — see module docstring for the bounds)
# --------------------------------------------------------------------------------------

def add(a, b):
    return a + b


def sub(a, b):
    """a - b + 2p. b must be canonical (16-bit limbs); a may be lazy."""
    return a + (SUB2P - b)


def neg(a):
    """2p - a. a must be canonical."""
    return SUB2P - a


def double(a):
    return a + a


# --------------------------------------------------------------------------------------
# Comparisons (canonical operands only)
# --------------------------------------------------------------------------------------

def is_zero(a):
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """cond ? a : b, with cond of batch shape (no limb axis)."""
    return jnp.where(cond[..., None], a, b)


# --------------------------------------------------------------------------------------
# Montgomery multiplication — the single normalization point
# --------------------------------------------------------------------------------------

def _carry_propagate(t, out_limbs: int):
    """lax.scan limb walk: normalize to 16-bit limbs, dropping any final carry
    (caller guarantees the value fits)."""
    limbs = jnp.moveaxis(t[..., :out_limbs], -1, 0)

    def step(c, v):
        v = v + c
        return v >> np.uint64(LIMB_BITS), v & MASK

    _, outs = jax.lax.scan(step, jnp.zeros_like(limbs[0]), limbs)
    return jnp.moveaxis(outs, 0, -1)


def _sub_limbs(a, b):
    """a - b with borrow chain (canonical operands). Returns (diff, borrow_out)."""
    pairs = (jnp.moveaxis(a, -1, 0), jnp.moveaxis(jnp.broadcast_to(b, a.shape), -1, 0))

    def step(borrow, ab):
        ai, bi = ab
        v = ai - bi - borrow
        return (v >> np.uint64(63)).astype(jnp.uint64), v & MASK

    borrow, outs = jax.lax.scan(step, jnp.zeros_like(pairs[0][0]), pairs)
    return jnp.moveaxis(outs, 0, -1), borrow


def _cond_sub_p(a):
    """Subtract p when a >= p (a < 2p, canonical limbs on entry)."""
    diff, borrow = _sub_limbs(a, P_LIMBS)
    return jnp.where((borrow == 1)[..., None], a, diff)


def _conv_product(a, b):
    """Schoolbook 25x25 convolution -> 50 uint64 accumulators. Exact for limbs up
    to 2^22 (25 * 2^44 < 2^50). Flat shifted-row sum — no update chains."""
    a, b = jnp.broadcast_arrays(a, b)
    prod = a[..., :, None] * b[..., None, :]  # [..., 25, 25]
    batch = prod.shape[:-2]
    rows = []
    for i in range(NLIMBS):
        pad = [(0, 0)] * len(batch) + [(i, NLIMBS - i)]
        rows.append(jnp.pad(prod[..., i, :], pad))
    return sum(rows)  # [..., 50]


def mont_mul(a, b):
    """Montgomery product a*b*R^-1 mod p; canonical output. Operand values may be
    lazy up to 600p with limbs up to 2^22 (see module docstring)."""
    t = _conv_product(a, b)
    t = jnp.moveaxis(t, -1, 0)  # [50, ...]
    p_tail = P_LIMBS[1:].reshape((NLIMBS - 1,) + (1,) * (t.ndim - 1))

    def step(carry, _):
        buf, c = carry
        ti = buf[0] + c
        m = (ti * N0) & MASK
        buf = buf.at[1:NLIMBS].add(m[None] * p_tail)
        c = (ti + m * P_LIMBS[0]) >> np.uint64(LIMB_BITS)
        buf = jnp.concatenate([buf[1:], jnp.zeros_like(buf[:1])], axis=0)
        return (buf, c), None

    (t, c), _ = jax.lax.scan(step, (t, jnp.zeros_like(t[0])), None, length=NLIMBS)
    res = jnp.moveaxis(t[:NLIMBS], 0, -1)
    res = res.at[..., 0].add(c)
    res = _carry_propagate(res, NLIMBS)  # value < 1.7p at the full operand budget
    return _cond_sub_p(res)


def mont_sqr(a):
    return mont_mul(a, a)


def normalize(a):
    """Lazy -> canonical without changing the Montgomery factor: a * R * R^-1."""
    return mont_mul(a, jnp.broadcast_to(ONE_M, a.shape))


def from_mont(a):
    """Montgomery -> canonical plain residue: a * 1 * R^-1."""
    return mont_mul(a, jnp.broadcast_to(ONE_RAW, a.shape))


# --------------------------------------------------------------------------------------
# Fixed-exponent powers (spec constants: inversion, sqrt)
# --------------------------------------------------------------------------------------

def pow_fixed_scan(a, e: int):
    """a^e for a fixed host-side exponent via lax.scan (MSB first)."""
    nbits = max(e.bit_length(), 1)
    bits = jnp.asarray(
        [(e >> (nbits - 1 - i)) & 1 for i in range(nbits)], dtype=jnp.uint64
    )

    def step(res, bit):
        res = mont_sqr(res)
        res = select(bit == 1, mont_mul(res, a), res)
        return res, None

    # initial carry derived from `a` (0*a + 1) so its device-varying type
    # matches the scan output under shard_map (scan-vma rule)
    res0 = jnp.broadcast_to(ONE_M, a.shape) + a * jnp.uint64(0)
    res, _ = jax.lax.scan(step, res0, bits)
    return res


def inv(a):
    """Field inverse via Fermat (a^(p-2)); inv(0) = 0 (RFC 9380 inv0 semantics)."""
    return pow_fixed_scan(a, P - 2)


def sqrt_candidate(a):
    """a^((p+1)/4) — a square root when a is a QR (p = 3 mod 4). Caller checks
    candidate^2 == a."""
    return pow_fixed_scan(a, (P + 1) // 4)


def sgn0(a):
    """RFC 9380 sgn0 (parity) of a Montgomery-form element."""
    return from_mont(a)[..., 0] & jnp.uint64(1)


def lex_gt_half_canon(canon):
    """x > (p-1)/2 on a *canonical plain-residue* limb array (MSB-first limb
    compare). Shared by the G1/G2 compressed-point sign-bit paths."""
    half = jnp.asarray(int_to_limbs((P - 1) // 2))
    gt = jnp.zeros(canon.shape[:-1], dtype=bool)
    decided = jnp.zeros(canon.shape[:-1], dtype=bool)
    for i in range(NLIMBS - 1, -1, -1):
        ai, hi = canon[..., i], half[i]
        gt = jnp.where(~decided & (ai > hi), True, gt)
        decided = decided | (ai != hi)
    return gt


def lex_gt_half(a):
    """y > (p-1)/2 on a Montgomery-form element — the compressed-point sign bit
    (ZCash serialization convention used by the reference's pubkey/sig bytes)."""
    return lex_gt_half_canon(from_mont(a))
