"""Fq (BLS12-381 base field) arithmetic as JAX limb kernels.

Representation: little-endian 16-bit limbs in uint64 lanes, **25 limbs** (plain
residues — no Montgomery domain), shape ``[..., 25]``. The 25th limb buys ~19 bits of
headroom over the 381-bit modulus, which enables the properties the whole kernel
stack is built on:

  * **Lazy addition/subtraction.** ``add``/``sub``/``neg`` are pure elementwise limb
    ops — no carry propagation, no comparison, ~2 HLO ops each. Limbs grow beyond 16
    bits and values beyond p; that's fine. The operand budget (enforced statically by
    plans.lincomb) is: values < 1200p and limbs < 2^22. The schoolbook convolution is
    exact for limbs up to 2^22 (25 * 2^44 < 2^50 per uint64 accumulator). Convention:
    values crossing a public tower-op boundary satisfy plans.PUB_BOUND (17-bit limbs,
    value < 16p, top limb <= 2); lazy values live only between two multiplies. ``sub(a, b)``/``neg``
    here require a public-bounded subtrahend (any multiply output): they add a
    borrow-inflated multiple of p whose limbs dominate the public bound. The
    tower layer (plans/tower) uses bound-tracked inflated constants instead.

  * **Branchless congruence-fold reduction — no sequential REDC.** A 50-limb
    convolution output is reduced by *folding*: limbs at positions >= 25 multiply a
    precomputed constant matrix F[j] = limbs(2^(16(25+j)) mod p) and accumulate onto
    the low limbs — one small matmul, a congruence mod p, no data-dependent carries.
    Interleaved elementwise "carry rounds" (lo = t & mask; t = lo + shift(t >> 16))
    keep limbs inside uint64 headroom. The only lax.scan left in the multiply path
    is the trivial-body 16-bit carry walk; the serial 25-step Montgomery REDC (a
    dynamic-update-slice scan that dominated both XLA compile time and VPU runtime)
    is gone, and with it the Montgomery domain itself: values are plain residues,
    so serialization and hashing skip domain conversion entirely.

``mont_mul`` (name kept for call-site compatibility) returns a *public-bounded*
value: <= 13p (PUB_VALUE_LIMIT), 17-bit limbs (PUB_LIMB_TARGET), top limb <= 2
— inside plans.PUB_BOUND. Equality, parity and serialization go through
``canonical()`` which finishes the reduction to < p. Every bound claim in this
module is machine-checked: the limb-bound certifier (``analysis/bounds.py``,
``python -m lighthouse_tpu.analysis --bounds``) re-executes the op graphs
abstractly and proves each obligation per backend (BOUNDS_CERT.json).

Correctness is pinned against ``lighthouse_tpu.ops.bls_oracle`` on random inputs.
This layer is the TPU twin of the blst field backend the reference links against
(``/root/reference/crypto/bls/src/impls/blst.rs`` seam).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..bls_oracle.fields import P

jax.config.update("jax_enable_x64", True)

NLIMBS = 25
LIMB_BITS = 16
MASK = np.uint64(0xFFFF)

R_MONT = 1  # plain-residue domain (no Montgomery factor; see module docstring)

# --------------------------------------------------------------------------------------
# Certification sink (analysis/bounds.py)
#
# Every bound this module proves statically at trace time — conv-accumulator
# exactness, fold-accumulator wrap safety, reduction-walk targets — is both
# asserted (as before) and, when a sink is installed, RECORDED as a proof
# obligation (kind, proven bound, declared limit). The limb-bound certifier
# re-executes the op graphs abstractly (jax.eval_shape) with the sink
# installed and emits BOUNDS_CERT.json from the records; production traces
# pay one `is None` check per obligation.
# --------------------------------------------------------------------------------------

_CERT_SINK = None


def _cert(kind: str, proven: int, limit: int, note: str = "") -> bool:
    """Record (and return) the obligation ``proven <= limit``. With no sink
    installed this is just the comparison the surrounding assert uses."""
    ok = proven <= limit
    if _CERT_SINK is not None:
        _CERT_SINK.record(kind, proven, limit, note=note, ok=ok)
    return ok


def int_to_limbs(x: int) -> np.ndarray:
    """Host helper: Python int -> uint64[25] little-endian 16-bit limbs."""
    return np.array(
        [(x >> (LIMB_BITS * i)) & 0xFFFF for i in range(NLIMBS)], dtype=np.uint64
    )


def limbs_to_int(a) -> int:
    """Host helper: limb array (last axis 25, any limb values) -> Python int."""
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(a))


def _inflated_kp(limb_cover: int, top_cover: int) -> np.ndarray:
    """Limbs of the smallest K*p whose borrow-inflated representation has every
    limb 0..23 >= limb_cover and limb 24 >= top_cover (so C - x never
    underflows per limb for x within those bounds)."""
    m = max(-(-limb_cover // ((1 << LIMB_BITS) - 1)), 1)
    K = 1
    while True:
        c = [int(v) for v in int_to_limbs(K * P)]
        assert (K * P).bit_length() <= NLIMBS * LIMB_BITS
        for i in range(1, NLIMBS):
            c[i - 1] += m << LIMB_BITS
            c[i] -= m
        if (
            all(v >= 0 for v in c)
            and all(c[i] >= limb_cover for i in range(24))
            and c[24] >= top_cover
        ):
            assert sum(v << (LIMB_BITS * i) for i, v in enumerate(c)) == K * P
            return np.array(c, dtype=np.uint64)
        K += 1


P_LIMBS = jnp.asarray(int_to_limbs(P))
# Covers any plans.PUB_BOUND subtrahend (16-bit limbs, top limb <= 2) — in
# particular every multiply output.
SUBPUB = jnp.asarray(_inflated_kp((1 << 17) - 1, 2))  # covers plans.PUB_LIMB
SUB2P = SUBPUB  # historical name
ONE_M = jnp.asarray(int_to_limbs(1))  # multiplicative identity (plain domain)
ONE_RAW = jnp.zeros((NLIMBS,), dtype=jnp.uint64).at[0].set(1)


def from_int(x: int, mont: bool = True):
    """Host int -> device limbs. The domain is plain residues, so the ``mont``
    flag (kept for call-site compatibility) is a no-op."""
    return jnp.asarray(int_to_limbs(x % P))


def from_ints(xs, mont: bool = True):
    """Batch host conversion: list of ints -> uint64[len(xs), 25]."""
    return jnp.asarray(np.stack([int_to_limbs(x % P) for x in xs]))


def to_int(a, mont: bool = True) -> int:
    """Device limbs -> Python int. Accepts lazy (non-canonical) values; the
    ``mont`` flag is a no-op (plain domain)."""
    return limbs_to_int(np.asarray(a)) % P


def to_ints(a, mont: bool = True) -> list:
    arr = np.asarray(a)
    return [to_int(arr[i], mont) for i in range(arr.shape[0])]


# --------------------------------------------------------------------------------------
# Lazy ring operations (no normalization — see module docstring for the bounds)
# --------------------------------------------------------------------------------------

def add(a, b):
    return a + b


def sub(a, b):
    """a - b + Kp. b must be public-bounded (16-bit limbs, top <= 2 — any
    multiply output or canonical value); a may be lazy."""
    return a + (SUBPUB - b)


def neg(a):
    """Kp - a. a must be public-bounded."""
    return SUBPUB - a


def double(a):
    return a + a


# --------------------------------------------------------------------------------------
# Comparisons (canonical operands only)
# --------------------------------------------------------------------------------------

def is_zero(a):
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """cond ? a : b, with cond of batch shape (no limb axis)."""
    return jnp.where(cond[..., None], a, b)


# --------------------------------------------------------------------------------------
# Multiplication: convolution + congruence-fold reduction (no sequential REDC)
# --------------------------------------------------------------------------------------

def _shift_up_one(t):
    """Shift limbs up one position (drop the top limb's value — caller
    guarantees it is statically zero)."""
    return jnp.concatenate([jnp.zeros_like(t[..., :1]), t[..., :-1]], axis=-1)


def _carry_lookahead(comb_g, comb_p):
    """Inclusive carry/borrow-lookahead over the limb axis: generate/propagate
    pairs composed with the standard associative carry operator. Log-depth
    elementwise ops — NO lax.scan/while (the serial carry walks used to emit a
    separate XLA while computation per call site, and with ~2 per plans.execute
    the fused verification kernels carried 600+ while ops; XLA CPU compiles
    every while body as its own computation, which dominated compile time —
    461 s at the 16x64 toy shape, VERDICT r3 #1/#2)."""

    def comb(a, b):
        ga, pa = a
        gb, pb = b
        return gb | (pb & ga), pb & pa

    return jax.lax.associative_scan(comb, (comb_g, comb_p), axis=-1)


def _carry_rounds(t, rounds: int):
    """Width-preserving carry-save rounds: limb bound b -> 0xFFFF + (b >> 16)
    per round (value invariant; the top limb's carry is statically zero when
    the value fits the width — limbs are non-negative so
    limb[-1] <= value >> (16*(n-1))). Dtype-generic (u64 masks / f64 floor)."""
    for _ in range(rounds):
        lo, hi = _split16(t)
        t = lo + _shift_up_one(hi)
    return t


def _carry_propagate(t, out_limbs: int):
    """Normalize to EXACT 16-bit limbs, dropping any final carry (caller
    guarantees the value fits out_limbs limbs). While-free: carry-save rounds
    bring limbs under 2^17, then one carry-lookahead finishes exactly. Only
    comparison/serialization sites need this; the multiply pipeline uses the
    cheaper approximate rounds (plans.PUB_BOUND allows 17-bit limbs)."""
    t = _carry_rounds(t[..., :out_limbs], 4)
    # exact finish: t = r + (g << 16) with g in {0,1}
    r = t & MASK
    gs = _shift_up_one(t >> np.uint64(LIMB_BITS))
    ssum = r + gs  # <= 0x10000
    G, _ = _carry_lookahead(ssum > MASK, ssum == MASK)
    cin = _shift_up_one(G.astype(t.dtype))
    return (ssum + cin) & MASK


def _sub_limbs(a, b):
    """a - b with borrow chain (canonical operands). Returns (diff, borrow_out).
    Borrow-lookahead (see _carry_lookahead) instead of a serial scan."""
    b = jnp.broadcast_to(b, a.shape)
    G, _ = _carry_lookahead(a < b, a == b)
    bin_ = _shift_up_one(G.astype(a.dtype))
    diff = (a - b - bin_) & MASK
    return diff, G[..., -1].astype(a.dtype)


def _cond_sub_p(a):
    """Subtract p when a >= p (a < 2p, canonical limbs on entry)."""
    diff, borrow = _sub_limbs(a, P_LIMBS)
    return jnp.where((borrow == 1)[..., None], a, diff)


def _conv_product_shear(a, b):
    """Schoolbook 25x25 convolution -> 50 uint64 accumulators. Exact for limbs up
    to 2^22 (25 * 2^44 < 2^50).

    The anti-diagonal sum T[s] = sum_{i+j=s} a_i b_j is materialized by the
    reshape *shear*: pad rows of the outer product to width 2*25, flatten, and
    re-slice at width 2*25-1 — row i then lands shifted by i columns, so a
    plain row-sum produces the convolution. ~6 HLO ops instead of the 25
    pad-and-add ops of the naive form (program size is compile time: the fused
    verification kernel inlines hundreds of these)."""
    a, b = jnp.broadcast_arrays(a, b)
    prod = a[..., :, None] * b[..., None, :]  # [..., 25, 25]
    batch = prod.shape[:-2]
    w = 2 * NLIMBS  # 50
    prod = jnp.pad(prod, [(0, 0)] * len(batch) + [(0, 0), (0, w - NLIMBS)])
    flat = prod.reshape(batch + (NLIMBS * w,))
    sheared = flat[..., : NLIMBS * (w - 1)].reshape(batch + (NLIMBS, w - 1))
    t = sheared.sum(axis=-2)  # [..., 49]; true limb 49 is always zero
    return jnp.pad(t, [(0, 0)] * len(batch) + [(0, 1)])


def _conv_product_f64(a, b):
    """Schoolbook convolution as a 25-term shifted-FMA chain in f64.

    Products are exact: conv inputs satisfy the lazy budget (limbs < 2^22), so
    every accumulator is < 25 * 2^44 < 2^49 < 2^53 (f64 integer exactness).
    The FMA chain fuses into one pass over the [..., 49] output — the
    shear-reshape form above materializes the full [..., 25, 50] outer
    product (160 MB at batch 16k) and is memory-bound at ~3x the runtime.
    Compile cost of the 25-term chain is ~0.2 s (the r3 compile blowup came
    from while-loops, not op count)."""
    af = a.astype(jnp.float64)
    bf = b.astype(jnp.float64)
    nb = [(0, 0)] * (a.ndim - 1)
    t = None
    for i in range(NLIMBS):
        term = jnp.pad(af[..., i : i + 1] * bf, nb + [(i, NLIMBS - 1 - i)])
        t = term if t is None else t + term
    # materialization fence: the chain is fully elementwise, and without the
    # barrier XLA CPU duplicates it into every consumer of the accumulators
    # inside large fused graphs (measured 1.7x slower map_to_g2)
    t = jax.lax.optimization_barrier(t)
    return jnp.pad(t, nb + [(0, 1)])


def _conv_product_f64_u64(a, b):
    return _conv_product_f64(a, b).astype(jnp.uint64)


# TPU digit path: base-2^8 digit split. Limb i (< 2^22) contributes bytes to
# digit positions 2i, 2i+1, 2i+2; overlapping chunks add, so digits are
# <= 255 + (limb >> 16) <= 318. 51 digits cover 25 limbs.
_N_DIGITS = 2 * NLIMBS + 1  # 51


def _digit_bound(limb_bound: int) -> int:
    return min(limb_bound, 255) + (limb_bound >> 16)


def _to_digits_f32(x):
    """u64 limbs [..., 25] -> f32 digits [..., 51] (base 2^8, overlap-added):
    digit[2i] = c0(i) + c2(i-1), digit[2i+1] = c1(i), digit[50] = c2(24)."""
    c0 = (x & jnp.uint64(0xFF)).astype(jnp.float32)
    c1 = ((x >> jnp.uint64(8)) & jnp.uint64(0xFF)).astype(jnp.float32)
    c2 = (x >> jnp.uint64(16)).astype(jnp.float32)
    nb = [(0, 0)] * (x.ndim - 1)
    # even digit slots 0..25: c0 padded with a tail slot + c2 shifted up one
    even = jnp.pad(c0, nb + [(0, 1)]) + jnp.pad(c2, nb + [(1, 0)])
    odd = jnp.pad(c1, nb + [(0, 1)])  # odd digit slots 1,3,..,49 (+ unused)
    inter = jnp.stack([even, odd], axis=-1)  # [..., 26, 2]
    d = inter.reshape(x.shape[:-1] + (2 * (NLIMBS + 1),))  # 52 slots
    return d[..., : _N_DIGITS]  # slot 51 (odd tail) is zero by construction


def _conv_product_digits(a, b):
    """TPU convolution: f32 digit-split shifted-FMA chain, recombined to the
    u64 16-bit-limb accumulator layout.

    TPUs have no fast 64-bit integer multiply (u64 lowers to multi-op u32
    emulation on the VPU) and f64 is software-emulated, but f32 FMA runs at
    full VPU rate. Digits are <= 318 (for 2^22-bounded limbs) so every conv
    accumulator is <= 51 * 318^2 < 2^23 — exact in f32. Recombined limb
    accumulators are < 2^30.4 pre-spill; limb 49 then absorbs the
    2^16-scaled spill of digit position 100 (see end of function), raising
    its bound to ~2^32.6 — still far tighter than the f64 path's 2^48.6,
    which shortens the fold schedule downstream (the fold walk uses the
    exact per-limb bounds from conv_limb_bounds, not these summaries)."""
    da = _to_digits_f32(a)
    db = _to_digits_f32(b)
    nb = [(0, 0)] * (a.ndim - 1)
    t = None
    for i in range(_N_DIGITS):
        term = jnp.pad(da[..., i : i + 1] * db, nb + [(i, _N_DIGITS - 1 - i)])
        t = term if t is None else t + term
    # digit accumulators [..., 101] -> u64 limbs: limb s = D[2s] + 2^8 D[2s+1]
    t = jnp.pad(t, nb + [(0, 1)])  # 102 digit slots = 51 limb pairs
    ti = t.astype(jnp.uint32).astype(jnp.uint64)
    pairs = ti.reshape(t.shape[:-1] + (_N_DIGITS, 2))
    limbs = pairs[..., 0] + (pairs[..., 1] << jnp.uint64(8))
    # digit position 100 (top-chunk x top-chunk) lands at limb 50, one past
    # the 50-limb accumulator layout; fold it into limb 49 (value-preserving,
    # bound ~2^32 — still far inside u64)
    spill = limbs[..., 2 * NLIMBS :] << jnp.uint64(LIMB_BITS)
    return jnp.concatenate(
        [limbs[..., : 2 * NLIMBS - 1], limbs[..., 2 * NLIMBS - 1 : 2 * NLIMBS] + spill],
        axis=-1,
    )


_CONV_IMPL = None


def conv_backend() -> str:
    """Which conv implementation the default backend gets: "pallas" on TPU
    (fused Pallas/Mosaic digit kernels — conv + congruence fold + carry as
    one MXU kernel, see pallas_kernels.py), "f64" elsewhere (CPU SIMD FMA).
    Cached on first use; override via
    LIGHTHOUSE_CONV_IMPL=pallas|digits|f64|shear for testing ("pallas" off
    TPU runs the kernels in interpret mode — exact, but an emulator)."""
    global _CONV_IMPL
    if _CONV_IMPL is None:
        import os

        forced = os.environ.get("LIGHTHOUSE_CONV_IMPL")
        if forced in ("pallas", "digits", "f64", "shear"):
            _CONV_IMPL = forced
        else:
            _CONV_IMPL = "pallas" if jax.default_backend() == "tpu" else "f64"
    return _CONV_IMPL


def conv_limb_bounds(in_limb_a: int, in_limb_b: int | None = None) -> list[int]:
    """Static per-accumulator bounds of _conv_product for inputs with limbs
    <= in_limb_a / in_limb_b under the active conv backend, asserting
    float-exactness of the chosen path."""
    if in_limb_b is None:
        in_limb_b = in_limb_a
    # "pallas" shares the digit-split accumulator shape: these bounds apply
    # to its (rarely taken) _conv_product fallback; the fused kernels track
    # their own digit-domain bounds in pallas_kernels.py
    if conv_backend() in ("digits", "pallas"):
        da = _digit_bound(in_limb_a)
        db = _digit_bound(in_limb_b)
        # digit conv position d has min(d, 100-d, 50)+1 terms
        per_digit = [
            (min(d, 2 * _N_DIGITS - 2 - d, _N_DIGITS - 1) + 1) * da * db
            for d in range(2 * _N_DIGITS - 1)
        ] + [0]
        assert _cert(
            "conv_digit_f32_exact", max(per_digit), (1 << 24) - 1
        ), "digit conv exceeds f32 exactness"
        # the u32 cast of the digit accumulators is lossless iff they are
        # f32-exact (< 2^24 < 2^32) — same obligation, recorded explicitly
        _cert("conv_digit_u32_nowrap", max(per_digit), (1 << 32) - 1)
        limb_b = [
            per_digit[2 * s] + (per_digit[2 * s + 1] << 8)
            for s in range(_N_DIGITS)
        ]
        # limb 50 is folded into limb 49 by _conv_product_digits
        limb_b[2 * NLIMBS - 1] += limb_b[2 * NLIMBS] << LIMB_BITS
        assert _cert(
            "conv_digit_u64_acc", max(limb_b), (1 << 64) - 1
        ), "digit conv u64 recombination overflow"
        return limb_b[: 2 * NLIMBS]
    bounds = [
        max(1, min(i + 1, NLIMBS, 2 * NLIMBS - 1 - i)) * in_limb_a * in_limb_b
        for i in range(2 * NLIMBS)
    ]
    if conv_backend() == "f64":
        assert _cert(
            "conv_f64_exact", max(bounds), (1 << 53) - 1
        ), "f64 conv exceeds f64 exactness"
    else:
        # shear path: plain u64 accumulators must not wrap
        assert _cert(
            "conv_u64_acc", max(bounds), (1 << 64) - 1
        ), "shear conv u64 accumulator overflow"
    return bounds


def _conv_product(a, b):
    """Convolution product -> 50 u64 accumulators (platform-dispatched; see
    _conv_product_f64 / _conv_product_digits / _conv_product_shear). Inputs
    must satisfy the lazy budget: limbs < 2^22, value < 1200p.

    Under the "pallas" backend the HOT path never calls this — mont_mul /
    mont_mul_lazy / plans.execute dispatch to the fused pallas kernels
    (conv + fold + carry in one pallas_call); stray callers of the bare
    conv seam get the bit-equivalent u64 digit accumulators."""
    impl = conv_backend()
    if impl in ("digits", "pallas"):
        return _conv_product_digits(a, b)
    if impl == "f64":
        return _conv_product_f64_u64(a, b)
    return _conv_product_shear(a, b)


# Row threshold for keeping the reduction walk in f64 (f64 backend only).
# Originally 32: host-dispatched micro-benchmarks suggested the longer f64
# schedule (2^53 cap) loses below ~32 rows. Re-measured inside lax.scan
# bodies (where the pairing's batch-1 final-exponentiation chains actually
# run, and dispatch cost amortizes away) the u64 path's scalarized
# multiplies lose at EVERY row count — a 63-step cyclotomic-square scan at
# batch 1 ran 2.3x faster on the f64 path — so the threshold is now 0:
# the f64 backend keeps the whole execute pipeline in f64 SIMD at all
# shapes. Static per-call-site dispatch — both paths are exact.
F64_WALK_MIN_ROWS = 0


def _static_rows(a) -> int:
    n = 1
    for d in a.shape[:-1]:
        n *= int(d)
    return n


def _conv_product_keep(a, b):
    """_conv_product, but on the f64 backend (and at row counts where it
    wins — F64_WALK_MIN_ROWS) the accumulators STAY f64 so the downstream
    reduction walk runs in f64 as well. x86 has no vectorized 64-bit integer
    multiply — the u64 congruence-fold passes scalarize and dominated the
    execute pipeline (~60% of a point-double); the f64 walk is the same
    fold schedule (2^53 exactness cap, statically re-derived) on SIMD FMAs.
    reduce_limbs casts back to u64 at the end."""
    impl = conv_backend()
    if impl in ("digits", "pallas"):
        return _conv_product_digits(a, b)
    if impl == "f64":
        if max(_static_rows(a), _static_rows(b)) >= F64_WALK_MIN_ROWS:
            return _conv_product_f64(a, b)
        return _conv_product_f64_u64(a, b)
    return _conv_product_shear(a, b)


# Congruence-fold rows: _FOLD_ROWS[j] = 16-bit limbs of 2^(16*(25+j)) mod p.
# Folding limb 25+j through its row is an exact congruence mod p.
_N_FOLD = 40
_FOLD_NP = np.stack(
    [int_to_limbs((1 << (LIMB_BITS * (NLIMBS + j))) % P) for j in range(_N_FOLD)]
)
_FOLD_ROWS = jnp.asarray(_FOLD_NP)
_FOLD_ROWS_F64 = jnp.asarray(_FOLD_NP.astype(np.float64))
_FOLD_VALS = [(1 << (LIMB_BITS * (NLIMBS + j))) % P for j in range(_N_FOLD)]

PUB_VALUE_LIMIT = 13 * P  # reduce() output value bound (plans.PUB_BOUND holds)


class _RState:
    """Exact static bound state for reduce_limbs(): per-limb bounds (Python
    ints) plus a value bound, mutually refined — any limb t_i <= value >> 16i
    since limbs are non-negative. Every transform updates the state exactly, so
    uint64 overflow and carry-drop safety are proved at trace time."""

    __slots__ = ("limbs", "value")

    def __init__(self, limbs, value):
        limbs = list(limbs)
        value = min(
            value, sum(b << (LIMB_BITS * i) for i, b in enumerate(limbs))
        )
        self.limbs = [min(b, value >> (LIMB_BITS * i)) for i, b in enumerate(limbs)]
        self.value = value


def _is_f64(t) -> bool:
    return t.dtype == jnp.float64


def _cap_of(t) -> int:
    """Largest exactly-representable accumulator bound for t's dtype: integer
    f64 stays exact below 2^53; u64 wraps at 2^64."""
    return (1 << 53) if _is_f64(t) else (1 << 64)


def _split16(t):
    """(low 16 bits, value >> 16) in t's dtype. The f64 form is exact for
    integer t < 2^53 (scaling by 2^-16 and floor are exact)."""
    if _is_f64(t):
        hi = jnp.floor(t * (1.0 / 65536.0))
        return t - hi * 65536.0, hi
    return t & MASK, t >> np.uint64(LIMB_BITS)


def _carry_round_array(t):
    """One elementwise carry-save round (appends a limb; value unchanged)."""
    lo, hi = _split16(t)
    nb = [(0, 0)] * (t.ndim - 1)
    return jnp.pad(lo, nb + [(0, 1)]) + jnp.pad(hi, nb + [(1, 0)])


def _carry_round(t, s: _RState):
    t = _carry_round_array(t)
    lo_b = [min(b, int(MASK)) for b in s.limbs] + [0]
    hi_b = [0] + [b >> LIMB_BITS for b in s.limbs]
    return t, _RState([a + b for a, b in zip(lo_b, hi_b)], s.value)


def _fold_high(t, s: _RState):
    """Fold limbs >= 25 through the 2^(16k) mod p rows — an exact congruence
    mod p that shrinks the value by ~2^19x per live high limb. On the f64
    walk the fold is ONE [..., n_hi] x [n_hi, 25] dot_general (SIMD matmul —
    5x the unrolled FMA chain at chain widths); on integer walks it stays
    unrolled broadcast-FMA terms (not a .sum(-2) reduction) so XLA fuses the
    fold into the surrounding elementwise chain — the reduction form
    materialized the [..., n_hi, 25] intermediate and cost an extra memory
    pass (and u64 dots scalarize)."""
    n_hi = t.shape[-1] - NLIMBS
    acc = t[..., :NLIMBS]
    if _is_f64(t):
        acc = acc + jax.lax.dot_general(
            t[..., NLIMBS:],
            _FOLD_ROWS_F64[:n_hi],
            (((t.ndim - 1,), (0,)), ((), ())),
        )
    else:
        rows = _FOLD_ROWS
        for j in range(n_hi):
            acc = acc + t[..., NLIMBS + j : NLIMBS + j + 1] * rows[j]
    lo_b, hi_b = s.limbs[:NLIMBS], s.limbs[NLIMBS:]
    limbs = [
        b + sum(hb * int(_FOLD_NP[j, i]) for j, hb in enumerate(hi_b))
        for i, b in enumerate(lo_b)
    ]
    assert _cert(
        "fold_acc_nowrap", max(limbs), _cap_of(t) - 1
    ), "fold accumulator overflow"
    lo_val = sum(b << (LIMB_BITS * i) for i, b in enumerate(lo_b))
    value = min(s.value, lo_val) + sum(
        hb * _FOLD_VALS[j] for j, hb in enumerate(hi_b)
    )
    return acc, _RState(limbs, value)


_RT384_VAL = (1 << 384) % P
_RT384_NP = int_to_limbs(_RT384_VAL)
_RT384_ROW = jnp.asarray(_RT384_NP)
_RT384_ROW_F64 = jnp.asarray(_RT384_NP.astype(np.float64))
_RT381_VAL = (1 << 381) % P
_RT381_ROW = jnp.asarray(int_to_limbs(_RT381_VAL))
# keep bits < 381: full limbs 0..22, 13 bits of limb 23, none of limb 24
_MASK_LOW381 = jnp.asarray(
    np.array([0xFFFF] * 23 + [0x1FFF, 0], dtype=np.uint64)
)


# constant masks (static-index .at[].set lowers to scatter — thousands of
# scatter ops dominated XLA compile time; a mask multiply fuses for free)
_MASK_NO24 = jnp.asarray(
    np.array([1] * 24 + [0], dtype=np.uint64)
)
_MASK_NO24_F64 = jnp.asarray(np.array([1.0] * 24 + [0.0]))


def _fold_384(t, s: _RState):
    """Fold the 2^384-and-up excess of a 25-limb array through 2^384 mod p."""
    top = t[..., 24]
    if _is_f64(t):
        t = t * _MASK_NO24_F64 + top[..., None] * _RT384_ROW_F64
    else:
        t = t * _MASK_NO24 + top[..., None] * _RT384_ROW
    top_b = s.limbs[24]
    limbs = [
        b + top_b * int(_RT384_NP[i]) for i, b in enumerate(s.limbs[:24])
    ] + [top_b * int(_RT384_NP[24])]
    assert _cert(
        "fold384_acc_nowrap", max(limbs), _cap_of(t) - 1
    ), "fold384 accumulator overflow"
    lo_val = sum(b << (LIMB_BITS * i) for i, b in enumerate(s.limbs[:24]))
    return t, _RState(limbs, min(s.value, lo_val) + top_b * _RT384_VAL)


PUB_LIMB_TARGET = (1 << 17) - 1  # plans.PUB_LIMB: 17-bit limbs suffice publicly


def _propagate_approx(t, s: _RState, n_out: int, target: int = PUB_LIMB_TARGET):
    """Approximate carry walk: width-preserving carry-save rounds (statically
    scheduled from the bound state) until every limb bound is <= target.
    Value-invariant, elementwise, no scan — exactness is only needed at
    comparison/serialization sites (fq.canonical), not inside the multiply
    pipeline, whose public contract tolerates 17-bit limbs."""
    assert _cert(
        "carry_walk_width", s.value, (1 << (LIMB_BITS * n_out)) - 1
    ), "carry walk would drop value"
    if t.shape[-1] < n_out:
        t = jnp.pad(t, [(0, 0)] * (t.ndim - 1) + [(0, n_out - t.shape[-1])])
    limbs = list(s.limbs) + [0] * (n_out - len(s.limbs))
    limbs = [min(b, s.value >> (LIMB_BITS * i)) for i, b in enumerate(limbs)]
    for _ in range(8):
        if max(limbs) <= target:
            break
        t = _carry_rounds(t, 1)
        carried = [0] + [b >> LIMB_BITS for b in limbs[:-1]]
        limbs = [min(b, int(MASK)) + c for b, c in zip(limbs, carried)]
        limbs = [
            min(b, s.value >> (LIMB_BITS * i)) for i, b in enumerate(limbs)
        ]
    else:  # pragma: no cover - static schedule
        raise AssertionError("carry walk did not converge")
    return t, _RState(limbs, s.value)


def _drop_zero_tops(t, s: _RState):
    while t.shape[-1] > NLIMBS and s.limbs[t.shape[-1] - 1] == 0:
        t = t[..., : t.shape[-1] - 1]
        s = _RState(s.limbs[: t.shape[-1]], s.value)
    return t, s


def reduce_limbs(
    t,
    limb_bounds,
    value_bound: int,
    value_limit: int = PUB_VALUE_LIMIT,
    limb_target: int = PUB_LIMB_TARGET,
):
    """Reduce [..., N] (N >= 25) to value <= value_limit, limbs <= limb_target
    (defaults: plans.PUB_BOUND — value < 13p, 17-bit limbs, top limb <= 2).
    Statically scheduled congruence folds + elementwise carry rounds — fully
    while-free; bounds proved at trace time. Dtype-generic: an f64 input runs
    the whole walk in f64 (exactness cap 2^53 instead of 2^64 — a slightly
    longer schedule of cheaper, fusion-friendly FMA steps) and is cast to u64
    at the end.

    A LAZIER target (plans.CHAIN_BOUND: value < 64p, 20-bit limbs) trims the
    tail of the walk — fewer 2^384 folds and carry rounds. Fixed-exponent /
    fixed-scalar chains (chain_plans) run their interior ops at that target:
    the output re-enters the next convolution directly (limbs < 2^22, value
    < 1200p budget) and only the chain's final result pays the full
    normalization."""
    cap = _cap_of(t)
    s = _RState(list(limb_bounds), value_bound)
    # phase 1: fold down to 25 limbs
    for _ in range(64):
        t, s = _drop_zero_tops(t, s)
        if t.shape[-1] == NLIMBS:
            break
        n_hi = t.shape[-1] - NLIMBS
        prod = max(s.limbs[:NLIMBS]) + sum(
            hb * int(MASK) for hb in s.limbs[NLIMBS:]
        )
        if n_hi <= _N_FOLD and prod < cap:
            t, s = _fold_high(t, s)
        else:
            t, s = _carry_round(t, s)
    else:  # pragma: no cover - static schedule
        raise AssertionError("reduce_limbs: phase 1 did not converge")
    # phase 2: one approximate walk, wide enough that no carry is dropped
    n_out = max(NLIMBS + 1, -(-s.value.bit_length() // LIMB_BITS) + 1)
    t, s = _propagate_approx(t, s, n_out, limb_target)
    # phase 3: drain high limbs and the 2^384 excess — all elementwise
    for _ in range(64):
        t, s = _drop_zero_tops(t, s)
        if t.shape[-1] > NLIMBS:
            prod = max(s.limbs[:NLIMBS]) + sum(
                hb * int(MASK) for hb in s.limbs[NLIMBS:]
            )
            if prod < cap:
                t, s = _fold_high(t, s)
            else:
                t, s = _carry_round(t, s)
        elif s.value > value_limit:
            # fold only when it provably shrinks the value (the excess may sit
            # in low limbs after a previous fold — surface it with a carry)
            lo_val = sum(
                b << (LIMB_BITS * i) for i, b in enumerate(s.limbs[:24])
            )
            predicted = min(s.value, lo_val) + s.limbs[24] * _RT384_VAL
            safe = s.limbs[24] * int(MASK) + max(s.limbs[:24]) < cap
            if safe and predicted < s.value:
                t, s = _fold_384(t, s)
            else:
                t, s = _carry_round(t, s)
        else:
            break
    else:  # pragma: no cover - static schedule
        raise AssertionError("reduce_limbs: phase 3 did not converge")
    # phase 4: final approximate walk to limb_target-bit limbs (PUB target:
    # top <= 2 since value < 13p and limbs are non-negative:
    # limb24 <= value >> 384)
    t, s = _propagate_approx(t, s, NLIMBS, limb_target)
    assert _cert("reduce_value", s.value, value_limit)
    assert _cert("reduce_limb", max(s.limbs), limb_target)
    if value_limit == PUB_VALUE_LIMIT:
        assert _cert(
            "reduce_top_limb",
            min(s.limbs[24], s.value >> (LIMB_BITS * 24)),
            2,
        )
    if _is_f64(t):
        # materialization fence + exact cast (limbs <= limb_target < 2^53):
        # without the barrier XLA CPU duplicates the whole elementwise walk
        # into every consumer of the result (the conv chain's known
        # recompute pathology — measured 6x on a composed point_add)
        t = jax.lax.optimization_barrier(t).astype(jnp.uint64)
    return t


# Conv-input budget (the plans.lincomb contract): limbs < 2^22, value < 1200p.
_IN_LIMB = (1 << 22) - 1
_IN_VALUE = 1200 * P


def _conv_limb_bounds(lb: int):
    """Backend-independent worst-case accumulator bounds (the u64/f64 shape);
    retained for probes. Prefer conv_limb_bounds, which is backend-aware."""
    return [max(1, min(i + 1, NLIMBS, 49 - i)) * lb * lb for i in range(2 * NLIMBS)]


def mont_mul(a, b):
    """Product a*b mod p (plain domain — the historical name is kept for the
    call sites). Operands may be lazy up to _IN_VALUE (1200p) with limbs up to
    _IN_LIMB (2^22); output satisfies plans.PUB_BOUND (<= 13p, 17-bit limbs,
    top <= 2).

    The conv runs in f64 (CPU) / fused f32 digit kernels (TPU "pallas"
    backend: conv + congruence fold + carry inside ONE pallas_call — see
    pallas_kernels.fused_mul). On the f64 backend the fold walk stays in f64
    as well (u64 multiplies scalarize on x86 — see _conv_product_keep); the
    conv chain's optimization_barrier fences the graph so XLA does not
    recompute it per consumer (the historical all-f64 pathology)."""
    if conv_backend() == "pallas":
        from . import pallas_kernels

        return pallas_kernels.fused_mul(a, b, lazy=False)
    t = _conv_product_keep(a, b)
    return reduce_limbs(t, conv_limb_bounds(_IN_LIMB), _IN_VALUE * _IN_VALUE)


def mont_sqr(a):
    return mont_mul(a, a)


# Lazy chain target (see reduce_limbs): interior values of fixed-exponent /
# fixed-scalar chains run at this bound and only the chain's final result
# pays the full normalization walk. THE derivation (single source of truth —
# plans.CHAIN_BOUND and every docstring bound derive from these names):
#
#   CHAIN_LIMB_TARGET = 2^20 - 1, CHAIN_VALUE_P = 64 (value < 64p) because a
#   chain step's output must re-enter the next convolution directly, i.e.
#   sit inside the lazy conv budget (_IN_LIMB = 2^22 - 1, _IN_VALUE = 1200p)
#   AND keep the conv accumulators exact on every backend:
#     f64:    25 * (2^20)^2         = 25 * 2^40   < 2^53   (f64 exactness)
#     digits: 51 * (255 + 2^4)^2    ~  2^21.8     < 2^24   (f32 exactness)
#   (both re-checked per trace by conv_limb_bounds and certified by
#   analysis/bounds.py). The top-limb bound is not independent: limbs are
#   non-negative, so limb 24 <= value >> 384 — chain_top_limb() below.
CHAIN_VALUE_P = 64
CHAIN_LIMB_TARGET = (1 << 20) - 1
CHAIN_VALUE_LIMIT = CHAIN_VALUE_P * P


def chain_top_limb() -> int:
    """Provable limb-24 bound of a chain-interior value: min(limb bound,
    value >> 384) — for 64p that is 6 (tightens the former hand-written 7,
    which over-declared what the reduction walk guarantees)."""
    return min(CHAIN_LIMB_TARGET, CHAIN_VALUE_LIMIT >> (LIMB_BITS * 24))


# the chain fixed point must sit inside the conv-input budget, or interior
# outputs could not feed the next multiply without renormalization
assert CHAIN_LIMB_TARGET <= _IN_LIMB and CHAIN_VALUE_LIMIT <= _IN_VALUE


def mont_mul_lazy(a, b):
    """Chain-interior product: operands at (or below) the lazy chain bound
    (limbs <= CHAIN_LIMB_TARGET, value <= CHAIN_VALUE_LIMIT); output at the
    same bound — a fixed point, so chains of any length stay in budget.
    Shorter reduction walk than mont_mul (bound-precise conv inputs AND a
    lazier target)."""
    _cert("chain_in_budget_limb", CHAIN_LIMB_TARGET, _IN_LIMB)
    _cert("chain_in_budget_value", CHAIN_VALUE_LIMIT, _IN_VALUE)
    if conv_backend() == "pallas":
        from . import pallas_kernels

        return pallas_kernels.fused_mul(a, b, lazy=True)
    t = _conv_product_keep(a, b)
    return reduce_limbs(
        t,
        conv_limb_bounds(CHAIN_LIMB_TARGET),
        CHAIN_VALUE_LIMIT * CHAIN_VALUE_LIMIT,
        CHAIN_VALUE_LIMIT,
        CHAIN_LIMB_TARGET,
    )


def mont_sqr_lazy(a):
    return mont_mul_lazy(a, a)


def canonical(a):
    """Fully reduce to the canonical residue < p (comparisons, parity,
    serialization). Accepts anything within the lazy budget. On the f64
    backend (at winning row counts) the fold walk runs in f64 (see
    _conv_product_keep)."""
    if (
        conv_backend() == "f64"
        and not _is_f64(a)
        and _static_rows(a) >= F64_WALK_MIN_ROWS
    ):
        a = a.astype(jnp.float64)
    t = reduce_limbs(a, [_IN_LIMB] * a.shape[-1], _IN_VALUE)
    # reduce_limbs leaves 17-bit limbs (PUB_LIMB_TARGET); the 2^381 folds
    # below mask limbs to 16 bits (_MASK_LOW381), so an EXACT propagation
    # must come first or bit 16 of limbs 0..22 is silently dropped
    t = _carry_propagate(t, NLIMBS)
    # value < 13p: two sub-limb folds at the 2^381 boundary bring it under 2p
    for _ in range(2):
        hi = (t[..., 23] >> np.uint64(13)) + (t[..., 24] << np.uint64(3))
        t = (t & _MASK_LOW381) + hi[..., None] * _RT381_ROW
        t = _carry_propagate(t, NLIMBS)
    return _cond_sub_p(t)


def normalize(a):
    """Lazy -> canonical (< p), value unchanged mod p."""
    return canonical(a)


def from_mont(a):
    """Canonical plain residue (the domain IS plain; name kept for callers)."""
    return canonical(a)


# --------------------------------------------------------------------------------------
# Fixed-exponent powers (spec constants: inversion, sqrt)
# --------------------------------------------------------------------------------------

def pow_fixed_scan(a, e: int):
    """a^e for a fixed host-side exponent, compiled by the fixed-scalar plan
    machinery (chain_plans): windowed schedule with a log-depth table build
    and LAZY interior bounds — only the final result pays the full
    normalization walk. Accepts anything within the lazy budget: the base is
    first brought to the chain bound the interior ops' static schedules
    assume (limbs <= CHAIN_LIMB_TARGET, value <= CHAIN_VALUE_LIMIT)."""
    from . import chain_plans

    a = reduce_limbs(
        a, [_IN_LIMB] * a.shape[-1], _IN_VALUE,
        CHAIN_VALUE_LIMIT, CHAIN_LIMB_TARGET,
    )
    sched = chain_plans.compile_chains((int(e),), signed=False)
    out = chain_plans.run_field_chains(
        sched, a[None, ..., None, :], mont_sqr_lazy, mont_mul_lazy, ONE_M
    )[0, ..., 0, :]
    # restore the public bound (callers feed comparisons and PUB-contract
    # plan inputs)
    return reduce_limbs(out, [CHAIN_LIMB_TARGET] * NLIMBS, CHAIN_VALUE_LIMIT)


def inv(a):
    """Field inverse via Fermat (a^(p-2)); inv(0) = 0 (RFC 9380 inv0 semantics)."""
    return pow_fixed_scan(a, P - 2)


def sqrt_candidate(a):
    """a^((p+1)/4) — a square root when a is a QR (p = 3 mod 4). Caller checks
    candidate^2 == a."""
    return pow_fixed_scan(a, (P + 1) // 4)


def sgn0(a):
    """RFC 9380 sgn0 (parity) of a lazy plain-residue element."""
    return from_mont(a)[..., 0] & jnp.uint64(1)


def lex_gt_half_canon(canon):
    """x > (p-1)/2 on a *canonical plain-residue* limb array (MSB-first limb
    compare). Shared by the G1/G2 compressed-point sign-bit paths."""
    half = jnp.asarray(int_to_limbs((P - 1) // 2))
    gt = jnp.zeros(canon.shape[:-1], dtype=bool)
    decided = jnp.zeros(canon.shape[:-1], dtype=bool)
    for i in range(NLIMBS - 1, -1, -1):
        ai, hi = canon[..., i], half[i]
        gt = jnp.where(~decided & (ai > hi), True, gt)
        decided = decided | (ai != hi)
    return gt


def lex_gt_half(a):
    """y > (p-1)/2 on a lazy plain-residue element — the compressed-point sign bit
    (ZCash serialization convention used by the reference's pubkey/sig bytes)."""
    return lex_gt_half_canon(from_mont(a))
