"""Branchless projective curve kernels for BLS12-381 G1/G2 (plan-compiled).

Points are homogeneous projective (X : Y : Z) on y^2 z = x^3 + b z^3 with the
point at infinity (0 : 1 : 0), stored as one flat array ``[..., 3k, 25]`` of
Montgomery-form 16-bit limbs (k = 1 for G1/Fq, k = 2 for G2/Fq2) — X | Y | Z
concatenated on the coefficient axis.

Group ops use the Renes–Costello–Batina *complete* addition formulas for a = 0
curves (eprint 2015/1060, algorithms 7 and 9): no branches, no special cases —
infinity, doubling, and inverse inputs all flow through the same arithmetic.
That is exactly what a vmapped/jitted TPU kernel wants, and it is the design
departure from the reference's blst backend (``/root/reference/crypto/bls/src/
impls/blst.rs``), which branches per point on the CPU.

Each formula is *depth-2 in multiplications*, so a point add/double compiles to
exactly two stacked Montgomery kernels (plans.execute): the 6 (add) / 4 (double)
field products of each level run as one wide ``mont_mul`` over all Karatsuba
lanes, with every linear step folded into the surrounding lincombs. Multiplying
by the curve constant b3 = 3b (12 for G1, 12(u+1) for G2) is linear and costs
no lanes. Static value/limb bounds are tracked and asserted by the plan
machinery at build time.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import fq
from . import plans
from . import tower
from .plans import LC, PUB_BOUND

# --------------------------------------------------------------------------------------
# Coefficient-vector helpers (k = 1: [LC]; k = 2: [LC, LC] little-endian Fq2)
# --------------------------------------------------------------------------------------


def _vec(k: int, off: int):
    return [LC.basis(off + i) for i in range(k)]


def _vadd(x, y):
    return [a + b for a, b in zip(x, y)]


def _vsub(x, y):
    return [a - b for a, b in zip(x, y)]


def _vscale(x, c: int):
    return [a.scale(c) for a in x]


def _b3(k: int, v):
    """Multiply by 3b: G1 b = 4 -> scale 12; G2 b = 4(u+1) -> 12 * (u+1)."""
    if k == 1:
        return _vscale(v, 12)
    return _vscale(plans.v2_nr(v), 12)


def _kmul(p: plans.Plan, k: int, x, y):
    return [p.lane(x[0], y[0])] if k == 1 else p.mul2(x, y)


def _ksqr(p: plans.Plan, k: int, x):
    return [p.lane(x[0], x[0])] if k == 1 else p.sqr2(x)


# --------------------------------------------------------------------------------------
# Plan builders (cached per k)
# --------------------------------------------------------------------------------------

_ADD_PLANS: dict[int, tuple] = {}
_DBL_PLANS: dict[int, tuple] = {}


def _add_plans(k: int):
    """RCB15 algorithm 7 as two plans.

    Level 1 emits [m_a, m_b, m_c, t0, t1, t2n] where
      m_a = X1Y2 + X2Y1,  m_b = Y1Z2 + Y2Z1,  m_c = X1Z2 + X2Z1,
      t0 = 3 X1X2,  t1 = Y1Y2,  t2n = b3 Z1Z2.
    Level 2 computes (with y3 = b3 m_c, z3p = t1 + t2n, t1p = t1 - t2n):
      X3 = m_a t1p - m_b y3,  Y3 = t1p z3p + y3 t0,  Z3 = z3p m_b + t0 m_a.
    """
    if k in _ADD_PLANS:
        return _ADD_PLANS[k]
    p1 = plans.Plan(3 * k, 3 * k)
    x1, y1, z1 = _vec(k, 0), _vec(k, k), _vec(k, 2 * k)
    x2, y2, z2 = _vec(k, 0), _vec(k, k), _vec(k, 2 * k)  # B side, same indices
    pxx = _kmul(p1, k, x1, x2)
    pyy = _kmul(p1, k, y1, y2)
    pzz = _kmul(p1, k, z1, z2)
    pxy = _kmul(p1, k, _vadd(x1, y1), _vadd(x2, y2))
    pyz = _kmul(p1, k, _vadd(y1, z1), _vadd(y2, z2))
    pxz = _kmul(p1, k, _vadd(x1, z1), _vadd(x2, z2))
    m_a = _vsub(_vsub(pxy, pxx), pyy)
    m_b = _vsub(_vsub(pyz, pyy), pzz)
    m_c = _vsub(_vsub(pxz, pxx), pzz)
    t0 = _vscale(pxx, 3)
    t1 = pyy
    t2n = _b3(k, pzz)
    p1.out_rows = m_a + m_b + m_c + t0 + t1 + t2n

    p2 = plans.Plan(6 * k, 6 * k)
    ma, mb, mc, t0v, t1v, t2v = (_vec(k, i * k) for i in range(6))
    y3 = _b3(k, mc)
    z3p = _vadd(t1v, t2v)
    t1p = _vsub(t1v, t2v)
    q1 = _kmul(p2, k, mb, y3)
    q2 = _kmul(p2, k, ma, t1p)
    q3 = _kmul(p2, k, y3, t0v)
    q4 = _kmul(p2, k, t1p, z3p)
    q5 = _kmul(p2, k, t0v, ma)
    q6 = _kmul(p2, k, z3p, mb)
    p2.out_rows = _vsub(q2, q1) + _vadd(q4, q3) + _vadd(q6, q5)
    _ADD_PLANS[k] = (p1, p2)
    return p1, p2


def _dbl_plans(k: int):
    """RCB15 algorithm 9 as two plans.

    Level 1 emits [w0, z8, t2n, pyz, pxy] = [Y^2, 8Y^2, b3 Z^2, YZ, XY].
    Level 2 (with t0m = w0 - 3 t2n, y3p = w0 + t2n):
      X3 = 2 t0m pxy,  Y3 = t2n z8 + t0m y3p,  Z3 = pyz z8.
    """
    if k in _DBL_PLANS:
        return _DBL_PLANS[k]
    p1 = plans.Plan(3 * k, 3 * k)
    x, y, z = _vec(k, 0), _vec(k, k), _vec(k, 2 * k)
    w0 = _ksqr(p1, k, y)
    szz = _ksqr(p1, k, z)
    pyz = _kmul(p1, k, y, z)
    pxy = _kmul(p1, k, x, y)
    p1.out_rows = w0 + _vscale(w0, 8) + _b3(k, szz) + pyz + pxy

    p2 = plans.Plan(5 * k, 5 * k)
    w0v, z8v, t2v, pyzv, pxyv = (_vec(k, i * k) for i in range(5))
    t0m = _vsub(w0v, _vscale(t2v, 3))
    y3p = _vadd(w0v, t2v)
    d1 = _kmul(p2, k, t2v, z8v)
    d2 = _kmul(p2, k, pyzv, z8v)
    d3 = _kmul(p2, k, t0m, y3p)
    d4 = _kmul(p2, k, t0m, pxyv)
    p2.out_rows = _vscale(d4, 2) + _vadd(d1, d3) + d2
    _DBL_PLANS[k] = (p1, p2)
    return p1, p2


# --------------------------------------------------------------------------------------
# Point operations
# --------------------------------------------------------------------------------------


def point_add(k: int, p, q):
    """Complete addition: works for any pair of on-curve points incl. infinity,
    equal, and inverse inputs. p, q: [..., 3k, 25]."""
    p1, p2 = _add_plans(k)
    mid = plans.execute(p1, p, q, PUB_BOUND, PUB_BOUND, f"g{k}add1")
    return plans.execute(p2, mid, mid, PUB_BOUND, PUB_BOUND, f"g{k}add2")


def point_dbl(k: int, p):
    p1, p2 = _dbl_plans(k)
    mid = plans.execute(p1, p, p, PUB_BOUND, PUB_BOUND, f"g{k}dbl1")
    return plans.execute(p2, mid, mid, PUB_BOUND, PUB_BOUND, f"g{k}dbl2")


def point_neg(k: int, p):
    """(X : -Y : Z), renormalized to public bounds."""
    y = plans.carry_norm(tower.t_neg(p[..., k : 2 * k, :]))
    return jnp.concatenate([p[..., 0:k, :], y, p[..., 2 * k :, :]], axis=-2)


def point_select(cond, p, q):
    """cond ? p : q with cond of batch shape."""
    return jnp.where(cond[..., None, None], p, q)


def inf_point(k: int, shape=()):
    """(0 : 1 : 0)."""
    z = np.zeros((3 * k, fq.NLIMBS), dtype=np.uint64)
    z[k] = np.asarray(fq.int_to_limbs(fq.R_MONT % fq.P))
    return jnp.broadcast_to(jnp.asarray(z), shape + (3 * k, fq.NLIMBS))


def is_inf(k: int, p):
    return tower.t_is_zero(p[..., 2 * k :, :])


def point_eq(k: int, p, q):
    """Projective equality X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1. Sound for curve
    points: the groups have odd order, so Y = 0 never occurs and infinity
    (0:1:0) cannot alias a finite point."""
    x1, y1, z1 = p[..., 0:k, :], p[..., k : 2 * k, :], p[..., 2 * k :, :]
    x2, y2, z2 = q[..., 0:k, :], q[..., k : 2 * k, :], q[..., 2 * k :, :]
    if k == 1:
        mul = lambda a, b: fq.mont_mul(a, b)
    else:
        mul = tower.fq2_mul
    ex = tower.t_eq(mul(x1, z2), mul(x2, z1))
    ey = tower.t_eq(mul(y1, z2), mul(y2, z1))
    return ex & ey


def to_affine(k: int, p):
    """(x, y) = (X/Z, Y/Z), each [..., k, 25]; infinity maps to (0, 0) (inv0).
    Inversion is Fermat (a^(p-2)) — wide-batch friendly."""
    x, y, z = p[..., 0:k, :], p[..., k : 2 * k, :], p[..., 2 * k :, :]
    if k == 1:
        zi = fq.inv(z[..., 0, :])[..., None, :]
        return fq.mont_mul(x, zi), fq.mont_mul(y, zi)
    zi = tower.fq2_inv(z)
    return tower.fq2_mul(x, zi), tower.fq2_mul(y, zi)


def from_affine(k: int, x, y, inf=None):
    """Affine coords -> projective; optional inf mask selects (0:1:0)."""
    one = tower.one(k, x.shape[:-2])
    pt = jnp.concatenate([x, y, one], axis=-2)
    if inf is not None:
        pt = point_select(inf, inf_point(k, x.shape[:-2]), pt)
    return pt


# --------------------------------------------------------------------------------------
# Scalar multiplication (double-and-add over a bit plane; branchless select)
# --------------------------------------------------------------------------------------


def scale_bits(k: int, point, bits):
    """[sum bits] * point. bits: uint64 [nbits, *batch] MSB-first; point
    [*batch, 3k, 25]. Runs nbits scan steps of dbl + add + select."""
    # Derive the initial carry from `point` (0*point + inf) so its device-varying
    # type matches the scan output under shard_map (see shard_map scan-vma docs).
    acc0 = point * jnp.uint64(0) + jnp.broadcast_to(inf_point(k), point.shape)

    def step(acc, bit):
        acc = point_dbl(k, acc)
        added = point_add(k, acc, point)
        return point_select(bit == 1, added, acc), None

    acc, _ = jax.lax.scan(step, acc0, bits)
    return acc


def scale_u64(k: int, point, scalars, window: int = 4):
    """Per-point 64-bit scalar multiply (the batch-verification random-scalar
    path, RAND_BITS = 64 per /root/reference/crypto/bls/src/impls/blst.rs:16).

    Fixed-window ladder over an on-device precomputed table: 64/w scan steps
    of (w dbl + 1 table add) — at the default w = 4 that is 16 adds versus
    the bit ladder's 64 (and the old 2-bit window's 32). The per-element
    digit table lookup is a gather; table[0] is infinity, so digit 0 needs
    no masking (complete formulas)."""
    return scale_u64_with_fixed(k, point, scalars, (), window)[0]


def scale_u64_with_fixed(
    k: int, point, scalars, fixed: tuple = (), window: int = 4
):
    """[r]P for device scalars r PLUS [e]P for each host-fixed e — all chains
    share ONE precomputed multiples table and ONE w-bit windowed scan, so
    every point_dbl/point_add dispatch covers the random-scalar chain and
    the fixed chains together (the prologue's subgroup |x|-chain rides the
    Fiat–Shamir scaling for free). fixed entries must be non-negative and
    < 2^64. Returns [1 + len(fixed), *batch, 3k, 25]."""
    assert 64 % window == 0, "window must divide the 64-bit scalar width"
    assert all(0 <= e < 1 << 64 for e in fixed)
    n_ent = 1 << window
    n_lane = 1 + len(fixed)
    inf = point * jnp.uint64(0) + jnp.broadcast_to(inf_point(k), point.shape)
    # incremental multiples as ONE scan (an unrolled build put 2^w - 2
    # point_add bodies in the top-level program — compile-time creep)
    def _tab_body(acc, _):
        nxt = point_add(k, acc, point)
        return nxt, nxt
    _, rest = jax.lax.scan(_tab_body, point, None, length=n_ent - 2)
    table = jnp.concatenate(
        [inf[None], point[None], rest], axis=0
    )  # [2^w, *batch, 3k, 25]
    n_dig = 64 // window
    shifts = jnp.arange(n_dig - 1, -1, -1, dtype=jnp.uint64) * jnp.uint64(window)
    digits = (
        scalars[None, ...] >> shifts.reshape((n_dig,) + (1,) * scalars.ndim)
    ) & jnp.uint64(n_ent - 1)  # [n_dig, *batch]
    digits = digits[:, None]  # lane axis
    if fixed:
        fx = np.array(
            [
                [(e >> (window * (n_dig - 1 - i))) & (n_ent - 1) for e in fixed]
                for i in range(n_dig)
            ],
            dtype=np.uint64,
        )  # [n_dig, F]
        fx = jnp.broadcast_to(
            jnp.asarray(fx).reshape((n_dig, len(fixed)) + (1,) * scalars.ndim),
            (n_dig, len(fixed)) + scalars.shape,
        )
        digits = jnp.concatenate([digits, fx], axis=1)  # [n_dig, L, *batch]

    def step(acc, digit):
        for _ in range(window):
            acc = point_dbl(k, acc)
        idx = digit.astype(jnp.int32)[None, ..., None, None]
        sel = jnp.take_along_axis(table[:, None], idx, axis=0)[0]
        return point_add(k, acc, sel), None

    acc0 = jnp.broadcast_to(
        point[None] * jnp.uint64(0)
        + jnp.broadcast_to(inf_point(k), point.shape),
        (n_lane,) + point.shape,
    )
    acc, _ = jax.lax.scan(step, acc0, digits)
    return acc


def fixed_schedule(e: int) -> list[tuple[int, int]]:
    """Double-and-add schedule of a positive scalar with the MSB consumed by
    initialization: list of (doubling_run, add_flag) segments."""
    bits = bin(e)[2:]
    segs = []
    i = 1
    while i < len(bits):
        j = bits.find("1", i)
        if j == -1:
            segs.append((len(bits) - i, 0))
            break
        segs.append((j - i + 1, 1))
        i = j + 1
    return segs


def scale_fixed(k: int, point, e: int, window: int | None = None):
    """Multiply by a host-fixed scalar (subgroup checks, cofactor clearing).

    Compiled at trace time by the fixed-scalar plan compiler
    (chain_plans.compile_chains): the scalar is recoded (binary / NAF /
    width-w wNAF, cheapest wins by a cost model) into a shared-doubling-run
    segment schedule with a precomputed odd-multiple table, and emitted as
    ONE lax.scan whose body is a dynamic-count doubling fori_loop plus one
    table-gather add — a single compiled (dbl + add) body per call site.
    For the weight-6 BLS |x| this is 61 dbl + 5 add (wNAF) vs the old plain
    binary schedule's 63 dbl + 6 add; dense scalars (x^2 - x - 1, u^2) gain
    far more from the window. Negative and zero scalars are handled in the
    plan (branchless final negation / the infinity table slot)."""
    from . import chain_plans

    return chain_plans.scale_fixed_chain(k, point, e, window)


# --------------------------------------------------------------------------------------
# Batch reduction (aggregation)
# --------------------------------------------------------------------------------------


def point_sum(k: int, pts, valid=None):
    """Sum points over the leading batch axis by halving tree reduction
    (log2(n) point_add kernels, each on a halved batch). pts: [n, *batch, 3k, 25].
    ``valid`` ([n, *batch] bool) masks entries (invalid -> infinity)."""
    n = pts.shape[0]
    if valid is not None:
        pts = point_select(valid, pts, jnp.broadcast_to(inf_point(k), pts.shape))
    while n > 1:
        if n % 2:
            pts = jnp.concatenate(
                [pts, jnp.broadcast_to(inf_point(k), (1,) + pts.shape[1:])], axis=0
            )
            n += 1
        pts = point_add(k, pts[: n // 2], pts[n // 2 :])
        n //= 2
    return pts[0]
