"""Fused Pallas/Mosaic limb kernels: conv -> congruence-fold -> carry on the MXU.

The third conv backend (``LIGHTHOUSE_CONV_IMPL=pallas``, the TPU default).
The u64/f64 backends materialize the limb multiply pipeline as separate HLO
stages — ``fq._conv_product`` accumulators, the out-lincomb, then the
``fq.reduce_limbs`` fold/carry walk — and XLA re-stages each boundary through
memory per call. Here the WHOLE pipeline after the input lincombs runs as ONE
``pl.pallas_call`` per tower op:

* **Number format.** Everything inside the kernel is base-2^8 *digit planes*
  in f32 (Mosaic has no u64; f32 FMA is the full-rate VPU/MXU path — the same
  reasoning as ``fq._conv_product_digits``). A 25x16-bit-limb element is 51
  digits; digit bounds are tracked exactly (Python ints) and every
  intermediate is proven < 2^24, the f32 integer-exactness cap, so the whole
  kernel is EXACT integer arithmetic in float registers.

* **Convolution as an MXU matmul tile.** The 51x51 digit outer product is
  flattened and multiplied by a constant 0/1 *shear* matrix S[(i,j), i+j]
  ([2601, 101]): one ``dot_general`` against constant weights — the systolic
  array does the anti-diagonal accumulation that the unrolled shifted-FMA
  chain of the XLA digits backend spreads over 51 VPU passes.

* **Congruence fold as a matmul.** Digit positions >= 48 (weight 2^384) fold
  through constant rows F8[h] = digits(2^(8*(48+h)) mod p): a
  ``[batch, n_hi] x [n_hi, 48]`` dot — exactly the shape the PR-4 f64 matmul
  fold wanted, now on MXU tiles inside the kernel.

* **Carry rounds stay in-register.** The width-preserving base-2^8
  carry-save rounds (exact f32 floor-multiply splits) interleave with folds
  per a STATIC schedule derived from the exact bound walk — the in-kernel
  twin of ``fq.reduce_limbs``'s phase structure, with zero HLO round-trips.

* **The out-lincomb rides inside too** (``execute_plan``): a tower op's
  output linear map runs on the unreduced conv digits as one
  ``[R, L] x [tile, L, W]`` contraction (negative coefficients via
  digit-space borrow constants == 0 mod p), so an fq12 multiply still reduces
  12 rows, not 54 lanes — the plans.py contract, fused.

Every bound the schedule relies on is recorded as a trace-time ``fq._cert``
obligation (kinds ``pallas_*``) and proven per-graph by
``analysis/bounds.py`` under all three backends; a bound that does not hold
raises at trace time and the certifier records the unproven edge.

On non-TPU platforms the kernels run in Pallas **interpret mode** — the same
kernel program executed by the XLA emulator — which is how tier-1 proves
bit-exact parity (canonical values equal the digits/f64 backends and the
oracle) on the CPU dev box. Interpret mode is an emulator: it validates
numerics and schedules, not wall clock.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import fq
from ..bls_oracle.fields import P

_D = 51                 # digits per 25-limb element (base 2^8; fq._N_DIGITS)
_CONV_D = 2 * _D - 1    # 101 conv output digit positions
_FOLD_BASE = 48         # digit position of 2^384: everything above folds mod p
_F32_CAP = (1 << 24) - 1  # f32 integer exactness cap
_N_FOLD8 = 64           # fold rows provisioned (widths stay far below this)

_LIMB_PER = 2           # digits per 16-bit limb
_OUT_D = 50             # output digit positions (25 limbs)


def _int_to_digits(x: int, n: int) -> list[int]:
    return [(x >> (8 * i)) & 0xFF for i in range(n)]


# Constant shear: S[(i, j), i + j] = 1 — conv as one MXU matmul.
_SHEAR_NP = np.zeros((_D * _D, _CONV_D), dtype=np.float32)
for _i in range(_D):
    for _j in range(_D):
        _SHEAR_NP[_i * _D + _j, _i + _j] = 1.0

# Congruence-fold rows in digit space: F8[h] = digits48(2^(8*(48+h)) mod p).
# Residues are < p < 2^381 — 48 digits each, entries <= 255.
_FOLD8_NP = np.stack(
    [
        np.array(
            _int_to_digits((1 << (8 * (_FOLD_BASE + h))) % P, _FOLD_BASE),
            dtype=np.float32,
        )
        for h in range(_N_FOLD8)
    ]
)
_FOLD8_INT = [
    [int(v) for v in _FOLD8_NP[h]] for h in range(_N_FOLD8)
]
_FOLD8_VALS = [(1 << (8 * (_FOLD_BASE + h))) % P for h in range(_N_FOLD8)]


def _interpret() -> bool:
    """Interpret (emulate) the kernels off-TPU; override for testing."""
    forced = os.environ.get("LIGHTHOUSE_PALLAS_INTERPRET")
    if forced in ("0", "1"):
        return forced == "1"
    return jax.default_backend() != "tpu"


# VMEM accounting sink (analysis/memory.py, pass 6): with a list installed,
# every per-trace kernel launch records its tile signature + estimated
# per-grid-step VMEM working set. The hook lives HERE, at the host wrapper
# level, because _build_call is lru_cached — a hook inside it would fire
# once per static signature ever, not once per trace the certifier runs.
_VMEM_SINK: list | None = None


def _record_vmem(tile: int, L: int, rows_p: int, out_key) -> None:
    if _VMEM_SINK is None:
        return
    if out_key is not None:
        R, mpos_np, mneg_np, oconst_np, n_pass, pass_w = _OUT_TABLE[out_key]
        n_rows_out = R
        const_b = mpos_np.nbytes
        if bool(mneg_np.any()):
            const_b += mneg_np.nbytes + oconst_np.nbytes
    else:
        n_rows_out, n_pass, pass_w = L, 0, 0
        const_b = 0
    const_b += _SHEAR_NP.nbytes + _FOLD8_NP.nbytes
    blocks_in = 2 * tile * L * _D * 4
    if n_pass:
        blocks_in += tile * n_pass * pass_w * 4
    block_out = tile * n_rows_out * _OUT_D * 4
    # the in-kernel digit outer product dominates (_row_tile budgets ~4 MiB
    # for it); grid-blocked operands double-buffer across grid steps
    prod = tile * L * _D * _D * 4
    _VMEM_SINK.append({
        "tile": tile,
        "lanes": L,
        "grid": rows_p // tile,
        "n_rows_out": n_rows_out,
        "n_pass": n_pass,
        "block_bytes": blocks_in + block_out,
        "const_bytes": const_b,
        "outer_product_bytes": prod,
        "est_vmem_bytes": prod + 2 * (blocks_in + block_out) + const_b,
    })


# --------------------------------------------------------------------------------------
# Exact digit-domain bound state (the _RState twin for base-2^8 planes)
# --------------------------------------------------------------------------------------


class _DState:
    """Per-digit-position bounds (Python ints) plus an exact value bound,
    mutually refined: digits are non-negative, so d_i <= value >> 8i. Every
    schedule op updates the state exactly — f32 exactness and the output
    value/limb targets are proven at trace time, like fq._RState."""

    __slots__ = ("digits", "value")

    def __init__(self, digits, value: int):
        digits = list(digits)
        value = min(value, sum(b << (8 * i) for i, b in enumerate(digits)))
        self.digits = [min(b, value >> (8 * i)) for i, b in enumerate(digits)]
        self.value = value


def _split_state(s: _DState) -> _DState:
    """One base-2^8 carry-save round: d -> (d & 0xFF) + (d_{i-1} >> 8),
    width + 1. Value-invariant; exact in f32 for digits < 2^24."""
    lo = [min(b, 0xFF) for b in s.digits] + [0]
    hi = [0] + [b >> 8 for b in s.digits]
    return _DState([a + b for a, b in zip(lo, hi)], s.value)


def _fold_state(s: _DState, name: str) -> _DState:
    """Fold positions >= 48 through the 2^(8k) mod p rows — exact congruence.
    Caller has checked the f32 budget; this records the obligation."""
    n_hi = len(s.digits) - _FOLD_BASE
    lo_b, hi_b = s.digits[:_FOLD_BASE], s.digits[_FOLD_BASE:]
    digits = [
        b + sum(hb * _FOLD8_INT[h][i] for h, hb in enumerate(hi_b))
        for i, b in enumerate(lo_b)
    ]
    assert fq._cert(
        "pallas_fold_f32_exact", max(digits), _F32_CAP, note=name
    ), f"{name}: pallas fold exceeds f32 exactness"
    lo_val = sum(b << (8 * i) for i, b in enumerate(lo_b))
    value = min(s.value, lo_val) + sum(
        hb * _FOLD8_VALS[h] for h, hb in enumerate(hi_b)
    )
    assert n_hi <= _N_FOLD8
    return _DState(digits, value)


def _fold_budget(s: _DState) -> int:
    """Worst post-fold digit if we folded now (f32-budget check)."""
    lo_b, hi_b = s.digits[:_FOLD_BASE], s.digits[_FOLD_BASE:]
    return max(
        b + sum(hb * _FOLD8_INT[h][i] for h, hb in enumerate(hi_b))
        for i, b in enumerate(lo_b)
    )


def _trim_state(s: _DState) -> _DState:
    digits = list(s.digits)
    while len(digits) > _FOLD_BASE and digits[-1] == 0:
        digits.pop()
    return _DState(digits, s.value)


def _reduce_schedule(
    s: _DState, value_limit: int, limb_target: int, name: str
) -> tuple[list, _DState]:
    """Static split/fold schedule bringing the state to value <= value_limit
    and recombined 16-bit limbs <= limb_target — the digit-domain twin of
    fq.reduce_limbs' phases, fully decided at trace time. Returns
    (ops, final state); ops are replayed verbatim by the kernel body.

    Positions 48-49 (the 25th limb) are LEGAL output positions: folding is
    only scheduled while the width exceeds the 50-digit output layout or the
    value target demands shrinking — a fold re-fattens the low digits by one
    row term, so folding past the value target would chase its own tail."""
    ops: list = []

    def trim(s: _DState) -> _DState:
        t = _trim_state(s)
        if len(t.digits) != len(s.digits):
            ops.append(("trim", len(t.digits)))
        return t

    def limbs_fit(s: _DState) -> bool:
        if len(s.digits) > _OUT_D:
            return False
        d = list(s.digits) + [0] * (_OUT_D - len(s.digits))
        return all(
            d[2 * i] + (d[2 * i + 1] << 8) <= limb_target
            for i in range(_OUT_D // 2)
        )

    for _ in range(96):
        s = trim(s)
        w = len(s.digits)
        if w > _OUT_D or (s.value > value_limit and w > _FOLD_BASE):
            if _fold_budget(s) <= _F32_CAP:
                s = _fold_state(s, name)
                ops.append(("fold", w - _FOLD_BASE))
            else:
                s = _split_state(s)
                ops.append(("split",))
        elif s.value > value_limit or not limbs_fit(s):
            # excess sits in low digits: surface it with a split; the next
            # iteration folds the spill at position >= 48 (always fits — the
            # digits are already carry-saved by then)
            s = _split_state(s)
            ops.append(("split",))
        else:
            break
    else:  # pragma: no cover - static schedule
        raise AssertionError(f"{name}: pallas reduce schedule did not converge")
    # final width must recombine into 25 limbs (positions 0..49)
    assert fq._cert(
        "pallas_out_width",
        sum(b << (8 * i) for i, b in enumerate(s.digits)),
        (1 << (8 * _OUT_D)) - 1,
        note=name,
    ), f"{name}: pallas output exceeds 25 limbs"
    return ops, s


def _final_certs(
    s: _DState, value_limit: int, limb_target: int, name: str
) -> None:
    """Record the output-contract obligations (value / limb / top limb)."""
    digits = list(s.digits) + [0] * (_OUT_D - len(s.digits))
    limbs = [
        digits[2 * i] + (digits[2 * i + 1] << 8) for i in range(_OUT_D // 2)
    ]
    assert fq._cert(
        "pallas_reduce_value", s.value, value_limit, note=name
    ), f"{name}: pallas value bound {s.value / P:.2f}p exceeds target"
    assert fq._cert(
        "pallas_reduce_limb", max(limbs), limb_target, note=name
    ), f"{name}: pallas limb bound {max(limbs):#x} exceeds target"
    # the f32 -> u32 recombination cast outside the kernel is lossless
    assert fq._cert(
        "pallas_digit_u32_nowrap", max(digits), (1 << 32) - 1, note=name
    )
    if value_limit == fq.PUB_VALUE_LIMIT:
        assert fq._cert(
            "pallas_reduce_top_limb",
            min(limbs[24], s.value >> (16 * 24)),
            2,
            note=name,
        )


# --------------------------------------------------------------------------------------
# Digit-space borrow constants for the fused out-lincomb
# --------------------------------------------------------------------------------------

_DSUBC_CACHE: dict[tuple[int, int], np.ndarray] = {}


def _dsubc_wide(n_digits: int, cover: int) -> np.ndarray:
    """A constant == 0 mod p in n_digits-digit space with every digit >=
    cover (subtraction cover for unreduced conv digit planes) — the base-2^8
    twin of plans._subc_wide."""
    key = (n_digits, cover)
    if key not in _DSUBC_CACHE:
        c = [cover] * n_digits
        adj = (-sum(v << (8 * i) for i, v in enumerate(c))) % P
        for i in range(_FOLD_BASE):
            c[i] += (adj >> (8 * i)) & 0xFF
        assert sum(v << (8 * i) for i, v in enumerate(c)) % P == 0
        _DSUBC_CACHE[key] = np.array(c, dtype=np.float32)
    return _DSUBC_CACHE[key]


# --------------------------------------------------------------------------------------
# Kernel construction
# --------------------------------------------------------------------------------------


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _row_tile(rows: int, lanes: int) -> int:
    """Row-tile size: the in-kernel outer product is [tile, L, 51, 51] f32 —
    budget ~4 MiB of VMEM for it (grid steps pipeline the rest)."""
    budget = (4 << 20) // max(1, lanes * _D * _D * 4)
    tile = max(8, min(128, _pow2_floor(max(1, budget))))
    return min(tile, max(8, _pow2_floor(max(1, rows))))


def _split_array(t):
    """In-kernel base-2^8 carry-save round (exact: digits < 2^24)."""
    hi = jnp.floor(t * (1.0 / 256.0))
    lo = t - hi * 256.0
    nb = [(0, 0)] * (t.ndim - 1)
    return jnp.pad(lo, nb + [(0, 1)]) + jnp.pad(hi, nb + [(1, 0)])


# Every in-kernel contraction is integer arithmetic in f32 registers: the
# MXU must NOT lower it through reduced-precision bf16 passes (the default
# f32 matmul policy on TPU), or the certified < 2^24 exactness silently
# breaks on the first real window. HIGHEST forces true f32 accumulation;
# on the CPU interpreter it is a no-op.
_EXACT = jax.lax.Precision.HIGHEST


def _replay(t, ops, f8):
    """Apply a static reduce schedule to in-kernel digit planes."""
    for op in ops:
        if op[0] == "split":
            t = _split_array(t)
        elif op[0] == "trim":
            t = t[..., : op[1]]
        else:  # fold
            n_hi = op[1]
            hi = t[..., _FOLD_BASE:]
            folded = jax.lax.dot_general(
                hi,
                f8[:n_hi],
                (((t.ndim - 1,), (0,)), ((), ())),
                precision=_EXACT,
                preferred_element_type=jnp.float32,
            )
            t = t[..., :_FOLD_BASE] + folded
    return t


def _pad_width(t, w: int):
    if t.shape[-1] < w:
        t = jnp.pad(t, [(0, 0)] * (t.ndim - 1) + [(0, w - t.shape[-1])])
    return t


@functools.lru_cache(maxsize=512)
def _build_call(
    rows_p: int,
    tile: int,
    n_lanes: int,
    pre_ops: tuple,
    out_key,          # None | (R, mpos bytes-key, mneg key, oconst key, n_pass, pass_w)
    post_ops: tuple,
    interpret: bool,
):
    """Build (and cache) the fused pallas_call for one static signature.
    The matrices referenced by ``out_key`` are re-materialized from the
    per-key side table (they are part of the cache key via content hash)."""
    L = n_lanes
    grid = rows_p // tile
    has_out = out_key is not None
    if has_out:
        R, mpos_np, mneg_np, oconst_np, n_pass, pass_w = _OUT_TABLE[out_key]
        has_neg = bool(mneg_np.any())
        n_rows_out = R
    else:
        n_rows_out = L
        has_neg = False
        n_pass = 0

    def body(*refs):
        a_ref, b_ref, shear_ref, f8_ref = refs[:4]
        idx = 4
        if has_out:
            mpos_ref = refs[idx]
            idx += 1
            if has_neg:
                mneg_ref, oconst_ref = refs[idx : idx + 2]
                idx += 2
        if n_pass:
            ain_ref = refs[idx]
            idx += 1
        o_ref = refs[idx]
        A = a_ref[...]  # [tile, L, 51]
        B = b_ref[...]
        # conv: digit outer product, anti-diagonals summed by the constant
        # shear matmul — one MXU tile per (row, lane)
        prod = A[..., :, None] * B[..., None, :]  # [tile, L, 51, 51]
        flat = prod.reshape(tile * L, _D * _D)
        t = jax.lax.dot_general(
            flat,
            shear_ref[...],
            (((1,), (0,)), ((), ())),
            precision=_EXACT,
            preferred_element_type=jnp.float32,
        )
        t = t.reshape(tile, L, _CONV_D)
        t = _replay(t, pre_ops, f8_ref[...])
        if has_out:
            w = t.shape[-1]
            if n_pass:
                t = jnp.concatenate(
                    [t, _pad_width(ain_ref[...], w)], axis=-2
                )
            pos = jnp.einsum(
                "tld,rl->trd", t, mpos_ref[...],
                precision=_EXACT,
                preferred_element_type=jnp.float32,
            )
            if has_neg:
                neg = jnp.einsum(
                    "tld,rl->trd", t, mneg_ref[...],
                    precision=_EXACT,
                    preferred_element_type=jnp.float32,
                )
                t = pos + (oconst_ref[...][None, :, :] - neg)
            else:
                t = pos
        t = _replay(t, post_ops, f8_ref[...])
        o_ref[...] = _pad_width(t, _OUT_D)

    # assemble specs
    def bs(shape):
        n = len(shape)
        return pl.BlockSpec(
            (tile,) + shape, lambda i, _n=n: (i,) + (0,) * _n
        )

    def const_bs(shape):
        n = len(shape)
        return pl.BlockSpec(shape, lambda i, _n=n: (0,) * _n)

    in_specs = [
        bs((L, _D)),
        bs((L, _D)),
        const_bs(_SHEAR_NP.shape),
        const_bs(_FOLD8_NP.shape),
    ]
    # keep the constant operands as NUMPY in the cached closure: a jnp
    # constant materialized inside whatever trace first built this call
    # would be a trace-local tracer — caching it leaks it into every later
    # trace (UnexpectedTracerError). asarray at run time is a per-trace
    # constant, folded by XLA.
    operands_const = [_SHEAR_NP, _FOLD8_NP]
    if has_out:
        in_specs.append(const_bs(mpos_np.shape))
        operands_const.append(mpos_np)
        if has_neg:
            in_specs += [const_bs(mneg_np.shape), const_bs(oconst_np.shape)]
            operands_const += [mneg_np, oconst_np]
    if n_pass:
        in_specs.append(bs((n_pass, pass_w)))
    out_spec = pl.BlockSpec(
        (tile, n_rows_out, _OUT_D), lambda i: (i, 0, 0)
    )

    call = pl.pallas_call(
        body,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct(
            (rows_p, n_rows_out, _OUT_D), jnp.float32
        ),
        interpret=interpret,
    )

    def run(A_d, B_d, Ain_d=None):
        args = [A_d, B_d] + [jnp.asarray(c) for c in operands_const]
        if n_pass:
            args.append(Ain_d)
        return call(*args)

    return run


# side table: content-addressed out-map matrices (lru_cache keys must be
# hashable; the key is a digest of the matrix content, the table holds the
# arrays themselves)
_OUT_TABLE: dict = {}


def _out_key(R, mpos, mneg, oconst, n_pass, pass_w):
    key = (
        R,
        mpos.tobytes(),
        mneg.tobytes(),
        oconst.tobytes(),
        n_pass,
        pass_w,
    )
    _OUT_TABLE[key] = (R, mpos, mneg, oconst, n_pass, pass_w)
    return key


# --------------------------------------------------------------------------------------
# Host-side wrappers
# --------------------------------------------------------------------------------------


def _digits_of(x):
    """u64 limb planes -> f32 digit planes (outside the kernel: Mosaic has
    no u64; the extraction is a handful of fused elementwise HLO ops)."""
    if x.dtype != jnp.uint64:
        # the f64 walk never reaches the pallas path; accept exact-int casts
        x = x.astype(jnp.uint64)
    return fq._to_digits_f32(x)


def _limbs_of(d):
    """f32 digit planes [..., 50] -> u64 16-bit-limb planes [..., 25]
    (exact: the schedule proves digits < 2^24 < 2^32)."""
    di = d.astype(jnp.uint32).astype(jnp.uint64)
    pairs = di.reshape(d.shape[:-1] + (_OUT_D // 2, 2))
    return pairs[..., 0] + (pairs[..., 1] << jnp.uint64(8))


def _rows_of(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _run_fused(A_d, B_d, pre_ops, out_key, post_ops, Ain_d=None):
    """Pad rows to the tile multiple, run the cached call, slice back."""
    rows = A_d.shape[0]
    L = A_d.shape[1]
    tile = _row_tile(rows, L)
    rows_p = -(-rows // tile) * tile
    pad = [(0, rows_p - rows)] + [(0, 0)] * (A_d.ndim - 1)
    if rows_p != rows:
        A_d = jnp.pad(A_d, pad)
        B_d = jnp.pad(B_d, pad)
        if Ain_d is not None:
            Ain_d = jnp.pad(
                Ain_d, [(0, rows_p - rows)] + [(0, 0)] * (Ain_d.ndim - 1)
            )
    _record_vmem(tile, L, rows_p, out_key)
    run = _build_call(
        rows_p, tile, L, tuple(pre_ops), out_key, tuple(post_ops), _interpret()
    )
    out = run(A_d, B_d, Ain_d)
    return out[:rows]


def fused_mul(a, b, lazy: bool = False):
    """The fused pallas twin of fq.mont_mul (lazy=False: operands within the
    lazy budget, output at plans.PUB_BOUND) / fq.mont_mul_lazy (lazy=True:
    chain-bound operands and output — the chain fixed point). One pallas_call:
    digit conv (MXU shear matmul) -> static fold/carry schedule, all
    in-register."""
    name = "pallas_mul_lazy" if lazy else "pallas_mul"
    if lazy:
        in_limb, in_value = fq.CHAIN_LIMB_TARGET, fq.CHAIN_VALUE_LIMIT
        value_limit, limb_target = fq.CHAIN_VALUE_LIMIT, fq.CHAIN_LIMB_TARGET
    else:
        in_limb, in_value = fq._IN_LIMB, fq._IN_VALUE
        value_limit, limb_target = fq.PUB_VALUE_LIMIT, fq.PUB_LIMB_TARGET
    a, b = jnp.broadcast_arrays(a, b)
    batch = a.shape[:-1]
    rows = _rows_of(batch)
    da = _digits_of(a).reshape(rows, 1, _D)
    db = _digits_of(b).reshape(rows, 1, _D)
    dig = fq._digit_bound(in_limb)
    conv = [
        (min(d, 2 * _D - 2 - d, _D - 1) + 1) * dig * dig
        for d in range(_CONV_D)
    ]
    assert fq._cert(
        "pallas_conv_digit_f32_exact", max(conv), _F32_CAP, note=name
    ), f"{name}: digit conv exceeds f32 exactness"
    state = _DState(conv, in_value * in_value)
    ops, state = _reduce_schedule(state, value_limit, limb_target, name)
    _final_certs(state, value_limit, limb_target, name)
    out = _run_fused(da, db, ops, None, ())
    return _limbs_of(out[:, 0]).reshape(batch + (fq.NLIMBS,))


def execute_plan(
    plan, a, b, in_bound_a, in_bound_b, name: str = "", out_bound=None
):
    """The full pallas arm of plans.execute: input lincombs (XLA u64 — they
    are constant-matrix dots the compiler already fuses), then ONE fused
    kernel for conv -> out-lincomb -> congruence-fold -> carry. Backend-
    independent entry (the certifier registers it under every backend);
    plans.execute dispatches here when conv_backend() == "pallas"."""
    from . import plans

    kname = name or "plan"
    A, ba = plans.lincomb(plan.a_rows, a, in_bound_a, kname + ".A")
    b = plans.append_const_pool(plan, b)
    B, bb = plans.lincomb(plan.b_rows, b, in_bound_b, kname + ".B")
    A, B = jnp.broadcast_arrays(A, B)
    batch = A.shape[:-2]
    rows = _rows_of(batch)
    L = len(plan.a_rows)
    A_d = _digits_of(A).reshape((rows, L, _D))
    B_d = _digits_of(B).reshape((rows, L, _D))

    # conv digit bounds per position, one lane-uniform state
    dig_a, dig_b = fq._digit_bound(ba.limb), fq._digit_bound(bb.limb)
    conv = [
        (min(d, 2 * _D - 2 - d, _D - 1) + 1) * dig_a * dig_b
        for d in range(_CONV_D)
    ]
    assert fq._cert(
        "pallas_conv_digit_f32_exact", max(conv), _F32_CAP, note=kname
    ), f"{kname}: digit conv exceeds f32 exactness"
    lane_value = (ba.value_p * P) * (bb.value_p * P)
    lane_state = _DState(conv, lane_value)

    # pass-through rows reference the raw input a
    has_pass = any(i < 0 for lc in plan.out_rows for i in lc.d)
    n_pass = a.shape[-2] if has_pass else 0
    pass_dig = fq._digit_bound(in_bound_a.limb)
    pass_value = in_bound_a.value_p * P
    if has_pass:
        out_rows = plans.remap_passthrough_rows(plan, L)
    else:
        out_rows = plan.out_rows

    # pre-split the conv lanes until the out-lincomb accumulators fit f32
    coeff_pos = [
        sum(c for c in lc.d.values() if c > 0) for lc in out_rows
    ]
    coeff_neg = [
        sum(-c for c in lc.d.values() if c < 0) for lc in out_rows
    ]
    pre_ops: list = []
    for _ in range(8):
        worst_lane = max(lane_state.digits)
        worst_in = max(worst_lane, pass_dig if has_pass else 0)
        cover = max(coeff_neg) * worst_in if any(coeff_neg) else 0
        budget = max(coeff_pos + [1]) * worst_in + cover + 255
        if budget <= _F32_CAP:
            break
        lane_state = _split_state(lane_state)
        pre_ops.append(("split",))
    else:  # pragma: no cover - static schedule
        raise AssertionError(f"{kname}: pallas out-lincomb does not fit f32")
    w = len(lane_state.digits)

    # out-row bound profiles + digit-space borrow constants
    def profile(idx):
        if idx < L:
            return lane_state.digits, lane_state.value
        return (
            [pass_dig] * _D + [0] * (w - _D),
            pass_value,
        )

    R = len(out_rows)
    mpos = np.zeros((R, L + n_pass), dtype=np.float32)
    mneg = np.zeros((R, L + n_pass), dtype=np.float32)
    oconst = np.zeros((R, w), dtype=np.float32)
    out_digits = [0] * w
    out_value = 0
    for r, lc in enumerate(out_rows):
        row_d = [0] * w
        row_v = 0
        n_cover = 0
        for idx, c in sorted(lc.d.items()):
            pdig, pval = profile(idx)
            if c > 0:
                mpos[r, idx] = c
                row_d = [x + c * y for x, y in zip(row_d, pdig)]
                row_v += c * pval
            else:
                mneg[r, idx] = -c
                n_cover += (-c) * max(pdig)
        if n_cover:
            subc = _dsubc_wide(w, n_cover)
            oconst[r] = subc
            row_d = [x + int(y) for x, y in zip(row_d, subc)]
            row_v += sum(int(y) << (8 * i) for i, y in enumerate(subc))
        assert fq._cert(
            "pallas_lincomb_f32_exact", max(row_d), _F32_CAP, note=kname
        ), f"{kname}: pallas out-row exceeds f32 exactness"
        out_digits = [max(x, y) for x, y in zip(out_digits, row_d)]
        out_value = max(out_value, row_v)

    out_state = _DState(out_digits, out_value)
    if out_bound is None:
        value_limit, limb_target = fq.PUB_VALUE_LIMIT, fq.PUB_LIMB_TARGET
    else:
        # the declared top-limb bound must dominate what the walk guarantees
        assert fq._cert(
            "pallas_out_bound_top_sound",
            min(out_bound.limb, (out_bound.value_p * P) >> (16 * 24)),
            out_bound.top,
            note=kname,
        ), "out_bound.top unsound for its value/limb bounds"
        value_limit, limb_target = out_bound.value_p * P, out_bound.limb
    post_ops, out_state = _reduce_schedule(
        out_state, value_limit, limb_target, kname
    )
    _final_certs(out_state, value_limit, limb_target, kname)

    Ain_d = None
    if has_pass:
        a_full = jnp.broadcast_to(a, batch + a.shape[-2:])
        Ain_d = _digits_of(a_full).reshape((rows, n_pass, _D))
    key = _out_key(R, mpos, mneg, oconst, n_pass, _D)
    out = _run_fused(A_d, B_d, pre_ops, key, post_ops, Ain_d)
    return _limbs_of(out).reshape(batch + (R, fq.NLIMBS))
