"""Fixed-scalar plan compiler: windowed/wNAF chain schedules for host-known
scalars and exponents, executed as ONE ``lax.scan`` per call site.

The scalar-mul analogue of the lincomb ``Plan`` machinery in ``plans.py``:
where a ``Plan`` flattens one tower *multiplication* into a single stacked
kernel, a ``ChainSchedule`` flattens a whole *scalar multiplication* (or a
fixed-exponent power) into a static schedule of shared-doubling runs plus
table-referencing add steps, compiled at trace time from the host-known
scalars:

  * ``compile_chains([e_0, .., e_C-1])`` recodes each scalar (plain binary,
    NAF, or width-w wNAF — a cost model picks the cheapest; sparse scalars
    like the BLS parameter |x| stay on the binary schedule, dense ones get a
    window) and merges the C chains onto ONE position-aligned segment list:
    every dbl/sqr kernel dispatch covers all chains at once.
  * ``run_point_chains`` executes the schedule on stacked curve points
    ([C, *batch, 3k, 25]) — odd-multiple tables built jointly, signs applied
    by a branchless negate-select (complete formulas make the infinity slot
    of a zero digit a no-op), body emitted as one scan over (run, digit)
    segments.
  * ``run_field_chains`` executes the same schedule shape in a multiplicative
    group (sqr/mul callbacks) with per-chain exponents — the h2c prep chains
    (sqrt-ratio / inversion exponents) run as one joint scan with
    lazy-bounded interiors (plans.CHAIN_BOUND) and a single trailing
    normalization.

Scalars may be negative (point chains negate branchlessly at the end) or
zero (the schedule degenerates to the identity/infinity). Windows are chosen
per call site by ``_schedule_cost`` unless forced.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


# --------------------------------------------------------------------------------------
# Host-side recoding
# --------------------------------------------------------------------------------------


def wnaf_digits(e: int, w: int) -> list[int]:
    """LSB-first width-w NAF: nonzero digits are odd, |d| < 2^(w-1), and any
    two nonzero digits are >= w positions apart. w = 1 gives plain binary
    (digits 0/1); w = 2 gives classic NAF."""
    assert e >= 0
    if w == 1:
        return [int(b) for b in bin(e)[2:][::-1]] if e else [0]
    out = []
    while e:
        if e & 1:
            d = e & ((1 << w) - 1)
            if d >= 1 << (w - 1):
                d -= 1 << w
            out.append(d)
            e -= d
        else:
            out.append(0)
        e >>= 1
    return out or [0]


class ChainSchedule:
    """Joint MSB-first schedule for C chains sharing doubling runs.

    segments: list of (run, digits) — ``run`` doublings (squarings), then one
    add (multiply) step consuming per-chain signed digit ``digits[c]`` (0 =
    no-op via the identity table slot). The leading segment has run = 0 and
    initializes the accumulators from the table directly.
    table_max: largest |digit| across chains — the joint table holds the
    multiples {identity, 1, 3, .., table_max} (odd only for signed schedules,
    every value for unsigned ones).
    """

    __slots__ = ("segments", "n_chains", "table_max", "signed", "negate")

    def __init__(self, segments, n_chains, table_max, signed, negate):
        self.segments = segments
        self.n_chains = n_chains
        self.table_max = table_max
        self.signed = signed
        self.negate = negate  # per-chain final negation (negative scalars)

    @property
    def n_doublings(self) -> int:
        return sum(r for r, _ in self.segments)

    @property
    def n_adds(self) -> int:
        return len(self.segments)

    def table_slots(self) -> list[int]:
        """Multiples materialized in the table, identity first."""
        if self.signed:
            return [0] + list(range(1, self.table_max + 1, 2))
        return list(range(self.table_max + 1))

    def slot_index(self, d: int) -> int:
        """Table slot of |digit| d."""
        if self.signed:
            return 0 if d == 0 else (abs(d) + 1) // 2
        return d


def _merge_digit_columns(digit_rows: list[list[int]]):
    """Per-chain LSB-first digit lists -> MSB-first merged (run, column)
    segments. A column is emitted wherever ANY chain has a nonzero digit."""
    n = max(len(r) for r in digit_rows)
    cols = []
    for i in range(n - 1, -1, -1):  # MSB first
        col = tuple(r[i] if i < len(r) else 0 for r in digit_rows)
        cols.append(col)
    segments = []
    run = 0
    started = False
    for col in cols:
        if any(col):
            segments.append((run if started else 0, col))
            run = 1
            started = True
        else:
            run += 1
    if not started:
        return [(0, tuple(0 for _ in digit_rows))]
    # trailing zero columns: pure doublings with a no-op digit column
    if run > 1:
        segments.append((run - 1, tuple(0 for _ in digit_rows)))
    return segments


def _schedule_cost(schedule: ChainSchedule, dbl_cost=1.0, add_cost=1.2) -> float:
    """Rough op-count model: doubling runs + add steps + table build."""
    slots = len(schedule.table_slots())
    return (
        schedule.n_doublings * dbl_cost
        + schedule.n_adds * add_cost
        + max(0, slots - 2) * add_cost
    )


@functools.lru_cache(maxsize=None)
def compile_chains(
    scalars: tuple, window: int | None = None, signed: bool = True
) -> ChainSchedule:
    """Compile host-known scalars into the cheapest joint schedule.

    signed=True allows wNAF recoding (group inverses are cheap for curve
    points); signed=False restricts to unsigned windows (field chains, where
    inversion is a whole Fermat chain). With window=None the cost model
    scans w in 1..6 and keeps the cheapest — sparse scalars (|x|, u^2) stay
    binary, dense ones (sqrt-ratio exponents) get a window.
    """
    mags = [abs(int(e)) for e in scalars]
    negate = tuple(e < 0 for e in scalars)

    def build(w: int) -> ChainSchedule:
        if signed and w > 1:
            rows = [wnaf_digits(e, w) for e in mags]
            table_max = max(
                [1] + [max((abs(d) for d in r), default=0) for r in rows]
            )
            return ChainSchedule(
                _merge_digit_columns(rows), len(mags), table_max, True, negate
            )
        # unsigned fixed window (w=1: binary)
        rows = []
        for e in mags:
            r = []
            while True:
                r.append(e & ((1 << w) - 1))
                e >>= w
                if not e:
                    break
            rows.append(r)
        table_max = max(max(r) for r in rows)
        segs = _merge_digit_columns(rows)
        # each unsigned-window column step costs w doublings, not 1
        segs = [(r * w, col) for r, col in segs]
        # the leading segment initializes from the table (no doublings)
        segs[0] = (0, segs[0][1])
        return ChainSchedule(segs, len(mags), table_max, False, negate)

    candidates = [build(w) for w in ((window,) if window else range(1, 7))]
    return min(candidates, key=_schedule_cost)


# --------------------------------------------------------------------------------------
# Executors
# --------------------------------------------------------------------------------------


def _segment_arrays(schedule: ChainSchedule):
    runs = jnp.asarray([r for r, _ in schedule.segments], dtype=jnp.int32)
    idx = jnp.asarray(
        [[schedule.slot_index(d) for d in col] for _, col in schedule.segments],
        dtype=jnp.int32,
    )
    sign = jnp.asarray(
        [[d < 0 for d in col] for _, col in schedule.segments], dtype=bool
    )
    return runs, idx, sign


def run_point_chains(k: int, points, schedule: ChainSchedule):
    """Execute a compiled schedule on stacked points [C, *batch, 3k, 25]
    (C = schedule.n_chains); returns the per-chain products, same shape.
    One joint odd-multiple table, one lax.scan — every point_dbl/point_add
    dispatch covers all C chains."""
    from . import curve

    assert points.shape[0] == schedule.n_chains
    inf = jnp.broadcast_to(curve.inf_point(k), points.shape)
    # derive from `points` so the scan carry's device-varying type matches
    # under shard_map (see curve.scale_bits)
    inf = points * jnp.uint64(0) + inf
    slots = schedule.table_slots()
    entries = {0: inf, 1: points}
    if schedule.signed:
        step2 = curve.point_dbl(k, points) if schedule.table_max > 1 else None
        for s in slots[2:]:
            entries[s] = curve.point_add(k, entries[s - 2], step2)
    else:
        for s in slots[2:]:
            entries[s] = curve.point_add(k, entries[s - 1], points)
    table = jnp.stack([entries[s] for s in slots], axis=0)  # [S, C, *batch, ..]

    runs, idx, sign = _segment_arrays(schedule)
    bshape = points.shape[1:-2]

    def gather(i, s):
        ii = i.reshape((1,) + i.shape + (1,) * (len(bshape) + 2))
        ent = jnp.take_along_axis(table, ii, axis=0)[0]
        neg = curve.point_neg(k, ent)
        return curve.point_select(
            jnp.broadcast_to(
                s.reshape(s.shape + (1,) * len(bshape)), ent.shape[:-2]
            ),
            neg,
            ent,
        )

    def seg_body(acc, xs):
        run, i, s = xs
        acc = jax.lax.fori_loop(
            0, run, lambda _, a: curve.point_dbl(k, a), acc
        )
        return curve.point_add(k, acc, gather(i, s)), None

    # leading segment (run = 0) initializes the accumulator from the table
    (_, i0, s0) = (schedule.segments[0][0], idx[0], sign[0])
    acc = gather(i0, s0)
    acc, _ = jax.lax.scan(seg_body, acc, (runs[1:], idx[1:], sign[1:]))
    if any(schedule.negate):
        negm = jnp.asarray(schedule.negate).reshape(
            (schedule.n_chains,) + (1,) * len(bshape)
        )
        acc = curve.point_select(
            jnp.broadcast_to(negm, acc.shape[:-2]),
            curve.point_neg(k, acc),
            acc,
        )
    return acc


def scale_fixed_chain(k: int, point, e: int, window: int | None = None):
    """Single-chain convenience: [e] * point via the plan compiler (the
    curve.scale_fixed replacement). Handles e < 0 and e == 0."""
    if e == 0:
        from . import curve

        return jnp.broadcast_to(curve.inf_point(k), point.shape)
    return run_point_chains(k, point[None], compile_chains((e,), window))[0]


def run_field_chains(
    schedule: ChainSchedule,
    bases,
    sqr_fn,
    mul_fn,
    one_arr,
    mul_many_fn=None,
):
    """Execute an (unsigned) schedule in a multiplicative group.

    bases: [C, *batch, k, 25] stacked chain bases; returns per-chain powers
    [C, *batch, k, 25]. sqr_fn/mul_fn operate on stacked arrays and may run
    at lazy interior bounds — callers normalize the result. The table is
    built with a log-depth ladder: level d computes entries 2^(d-1)+1 .. 2^d
    as ONE stacked multiply (mul_many_fn(x, y) defaults to mul_fn)."""
    assert not schedule.signed and not any(schedule.negate)
    mul_many_fn = mul_many_fn or mul_fn
    slots = schedule.table_slots()
    n_slots = len(slots)
    one = jnp.broadcast_to(one_arr, bases.shape) + bases * jnp.uint64(0)
    entries = [one, bases]
    while len(entries) < n_slots:
        # T_j = base^j built 0..L-1; extend with T_{L-1} * T_{1..take} — one
        # stacked multiply doubles the table per level (log-depth build)
        take = min(len(entries) - 1, n_slots - len(entries))
        lhs = jnp.broadcast_to(
            entries[-1][None], (take,) + entries[-1].shape
        )
        rhs = jnp.stack(entries[1 : take + 1], axis=0)
        prod = mul_many_fn(lhs, rhs)
        for j in range(take):
            entries.append(prod[j])
    table = jnp.stack(entries, axis=0)  # [S, C, *batch, k, 25]

    runs, idx, _ = _segment_arrays(schedule)
    bshape = bases.shape[1:-2]

    def gather(i):
        ii = i.reshape((1,) + i.shape + (1,) * (len(bshape) + 2))
        return jnp.take_along_axis(table, ii, axis=0)[0]

    def seg_body(acc, xs):
        run, i = xs
        acc = jax.lax.fori_loop(0, run, lambda _, a: sqr_fn(a), acc)
        return mul_fn(acc, gather(i)), None

    acc = gather(idx[0])
    acc, _ = jax.lax.scan(seg_body, acc, (runs[1:], idx[1:]))
    return acc
