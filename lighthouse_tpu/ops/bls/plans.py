"""Lane-plan compiler: flattens tower algebra into lincomb -> conv -> lincomb -> fold.

A multiplication in Fq2/Fq6/Fq12 is a bilinear map. Karatsuba decomposes it into L
independent base-field products whose operands are small integer linear combinations
of the input coefficients, and whose outputs recombine linearly. This module derives
those linear maps **symbolically at import time** and materializes a tower op as:

    A = lincomb(a)            # [..., L, 25]   (flat adds/subs, no carries)
    B = lincomb(b)
    T = fq._conv_product(A,B) # [..., L, 50]   unreduced accumulators
    out = wide-lincomb(T)     # [..., k, 51]   output map on UNREDUCED limbs
    out = fq.reduce_limbs(out)# congruence-fold reduction, ONE per output row

The output linear maps commute with modular reduction, so recombination happens on
the raw convolution accumulators and only the k output rows are reduced — an Fq12
multiply reduces 12 rows, not its 54 Karatsuba lanes. Reduction itself is the
fold pipeline in fq.py (no sequential Montgomery REDC, two trivial carry scans).

Why one wide kernel: emitting each base-field multiply as its own XLA op cost ~1s
of compile *per instance*; one stacked kernel compiles once and feeds the VPU a
[L * batch]-lane workload.

Subtraction never goes negative: a - b is computed as a + (C - b) where C is a
borrow-inflated multiple of p (every limb of C >= the static per-limb bound of b).
Static bounds (value in units of p, per-limb magnitude) are tracked through every
linear combination and asserted against the lazy operand budget
(value < 1200p, limbs < 2^22 — see fq.py docstring) at plan-build time.

Element layout (little-endian coefficient order, flat over the tower):
    fq2  = [..., 2, 25]   (c0, c1)
    fq6  = [..., 6, 25]   (a0.c0, a0.c1, a1.c0, a1.c1, a2.c0, a2.c1)
    fq12 = [..., 12, 25]  (b0 fq6 | b1 fq6)
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import fq
from ..bls_oracle.fields import P

# --------------------------------------------------------------------------------------
# Static bounds for public elements (enforced by carry_norm after every op)
# --------------------------------------------------------------------------------------

PUB_VALUE_P = 16          # public elements have value < 16 p
PUB_LIMB = fq.PUB_LIMB_TARGET  # ... and 17-bit limbs (limbs 0..23); exact
                          # 16-bit normalization only at comparison sites
PUB_TOP_LIMB = 2          # ... limb 24 <= 2 (value < 16p refines it)

# Lazy operand budget — the SAME constants fq.py's conv pipeline assumes
# (fq._IN_VALUE / fq._IN_LIMB); single source of truth in fq.py.
MAX_VALUE_P = 1200
assert MAX_VALUE_P * P == fq._IN_VALUE
MAX_LIMB = fq._IN_LIMB + 1  # strict bound: limbs < 2^22


class LC:
    """Integer linear combination over a basis (dict idx -> coeff)."""

    __slots__ = ("d",)

    def __init__(self, d=None):
        self.d = {k: v for k, v in (d or {}).items() if v}

    @staticmethod
    def basis(i):
        return LC({i: 1})

    def __add__(self, o):
        d = dict(self.d)
        for k, v in o.d.items():
            d[k] = d.get(k, 0) + v
        return LC(d)

    def __sub__(self, o):
        d = dict(self.d)
        for k, v in o.d.items():
            d[k] = d.get(k, 0) - v
        return LC(d)

    def __neg__(self):
        return LC({k: -v for k, v in self.d.items()})

    def scale(self, k: int):
        return LC({i: v * k for i, v in self.d.items()})

    def __repr__(self):
        return f"LC({self.d})"


# fq2 as [LC, LC]; fq6 as list of 6 LC; fq12 as list of 12 LC.

def v2_add(x, y):
    return [x[0] + y[0], x[1] + y[1]]


def v2_sub(x, y):
    return [x[0] - y[0], x[1] - y[1]]


def v2_nr(x):
    """Multiply by (u+1)."""
    return [x[0] - x[1], x[0] + x[1]]


def v2_neg(x):
    return [-x[0], -x[1]]


def v2_conj(x):
    return [x[0], -x[1]]


def v6_add(x, y):
    return [a + b for a, b in zip(x, y)]


def v6_sub(x, y):
    return [a - b for a, b in zip(x, y)]


def v6_nr(x):
    """Multiply by v: (c0, c1, c2) -> (nr(c2), c0, c1)."""
    return v2_nr(x[4:6]) + x[0:4]


def vbasis(n, off=0):
    return [LC.basis(off + i) for i in range(n)]


# --------------------------------------------------------------------------------------
# Plan builder
# --------------------------------------------------------------------------------------

class Plan:
    """a_rows/b_rows: LCs over the A/B input coefficient bases (B may reference a
    constant pool via indices >= n_b). out_rows: LCs over the lane basis."""

    def __init__(self, n_a: int, n_b: int, consts=None):
        self.n_a = n_a
        self.n_b = n_b
        self.consts = consts or []  # list of Python ints (plain residues)
        self.a_rows: list[LC] = []
        self.b_rows: list[LC] = []
        self.out_rows: list[LC] = []

    def lane(self, va: LC, vb: LC) -> LC:
        self.a_rows.append(va)
        self.b_rows.append(vb)
        return LC.basis(len(self.a_rows) - 1)

    @staticmethod
    def inp(i: int) -> LC:
        """Reference input coefficient i inside an out_row (input pass-through).
        Encoded as negative basis index; execute() remaps onto [lanes | a]."""
        return LC.basis(-(i + 1))

    def mul2(self, x, y):
        """3-lane Karatsuba Fq2 product; returns fq2 over lanes."""
        l0 = self.lane(x[0], y[0])
        l1 = self.lane(x[1], y[1])
        l2 = self.lane(x[0] + x[1], y[0] + y[1])
        return [l0 - l1, l2 - l0 - l1]

    def sqr2(self, x):
        """2-lane Fq2 square (same operand on both sides)."""
        l0 = self.lane(x[0] + x[1], x[0] - x[1])
        l1 = self.lane(x[0], x[1])
        return [l0, l1 + l1]

    def mul6(self, x, y):
        x0, x1, x2 = x[0:2], x[2:4], x[4:6]
        y0, y1, y2 = y[0:2], y[2:4], y[4:6]
        t0 = self.mul2(x0, y0)
        t1 = self.mul2(x1, y1)
        t2 = self.mul2(x2, y2)
        t12 = self.mul2(v2_add(x1, x2), v2_add(y1, y2))
        t01 = self.mul2(v2_add(x0, x1), v2_add(y0, y1))
        t02 = self.mul2(v2_add(x0, x2), v2_add(y0, y2))
        c0 = v2_add(v2_nr(v2_sub(v2_sub(t12, t1), t2)), t0)
        c1 = v2_add(v2_sub(v2_sub(t01, t0), t1), v2_nr(t2))
        c2 = v2_add(v2_sub(v2_sub(t02, t0), t2), t1)
        return c0 + c1 + c2

    def mul12(self, x, y):
        x0, x1 = x[0:6], x[6:12]
        y0, y1 = y[0:6], y[6:12]
        t0 = self.mul6(x0, y0)
        t1 = self.mul6(x1, y1)
        t2 = self.mul6(v6_add(x0, x1), v6_add(y0, y1))
        c0 = v6_add(t0, v6_nr(t1))
        c1 = v6_sub(v6_sub(t2, t0), t1)
        return c0 + c1


# --------------------------------------------------------------------------------------
# Borrow-inflated subtraction constants
# --------------------------------------------------------------------------------------

_SUBC_CACHE: dict[tuple[int, int], tuple[np.ndarray, int]] = {}


def _subc(limb_cover: int, top_cover: int):
    """A constant C = K*p whose borrow-inflated limb representation has every limb
    0..23 >= limb_cover and limb 24 >= top_cover (so C - x never underflows per
    limb for x within those bounds). Returns (limbs uint64[25], K)."""
    key = (limb_cover, top_cover)
    if key in _SUBC_CACHE:
        return _SUBC_CACHE[key]
    # borrow m from each limb into the one below: limbs 1..23 gain m*2^16 - m
    m = max(-(-limb_cover // ((1 << 16) - 1)), 1)
    K = 1
    while True:
        if (K * P).bit_length() > 400:
            raise AssertionError("subc constant exceeds 25 limbs")
        c = [int(v) for v in fq.int_to_limbs(K * P)]
        for i in range(1, 25):
            c[i - 1] += m << 16
            c[i] -= m
        if (
            all(v >= 0 for v in c)
            and all(c[i] >= limb_cover for i in range(24))
            and c[24] >= top_cover
        ):
            assert sum(v << (16 * i) for i, v in enumerate(c)) == K * P
            arr = np.array(c, dtype=np.uint64)
            _SUBC_CACHE[key] = (arr, K)
            return arr, K
        K += 1


# --------------------------------------------------------------------------------------
# Materializer
# --------------------------------------------------------------------------------------

class _Bound:
    """Static (value_p, limb, top_limb) bound triple with exact algebra: bounds
    compose through lazy adds/subs so every borrow-inflated constant provably
    dominates its subtrahend limb-by-limb."""

    __slots__ = ("value_p", "limb", "top")

    def __init__(self, value_p, limb, top):
        self.value_p = value_p
        self.limb = limb
        self.top = top

    def __add__(self, o: "_Bound") -> "_Bound":
        return _Bound(self.value_p + o.value_p, self.limb + o.limb, self.top + o.top)

    def __or__(self, o: "_Bound") -> "_Bound":
        """Elementwise max (either-of)."""
        return _Bound(
            max(self.value_p, o.value_p), max(self.limb, o.limb), max(self.top, o.top)
        )

    def scaled(self, k: int) -> "_Bound":
        return _Bound(self.value_p * k, self.limb * k, self.top * k)


def sub_bound(minuend: "_Bound", subtrahend: "_Bound") -> "_Bound":
    """Bound of minuend + (C - subtrahend) for the _subc constant that covers
    the subtrahend."""
    sc, K = _subc(subtrahend.limb, subtrahend.top)
    return _Bound(
        minuend.value_p + K,
        minuend.limb + int(max(sc[:24])),
        minuend.top + int(sc[24]),
    )


PUB_BOUND = _Bound(PUB_VALUE_P, PUB_LIMB, PUB_TOP_LIMB)
CANON_BOUND = _Bound(1, (1 << 16) - 1, 0)  # canonical values are exact 16-bit
# Lazy chain-interior bound, DERIVED from fq.py's named constants (the
# derivation — why 20-bit limbs / 64p re-enter the conv budget on every
# backend — lives in one place, next to fq.CHAIN_LIMB_TARGET). A fixed
# point of chain steps: outputs at this bound feed the next step's lincombs
# within the lazy budget, skipping the tail of the reduction walk (see
# fq.reduce_limbs). PUB_BOUND inputs are below it, so chains start from
# public values without renormalization.
CHAIN_BOUND = _Bound(
    fq.CHAIN_VALUE_P, fq.CHAIN_LIMB_TARGET, fq.chain_top_limb()
)
# Lazy fq12-interior bound for the pairing chains (Miller accumulator, the
# final exponentiation's cyclotomic runs). CHAIN_BOUND's 20-bit limbs are too
# wide here: the fq12/fq6 plans' input lincombs sum up to ~4 coefficient
# magnitudes plus a borrow-inflated constant, so 2^20-limb inputs would
# overflow the 2^22 conv-input budget. 18-bit limbs at the same 64p value
# compose through every fq12-level lincomb within budget (asserted per plan
# at build time, certified by analysis/bounds.py) while still trimming the
# tail of the reduction walk versus PUB_BOUND (value 64p vs 13p, limbs 2^18
# vs 2^17). The top-limb bound is the same derivation as chain_top_limb():
# limbs are non-negative, so limb 24 <= value >> 384.
F12_BOUND = _Bound(
    fq.CHAIN_VALUE_P,
    (1 << 18) - 1,
    min((1 << 18) - 1, fq.CHAIN_VALUE_LIMIT >> (16 * 24)),
)
assert F12_BOUND.limb <= fq.CHAIN_LIMB_TARGET <= fq._IN_LIMB


def f12_interior():
    """(in/out bound, out_bound kwarg) for fq12 chain interiors, by backend.

    On the digits backend the conv accumulator bound is set by the base-2^8
    digit split (~2^32.6) regardless of input limb width, so running chain
    interiors at F12_BOUND is free on the way in and trims the walk tail on
    the way out. On the f64 backend the accumulator bound grows with the
    input limbs (25 * limb^2): F12_BOUND's extra input bit costs MORE fold
    rounds than its looser target saves (measured ~15% slower per fq12 op),
    so interiors stay at PUB_BOUND and the walk kwarg stays default.

    The "pallas" backend shares the digit-split property (its in-kernel conv
    accumulator bound comes from the base-2^8 digit split, not the input
    limb width), so it takes the digits arm."""
    if fq.conv_backend() in ("digits", "pallas"):
        return F12_BOUND, F12_BOUND
    return PUB_BOUND, None


def _lincomb_bounds(rows: list[LC], bound_for, name: str):
    """Static bound walk of a lincomb: per-row (value_p, limb, top) plus the
    per-row borrow constant covering its negative part. Returns
    (neg_consts [n_rows, 25] uint64, worst _Bound)."""
    consts = np.zeros((len(rows), fq.NLIMBS), dtype=np.uint64)
    worst = _Bound(0, 0, 0)
    for r, lc in enumerate(rows):
        value_p = limb = top = 0
        n_limb = n_top = 0
        any_neg = False
        for idx, c in sorted(lc.d.items()):
            b = bound_for(idx)
            mag = abs(c)
            if c > 0:
                value_p += mag * b.value_p
                limb += mag * b.limb
                top += mag * b.top
            else:
                any_neg = True
                n_limb += mag * b.limb
                n_top += mag * b.top
        if any_neg:
            subc, K = _subc(n_limb, n_top)
            consts[r] = subc
            value_p += K
            limb += int(max(subc[:24]))
            top += int(subc[24])
        assert fq._cert(
            "lincomb_value_budget", value_p, MAX_VALUE_P - 1, note=name
        ), f"{name}: value bound {value_p}p exceeds budget"
        assert fq._cert(
            "lincomb_limb_budget", limb, MAX_LIMB - 1, note=name
        ), f"{name}: limb bound {limb} exceeds 2^22"
        worst.value_p = max(worst.value_p, value_p)
        worst.limb = max(worst.limb, limb)
        worst.top = max(worst.top, top)
    return consts, worst


def _lincomb_matrices(rows: list[LC], n_in: int):
    """Split the integer row matrix into positive / negative-magnitude halves
    (M_pos - M_neg). uint64 so the dot stays in the limb dtype."""
    m_pos = np.zeros((len(rows), n_in), dtype=np.uint64)
    m_neg = np.zeros((len(rows), n_in), dtype=np.uint64)
    for r, lc in enumerate(rows):
        for idx, c in lc.d.items():
            if c > 0:
                m_pos[r, idx] = c
            else:
                m_neg[r, idx] = -c
    return m_pos, m_neg


def _apply_matrices(m_pos, m_neg, consts, x):
    """rows @ x as two constant-matrix dot_generals plus the borrow constants:
    out[..., r, :] = (M_pos @ x) + (C_r - M_neg @ x). The dot form emits ~5 HLO
    ops per lincomb where the term-by-term form emitted hundreds (slice +
    scale + add per coefficient) — program size was the r3 compile bottleneck.

    Dtype follows x: an f64 operand gets f64 matrices/constants (exact — every
    bound is asserted < 2^53 by the callers), keeping the pipeline off u64
    multiplies, which have no SIMD path on CPU."""
    f64 = x.dtype == jnp.float64
    dt = jnp.float64 if f64 else jnp.uint64
    dn = (((1,), (x.ndim - 2,)), ((), ()))
    pos = jax.lax.dot_general(
        jnp.asarray(m_pos, dtype=dt), x, dn, preferred_element_type=dt
    )
    pos = jnp.moveaxis(pos, 0, -2)
    if not m_neg.any():
        return pos
    neg = jax.lax.dot_general(
        jnp.asarray(m_neg, dtype=dt), x, dn, preferred_element_type=dt
    )
    neg = jnp.moveaxis(neg, 0, -2)
    return pos + (jnp.asarray(consts, dtype=dt) - neg)


def lincomb(rows: list[LC], x, in_bound: _Bound, name: str = "", bound_for=None) -> tuple:
    """Materialize rows of linear combinations of x[..., n, 25]. Returns
    (stacked [..., L, 25], out_bound). ``bound_for(idx)`` optionally gives a
    per-index input bound (default: in_bound for all indices)."""
    bound_for = bound_for or (lambda _i: in_bound)
    consts, worst = _lincomb_bounds(rows, bound_for, name)
    m_pos, m_neg = _lincomb_matrices(rows, x.shape[-2])
    return _apply_matrices(m_pos, m_neg, consts, x), worst


def append_const_pool(plan: Plan, b):
    """Concatenate the plan's constant pool onto the B operand — the pool
    append ORDER defines what plan.b_rows indices >= n_b mean, so both
    executors (the XLA path below and pallas_kernels.execute_plan) must go
    through this one helper."""
    if not plan.consts:
        return b
    cpool = jnp.asarray(np.stack([fq.int_to_limbs(c) for c in plan.consts]))
    cpool = jnp.broadcast_to(cpool, b.shape[:-2] + cpool.shape)
    return jnp.concatenate([b, cpool.astype(b.dtype)], axis=-2)


def remap_passthrough_rows(plan: Plan, n_lanes: int) -> list[LC]:
    """Out rows with Plan.inp() pass-through references remapped onto the
    [lanes | a] concatenated basis (negative index -(i+1) -> n_lanes + i).
    The addressing convention is shared by both executors — one definition."""
    return [
        LC({(i if i >= 0 else n_lanes - 1 - i): c for i, c in lc.d.items()})
        for lc in plan.out_rows
    ]


# Raw (non-domain) limbs of 2^384 mod p: folds limb-24 excess back below 2^384.
# Works on Montgomery-coded values too — the fold is a congruence on the coded value.
_RT384 = jnp.asarray(fq.int_to_limbs((1 << 384) % P))


def _verify_carry_norm_schedule(n_folds: int) -> None:
    """Import-time proof that the carry_norm schedule lands on PUB_BOUND for
    ANY input within the lazy budget (limbs < 2^22, value < 1200p): walk the
    per-limb/value bounds through each round+fold with exact integers."""
    limbs = [MAX_LIMB - 1] * fq.NLIMBS
    value = MAX_VALUE_P * P
    rt = [int(v) for v in fq._RT384_NP]
    rt_val = fq._RT384_VAL
    for _ in range(n_folds):
        # carry-save round (width-preserving; value invariant)
        carried = [0] + [b >> 16 for b in limbs[:-1]]
        limbs = [min(b, 0xFFFF) + c for b, c in zip(limbs, carried)]
        limbs = [min(b, value >> (16 * i)) for i, b in enumerate(limbs)]
        # fold the 2^384 excess: new value <= (value below 2^384) + top * rt_val
        top = limbs[24]
        assert fq._cert(
            "carry_norm_fold_nowrap",
            top * max(rt) + max(limbs[:24]),
            (1 << 64) - 1,
            note="carry_norm",
        )
        lo_val = sum(b << (16 * i) for i, b in enumerate(limbs[:24]))
        value = min(lo_val, value) + top * rt_val
        limbs = [b + top * rt[i] for i, b in enumerate(limbs[:24])] + [
            top * rt[24]
        ]
        limbs = [min(b, value >> (16 * i)) for i, b in enumerate(limbs)]
    # final round
    carried = [0] + [b >> 16 for b in limbs[:-1]]
    limbs = [min(b, 0xFFFF) + c for b, c in zip(limbs, carried)]
    limbs = [min(b, value >> (16 * i)) for i, b in enumerate(limbs)]
    assert fq._cert(
        "carry_norm_value", value, PUB_VALUE_P * P - 1, note="carry_norm"
    ), f"carry_norm value bound {value / P}p"
    assert fq._cert(
        "carry_norm_limb", max(limbs), PUB_LIMB, note="carry_norm"
    ), f"carry_norm limb bound {max(limbs):#x}"
    assert fq._cert(
        "carry_norm_top_limb", limbs[24], PUB_TOP_LIMB, note="carry_norm"
    )


_CARRY_NORM_FOLDS = 3
_verify_carry_norm_schedule(_CARRY_NORM_FOLDS)


def carry_norm(x):
    """Restore public bounds (value < 16p, 17-bit limbs, top limb <= 2) for any
    input within the lazy budget: alternate width-preserving carry-save rounds
    with folds of the 2^384-and-up excess through (2^384 mod p). The schedule
    is proved at import time by _verify_carry_norm_schedule — and it is fully
    elementwise (~25 HLO ops), where the previous exact-walk version cost
    three lax.scans per call site."""
    for _ in range(_CARRY_NORM_FOLDS):
        x = fq._carry_rounds(x, 1)
        top = x[..., 24]
        x = x * fq._MASK_NO24 + top[..., None] * _RT384
    return fq._carry_rounds(x, 1)


_SUBC_WIDE_CACHE: dict[tuple[int, int], np.ndarray] = {}


def _subc_wide(n_limbs: int, cover: int) -> np.ndarray:
    """A constant == 0 mod p in n_limbs-limb space with every limb >= cover
    (subtraction cover for unreduced convolution accumulators)."""
    key = (n_limbs, cover)
    if key not in _SUBC_WIDE_CACHE:
        c = [cover] * n_limbs
        adj = (-sum(v << (16 * i) for i, v in enumerate(c))) % P
        for i in range(fq.NLIMBS):
            c[i] += (adj >> (16 * i)) & 0xFFFF
        assert sum(v << (16 * i) for i, v in enumerate(c)) % P == 0
        assert max(c) < 1 << 63
        _SUBC_WIDE_CACHE[key] = np.array(c, dtype=np.uint64)
    return _SUBC_WIDE_CACHE[key]


def execute(
    plan: Plan, a, b, in_bound_a=PUB_BOUND, in_bound_b=PUB_BOUND, name="",
    out_bound: "_Bound | None" = None,
):
    """Run a plan: returns [..., n_out, 25] public-bounded output.

    The output linear maps commute with reduction, so they run on the
    *unreduced* convolution accumulators: conv -> out-lincomb (wide limbs) ->
    ONE congruence-fold reduction per OUTPUT row. An Fq12 multiply reduces 12
    rows instead of its 54 Karatsuba lanes, and the fold reduction already
    lands on plans.PUB_BOUND — no trailing carry_norm.

    ``out_bound=CHAIN_BOUND`` requests the lazier chain-interior target
    instead (shorter reduction walk; see fq.reduce_limbs) — used by
    chain_plans for the interiors of fixed-exponent scans.

    On the f64 conv backend (CPU), at row counts where the f64 walk wins
    (fq.F64_WALK_MIN_ROWS), the ENTIRE pipeline — input lincombs,
    convolution, out-lincomb, reduction walk — runs in f64 and only the
    final reduced limbs are cast back to u64: u64 multiplies have no x86
    SIMD path and dominated the execute cost. Exactness: every intermediate
    bound is asserted below the 2^53 f64 integer cap.

    On the "pallas" backend the pipeline after the input lincombs — conv,
    out-lincomb, fold, carry — runs as ONE fused Pallas kernel
    (pallas_kernels.execute_plan); bounds are tracked in digit space there."""
    if fq.conv_backend() == "pallas":
        from . import pallas_kernels

        return pallas_kernels.execute_plan(
            plan, a, b, in_bound_a, in_bound_b, name, out_bound
        )
    lane_rows = fq._static_rows(a[..., 0, :]) * len(plan.a_rows)
    if fq.conv_backend() == "f64" and lane_rows >= fq.F64_WALK_MIN_ROWS:
        a = a.astype(jnp.float64)
        b = b.astype(jnp.float64)
    A, ba = lincomb(plan.a_rows, a, in_bound_a, name + ".A")
    b = append_const_pool(plan, b)
    B, bb = lincomb(plan.b_rows, b, in_bound_b, name + ".B")
    T = fq._conv_product_keep(A, B)  # [..., L, 50] unreduced accumulators
    conv_limb = max(fq.conv_limb_bounds(ba.limb, bb.limb))
    cap = fq._cap_of(T)
    assert fq._cert(
        "execute_conv_acc", conv_limb, (1 << 63) - 1, note=name
    ), f"{name}: conv accumulator overflow"
    # a carry round caps limbs (~2^33) so out-row accumulation and
    # subtraction covers stay inside the dtype cap (f64: 2^53) — SKIPPED
    # when the raw conv bounds already fit (common for lazy chain interiors,
    # whose tighter inputs leave headroom): a row's accumulator is at most
    # sum(|coeff|) * lane_limb for the positive part plus a borrow constant
    # that itself covers the negative part, so 2x the full coefficient sum
    # dominates both
    coeff_sum = max(
        (sum(abs(c) for c in lc.d.values()) for lc in plan.out_rows),
        default=1,
    )
    if 2 * coeff_sum * conv_limb + (1 << 20) < cap:
        lane_limb = conv_limb
    else:
        T = fq._carry_round_array(T)  # [..., L, 51]
        lane_limb = (1 << 16) + (conv_limb >> 16)
    n_wide = T.shape[-1]
    L = len(plan.a_rows)
    has_passthrough = any(i < 0 for lc in plan.out_rows for i in lc.d)
    if has_passthrough:
        # pass-through rows reference `a`: zero-pad it into the wide space
        pad = [(0, 0)] * (a.ndim - 1) + [(0, n_wide - a.shape[-1])]
        T = jnp.concatenate([T, jnp.pad(a, pad).astype(T.dtype)], axis=-2)
        out_rows = remap_passthrough_rows(plan, L)
    else:
        out_rows = plan.out_rows
    worst_limb = 0
    consts = np.zeros((len(out_rows), n_wide), dtype=np.uint64)
    for r, lc in enumerate(out_rows):
        limb = n_limb = 0
        any_neg = False
        for idx, c in sorted(lc.d.items()):
            lb = lane_limb if idx < L else in_bound_a.limb
            mag = abs(c)
            if c > 0:
                limb += mag * lb
            else:
                any_neg = True
                n_limb += mag * lb
        if any_neg:
            subc = _subc_wide(n_wide, n_limb)
            consts[r] = subc
            limb += int(subc.max())
        assert fq._cert(
            "execute_wide_acc", limb, cap - 1, note=name
        ), f"{name}: wide accumulator bound 2^{limb.bit_length()}"
        worst_limb = max(worst_limb, limb)
    m_pos, m_neg = _lincomb_matrices(out_rows, T.shape[-2])
    out = _apply_matrices(m_pos, m_neg, consts, T)
    value_bound = sum(worst_limb << (16 * i) for i in range(n_wide))
    if out_bound is None:
        return fq.reduce_limbs(out, [worst_limb] * n_wide, value_bound)
    # the declared top-limb bound must dominate what the walk guarantees
    assert fq._cert(
        "out_bound_top_sound",
        min(out_bound.limb, (out_bound.value_p * P) >> (16 * 24)),
        out_bound.top,
        note=name,
    ), "out_bound.top unsound for its value/limb bounds"
    return fq.reduce_limbs(
        out,
        [worst_limb] * n_wide,
        value_bound,
        out_bound.value_p * P,
        out_bound.limb,
    )


# --------------------------------------------------------------------------------------
# Prebuilt plans
# --------------------------------------------------------------------------------------

def _build_mul(k: int) -> Plan:
    p = Plan(k, k)
    x, y = vbasis(k), vbasis(k)
    if k == 2:
        p.out_rows = p.mul2(x, y)
    elif k == 6:
        p.out_rows = p.mul6(x, y)
    elif k == 12:
        p.out_rows = p.mul12(x, y)
    return p


MUL2 = _build_mul(2)
MUL6 = _build_mul(6)
MUL12 = _build_mul(12)


def _build_sqr2() -> Plan:
    p = Plan(2, 2)
    x = vbasis(2)
    p.out_rows = p.sqr2(x)
    # sqr plans put the same element on both sides; b_rows reference the A basis
    return p


SQR2 = _build_sqr2()


def _build_sqr12() -> Plan:
    """fq12 square via 2 fq6 products: t = a0*a1; s = (a0+a1)(a0 + nr(a1));
    c0 = s - t - nr(t); c1 = 2t."""
    p = Plan(12, 12)
    x = vbasis(12)
    a0, a1 = x[0:6], x[6:12]
    t = p.mul6(a0, a1)
    s = p.mul6(v6_add(a0, a1), v6_add(a0, v6_nr(a1)))
    c0 = v6_sub(v6_sub(s, t), v6_nr(t))
    c1 = v6_add(t, t)
    p.out_rows = c0 + c1
    return p


SQR12 = _build_sqr12()


def _build_cyc_sqr() -> Plan:
    """Granger-Scott cyclotomic square: 9 Fq2 squares (18 lanes) + linear glue."""
    p = Plan(12, 12)
    x = vbasis(12)
    # coefficient layout: fq12 = (c0=(z0,z4,z3), c1=(z2,z1,z5)) in fq2 slots
    z0, z4, z3 = x[0:2], x[2:4], x[4:6]
    z2, z1, z5 = x[6:8], x[8:10], x[10:12]
    # out-row references to the inputs use pass-through indices
    iz0, iz4, iz3 = [p.inp(0), p.inp(1)], [p.inp(2), p.inp(3)], [p.inp(4), p.inp(5)]
    iz2, iz1, iz5 = [p.inp(6), p.inp(7)], [p.inp(8), p.inp(9)], [p.inp(10), p.inp(11)]
    sq = {}
    for nm, (u, v) in {"a": (z0, z1), "b": (z2, z3), "c": (z4, z5)}.items():
        sq[nm + "0"] = p.sqr2(u)
        sq[nm + "1"] = p.sqr2(v)
        sq[nm + "x"] = p.sqr2(v2_add(u, v))

    def fq4(nm):
        t0, t1, txy = sq[nm + "0"], sq[nm + "1"], sq[nm + "x"]
        return (
            v2_add(v2_nr(t1), t0),
            v2_sub(v2_sub(txy, t0), t1),
        )

    t0, t1 = fq4("a")
    t2, t3 = fq4("b")
    t4, t5 = fq4("c")

    def tri_sub(t, z):
        d = v2_sub(t, z)
        return v2_add(v2_add(d, d), t)

    def tri_add(t, z):
        s = v2_add(t, z)
        return v2_add(v2_add(s, s), t)

    z0n = tri_sub(t0, iz0)
    z1n = tri_add(t1, iz1)
    z2n = tri_add(v2_nr(t5), iz2)
    z3n = tri_sub(t4, iz3)
    z4n = tri_sub(t2, iz4)
    z5n = tri_add(t3, iz5)
    p.out_rows = z0n + z4n + z3n + z2n + z1n + z5n
    return p


CYC_SQR = _build_cyc_sqr()


def _mont(c: int) -> int:
    return c * fq.R_MONT % P


def _build_frob12() -> Plan:
    """Power-1 Frobenius on fq12. Lanes multiply conjugated coefficients by the
    Frobenius constants (constant pool on the B side); z0-conj passes through a
    multiply by one to keep everything in one kernel."""
    from ..bls_oracle import fields as _of

    g6c1, g6c2, g12 = _of._FROB_FQ6_C1_1, _of._FROB_FQ6_C2_1, _of._FROB_FQ12_C1_1
    consts = []

    def cidx(val: int) -> LC:
        v = _mont(val)
        if v not in consts:
            consts.append(v)
        return LC.basis(12 + consts.index(v))

    p = Plan(12, 12)
    x = vbasis(12)

    def fq6_frob(sl, extra: "_of.Fq2 | None"):
        """Frobenius of an fq6 slice, optionally followed by * extra (fq12 gamma)."""
        cs = [v2_conj(sl[0:2]), v2_conj(sl[2:4]), v2_conj(sl[4:6])]
        gammas = [_of.Fq2(1, 0), g6c1, g6c2]
        out = []
        for coef, gam in zip(cs, gammas):
            g = gam * extra if extra is not None else gam
            # (c0 + c1 u) * (g0 + g1 u) with g constant:
            g0, g1 = cidx(g.c0), cidx(g.c1)
            l00 = p.lane(coef[0], g0)
            l11 = p.lane(coef[1], g1)
            lx = p.lane(coef[0] + coef[1], g0 + g1)
            out += [l00 - l11, lx - l00 - l11]
        return out

    c0 = fq6_frob(x[0:6], None)
    c1 = fq6_frob(x[6:12], g12)
    p.out_rows = c0 + c1
    p.consts = consts
    return p


FROB12 = _build_frob12()
