"""G2 (E'(Fq2): y^2 = x^3 + 4(u+1)) device kernels.

Instantiation of curve.py with k = 2 plus the psi (untwist-Frobenius-twist)
endomorphism: the fast subgroup check psi(Q) == [x]Q (the check blst performs
for signature group-checks, ``/root/reference/crypto/bls/src/impls/blst.rs:75``)
and, later, fast cofactor clearing for hash-to-curve. Signatures are 96-byte
compressed G2 points (``generic_signature.rs``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import curve, fq, plans, tower
from ..bls_oracle.fields import P, BLS_X, Fq2
from ..bls_oracle import curves as _oc

K = 2

# psi(x, y) = (CX * conj(x), CY * conj(y)) acts as multiplication by x (the BLS
# parameter) on the r-order subgroup; constants derived from the twist
# nonresidue xi = 1 + u and verified against the oracle in tests.
_XI = Fq2(1, 1)
_CX = _XI.pow((P - 1) // 3).inv()
_CY = _XI.pow((P - 1) // 2).inv()

_CX_M = tower.from_ints([_CX.c0, _CX.c1])
_CY_M = tower.from_ints([_CY.c0, _CY.c1])

B2_M = tower.from_ints([4, 4])  # curve constant 4(u+1), Montgomery form


def generator(shape=()):
    g = curve.from_affine(
        K,
        tower.from_ints([_oc.G2_X.c0, _oc.G2_X.c1]),
        tower.from_ints([_oc.G2_Y.c0, _oc.G2_Y.c1]),
    )
    return jnp.broadcast_to(g, shape + (6, fq.NLIMBS)) if shape else g


def add(p, q):
    return curve.point_add(K, p, q)


def dbl(p):
    return curve.point_dbl(K, p)


def neg(p):
    return curve.point_neg(K, p)


def scale_u64(p, scalars):
    return curve.scale_u64(K, p, scalars)


def scale_fixed(p, e: int):
    return curve.scale_fixed(K, p, e)


def psum(pts, valid=None):
    return curve.point_sum(K, pts, valid)


def to_affine(p):
    return curve.to_affine(K, p)


def is_inf(p):
    return curve.is_inf(K, p)


def eq(p, q):
    return curve.point_eq(K, p, q)


def psi(p):
    """Endomorphism on projective coords: (CX conj(X) : CY conj(Y) : conj(Z))."""
    x, y, z = p[..., 0:2, :], p[..., 2:4, :], p[..., 4:6, :]
    conj = lambda a: plans.carry_norm(tower.fq2_conj(a))
    xn = tower.fq2_mul(conj(x), jnp.broadcast_to(_CX_M, x.shape))
    yn = tower.fq2_mul(conj(y), jnp.broadcast_to(_CY_M, y.shape))
    return jnp.concatenate([xn, yn, conj(z)], axis=-2)


def subgroup_check(p):
    """psi(Q) == [x]Q (x = BLS_X < 0). Infinity passes — callers gate it."""
    xq = curve.point_neg(K, scale_fixed(p, -BLS_X))
    return curve.point_eq(K, psi(p), xq)


def on_curve(p):
    """Y^2 Z == X^3 + 4(u+1) Z^3 (infinity passes)."""
    x, y, z = p[..., 0:2, :], p[..., 2:4, :], p[..., 4:6, :]
    y2z = tower.fq2_mul(tower.fq2_sqr(y), z)
    x3 = tower.fq2_mul(tower.fq2_sqr(x), x)
    z3 = tower.fq2_mul(tower.fq2_sqr(z), z)
    rhs = plans.carry_norm(x3 + tower.fq2_mul(z3, jnp.broadcast_to(B2_M, z3.shape)))
    return tower.t_eq(y2z, rhs)


# --------------------------------------------------------------------------------------
# Sign / decompression
# --------------------------------------------------------------------------------------


def lex_sign(y):
    """ZCash G2 sign bit: c1 > (p-1)/2 if c1 != 0 else c0 > (p-1)/2.
    One from_mont canonicalization; the comparator is shared with G1."""
    c = fq.from_mont(y)
    c0, c1 = c[..., 0, :], c[..., 1, :]
    return jnp.where(
        fq.is_zero(c1), fq.lex_gt_half_canon(c0), fq.lex_gt_half_canon(c1)
    )


def decompress(x_mont, s_flag):
    """x_mont [..., 2, 25] Montgomery-form x; s_flag [...]. Returns
    (point [..., 6, 25], ok [...]): ok = x is on curve (y^2 solvable).
    Infinity/flag parsing happens host-side."""
    x = x_mont
    rhs = plans.carry_norm(
        tower.fq2_mul(tower.fq2_sqr(x), x)
        + jnp.broadcast_to(B2_M, x.shape)
    )
    y, ok = tower.fq2_sqrt(rhs)
    flip = lex_sign(y) ^ (s_flag == 1)
    y = plans.carry_norm(tower.t_select(flip, tower.fq2_neg(tower.t_canon(y)), y))
    return curve.from_affine(K, x, y), ok


# --------------------------------------------------------------------------------------
# Host conversions (oracle interop)
# --------------------------------------------------------------------------------------


def from_oracle(p):
    if p is None:
        return curve.inf_point(K)
    return jnp.concatenate(
        [
            tower.from_ints([p[0].c0, p[0].c1]),
            tower.from_ints([p[1].c0, p[1].c1]),
            tower.one(2),
        ],
        axis=0,
    )


def from_oracle_batch(pts):
    return jnp.stack([from_oracle(p) for p in pts])


def to_oracle(p):
    if bool(np.asarray(is_inf(p))):
        return None
    x, y = to_affine(p)
    xi = tower.to_ints(np.asarray(tower.t_canon(x)))
    yi = tower.to_ints(np.asarray(tower.t_canon(y)))
    return (Fq2(*xi), Fq2(*yi))
