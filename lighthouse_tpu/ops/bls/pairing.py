"""Optimal-ate pairing on BLS12-381, compiled through the chain-plan machinery.

The TPU twin of the pairing engine blst provides to the reference's batch
verifier (``/root/reference/crypto/bls/src/impls/blst.rs:37-119``). Since the
BLS parameter |x| = 0xd201000000010000 is a host constant, BOTH pairing stages
are *fixed* schedules, and the whole endgame is compiled the way
``ops/bls/chain_plans.py`` compiles fixed scalars:

  * **Planned Miller loop** (two passes over the trace-time |x| schedule):
    pass 1 iterates ONLY the twist point — each doubling step is a dedicated
    two-level plan pair (CLN homogeneous-projective formulas with every
    linear step folded into the plan lincombs, 21 lanes total, line
    coefficients emitted through pass-through rows) collecting the 63
    doubling + 5 addition line coefficients. All 68 lines are then scaled by
    the G1 coordinates in ONE stacked plan execution, and the 5 addition
    lines are pre-multiplied into their doubling-step partners (sparse 014 x
    014 -> 01245) in one more stacked kernel, so the accumulator pass is a
    uniform run of ``f^2 * line`` folds. Pass 2 walks the accumulator under
    lazy fq12-interior bounds (plans.F12_BOUND: value < 64p, 18-bit limbs —
    the certifier-proved fixed point of fq12 chain steps) and only the loop
    output pays the full public-bound walk.
  * **Planned final exponentiation**: the hard part keeps the x-addition
    chain (its five |x|-exponentiations are data-sequential — each feeds the
    next — and |x|'s weight-6 sparsity makes the per-factor chains optimal),
    but every exponentiation runs as one ``chain_plans`` schedule with lazy
    interiors, and cyclotomic squaring has an opt-in Karabina compressed
    kernel (``tower.fq12_compressed_sqr``, LIGHTHOUSE_PAIRING_KARABINA=1).
  * **Batching**: every op broadcasts over leading axes; a batch of pairings
    is one Miller loop over stacked points, the product is a halving
    fq12_mul tree, and the whole check costs ONE final exponentiation (same
    shape as blst's ``verify_multiple_aggregate_signatures``).

Correctness is pinned against ``ops.bls_oracle.pairing`` (values agree after
final exponentiation; both compute e(P,Q)^3 — the harmless cube of the
x-addition-chain hard part, gcd(3, r) = 1).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import fq, plans, tower
from .plans import LC, PUB_BOUND, F12_BOUND, v2_add, v2_sub, v2_nr, v6_add, v6_sub, v6_nr
from ..bls_oracle.fields import BLS_X

X_ABS = -BLS_X  # 0xd201000000010000

# --------------------------------------------------------------------------------------
# Sparse fold plans
# --------------------------------------------------------------------------------------


def _mul6_sp2(p: plans.Plan, xs, d0, d1):
    """Karatsuba fq6 * (d0, d1, 0) — 5 mul2 lanes."""
    x0, x1, x2 = xs[0:2], xs[2:4], xs[4:6]
    m00 = p.mul2(x0, d0)
    m11 = p.mul2(x1, d1)
    mx = p.mul2(v2_add(x0, x1), v2_add(d0, d1))
    m20 = p.mul2(x2, d0)
    m21 = p.mul2(x2, d1)
    r0 = v2_add(m00, v2_nr(m21))
    r1 = v2_sub(v2_sub(mx, m00), m11)
    r2 = v2_add(m11, m20)
    return r0 + r1 + r2


def _mul6_sp1(p: plans.Plan, xs, d):
    """fq6 * (0, d, 0) = (nr(x2 d), x0 d, x1 d) — 3 mul2 lanes."""
    x0, x1, x2 = xs[0:2], xs[2:4], xs[4:6]
    n0 = p.mul2(x0, d)
    n1 = p.mul2(x1, d)
    n2 = p.mul2(x2, d)
    return v2_nr(n2) + n0 + n1


def _mul6_sp12(p: plans.Plan, xs, d1, d2):
    """Karatsuba fq6 * (0, d1, d2) — 5 mul2 lanes."""
    x0, x1, x2 = xs[0:2], xs[2:4], xs[4:6]
    m01 = p.mul2(x0, d1)
    m02 = p.mul2(x0, d2)
    m11 = p.mul2(x1, d1)
    m22 = p.mul2(x2, d2)
    mx = p.mul2(v2_add(x1, x2), v2_add(d1, d2))
    r0 = v2_nr(v2_sub(v2_sub(mx, m11), m22))
    r1 = v2_add(m01, v2_nr(m22))
    r2 = v2_add(m02, m11)
    return r0 + r1 + r2


def _build_mul_by_014() -> plans.Plan:
    """A-side: full fq12 (12 coeffs). B-side: 6 coeffs [c0 | c1 | c4]."""
    p = plans.Plan(12, 6)
    x = plans.vbasis(12)
    a0, a1 = x[0:6], x[6:12]
    c0 = [LC.basis(0), LC.basis(1)]
    c1 = [LC.basis(2), LC.basis(3)]
    c4 = [LC.basis(4), LC.basis(5)]
    t0 = _mul6_sp2(p, a0, c0, c1)
    t1 = _mul6_sp1(p, a1, c4)
    t2 = _mul6_sp2(p, plans.v6_add(a0, a1), c0, v2_add(c1, c4))
    out0 = plans.v6_add(t0, plans.v6_nr(t1))
    out1 = plans.v6_sub(plans.v6_sub(t2, t0), t1)
    p.out_rows = out0 + out1
    return p


MUL_BY_014 = _build_mul_by_014()


def _build_mul_by_01245() -> plans.Plan:
    """A-side: full fq12. B-side: 10 coeffs [c0|c1|c2|c4|c5] — the product of
    two scaled 014-lines (every fq6 slot except w-slot 0). 51 lanes."""
    p = plans.Plan(12, 10)
    x = plans.vbasis(12)
    a0, a1 = x[0:6], x[6:12]
    b0 = plans.vbasis(6)              # [c0 | c1 | c2]
    d1 = [LC.basis(6), LC.basis(7)]   # c4
    d2 = [LC.basis(8), LC.basis(9)]   # c5
    t0 = p.mul6(a0, b0)
    t1 = _mul6_sp12(p, a1, d1, d2)
    ysum = b0[0:2] + v2_add(b0[2:4], d1) + v2_add(b0[4:6], d2)
    t2 = p.mul6(v6_add(a0, a1), ysum)
    out0 = v6_add(t0, v6_nr(t1))
    out1 = v6_sub(v6_sub(t2, t0), t1)
    p.out_rows = out0 + out1
    return p


MUL_BY_01245 = _build_mul_by_01245()


def _build_sp_sp() -> plans.Plan:
    """Product of two scaled lines: (a0 + a1 v + a4 vw)(b0 + b1 v + b4 vw) ->
    [c0|c1|c2|c4|c5] (slot 3 provably zero). 18 Karatsuba lanes."""
    p = plans.Plan(6, 6)
    x, y = plans.vbasis(6), plans.vbasis(6)
    a0, a1, a4 = x[0:2], x[2:4], x[4:6]
    b0, b1, b4 = y[0:2], y[2:4], y[4:6]
    m00 = p.mul2(a0, b0)
    m11 = p.mul2(a1, b1)
    m44 = p.mul2(a4, b4)
    mx01 = p.mul2(v2_add(a0, a1), v2_add(b0, b1))
    mx04 = p.mul2(v2_add(a0, a4), v2_add(b0, b4))
    mx14 = p.mul2(v2_add(a1, a4), v2_add(b1, b4))
    c0 = v2_add(m00, v2_nr(m44))
    c1 = v2_sub(v2_sub(mx01, m00), m11)
    c2 = m11
    c4 = v2_sub(v2_sub(mx04, m00), m44)
    c5 = v2_sub(v2_sub(mx14, m11), m44)
    p.out_rows = c0 + c1 + c2 + c4 + c5
    return p


SP_SP = _build_sp_sp()


def _build_scale_line() -> plans.Plan:
    """A-side: unscaled line [c0|c1|c2]. B-side: [px|py] (fq coefficients).
    Output [c0 | c1*px | c2*py] — mul_by_014's sparse operand layout. c0
    passes through; 4 lanes."""
    p = plans.Plan(6, 2)
    px, py = LC.basis(0), LC.basis(1)
    l10 = p.lane(LC.basis(2), px)
    l11 = p.lane(LC.basis(3), px)
    l20 = p.lane(LC.basis(4), py)
    l21 = p.lane(LC.basis(5), py)
    p.out_rows = [p.inp(0), p.inp(1), l10, l11, l20, l21]
    return p


SCALE_LINE = _build_scale_line()


def mul_by_014(f, c):
    """f [..., 12, 25] times the sparse element with Fq2 coefficients
    c = [c0 | c1 | c4] [..., 6, 25] at Fq6-slot positions 0, 1, 4."""
    return plans.execute(MUL_BY_014, f, c, PUB_BOUND, PUB_BOUND, "mul014")


def mul_by_01245(f, c):
    """f times the 10-coefficient sparse element [c0|c1|c2|c4|c5] (a product
    of two lines)."""
    return plans.execute(MUL_BY_01245, f, c, PUB_BOUND, PUB_BOUND, "mul01245")


def _mul014_lazy(f, c):
    bd, ob = plans.f12_interior()
    return plans.execute(MUL_BY_014, f, c, bd, bd, "mul014_c", out_bound=ob)


def _mul01245_lazy(f, c):
    bd, ob = plans.f12_interior()
    return plans.execute(MUL_BY_01245, f, c, bd, bd, "mul01245_c", out_bound=ob)


# --------------------------------------------------------------------------------------
# Miller-loop step plans (CLN homogeneous projective, two_inv cleared by 4x rescale)
# --------------------------------------------------------------------------------------
#
# The doubling step is two dedicated plans with ALL linear glue (h, e, b+-3e,
# the line coefficients) folded into lincombs/pass-through rows — no separate
# carry_norm or lazy-add traffic between kernels, and both levels run at the
# lazy F12_BOUND interior:
#
#   Level 1: lanes a' = XY, b = Y^2, c = Z^2, j = X^2, s = (Y+Z)^2 (11 lanes);
#     rows  [a', b - 3e, b + 3e, e, b, h, j] with e = 12 nr(c), h = s - b - c.
#   Level 2: lanes m0 = a'(b - 3e), m1 = (b + 3e)^2, m2 = e^2, m3 = b h
#     (10 lanes); rows X3 = 2 m0, Y3 = m1 - 12 m2, Z3 = 4 m3 and the line
#     (e - b, 3j, -h) through pass-through references.


def _build_dbl_plans() -> tuple[plans.Plan, plans.Plan]:
    p1 = plans.Plan(6, 6)
    x = plans.vbasis(6)
    X, Y, Z = x[0:2], x[2:4], x[4:6]
    aj = p1.mul2(X, Y)
    b = p1.sqr2(Y)
    c = p1.sqr2(Z)
    j = p1.sqr2(X)
    s = p1.sqr2(v2_add(Y, Z))
    e = [t.scale(12) for t in v2_nr(c)]
    e3 = [t.scale(3) for t in e]
    bmf = v2_sub(b, e3)
    bpf = v2_add(b, e3)
    h = v2_sub(v2_sub(s, b), c)
    p1.out_rows = aj + bmf + bpf + e + b + h + j

    p2 = plans.Plan(14, 14)
    y = plans.vbasis(14)
    aj2, bmf2, bpf2, e2, b2, h2 = (
        y[0:2], y[2:4], y[4:6], y[6:8], y[8:10], y[10:12]
    )
    m0 = p2.mul2(aj2, bmf2)
    m1 = p2.sqr2(bpf2)
    m2 = p2.sqr2(e2)
    m3 = p2.mul2(b2, h2)
    x3 = [t.scale(2) for t in m0]
    y3 = v2_sub(m1, [t.scale(12) for t in m2])
    z3 = [t.scale(4) for t in m3]
    l0 = [p2.inp(6) - p2.inp(8), p2.inp(7) - p2.inp(9)]      # e - b
    l1 = [p2.inp(12).scale(3), p2.inp(13).scale(3)]          # 3 j
    l2 = [-p2.inp(10), -p2.inp(11)]                          # -h
    p2.out_rows = x3 + y3 + z3 + l0 + l1 + l2
    return p1, p2


DBL1, DBL2 = _build_dbl_plans()


def _dbl_step(r):
    """r = (X:Y:Z) on the twist (F12-bounded) -> (4-scaled doubled point,
    unscaled line [c0|c1|c2]), both F12-bounded."""
    bd, ob = plans.f12_interior()
    mid = plans.execute(DBL1, r, r, bd, bd, "mldbl1", out_bound=ob)
    out = plans.execute(DBL2, mid, mid, bd, bd, "mldbl2", out_bound=ob)
    return out[..., 0:6, :], out[..., 6:12, :]


def _add_step(r, qx, qy):
    """Mixed addition r + Q (Q affine on the twist) -> (new point, unscaled
    line). Runs only at the 5 set bits of |x|; r may be F12-bounded.

    theta = Y - qy Z, lam = X - qx Z; c = theta^2, d = lam^2; e = lam d,
    f = Z c, g = X d; h = e + f - 2g; X3 = lam h, Y3 = theta (g - h) - e Y,
    Z3 = Z e; line = (theta qx - lam qy, -theta, lam).
    """
    B = plans.f12_interior()[0]
    x, y, z = r[..., 0:2, :], r[..., 2:4, :], r[..., 4:6, :]
    qyz, qxz = tower.fq2_mul_many([(qy, z), (qx, z)], in_bound=B)
    pre = plans.carry_norm(
        jnp.concatenate(
            [tower.t_sub(y, qyz, B), tower.t_sub(x, qxz, B)], axis=-2
        )
    )
    theta, lam = pre[..., 0:2, :], pre[..., 2:4, :]
    c, d = tower.fq2_mul_many([(theta, theta), (lam, lam)])
    e, f, g = tower.fq2_mul_many([(lam, d), (z, c), (x, d)], in_bound=B)
    h = plans.carry_norm(tower.t_sub(e + f, g * np.uint64(2), PUB_BOUND.scaled(2)))
    gmh = plans.carry_norm(tower.t_sub(g, h))
    x3, t1, t2, z3, j1, j2 = tower.fq2_mul_many(
        [(lam, h), (theta, gmh), (e, y), (z, e), (theta, qx), (lam, qy)],
        in_bound=B,
    )
    out = jnp.concatenate(
        [
            x3,
            tower.t_sub(t1, t2),          # Y3
            z3,
            tower.t_sub(j1, j2),          # line c0
            tower.t_neg(theta),           # line c1
            lam,                          # line c2
        ],
        axis=-2,
    )
    out = plans.carry_norm(out)
    return out[..., 0:6, :], out[..., 6:12, :]


# --------------------------------------------------------------------------------------
# Miller loop driver (trace-time |x| schedule, two passes)
# --------------------------------------------------------------------------------------


def _expand_01245(m):
    """[..., 10, 25] sparse [c0|c1|c2|c4|c5] -> full fq12 (slot 3 zero)."""
    z = jnp.zeros_like(m[..., 0:2, :])
    return jnp.concatenate(
        [m[..., 0:6, :], z, m[..., 6:8, :], m[..., 8:10, :]], axis=-2
    )


def _expand_014(c):
    """[..., 6, 25] sparse [c0|c1|c4] -> full fq12 (slots 2, 3, 5 zero)."""
    z = jnp.zeros_like(c[..., 0:2, :])
    return jnp.concatenate(
        [c[..., 0:4, :], z, z, c[..., 4:6, :], z], axis=-2
    )


def _fold_walk(f, lines):
    """f <- (f^2) * line over the leading axis of ``lines`` — the uniform
    doubling-position accumulator body, all at F12_BOUND interiors."""

    def body(g, ln):
        g = tower.fq12_sqr_lazy(g)
        return _mul014_lazy(g, ln), None

    f, _ = jax.lax.scan(body, f, lines)
    return f


def _collect_lines(px, py, qx, qy):
    """Pass 1 of the planned Miller loop: iterate ONLY the twist point over
    the trace-time |x| schedule, collect the 63 doubling + 5 addition lines,
    and scale all 68 by the G1 coordinates in one stacked plan execution.
    Returns (segs, add_pos, sd, sa): the schedule, the doubling positions
    paired with an addition, the scaled doubling lines [63, *batch, 6, 25]
    and the scaled addition lines [5, *batch, 6, 25] — line operands at the
    backend's fq12 interior bound."""
    from .curve import fixed_schedule

    segs = fixed_schedule(X_ABS)
    assert segs[0] == (1, 1), "BLS |x| starts 0b11"
    batch = qx.shape[:-2]
    bd, ob = plans.f12_interior()

    r = jnp.concatenate([qx, qy, tower.one(2, batch)], axis=-2)

    def dbl_body(rr, _):
        rr2, line = _dbl_step(rr)
        return rr2, line

    dbl_lines = []
    add_lines = []
    for run, add in segs:
        r, ls = jax.lax.scan(dbl_body, r, None, length=run)
        dbl_lines.append(ls)
        if add:
            r, la = _add_step(r, qx, qy)
            add_lines.append(la)
    dbl_lines = jnp.concatenate(dbl_lines, axis=0)   # [63, *batch, 6, 25]
    add_lines = jnp.stack(add_lines, axis=0)         # [5, *batch, 6, 25]

    # ---- one stacked scaling of all 68 lines by the G1 coordinates
    pxy = jnp.stack([px, py], axis=-2)               # [*batch, 2, 25]
    all_lines = jnp.concatenate([dbl_lines, add_lines], axis=0)
    scaled = plans.execute(
        SCALE_LINE,
        all_lines,
        jnp.broadcast_to(pxy, all_lines.shape[:1] + pxy.shape),
        bd,
        PUB_BOUND,
        "ml_scale",
        out_bound=ob,
    )
    ends = np.cumsum([run for run, _ in segs])
    add_pos = [int(e) - 1 for e, (_, a) in zip(ends, segs) if a]
    return (
        segs, add_pos,
        scaled[: dbl_lines.shape[0]], scaled[dbl_lines.shape[0] :],
    )


def _conj_norm(f):
    """x < 0: conjugate the walked accumulator; restore the public bound."""
    bd = plans.f12_interior()[0]
    f = jnp.concatenate(
        [f[..., 0:6, :], tower.t_neg(f[..., 6:12, :], bd)], axis=-2
    )
    return plans.carry_norm(f)


def miller_loop(px, py, qx, qy):
    """Unreduced pairing f_{x,Q}(P) for P = (px, py) in G1 affine (each
    [..., 25], canonical) and Q = (qx, qy) in G2 affine on the twist (each
    [..., 2, 25]). Returns fq12 [..., 12, 25], public-bounded. Infinity
    inputs produce garbage — callers mask (branchless integer arithmetic).

    Two passes over the trace-time |x| schedule (see module docstring):
    point-only line collection, one stacked line scaling, one stacked
    addition-line pre-multiply, then the lazy-interior accumulator walk."""
    segs, add_pos, sd, sa = _collect_lines(px, py, qx, qy)
    bd, ob = plans.f12_interior()

    # ---- pre-multiply each addition line into its doubling partner
    merged = plans.execute(
        SP_SP, sd[jnp.asarray(add_pos)], sa, bd, bd, "ml_spsp", out_bound=ob,
    )                                                # [5, *batch, 10, 25]

    # ---- pass 2: accumulator walk (init consumes the leading 11 bits of |x|)
    f = _expand_01245(merged[0])
    mi = 1
    start = segs[0][0]
    for run, add in segs[1:]:
        n_plain = run - (1 if add else 0)
        if n_plain:
            f = _fold_walk(f, sd[start : start + n_plain])
        if add:
            f = _mul01245_lazy(tower.fq12_sqr_lazy(f), merged[mi])
            mi += 1
        start += run
    return _conj_norm(f)


def _cross_pair_products(lines, valid=None):
    """Per-position products of the n pairs' scaled lines: [P, n, 6, 25]
    sparse-014 operands -> [P, 12, 25] full fq12, at interior bounds.

    One batched sparse SP_SP level (every 014 x 014 product costs 18 lanes
    instead of a 54-lane dense multiply), then a halving fq12_mul tree, then
    one sparse 014-fold of the odd leftover line — log2(n) + 2 stacked plan
    executions covering ALL positions. ``valid`` masks pairs by replacing
    their lines with the identity line (c0 = 1)."""
    if valid is not None:
        ident = jnp.concatenate(
            [
                tower.one(2, lines.shape[:2]),
                jnp.zeros_like(lines[..., 0:4, :]),
            ],
            axis=-2,
        )
        mask = jnp.broadcast_to(valid[None], lines.shape[:2])
        lines = tower.t_select(mask, lines, ident)
    n = lines.shape[1]
    if n == 1:
        return _expand_014(lines[:, 0])
    bd, ob = plans.f12_interior()
    half = n // 2
    leftover = lines[:, -1] if n % 2 else None
    sp = plans.execute(
        SP_SP, lines[:, :half], lines[:, half : 2 * half], bd, bd,
        "ml_spsp", out_bound=ob,
    )
    L = _expand_01245(sp)                             # [P, half, 12, 25]
    m = L.shape[1]
    while m > 1:
        h = m // 2
        prod = tower.fq12_mul_lazy(L[:, :h], L[:, h : 2 * h])
        if m % 2:
            prod = jnp.concatenate([prod, L[:, 2 * h :]], axis=1)
        L = prod
        m = L.shape[1]
    L = L[:, 0]
    if leftover is not None:
        L = _mul014_lazy(L, leftover)
    return L


def miller_loop_product(px, py, qx, qy, valid=None):
    """prod_i f_{x,Q_i}(P_i) over the LEADING batch axis with ONE shared
    accumulator (blst's aggregate-verify shape): every pairing in the
    product squares its accumulator on the same |x| schedule, so the product
    squares a single fq12 once per step and folds each step's cross-pair
    line product as one full element — the O(n) accumulator squarings of n
    batched Miller loops collapse to O(1), and the line products themselves
    are sparse-first batched trees (_cross_pair_products) over all 68 line
    positions at once.

    Pass 1 (per-pair point iteration + stacked scaling) is shared with
    ``miller_loop``; the walk is a single uniform ``f <- f^2 * L[i]`` scan
    at batch 1. ``valid`` masks pairs (an invalid pair's lines become one,
    so it contributes nothing to the product)."""
    segs, add_pos, sd, sa = _collect_lines(px, py, qx, qy)

    # [68, 12, 25]: per-position cross-pair products (63 dbl + 5 add)
    L = _cross_pair_products(jnp.concatenate([sd, sa], axis=0), valid)
    # fold each addition-position product into its doubling partner, so the
    # walk is uniform (one squaring, one multiply per position)
    ap = jnp.asarray(add_pos)
    Lm = tower.fq12_mul_lazy(L[ap], L[63:])
    Ld = L[:63].at[ap].set(Lm)

    def body(g, ln):
        g = tower.fq12_sqr_lazy(g)
        return tower.fq12_mul_lazy(g, ln), None

    f, _ = jax.lax.scan(body, Ld[0], Ld[1:])
    return _conj_norm(f)


# --------------------------------------------------------------------------------------
# Final exponentiation (easy part + x-addition-chain hard part, exponent 3λ)
# --------------------------------------------------------------------------------------


def final_exponentiation(f):
    """f^((p^6-1)(p^2+1)) then the hard part f^(3 (p^4 - p^2 + 1)/r) via
    3λ = (x-1)^2 (x+p) (x^2 + p^2 - 1) + 3 (mirrors the oracle chain).

    The five |x|-exponentiations are data-sequential (each feeds the next —
    the x-addition chain is the optimal factorization for the weight-6 |x|),
    but each one is a single compiled chain-plan scan with lazy fq12
    interiors (see tower.fq12_cyclotomic_exp_abs_x); the Frobenius/conjugate
    glue and the f^3 term run at chain boundaries."""
    f = tower.fq12_mul(tower.fq12_conj(f), tower.fq12_inv(f))
    f = tower.fq12_mul(tower.fq12_frobenius(f, 2), f)  # cyclotomic now

    def exp_x_minus_1(g):
        gx = tower.fq12_cyclotomic_exp_abs_x(g)
        return tower.fq12_conj(tower.fq12_mul(gx, g))

    m1 = exp_x_minus_1(f)
    m2 = exp_x_minus_1(m1)
    m2x = tower.fq12_conj(tower.fq12_cyclotomic_exp_abs_x(m2))
    m3 = tower.fq12_mul(m2x, tower.fq12_frobenius(m2, 1))
    m3x = tower.fq12_conj(tower.fq12_cyclotomic_exp_abs_x(m3))
    m3x2 = tower.fq12_conj(tower.fq12_cyclotomic_exp_abs_x(m3x))
    m4 = tower.fq12_mul(
        m3x2, tower.fq12_mul(tower.fq12_frobenius(m3, 2), tower.fq12_conj(m3))
    )
    f3 = tower.fq12_mul(tower.fq12_mul(f, f), f)
    return tower.fq12_mul(m4, f3)


def fq12_prod(fs):
    """Product over the leading axis by halving tree (pads with one)."""
    n = fs.shape[0]
    while n > 1:
        if n % 2:
            fs = jnp.concatenate(
                [fs, tower.one(12, (1,) + fs.shape[1:-2])], axis=0
            )
            n += 1
        fs = tower.fq12_mul(fs[: n // 2], fs[n // 2 :])
        n //= 2
    return fs[0]


def pairing(px, py, qx, qy):
    """Reduced pairing e(P, Q)^3 (consistent cube — same as the oracle)."""
    return final_exponentiation(miller_loop(px, py, qx, qy))


def miller_product(px, py, qx, qy, valid=None):
    """Unreduced prod_i f_{x,Q_i}(P_i) over the leading batch axis — the
    verify path's Miller stage, dispatched by conv backend at trace time:

    * digits / pallas (TPU): the shared-accumulator ``miller_loop_product``
      — conv lane counts dominate there, and collapsing the n per-pair
      accumulator squarings to one plus sparse-first cross-pair line trees
      is a strict lane win (the pallas fused kernels inherit the digit
      backend's lane-count economics);
    * f64 (CPU): independent batched accumulators + a halving product tree —
      measured FASTER below ~dozens of pairs (at the 9-pair verify shape the
      cross-pair trees' dense fq12 multiplies at shrinking batch widths cost
      more than the n-1 extra squarings they avoid, which SIMD over the
      batch axis makes nearly free).
    """
    if fq.conv_backend() in ("digits", "pallas"):
        return miller_loop_product(px, py, qx, qy, valid)
    fs = miller_loop(px, py, qx, qy)
    if valid is not None:
        fs = tower.t_select(valid, fs, tower.one(12, fs.shape[:-2]))
    return fq12_prod(fs)


def multi_pairing_is_one(px, py, qx, qy, valid=None):
    """prod_i e(P_i, Q_i) == 1 over the leading batch axis with ONE final
    exponentiation; the Miller stage is the backend-dispatched
    ``miller_product``. ``valid`` masks entries (invalid -> contributes
    one)."""
    f = miller_product(px, py, qx, qy, valid)
    return tower.fq12_is_one(final_exponentiation(f))
