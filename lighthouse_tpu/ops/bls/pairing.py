"""Optimal-ate pairing on BLS12-381 as JAX device kernels.

The TPU twin of the pairing engine blst provides to the reference's batch
verifier (``/root/reference/crypto/bls/src/impls/blst.rs:37-119``). Design:

  * **Miller loop**: homogeneous-projective doubling/addition steps on the
    M-type twist (Costello–Lange–Naehrig formulas, two_inv eliminated by a
    uniform projective rescale), producing sparse line coefficients that fold
    into the Fq12 accumulator via a dedicated 39-lane ``mul_by_014`` plan.
    Denominator/subfield factors introduced by rescaling live in Fq2 and are
    annihilated by the easy part of the final exponentiation.
  * **Loop structure**: the BLS parameter |x| = 0xd201000000010000 has Hamming
    weight 6, so the 63-step loop is host-segmented into runs of pure doubling
    (each one ``lax.scan`` over a shared branchless body) with the 5 addition
    steps unrolled in between — no per-step conditionals on device.
  * **Batching**: every op broadcasts over leading axes; a batch of pairings is
    one Miller loop over stacked points, the product is a halving fq12_mul
    tree, and the whole check costs ONE final exponentiation (same shape as
    blst's ``verify_multiple_aggregate_signatures``).

Correctness is pinned against ``ops.bls_oracle.pairing`` (values agree after
final exponentiation; both compute e(P,Q)^3 — the harmless cube of the
x-addition-chain hard part, gcd(3, r) = 1).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import fq, plans, tower
from .plans import LC, PUB_BOUND, v2_add, v2_sub, v2_nr
from ..bls_oracle.fields import BLS_X

# --------------------------------------------------------------------------------------
# Sparse fold plan: f * (c0 + c1 v + c4 v w)   [Fq6-slot positions 0, 1, 4]
# --------------------------------------------------------------------------------------


def _mul6_sp2(p: plans.Plan, xs, d0, d1):
    """Karatsuba fq6 * (d0, d1, 0) — 5 mul2 lanes."""
    x0, x1, x2 = xs[0:2], xs[2:4], xs[4:6]
    m00 = p.mul2(x0, d0)
    m11 = p.mul2(x1, d1)
    mx = p.mul2(v2_add(x0, x1), v2_add(d0, d1))
    m20 = p.mul2(x2, d0)
    m21 = p.mul2(x2, d1)
    r0 = v2_add(m00, v2_nr(m21))
    r1 = v2_sub(v2_sub(mx, m00), m11)
    r2 = v2_add(m11, m20)
    return r0 + r1 + r2


def _mul6_sp1(p: plans.Plan, xs, d):
    """fq6 * (0, d, 0) = (nr(x2 d), x0 d, x1 d) — 3 mul2 lanes."""
    x0, x1, x2 = xs[0:2], xs[2:4], xs[4:6]
    n0 = p.mul2(x0, d)
    n1 = p.mul2(x1, d)
    n2 = p.mul2(x2, d)
    return v2_nr(n2) + n0 + n1


def _build_mul_by_014() -> plans.Plan:
    """A-side: full fq12 (12 coeffs). B-side: 6 coeffs [c0 | c1 | c4]."""
    p = plans.Plan(12, 6)
    x = plans.vbasis(12)
    a0, a1 = x[0:6], x[6:12]
    c0 = [LC.basis(0), LC.basis(1)]
    c1 = [LC.basis(2), LC.basis(3)]
    c4 = [LC.basis(4), LC.basis(5)]
    t0 = _mul6_sp2(p, a0, c0, c1)
    t1 = _mul6_sp1(p, a1, c4)
    t2 = _mul6_sp2(p, plans.v6_add(a0, a1), c0, v2_add(c1, c4))
    out0 = plans.v6_add(t0, plans.v6_nr(t1))
    out1 = plans.v6_sub(plans.v6_sub(t2, t0), t1)
    p.out_rows = out0 + out1
    return p


MUL_BY_014 = _build_mul_by_014()


def mul_by_014(f, c):
    """f [..., 12, 25] times the sparse element with Fq2 coefficients
    c = [c0 | c1 | c4] [..., 6, 25] at Fq6-slot positions 0, 1, 4."""
    return plans.execute(MUL_BY_014, f, c, PUB_BOUND, PUB_BOUND, "mul014")


# --------------------------------------------------------------------------------------
# Miller-loop steps (CLN homogeneous projective, two_inv cleared by 4x rescale)
# --------------------------------------------------------------------------------------

_B2 = PUB_BOUND.scaled(2)


def _dbl_step(r):
    """r = (X:Y:Z) on the twist -> (4-scaled doubled point, line [c0|c1|c2]).

    Level 1: a' = XY, b = Y^2, c = Z^2, j = X^2, s = (Y+Z)^2.
    Linear:  h = s - b - c, e = 12 nr(c) (= 3 b' c for b' = 4(u+1)), f3 = 3e.
    Level 2: m0 = a'(b - f3), m1 = (b + f3)^2, m2 = e^2, m3 = b h.
    Out:     X3 = 2 m0, Y3 = m1 - 12 m2, Z3 = 4 m3; line = (e - b, 3j, -h).
    """
    x, y, z = r[..., 0:2, :], r[..., 2:4, :], r[..., 4:6, :]
    aj, b, c, j, s = tower.fq2_mul_many(
        [(x, y), (y, y), (z, z), (x, x), (y + z, y + z)], in_bound=_B2
    )
    h = tower.t_sub(tower.t_sub(s, b), c)
    h_b = plans.sub_bound(plans.sub_bound(PUB_BOUND, PUB_BOUND), PUB_BOUND)
    e = plans.carry_norm(tower.fq2_mul_by_nonresidue(c) * np.uint64(12))
    f3 = e * np.uint64(3)
    bmf = tower.t_sub(b, f3, PUB_BOUND.scaled(3))
    bpf = b + f3
    lvl2_b = plans.sub_bound(PUB_BOUND, PUB_BOUND.scaled(3)) | PUB_BOUND.scaled(4) | h_b
    m0, m1, m2, m3 = tower.fq2_mul_many(
        [(aj, bmf), (bpf, bpf), (e, e), (b, plans.carry_norm(h))], in_bound=lvl2_b
    )
    out = jnp.concatenate(
        [
            m0 * np.uint64(2),                                      # X3
            tower.t_sub(m1, m2 * np.uint64(12), PUB_BOUND.scaled(12)),  # Y3
            m3 * np.uint64(4),                                      # Z3
            tower.t_sub(e, b),                                      # line c0 = e - b
            j * np.uint64(3),                                       # line c1 = 3j
            tower.t_neg(plans.carry_norm(h)),                       # line c2 = -h
        ],
        axis=-2,
    )
    out = plans.carry_norm(out)
    return out[..., 0:6, :], out[..., 6:12, :]


def _add_step(r, qx, qy):
    """Mixed addition r + Q (Q affine on the twist) -> (new point, line).

    theta = Y - qy Z, lam = X - qx Z; c = theta^2, d = lam^2; e = lam d,
    f = Z c, g = X d; h = e + f - 2g; X3 = lam h, Y3 = theta (g - h) - e Y,
    Z3 = Z e; line = (theta qx - lam qy, -theta, lam).
    """
    x, y, z = r[..., 0:2, :], r[..., 2:4, :], r[..., 4:6, :]
    qyz, qxz = tower.fq2_mul_many([(qy, z), (qx, z)])
    pre = plans.carry_norm(
        jnp.concatenate([tower.t_sub(y, qyz), tower.t_sub(x, qxz)], axis=-2)
    )
    theta, lam = pre[..., 0:2, :], pre[..., 2:4, :]
    c, d = tower.fq2_mul_many([(theta, theta), (lam, lam)])
    e, f, g = tower.fq2_mul_many([(lam, d), (z, c), (x, d)])
    h = plans.carry_norm(tower.t_sub(e + f, g * np.uint64(2), PUB_BOUND.scaled(2)))
    gmh = plans.carry_norm(tower.t_sub(g, h))
    x3, t1, t2, z3, j1, j2 = tower.fq2_mul_many(
        [(lam, h), (theta, gmh), (e, y), (z, e), (theta, qx), (lam, qy)]
    )
    out = jnp.concatenate(
        [
            x3,
            tower.t_sub(t1, t2),          # Y3
            z3,
            tower.t_sub(j1, j2),          # line c0
            tower.t_neg(theta),           # line c1
            lam,                          # line c2
        ],
        axis=-2,
    )
    out = plans.carry_norm(out)
    return out[..., 0:6, :], out[..., 6:12, :]


def _ell(f, line, pxy2):
    """Fold a line into f: f * (c0, c1 px, c2 py). pxy2 [..., 4, 25] is the
    precomputed [px, px, py, py] broadcast block (Montgomery, canonical)."""
    scaled = fq.mont_mul(line[..., 2:6, :], pxy2)
    c = jnp.concatenate([line[..., 0:2, :], scaled], axis=-2)
    return mul_by_014(f, c)


# --------------------------------------------------------------------------------------
# Miller loop driver (host-segmented over the weight-6 |x|)
# --------------------------------------------------------------------------------------

X_ABS = -BLS_X  # 0xd201000000010000


def miller_loop(px, py, qx, qy):
    """Unreduced pairing f_{x,Q}(P) for P = (px, py) in G1 affine (each
    [..., 25], Montgomery) and Q = (qx, qy) in G2 affine on the twist (each
    [..., 2, 25]). Returns fq12 [..., 12, 25]. Infinity inputs produce garbage
    — callers mask (branchless integer arithmetic, no NaNs).

    Loop structure: the 63-step walk over |x|'s bits runs as ONE lax.scan over
    the (doubling_run, add_flag) segment schedule — a dynamic-count fori_loop
    of the shared doubling body plus a masked addition step. Runtime matches
    the sparse form (63 dbl, 5 add — |x| has weight 6) while compiling a
    single body instead of unrolling each segment into the program."""
    from .curve import fixed_schedule

    batch = qx.shape[:-2]
    pxy2 = jnp.stack([px, px, py, py], axis=-2)
    # varying-safe initial state: derive from inputs (shard_map scan vma)
    f = tower.one(12, batch) + qx[..., 0:1, :] * jnp.uint64(0)
    r = jnp.concatenate([qx, qy, tower.one(2, batch)], axis=-2)

    def dbl_body(_, carry):
        f, r = carry
        f = tower.fq12_sqr(f)
        r, line = _dbl_step(r)
        f = _ell(f, line, pxy2)
        return f, r

    segs = fixed_schedule(X_ABS)
    runs = jnp.asarray([s for s, _ in segs], dtype=jnp.int32)
    adds = jnp.asarray([a for _, a in segs], dtype=jnp.int32)

    def seg_body(carry, seg):
        run, addf = seg
        f, r = jax.lax.fori_loop(0, run, dbl_body, carry)
        ra, line = _add_step(r, qx, qy)
        fa = _ell(f, line, pxy2)
        f = tower.t_select(jnp.broadcast_to(addf == 1, f.shape[:-2]), fa, f)
        r = tower.t_select(jnp.broadcast_to(addf == 1, r.shape[:-2]), ra, r)
        return (f, r), None

    (f, r), _ = jax.lax.scan(seg_body, (f, r), (runs, adds))
    # x < 0: conjugate
    return tower.fq12_conj(f)


# --------------------------------------------------------------------------------------
# Final exponentiation (easy part + x-addition-chain hard part, exponent 3λ)
# --------------------------------------------------------------------------------------


def final_exponentiation(f):
    """f^((p^6-1)(p^2+1)) then the hard part f^(3 (p^4 - p^2 + 1)/r) via
    3λ = (x-1)^2 (x+p) (x^2 + p^2 - 1) + 3 (mirrors the oracle chain)."""
    f = tower.fq12_mul(tower.fq12_conj(f), tower.fq12_inv(f))
    f = tower.fq12_mul(tower.fq12_frobenius(f, 2), f)  # cyclotomic now

    def exp_x_minus_1(g):
        gx = tower.fq12_cyclotomic_exp_abs_x(g)
        return tower.fq12_conj(tower.fq12_mul(gx, g))

    m1 = exp_x_minus_1(f)
    m2 = exp_x_minus_1(m1)
    m2x = tower.fq12_conj(tower.fq12_cyclotomic_exp_abs_x(m2))
    m3 = tower.fq12_mul(m2x, tower.fq12_frobenius(m2, 1))
    m3x = tower.fq12_conj(tower.fq12_cyclotomic_exp_abs_x(m3))
    m3x2 = tower.fq12_conj(tower.fq12_cyclotomic_exp_abs_x(m3x))
    m4 = tower.fq12_mul(
        m3x2, tower.fq12_mul(tower.fq12_frobenius(m3, 2), tower.fq12_conj(m3))
    )
    f3 = tower.fq12_mul(tower.fq12_mul(f, f), f)
    return tower.fq12_mul(m4, f3)


def fq12_prod(fs):
    """Product over the leading axis by halving tree (pads with one)."""
    n = fs.shape[0]
    while n > 1:
        if n % 2:
            fs = jnp.concatenate(
                [fs, tower.one(12, (1,) + fs.shape[1:-2])], axis=0
            )
            n += 1
        fs = tower.fq12_mul(fs[: n // 2], fs[n // 2 :])
        n //= 2
    return fs[0]


def pairing(px, py, qx, qy):
    """Reduced pairing e(P, Q)^3 (consistent cube — same as the oracle)."""
    return final_exponentiation(miller_loop(px, py, qx, qy))


def multi_pairing_is_one(px, py, qx, qy, valid=None):
    """prod_i e(P_i, Q_i) == 1 over the leading batch axis with ONE final
    exponentiation. ``valid`` masks entries (invalid -> contributes one)."""
    fs = miller_loop(px, py, qx, qy)
    if valid is not None:
        fs = tower.t_select(valid, fs, tower.one(12, fs.shape[:-2]))
    return tower.fq12_is_one(final_exponentiation(fq12_prod(fs)))
