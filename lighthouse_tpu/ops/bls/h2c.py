"""Hash-to-curve G2 on device (RFC 9380, suite BLS12381G2_XMD:SHA-256_SSWU_RO_).

Split mirrors the suite's structure: ``hash_to_field`` is host-side SHA-256
(9 hashlib calls per 32-byte message — negligible next to the pairing) that
yields Fq2 limb arrays; everything algebraic — simplified SWU on the
3-isogenous curve, the 3-isogeny map, and Budroni–Pintore psi-based cofactor
clearing — runs branchless on device over the whole message batch at once.

Constants come from the oracle module (``ops.bls_oracle.hash_to_curve``),
which cross-validates them (h_eff vs psi clearing) in its own tests.
Parity: blst's hash-or-encode path used by the reference's sign/verify
(``/root/reference/crypto/bls/src/impls/blst.rs``).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import curve, fq, g2, plans, tower
from ..bls_oracle import hash_to_curve as _oh
from ..bls_oracle.fields import BLS_X, Fq2

# -- host: hash_to_field --------------------------------------------------------------


def hash_to_field_batch(msgs: list[bytes], dst: bytes):
    """[n messages] -> (u0, u1) device fq2 arrays [n, 2, 25] each."""
    u0s, u1s = [], []
    for m in msgs:
        u0, u1 = _oh.hash_to_field_fq2(m, dst, 2)
        u0s.append(tower.from_ints([u0.c0, u0.c1]))
        u1s.append(tower.from_ints([u1.c0, u1.c1]))
    return jnp.stack(u0s), jnp.stack(u1s)


# -- device constants -----------------------------------------------------------------


def _c2(v: Fq2):
    return tower.from_ints([v.c0, v.c1])


_A = _c2(_oh.ISO_A)
_B = _c2(_oh.ISO_B)
_Z = _c2(_oh.SSWU_Z)

_KX_NUM = [_c2(k) for k in _oh._K["x_num"]]
_KX_DEN = [_c2(k) for k in _oh._K["x_den"]]
_KY_NUM = [_c2(k) for k in _oh._K["y_num"]]
_KY_DEN = [_c2(k) for k in _oh._K["y_den"]]


def _bc(c, like):
    return jnp.broadcast_to(c, like.shape[:-2] + (2, fq.NLIMBS))


# -- device: simplified SWU on E' ----------------------------------------------------


def map_to_curve_sswu_fraction(u):
    """u [..., 2, 25] -> (xn, xd, y): x = xn/xd on E' as a FRACTION, y exact.

    The RFC 9380 appendix F.2 straight-line form of 6.6.2: the x-coordinate
    is never inverted (the 3-isogeny consumes the fraction and the final
    projective point absorbs the denominator), and ONE sqrt_ratio chain
    serves both candidates — gx2 = Z^3 u^6 gx1, so the non-square branch's
    root is tv1·u·y1 with no second exponentiation. Replaces the 6.6.2
    direct form's three sequential Fermat chains (inv0, a^((p-3)/4),
    (α+1)^((p-1)/2)) with a single joint chain (tower.fq2_sqrt_ratio).

    ``u`` must be canonical (hash_to_field outputs are) — sgn0(u) reads limb
    parity without a reduction walk."""
    A_M = _bc(_A, u)
    B_M = _bc(_B, u)
    u2 = tower.fq2_sqr(u)
    tv1 = tower.fq2_mul(_bc(_Z, u), u2)                     # Z u^2
    tv2 = plans.carry_norm(tower.fq2_sqr(tv1) + tv1)        # Z^2u^4 + Zu^2
    tv2_nz = ~tower.t_is_zero(tv2)
    one = tower.one(2, u.shape[:-2])
    tv3 = tower.fq2_mul(B_M, plans.carry_norm(tv2 + one))   # x1 numerator
    neg_tv2 = plans.carry_norm(tower.fq2_neg(tv2))
    tv4 = tower.fq2_mul(
        A_M, tower.t_select(tv2_nz, neg_tv2, _bc(_Z, u))
    )                                                       # x1 denominator
    tv3s, tv4s = tower.fq2_mul_many([(tv3, tv3), (tv4, tv4)])
    tv3c, tv4c, t34 = tower.fq2_mul_many(
        [(tv3s, tv3), (tv4s, tv4), (tv4s, tv3)]
    )
    a34, b4c = tower.fq2_mul_many([(t34, A_M), (tv4c, B_M)])
    gx1_num = plans.carry_norm(tv3c + a34 + b4c)  # tv3^3 + A tv3 tv4^2 + B tv4^3
    is_sq, y1 = tower.fq2_sqrt_ratio(gx1_num, tv4c)
    # candidate 2 (gx1 non-square): x2 = tv1 x1, y2 = tv1 u y1
    t1u = tower.fq2_mul(tv1, u)
    y2, x2n = tower.fq2_mul_many([(t1u, y1), (tv1, tv3)])
    xn = tower.t_select(is_sq, tv3, x2n)
    y = tower.t_select(is_sq, y1, y2)
    # u arrives canonical from hash_to_field (host from_ints) — its sgn0
    # needs no reduction walk; y is a fresh multiply output and does. The
    # negation works on the PUB-bounded y directly (borrow-inflated
    # constant): no canonicalization needed before it.
    flip = tower.fq2_sgn0_canon(u) != tower.fq2_sgn0(y)
    y = plans.carry_norm(tower.t_select(flip, tower.fq2_neg(y), y))
    return xn, tv4, y


def map_to_curve_sswu(u):
    """u [..., 2, 25] -> affine (x, y) on the isogenous curve E' (RFC 9380
    6.6.2 semantics). Affine convenience wrapper over the fraction form —
    the production path (map_to_g2) never divides."""
    xn, xd, y = map_to_curve_sswu_fraction(u)
    return tower.fq2_mul(xn, tower.fq2_inv(xd)), y


# -- device: 3-isogeny map ------------------------------------------------------------


def iso_map_fraction(xn, xd, y):
    """E' point with x = xn/xd (fraction) and exact y -> projective E2 point
    [..., 6, 25].

    Each Horner level homogenizes with the matching power of xd:
    P(xn/xd)·xd^3 = ((k3·xn + k2·xd)·xn + k1·xd^2)·xn + k0·xd^3 — the xd^3
    factor is shared by all four polynomials and cancels in the projective
    ratios, so the output formula is unchanged:
    (X:Y:Z) = (x_num' y_den', y y_num' x_den', x_den' y_den'). All four
    acc·xn products and all four k·xd^j products of a level run as ONE
    stacked kernel (fq2_mul_many)."""
    tables = [_KX_NUM, _KX_DEN, _KY_NUM, _KY_DEN]
    max_len = max(len(t) for t in tables)
    zero2 = tower.zero(2)
    tables = [t + [zero2] * (max_len - len(t)) for t in tables]
    xd2 = tower.fq2_sqr(xd)
    xd3 = tower.fq2_mul(xd2, xd)
    xd_pows = [None, xd, xd2, xd3]  # xd^(depth-level)
    accs = [_bc(t[-1], xn) for t in tables]
    for lvl in range(max_len - 2, -1, -1):
        pairs = [(a, xn) for a in accs] + [
            (_bc(t[lvl], xn), xd_pows[max_len - 1 - lvl]) for t in tables
        ]
        prods = tower.fq2_mul_many(pairs)
        accs = [
            plans.carry_norm(p + kx)
            for p, kx in zip(prods[:4], prods[4:])
        ]
    x_num, x_den, y_num, y_den = accs
    xz, yz, zz = tower.fq2_mul_many(
        [(x_num, y_den), (tower.fq2_mul(y, y_num), x_den), (x_den, y_den)]
    )
    return jnp.concatenate([xz, yz, zz], axis=-2)


def iso_map(x, y):
    """Affine E' point -> projective E2 point (degenerate-fraction wrapper)."""
    one = tower.one(2, x.shape[:-2])
    return iso_map_fraction(x, one, y)


# -- device: cofactor clearing (Budroni–Pintore) -------------------------------------


def clear_cofactor(p):
    """[x^2-x-1]P + [x-1]psi(P) + psi^2(2P) with x < 0:
    = [x]([x]P) - [x]P - P + [x]psi(P) - psi(P) + psi^2(2P)
    where [x]Q = -[|x|]Q. psi commutes with scalar multiplication
    ([x]psi(P) = psi([x]P)), so only TWO |x|-chains are needed — they are
    sequentially dependent (x^2 needs xP), which is exactly why this BP form
    beats the joint-axis [x^2-x-1 ; x-1] alternative here: |x| is weight-6
    sparse, so two wNAF chains cost 124 dbl + ~10 add total, the same
    doubling depth as one dense 127-bit chain but a third of its adds and at
    half the kernel width. Each chain runs as a compiled plan
    (chain_plans.scale_fixed_chain via curve.scale_fixed)."""
    xP = curve.scale_fixed(2, p, BLS_X)                # [x]P (sign in plan)
    xxP = curve.scale_fixed(2, xP, BLS_X)              # [x^2]P
    psiP = g2.psi(p)
    xpsiP = g2.psi(xP)                                 # [x]psi(P) = psi([x]P)
    psi2_2P = g2.psi(g2.psi(curve.point_dbl(2, p)))
    acc = curve.point_add(2, xxP, curve.point_neg(2, xP))
    acc = curve.point_add(2, acc, curve.point_neg(2, p))
    acc = curve.point_add(2, acc, xpsiP)
    acc = curve.point_add(2, acc, curve.point_neg(2, psiP))
    return curve.point_add(2, acc, psi2_2P)


# -- full pipeline --------------------------------------------------------------------


def map_to_g2(u0, u1):
    """Device map: two field elements per message -> projective G2 point.
    u0/u1 are stacked into one doubled leading batch so SSWU + the isogeny
    compile (and dispatch) ONCE instead of twice; x-coordinates stay in
    fraction form end-to-end (the projective output absorbs denominators)."""
    u = jnp.stack([u0, u1], axis=0)
    q = iso_map_fraction(*map_to_curve_sswu_fraction(u))
    return clear_cofactor(curve.point_add(2, q[0], q[1]))


def hash_to_curve_g2(msgs: list[bytes], dst: bytes):
    """[n messages] -> [n, 6, 25] projective G2 points (device)."""
    u0, u1 = hash_to_field_batch(msgs, dst)
    return map_to_g2(u0, u1)
