"""Hash-to-curve G2 on device (RFC 9380, suite BLS12381G2_XMD:SHA-256_SSWU_RO_).

Split mirrors the suite's structure: ``hash_to_field`` is host-side SHA-256
(9 hashlib calls per 32-byte message — negligible next to the pairing) that
yields Fq2 limb arrays; everything algebraic — simplified SWU on the
3-isogenous curve, the 3-isogeny map, and Budroni–Pintore psi-based cofactor
clearing — runs branchless on device over the whole message batch at once.

Constants come from the oracle module (``ops.bls_oracle.hash_to_curve``),
which cross-validates them (h_eff vs psi clearing) in its own tests.
Parity: blst's hash-or-encode path used by the reference's sign/verify
(``/root/reference/crypto/bls/src/impls/blst.rs``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import curve, fq, g2, plans, tower
from ..bls_oracle import hash_to_curve as _oh
from ..bls_oracle.fields import P, BLS_X, Fq2

# -- host: hash_to_field --------------------------------------------------------------


def hash_to_field_batch(msgs: list[bytes], dst: bytes):
    """[n messages] -> (u0, u1) device fq2 arrays [n, 2, 25] each."""
    u0s, u1s = [], []
    for m in msgs:
        u0, u1 = _oh.hash_to_field_fq2(m, dst, 2)
        u0s.append(tower.from_ints([u0.c0, u0.c1]))
        u1s.append(tower.from_ints([u1.c0, u1.c1]))
    return jnp.stack(u0s), jnp.stack(u1s)


# -- device constants -----------------------------------------------------------------


def _c2(v: Fq2):
    return tower.from_ints([v.c0, v.c1])


_A = _c2(_oh.ISO_A)
_B = _c2(_oh.ISO_B)
_Z = _c2(_oh.SSWU_Z)
_C1 = _c2(-_oh.ISO_B * _oh.ISO_A.inv())          # -B/A
_C2 = _c2(_oh.ISO_B * (_oh.SSWU_Z * _oh.ISO_A).inv())  # B/(Z*A)

_KX_NUM = [_c2(k) for k in _oh._K["x_num"]]
_KX_DEN = [_c2(k) for k in _oh._K["x_den"]]
_KY_NUM = [_c2(k) for k in _oh._K["y_num"]]
_KY_DEN = [_c2(k) for k in _oh._K["y_den"]]


def _bc(c, like):
    return jnp.broadcast_to(c, like.shape[:-2] + (2, fq.NLIMBS))


# -- device: simplified SWU on E' ----------------------------------------------------


def map_to_curve_sswu(u):
    """u [..., 2, 25] -> affine (x, y) on the isogenous curve E'. Branchless
    (RFC 9380 6.6.2 with inv0/select semantics)."""
    u2 = tower.fq2_sqr(u)
    zu2 = tower.fq2_mul(_bc(_Z, u), u2)
    tv = plans.carry_norm(tower.fq2_sqr(zu2) + zu2)
    tv_zero = tower.t_is_zero(tv)
    tv1 = tower.fq2_inv(tv)  # inv0
    one = tower.one(2, u.shape[:-2])
    x1 = tower.fq2_mul(_bc(_C1, u), plans.carry_norm(one + tv1))
    x1 = tower.t_select(tv_zero, _bc(_C2, u), x1)

    def g_of(x):
        return plans.carry_norm(
            tower.fq2_mul(plans.carry_norm(tower.fq2_sqr(x) + _bc(_A, u)), x)
            + _bc(_B, u)
        )

    gx1 = g_of(x1)
    x2 = tower.fq2_mul(zu2, x1)
    gx2 = g_of(x2)
    # one stacked sqrt for both candidates (halves the compiled chain)
    y12, ok12 = tower.fq2_sqrt(jnp.stack([gx1, gx2], axis=0))
    is_sq = ok12[0]
    x = tower.t_select(is_sq, x1, x2)
    y = tower.t_select(is_sq, y12[0], y12[1])
    flip = tower.fq2_sgn0(u) != tower.fq2_sgn0(y)
    y = plans.carry_norm(tower.t_select(flip, tower.fq2_neg(tower.t_canon(y)), y))
    return x, y


# -- device: 3-isogeny map ------------------------------------------------------------


def iso_map(x, y):
    """Affine E' point -> projective E2 point [..., 6, 25].

    All four Horner chains share powers of x; each level's four multiplies run
    as one stacked kernel (fq2_mul_many). Projective output avoids the two
    inversions: (X:Y:Z) = (x_num * y_den, y * y_num * x_den, x_den * y_den).
    """
    tables = [_KX_NUM, _KX_DEN, _KY_NUM, _KY_DEN]
    max_len = max(len(t) for t in tables)
    # pad shorter polynomials (x_den is degree 2) with a leading zero
    # coefficient so all four Horner chains share the same depth
    zero2 = tower.zero(2)
    tables = [t + [zero2] * (max_len - len(t)) for t in tables]
    accs = [_bc(t[-1], x) for t in tables]
    for lvl in range(max_len - 2, -1, -1):
        prods = tower.fq2_mul_many([(a, x) for a in accs])
        accs = [
            plans.carry_norm(p + _bc(t[lvl], x)) for p, t in zip(prods, tables)
        ]
    x_num, x_den, y_num, y_den = accs
    xz, yz, zz = tower.fq2_mul_many(
        [(x_num, y_den), (tower.fq2_mul(y, y_num), x_den), (x_den, y_den)]
    )
    return jnp.concatenate([xz, yz, zz], axis=-2)


# -- device: cofactor clearing (Budroni–Pintore) -------------------------------------


def _mul_by_abs_x(p):
    return curve.scale_fixed(2, p, -BLS_X)  # |x| (BLS_X negative)


def clear_cofactor(p):
    """[x^2-x-1]P + [x-1]psi(P) + psi^2(2P) with x < 0:
    = [x]([x]P) - [x]P - P + [x]psi(P) - psi(P) + psi^2(2P)
    where [x]Q = -[|x|]Q. psi commutes with scalar multiplication
    ([x]psi(P) = psi([x]P)), so only TWO |x|-chains are needed (they are
    sequentially dependent: x^2 needs xP)."""
    xP = curve.point_neg(2, _mul_by_abs_x(p))          # [x]P
    xxP = curve.point_neg(2, _mul_by_abs_x(xP))        # [x^2]P
    psiP = g2.psi(p)
    xpsiP = g2.psi(xP)                                 # [x]psi(P) = psi([x]P)
    psi2_2P = g2.psi(g2.psi(curve.point_dbl(2, p)))
    acc = curve.point_add(2, xxP, curve.point_neg(2, xP))
    acc = curve.point_add(2, acc, curve.point_neg(2, p))
    acc = curve.point_add(2, acc, xpsiP)
    acc = curve.point_add(2, acc, curve.point_neg(2, psiP))
    return curve.point_add(2, acc, psi2_2P)


# -- full pipeline --------------------------------------------------------------------


def map_to_g2(u0, u1):
    """Device map: two field elements per message -> projective G2 point.
    u0/u1 are stacked into one doubled leading batch so SSWU + the isogeny
    compile (and dispatch) ONCE instead of twice."""
    u = jnp.stack([u0, u1], axis=0)
    q = iso_map(*map_to_curve_sswu(u))
    return clear_cofactor(curve.point_add(2, q[0], q[1]))


def hash_to_curve_g2(msgs: list[bytes], dst: bytes):
    """[n messages] -> [n, 6, 25] projective G2 points (device)."""
    u0, u1 = hash_to_field_batch(msgs, dst)
    return map_to_g2(u0, u1)
