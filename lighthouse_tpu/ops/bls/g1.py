"""G1 (E(Fq): y^2 = x^3 + 4) device kernels.

Thin instantiation of curve.py with k = 1 plus G1-specific pieces: the GLV
endomorphism subgroup check and batched decompression. Parity targets:
``/root/reference/crypto/bls/src/generic_public_key.rs`` (48-byte pubkeys) and
blst ``key_validate`` used at ``impls/blst.rs:75``.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import curve, fq, plans, tower
from ..bls_oracle.fields import P
from ..bls_oracle import curves as _oc

K = 1

# GLV endomorphism phi(x, y) = (BETA x, y) acts as multiplication by -u^2 on the
# r-order subgroup (BETA is the cube root of unity below; verified against the
# oracle in tests). Subgroup check: phi(P) == -[u^2] P  (Scott, eprint 2021/1130).
BETA = 0x5F19672FDF76CE51BA69C6076A0F77EADDB3A93BE6F89688DE17D813620A00022E01FFFFFFFEFFFE

from ..bls_oracle.fields import BLS_X as _X

U2 = _X * _X  # positive 127-bit scalar

_BETA_M = jnp.asarray(fq.int_to_limbs(BETA * fq.R_MONT % P))


def generator(shape=()):
    g = curve.from_affine(
        K, fq.from_int(_oc.G1_X)[None, :], fq.from_int(_oc.G1_Y)[None, :]
    )
    return jnp.broadcast_to(g, shape + (3, fq.NLIMBS)) if shape else g


def add(p, q):
    return curve.point_add(K, p, q)


def dbl(p):
    return curve.point_dbl(K, p)


def neg(p):
    return curve.point_neg(K, p)


def scale_u64(p, scalars):
    return curve.scale_u64(K, p, scalars)


def scale_fixed(p, e: int):
    return curve.scale_fixed(K, p, e)


def psum(pts, valid=None):
    return curve.point_sum(K, pts, valid)


def to_affine(p):
    return curve.to_affine(K, p)


def is_inf(p):
    return curve.is_inf(K, p)


def eq(p, q):
    return curve.point_eq(K, p, q)


def phi(p):
    """GLV endomorphism on projective coords: (BETA X : Y : Z)."""
    x = fq.mont_mul(p[..., 0:1, :], jnp.broadcast_to(_BETA_M, p.shape[:-2] + (1, fq.NLIMBS)))
    return jnp.concatenate([x, p[..., 1:, :]], axis=-2)


def subgroup_check(p):
    """phi(P) == -[u^2]P. Infinity passes (blst key_validate rejects infinity
    separately at the key-validation layer)."""
    return curve.point_eq(K, phi(p), curve.point_neg(K, scale_fixed(p, U2)))


def on_curve(p):
    """Projective on-curve check Y^2 Z == X^3 + 4 Z^3 (infinity passes)."""
    x, y, z = p[..., 0:1, :], p[..., 1:2, :], p[..., 2:3, :]
    y2z = fq.mont_mul(fq.mont_mul(y, y), z)
    x3 = fq.mont_mul(fq.mont_mul(x, x), x)
    z3 = fq.mont_mul(fq.mont_mul(z, z), z)
    rhs = plans.carry_norm(x3 + z3 * np.uint64(4))
    return tower.t_eq(y2z, rhs)


# --------------------------------------------------------------------------------------
# Batched decompression: x limbs + sign flag -> affine point (+ validity)
# --------------------------------------------------------------------------------------


def decompress(x_mont, s_flag):
    """x_mont [..., 1, 25] Montgomery-form x; s_flag [...] (0/1 lex-largest-y bit).
    Returns (point [..., 3, 25], ok [...]): ok = x is on curve. Infinity/flag
    parsing happens host-side (the byte layer)."""
    x = x_mont
    x3b = plans.carry_norm(
        fq.mont_mul(fq.mont_mul(x, x), x) + tower.one(1, x.shape[:-2]) * np.uint64(4)
    )
    y = fq.sqrt_candidate(x3b[..., 0, :])
    ok = fq.eq(fq.canonical(fq.mont_mul(y, y)), fq.normalize(x3b[..., 0, :]))
    big = fq.lex_gt_half(y)
    y = plans.carry_norm(fq.select(big ^ (s_flag == 1), fq.neg(y), y))
    return curve.from_affine(K, x, y[..., None, :]), ok


# --------------------------------------------------------------------------------------
# Host conversions (oracle interop)
# --------------------------------------------------------------------------------------


def from_oracle(p):
    """Oracle affine point (or None) -> device projective [3, 25]."""
    if p is None:
        return curve.inf_point(K)
    return jnp.concatenate(
        [fq.from_int(p[0])[None], fq.from_int(p[1])[None], tower.one(1)], axis=0
    )


def from_oracle_batch(pts):
    return jnp.stack([from_oracle(p) for p in pts])


def to_oracle(p):
    """Device projective point -> oracle affine (or None)."""
    if bool(np.asarray(is_inf(p))):
        return None
    x, y = to_affine(p)
    return (fq.to_int(np.asarray(x)[0]), fq.to_int(np.asarray(y)[0]))
