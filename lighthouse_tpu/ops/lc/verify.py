"""Batched light-client update verification: ONE pairing check per batch.

Per session (spec ``process_light_client_update`` signature core): the
participants' aggregated committee pubkey P_i signs the attested header's
signing root m_i, so the check is e(P_i, H(m_i)) == e(G1, sig_i). Every
session shares the G1 generator on the signature side, so B heterogeneous
sessions (distinct periods, bitfields, attested roots) fold under
Fiat-Shamir weights r_i into the blst ``verify_multiple_aggregate_
signatures`` shape::

    prod_i e(r_i * P_i, H(m_i)) * e(-G1, sum_i r_i * sig_i) == 1

— B+1 pairs, one shared-accumulator Miller product, ONE final
exponentiation. P_i is a bitfield-masked sum over a device-resident
per-period committee pubkey cache ``[P, C, 3, 25]``: session i gathers
row ``pidx[i]``, so a batch mixing sync-committee periods still runs as
one dispatch.

The security prologue mirrors ``bls/tpu_backend._set_prologue`` (blst's
``sigs_groupcheck``): G2 subgroup check via psi(Q) == [x]Q fused with the
random scaling into one windowed pass, infinity rejection for both the
aggregate pubkey and the signature, well-formedness + on-curve flags from
decompression, and an empty-bitfield reject. A session failing ANY check
fails the whole batch (callers bisect, exactly like the attestation
firehose).

``PROBE`` counts trace-time pairing checks/pairs: jit tracing runs this
module's Python once per compile, so a probe of exactly one
``multi_pairing_is_one`` per batch is a property of the LOWERED graph,
not of runtime logging (bench ``--light-clients`` embeds the record).

Staged like the firehose hot path (``_gathered_kernel``'s three-stage
design — one fused program compiled superlinearly, the r3 pathology):
``lc_h2c`` / ``lc_prep`` / ``lc_pair`` are separate compile units and
``lc_batch_check`` is their composition (what the bounds registry and the
compile probe lower).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..bls import curve, fq, g1, g2, h2c, pairing
from ..bls_oracle import curves as _oc
from ..bls_oracle.fields import BLS_X

# trace-time instrumentation (see module docstring)
PROBE = {"pairing_checks": 0, "pairs": 0, "agg_sums": 0}

_MINUS_G1 = _oc.g1_neg(_oc.g1_generator())
_MG1_X = fq.from_int(_MINUS_G1[0])
_MG1_Y = fq.from_int(_MINUS_G1[1])


def lc_h2c(u0, u1):
    """Stage 1: device hash-to-curve for the signing roots.

    u0/u1 [B, 2, 25] hash_to_field residues (host SHA-256) -> affine
    G2 message points (mx, my) [B, 2, 25] each."""
    return g2.to_affine(h2c.map_to_g2(u0, u1))


def lc_prep(cache, pidx, bits, sxc0, sxc1, s_flag, sig_wf, scalars, valid):
    """Stage 2: committee gather + masked aggregation + security prologue.

    cache  [P, C, 3, 25]  per-period committee pubkeys (projective); each
                          row p holds period p's C decompressed keys
    pidx   [B] int32      per-session cache row (heterogeneous periods)
    bits   [B, C] bool    sync-committee participation bitfields
    sxc0/sxc1 [B, 25]     raw signature x limbs (flags cleared)
    s_flag [B] uint64     lex-sign bit; sig_wf [B] bool well-formed encoding
    scalars [B] uint64    Fiat-Shamir weights; valid [B] bool real sessions

    Returns affine (pkx, pky, sax, say) for the pairing stage plus the
    per-session set_ok flags."""
    sig, on_curve = g2.decompress(jnp.stack([sxc0, sxc1], axis=-2), s_flag)
    pts = jnp.take(cache, pidx, axis=0)              # [B, C, 3, 25]
    pk_agg = curve.point_sum(
        1, jnp.moveaxis(pts, 1, 0), jnp.moveaxis(bits, 1, 0)
    )
    PROBE["agg_sums"] += 1
    # blst sigs_groupcheck: psi(Q) == [x]Q (x < 0: [x]Q = -[|x|]Q), fused
    # with the Fiat-Shamir scaling into one windowed pass over sig
    accs = curve.scale_u64_with_fixed(2, sig, scalars, (-BLS_X,))
    sig_scaled, abs_x_sig = accs[0], accs[1]
    sig_grp = curve.point_eq(2, g2.psi(sig), curve.point_neg(2, abs_x_sig))
    set_ok = ~valid | (sig_grp & ~g1.is_inf(pk_agg) & ~g2.is_inf(sig))
    set_ok = set_ok & (~valid | (sig_wf & on_curve & jnp.any(bits, axis=1)))
    pk_scaled = g1.scale_u64(pk_agg, scalars)
    sig_sum = g2.psum(sig_scaled, valid)
    pkx, pky = g1.to_affine(pk_scaled)
    sax, say = g2.to_affine(sig_sum)
    return pkx, pky, sax, say, set_ok


def lc_pair(pkx, pky, sax, say, mxa, mya, set_ok, valid):
    """Stage 3: B+1-pair Miller product + ONE final exponentiation +
    verdict. The -G1 generator pairs with the scaled signature sum."""
    b = valid.shape[0]
    px = jnp.concatenate([pkx[:, 0, :], _MG1_X[None]], axis=0)
    py = jnp.concatenate([pky[:, 0, :], _MG1_Y[None]], axis=0)
    qx = jnp.concatenate([mxa, sax[None]], axis=0)
    qy = jnp.concatenate([mya, say[None]], axis=0)
    pair_valid = jnp.concatenate([valid, jnp.ones((1,), dtype=bool)])
    PROBE["pairing_checks"] += 1
    PROBE["pairs"] += b + 1
    ok = pairing.multi_pairing_is_one(px, py, qx, qy, pair_valid)
    return ok & jnp.all(set_ok) & jnp.any(valid)


def lc_batch_check(cache, pidx, bits, u0, u1, sxc0, sxc1, s_flag, sig_wf,
                   scalars, valid):
    """The full batched update-check graph (stage composition): scalar
    bool — the WHOLE batch of sessions verifies. Padded rows carry
    valid=False and contribute the identity everywhere."""
    mxa, mya = lc_h2c(u0, u1)
    pkx, pky, sax, say, set_ok = lc_prep(
        cache, pidx, bits, sxc0, sxc1, s_flag, sig_wf, scalars, valid
    )
    return lc_pair(pkx, pky, sax, say, mxa, mya, set_ok, valid)
