"""Device light-client kernels: batched sync-committee update verification.

The third cryptosystem consumer on the plan compiler (ISSUE 17), after the
BLS firehose and the KZG cell engine. Everything rides ``ops/bls``: the
25x16-bit limb layout and ``fq._conv_product`` seam (all three
``LIGHTHOUSE_CONV_IMPL`` backends unchanged), ``h2c.map_to_g2`` for the
signing roots, ``curve``/``g1``/``g2`` for the masked committee
aggregation and the security prologue, and the shared-accumulator
``pairing.miller_loop_product`` for the ONE combined pairing check per
batch.

* ``verify`` — the batched update-check graph: per-session participant
  pubkey aggregation as a bitfield-masked G1 sum over a device-resident
  per-period committee cache (heterogeneous periods gather different
  cache rows in the SAME dispatch), signature decompression + subgroup
  checks, Fiat-Shamir random scaling, and one B+1-pair Miller product +
  one final exponentiation for the whole batch.
"""

from . import verify  # noqa: F401
