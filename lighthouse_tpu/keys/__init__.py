"""Key management: EIP-2333 derivation, EIP-2335 keystores, EIP-2386 wallets.

Twin of ``crypto/eth2_key_derivation``, ``crypto/eth2_keystore``,
``crypto/eth2_wallet``.
"""

from .derivation import derive_child_sk, derive_master_sk, path_to_nodes, derive_sk_from_path
from .keystore import Keystore, KeystoreError
from .wallet import Wallet
