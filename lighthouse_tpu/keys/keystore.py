"""EIP-2335 keystores (scrypt/pbkdf2 + AES-128-CTR + sha256 checksum).

Twin of ``/root/reference/crypto/eth2_keystore`` (``Keystore::{encrypt,
decrypt}``). JSON layout, KDF parameters, and password normalization (NFKD,
control-char stripping) match the EIP so keystores interchange with the
reference and other clients.
"""

from __future__ import annotations

import hashlib
import json
import os
import unicodedata
import uuid

try:  # gated: interop-key flows (vc --interop-validators) need no AES at all
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    _HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - environment-dependent
    Cipher = algorithms = modes = None
    _HAVE_CRYPTOGRAPHY = False

from ..ops.bls_oracle import ciphersuite as _cs
from ..ops.bls_oracle import curves as _oc


class KeystoreError(Exception):
    pass


def normalize_password(password: str) -> bytes:
    """EIP-2335: NFKD normalize, strip C0/C1/DEL control codes."""
    norm = unicodedata.normalize("NFKD", password)
    stripped = "".join(
        c for c in norm
        if not (ord(c) < 0x20 or 0x7F <= ord(c) <= 0x9F)
    )
    return stripped.encode("utf-8")


def _aes128ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    if not _HAVE_CRYPTOGRAPHY:
        raise KeystoreError(
            "EIP-2335 keystore encryption needs the 'cryptography' package"
        )
    c = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return c.update(data) + c.finalize()


class Keystore:
    def __init__(self, obj: dict):
        self.obj = obj

    # -- construction -----------------------------------------------------------

    @classmethod
    def encrypt(
        cls,
        secret: bytes,
        password: str,
        path: str = "",
        kdf: str = "scrypt",
        pubkey: str | None = None,
        description: str = "",
    ) -> "Keystore":
        if len(secret) != 32:
            raise KeystoreError("secret must be 32 bytes")
        pw = normalize_password(password)
        salt = os.urandom(32)
        iv = os.urandom(16)
        if kdf == "scrypt":
            dk = hashlib.scrypt(pw, salt=salt, n=262144, r=8, p=1, dklen=32,
                                maxmem=512 * 1024 * 1024)
            kdf_module = {
                "function": "scrypt",
                "params": {"dklen": 32, "n": 262144, "p": 1, "r": 8,
                           "salt": salt.hex()},
                "message": "",
            }
        elif kdf == "pbkdf2":
            dk = hashlib.pbkdf2_hmac("sha256", pw, salt, 262144, dklen=32)
            kdf_module = {
                "function": "pbkdf2",
                "params": {"dklen": 32, "c": 262144, "prf": "hmac-sha256",
                           "salt": salt.hex()},
                "message": "",
            }
        else:
            raise KeystoreError(f"unsupported kdf {kdf}")
        cipher_message = _aes128ctr(dk[:16], iv, secret)
        checksum = hashlib.sha256(dk[16:32] + cipher_message).digest()
        if pubkey is None:
            sk = int.from_bytes(secret, "big")
            pubkey = _oc.g1_compress(_cs.sk_to_pk(sk)).hex()
        obj = {
            "crypto": {
                "kdf": kdf_module,
                "checksum": {
                    "function": "sha256", "params": {},
                    "message": checksum.hex(),
                },
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": iv.hex()},
                    "message": cipher_message.hex(),
                },
            },
            "description": description,
            "pubkey": pubkey,
            "path": path,
            "uuid": str(uuid.uuid4()),
            "version": 4,
        }
        return cls(obj)

    def decrypt(self, password: str) -> bytes:
        crypto = self.obj["crypto"]
        pw = normalize_password(password)
        kdf = crypto["kdf"]
        params = kdf["params"]
        salt = bytes.fromhex(params["salt"])
        if kdf["function"] == "scrypt":
            dk = hashlib.scrypt(
                pw, salt=salt, n=params["n"], r=params["r"], p=params["p"],
                dklen=params["dklen"], maxmem=512 * 1024 * 1024,
            )
        elif kdf["function"] == "pbkdf2":
            if params.get("prf", "hmac-sha256") != "hmac-sha256":
                raise KeystoreError("unsupported prf")
            dk = hashlib.pbkdf2_hmac(
                "sha256", pw, salt, params["c"], dklen=params["dklen"]
            )
        else:
            raise KeystoreError(f"unsupported kdf {kdf['function']}")
        cipher_message = bytes.fromhex(crypto["cipher"]["message"])
        checksum = hashlib.sha256(dk[16:32] + cipher_message).digest()
        if checksum.hex() != crypto["checksum"]["message"]:
            raise KeystoreError("invalid password (checksum mismatch)")
        if crypto["cipher"]["function"] != "aes-128-ctr":
            raise KeystoreError("unsupported cipher")
        iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
        return _aes128ctr(dk[:16], iv, cipher_message)

    # -- (de)serialization ------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(self.obj)

    @classmethod
    def from_json(cls, data: str) -> "Keystore":
        obj = json.loads(data)
        if obj.get("version") != 4:
            raise KeystoreError("unsupported keystore version")
        return cls(obj)

    @property
    def pubkey(self) -> str:
        return self.obj["pubkey"]

    @property
    def path(self) -> str:
        return self.obj.get("path", "")

    @property
    def uuid(self) -> str:
        return self.obj["uuid"]
