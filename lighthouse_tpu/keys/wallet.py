"""EIP-2386 hierarchical wallets over EIP-2335 keystores + EIP-2333 paths.

Twin of ``/root/reference/crypto/eth2_wallet`` (``Wallet``): an encrypted
seed plus a ``nextaccount`` counter; validator keys derive at
m/12381/3600/{i}/0/0 (voting) and .../0 (withdrawal).
"""

from __future__ import annotations

import json
import os
import uuid as _uuid

from .derivation import derive_sk_from_path
from .keystore import Keystore, KeystoreError


class Wallet:
    def __init__(self, obj: dict):
        self.obj = obj

    @classmethod
    def create(
        cls, name: str, password: str, seed: bytes | None = None,
        kdf: str = "pbkdf2",
    ) -> "Wallet":
        seed = seed if seed is not None else os.urandom(32)
        ks = Keystore.encrypt(seed, password, kdf=kdf, pubkey="")
        obj = {
            "crypto": ks.obj["crypto"],
            "name": name,
            "nextaccount": 0,
            "type": "hierarchical deterministic",
            "uuid": str(_uuid.uuid4()),
            "version": 1,
        }
        return cls(obj)

    def decrypt_seed(self, password: str) -> bytes:
        ks = Keystore({"crypto": self.obj["crypto"], "version": 4,
                       "pubkey": "", "uuid": self.obj["uuid"]})
        return ks.decrypt(password)

    def next_validator(
        self, wallet_password: str, voting_password: str,
        withdrawal_password: str | None = None,
    ):
        """Derive the next validator's keystores; bumps nextaccount."""
        seed = self.decrypt_seed(wallet_password)
        i = self.obj["nextaccount"]
        voting_path = f"m/12381/3600/{i}/0/0"
        withdrawal_path = f"m/12381/3600/{i}/0"
        voting_sk = derive_sk_from_path(seed, voting_path)
        withdrawal_sk = derive_sk_from_path(seed, withdrawal_path)
        voting = Keystore.encrypt(
            voting_sk.to_bytes(32, "big"), voting_password,
            path=voting_path, kdf="pbkdf2",
        )
        withdrawal = Keystore.encrypt(
            withdrawal_sk.to_bytes(32, "big"),
            withdrawal_password or voting_password,
            path=withdrawal_path, kdf="pbkdf2",
        )
        self.obj["nextaccount"] = i + 1
        return voting, withdrawal

    def to_json(self) -> str:
        return json.dumps(self.obj)

    @classmethod
    def from_json(cls, data: str) -> "Wallet":
        obj = json.loads(data)
        if obj.get("version") != 1:
            raise KeystoreError("unsupported wallet version")
        return cls(obj)

    @property
    def name(self) -> str:
        return self.obj["name"]

    @property
    def nextaccount(self) -> int:
        return self.obj["nextaccount"]
