"""EIP-2333 hierarchical BLS key derivation.

Twin of ``/root/reference/crypto/eth2_key_derivation`` (``DerivedKey``): the
lamport-from-parent tree with hkdf_mod_r at each node, plus EIP-2334 path
parsing (m/12381/3600/i/0/0 style paths).
"""

from __future__ import annotations

import hashlib
import hmac

from ..ops.bls_oracle.fields import R as CURVE_ORDER

_SALT = b"BLS-SIG-KEYGEN-SALT-"


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_mod_r(ikm: bytes, key_info: bytes = b"") -> int:
    salt = _SALT
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % CURVE_ORDER
    return sk


def _ikm_to_lamport_sk(ikm: bytes, salt: bytes) -> list[bytes]:
    prk = _hkdf_extract(salt, ikm)
    okm = _hkdf_expand(prk, b"", 255 * 32)
    return [okm[i * 32 : (i + 1) * 32] for i in range(255)]


def _parent_sk_to_lamport_pk(parent_sk: int, index: int) -> bytes:
    salt = index.to_bytes(4, "big")
    ikm = parent_sk.to_bytes(32, "big")
    lamport_0 = _ikm_to_lamport_sk(ikm, salt)
    not_ikm = bytes(b ^ 0xFF for b in ikm)
    lamport_1 = _ikm_to_lamport_sk(not_ikm, salt)
    combined = b"".join(
        hashlib.sha256(chunk).digest() for chunk in lamport_0 + lamport_1
    )
    return hashlib.sha256(combined).digest()


def derive_master_sk(seed: bytes) -> int:
    if len(seed) < 32:
        raise ValueError("seed must be >= 32 bytes (EIP-2333)")
    return hkdf_mod_r(seed)


def derive_child_sk(parent_sk: int, index: int) -> int:
    return hkdf_mod_r(_parent_sk_to_lamport_pk(parent_sk, index))


def path_to_nodes(path: str) -> list[int]:
    """EIP-2334 path 'm/12381/3600/0/0/0' -> node indices."""
    parts = path.strip().split("/")
    if parts[0] != "m":
        raise ValueError("path must start with m")
    nodes = []
    for p in parts[1:]:
        if not p.isdigit():
            raise ValueError(f"invalid path node {p!r}")
        n = int(p)
        if n >= 2**32:
            raise ValueError("node out of range")
        nodes.append(n)
    return nodes


def derive_sk_from_path(seed: bytes, path: str) -> int:
    sk = derive_master_sk(seed)
    for node in path_to_nodes(path):
        sk = derive_child_sk(sk, node)
    return sk
