"""Overload protection: deadline propagation, admission control, adaptive
client pacing (ISSUE 18).

Four layers, each usable alone:

* :mod:`.deadline` — ingest-timestamp + per-work-type deadlines; queues drop
  expired work before any BLS/device dispatch.
* :mod:`.monitor` — ``LoadMonitor`` folds queue depth / drop rate /
  resilience-ladder state / worker lag into HEALTHY -> BUSY -> SATURATED;
  fails CLOSED (SATURATED) when sampling itself fails. Injection stage:
  ``loadshed.monitor_sample``.
* :mod:`.priorities` — P0/P1 HTTP route split and Req/Resp method priority
  classes; shedding is strictly lowest-priority-first.
* :mod:`.adaptive` — per-peer EWMA RTT timeouts (RFC 6298 shape), jittered
  exponential backoff with per-peer cooldown, and client-side self-limiting
  against a peer's rate quotas.
"""

from __future__ import annotations

from .adaptive import (  # noqa: F401
    BackoffPolicy,
    RttEstimator,
    SelfLimiter,
)
from .deadline import (  # noqa: F401
    DEFAULT_SLOT_SECONDS,
    budget_for,
    deadline_for,
    expired,
)
from .monitor import (  # noqa: F401
    AdmissionLevel,
    LoadMonitor,
    LoadThresholds,
)
from .priorities import (  # noqa: F401
    METHOD_PRIORITY,
    P0_ROUTES,
    is_p0_route,
    method_priority,
    shed_floor,
    should_shed_method,
)
