"""Adaptive client-side pacing: RTT-derived timeouts, jittered backoff,
and self-limiting against a peer's published rate quotas.

Three policies, all host-side and allocation-free on the hot path:

* ``RttEstimator`` — Jacobson/Karels RTO (RFC 6298): per-peer smoothed RTT
  + variance derive the Req/Resp timeout instead of a fixed 10 s, with
  exponential backoff on timeout until a fresh sample lands.
* ``BackoffPolicy`` — jittered exponential backoff with a per-peer
  cooldown, for sync's peer-rotation retry loop: a failing peer is not
  re-asked until its cooldown expires, and consecutive failures grow it.
* ``SelfLimiter`` — a client-side shadow of the peer's token buckets
  (``rate_limiter.DEFAULT_QUOTAS`` scaled by a safety margin): an honest
  node paces itself below the peer's refill rate so it NEVER trips the
  remote limiter and never takes the -20 score hit.

Jitter is seeded from ``LIGHTHOUSE_RESILIENCE_SEED`` (the same knob that
pins the resilience retry jitter) so chaos runs stay deterministic.
"""

from __future__ import annotations

import os
import random
import threading
import time

# NOTE: ..network.rate_limiter is imported lazily inside SelfLimiter.
# A module-level import would execute network/__init__ (which imports
# socket_transport, which imports this module) whenever loadshed loads
# before the network package — a hard import cycle.


class RttEstimator:
    """Per-peer adaptive Req/Resp timeout (RFC 6298 shape).

    Not internally locked: the owning transport serializes access under its
    own lock (never while blocking on the wire).
    """

    def __init__(self, min_timeout: float = 0.25, max_timeout: float = 10.0,
                 k: float = 4.0, alpha: float = 0.125, beta: float = 0.25):
        self.min_timeout = float(min_timeout)
        self.max_timeout = float(max_timeout)
        self.k = float(k)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.srtt: float | None = None
        self.rttvar: float = 0.0
        self.samples = 0
        self._backoff = 1.0

    def observe(self, rtt: float) -> None:
        rtt = max(float(rtt), 1e-6)
        if self.srtt is None:
            self.srtt = rtt
            self.rttvar = rtt / 2.0
        else:
            self.rttvar = (
                (1.0 - self.beta) * self.rttvar
                + self.beta * abs(self.srtt - rtt)
            )
            self.srtt = (1.0 - self.alpha) * self.srtt + self.alpha * rtt
        self.samples += 1
        self._backoff = 1.0  # a fresh sample resets timeout inflation

    def on_timeout(self) -> None:
        """Exponentially inflate until a successful sample arrives."""
        self._backoff = min(self._backoff * 2.0, 16.0)

    def timeout(self) -> float:
        """Current request timeout: srtt + k*rttvar, inflated by timeout
        backoff, clamped to [min_timeout, max_timeout]. With no samples yet
        the ceiling applies (the conservative legacy behaviour)."""
        if self.srtt is None:
            return self.max_timeout
        rto = (self.srtt + self.k * max(self.rttvar, 1e-3)) * self._backoff
        return min(self.max_timeout, max(self.min_timeout, rto))


def _default_seed():
    s = os.environ.get("LIGHTHOUSE_RESILIENCE_SEED")
    return int(s) if s else None


class BackoffPolicy:
    """Jittered exponential backoff with per-peer cooldown.

    ``record_failure(peer)`` starts/grows the peer's cooldown; ``ready``
    gates rotation so a failing peer is skipped until it expires.
    ``attempt_delay(n)`` is the inter-attempt sleep inside one retry loop
    (0 for the first attempt).
    """

    def __init__(self, base: float = 0.2, factor: float = 2.0,
                 max_attempt_delay: float = 2.0, cooldown_cap: float = 30.0,
                 jitter: float = 0.5, seed=None, clock=time.monotonic):
        self.base = float(base)
        self.factor = float(factor)
        self.max_attempt_delay = float(max_attempt_delay)
        self.cooldown_cap = float(cooldown_cap)
        self.jitter = float(jitter)
        self._clock = clock
        self._rng = random.Random(
            seed if seed is not None else _default_seed()
        )
        self._lock = threading.Lock()
        self._fails: dict[str, int] = {}
        self._until: dict[str, float] = {}

    def _jittered(self, delay: float) -> float:
        # full-jitter lower half: uniform in [delay*(1-jitter), delay]
        with self._lock:
            u = self._rng.random()
        return delay * (1.0 - self.jitter * u)

    def record_failure(self, peer: str) -> float:
        """Grow ``peer``'s cooldown; returns the cooldown applied (s)."""
        now = self._clock()
        with self._lock:
            n = self._fails.get(peer, 0) + 1
            self._fails[peer] = n
            delay = min(self.base * self.factor ** (n - 1),
                        self.cooldown_cap)
            delay *= 1.0 - self.jitter * self._rng.random()
            self._until[peer] = now + delay
        return delay

    def record_success(self, peer: str) -> None:
        with self._lock:
            self._fails.pop(peer, None)
            self._until.pop(peer, None)

    def ready(self, peer: str) -> bool:
        now = self._clock()
        with self._lock:
            return now >= self._until.get(peer, 0.0)

    def cooldown_remaining(self, peer: str) -> float:
        now = self._clock()
        with self._lock:
            return max(0.0, self._until.get(peer, 0.0) - now)

    def failures(self, peer: str) -> int:
        with self._lock:
            return self._fails.get(peer, 0)

    def attempt_delay(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (0-based) within one loop."""
        if attempt <= 0:
            return 0.0
        return self._jittered(
            min(self.base * self.factor ** (attempt - 1),
                self.max_attempt_delay)
        )

    def forget(self, peer: str) -> None:
        self.record_success(peer)


class SelfLimiter:
    """Client-side shadow of a peer's Req/Resp rate limiter.

    Before sending, ``throttle(peer, method, cost)`` spends from a local
    bucket mirroring the peer's quota scaled by ``margin`` (< 1.0 absorbs
    clock skew). It returns the seconds the caller must wait before the
    send is safe (0.0 = send now — the tokens are already spent).
    """

    def __init__(self, quotas=None, margin: float = 0.9,
                 clock=time.monotonic):
        from ..network.rate_limiter import DEFAULT_QUOTAS, Quota, RateLimiter

        src = DEFAULT_QUOTAS if quotas is None else quotas
        self.margin = float(margin)
        scaled = {
            m: Quota(max(1.0, q.max_tokens * self.margin), q.period)
            for m, q in src.items()
        }
        self._limiter = RateLimiter(quotas=scaled, clock=clock)

    def throttle(self, peer: str, method: str, cost: float = 1.0) -> float:
        if self._limiter.allow(peer, method, cost):
            return 0.0
        return self._limiter.wait_time(peer, method, cost)

    def wait_time(self, peer: str, method: str, cost: float = 1.0) -> float:
        return self._limiter.wait_time(peer, method, cost)
