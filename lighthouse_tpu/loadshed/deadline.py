"""Deadline propagation for ingest work.

Every unit of work entering the node — gossip attestation/aggregate/block,
Req/Resp request, HTTP request — is stamped with the monotonic time it left
the wire plus a deadline derived from its type. Queues drop expired work
BEFORE it reaches any BLS/device dispatch: a stale attestation past its
inclusion window or an RPC request whose client already gave up only wastes
device cycles that admitted work is waiting for (the reference expresses the
same idea as per-queue TTLs in ``beacon_processor/src/lib.rs``; here the
deadline rides the work item itself so every hop can check it).

All times are ``time.monotonic()`` — deadlines never cross processes.
"""

from __future__ import annotations

import time

# Per-work-type deadline budgets, in seconds from wire ingest, scaled by
# slot seconds where the protocol defines the useful lifetime:
#   - unaggregated attestations are useless once the aggregation cut-off for
#     their slot has passed (~1 slot of slack covers clock skew + late votes)
#   - aggregates ride the same window
#   - sync-committee messages are per-slot only
#   - blocks and RPC work stay useful much longer (sync, backfill)
# Values are expressed in SLOTS; ``budget_for`` multiplies by the spec's
# seconds-per-slot (default mainnet 12s).
_SLOT_BUDGETS = {
    "GossipAttestation": 1.0,
    "GossipAggregate": 1.0,
    "UnknownBlockAttestation": 2.0,
    "UnknownBlockAggregate": 2.0,
    "GossipSyncSignature": 1.0,
    "GossipSyncContribution": 1.0,
}

# Flat budgets in seconds for work whose lifetime is a client-side timeout,
# not a protocol window (Req/Resp servicing: the default client rpc_timeout
# is the longest any well-behaved requester will wait).
_FLAT_BUDGETS = {
    "Status": 10.0,
    "BlocksByRangeRequest": 10.0,
    "BlocksByRootsRequest": 10.0,
    "LightClientUpdate": 10.0,
    "ApiRequestP0": 10.0,
    "ApiRequestP1": 10.0,
}

DEFAULT_SLOT_SECONDS = 12.0


def budget_for(work_type, slot_seconds: float = DEFAULT_SLOT_SECONDS):
    """Deadline budget in seconds for ``work_type`` (None = no deadline).

    ``work_type`` may be a WorkType enum member or its name string.
    """
    name = getattr(work_type, "name", work_type)
    slots = _SLOT_BUDGETS.get(name)
    if slots is not None:
        return slots * float(slot_seconds)
    return _FLAT_BUDGETS.get(name)


def deadline_for(work_type, now: float | None = None,
                 slot_seconds: float = DEFAULT_SLOT_SECONDS):
    """Absolute monotonic deadline for ``work_type`` ingested at ``now``
    (None when the type carries no deadline)."""
    budget = budget_for(work_type, slot_seconds)
    if budget is None:
        return None
    return (time.monotonic() if now is None else now) + budget


def expired(deadline, now: float | None = None) -> bool:
    """True iff ``deadline`` (absolute monotonic, or None) has passed."""
    if deadline is None:
        return False
    return (time.monotonic() if now is None else now) > deadline
