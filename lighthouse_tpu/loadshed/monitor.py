"""Admission-level monitor: HEALTHY -> BUSY -> SATURATED.

A ``LoadMonitor`` folds queue depths, drop rates, resilience-ladder health
and worker lag from any number of attached sources into one admission level
that every shedding surface (HTTP API gate, Req/Resp method shedding) reads.

Sampling is PASSIVE: ``level()`` recomputes from the sources at most once
per ``min_sample_interval`` — no monitor thread exists, so there is nothing
to join and nothing that can wedge. A source that raises (or an injected
fault on the ``loadshed.monitor_sample`` stage) drives the monitor to
SATURATED: when we cannot see the load, we fail CLOSED toward shedding
deferrable work, never toward unbounded admission.

Source protocol: a zero-arg callable returning a dict with any subset of
  fill        float 0..1   worst queue-fill fraction this source sees
  submitted   int          cumulative accepted work (for drop-rate windows)
  dropped     int          cumulative dropped work
  lag_s       float        age of the oldest queued item (worker lag)
  degraded    bool         a resilience ladder is off its primary rung
  quarantined bool         a resilience ladder is quarantined / exhausted
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass

from ..resilience import maybe_fault
from ..utils.metrics import ADMISSION_LEVEL, ADMISSION_TRANSITIONS


class AdmissionLevel(enum.IntEnum):
    HEALTHY = 0
    BUSY = 1
    SATURATED = 2


@dataclass
class LoadThresholds:
    """Trip points. Defaults: queues half full or any recent drops or a
    degraded ladder = BUSY; queues near capacity, sustained drop rate, long
    worker lag or a quarantined ladder = SATURATED."""

    busy_fill: float = 0.50
    saturated_fill: float = 0.90
    busy_lag_s: float = 1.0
    saturated_lag_s: float = 4.0
    saturated_drop_rate: float = 0.05   # drops / submissions over the window
    min_sample_interval: float = 0.05


class LoadMonitor:
    def __init__(self, thresholds: LoadThresholds | None = None,
                 clock=time.monotonic):
        self.thresholds = thresholds or LoadThresholds()
        self._clock = clock
        self._lock = threading.Lock()
        self._sources: list[tuple[str, object]] = []
        self._level = AdmissionLevel.HEALTHY
        self._forced: AdmissionLevel | None = None
        self._last_sample_t = float("-inf")
        # per-source cumulative (submitted, dropped) at the previous sample,
        # for windowed drop-rate computation
        self._prev: dict[str, tuple[int, int]] = {}
        self._transitions: list[tuple[float, str, str]] = []
        self._sample_failures = 0

    # -- sources -----------------------------------------------------------

    def add_source(self, name: str, fn) -> None:
        with self._lock:
            self._sources.append((name, fn))

    def attach_processor(self, proc) -> None:
        """Sample a BeaconProcessor's queues + drop counters. Reads are
        GIL-atomic snapshots (len / int loads); sampling never takes the
        processor's lock, so the monitor can't add scheduler contention."""

        def sample():
            lengths = proc.config.queue_lengths
            fill = 0.0
            for t, q in proc.queues.items():
                limit = lengths.limit(t)
                if limit > 0:
                    fill = max(fill, len(q) / limit)
            return {
                "fill": fill,
                "submitted": sum(proc.processed.values()),
                "dropped": sum(proc.dropped.values()),
            }

        self.add_source("beacon_processor", sample)

    def attach_batcher(self, batcher) -> None:
        """Sample a firehose AdaptiveBatcher's intake depth + shed counts."""

        def sample():
            cap = max(1, batcher.config.intake_capacity)
            depth = batcher.depth()
            out = {
                "fill": depth / cap,
                "submitted": batcher.submitted,
                "dropped": batcher.dropped_total,
            }
            oldest = batcher.oldest_age()
            if oldest is not None:
                out["lag_s"] = oldest
            return out

        self.add_source("firehose_batcher", sample)

    def attach_supervisors(self, snapshot_fn=None) -> None:
        """Fold resilience-ladder state in: any DEGRADED domain is at least
        BUSY, any QUARANTINED/exhausted domain is SATURATED."""
        if snapshot_fn is None:
            from ..resilience import snapshot_all as snapshot_fn  # noqa: N813

        def sample():
            snaps = snapshot_fn()
            states = [s.get("state", "HEALTHY") for s in snaps.values()]
            return {
                "degraded": any(s == "DEGRADED" for s in states),
                "quarantined": any(
                    s == "QUARANTINED" or snap.get("exhausted")
                    for s, snap in zip(states, snaps.values())
                ),
            }

        self.add_source("resilience", sample)

    # -- level -------------------------------------------------------------

    def force_level(self, level: AdmissionLevel | None) -> None:
        """Pin the level (bench/test hook); None releases the pin."""
        with self._lock:
            self._forced = level
            if level is not None:
                self._note_transition_locked(level)
            else:
                # releasing the pin invalidates the sample cache, so the
                # next level() reads the true load, not the pinned residue
                self._last_sample_t = float("-inf")

    def level(self) -> AdmissionLevel:
        """Current admission level, resampling if the last sample is stale."""
        now = self._clock()
        with self._lock:
            if self._forced is not None:
                return self._forced
            if now - self._last_sample_t < self.thresholds.min_sample_interval:
                return self._level
        return self.sample()

    def sample(self) -> AdmissionLevel:
        """Resample every source now and fold into a level."""
        now = self._clock()
        with self._lock:
            sources = list(self._sources)
        try:
            maybe_fault("loadshed.monitor_sample")
            readings = [(name, fn()) for name, fn in sources]
            level = self._fold(readings)
        except Exception:  # noqa: BLE001 — incl. InjectedFault: fail closed
            with self._lock:
                self._sample_failures += 1
            level = AdmissionLevel.SATURATED
        with self._lock:
            self._last_sample_t = now
            if self._forced is not None:
                return self._forced
            self._note_transition_locked(level)
            return self._level

    def _fold(self, readings) -> AdmissionLevel:
        th = self.thresholds
        fill = 0.0
        lag = 0.0
        degraded = False
        quarantined = False
        d_submitted = 0
        d_dropped = 0
        with self._lock:
            prev = dict(self._prev)
        cur: dict[str, tuple[int, int]] = {}
        for name, r in readings:
            fill = max(fill, float(r.get("fill", 0.0)))
            lag = max(lag, float(r.get("lag_s", 0.0)))
            degraded = degraded or bool(r.get("degraded"))
            quarantined = quarantined or bool(r.get("quarantined"))
            sub = int(r.get("submitted", 0))
            drp = int(r.get("dropped", 0))
            psub, pdrp = prev.get(name, (sub, drp))
            d_submitted += max(0, sub - psub)
            d_dropped += max(0, drp - pdrp)
            cur[name] = (sub, drp)
        with self._lock:
            self._prev.update(cur)
        drop_rate = d_dropped / max(1, d_submitted + d_dropped)
        if (
            quarantined
            or fill >= th.saturated_fill
            or lag >= th.saturated_lag_s
            or (d_dropped > 0 and drop_rate >= th.saturated_drop_rate)
        ):
            return AdmissionLevel.SATURATED
        if (
            degraded
            or fill >= th.busy_fill
            or lag >= th.busy_lag_s
            or d_dropped > 0
        ):
            return AdmissionLevel.BUSY
        return AdmissionLevel.HEALTHY

    def _note_transition_locked(self, level: AdmissionLevel) -> None:
        if level != self._level:
            self._transitions.append(
                (self._clock(), self._level.name, level.name)
            )
            ADMISSION_TRANSITIONS.inc(
                from_level=self._level.name, to_level=level.name
            )
            self._level = level
        ADMISSION_LEVEL.set(int(level))

    # -- introspection -----------------------------------------------------

    def transitions(self) -> list[tuple[float, str, str]]:
        with self._lock:
            return list(self._transitions)

    def retry_after_s(self) -> int:
        """Suggested Retry-After for shed HTTP requests."""
        return 1

    def summary(self) -> dict:
        with self._lock:
            return {
                "level": self._level.name,
                "forced": self._forced.name if self._forced else None,
                "transitions": len(self._transitions),
                "sample_failures": self._sample_failures,
                "sources": [name for name, _ in self._sources],
            }
