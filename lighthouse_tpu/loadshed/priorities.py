"""Priority tables for admission control.

Two shedding surfaces gate on the LoadMonitor's admission level:

* **HTTP API**: P0 routes are the validator-duty critical path — dropping
  them costs the operator money (missed attestations/proposals), so they are
  ALWAYS admitted. Everything else is P1 and gets ``503 + Retry-After`` when
  the node is SATURATED (beacon_processor's ApiRequestP0/P1 split,
  ``beacon_node/beacon_processor/src/lib.rs:629-630``).

* **Req/Resp**: methods carry a priority class; under load the server sheds
  the lowest class first, so cheap control traffic (status/ping — what keeps
  the peer table honest) survives longest and bulk serving (by_range walks,
  light-client updates) goes first.
"""

from __future__ import annotations

# HTTP route names (http_api/server.py _ROUTES) on the validator-duty
# critical path. health/events/syncing ride along: monitoring and SSE duty
# feeds must stay reachable precisely when the node is struggling.
P0_ROUTES = frozenset({
    "proposer",
    "attester",
    "att_data",
    "produce_block",
    "produce_blinded",
    "publish_block",
    "publish_blinded",
    "publish_atts",
    "publish_aggregates",
    "aggregate_att",
    "sync_duties",
    "publish_sync",
    "publish_contributions",
    "liveness",
    "syncing",
    "health",
    "events",
})


def is_p0_route(name: str) -> bool:
    return name in P0_ROUTES


# Req/Resp method -> priority class. Lower = more critical; shedding starts
# from the HIGHEST class and works down as saturation deepens.
#   0  control / liveness        — never shed
#   1  targeted block fetches    — unblocks fork-choice; shed only last
#   2  bulk range serving        — a peer's sync can wait
#   3  light-client mass serving — pure service tier, first to go
METHOD_PRIORITY: dict[str, int] = {
    "status": 0,
    "goodbye": 0,
    "ping": 0,
    "metadata": 0,
    "blocks_by_root": 1,
    "blob_sidecars_by_root": 1,
    "data_column_sidecars_by_root": 1,
    "blocks_by_range": 2,
    "blob_sidecars_by_range": 2,
    "data_column_sidecars_by_range": 2,
    "light_client_bootstrap": 3,
    "light_client_updates_by_range": 3,
    "light_client_finality_update": 3,
    "light_client_optimistic_update": 3,
}
_DEFAULT_METHOD_PRIORITY = 2  # unlisted methods are treated as bulk


def method_priority(method: str) -> int:
    return METHOD_PRIORITY.get(method, _DEFAULT_METHOD_PRIORITY)


def shed_floor(level) -> int | None:
    """Lowest priority class still ADMITTED at ``level`` (methods with a
    class strictly above the floor are shed). None = shed nothing."""
    # imported lazily to keep priorities import-light
    from .monitor import AdmissionLevel

    if level == AdmissionLevel.SATURATED:
        return 1   # keep control + targeted fetches, shed all bulk
    if level == AdmissionLevel.BUSY:
        return 2   # shed only the light-client service tier
    return None


def should_shed_method(method: str, level) -> bool:
    floor = shed_floor(level)
    if floor is None:
        return False
    return method_priority(method) > floor
