"""Slashing protection: SQLite interlock on every signature (EIP-3076).

Twin of ``/root/reference/validator_client/slashing_protection`` (3,561 LoC):
same schema shape (validators / signed_blocks / signed_attestations), the
minimal-slot/epoch pruning rules, double+surround vote rejection in both
directions, and the EIP-3076 interchange JSON for import/export between
clients.
"""

from __future__ import annotations

import json
import sqlite3
import threading


class NotSafe(Exception):
    """Signing refused: would violate slashing conditions."""


class SafeKind:
    VALID = "valid"
    SAME_DATA = "same_data"  # exact re-sign of identical data: permitted


_SCHEMA = """
CREATE TABLE IF NOT EXISTS validators (
    id INTEGER PRIMARY KEY,
    public_key BLOB NOT NULL UNIQUE
);
CREATE TABLE IF NOT EXISTS signed_blocks (
    validator_id INTEGER NOT NULL REFERENCES validators(id),
    slot INTEGER NOT NULL,
    signing_root BLOB,
    UNIQUE (validator_id, slot)
);
CREATE TABLE IF NOT EXISTS signed_attestations (
    validator_id INTEGER NOT NULL REFERENCES validators(id),
    source_epoch INTEGER NOT NULL,
    target_epoch INTEGER NOT NULL,
    signing_root BLOB,
    UNIQUE (validator_id, target_epoch)
);
"""


class SlashingDatabase:
    INTERCHANGE_VERSION = "5"

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.executescript(_SCHEMA)
        self._lock = threading.RLock()

    # -- registration ------------------------------------------------------------

    def register_validator(self, pubkey: bytes) -> int:
        with self._lock:
            cur = self._conn.execute(
                "SELECT id FROM validators WHERE public_key = ?", (pubkey,)
            ).fetchone()
            if cur:
                return cur[0]
            c = self._conn.execute(
                "INSERT INTO validators (public_key) VALUES (?)", (pubkey,)
            )
            self._conn.commit()
            return c.lastrowid

    def _vid(self, pubkey: bytes) -> int:
        row = self._conn.execute(
            "SELECT id FROM validators WHERE public_key = ?", (pubkey,)
        ).fetchone()
        if row is None:
            raise NotSafe(f"unregistered validator {pubkey.hex()[:16]}")
        return row[0]

    # -- blocks -------------------------------------------------------------------

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> str:
        with self._lock:
            vid = self._vid(pubkey)
            same = self._conn.execute(
                "SELECT signing_root FROM signed_blocks"
                " WHERE validator_id = ? AND slot = ?",
                (vid, slot),
            ).fetchone()
            if same is not None:
                if same[0] == signing_root:
                    return SafeKind.SAME_DATA
                raise NotSafe(f"double block proposal at slot {slot}")
            low = self._conn.execute(
                "SELECT MAX(slot) FROM signed_blocks WHERE validator_id = ?",
                (vid,),
            ).fetchone()[0]
            if low is not None and slot <= low:
                # EIP-3076: refuse anything at or below the highest signed slot
                raise NotSafe(f"slot {slot} <= max signed slot {low}")
            self._conn.execute(
                "INSERT INTO signed_blocks VALUES (?, ?, ?)",
                (vid, slot, signing_root),
            )
            self._conn.commit()
            return SafeKind.VALID

    # -- attestations ------------------------------------------------------------

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int,
        signing_root: bytes,
    ) -> str:
        if source_epoch > target_epoch:
            raise NotSafe("source epoch after target epoch")
        with self._lock:
            vid = self._vid(pubkey)
            same = self._conn.execute(
                "SELECT signing_root, source_epoch FROM signed_attestations"
                " WHERE validator_id = ? AND target_epoch = ?",
                (vid, target_epoch),
            ).fetchone()
            if same is not None:
                if same[0] == signing_root and same[1] == source_epoch:
                    return SafeKind.SAME_DATA
                raise NotSafe(f"double vote at target {target_epoch}")
            # surround checks (both directions)
            surrounding = self._conn.execute(
                "SELECT 1 FROM signed_attestations WHERE validator_id = ?"
                " AND source_epoch < ? AND target_epoch > ? LIMIT 1",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounding:
                raise NotSafe("attestation surrounded by prior vote")
            surrounded = self._conn.execute(
                "SELECT 1 FROM signed_attestations WHERE validator_id = ?"
                " AND source_epoch > ? AND target_epoch < ? LIMIT 1",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounded:
                raise NotSafe("attestation surrounds a prior vote")
            # EIP-3076 minimums
            max_src, max_tgt = self._conn.execute(
                "SELECT MAX(source_epoch), MAX(target_epoch)"
                " FROM signed_attestations WHERE validator_id = ?",
                (vid,),
            ).fetchone()
            if max_src is not None and source_epoch < max_src:
                raise NotSafe(f"source {source_epoch} < min source {max_src}")
            if max_tgt is not None and target_epoch <= max_tgt:
                raise NotSafe(f"target {target_epoch} <= min target {max_tgt}")
            self._conn.execute(
                "INSERT INTO signed_attestations VALUES (?, ?, ?, ?)",
                (vid, source_epoch, target_epoch, signing_root),
            )
            self._conn.commit()
            return SafeKind.VALID

    # -- interchange (EIP-3076) ----------------------------------------------------

    def prune(self, finalized_epoch: int, slots_per_epoch: int = 32) -> dict:
        """Drop history that can no longer protect anything
        (``slashing_database.rs`` prune_all_signed_{blocks,attestations}):
        finalized data is immutable, so entries strictly below the
        finalized boundary are dead weight — EXCEPT each validator's
        maximum entry, which is the lower bound future signings are
        checked against and must survive."""
        finalized_slot = finalized_epoch * slots_per_epoch
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(
                """DELETE FROM signed_blocks WHERE slot < ? AND slot < (
                     SELECT MAX(slot) FROM signed_blocks b2
                     WHERE b2.validator_id = signed_blocks.validator_id)""",
                (finalized_slot,),
            )
            blocks = cur.rowcount
            cur.execute(
                """DELETE FROM signed_attestations
                   WHERE target_epoch < ? AND target_epoch < (
                     SELECT MAX(target_epoch) FROM signed_attestations a2
                     WHERE a2.validator_id
                           = signed_attestations.validator_id)""",
                (finalized_epoch,),
            )
            atts = cur.rowcount
            self._conn.commit()
        return {"blocks_pruned": blocks, "attestations_pruned": atts}

    def export_interchange(self, genesis_validators_root: bytes) -> dict:
        with self._lock:
            data = []
            for vid, pk in self._conn.execute(
                "SELECT id, public_key FROM validators"
            ):
                blocks = [
                    {"slot": str(s), "signing_root": "0x" + (r or b"").hex()}
                    for s, r in self._conn.execute(
                        "SELECT slot, signing_root FROM signed_blocks"
                        " WHERE validator_id = ?", (vid,),
                    )
                ]
                atts = [
                    {
                        "source_epoch": str(se),
                        "target_epoch": str(te),
                        "signing_root": "0x" + (r or b"").hex(),
                    }
                    for se, te, r in self._conn.execute(
                        "SELECT source_epoch, target_epoch, signing_root"
                        " FROM signed_attestations WHERE validator_id = ?",
                        (vid,),
                    )
                ]
                data.append(
                    {
                        "pubkey": "0x" + pk.hex(),
                        "signed_blocks": blocks,
                        "signed_attestations": atts,
                    }
                )
            return {
                "metadata": {
                    "interchange_format_version": self.INTERCHANGE_VERSION,
                    "genesis_validators_root": "0x"
                    + genesis_validators_root.hex(),
                },
                "data": data,
            }

    def import_interchange(self, obj: dict) -> int:
        n = 0
        with self._lock:
            for entry in obj.get("data", []):
                pk = bytes.fromhex(entry["pubkey"].removeprefix("0x"))
                vid = self.register_validator(pk)
                for b in entry.get("signed_blocks", []):
                    self._conn.execute(
                        "INSERT OR IGNORE INTO signed_blocks VALUES (?, ?, ?)",
                        (
                            vid,
                            int(b["slot"]),
                            bytes.fromhex(
                                b.get("signing_root", "0x").removeprefix("0x")
                            ),
                        ),
                    )
                    n += 1
                for a in entry.get("signed_attestations", []):
                    self._conn.execute(
                        "INSERT OR IGNORE INTO signed_attestations"
                        " VALUES (?, ?, ?, ?)",
                        (
                            vid,
                            int(a["source_epoch"]),
                            int(a["target_epoch"]),
                            bytes.fromhex(
                                a.get("signing_root", "0x").removeprefix("0x")
                            ),
                        ),
                    )
                    n += 1
            self._conn.commit()
        return n
