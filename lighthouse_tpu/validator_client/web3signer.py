"""Web3Signer remote signing: the HTTP SigningMethod.

Twin of the reference's ``validator_client/signing_method/src/web3signer.rs``:
the validator store signs via POST
``{base}/api/v1/eth2/sign/{0xpubkey}`` with the 32-byte signing root; the
secret key lives in the remote signer. Slashing protection stays local — the
store gates every remote signature exactly like a local one.

``MockWeb3Signer`` is the in-process test double (the reference tests against
a real Web3Signer jar, ``testing/web3signer_tests``).
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import bls


class Web3SignerError(Exception):
    pass


class Web3SignerMethod:
    """SigningMethod implemented by a remote HTTP signer."""

    def __init__(self, pubkey: bytes, base_url: str, timeout: float = 10.0):
        self.pubkey = bytes(pubkey)
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    def sign(self, signing_root: bytes) -> bls.Signature:
        url = f"{self.base}/api/v1/eth2/sign/0x{self.pubkey.hex()}"
        body = json.dumps({"signing_root": "0x" + bytes(signing_root).hex()})
        req = urllib.request.Request(
            url, data=body.encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                sig_hex = json.loads(resp.read().decode())["signature"]
        except Exception as e:  # noqa: BLE001 — surface as signer failure
            raise Web3SignerError(f"remote sign failed: {e}") from None
        return bls.Signature.from_bytes(bytes.fromhex(sig_hex[2:]))


class MockWeb3Signer:
    """Minimal Web3Signer-compatible HTTP server holding secret keys."""

    def __init__(self, secret_keys: list[bls.SecretKey], port: int = 0):
        self.keys = {
            sk.public_key().serialize(): sk for sk in secret_keys
        }
        signer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path != "/api/v1/eth2/publicKeys":
                    self.send_error(404)
                    return
                out = json.dumps(
                    ["0x" + pk.hex() for pk in signer.keys]
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_POST(self):
                prefix = "/api/v1/eth2/sign/"
                if not self.path.startswith(prefix):
                    self.send_error(404)
                    return
                pk = bytes.fromhex(self.path[len(prefix):].removeprefix("0x"))
                sk = signer.keys.get(pk)
                if sk is None:
                    self.send_error(404, "unknown key")
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n).decode())
                root = bytes.fromhex(body["signing_root"][2:])
                sig = sk.sign(root).serialize()
                out = json.dumps({"signature": "0x" + sig.hex()}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread: threading.Thread | None = None

    def start(self) -> "MockWeb3Signer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
