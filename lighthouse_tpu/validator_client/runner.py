"""Production validator client runner (ref validator_client/src/lib.rs:77-107
ProductionValidatorClient).

Loads keys (interop range or EIP-2335 keystore directory), connects to a
beacon node over HTTP only, and drives the duties/attestation/block services
slot by slot.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .. import bls
from ..api_client import BeaconNodeHttpClient
from ..state_transition.genesis import interop_secret_keys
from ..utils.logging import get_logger
from .services import (
    AttestationService,
    BlockService,
    DutiesService,
    ValidatorClientContext,
)
from .validator_store import ValidatorStore

log = get_logger("validator_client")


class ProductionValidatorClient:
    def __init__(self, spec, beacon_url: str):
        self.spec = spec
        self.client = BeaconNodeHttpClient(beacon_url)
        self.store = ValidatorStore(spec)
        self._stop = threading.Event()
        self._last_slot = -1
        self._last_duties_epoch = -1

    # -- key loading --------------------------------------------------------

    def load_interop_keys(self, count: int) -> int:
        for sk in interop_secret_keys(count):
            self.store.add_validator_sk(
                bls.SecretKey.from_bytes(sk.to_bytes(32, "big"))
            )
        return count

    def load_keystore_dir(self, directory: str, password: str) -> int:
        """EIP-2335 keystores named ``keystore-*.json`` (account_manager's
        validator directory layout)."""
        from ..keys.keystore import Keystore

        n = 0
        for name in sorted(os.listdir(directory)):
            if not name.startswith("keystore") or not name.endswith(".json"):
                continue
            with open(os.path.join(directory, name)) as fh:
                ks = Keystore.from_json(fh.read())
            self.store.add_validator_keystore(ks, password)
            n += 1
        log.info("Loaded keystores", count=n, directory=directory)
        return n

    # -- duty loop ----------------------------------------------------------

    def connect(self) -> "ProductionValidatorClient":
        self.ctx = ValidatorClientContext(self.client, self.store)
        self.duties = DutiesService(self.client, self.store)
        self.attestations = AttestationService(self.ctx, self.duties)
        self.blocks = BlockService(self.ctx, self.duties)
        return self

    def run_slot(self, slot: int) -> dict:
        """One slot's duties: poll (per epoch), propose, attest."""
        spe = self.spec.preset.SLOTS_PER_EPOCH
        epoch = slot // spe
        if epoch != self._last_duties_epoch:
            self.duties.poll(epoch)
            # poll one epoch ahead like the reference's lookahead
            self.duties.poll(epoch + 1)
            self._last_duties_epoch = epoch
        proposed = self.blocks.propose(slot)
        attested = self.attestations.attest(slot)
        return {"slot": slot, "proposed": proposed, "attested": attested}

    def run(self, genesis_time: int | None = None) -> None:
        """Wall-clock duty loop until stop() (the tokio interval loop)."""
        g = self.ctx.genesis
        if genesis_time is None:
            genesis_time = int(g.genesis_time)
        sps = self.spec.preset.SECONDS_PER_SLOT
        while not self._stop.is_set():
            now = time.time()
            slot = max(0, int(now - genesis_time) // sps)
            if slot > self._last_slot:
                self._last_slot = slot
                try:
                    stats = self.run_slot(slot)
                    log.info("Slot duties", **stats)
                except Exception as e:  # noqa: BLE001 — keep the loop alive
                    log.error("Duty failure", slot=slot, error=str(e))
            self._stop.wait(0.25)

    def stop(self) -> None:
        self._stop.set()
