"""Production validator client runner (ref validator_client/src/lib.rs:77-107
ProductionValidatorClient).

Loads keys (interop range or EIP-2335 keystore directory), connects to a
beacon node over HTTP only, and drives the duties/attestation/block services
slot by slot.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .. import bls
from ..api_client import BeaconNodeHttpClient
from ..state_transition.genesis import interop_secret_keys
from ..utils.logging import get_logger
from .services import (
    AggregationService,
    AttestationService,
    BlockService,
    DutiesService,
    SyncCommitteeService,
    ValidatorClientContext,
)
from .validator_store import ValidatorStore

log = get_logger("validator_client")


class ProductionValidatorClient:
    def __init__(self, spec, beacon_url, enable_doppelganger: bool = False,
                 keymanager_port: int | None = None):
        from .beacon_node_fallback import BeaconNodeFallback

        self.spec = spec
        urls = (
            [u.strip() for u in beacon_url.split(",") if u.strip()]
            if isinstance(beacon_url, str)
            else list(beacon_url)
        )
        # single node still goes through the fallback shell so health scoring
        # and retry semantics are uniform (beacon_node_fallback.rs)
        self.client = BeaconNodeFallback(urls)
        self.store = ValidatorStore(spec)
        self.doppelganger = None
        if enable_doppelganger:
            from .doppelganger import DoppelgangerService

            self.doppelganger = DoppelgangerService(self.store, self.client)
        self.keymanager = None
        if keymanager_port is not None:
            from .keymanager import KeymanagerServer

            self.keymanager = KeymanagerServer(self.store, port=keymanager_port)
        self._stop = threading.Event()
        self._last_slot = -1
        self._last_duties_epoch = -1

    # -- key loading --------------------------------------------------------

    def load_interop_keys(self, count: int) -> int:
        for sk in interop_secret_keys(count):
            self.store.add_validator_sk(
                bls.SecretKey.from_bytes(sk.to_bytes(32, "big"))
            )
        return count

    def load_web3signer(self, signer_url: str) -> int:
        """Register every key the remote signer serves
        (/api/v1/eth2/publicKeys — Web3Signer's key-listing endpoint).
        An unreachable signer is a startup error, not a silent zero-key run;
        individual keys can also be registered later via the keymanager
        remotekeys API."""
        import json
        import urllib.request

        try:
            with urllib.request.urlopen(
                signer_url.rstrip("/") + "/api/v1/eth2/publicKeys", timeout=10
            ) as resp:
                pubkeys = json.loads(resp.read().decode())
        except Exception as e:
            log.error("Web3Signer unreachable", signer=signer_url, error=str(e))
            raise RuntimeError(
                f"web3signer key listing failed at {signer_url}: {e}"
            ) from None
        for p in pubkeys:
            self.store.add_validator_remote(bytes.fromhex(p[2:]), signer_url)
        log.info("Registered remote keys", count=len(pubkeys), signer=signer_url)
        return len(pubkeys)

    def load_keystore_dir(self, directory: str, password: str) -> int:
        """EIP-2335 keystores named ``keystore-*.json`` (account_manager's
        validator directory layout)."""
        from ..keys.keystore import Keystore

        n = 0
        for name in sorted(os.listdir(directory)):
            if not name.startswith("keystore") or not name.endswith(".json"):
                continue
            with open(os.path.join(directory, name)) as fh:
                ks = Keystore.from_json(fh.read())
            self.store.add_validator_keystore(ks, password)
            n += 1
        log.info("Loaded keystores", count=n, directory=directory)
        return n

    # -- duty loop ----------------------------------------------------------

    def connect(self) -> "ProductionValidatorClient":
        self.ctx = ValidatorClientContext(self.client, self.store)
        self.duties = DutiesService(self.client, self.store)
        self.attestations = AttestationService(self.ctx, self.duties)
        self.blocks = BlockService(self.ctx, self.duties)
        self.sync_committee = SyncCommitteeService(self.ctx, self.duties)
        self.aggregation = AggregationService(
            self.ctx, self.duties, self.attestations
        )
        g = self.ctx.genesis
        self.client.pin_genesis(g.genesis_validators_root)
        self.client.update_all_candidates()
        if self.keymanager is not None:
            self.keymanager.start()
        return self

    def run_slot(self, slot: int) -> dict:
        """One slot's duties: poll (per epoch), doppelganger gate, propose,
        attest."""
        spe = self.spec.preset.SLOTS_PER_EPOCH
        epoch = slot // spe
        if epoch != self._last_duties_epoch:
            # re-score the fallback candidates once per epoch (the
            # reference's periodic health poll)
            self.client.update_all_candidates()
            self.duties.poll(epoch)
            # poll one epoch ahead like the reference's lookahead
            self.duties.poll(epoch + 1)
            if self.doppelganger is not None:
                if self._last_duties_epoch < 0:
                    self.doppelganger.register_all(epoch)
                else:
                    self.doppelganger.check(
                        epoch, self.duties.validator_indices()
                    )
            # prune slashing-protection history below finality once per
            # epoch (slashing_database.rs prune; the max entry per
            # validator always survives as the signing lower bound)
            try:
                fin = self.client.get_finality_checkpoints()
                fin_epoch = int(fin["finalized"]["epoch"])
                if fin_epoch > 0:
                    self.store.slashing_db.prune(fin_epoch, spe)
            except Exception:  # noqa: BLE001 — pruning is best-effort
                pass
            self._last_duties_epoch = epoch
        proposed = self.blocks.propose(slot)
        attested = self.attestations.attest(slot)
        aggregated = self.aggregation.aggregate(slot)
        synced = self.sync_committee.sign_and_publish(slot)
        return {
            "slot": slot, "proposed": proposed, "attested": attested,
            "aggregated": aggregated, "sync_signed": synced,
        }

    def run(self, genesis_time: int | None = None) -> None:
        """Wall-clock duty loop until stop() (the tokio interval loop)."""
        g = self.ctx.genesis
        if genesis_time is None:
            genesis_time = int(g.genesis_time)
        sps = self.spec.preset.SECONDS_PER_SLOT
        while not self._stop.is_set():
            now = time.time()
            slot = max(0, int(now - genesis_time) // sps)
            if slot > self._last_slot:
                self._last_slot = slot
                try:
                    stats = self.run_slot(slot)
                    log.info("Slot duties", **stats)
                except Exception as e:  # noqa: BLE001 — keep the loop alive
                    log.error("Duty failure", slot=slot, error=str(e))
            self._stop.wait(0.25)

    def stop(self) -> None:
        self._stop.set()
