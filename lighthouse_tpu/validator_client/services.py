"""Validator-client services: duties, attestation, block production.

Twin of ``validator_client/validator_services/src/{duties_service,
attestation_service,block_service}.rs``: duties polled from the BN over HTTP,
per-slot attestation signing + publication, proposer-duty block production —
all signing through the ValidatorStore (slashing-protected) and all BN
interaction through the typed HTTP client only.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..api_client import BeaconNodeHttpClient
from ..api_client.client import AttesterDuty, ProposerDuty
from ..types.containers import AttestationData, Fork, for_preset
from .slashing_protection import NotSafe
from .validator_store import ValidatorStore


@dataclass
class ForkInfo:
    """The slice of state that domain computation needs (fork +
    genesis_validators_root), built from API responses — the VC never holds a
    BeaconState."""

    fork: Fork
    genesis_validators_root: bytes


class DutiesService:
    """Polls proposer/attester duties per epoch (duties_service.rs)."""

    def __init__(self, client: BeaconNodeHttpClient, store: ValidatorStore):
        self.client = client
        self.store = store
        self._indices: dict[bytes, int] = {}
        self.proposer: dict[int, list[ProposerDuty]] = {}
        self.attester: dict[int, list[AttesterDuty]] = {}

    def validator_indices(self) -> dict[bytes, int]:
        # Re-poll while any managed key is still unresolved — validators can
        # activate after the first poll (duties_service.rs re-polls per cycle).
        if len(self._indices) < len(self.store.validators):
            all_indices = self.client.get_validator_indices()
            self._indices = {
                pk: idx
                for pk, idx in all_indices.items()
                if pk in self.store.validators
            }
        return self._indices

    def poll(self, epoch: int) -> None:
        ours = set(self.validator_indices().values())
        props = self.client.get_proposer_duties(epoch)
        self.proposer[epoch] = [
            d for d in props if d.validator_index in ours
        ]
        self.attester[epoch] = self.client.get_attester_duties(
            epoch, sorted(ours)
        )

    def proposers_at(self, slot: int, epoch: int) -> list[ProposerDuty]:
        return [d for d in self.proposer.get(epoch, []) if d.slot == slot]

    def attesters_at(self, slot: int, epoch: int) -> list[AttesterDuty]:
        return [d for d in self.attester.get(epoch, []) if d.slot == slot]


class ValidatorClientContext:
    """Shared per-VC context: spec, fork info from the BN."""

    def __init__(self, client: BeaconNodeHttpClient, store: ValidatorStore):
        self.client = client
        self.store = store
        genesis = client.get_genesis()
        self.genesis = genesis
        self.store.genesis_validators_root = genesis.genesis_validators_root

    def fork_info(self) -> ForkInfo:
        f = self.client.get_fork("head")
        return ForkInfo(
            fork=Fork(
                previous_version=f["previous_version"],
                current_version=f["current_version"],
                epoch=f["epoch"],
            ),
            genesis_validators_root=self.genesis.genesis_validators_root,
        )


class AttestationService:
    """Per-slot attestation duty execution (attestation_service.rs:231-507,
    minus the aggregation phase which rides sign_selection_proof)."""

    def __init__(self, ctx: ValidatorClientContext, duties: DutiesService):
        self.ctx = ctx
        self.duties = duties
        # (slot, committee_index) -> AttestationData, shared with aggregation
        self.data_cache: dict = {}

    def attest(self, slot: int) -> int:
        """Sign + publish one attestation per owned attester duty at slot.
        Returns the number published. The fetched AttestationData is cached
        per (slot, committee) for the aggregation phase."""
        spec = self.ctx.store.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        my = self.duties.attesters_at(slot, epoch)
        if not my:
            return 0
        fork_info = self.ctx.fork_info()
        ns = for_preset(spec.preset.name)
        published = []
        for duty in my:
            data = AttestationData.decode(
                self.ctx.client.get_attestation_data(slot, duty.committee_index)
            )
            self.data_cache[(slot, duty.committee_index)] = data
            if len(self.data_cache) > 256:
                self.data_cache = {
                    k: v for k, v in self.data_cache.items() if k[0] >= slot - 2
                }
            try:
                sig = self.ctx.store.sign_attestation(
                    duty.pubkey, data, fork_info
                )
            except NotSafe:
                # held back (doppelganger) or slashing-protected — skip this
                # validator, keep attesting with the rest
                continue
            bits = np.zeros(duty.committee_length, dtype=bool)
            bits[duty.validator_committee_index] = True
            att = ns.Attestation(
                aggregation_bits=bits, data=data, signature=sig.serialize()
            )
            published.append(ns.Attestation.encode(att))
        if published:
            self.ctx.client.publish_attestations(published)
        return len(published)


class AggregationService:
    """The aggregation phase of attestation duties
    (attestation_service.rs:231-507 second half): a validator whose selection
    proof selects it as the committee aggregator fetches the naive pool's
    aggregate from the BN, wraps it in a SignedAggregateAndProof, and
    publishes it through the 3-sets verification endpoint."""

    def __init__(self, ctx: ValidatorClientContext, duties: DutiesService,
                 attestations: "AttestationService | None" = None):
        self.ctx = ctx
        self.duties = duties
        self.attestations = attestations

    @staticmethod
    def is_aggregator(committee_length: int, target_per_committee: int,
                      selection_proof: bytes) -> bool:
        """spec is_aggregator: hash(proof) mod ceil-ish committee/TARGET."""
        import hashlib

        modulo = max(1, committee_length // target_per_committee)
        digest = hashlib.sha256(bytes(selection_proof)).digest()
        return int.from_bytes(digest[0:8], "little") % modulo == 0

    def aggregate(self, slot: int) -> int:
        """Run after attest(slot): publish one SignedAggregateAndProof per
        owned aggregator duty. Returns the number published."""
        spec = self.ctx.store.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        my = self.duties.attesters_at(slot, epoch)
        if not my:
            return 0
        fork_info = self.ctx.fork_info()
        ns = for_preset(spec.preset.name)
        published = []
        seen_committees = set()
        for duty in my:
            if duty.committee_index in seen_committees:
                continue
            try:
                proof = self.ctx.store.sign_selection_proof(
                    duty.pubkey, slot, fork_info
                )
            except NotSafe:
                continue
            if not self.is_aggregator(
                duty.committee_length,
                spec.target_aggregators_per_committee,
                proof.serialize(),
            ):
                continue
            data = None
            if self.attestations is not None:
                data = self.attestations.data_cache.get(
                    (slot, duty.committee_index)
                )
            if data is None:
                data = AttestationData.decode(
                    self.ctx.client.get_attestation_data(
                        slot, duty.committee_index
                    )
                )
            from ..api_client import ApiClientError

            try:
                agg_ssz = self.ctx.client.get_aggregate_attestation(
                    AttestationData.hash_tree_root(data)
                )
            except ApiClientError as e:
                if e.code != 404:
                    raise  # outages must not masquerade as 'nothing pooled'
                continue
            aggregate = ns.Attestation.decode(agg_ssz)
            aap = ns.AggregateAndProof(
                aggregator_index=duty.validator_index,
                aggregate=aggregate,
                selection_proof=proof.serialize(),
            )
            sig = self.ctx.store.sign_aggregate_and_proof(
                duty.pubkey, aap, fork_info
            )
            sap = ns.SignedAggregateAndProof(
                message=aap, signature=sig.serialize()
            )
            published.append(ns.SignedAggregateAndProof.encode(sap))
            seen_committees.add(duty.committee_index)
        if published:
            self.ctx.client.publish_aggregate_and_proofs(published)
        return len(published)


class SyncCommitteeService:
    """Per-slot sync-committee duty (sync_committee_service.rs): every owned
    validator in the current committee signs the head root each slot."""

    def __init__(self, ctx: ValidatorClientContext, duties: DutiesService):
        self.ctx = ctx
        self.duties = duties
        self._duty_cache: dict[int, list] = {}  # epoch -> sync duties

    def _sync_duties(self, epoch: int) -> list:
        if epoch not in self._duty_cache:
            indices = self.duties.validator_indices()
            self._duty_cache[epoch] = self.ctx.client.get_sync_duties(
                epoch, sorted(indices.values())
            )
            self._duty_cache = {
                e: d for e, d in self._duty_cache.items() if e >= epoch - 1
            }
        return self._duty_cache[epoch]

    def sign_and_publish(self, slot: int) -> int:
        spec = self.ctx.store.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        duties = self._sync_duties(epoch)
        if not duties:
            return 0
        head = self.ctx.client.get_head_header()
        fork_info = self.ctx.fork_info()
        ns = for_preset(spec.preset.name)
        out = []
        for duty in duties:
            pubkey = bytes.fromhex(duty["pubkey"][2:])
            try:
                sig = self.ctx.store.sign_sync_committee_message(
                    pubkey, slot, head["root"], fork_info
                )
            except NotSafe:
                continue
            msg = ns.SyncCommitteeMessage(
                slot=slot,
                beacon_block_root=head["root"],
                validator_index=int(duty["validator_index"]),
                signature=sig.serialize(),
            )
            out.append(ns.SyncCommitteeMessage.encode(msg))
        if out:
            self.ctx.client.publish_sync_messages(out)
        return len(out)


class BlockService:
    """Proposer duty execution (block_service.rs): randao sign -> produce via
    BN -> sign -> publish."""

    def __init__(self, ctx: ValidatorClientContext, duties: DutiesService):
        self.ctx = ctx
        self.duties = duties

    def propose(self, slot: int) -> bool:
        spec = self.ctx.store.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        my = self.duties.proposers_at(slot, epoch)
        if not my:
            return False
        duty = my[0]
        fork_info = self.ctx.fork_info()
        try:
            randao = self.ctx.store.sign_randao(duty.pubkey, epoch, fork_info)
        except NotSafe:
            return False  # held back (doppelganger) — skip the proposal
        version, block_ssz = self.ctx.client.produce_block(
            slot, randao.serialize()
        )
        ns = for_preset(spec.preset.name)
        block_cls = ns.block_types[version]
        inner_cls = dict(block_cls.FIELDS)["message"]
        block = inner_cls.decode(block_ssz)
        sig = self.ctx.store.sign_block(duty.pubkey, block, fork_info)
        signed = block_cls(message=block, signature=sig.serialize())
        self.ctx.client.publish_block(version, block_cls.encode(signed))
        return True
