"""Validator client (validator_client/* twin): duties-driven signer."""

from .slashing_protection import SlashingDatabase, NotSafe
from .validator_store import ValidatorStore
