"""Validator client (validator_client/* twin): duties-driven signer."""

from .beacon_node_fallback import AllErrored, BeaconNodeFallback, Health
from .doppelganger import DoppelgangerService
from .keymanager import KeymanagerServer
from .slashing_protection import NotSafe, SlashingDatabase
from .validator_store import ValidatorStore
from .web3signer import MockWeb3Signer, Web3SignerMethod
