"""Keymanager API: the standard key-management HTTP surface on the VC.

Twin of the reference's validator-client HTTP API (``validator_client/http_api``,
6,629 LoC — keystores + remotekeys CRUD with slashing-protection export on
delete). Routes follow the Eth keymanager-API paths:

  GET    /eth/v1/keystores            list local keys
  POST   /eth/v1/keystores            import EIP-2335 keystores
  DELETE /eth/v1/keystores            delete keys + export slashing history
  GET    /eth/v1/remotekeys           list Web3Signer-backed keys
  POST   /eth/v1/remotekeys           register remote keys
  DELETE /eth/v1/remotekeys           unregister remote keys
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..keys.keystore import Keystore
from ..utils.logging import get_logger
from .web3signer import Web3SignerMethod

log = get_logger("keymanager")


class KeymanagerServer:
    def __init__(self, store, host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self._httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread: threading.Thread | None = None

    def start(self) -> "KeymanagerServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        log.info("Keymanager API started", url=self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # -- handlers ----------------------------------------------------------

    def list_keystores(self):
        out = []
        for pk, v in self.store.validators.items():
            if isinstance(v.method, Web3SignerMethod):
                continue
            out.append(
                {
                    "validating_pubkey": "0x" + pk.hex(),
                    "derivation_path": "",
                    "readonly": not v.enabled,
                }
            )
        return out

    def import_keystores(self, body: dict):
        keystores = body.get("keystores", [])
        passwords = body.get("passwords", [])
        statuses = []
        for ks_json, pw in zip(keystores, passwords):
            try:
                ks = Keystore.from_json(
                    ks_json if isinstance(ks_json, str) else json.dumps(ks_json)
                )
                self.store.add_validator_keystore(ks, pw)
                statuses.append({"status": "imported"})
            except Exception as e:  # noqa: BLE001 — per-key status
                statuses.append({"status": "error", "message": str(e)})
        if body.get("slashing_protection"):
            sp = body["slashing_protection"]
            self.store.slashing_db.import_interchange(
                sp if isinstance(sp, dict) else json.loads(sp)
            )
        return statuses

    def delete_keystores(self, body: dict):
        pubkeys = [bytes.fromhex(p[2:]) for p in body.get("pubkeys", [])]
        statuses = []
        for pk in pubkeys:
            v = self.store.validators.get(pk)
            if v is not None and isinstance(v.method, Web3SignerMethod):
                # keystores CRUD must not affect remotekeys (keymanager spec)
                statuses.append({"status": "not_found"})
                continue
            removed = self.store.remove_validator(pk)
            statuses.append(
                {"status": "deleted" if removed else "not_found"}
            )
        interchange = self.store.slashing_db.export_interchange(
            self.store.genesis_validators_root
        )
        return {"data": statuses, "slashing_protection": interchange}

    def list_remotekeys(self):
        return [
            {
                "pubkey": "0x" + pk.hex(),
                "url": v.method.base,
                "readonly": not v.enabled,
            }
            for pk, v in self.store.validators.items()
            if isinstance(v.method, Web3SignerMethod)
        ]

    def import_remotekeys(self, body: dict):
        statuses = []
        for item in body.get("remote_keys", []):
            try:
                self.store.add_validator_remote(
                    bytes.fromhex(item["pubkey"][2:]), item["url"]
                )
                statuses.append({"status": "imported"})
            except Exception as e:  # noqa: BLE001 — per-key status
                statuses.append({"status": "error", "message": str(e)})
        return statuses

    def delete_remotekeys(self, body: dict):
        statuses = []
        for p in body.get("pubkeys", []):
            pk = bytes.fromhex(p[2:])
            v = self.store.validators.get(pk)
            if v is None or not isinstance(v.method, Web3SignerMethod):
                statuses.append({"status": "not_found"})
                continue
            removed = self.store.remove_validator(pk)
            statuses.append({"status": "deleted" if removed else "not_found"})
        return statuses


def _make_handler(api: KeymanagerServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, code: int, payload) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw.decode() or "{}")

        def _route(self, method: str):
            path = self.path.split("?")[0]
            if path == "/eth/v1/keystores":
                if method == "GET":
                    return {"data": api.list_keystores()}
                if method == "POST":
                    return {"data": api.import_keystores(self._body())}
                if method == "DELETE":
                    return api.delete_keystores(self._body())
            if path == "/eth/v1/remotekeys":
                if method == "GET":
                    return {"data": api.list_remotekeys()}
                if method == "POST":
                    return {"data": api.import_remotekeys(self._body())}
                if method == "DELETE":
                    return {"data": api.delete_remotekeys(self._body())}
            return None

        def _dispatch(self, method: str) -> None:
            try:
                out = self._route(method)
                if out is None:
                    self._reply(404, {"message": f"no route {self.path}"})
                else:
                    self._reply(200, out)
            except Exception as e:  # noqa: BLE001 — API boundary
                self._reply(500, {"message": f"{type(e).__name__}: {e}"})

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def do_DELETE(self):
            self._dispatch("DELETE")

    return Handler
