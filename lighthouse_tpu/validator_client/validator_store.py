"""Validator store: key management + slashing-protected signing.

Twin of ``validator_client/validator_store`` + ``signing_method``: local
keystore signing (the Web3Signer remote path plugs into the same seam as an
alternative ``SigningMethod``), every block/attestation signature gated by the
SlashingDatabase, doppelganger-aware.
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import bls
from ..types.helpers import compute_signing_root, get_domain
from ..types.spec import ChainSpec
from .slashing_protection import NotSafe, SlashingDatabase


class SigningMethod:
    """Local secret key (keystore-decrypted). Web3Signer would implement the
    same interface with an HTTP call (signing_method/src/web3signer.rs)."""

    def __init__(self, sk: bls.SecretKey):
        self.sk = sk

    def sign(self, signing_root: bytes) -> bls.Signature:
        return self.sk.sign(signing_root)


@dataclass
class InitializedValidator:
    pubkey: bytes
    method: SigningMethod
    enabled: bool = True


class ValidatorStore:
    def __init__(
        self,
        spec: ChainSpec,
        slashing_db: SlashingDatabase | None = None,
        genesis_validators_root: bytes = b"\x00" * 32,
    ):
        self.spec = spec
        self.slashing_db = slashing_db or SlashingDatabase()
        self.genesis_validators_root = genesis_validators_root
        self.validators: dict[bytes, InitializedValidator] = {}
        self.doppelganger_suspect: set[bytes] = set()

    # -- registration ------------------------------------------------------------

    def add_validator_sk(self, sk: bls.SecretKey) -> bytes:
        pk = sk.public_key().serialize()
        self.validators[pk] = InitializedValidator(pk, SigningMethod(sk))
        self.slashing_db.register_validator(pk)
        return pk

    def add_validator_keystore(self, keystore, password: str) -> bytes:
        secret = keystore.decrypt(password)
        return self.add_validator_sk(bls.SecretKey.from_bytes(secret))

    def add_validator_remote(self, pubkey: bytes, signer_url: str) -> bytes:
        """Register a Web3Signer-backed validator (remote key; local slashing
        protection still gates every signature)."""
        from .web3signer import Web3SignerMethod

        pk = bytes(pubkey)
        self.validators[pk] = InitializedValidator(
            pk, Web3SignerMethod(pk, signer_url)
        )
        self.slashing_db.register_validator(pk)
        return pk

    def remove_validator(self, pubkey: bytes) -> bool:
        """Delete a key from the store (keymanager DELETE). The slashing
        history stays in the database — it must survive key round-trips."""
        return self.validators.pop(bytes(pubkey), None) is not None

    def voting_pubkeys(self) -> list[bytes]:
        return [pk for pk, v in self.validators.items() if v.enabled]

    def _method(self, pubkey: bytes) -> SigningMethod:
        v = self.validators.get(bytes(pubkey))
        if v is None or not v.enabled:
            raise NotSafe("unknown or disabled validator")
        if bytes(pubkey) in self.doppelganger_suspect:
            raise NotSafe("doppelganger protection active")
        return v.method

    # -- signing (each gated by slashing protection) -------------------------------

    def sign_block(self, pubkey: bytes, block, state) -> bls.Signature:
        method = self._method(pubkey)
        domain = get_domain(
            self.spec, state, self.spec.DOMAIN_BEACON_PROPOSER,
            epoch=self.spec.compute_epoch_at_slot(block.slot),
        )
        root = compute_signing_root(block, domain)
        self.slashing_db.check_and_insert_block_proposal(
            bytes(pubkey), int(block.slot), root
        )
        # crash point BETWEEN the recorded watermark and the signature
        # leaving this process: the EIP-3076 record is committed first, so
        # a kill here can never lead to a conflicting re-sign after restart
        from ..resilience.crashpoints import maybe_crash

        maybe_crash("persist.slashing_protection")
        return method.sign(root)

    def sign_attestation(self, pubkey: bytes, data, state) -> bls.Signature:
        method = self._method(pubkey)
        domain = get_domain(
            self.spec, state, self.spec.DOMAIN_BEACON_ATTESTER,
            epoch=data.target.epoch,
        )
        root = compute_signing_root(data, domain)
        self.slashing_db.check_and_insert_attestation(
            bytes(pubkey), int(data.source.epoch), int(data.target.epoch), root
        )
        from ..resilience.crashpoints import maybe_crash

        maybe_crash("persist.slashing_protection")
        return method.sign(root)

    def sign_randao(self, pubkey: bytes, epoch: int, state) -> bls.Signature:
        from ..ssz import uint64
        from ..types.containers import SigningData

        method = self._method(pubkey)
        domain = get_domain(self.spec, state, self.spec.DOMAIN_RANDAO, epoch=epoch)
        root = SigningData(
            object_root=uint64.hash_tree_root(epoch), domain=domain
        ).tree_root()
        return method.sign(root)

    def sign_selection_proof(self, pubkey: bytes, slot: int, state) -> bls.Signature:
        from ..ssz import uint64
        from ..types.containers import SigningData

        method = self._method(pubkey)
        domain = get_domain(
            self.spec, state, self.spec.DOMAIN_SELECTION_PROOF,
            epoch=self.spec.compute_epoch_at_slot(slot),
        )
        root = SigningData(
            object_root=uint64.hash_tree_root(slot), domain=domain
        ).tree_root()
        return method.sign(root)

    def sign_aggregate_and_proof(self, pubkey: bytes, agg_and_proof, state):
        method = self._method(pubkey)
        domain = get_domain(
            self.spec, state, self.spec.DOMAIN_AGGREGATE_AND_PROOF,
            epoch=self.spec.compute_epoch_at_slot(agg_and_proof.aggregate.data.slot),
        )
        root = compute_signing_root(agg_and_proof, domain)
        return method.sign(root)

    def sign_sync_committee_message(
        self, pubkey: bytes, slot: int, beacon_block_root: bytes, state
    ) -> bls.Signature:
        from ..types.helpers import sync_committee_signing_root

        method = self._method(pubkey)
        return method.sign(
            sync_committee_signing_root(
                self.spec, state, slot, beacon_block_root
            )
        )

    def sign_voluntary_exit(self, pubkey: bytes, exit_msg, state) -> bls.Signature:
        method = self._method(pubkey)
        domain = get_domain(
            self.spec, state, self.spec.DOMAIN_VOLUNTARY_EXIT,
            epoch=exit_msg.epoch,
        )
        root = compute_signing_root(exit_msg, domain)
        return method.sign(root)
