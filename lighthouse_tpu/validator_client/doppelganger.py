"""Doppelganger protection: refuse to sign until the network shows no other
instance of our keys is live.

Twin of the reference's ``validator_client/doppelganger_service`` (1,471 LoC):
newly-started validators are held back from signing while the service watches
``/eth/v1/validator/liveness/{epoch}`` for their indices over the previous
epoch(s). Any observed liveness for a held-back key is treated as a duplicate
instance: the key stays disabled and the operator is alerted. After
``detection_epochs`` clean epochs the key is released for signing.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.logging import get_logger

log = get_logger("doppelganger")

DEFAULT_DETECTION_EPOCHS = 2  # current remainder + 1 full epoch (ref default)


@dataclass
class _WatchState:
    start_epoch: int
    next_epoch: int  # next epoch whose liveness has NOT been examined yet
    epochs_checked: int = 0
    doppelganger_detected: bool = False


class DoppelgangerService:
    def __init__(self, store, client, detection_epochs: int = DEFAULT_DETECTION_EPOCHS):
        self.store = store
        self.client = client  # BeaconNodeHttpClient | BeaconNodeFallback
        self.detection_epochs = detection_epochs
        self._watch: dict[bytes, _WatchState] = {}

    # -- lifecycle ---------------------------------------------------------

    def register_all(self, current_epoch: int) -> int:
        """Hold back every enabled key and start watching (VC startup)."""
        n = 0
        for pk in list(self.store.validators):
            self._watch[pk] = _WatchState(
                start_epoch=current_epoch, next_epoch=current_epoch
            )
            self.store.doppelganger_suspect.add(pk)
            n += 1
        if n:
            log.info(
                "Doppelganger detection started",
                validators=n, epochs=self.detection_epochs,
            )
        return n

    def detected(self) -> list[bytes]:
        return [
            pk for pk, w in self._watch.items() if w.doppelganger_detected
        ]

    # -- per-epoch check ---------------------------------------------------

    def check(self, current_epoch: int, indices_by_pubkey: dict[bytes, int]) -> None:
        """Examine liveness for EVERY not-yet-checked completed epoch (so a
        process suspended across epochs never skips one) and release/flag keys.

        Mirrors the reference's decision table: liveness seen while held back
        => permanent disable + alert; ``detection_epochs`` clean epoch checks
        => release for signing.
        """
        if current_epoch < 1:
            return
        watched = [
            (pk, w) for pk, w in self._watch.items()
            if not w.doppelganger_detected and pk in self.store.doppelganger_suspect
        ]
        if not watched:
            return
        indices = [
            indices_by_pubkey[pk] for pk, _ in watched if pk in indices_by_pubkey
        ]
        # every completed epoch any watched key hasn't examined yet
        lo = min(w.next_epoch for _, w in watched)
        live: dict[int, dict[int, bool]] = {}  # epoch -> index -> live
        for epoch in range(lo, current_epoch):
            if indices:
                live[epoch] = {
                    int(r["index"]): bool(r["is_live"])
                    for r in self.client.get_validator_liveness(epoch, indices)
                }
            else:
                live[epoch] = {}
        for pk, w in watched:
            idx = indices_by_pubkey.get(pk)
            for epoch in range(w.next_epoch, current_epoch):
                if idx is not None and live[epoch].get(idx, False):
                    w.doppelganger_detected = True
                    log.error(
                        "DOPPELGANGER DETECTED — validator stays disabled",
                        pubkey=pk.hex()[:16], index=idx, epoch=epoch,
                    )
                    break
                w.next_epoch = epoch + 1
                w.epochs_checked += 1
            if (
                not w.doppelganger_detected
                and w.epochs_checked >= self.detection_epochs
            ):
                self.store.doppelganger_suspect.discard(pk)
                log.info(
                    "Doppelganger check clean — validator enabled",
                    pubkey=pk.hex()[:16],
                )
