"""Multi-beacon-node failover with health scoring.

Twin of the reference's ``validator_client/beacon_node_fallback`` (1,317 LoC):
the VC holds N candidate beacon nodes, health-checks them (syncing status +
genesis agreement), orders candidates Synced > Syncing > Offline, and routes
every API call to the first candidate that succeeds — demoting a candidate on
error and retrying the next (``first_success`` semantics,
``beacon_node_fallback/src/lib.rs``).
"""

from __future__ import annotations

import enum
import threading

from ..api_client import BeaconNodeHttpClient
from ..utils.logging import get_logger

log = get_logger("beacon_node_fallback")


class Health(enum.IntEnum):
    # ordering = routing preference (lower value tried first)
    Synced = 0
    Syncing = 1
    Offline = 2


class CandidateBeaconNode:
    def __init__(self, client: BeaconNodeHttpClient):
        self.client = client
        self.health = Health.Offline
        self.last_error: str | None = None

    def refresh_health(self, expected_genesis_root: bytes | None) -> Health:
        try:
            if expected_genesis_root is not None:
                g = self.client.get_genesis()
                if g.genesis_validators_root != expected_genesis_root:
                    raise RuntimeError("genesis mismatch (wrong network)")
            sync = self.client.get_syncing()
            self.health = (
                Health.Syncing if sync.get("is_syncing") else Health.Synced
            )
            self.last_error = None
        except Exception as e:  # noqa: BLE001 — any failure = offline
            self.health = Health.Offline
            self.last_error = str(e)
        return self.health


class AllErrored(Exception):
    def __init__(self, errors: list[tuple[str, str]]):
        super().__init__(
            "all beacon nodes errored: "
            + "; ".join(f"{u}: {e}" for u, e in errors)
        )
        self.errors = errors


class BeaconNodeFallback:
    """Drop-in for ``BeaconNodeHttpClient``: exposes the same method surface,
    dispatching each call through ``first_success``."""

    def __init__(self, clients_or_urls):
        self.candidates = [
            CandidateBeaconNode(
                c if isinstance(c, BeaconNodeHttpClient)
                else BeaconNodeHttpClient(c)
            )
            for c in clients_or_urls
        ]
        if not self.candidates:
            raise ValueError("at least one beacon node required")
        self._lock = threading.Lock()
        self._genesis_root: bytes | None = None

    # -- health ------------------------------------------------------------

    def update_all_candidates(self) -> None:
        """Re-score every candidate (the reference's periodic poll)."""
        for c in self.candidates:
            c.refresh_health(self._genesis_root)

    def pin_genesis(self, genesis_validators_root: bytes) -> None:
        """Candidates on a different network are scored Offline."""
        self._genesis_root = bytes(genesis_validators_root)

    def num_available(self) -> int:
        return sum(1 for c in self.candidates if c.health != Health.Offline)

    # -- dispatch ----------------------------------------------------------

    def first_success(self, method: str, *args, **kwargs):
        with self._lock:
            ordered = sorted(self.candidates, key=lambda c: c.health)
        errors = []
        for cand in ordered:
            try:
                out = getattr(cand.client, method)(*args, **kwargs)
                if cand.health is Health.Offline:
                    cand.health = Health.Syncing  # give it a chance to rescore
                return out
            except Exception as e:  # noqa: BLE001 — try the next node
                cand.health = Health.Offline
                cand.last_error = str(e)
                errors.append((cand.client.base, str(e)))
                log.warn(
                    "Beacon node failed, trying fallback",
                    node=cand.client.base, method=method, error=str(e),
                )
        raise AllErrored(errors)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # every public client method becomes a fallback dispatch
        if not hasattr(BeaconNodeHttpClient, name):
            raise AttributeError(name)

        def call(*args, **kwargs):
            return self.first_success(name, *args, **kwargs)

        return call
