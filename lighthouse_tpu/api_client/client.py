"""BeaconNodeHttpClient: stdlib-urllib typed client for the Beacon API."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass


class ApiClientError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


@dataclass
class ProposerDuty:
    pubkey: bytes
    validator_index: int
    slot: int


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    committee_index: int
    committee_length: int
    committees_at_slot: int
    validator_committee_index: int
    slot: int


@dataclass
class GenesisInfo:
    genesis_time: int
    genesis_validators_root: bytes
    genesis_fork_version: bytes


class BeaconNodeHttpClient:
    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    # -- plumbing ----------------------------------------------------------

    def _req(self, method: str, path: str, body=None):
        url = self.base + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read().decode()).get("message", "")
            except Exception:
                msg = str(e)
            raise ApiClientError(e.code, msg) from None

    def _get(self, path: str):
        return self._req("GET", path)

    def _post(self, path: str, body):
        return self._req("POST", path, body)

    # -- endpoints ---------------------------------------------------------

    def get_genesis(self) -> GenesisInfo:
        d = self._get("/eth/v1/beacon/genesis")["data"]
        return GenesisInfo(
            genesis_time=int(d["genesis_time"]),
            genesis_validators_root=_unhex(d["genesis_validators_root"]),
            genesis_fork_version=_unhex(d["genesis_fork_version"]),
        )

    def get_fork(self, state_id: str = "head"):
        d = self._get(f"/eth/v1/beacon/states/{state_id}/fork")["data"]
        return {
            "previous_version": _unhex(d["previous_version"]),
            "current_version": _unhex(d["current_version"]),
            "epoch": int(d["epoch"]),
        }

    def get_finality_checkpoints(self, state_id: str = "head"):
        d = self._get(
            f"/eth/v1/beacon/states/{state_id}/finality_checkpoints"
        )["data"]
        return {
            k: {"epoch": int(v["epoch"]), "root": _unhex(v["root"])}
            for k, v in d.items()
        }

    def get_validator_indices(self) -> dict[bytes, int]:
        d = self._get("/eth/v1/beacon/states/head/validators")["data"]
        return {
            _unhex(v["validator"]["pubkey"]): int(v["index"]) for v in d
        }

    def get_syncing(self):
        return self._get("/eth/v1/node/syncing")["data"]

    def get_proposer_duties(self, epoch: int) -> list[ProposerDuty]:
        d = self._get(f"/eth/v1/validator/duties/proposer/{epoch}")["data"]
        return [
            ProposerDuty(
                pubkey=_unhex(x["pubkey"]),
                validator_index=int(x["validator_index"]),
                slot=int(x["slot"]),
            )
            for x in d
        ]

    def get_attester_duties(
        self, epoch: int, indices: list[int]
    ) -> list[AttesterDuty]:
        d = self._post(f"/eth/v1/validator/duties/attester/{epoch}", indices)[
            "data"
        ]
        return [
            AttesterDuty(
                pubkey=_unhex(x["pubkey"]),
                validator_index=int(x["validator_index"]),
                committee_index=int(x["committee_index"]),
                committee_length=int(x["committee_length"]),
                committees_at_slot=int(x["committees_at_slot"]),
                validator_committee_index=int(x["validator_committee_index"]),
                slot=int(x["slot"]),
            )
            for x in d
        ]

    def get_attestation_data(self, slot: int, committee_index: int) -> bytes:
        d = self._get(
            f"/eth/v1/validator/attestation_data?slot={slot}"
            f"&committee_index={committee_index}"
        )["data"]
        return _unhex(d["data"])  # SSZ-encoded AttestationData

    def produce_block(self, slot: int, randao_reveal: bytes) -> tuple[str, bytes]:
        d = self._get(
            f"/eth/v2/validator/blocks/{slot}?randao_reveal={_hex(randao_reveal)}"
        )
        return d["version"], _unhex(d["data"])  # SSZ-encoded BeaconBlock

    def publish_block(self, version: str, signed_block_ssz: bytes) -> None:
        self._post(
            "/eth/v1/beacon/blocks",
            {"version": version, "data": _hex(signed_block_ssz)},
        )

    def publish_attestations(self, atts_ssz: list[bytes]) -> None:
        self._post(
            "/eth/v1/beacon/pool/attestations",
            [{"data": _hex(a)} for a in atts_ssz],
        )

    def get_head_header(self):
        d = self._get("/eth/v1/beacon/headers/head")["data"]
        return {
            "root": _unhex(d["root"]),
            "slot": int(d["header"]["message"]["slot"]),
        }

    def get_validator_liveness(self, epoch: int, indices: list[int]):
        return self._post(f"/eth/v1/validator/liveness/{epoch}", indices)["data"]

    def get_aggregate_attestation(self, data_root: bytes) -> bytes:
        d = self._get(
            "/eth/v1/validator/aggregate_attestation"
            f"?attestation_data_root={_hex(data_root)}"
        )["data"]
        return _unhex(d)

    def publish_aggregate_and_proofs(self, saps_ssz: list[bytes]) -> None:
        self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            [{"data": _hex(s)} for s in saps_ssz],
        )

    def get_sync_duties(self, epoch: int, indices: list[int]):
        return self._post(f"/eth/v1/validator/duties/sync/{epoch}", indices)[
            "data"
        ]

    def publish_sync_messages(self, msgs_ssz: list[bytes]) -> None:
        self._post(
            "/eth/v1/beacon/pool/sync_committees",
            [{"data": _hex(m)} for m in msgs_ssz],
        )

    def get_block_ssz(self, block_id) -> tuple[str, bytes]:
        """Signed block by slot/root/'head' (fork-versioned SSZ)."""
        d = self._get(f"/eth/v2/beacon/blocks/{block_id}")["data"]
        return d["version"], _unhex(d["data"])

    def get_state_ssz(self, state_id: str = "finalized") -> tuple[str, bytes]:
        """Full BeaconState SSZ (the checkpoint-sync fetch; debug API)."""
        d = self._get(f"/eth/v2/debug/beacon/states/{state_id}")["data"]
        return d["version"], _unhex(d["data"])
