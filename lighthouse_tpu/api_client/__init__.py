"""Typed Beacon-API HTTP client (the ``common/eth2`` twin).

Used by the validator client's services and by tests/tools; every method maps
one endpoint of ``http_api`` (``common/eth2/src/lib.rs`` BeaconNodeHttpClient).
"""

from .client import ApiClientError, BeaconNodeHttpClient  # noqa: F401
