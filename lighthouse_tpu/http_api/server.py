"""Beacon-API server implementation.

Work items arriving over HTTP correspond to the reference's ApiRequestP0/P1
beacon-processor queues (``beacon_processor/src/lib.rs:629-630``); here the
handler calls the chain directly (the stdlib threading server provides the
concurrency seam). Endpoints follow the Eth Beacon API paths served by
``http_api/src/lib.rs`` with SSZ-hex payload envelopes.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..loadshed import AdmissionLevel, is_p0_route
from ..state_transition import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
    process_slots,
)
from ..types.containers import AttestationData, Checkpoint
from ..types.helpers import compute_fork_digest
from ..utils.metrics import SHED_REQUESTS


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class BeaconApiServer:
    """Wraps a BeaconChain (and optionally its op pool / gossip publisher —
    a BeaconNodeService provides both) behind the Beacon API."""

    def __init__(self, chain, op_pool=None, network_service=None,
                 host: str = "127.0.0.1", port: int = 0,
                 load_monitor=None):
        self.chain = chain
        self.op_pool = op_pool
        self.network = network_service
        # admission control: when the node is SATURATED, P1 (non-duty)
        # routes are refused with 503 + Retry-After; P0 duty routes are
        # always admitted (shedding a proposal costs more than any queue)
        self.load_monitor = load_monitor
        self._httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None
        # blinded flow: payloads produced here, awaited by publication
        # (execution_layer payload cache parity), keyed by block_hash;
        # bounded — publication pops, unclaimed entries age out FIFO
        from collections import OrderedDict

        self._payload_cache: "OrderedDict[bytes, object]" = OrderedDict()
        self._payload_cache_size = 8
        # insert+evict / pop interleave across ThreadingHTTPServer handler
        # threads; the GIL makes single dict ops atomic but not the
        # size-trim loop, so guard the cache with its own small lock
        self._payload_cache_lock = threading.Lock()
        # Share the CHAIN's mutation lock so handler threads serialize
        # against every other driver of this chain (network router,
        # simulator loops), not just each other.
        self._chain_lock = chain.lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BeaconApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- state resolution --------------------------------------------------

    def _state(self, state_id: str):
        if state_id == "head":
            return self.chain.head.state
        if state_id in ("justified", "finalized"):
            head = self.chain.head.state
            cp = (
                head.current_justified_checkpoint
                if state_id == "justified"
                else head.finalized_checkpoint
            )
            root = bytes(cp.root)
            if root == b"\x00" * 32:  # pre-genesis-justification alias
                root = self.chain.genesis_block_root
            st = self.chain.state_by_root(root)
            if st is None:
                raise ApiError(404, f"{state_id} state not held: {root.hex()}")
            # the checkpoint block can predate its epoch start (skipped
            # slots); the checkpoint STATE is advanced to the boundary
            boundary = self.chain.spec.start_slot(int(cp.epoch))
            if st.slot < boundary:
                st = st.copy()
                process_slots(self.chain.spec, st, boundary)
            return st
        raise ApiError(400, f"unsupported state id {state_id!r}")

    # -- endpoint handlers -------------------------------------------------

    def get_genesis(self):
        st = self.chain.genesis_state
        return {
            "genesis_time": str(int(st.genesis_time)),
            "genesis_validators_root": _hex(st.genesis_validators_root),
            "genesis_fork_version": _hex(self.chain.spec.genesis_fork_version),
        }

    def get_fork(self, state_id: str):
        st = self._state(state_id)
        return {
            "previous_version": _hex(st.fork.previous_version),
            "current_version": _hex(st.fork.current_version),
            "epoch": str(int(st.fork.epoch)),
        }

    def get_finality_checkpoints(self, state_id: str):
        st = self._state(state_id)

        def cp(c):
            return {"epoch": str(int(c.epoch)), "root": _hex(c.root)}

        return {
            "previous_justified": cp(st.previous_justified_checkpoint),
            "current_justified": cp(st.current_justified_checkpoint),
            "finalized": cp(st.finalized_checkpoint),
        }

    @staticmethod
    def _validator_status(v, epoch: int, far: int) -> str:
        """Beacon-API validator status taxonomy (validator/mod.rs
        ValidatorStatus)."""
        if int(v.activation_epoch) > epoch:
            return (
                "pending_queued"
                if int(v.activation_eligibility_epoch) <= epoch
                else "pending_initialized"
            )
        if epoch < int(v.exit_epoch):
            if int(v.exit_epoch) != far:
                return "active_exiting"
            return "active_slashed" if v.slashed else "active_ongoing"
        if epoch < int(v.withdrawable_epoch):
            return "exited_slashed" if v.slashed else "exited_unslashed"
        return "withdrawal_possible"

    def _validator_entry(self, st, i: int, epoch: int, far: int) -> dict:
        v = st.validators[i]
        return {
            "index": str(i),
            "balance": str(int(st.balances[i])),
            "status": self._validator_status(v, epoch, far),
            "validator": {
                "pubkey": _hex(v.pubkey),
                "withdrawal_credentials": _hex(v.withdrawal_credentials),
                "effective_balance": str(int(v.effective_balance)),
                "slashed": bool(v.slashed),
                "activation_eligibility_epoch": str(
                    int(v.activation_eligibility_epoch)
                ),
                "activation_epoch": str(int(v.activation_epoch)),
                "exit_epoch": str(int(v.exit_epoch)),
                "withdrawable_epoch": str(int(v.withdrawable_epoch)),
            },
        }

    def _resolve_validator_index(self, st, vid: str) -> int:
        if vid.startswith("0x"):
            pk = _unhex(vid)
            # O(1) via the chain's pubkey index; linear fallback only for
            # keys the cache hasn't imported yet
            idx = self.chain.pubkey_cache.get_index(pk)
            if idx is not None and idx < len(st.validators):
                return idx
            for i, v in enumerate(st.validators):
                if bytes(v.pubkey) == pk:
                    return i
            raise ApiError(404, f"no validator with pubkey {vid[:18]}…")
        if not vid.isdigit():
            raise ApiError(400, f"bad validator id {vid!r}")
        i = int(vid)
        if i >= len(st.validators):
            raise ApiError(404, f"validator index {i} out of range")
        return i

    def _resolve_validator_indices(self, st, ids: str) -> list[int]:
        """Batch-query id resolution: unknown pubkeys / out-of-range indices
        are OMITTED (the reference filters by set membership — VCs routinely
        query keys whose deposits are not yet processed); malformed ids are
        still a 400. 404 is reserved for the single-validator endpoint."""
        out = []
        for x in ids.split(","):
            if not x:
                continue
            try:
                out.append(self._resolve_validator_index(st, x))
            except ApiError as e:
                if e.code != 404:
                    raise
        return out

    def get_validators(self, state_id: str, ids: str | None = None):
        from ..types.spec import FAR_FUTURE_EPOCH

        st = self._state(state_id)
        spec = self.chain.spec
        epoch = int(st.slot) // spec.preset.SLOTS_PER_EPOCH
        if ids:
            indices = self._resolve_validator_indices(st, ids)
        else:
            indices = range(len(st.validators))
        return [
            self._validator_entry(st, i, epoch, FAR_FUTURE_EPOCH)
            for i in indices
        ]

    def get_validator(self, state_id: str, vid: str):
        from ..types.spec import FAR_FUTURE_EPOCH

        st = self._state(state_id)
        spec = self.chain.spec
        epoch = int(st.slot) // spec.preset.SLOTS_PER_EPOCH
        i = self._resolve_validator_index(st, vid)
        return self._validator_entry(st, i, epoch, FAR_FUTURE_EPOCH)

    def get_validator_balances(self, state_id: str, ids: str | None = None):
        st = self._state(state_id)
        if ids:
            indices = self._resolve_validator_indices(st, ids)
        else:
            indices = range(len(st.validators))
        return [
            {"index": str(i), "balance": str(int(st.balances[i]))}
            for i in indices
        ]

    def get_committees(self, state_id: str, q: dict):
        """GET /eth/v1/beacon/states/{id}/committees with epoch/index/slot
        filters (http_api committees endpoint)."""
        st = self._state(state_id)
        spec = self.chain.spec
        state_epoch = int(st.slot) // spec.preset.SLOTS_PER_EPOCH
        epoch = int(q.get("epoch", state_epoch))
        # the state can answer exactly [previous, current, next] epochs
        # (shuffling seeds beyond the lookahead are not yet decided; older
        # epochs would silently compute WRONG committees) — match the
        # reference's bounds with a 400, and never process_slots over an
        # unbounded attacker-chosen range
        if epoch > state_epoch + 1 or epoch + 1 < state_epoch:
            raise ApiError(
                400,
                f"epoch {epoch} outside the computable range "
                f"[{max(state_epoch - 1, 0)}, {state_epoch + 1}] "
                f"of state {state_id}",
            )
        state = st
        start = spec.start_slot(epoch)
        if state.slot < start:
            state = state.copy()
            process_slots(spec, state, start)
        want_slot = int(q["slot"]) if "slot" in q else None
        want_index = int(q["index"]) if "index" in q else None
        out = []
        per_slot = get_committee_count_per_slot(spec, state, epoch)
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            if want_slot is not None and slot != want_slot:
                continue
            for index in range(per_slot):
                if want_index is not None and index != want_index:
                    continue
                committee = get_beacon_committee(spec, state, slot, index)
                out.append(
                    {
                        "index": str(index),
                        "slot": str(slot),
                        "validators": [str(int(v)) for v in committee],
                    }
                )
        return out

    def get_randao(self, state_id: str, q: dict):
        from ..state_transition import get_randao_mix

        st = self._state(state_id)
        spec = self.chain.spec
        epoch = int(
            q.get("epoch", int(st.slot) // spec.preset.SLOTS_PER_EPOCH)
        )
        return {"randao": _hex(get_randao_mix(spec, st, epoch))}

    def get_blob_sidecars(self, block_id: str, q: dict):
        """GET /eth/v1/beacon/blob_sidecars/{block_id} from the blobs
        column (hot_cold_store.rs get_blobs)."""
        root = self._block_root_of(block_id)
        raws = self.chain.store.get_blob_sidecars(root)
        if raws is None:
            return []
        indices = (
            {int(x) for x in q["indices"].split(",")} if "indices" in q else None
        )
        cls = self.chain.ns.BlobSidecar
        out = []
        for raw in raws:
            sc = cls.decode(raw)
            if indices is None or int(sc.index) in indices:
                out.append(_hex(raw))
        return out

    def get_syncing(self):
        head = self.chain.head.slot
        current = self.chain.current_slot()
        return {
            "head_slot": str(head),
            "sync_distance": str(max(0, current - head)),
            "is_syncing": current > head + 1,
            "is_optimistic": False,
            "el_offline": self.chain.execution_layer is None,
        }

    def get_proposer_duties(self, epoch: int):
        spec = self.chain.spec
        state = self.chain.head.state.copy()
        start = spec.start_slot(epoch)
        if state.slot < start:
            process_slots(spec, state, start)
        duties = []
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            idx = get_beacon_proposer_index(spec, state, slot=slot)
            duties.append(
                {
                    "pubkey": _hex(state.validators[idx].pubkey),
                    "validator_index": str(idx),
                    "slot": str(slot),
                }
            )
        return duties

    def get_attester_duties(self, epoch: int, indices: list[int]):
        spec = self.chain.spec
        state = self.chain.head.state.copy()
        start = spec.start_slot(epoch)
        if state.slot < start:
            process_slots(spec, state, start)
        wanted = set(indices)
        duties = []
        committees_per_slot = get_committee_count_per_slot(spec, state, epoch)
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            for index in range(committees_per_slot):
                committee = get_beacon_committee(spec, state, slot, index)
                for pos, v in enumerate(committee):
                    if int(v) in wanted:
                        duties.append(
                            {
                                "pubkey": _hex(state.validators[int(v)].pubkey),
                                "validator_index": str(int(v)),
                                "committee_index": str(index),
                                "committee_length": str(committee.size),
                                "committees_at_slot": str(committees_per_slot),
                                "validator_committee_index": str(pos),
                                "slot": str(slot),
                            }
                        )
        return duties

    def get_sync_duties(self, epoch: int, indices: list[int]):
        """Sync-committee duties (duties/sync/{epoch}): committee positions
        per requested validator, computed on a state advanced to the
        requested epoch (period boundaries rotate the committee)."""
        spec = self.chain.spec
        state = self.chain.head.state
        if not hasattr(state, "current_sync_committee"):
            return []
        start = spec.start_slot(epoch)
        if state.slot < start:
            state = state.copy()
            process_slots(spec, state, start)
        out = []
        for idx in indices:
            positions = self.chain.sync_committee_positions(state, idx)
            if positions:
                out.append(
                    {
                        "pubkey": _hex(state.validators[idx].pubkey),
                        "validator_index": str(idx),
                        "validator_sync_committee_indices": [
                            str(p) for p in positions
                        ],
                    }
                )
        return out

    def publish_sync_messages(self, body: list):
        """POST /eth/v1/beacon/pool/sync_committees: verify + pool."""
        ns = self.chain.ns
        msgs = [
            ns.SyncCommitteeMessage.decode(_unhex(item["data"]))
            for item in body
        ]
        results = self.chain.verify_sync_committee_messages(msgs)
        failures = [
            {"index": i, "message": str(v)}
            for i, (_, v) in enumerate(results)
            if isinstance(v, Exception)
        ]
        if failures:
            raise ApiError(400, f"sync messages rejected: {failures}")
        if self.network is not None:
            publish = getattr(self.network, "publish_sync_message", None)
            if publish is not None:
                for m in msgs:
                    publish(m)
        return {"accepted": len(msgs)}

    def get_aggregate_attestation(self, data_root: bytes):
        """GET /eth/v1/validator/aggregate_attestation: the naive pool's best
        aggregate for an AttestationData root."""
        agg = self.chain.naive_aggregation_pool.get_by_root(data_root)
        if agg is None:
            raise ApiError(404, "no aggregate for data root")
        cls = self.chain.ns.Attestation
        return _hex(cls.encode(agg))

    def publish_aggregates(self, body: list):
        """POST /eth/v1/validator/aggregate_and_proofs: the 3-sets-per-
        aggregate batch verification path + op pool insert."""
        ns = self.chain.ns
        saps = [
            ns.SignedAggregateAndProof.decode(_unhex(item["data"]))
            for item in body
        ]
        results = self.chain.verify_aggregated_attestations(saps)
        failures = []
        accepted = 0
        for i, (sap, verdict) in enumerate(results):
            if isinstance(verdict, Exception):
                failures.append({"index": i, "message": str(verdict)})
                continue
            accepted += 1
            if self.op_pool is not None:
                self.op_pool.insert_attestation(sap.message.aggregate)
            if self.network is not None:
                self.network.publish_aggregate(sap)
        if failures:
            # valid aggregates are already applied; report the rest
            raise ApiError(400, f"aggregates rejected: {failures}")
        return {"accepted": accepted}

    def publish_contributions(self, body: list):
        """POST /eth/v1/validator/contribution_and_proofs."""
        ns = self.chain.ns
        scs = [
            ns.SignedContributionAndProof.decode(_unhex(item["data"]))
            for item in body
        ]
        results = self.chain.verify_sync_contributions(scs)
        failures = [
            {"index": i, "message": str(v)}
            for i, (_, v) in enumerate(results)
            if isinstance(v, Exception)
        ]
        if failures:
            raise ApiError(400, f"contributions rejected: {failures}")
        return {"accepted": len(scs)}

    def get_attestation_data(self, slot: int, committee_index: int):
        spec = self.chain.spec
        # one snapshot: a concurrent import swaps chain.head atomically, so
        # every field here must come from the SAME head view
        head = self.chain.head
        # early-attester cache (early_attester_cache.rs): same-epoch
        # attestations to the current head never touch (or slot-advance) a
        # state — the validator-client stampede at the attestation deadline
        # is served from six cached fields
        cached = self.chain.early_attester_cache.try_attestation_data(
            spec, slot, committee_index, head.root
        )
        if cached is not None:
            return {"data": _hex(AttestationData.encode(cached))}
        state = head.state
        if state.slot < slot:
            state = state.copy()
            process_slots(spec, state, slot)
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        head_root = head.root
        if slot == spec.start_slot(epoch) and head.slot <= slot:
            target_root = head_root
        else:
            from ..state_transition import get_block_root_at_slot

            target_root = get_block_root_at_slot(
                spec, state, spec.start_slot(epoch)
            )
        data = AttestationData(
            slot=slot,
            index=committee_index,
            beacon_block_root=head_root,
            source=state.current_justified_checkpoint,
            target=Checkpoint(epoch=epoch, root=target_root),
        )
        return {"data": _hex(AttestationData.encode(data))}

    def produce_block(self, slot: int, randao_reveal: bytes, graffiti: bytes):
        chain = self.chain
        state = _advanced(chain, slot)  # advance once; shared by pool + production
        atts = self.op_pool.get_attestations(state) if self.op_pool else []
        block, _post = chain.produce_block_on_state(
            state, slot, randao_reveal, attestations=atts,
            graffiti=graffiti or b"\x00" * 32, op_pool=self.op_pool,
        )
        fork = chain.spec.fork_name_at_epoch(
            slot // chain.spec.preset.SLOTS_PER_EPOCH
        )
        inner_cls = dict(chain.ns.block_types[fork].FIELDS)["message"]
        return {
            "version": fork,
            "data": _hex(inner_cls.encode(block)),
        }

    def produce_blinded_block(
        self, slot: int, randao_reveal: bytes, graffiti: bytes
    ):
        """GET /eth/v1/validator/blinded_blocks/{slot}: full production,
        payload swapped for its header; the payload is cached for
        publication (execution_layer blinded flow — the builder seam)."""
        from ..types.blinded import blind_signed_block

        full = self.produce_block(slot, randao_reveal, graffiti)
        fork = full["version"]
        if fork not in self.chain.ns.payload_header_types:
            raise ApiError(400, f"no blinded flow before bellatrix ({fork})")
        chain = self.chain
        inner_cls = dict(chain.ns.block_types[fork].FIELDS)["message"]
        block = inner_cls.decode(_unhex(full["data"]))
        payload = block.body.execution_payload
        with self._payload_cache_lock:
            self._payload_cache[bytes(payload.block_hash)] = payload
            while len(self._payload_cache) > self._payload_cache_size:
                self._payload_cache.popitem(last=False)
        signed_shell = chain.ns.block_types[fork](
            message=block, signature=b"\x00" * 96
        )
        blinded = blind_signed_block(chain.ns, fork, signed_shell)
        inner_blinded = blinded.message
        return {
            "version": fork,
            "data": _hex(type(inner_blinded).encode(inner_blinded)),
        }

    def publish_blinded_block(self, body: dict):
        """POST /eth/v1/beacon/blinded_blocks: reconstruct the full block
        from the cached payload (publish_blocks.rs blinded path) and import."""
        from ..types.blinded import blinded_types, unblind_signed_block

        chain = self.chain
        fork = body.get("version") or chain.spec.fork_name_at_slot(
            chain.current_slot()
        )
        ns = blinded_types(chain.ns)
        if fork not in ns.blinded_block_types:
            raise ApiError(400, f"no blinded flow before bellatrix ({fork})")
        signed_blinded = ns.blinded_block_types[fork].decode(
            _unhex(body["data"])
        )
        hdr = signed_blinded.message.body.execution_payload_header
        with self._payload_cache_lock:
            payload = self._payload_cache.pop(bytes(hdr.block_hash), None)
        if payload is None:
            raise ApiError(400, "unknown payload for blinded block")
        try:
            full = unblind_signed_block(ns, fork, signed_blinded, payload)
        except ValueError as e:
            raise ApiError(400, str(e)) from None
        return self.publish_block(
            {"version": fork, "data": _hex(type(full).encode(full))}
        )

    def publish_block(self, body: dict):
        version = body.get("version", None)
        fork = version or self.chain.spec.fork_name_at_slot(
            self.chain.current_slot()
        )
        block_cls = self.chain.ns.block_types[fork]
        signed = block_cls.decode(_unhex(body["data"]))
        from ..beacon_chain.chain import BlockError, BlockPendingAvailability

        # deneb BlockContents: blobs + proofs ride alongside the block
        sidecars = []
        if body.get("blobs"):
            from ..beacon_chain.data_availability import make_blob_sidecars

            sidecars = make_blob_sidecars(
                self.chain.ns,
                signed,
                [_unhex(x) for x in body["blobs"]],
                [_unhex(x) for x in body.get("kzg_proofs", [])],
            )
        try:
            self.chain.process_block(signed)
        except BlockPendingAvailability:
            from ..beacon_chain.data_availability import BlobError

            imported = None
            try:
                for sc in sidecars:
                    imported = self.chain.process_gossip_blob(sc)
            except (BlobError, BlockError) as e:
                raise ApiError(400, str(e)) from None
            if imported is None:
                raise ApiError(
                    400, "block pending blob availability"
                ) from None
        except BlockError as e:
            raise ApiError(400, str(e)) from None
        if self.network is not None:
            self.network.publish_block(signed)
            publish_blob = getattr(self.network, "publish_blob", None)
            if publish_blob is not None:
                for sc in sidecars:
                    publish_blob(sc)
        return {}

    def publish_attestations(self, body: list):
        att_cls = self.chain.ns.Attestation
        atts = [att_cls.decode(_unhex(item["data"])) for item in body]
        results = self.chain.verify_unaggregated_attestations(atts)
        failures = []
        for i, (att, verdict) in enumerate(results):
            if isinstance(verdict, Exception):
                failures.append({"index": i, "message": str(verdict)})
                continue
            if self.op_pool is not None:
                self.op_pool.insert_attestation(att)
            if self.network is not None:
                self.network.publish_attestation(att)
        if failures:
            raise ApiError(400, json.dumps(failures))
        return {}

    def _signed_block(self, root: bytes):
        """Decoded signed block by root: memory cache first, then the store
        (finalized blocks are migrated out of memory but stay on disk)."""
        chain = self.chain
        sb = chain._blocks.get(root)
        if sb is not None:
            return sb
        raw = chain.store.get_block(root)
        if raw is None:
            return None
        for fork in reversed(list(chain.ns.block_types)):
            try:
                return chain.ns.block_types[fork].decode(raw)
            except Exception:
                continue
        return None

    def _block_root_of(self, block_id: str) -> bytes:
        """Resolve 'head'/'finalized'/slot/0x-root to a canonical block
        root."""
        chain = self.chain
        if block_id == "head":
            return chain.head.root
        if block_id == "finalized":
            root = bytes(
                chain.head.state.finalized_checkpoint.root
            )
            return root if root != b"\x00" * 32 else chain.genesis_block_root
        if block_id == "genesis":
            return chain.genesis_block_root
        if block_id.startswith("0x"):
            return _unhex(block_id)
        if block_id.isdigit():
            # canonical walk from head, bounded by the head slot; store
            # fallback covers migrated (finalized) history
            want = int(block_id)
            if want > chain.head.slot:
                raise ApiError(404, f"no canonical block at slot {want}")
            root = chain.head.root
            while root is not None:
                sb = self._signed_block(root)
                if sb is None:
                    break
                s = int(sb.message.slot)
                if s == want:
                    return root
                if s < want or root == chain.genesis_block_root:
                    break
                root = bytes(sb.message.parent_root)
            raise ApiError(404, f"no canonical block at slot {want}")
        raise ApiError(400, f"unsupported block id {block_id!r}")

    def get_block(self, block_id: str):
        """Signed block by id (fork-versioned SSZ envelope;
        /eth/v2/beacon/blocks/{block_id})."""
        chain = self.chain
        root = self._block_root_of(block_id)
        sb = self._signed_block(root)
        if sb is None:
            raise ApiError(404, f"block {root.hex()[:16]} not held")
        fork = chain.spec.fork_name_at_slot(int(sb.message.slot))
        cls = chain.ns.block_types[fork]
        return {"version": fork, "data": _hex(cls.encode(sb))}

    def get_block_root(self, block_id: str):
        root = self._block_root_of(block_id)
        if block_id.startswith("0x") and self._signed_block(root) is None:
            raise ApiError(404, f"block {block_id[:18]}… not held")
        return {"root": _hex(root)}

    def _is_canonical(self, root: bytes, slot: int) -> bool:
        """True iff `root` is the canonical block at its slot (explicit
        0x-root lookups may name blocks off the canonical chain)."""
        try:
            return self._block_root_of(str(int(slot))) == root
        except ApiError:
            return False

    def get_header(self, block_id: str = "head"):
        root = self._block_root_of(block_id)
        sb = self._signed_block(root)
        if sb is not None:
            msg = sb.message
            fields = {
                "slot": str(int(msg.slot)),
                "proposer_index": str(int(msg.proposer_index)),
                "parent_root": _hex(msg.parent_root),
                "state_root": _hex(msg.state_root),
                "body_root": _hex(type(msg.body).hash_tree_root(msg.body)),
            }
            sig = _hex(sb.signature)
        else:
            # anchor-state head (checkpoint sync): the block body is not
            # held; the state's latest header carries the message fields
            head = self.chain.head
            if root != head.root:
                raise ApiError(404, f"block {root.hex()[:16]} not held")
            hdr = head.state.latest_block_header.copy()
            if bytes(hdr.state_root) == b"\x00" * 32:
                hdr.state_root = head.state.tree_root()
            fields = {
                "slot": str(int(hdr.slot)),
                "proposer_index": str(int(hdr.proposer_index)),
                "parent_root": _hex(hdr.parent_root),
                "state_root": _hex(hdr.state_root),
                "body_root": _hex(hdr.body_root),
            }
            sig = _hex(b"\x00" * 96)
        canonical = (
            True
            if not block_id.startswith("0x")
            else self._is_canonical(root, int(fields["slot"]))
        )
        return {
            "root": _hex(root),
            "canonical": canonical,
            "header": {"message": fields, "signature": sig},
        }

    # -- pool endpoints ----------------------------------------------------

    def get_pool_attester_slashings(self):
        pool = self.op_pool
        items = list(pool._attester_slashings) if pool else []
        return [_hex(type(s).encode(s)) for s in items]

    def get_pool_proposer_slashings(self):
        pool = self.op_pool
        items = list(pool._proposer_slashings.values()) if pool else []
        return [_hex(type(s).encode(s)) for s in items]

    def get_pool_voluntary_exits(self):
        pool = self.op_pool
        items = list(pool._voluntary_exits.values()) if pool else []
        return [_hex(type(s).encode(s)) for s in items]

    def get_pool_bls_changes(self):
        pool = self.op_pool
        items = list(pool._bls_changes.values()) if pool else []
        return [_hex(type(s).encode(s)) for s in items]

    def _verify_op_on_head(self, apply_fn, *args):
        """Run an operation's full verification against a head-state copy
        (verify_operation.rs SigVerifiedOp semantics: pool admission re-runs
        the state checks + signature)."""
        from ..state_transition.per_block import BlockProcessingError

        state = self.chain.head.state.copy()
        try:
            apply_fn(state, *args)
        except BlockProcessingError as e:
            raise ApiError(400, str(e)) from None

    def post_pool_attester_slashing(self, body: dict):
        from ..state_transition.per_block import process_attester_slashing

        ns = self.chain.ns
        fork = self.chain.spec.fork_name_at_slot(self.chain.current_slot())
        cls = ns.attester_slashing_types[fork]
        sl = cls.decode(_unhex(body["data"]))
        self._verify_op_on_head(
            lambda st: process_attester_slashing(
                self.chain.spec, st, sl, verify=True
            )
        )
        if self.op_pool is not None:
            self.op_pool.insert_attester_slashing(sl)
        return {}

    def post_pool_proposer_slashing(self, body: dict):
        from ..state_transition.per_block import (
            ConsensusContext,
            process_proposer_slashing,
        )

        from ..types.containers import ProposerSlashing

        sl = ProposerSlashing.decode(_unhex(body["data"]))
        self._verify_op_on_head(
            lambda st: process_proposer_slashing(
                self.chain.spec, st, sl, ConsensusContext(), verify=True
            )
        )
        if self.op_pool is not None:
            self.op_pool.insert_proposer_slashing(sl)
        return {}

    def post_pool_voluntary_exit(self, body: dict):
        from ..state_transition.per_block import process_exit

        from ..types.containers import SignedVoluntaryExit

        ex = SignedVoluntaryExit.decode(_unhex(body["data"]))
        self._verify_op_on_head(
            lambda st: process_exit(self.chain.spec, st, ex, verify=True)
        )
        if self.op_pool is not None:
            self.op_pool.insert_voluntary_exit(ex)
        return {}

    def post_pool_bls_change(self, body: dict):
        from ..state_transition.per_block import (
            process_bls_to_execution_change,
        )
        from ..types.containers import SignedBLSToExecutionChange

        ch = SignedBLSToExecutionChange.decode(_unhex(body["data"]))
        self._verify_op_on_head(
            lambda st: process_bls_to_execution_change(
                self.chain.spec, st, ch, verify=True
            )
        )
        if self.op_pool is not None:
            self.op_pool.insert_bls_to_execution_change(ch)
        return {}

    # -- node / config -----------------------------------------------------

    def get_node_identity(self):
        net = self.network
        peer_id = ""
        addrs = []
        if net is not None:
            transport = getattr(net, "transport", None)
            if transport is not None:
                peer_id = str(getattr(transport, "node_id", ""))
                addr = getattr(transport, "address", None)
                if addr:
                    addrs = [f"/ip4/{addr[0]}/tcp/{addr[1]}"]
        return {
            "peer_id": peer_id,
            "enr": "",
            "p2p_addresses": addrs,
            "discovery_addresses": [],
            "metadata": {"seq_number": "0", "attnets": "0x00"},
        }

    def get_node_peers(self):
        net = self.network
        out = []
        if net is not None:
            transport = getattr(net, "transport", None)
            if transport is not None:
                for p in transport.peers():
                    out.append(
                        {
                            "peer_id": str(p),
                            "state": "connected",
                            "direction": "outbound",
                        }
                    )
        return out

    def node_health_code(self) -> int:
        head = self.chain.head.slot
        current = self.chain.current_slot()
        return 206 if current > head + 1 else 200

    def get_config_spec(self):
        spec = self.chain.spec
        p = spec.preset
        out = {
            "PRESET_BASE": p.name,
            "SECONDS_PER_SLOT": str(p.SECONDS_PER_SLOT),
            "SLOTS_PER_EPOCH": str(p.SLOTS_PER_EPOCH),
            "MAX_COMMITTEES_PER_SLOT": str(p.MAX_COMMITTEES_PER_SLOT),
            "MAX_EFFECTIVE_BALANCE": str(spec.max_effective_balance),
            "MIN_ATTESTATION_INCLUSION_DELAY": str(
                spec.min_attestation_inclusion_delay
            ),
            "SHARD_COMMITTEE_PERIOD": str(spec.shard_committee_period),
            "GENESIS_FORK_VERSION": _hex(spec.genesis_fork_version),
        }
        for fork in ("altair", "bellatrix", "capella", "deneb", "electra"):
            out[f"{fork.upper()}_FORK_EPOCH"] = str(spec.fork_epoch(fork))
            out[f"{fork.upper()}_FORK_VERSION"] = _hex(
                spec.fork_version(fork)
            )
        return out

    def get_fork_schedule(self):
        spec = self.chain.spec
        out = []
        prev = spec.genesis_fork_version
        for fork in ("phase0", "altair", "bellatrix", "capella", "deneb",
                     "electra"):
            epoch = 0 if fork == "phase0" else spec.fork_epoch(fork)
            version = spec.fork_version(fork)
            out.append(
                {
                    "previous_version": _hex(prev),
                    "current_version": _hex(version),
                    "epoch": str(epoch),
                }
            )
            prev = version
        return out

    def get_deposit_contract(self):
        spec = self.chain.spec
        return {
            "chain_id": str(getattr(spec, "deposit_chain_id", 0)),
            "address": _hex(getattr(spec, "deposit_contract_address",
                                    b"\x00" * 20)),
        }


def _advanced(chain, slot):
    # one head snapshot: a concurrent import swaps chain.head atomically and
    # mixing two views would mistake the swap for a re-org decision
    head = chain.head
    # proposer re-org heuristic: a weak, late head may be orphaned by
    # building on its parent (fork_choice get_proposer_head)
    base_root = chain.fork_choice.get_proposer_head(slot, head.root)
    if base_root != head.root:
        parent_state = chain.state_by_root(bytes(base_root))
        state = parent_state if parent_state is not None else head.state
    else:
        state = head.state
    if state.slot < slot:
        state = state.copy()
        process_slots(chain.spec, state, slot)
    return state


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------

_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(r"^/eth/v1/beacon/genesis$"), "genesis"),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/fork$"), "fork"),
    (
        "GET",
        re.compile(r"^/eth/v1/beacon/states/(\w+)/finality_checkpoints$"),
        "finality",
    ),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/validators$"), "validators"),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/validators/([0-9a-zA-Zx]+)$"), "validator"),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/validator_balances$"), "validator_balances"),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/committees$"), "committees"),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/randao$"), "randao"),
    ("GET", re.compile(r"^/eth/v1/beacon/blob_sidecars/(\w+|0x[0-9a-fA-F]{64})$"), "blob_sidecars"),
    ("GET", re.compile(r"^/eth/v1/node/syncing$"), "syncing"),
    ("GET", re.compile(r"^/eth/v1/node/version$"), "version"),
    ("GET", re.compile(r"^/eth/v1/node/health$"), "health"),
    ("GET", re.compile(r"^/eth/v1/node/identity$"), "identity"),
    ("GET", re.compile(r"^/eth/v1/node/peers$"), "peers"),
    ("GET", re.compile(r"^/eth/v1/config/spec$"), "config_spec"),
    ("GET", re.compile(r"^/eth/v1/config/fork_schedule$"), "fork_schedule"),
    ("GET", re.compile(r"^/eth/v1/config/deposit_contract$"), "deposit_contract"),
    ("GET", re.compile(r"^/eth/v1/validator/duties/proposer/(\d+)$"), "proposer"),
    ("POST", re.compile(r"^/eth/v1/validator/duties/attester/(\d+)$"), "attester"),
    ("GET", re.compile(r"^/eth/v1/validator/attestation_data$"), "att_data"),
    ("GET", re.compile(r"^/eth/v2/validator/blocks/(\d+)$"), "produce_block"),
    ("GET", re.compile(r"^/eth/v1/validator/blinded_blocks/(\d+)$"), "produce_blinded"),
    ("POST", re.compile(r"^/eth/v1/beacon/blocks$"), "publish_block"),
    ("POST", re.compile(r"^/eth/v1/beacon/blinded_blocks$"), "publish_blinded"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/attestations$"), "publish_atts"),
    ("GET", re.compile(r"^/eth/v1/beacon/pool/attester_slashings$"), "pool_att_slashings"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/attester_slashings$"), "post_att_slashing"),
    ("GET", re.compile(r"^/eth/v1/beacon/pool/proposer_slashings$"), "pool_prop_slashings"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/proposer_slashings$"), "post_prop_slashing"),
    ("GET", re.compile(r"^/eth/v1/beacon/pool/voluntary_exits$"), "pool_exits"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/voluntary_exits$"), "post_exit"),
    ("GET", re.compile(r"^/eth/v1/beacon/pool/bls_to_execution_changes$"), "pool_bls_changes"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/bls_to_execution_changes$"), "post_bls_change"),
    ("GET", re.compile(r"^/eth/v1/beacon/headers/(\w+|0x[0-9a-fA-F]{64})$"), "header"),
    ("GET", re.compile(r"^/eth/v1/beacon/blocks/(\w+|0x[0-9a-fA-F]{64})/root$"), "block_root"),
    ("GET", re.compile(r"^/eth/v1/events$"), "events"),
    ("POST", re.compile(r"^/eth/v1/validator/liveness/(\d+)$"), "liveness"),
    ("POST", re.compile(r"^/eth/v1/validator/duties/sync/(\d+)$"), "sync_duties"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/sync_committees$"), "publish_sync"),
    ("POST", re.compile(r"^/eth/v1/validator/contribution_and_proofs$"), "publish_contributions"),
    ("GET", re.compile(r"^/eth/v1/validator/aggregate_attestation$"), "aggregate_att"),
    ("POST", re.compile(r"^/eth/v1/validator/aggregate_and_proofs$"), "publish_aggregates"),
    ("GET", re.compile(r"^/eth/v2/debug/beacon/states/(head|justified|finalized)$"), "debug_state"),
    ("GET", re.compile(r"^/eth/v2/beacon/blocks/(\w+|0x[0-9a-fA-F]{64})$"), "block"),
    ("GET", re.compile(r"^/eth/v1/beacon/light_client/bootstrap/(0x[0-9a-fA-F]{64})$"), "lc_bootstrap"),
    ("GET", re.compile(r"^/eth/v1/beacon/light_client/updates$"), "lc_updates"),
    ("GET", re.compile(r"^/eth/v1/beacon/light_client/optimistic_update$"), "lc_optimistic"),
    ("GET", re.compile(r"^/eth/v1/beacon/light_client/finality_update$"), "lc_finality"),
]

# Routes that mutate chain state and therefore serialize on the chain's
# mutation lock. Everything else reads immutable snapshots.
_MUTATING = {"publish_block", "publish_blinded", "publish_atts", "publish_sync", "publish_contributions", "publish_aggregates"}


def _make_handler(api: BeaconApiServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, payload, retry_after=None) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            if retry_after is not None:
                self.send_header("Retry-After", str(int(retry_after)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self):
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw.decode() or "{}")

        def _block_body(self):
            """Block publication body: JSON envelope, or raw SSZ when
            Content-Type is application/octet-stream with the fork named by
            Eth-Consensus-Version (the Beacon API's SSZ request flow)."""
            ctype = self.headers.get("Content-Type", "")
            if "octet-stream" in ctype:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                version = self.headers.get("Eth-Consensus-Version")
                body = {"data": "0x" + raw.hex()}
                if version:
                    body["version"] = version.lower()
                return body
            return self._body()

        def _stream_events(self, topics) -> None:
            """SSE stream (events.rs + eventsource): holds the connection
            and relays the chain's event bus until the client goes away."""
            import queue as _q

            sub = api.chain.subscribe_events(topics)
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                while True:
                    try:
                        topic, payload = sub.get(timeout=1.0)
                    except _q.Empty:
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        continue
                    chunk = (
                        f"event: {topic}\ndata: {json.dumps(payload)}\n\n"
                    ).encode()
                    self.wfile.write(chunk)
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
            finally:
                api.chain.unsubscribe_events(sub)

        def _dispatch(self, method: str) -> None:
            from urllib.parse import parse_qs, urlparse

            u = urlparse(self.path)
            try:
                for m, pat, name in _ROUTES:
                    if m != method:
                        continue
                    match = pat.match(u.path)
                    if not match:
                        continue
                    q = {k: v[0] for k, v in parse_qs(u.query).items()}
                    mon = api.load_monitor
                    if (
                        mon is not None
                        and not is_p0_route(name)
                        and mon.level() is AdmissionLevel.SATURATED
                    ):
                        # P1 load is refused while saturated so duty-path
                        # (P0) requests keep their latency budget
                        SHED_REQUESTS.inc(surface="http_api", priority="p1")
                        self._reply(
                            503,
                            {"message": "node overloaded, retry later"},
                            retry_after=mon.retry_after_s(),
                        )
                        return
                    if name == "events":
                        topics = [
                            t for t in q.get("topics", "head").split(",") if t
                        ]
                        self._stream_events(topics)
                        return
                    if name == "health":
                        self._reply(api.node_health_code(), {})
                        return
                    if name in _MUTATING:
                        # Only mutation routes serialize on the chain lock;
                        # reads work from the atomically-swapped head snapshot
                        # (the reference's cached head view, canonical_head.rs
                        # :474-497), so duties stay responsive while a block
                        # import runs BLS verification.
                        with api._chain_lock:
                            out = self._route(name, match, q)
                    else:
                        out = self._route(name, match, q)
                    enveloped = name not in ("produce_block", "produce_blinded")
                    self._reply(200, {"data": out} if enveloped else out)
                    return
                self._reply(404, {"message": f"no route {u.path}"})
            except ApiError as e:
                self._reply(e.code, {"message": str(e)})
            except Exception as e:  # noqa: BLE001 — API boundary
                self._reply(500, {"message": f"{type(e).__name__}: {e}"})

        def _route(self, name: str, match, q):
            if name == "genesis":
                return api.get_genesis()
            if name == "fork":
                return api.get_fork(match.group(1))
            if name == "finality":
                return api.get_finality_checkpoints(match.group(1))
            if name == "validators":
                return api.get_validators(match.group(1), q.get("id"))
            if name == "syncing":
                return api.get_syncing()
            if name == "version":
                from .. import __version__

                return {"version": f"lighthouse_tpu/{__version__}"}
            if name == "proposer":
                return api.get_proposer_duties(int(match.group(1)))
            if name == "attester":
                return api.get_attester_duties(
                    int(match.group(1)), [int(x) for x in self._body()]
                )
            if name == "att_data":
                return api.get_attestation_data(
                    int(q["slot"]), int(q.get("committee_index", 0))
                )
            if name == "produce_block":
                return api.produce_block(
                    int(match.group(1)),
                    _unhex(q["randao_reveal"]),
                    _unhex(q["graffiti"]) if "graffiti" in q else b"",
                )
            if name == "publish_block":
                return api.publish_block(self._block_body())
            if name == "publish_blinded":
                return api.publish_blinded_block(self._block_body())
            if name == "produce_blinded":
                return api.produce_blinded_block(
                    int(match.group(1)),
                    _unhex(q["randao_reveal"]),
                    _unhex(q["graffiti"]) if "graffiti" in q else b"",
                )
            if name == "publish_atts":
                return api.publish_attestations(self._body())
            if name == "header":
                return api.get_header(match.group(1))
            if name == "block_root":
                return api.get_block_root(match.group(1))
            if name == "validator":
                return api.get_validator(match.group(1), match.group(2))
            if name == "validator_balances":
                return api.get_validator_balances(match.group(1), q.get("id"))
            if name == "committees":
                return api.get_committees(match.group(1), q)
            if name == "randao":
                return api.get_randao(match.group(1), q)
            if name == "blob_sidecars":
                return api.get_blob_sidecars(match.group(1), q)
            if name == "identity":
                return api.get_node_identity()
            if name == "peers":
                return api.get_node_peers()
            if name == "config_spec":
                return api.get_config_spec()
            if name == "fork_schedule":
                return api.get_fork_schedule()
            if name == "deposit_contract":
                return api.get_deposit_contract()
            if name == "pool_att_slashings":
                return api.get_pool_attester_slashings()
            if name == "post_att_slashing":
                return api.post_pool_attester_slashing(self._body())
            if name == "pool_prop_slashings":
                return api.get_pool_proposer_slashings()
            if name == "post_prop_slashing":
                return api.post_pool_proposer_slashing(self._body())
            if name == "pool_exits":
                return api.get_pool_voluntary_exits()
            if name == "post_exit":
                return api.post_pool_voluntary_exit(self._body())
            if name == "pool_bls_changes":
                return api.get_pool_bls_changes()
            if name == "post_bls_change":
                return api.post_pool_bls_change(self._body())
            if name == "lc_bootstrap":
                b = api.chain.light_client_cache.bootstrap(
                    _unhex(match.group(1))
                )
                if b is None:
                    raise ApiError(404, "bootstrap unavailable for root")
                return _hex(type(b).encode(b))
            if name == "lc_updates":
                start = int(q.get("start_period", 0))
                count = max(0, min(int(q.get("count", 1)), 128))
                ups = api.chain.light_client_cache.updates_by_range(
                    start, count
                )
                return [_hex(type(u).encode(u)) for u in ups]
            if name == "lc_optimistic":
                u = api.chain.light_client_cache.latest_optimistic
                if u is None:
                    raise ApiError(404, "no optimistic update yet")
                return _hex(type(u).encode(u))
            if name == "lc_finality":
                u = api.chain.light_client_cache.latest_finality
                if u is None:
                    raise ApiError(404, "no finality update yet")
                return _hex(type(u).encode(u))
            if name == "sync_duties":
                indices = [int(x) for x in self._body()]
                return api.get_sync_duties(int(match.group(1)), indices)
            if name == "publish_sync":
                return api.publish_sync_messages(self._body())
            if name == "publish_contributions":
                return api.publish_contributions(self._body())
            if name == "aggregate_att":
                return api.get_aggregate_attestation(
                    _unhex(q["attestation_data_root"])
                )
            if name == "publish_aggregates":
                return api.publish_aggregates(self._body())
            if name == "block":
                return api.get_block(match.group(1))
            if name == "debug_state":
                st = api._state(match.group(1))
                spec = api.chain.spec
                fork = spec.fork_name_at_slot(int(st.slot))
                state_cls = api.chain.ns.state_types[fork]
                return {"version": fork, "data": _hex(state_cls.encode(st))}
            if name == "liveness":
                epoch = int(match.group(1))
                indices = [int(x) for x in self._body()]
                live = api.chain.validator_liveness(epoch, indices)
                return [
                    {"index": str(i), "is_live": bool(l)}
                    for i, l in zip(indices, live)
                ]
            raise ApiError(500, f"unwired route {name}")

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

    return Handler
