"""Beacon-API server implementation.

Work items arriving over HTTP correspond to the reference's ApiRequestP0/P1
beacon-processor queues (``beacon_processor/src/lib.rs:629-630``); here the
handler calls the chain directly (the stdlib threading server provides the
concurrency seam). Endpoints follow the Eth Beacon API paths served by
``http_api/src/lib.rs`` with SSZ-hex payload envelopes.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..state_transition import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
    process_slots,
)
from ..types.containers import AttestationData, Checkpoint
from ..types.helpers import compute_fork_digest


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class BeaconApiServer:
    """Wraps a BeaconChain (and optionally its op pool / gossip publisher —
    a BeaconNodeService provides both) behind the Beacon API."""

    def __init__(self, chain, op_pool=None, network_service=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.chain = chain
        self.op_pool = op_pool
        self.network = network_service
        self._httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None
        # Share the CHAIN's mutation lock so handler threads serialize
        # against every other driver of this chain (network router,
        # simulator loops), not just each other.
        self._chain_lock = chain.lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BeaconApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- state resolution --------------------------------------------------

    def _state(self, state_id: str):
        if state_id == "head":
            return self.chain.head.state
        if state_id in ("justified", "finalized"):
            head = self.chain.head.state
            cp = (
                head.current_justified_checkpoint
                if state_id == "justified"
                else head.finalized_checkpoint
            )
            root = bytes(cp.root)
            if root == b"\x00" * 32:  # pre-genesis-justification alias
                root = self.chain.genesis_block_root
            st = self.chain.state_by_root(root)
            if st is None:
                raise ApiError(404, f"{state_id} state not held: {root.hex()}")
            # the checkpoint block can predate its epoch start (skipped
            # slots); the checkpoint STATE is advanced to the boundary
            boundary = self.chain.spec.start_slot(int(cp.epoch))
            if st.slot < boundary:
                st = st.copy()
                process_slots(self.chain.spec, st, boundary)
            return st
        raise ApiError(400, f"unsupported state id {state_id!r}")

    # -- endpoint handlers -------------------------------------------------

    def get_genesis(self):
        st = self.chain.genesis_state
        return {
            "genesis_time": str(int(st.genesis_time)),
            "genesis_validators_root": _hex(st.genesis_validators_root),
            "genesis_fork_version": _hex(self.chain.spec.genesis_fork_version),
        }

    def get_fork(self, state_id: str):
        st = self._state(state_id)
        return {
            "previous_version": _hex(st.fork.previous_version),
            "current_version": _hex(st.fork.current_version),
            "epoch": str(int(st.fork.epoch)),
        }

    def get_finality_checkpoints(self, state_id: str):
        st = self._state(state_id)

        def cp(c):
            return {"epoch": str(int(c.epoch)), "root": _hex(c.root)}

        return {
            "previous_justified": cp(st.previous_justified_checkpoint),
            "current_justified": cp(st.current_justified_checkpoint),
            "finalized": cp(st.finalized_checkpoint),
        }

    def get_validators(self, state_id: str):
        st = self._state(state_id)
        out = []
        for i, v in enumerate(st.validators):
            out.append(
                {
                    "index": str(i),
                    "balance": str(int(st.balances[i])),
                    "status": "active_ongoing",
                    "validator": {"pubkey": _hex(v.pubkey)},
                }
            )
        return out

    def get_syncing(self):
        head = self.chain.head.slot
        current = self.chain.current_slot()
        return {
            "head_slot": str(head),
            "sync_distance": str(max(0, current - head)),
            "is_syncing": current > head + 1,
            "is_optimistic": False,
            "el_offline": self.chain.execution_layer is None,
        }

    def get_proposer_duties(self, epoch: int):
        spec = self.chain.spec
        state = self.chain.head.state.copy()
        start = spec.start_slot(epoch)
        if state.slot < start:
            process_slots(spec, state, start)
        duties = []
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            idx = get_beacon_proposer_index(spec, state, slot=slot)
            duties.append(
                {
                    "pubkey": _hex(state.validators[idx].pubkey),
                    "validator_index": str(idx),
                    "slot": str(slot),
                }
            )
        return duties

    def get_attester_duties(self, epoch: int, indices: list[int]):
        spec = self.chain.spec
        state = self.chain.head.state.copy()
        start = spec.start_slot(epoch)
        if state.slot < start:
            process_slots(spec, state, start)
        wanted = set(indices)
        duties = []
        committees_per_slot = get_committee_count_per_slot(spec, state, epoch)
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            for index in range(committees_per_slot):
                committee = get_beacon_committee(spec, state, slot, index)
                for pos, v in enumerate(committee):
                    if int(v) in wanted:
                        duties.append(
                            {
                                "pubkey": _hex(state.validators[int(v)].pubkey),
                                "validator_index": str(int(v)),
                                "committee_index": str(index),
                                "committee_length": str(committee.size),
                                "committees_at_slot": str(committees_per_slot),
                                "validator_committee_index": str(pos),
                                "slot": str(slot),
                            }
                        )
        return duties

    def get_sync_duties(self, epoch: int, indices: list[int]):
        """Sync-committee duties (duties/sync/{epoch}): committee positions
        per requested validator, computed on a state advanced to the
        requested epoch (period boundaries rotate the committee)."""
        spec = self.chain.spec
        state = self.chain.head.state
        if not hasattr(state, "current_sync_committee"):
            return []
        start = spec.start_slot(epoch)
        if state.slot < start:
            state = state.copy()
            process_slots(spec, state, start)
        out = []
        for idx in indices:
            positions = self.chain.sync_committee_positions(state, idx)
            if positions:
                out.append(
                    {
                        "pubkey": _hex(state.validators[idx].pubkey),
                        "validator_index": str(idx),
                        "validator_sync_committee_indices": [
                            str(p) for p in positions
                        ],
                    }
                )
        return out

    def publish_sync_messages(self, body: list):
        """POST /eth/v1/beacon/pool/sync_committees: verify + pool."""
        ns = self.chain.ns
        msgs = [
            ns.SyncCommitteeMessage.decode(_unhex(item["data"]))
            for item in body
        ]
        results = self.chain.verify_sync_committee_messages(msgs)
        failures = [
            {"index": i, "message": str(v)}
            for i, (_, v) in enumerate(results)
            if isinstance(v, Exception)
        ]
        if failures:
            raise ApiError(400, f"sync messages rejected: {failures}")
        if self.network is not None:
            publish = getattr(self.network, "publish_sync_message", None)
            if publish is not None:
                for m in msgs:
                    publish(m)
        return {"accepted": len(msgs)}

    def get_aggregate_attestation(self, data_root: bytes):
        """GET /eth/v1/validator/aggregate_attestation: the naive pool's best
        aggregate for an AttestationData root."""
        agg = self.chain.naive_aggregation_pool.get_by_root(data_root)
        if agg is None:
            raise ApiError(404, "no aggregate for data root")
        cls = self.chain.ns.Attestation
        return _hex(cls.encode(agg))

    def publish_aggregates(self, body: list):
        """POST /eth/v1/validator/aggregate_and_proofs: the 3-sets-per-
        aggregate batch verification path + op pool insert."""
        ns = self.chain.ns
        saps = [
            ns.SignedAggregateAndProof.decode(_unhex(item["data"]))
            for item in body
        ]
        results = self.chain.verify_aggregated_attestations(saps)
        failures = []
        accepted = 0
        for i, (sap, verdict) in enumerate(results):
            if isinstance(verdict, Exception):
                failures.append({"index": i, "message": str(verdict)})
                continue
            accepted += 1
            if self.op_pool is not None:
                self.op_pool.insert_attestation(sap.message.aggregate)
            if self.network is not None:
                self.network.publish_aggregate(sap)
        if failures:
            # valid aggregates are already applied; report the rest
            raise ApiError(400, f"aggregates rejected: {failures}")
        return {"accepted": accepted}

    def publish_contributions(self, body: list):
        """POST /eth/v1/validator/contribution_and_proofs."""
        ns = self.chain.ns
        scs = [
            ns.SignedContributionAndProof.decode(_unhex(item["data"]))
            for item in body
        ]
        results = self.chain.verify_sync_contributions(scs)
        failures = [
            {"index": i, "message": str(v)}
            for i, (_, v) in enumerate(results)
            if isinstance(v, Exception)
        ]
        if failures:
            raise ApiError(400, f"contributions rejected: {failures}")
        return {"accepted": len(scs)}

    def get_attestation_data(self, slot: int, committee_index: int):
        spec = self.chain.spec
        # one snapshot: a concurrent import swaps chain.head atomically, so
        # every field here must come from the SAME head view
        head = self.chain.head
        state = head.state
        if state.slot < slot:
            state = state.copy()
            process_slots(spec, state, slot)
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        head_root = head.root
        if slot == spec.start_slot(epoch) and head.slot <= slot:
            target_root = head_root
        else:
            from ..state_transition import get_block_root_at_slot

            target_root = get_block_root_at_slot(
                spec, state, spec.start_slot(epoch)
            )
        data = AttestationData(
            slot=slot,
            index=committee_index,
            beacon_block_root=head_root,
            source=state.current_justified_checkpoint,
            target=Checkpoint(epoch=epoch, root=target_root),
        )
        return {"data": _hex(AttestationData.encode(data))}

    def produce_block(self, slot: int, randao_reveal: bytes, graffiti: bytes):
        chain = self.chain
        state = _advanced(chain, slot)  # advance once; shared by pool + production
        atts = self.op_pool.get_attestations(state) if self.op_pool else []
        block, _post = chain.produce_block_on_state(
            state, slot, randao_reveal, attestations=atts,
            graffiti=graffiti or b"\x00" * 32,
        )
        fork = chain.spec.fork_name_at_epoch(
            slot // chain.spec.preset.SLOTS_PER_EPOCH
        )
        inner_cls = dict(chain.ns.block_types[fork].FIELDS)["message"]
        return {
            "version": fork,
            "data": _hex(inner_cls.encode(block)),
        }

    def publish_block(self, body: dict):
        version = body.get("version", None)
        fork = version or self.chain.spec.fork_name_at_slot(
            self.chain.current_slot()
        )
        block_cls = self.chain.ns.block_types[fork]
        signed = block_cls.decode(_unhex(body["data"]))
        from ..beacon_chain.chain import BlockError, BlockPendingAvailability

        # deneb BlockContents: blobs + proofs ride alongside the block
        sidecars = []
        if body.get("blobs"):
            from ..beacon_chain.data_availability import make_blob_sidecars

            sidecars = make_blob_sidecars(
                self.chain.ns,
                signed,
                [_unhex(x) for x in body["blobs"]],
                [_unhex(x) for x in body.get("kzg_proofs", [])],
            )
        try:
            self.chain.process_block(signed)
        except BlockPendingAvailability:
            from ..beacon_chain.data_availability import BlobError

            imported = None
            try:
                for sc in sidecars:
                    imported = self.chain.process_gossip_blob(sc)
            except (BlobError, BlockError) as e:
                raise ApiError(400, str(e)) from None
            if imported is None:
                raise ApiError(
                    400, "block pending blob availability"
                ) from None
        except BlockError as e:
            raise ApiError(400, str(e)) from None
        if self.network is not None:
            self.network.publish_block(signed)
            publish_blob = getattr(self.network, "publish_blob", None)
            if publish_blob is not None:
                for sc in sidecars:
                    publish_blob(sc)
        return {}

    def publish_attestations(self, body: list):
        att_cls = self.chain.ns.Attestation
        atts = [att_cls.decode(_unhex(item["data"])) for item in body]
        results = self.chain.verify_unaggregated_attestations(atts)
        failures = []
        for i, (att, verdict) in enumerate(results):
            if isinstance(verdict, Exception):
                failures.append({"index": i, "message": str(verdict)})
                continue
            if self.op_pool is not None:
                self.op_pool.insert_attestation(att)
            if self.network is not None:
                self.network.publish_attestation(att)
        if failures:
            raise ApiError(400, json.dumps(failures))
        return {}

    def _signed_block(self, root: bytes):
        """Decoded signed block by root: memory cache first, then the store
        (finalized blocks are migrated out of memory but stay on disk)."""
        chain = self.chain
        sb = chain._blocks.get(root)
        if sb is not None:
            return sb
        raw = chain.store.get_block(root)
        if raw is None:
            return None
        for fork in reversed(list(chain.ns.block_types)):
            try:
                return chain.ns.block_types[fork].decode(raw)
            except Exception:
                continue
        return None

    def get_block(self, block_id: str):
        """Signed block by 'head', slot number, or 0x-root (fork-versioned
        SSZ envelope; /eth/v2/beacon/blocks/{block_id})."""
        chain = self.chain
        if block_id == "head":
            root = chain.head.root
        elif block_id.startswith("0x"):
            root = _unhex(block_id)
        elif block_id.isdigit():
            # canonical walk from head, bounded by the head slot; store
            # fallback covers migrated (finalized) history
            want = int(block_id)
            if want > chain.head.slot:
                raise ApiError(404, f"no canonical block at slot {want}")
            root = chain.head.root
            found = None
            while root is not None:
                sb = self._signed_block(root)
                if sb is None:
                    break
                s = int(sb.message.slot)
                if s == want:
                    found = root
                    break
                if s < want:
                    break
                if root == chain.genesis_block_root:
                    break
                root = bytes(sb.message.parent_root)
            if found is None:
                raise ApiError(404, f"no canonical block at slot {want}")
            root = found
        else:
            raise ApiError(400, f"unsupported block id {block_id!r}")
        sb = self._signed_block(root)
        if sb is None:
            raise ApiError(404, f"block {root.hex()[:16]} not held")
        fork = chain.spec.fork_name_at_slot(int(sb.message.slot))
        cls = chain.ns.block_types[fork]
        return {"version": fork, "data": _hex(cls.encode(sb))}

    def get_header(self):
        head = self.chain.head
        return {
            "root": _hex(head.root),
            "header": {"slot": str(head.slot)},
        }


def _advanced(chain, slot):
    # one head snapshot: a concurrent import swaps chain.head atomically and
    # mixing two views would mistake the swap for a re-org decision
    head = chain.head
    # proposer re-org heuristic: a weak, late head may be orphaned by
    # building on its parent (fork_choice get_proposer_head)
    base_root = chain.fork_choice.get_proposer_head(slot, head.root)
    if base_root != head.root:
        parent_state = chain.state_by_root(bytes(base_root))
        state = parent_state if parent_state is not None else head.state
    else:
        state = head.state
    if state.slot < slot:
        state = state.copy()
        process_slots(chain.spec, state, slot)
    return state


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------

_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(r"^/eth/v1/beacon/genesis$"), "genesis"),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/fork$"), "fork"),
    (
        "GET",
        re.compile(r"^/eth/v1/beacon/states/(\w+)/finality_checkpoints$"),
        "finality",
    ),
    ("GET", re.compile(r"^/eth/v1/beacon/states/(\w+)/validators$"), "validators"),
    ("GET", re.compile(r"^/eth/v1/node/syncing$"), "syncing"),
    ("GET", re.compile(r"^/eth/v1/node/version$"), "version"),
    ("GET", re.compile(r"^/eth/v1/validator/duties/proposer/(\d+)$"), "proposer"),
    ("POST", re.compile(r"^/eth/v1/validator/duties/attester/(\d+)$"), "attester"),
    ("GET", re.compile(r"^/eth/v1/validator/attestation_data$"), "att_data"),
    ("GET", re.compile(r"^/eth/v2/validator/blocks/(\d+)$"), "produce_block"),
    ("POST", re.compile(r"^/eth/v1/beacon/blocks$"), "publish_block"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/attestations$"), "publish_atts"),
    ("GET", re.compile(r"^/eth/v1/beacon/headers/head$"), "header"),
    ("GET", re.compile(r"^/eth/v1/events$"), "events"),
    ("POST", re.compile(r"^/eth/v1/validator/liveness/(\d+)$"), "liveness"),
    ("POST", re.compile(r"^/eth/v1/validator/duties/sync/(\d+)$"), "sync_duties"),
    ("POST", re.compile(r"^/eth/v1/beacon/pool/sync_committees$"), "publish_sync"),
    ("POST", re.compile(r"^/eth/v1/validator/contribution_and_proofs$"), "publish_contributions"),
    ("GET", re.compile(r"^/eth/v1/validator/aggregate_attestation$"), "aggregate_att"),
    ("POST", re.compile(r"^/eth/v1/validator/aggregate_and_proofs$"), "publish_aggregates"),
    ("GET", re.compile(r"^/eth/v2/debug/beacon/states/(head|justified|finalized)$"), "debug_state"),
    ("GET", re.compile(r"^/eth/v2/beacon/blocks/(\w+)$"), "block"),
    ("GET", re.compile(r"^/eth/v1/beacon/light_client/bootstrap/(0x[0-9a-fA-F]{64})$"), "lc_bootstrap"),
    ("GET", re.compile(r"^/eth/v1/beacon/light_client/optimistic_update$"), "lc_optimistic"),
    ("GET", re.compile(r"^/eth/v1/beacon/light_client/finality_update$"), "lc_finality"),
]

# Routes that mutate chain state and therefore serialize on the chain's
# mutation lock. Everything else reads immutable snapshots.
_MUTATING = {"publish_block", "publish_atts", "publish_sync", "publish_contributions", "publish_aggregates"}


def _make_handler(api: BeaconApiServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, payload) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self):
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw.decode() or "{}")

        def _stream_events(self, topics) -> None:
            """SSE stream (events.rs + eventsource): holds the connection
            and relays the chain's event bus until the client goes away."""
            import queue as _q

            sub = api.chain.subscribe_events(topics)
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                while True:
                    try:
                        topic, payload = sub.get(timeout=1.0)
                    except _q.Empty:
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        continue
                    chunk = (
                        f"event: {topic}\ndata: {json.dumps(payload)}\n\n"
                    ).encode()
                    self.wfile.write(chunk)
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
            finally:
                api.chain.unsubscribe_events(sub)

        def _dispatch(self, method: str) -> None:
            from urllib.parse import parse_qs, urlparse

            u = urlparse(self.path)
            try:
                for m, pat, name in _ROUTES:
                    if m != method:
                        continue
                    match = pat.match(u.path)
                    if not match:
                        continue
                    q = {k: v[0] for k, v in parse_qs(u.query).items()}
                    if name == "events":
                        topics = [
                            t for t in q.get("topics", "head").split(",") if t
                        ]
                        self._stream_events(topics)
                        return
                    if name in _MUTATING:
                        # Only mutation routes serialize on the chain lock;
                        # reads work from the atomically-swapped head snapshot
                        # (the reference's cached head view, canonical_head.rs
                        # :474-497), so duties stay responsive while a block
                        # import runs BLS verification.
                        with api._chain_lock:
                            out = self._route(name, match, q)
                    else:
                        out = self._route(name, match, q)
                    self._reply(200, {"data": out} if name != "produce_block" else out)
                    return
                self._reply(404, {"message": f"no route {u.path}"})
            except ApiError as e:
                self._reply(e.code, {"message": str(e)})
            except Exception as e:  # noqa: BLE001 — API boundary
                self._reply(500, {"message": f"{type(e).__name__}: {e}"})

        def _route(self, name: str, match, q):
            if name == "genesis":
                return api.get_genesis()
            if name == "fork":
                return api.get_fork(match.group(1))
            if name == "finality":
                return api.get_finality_checkpoints(match.group(1))
            if name == "validators":
                return api.get_validators(match.group(1))
            if name == "syncing":
                return api.get_syncing()
            if name == "version":
                from .. import __version__

                return {"version": f"lighthouse_tpu/{__version__}"}
            if name == "proposer":
                return api.get_proposer_duties(int(match.group(1)))
            if name == "attester":
                return api.get_attester_duties(
                    int(match.group(1)), [int(x) for x in self._body()]
                )
            if name == "att_data":
                return api.get_attestation_data(
                    int(q["slot"]), int(q.get("committee_index", 0))
                )
            if name == "produce_block":
                return api.produce_block(
                    int(match.group(1)),
                    _unhex(q["randao_reveal"]),
                    _unhex(q["graffiti"]) if "graffiti" in q else b"",
                )
            if name == "publish_block":
                return api.publish_block(self._body())
            if name == "publish_atts":
                return api.publish_attestations(self._body())
            if name == "header":
                return api.get_header()
            if name == "lc_bootstrap":
                b = api.chain.light_client_cache.bootstrap(
                    _unhex(match.group(1))
                )
                if b is None:
                    raise ApiError(404, "bootstrap unavailable for root")
                return _hex(type(b).encode(b))
            if name == "lc_optimistic":
                u = api.chain.light_client_cache.latest_optimistic
                if u is None:
                    raise ApiError(404, "no optimistic update yet")
                return _hex(type(u).encode(u))
            if name == "lc_finality":
                u = api.chain.light_client_cache.latest_finality
                if u is None:
                    raise ApiError(404, "no finality update yet")
                return _hex(type(u).encode(u))
            if name == "sync_duties":
                indices = [int(x) for x in self._body()]
                return api.get_sync_duties(int(match.group(1)), indices)
            if name == "publish_sync":
                return api.publish_sync_messages(self._body())
            if name == "publish_contributions":
                return api.publish_contributions(self._body())
            if name == "aggregate_att":
                return api.get_aggregate_attestation(
                    _unhex(q["attestation_data_root"])
                )
            if name == "publish_aggregates":
                return api.publish_aggregates(self._body())
            if name == "block":
                return api.get_block(match.group(1))
            if name == "debug_state":
                st = api._state(match.group(1))
                spec = api.chain.spec
                fork = spec.fork_name_at_slot(int(st.slot))
                state_cls = api.chain.ns.state_types[fork]
                return {"version": fork, "data": _hex(state_cls.encode(st))}
            if name == "liveness":
                epoch = int(match.group(1))
                indices = [int(x) for x in self._body()]
                live = api.chain.validator_liveness(epoch, indices)
                return [
                    {"index": str(i), "is_live": bool(l)}
                    for i, l in zip(indices, live)
                ]
            raise ApiError(500, f"unwired route {name}")

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

    return Handler
