"""Beacon-API HTTP server (the reference's ``beacon_node/http_api`` twin).

Serves the validator-required slice of the Eth Beacon API over stdlib HTTP:
genesis/fork/finality/validators state queries, node syncing, proposer and
attester duties, attestation data, unsigned block production, and publication
of signed blocks and attestations. Container payloads travel as SSZ hex
inside JSON envelopes ({"data": "0x..."}) — the SSZ-wire flavor of the
reference's dual JSON/SSZ content negotiation (``http_api/src/lib.rs``).
"""

from .server import BeaconApiServer  # noqa: F401
