"""Periodic human-readable sync status (ref client/src/notifier.rs)."""

from __future__ import annotations

import threading

from ..utils.logging import get_logger

log = get_logger("notifier")


class Notifier:
    def __init__(self, chain, interval: float | None = None):
        self.chain = chain
        self.interval = interval or chain.spec.preset.SECONDS_PER_SLOT
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def status_line(self) -> dict:
        head = self.chain.head
        current = self.chain.current_slot()
        distance = max(0, current - head.slot)
        return {
            "slot": current,
            "head_slot": head.slot,
            "head": head.root.hex()[:10],
            "finalized_epoch": int(
                head.state.finalized_checkpoint.epoch
            ),
            "sync": "synced" if distance <= 1 else f"behind ({distance})",
        }

    def tick(self) -> None:
        status = self.status_line()
        log.info("Synced" if status["sync"] == "synced" else "Syncing",
                 **status)

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.tick()
                except Exception:
                    pass

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            # the loop wakes from its interval wait as soon as the event
            # sets, so a short bounded join reclaims the thread
            self._thread.join(timeout=5.0)
