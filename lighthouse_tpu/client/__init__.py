"""Beacon-node client assembly (ref beacon_node/client/src/builder.rs:74-786
+ beacon_node/src/lib.rs ProductionBeaconNode).

``ClientBuilder`` chains the same construction steps the reference does —
chain, processor, network service, HTTP API, metrics, slasher, notifier —
and ``Client`` owns their lifecycles.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..beacon_chain.chain import BeaconChain
from ..op_pool import OperationPool
from ..store.hot_cold import HotColdDB, StoreConfig
from ..store.kv import LevelStore
from ..types.spec import ChainSpec
from ..utils.logging import get_logger, init_logging
from ..utils.slot_clock import ManualSlotClock, SystemTimeSlotClock
from .notifier import Notifier

log = get_logger("client")


@dataclass
class ClientConfig:
    datadir: str | None = None  # None = in-memory stores
    http_enabled: bool = True
    http_port: int = 0  # 0 = ephemeral
    metrics_enabled: bool = False
    metrics_port: int = 0
    slasher_enabled: bool = False
    validator_monitor_auto: bool = False
    validator_monitor_indices: tuple = ()
    interop_validators: int = 16
    genesis_time: int | None = None  # None = now
    debug_level: str = "info"
    use_system_clock: bool = True
    listen_port: int | None = None  # TCP gossip/RPC listener (None = no p2p)
    boot_nodes: str = ""  # comma-separated UDP boot-node addresses
    boot_enrs: str = ""   # comma-separated hex ENRs (discv5-style discovery)


class Client:
    def __init__(self, chain, op_pool, http_server, metrics_server,
                 slasher_service, notifier, network_service=None):
        self.chain = chain
        self.op_pool = op_pool
        self.http_server = http_server
        self.metrics_server = metrics_server
        self.slasher_service = slasher_service
        self.notifier = notifier
        self.network_service = network_service
        self._shutdown = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> "Client":
        if self.http_server is not None:
            self.http_server.start()
            log.info("Beacon API started", url=self.http_server.url)
        if self.metrics_server is not None:
            self.metrics_server.start()
            log.info("Metrics server started", url=self.metrics_server.url)
        if self.notifier is not None:
            self.notifier.start()
        if self.slasher_service is not None:
            self._slasher_ticker = threading.Thread(
                target=self._run_slasher_ticks, daemon=True,
                name="slasher-tick",
            )
            self._slasher_ticker.start()
            self._threads.append(self._slasher_ticker)
        if self.chain.eth1_service is not None:
            th = threading.Thread(
                target=self._run_eth1_polls, daemon=True, name="eth1-poll"
            )
            th.start()
            self._threads.append(th)
        # the warmup thread is deliberately NOT joined on stop: it runs one
        # uninterruptible best-effort compile and exits — joining it would
        # stall every shutdown behind XLA for no correctness gain
        threading.Thread(
            target=self._warmup_bls, daemon=True, name="bls-warmup"
        ).start()
        return self

    def _run_slasher_ticks(self) -> None:
        """Per-slot slasher batch processing (the reference's timer task at
        slot_offset into each slot, slasher/service/src/service.rs)."""
        sps = self.chain.spec.preset.SECONDS_PER_SLOT
        while not self._shutdown.wait(sps):
            try:
                self.slasher_service.tick()
            except Exception as e:  # noqa: BLE001 — keep the timer alive
                log.warning("Slasher tick failed", error=str(e))

    def _run_eth1_polls(self) -> None:
        """Periodic eth1 follow poll (eth1/src/service.rs update interval)."""
        sps = self.chain.spec.preset.SECONDS_PER_SLOT
        while not self._shutdown.wait(sps):
            try:
                self.chain.eth1_service.update()
            except Exception as e:  # noqa: BLE001 — keep polling
                log.warn("Eth1 poll failed", error=str(e))

    def _warmup_bls(self) -> None:
        """Compile the verification kernels off the serving path so the first
        block publish doesn't pay XLA compilation inside an HTTP request."""
        from .. import bls

        try:
            t0 = time.monotonic()
            ok = bls.warmup()
            if bls.get_backend() == "tpu":
                import hashlib

                from ..bls import tpu_backend as tb

                root = hashlib.sha256(b"lighthouse-tpu-warmup").digest()
                sk = bls.SecretKey.from_bytes((7).to_bytes(32, "big"))
                sig = sk.sign(root).serialize()
                tb.verify_indexed_sets_device(
                    self.chain.pubkey_cache.device_array(),
                    [([0], root, sig)],
                )
            log.info(
                "BLS backend warm",
                backend=bls.get_backend(),
                healthy=ok,
                seconds=round(time.monotonic() - t0, 1),
            )
        except Exception as e:  # noqa: BLE001 — warmup is best-effort
            log.warning("BLS warmup failed", error=str(e))

    def stop(self) -> None:
        self._shutdown.set()
        for th in self._threads:
            # the periodic loops wake from their interval wait the moment
            # the shutdown event sets, so these joins return in ms
            th.join(timeout=2.0)
        if self.notifier is not None:
            self.notifier.stop()
        if self.http_server is not None:
            self.http_server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.network_service is not None:
            self.network_service.stop()
        # persist fork choice + op pool for the next boot
        # (persisted_fork_choice.rs / operation_pool persistence.rs)
        try:
            from ..fork_choice import persistence as fc_persist
            from ..op_pool import persistence as pool_persist

            self.chain.store.put_meta(
                fc_persist.META_KEY,
                fc_persist.serialize_fork_choice(self.chain.fork_choice),
            )
            self.chain.store.put_meta(
                pool_persist.META_KEY, pool_persist.serialize_pool(self.op_pool)
            )
        except Exception as e:  # noqa: BLE001 — shutdown must not fail
            log.warn("Persistence on shutdown failed", error=str(e))

    def wait_for_shutdown(self) -> None:
        """Block until stop() or KeyboardInterrupt (Environment's shutdown
        channel, common/task_executor/src/lib.rs:205)."""
        try:
            while not self._shutdown.wait(0.5):
                pass
        except KeyboardInterrupt:
            log.info("Shutting down", reason="interrupt")
            self.stop()


class ClientBuilder:
    def __init__(self, spec: ChainSpec, config: ClientConfig | None = None):
        self.spec = spec
        self.config = config or ClientConfig()
        self._genesis_state = None
        self._slot_clock = None
        self._eth1 = None

    def interop_genesis(self) -> "ClientBuilder":
        from ..state_transition.genesis import interop_genesis_state

        genesis_time = (
            int(time.time())
            if self.config.genesis_time is None
            else self.config.genesis_time
        )
        self._genesis_state = interop_genesis_state(
            self.spec, self.config.interop_validators, genesis_time
        )
        return self

    def genesis_state(self, state) -> "ClientBuilder":
        """Boot from a provided state (the checkpoint-sync seam:
        client/src/builder.rs genesis-state branch)."""
        self._genesis_state = state
        return self

    def checkpoint_sync(self, url: str, state_id: str = "finalized") -> "ClientBuilder":
        """Fetch a trusted finalized state over HTTP and anchor the chain on
        it (client/src/builder.rs checkpoint-sync genesis branch; history is
        filled backwards by sync, not required to serve)."""
        from ..api_client import BeaconNodeHttpClient
        from ..types.containers import for_preset

        version, raw = BeaconNodeHttpClient(url).get_state_ssz(state_id)
        ns = for_preset(self.spec.preset.name)
        state = ns.state_types[version].decode(raw)
        log.info(
            "Checkpoint state fetched",
            url=url, slot=int(state.slot), fork=version,
        )
        self._genesis_state = state
        return self

    def eth1_service(self, service) -> "ClientBuilder":
        """Attach a deposit/eth1-data bridge (eth1/Eth1Service)."""
        self._eth1 = service
        return self

    def slot_clock(self, clock) -> "ClientBuilder":
        self._slot_clock = clock
        return self

    def build(self) -> Client:
        cfg = self.config
        init_logging(cfg.debug_level)
        if self._genesis_state is None:
            self.interop_genesis()
        state = self._genesis_state

        if cfg.datadir:
            import os

            os.makedirs(cfg.datadir, exist_ok=True)
            store = HotColdDB(
                hot=LevelStore(os.path.join(cfg.datadir, "chain.db")),
                cold=LevelStore(os.path.join(cfg.datadir, "freezer.db")),
                config=StoreConfig(),
            )
        else:
            store = HotColdDB()

        clock = self._slot_clock
        if clock is None:
            clock = (
                SystemTimeSlotClock(
                    int(state.genesis_time), self.spec.preset.SECONDS_PER_SLOT
                )
                if cfg.use_system_clock
                else ManualSlotClock(0)
            )
        chain = BeaconChain(self.spec, state, store=store, slot_clock=clock)
        if self._eth1 is not None:
            chain.eth1_service = self._eth1
        op_pool = OperationPool(self.spec, chain.ns.Attestation)

        # restore persisted fork choice + op pool (persisted_fork_choice.rs,
        # operation_pool/persistence.rs): best-effort — a corrupt or
        # incompatible snapshot falls back to the fresh anchor
        from ..fork_choice import persistence as fc_persist
        from ..op_pool import persistence as pool_persist

        blob = store.get_meta(fc_persist.META_KEY)
        if blob:
            fresh_fc = chain.fork_choice
            try:
                restored = fc_persist.restore_fork_choice(self.spec, blob)
                if chain.genesis_block_root in restored.proto.indices:
                    # rehydrate the unfinalized blocks the restored graph
                    # references — imports, production and serving all key
                    # off the chain's block/seen maps
                    for node in restored.proto.nodes:
                        raw = store.get_block(node.root)
                        if raw is not None:
                            fork = self.spec.fork_name_at_slot(node.slot)
                            chain._blocks[node.root] = chain.ns.block_types[
                                fork
                            ].decode(raw)
                        chain._seen_blocks.add(node.root)
                    chain.fork_choice = restored
                    chain.recompute_head()
                    log.info(
                        "Fork choice restored",
                        nodes=len(restored.proto.nodes),
                        head=chain.head.root.hex()[:10],
                    )
            except Exception as e:  # noqa: BLE001 — stale snapshot
                chain.fork_choice = fresh_fc
                log.warn("Fork choice restore failed", error=str(e))
        blob = store.get_meta(pool_persist.META_KEY)
        if blob:
            try:
                n = pool_persist.restore_pool(op_pool, chain.ns, blob)
                log.info("Op pool restored", attestations=n)
            except Exception as e:  # noqa: BLE001
                log.warn("Op pool restore failed", error=str(e))

        network_service = None
        if cfg.listen_port is not None:
            from ..network import BeaconNodeService, GossipsubTransport

            discovery = None
            boot_enrs = [
                b.strip() for b in cfg.boot_enrs.split(",") if b.strip()
            ]
            if boot_enrs:
                from ..network.discovery import DiscoveryService
                from ..types.helpers import compute_fork_digest

                st = chain.head.state
                digest = compute_fork_digest(
                    bytes(st.fork.current_version),
                    bytes(st.genesis_validators_root),
                )
                discovery = DiscoveryService(fork_digest=digest).start()
            transport = GossipsubTransport(
                self.spec, port=cfg.listen_port, discovery=discovery
            )
            network_service = BeaconNodeService(
                transport.local_addr, self.spec, transport=transport,
                chain=chain, op_pool=op_pool,
            )
            if discovery is not None:
                from ..network.discovery import ENR

                for hexenr in boot_enrs:
                    try:
                        enr, _ = ENR.decode(bytes.fromhex(hexenr))
                        discovery.bootstrap(enr)
                    except (ValueError, OSError) as e:
                        log.warn("Bad boot ENR", error=str(e))
                transport.discover_enr()
                log.info(
                    "ENR discovery active",
                    enr=discovery.enr.encode().hex(),
                    known=len(discovery.table),
                )
            for boot in [b.strip() for b in cfg.boot_nodes.split(",") if b.strip()]:
                try:
                    transport.discover(boot)
                except OSError as e:
                    log.warn("Boot node unreachable", addr=boot, error=str(e))
            for peer in transport.peers():
                try:
                    network_service.connect(peer)
                except ConnectionError as e:
                    log.warn("Peer handshake failed", peer=peer, error=str(e))
            log.info(
                "P2P listening", addr=transport.local_addr,
                peers=len(transport.peers()),
            )

        http_server = None
        if cfg.http_enabled:
            from ..http_api import BeaconApiServer

            http_server = BeaconApiServer(
                chain, op_pool=op_pool, port=cfg.http_port,
                network_service=network_service,
            )

        metrics_server = None
        if cfg.metrics_enabled:
            from ..http_metrics import MetricsServer

            metrics_server = MetricsServer(
                port=cfg.metrics_port, datadir=cfg.datadir
            )

        slasher_service = None
        if cfg.slasher_enabled:
            from ..slasher import SlasherService, make_slasher

            # the engine-backed slasher behind LIGHTHOUSE_SLASHER_BACKEND
            # (device-resident span store / numpy twin); the seed per-row
            # Slasher remains importable as the DB-backed reference twin
            slasher = make_slasher(store.hot, chain.ns)
            slasher_service = SlasherService(chain, slasher, op_pool)
            # subscribe to the chain's ingest seams (service.rs gossip taps)
            chain.block_observers.append(slasher_service.block_observed)
            chain.attestation_observers.append(
                slasher_service.attestation_observed
            )

        if cfg.validator_monitor_auto or cfg.validator_monitor_indices:
            from ..beacon_chain.validator_monitor import ValidatorMonitor

            chain.validator_monitor = ValidatorMonitor(
                chain, indices=cfg.validator_monitor_indices,
                auto=cfg.validator_monitor_auto,
            )

        notifier = Notifier(chain)
        return Client(
            chain, op_pool, http_server, metrics_server, slasher_service,
            notifier, network_service=network_service,
        )
